"""Fold disciplines: how chunk-level partial results combine into one
job-level answer, and how each partial is framed on the wire/journal.

The mining plane folds by *min* — every settle carries a candidate
``(hash, nonce)`` and the job keeps the smallest (coordinator
``_Job.fold``). ISSUE 15 generalizes that one hard-coded reduction into
a discipline object with four registered shapes:

- **fmin** — the mining default, generalized: keep the single best
  ``(value, index)`` pair, ties at the lowest index.
- **top-k** — keep the k best pairs, globally ordered by
  ``(value, index)`` so ties always resolve to the lowest index.
- **first-match** — the earliest index whose value clears a threshold;
  ``is_final`` fires the coordinator's early-finish path (the Cancel
  broadcast that already retires a found mining job).
- **sum** — map-reduce: total + count. The only NON-idempotent fold;
  replay safety comes from the coverage gate in
  :mod:`tpuminter.workloads` (a settle absorbed twice is a no-op), not
  from the algebra.

Each discipline owns its chunk-partial codec: a tagged, CRC-trailed
binary frame in the same ``tag ‖ fields ‖ crc32`` shape as the PR 4
wire codec, carried opaquely inside WorkResult payloads and journal
settle records (``"wp"`` field). Tags 0xC1–0xC4 live in the same
process-wide byte namespace as the wire/journal tags (0xB1–0xBB) — the
codec-conformance checker proves the non-collision statically. The
payload-level CRC is load-bearing: a JSON-fallback WorkResult carries
the payload as bare hex with no envelope CRC, so the trailer here is
the only corruption check those bytes ever get.

Accumulators are plain JSON-able values (lists/ints/None) so they ride
journal snapshots and replication unchanged.
"""

from __future__ import annotations

import struct
import zlib
from typing import Any, List, Optional

__all__ = [
    "Fold", "FMin", "TopK", "FirstMatch", "FSum", "seal_payload",
    "tree_merge",
]

_U64 = 1 << 64
_U128 = 1 << 128

#: Chunk-partial codec tags. Same rules as protocol.py v1: never reuse,
#: never collide with '{' (0x7B), new layouts get NEW tags.
_TAG_WMIN = 0xC1
_TAG_WTOPK = 0xC2
_TAG_WMATCH = 0xC3
_TAG_WSUM = 0xC4

#: Top-k payloads carry a fixed 8-slot table (k <= 8 is enforced at
#: params parse); unused slots are zero and ignored past ``count``.
TOPK_SLOTS = 8

# Distinct total packed lengths (the checker's secondary dispatch key):
# 18, 130, 26, 25 (+4 CRC each).
_BIN_WMIN = struct.Struct("<BBQQ")        # tag, has, value, index
# tag, count, then TOPK_SLOTS (value, index) pairs. The format is a
# literal (not "QQ" * TOPK_SLOTS) so the codec-conformance checker's
# AST extractor sees the layout and keeps this kind under its eye.
_BIN_WTOPK = struct.Struct("<BBQQQQQQQQQQQQQQQQ")
_BIN_WMATCH = struct.Struct("<BBQQQ")     # tag, has, index, value, probes
_BIN_WSUM = struct.Struct("<B16sQ")       # tag, total (u128 LE), count
_CRC = struct.Struct("<I")

assert _BIN_WTOPK.size == 2 + 16 * TOPK_SLOTS, "slot table out of sync"


def seal_payload(body: bytes) -> bytes:
    """``body ‖ crc32(body)`` — the chunk-partial frame trailer."""
    return body + _CRC.pack(zlib.crc32(body))


def tree_merge(fold: "Fold", groups: List[List[Any]]) -> Any:
    """Fold a partition of chunk partials group-by-group, then combine
    the group accumulators — the two-tier composition the federation
    plane rides (each aggregator folds its fleet's partials into ONE
    upward result; the parent combines per-aggregator results). Equals
    the flat fold over the concatenation for every registered
    discipline, because ``combine`` is associative and commutative;
    tests/test_federation.py pins that equality under duplicate
    delivery and replay for the idempotent folds, while FSum's half of
    exactly-once is the coverage gate (each tier absorbs a given
    coverage range once, so no partial reaches ``combine`` twice)."""
    acc = fold.initial()
    for group in groups:
        sub = fold.initial()
        for part in group:
            sub = fold.combine(sub, part)
        acc = fold.combine(acc, sub)
    return acc


def _open_payload(data: bytes, layout: struct.Struct, tag: int) -> tuple:
    """Validate length, tag, and CRC; unpack. Raises ValueError on any
    mismatch — callers treat a bad payload like a bad wire frame."""
    if len(data) != layout.size + _CRC.size:
        raise ValueError(
            f"fold payload: want {layout.size + _CRC.size} bytes, "
            f"got {len(data)}"
        )
    body, (crc,) = data[:-_CRC.size], _CRC.unpack(data[-_CRC.size:])
    if zlib.crc32(body) != crc:
        raise ValueError("fold payload: CRC mismatch")
    fields = layout.unpack(body)
    if fields[0] != tag:
        raise ValueError(
            f"fold payload: tag 0x{fields[0]:02X}, want 0x{tag:02X}"
        )
    return fields


class Fold:
    """One reduction discipline. Accumulators are JSON-able; ``combine``
    is associative and commutative so segmented-WAL merges and replay
    order don't matter. ``idempotent`` declares whether combining
    overlapping coverage is harmless (min/top-k/first-match) or corrupts
    the answer (sum) — the coverage gate consults it."""

    name = "fold"
    idempotent = True

    def initial(self) -> Any:
        return None

    def combine(self, a: Any, b: Any) -> Any:
        raise NotImplementedError

    def of_batch(self, index0: int, values: List[int]) -> Any:
        """Fold one contiguous batch of objective values starting at
        global ``index0`` into a chunk-partial accumulator."""
        raise NotImplementedError

    def is_final(self, acc: Any) -> bool:
        """True when this accumulator already decides the job — the
        coordinator finishes early and Cancel-broadcasts the rest."""
        return False

    def found(self, acc: Any) -> bool:
        """The finish-record ``found`` flag once the range exhausts."""
        return acc is not None

    def encode(self, acc: Any) -> bytes:
        raise NotImplementedError

    def decode(self, data: bytes) -> Any:
        raise NotImplementedError

    def describe(self, acc: Any) -> str:
        """Human rendering for the client CLI."""
        return repr(acc)


class FMin(Fold):
    """Keep the single smallest ``[value, index]``; ties break to the
    lowest index (total order ``(value, index)``, matching the mining
    plane's deterministic winner)."""

    name = "fmin"

    def combine(self, a, b):
        if a is None:
            return None if b is None else list(b)
        if b is None:
            return list(a)
        return list(min((tuple(a), tuple(b))))

    def of_batch(self, index0, values):
        if not values:
            return None
        value = min(values)
        return [value, index0 + values.index(value)]

    def encode(self, acc):
        if acc is None:
            return seal_payload(_BIN_WMIN.pack(_TAG_WMIN, 0, 0, 0))
        value, index = acc
        if not (0 <= value < _U64 and 0 <= index < _U64):
            raise ValueError("fmin acc out of u64 range")
        return seal_payload(_BIN_WMIN.pack(_TAG_WMIN, 1, value, index))

    def decode(self, data):
        _tag, has, value, index = _open_payload(data, _BIN_WMIN, _TAG_WMIN)
        return [value, index] if has else None

    def describe(self, acc):
        if acc is None:
            return "fmin: empty range"
        return f"fmin: value={acc[0]} index={acc[1]}"


class TopK(Fold):
    """Keep the ``k`` smallest ``[value, index]`` pairs, globally sorted
    by ``(value, index)`` — equal values always rank the LOWER global
    index first, so the answer is one deterministic list no matter how
    chunks interleave."""

    name = "topk"

    def __init__(self, k: int):
        if not 1 <= k <= TOPK_SLOTS:
            raise ValueError(f"topk: k must be in [1, {TOPK_SLOTS}]")
        self.k = k

    def initial(self):
        return []

    def combine(self, a, b):
        merged = {int(i): int(v) for v, i in (a or [])}
        # same index seen twice can only carry the same deterministic
        # value; keep the smaller defensively
        for v, i in (b or []):
            v, i = int(v), int(i)
            merged[i] = min(merged.get(i, v), v)
        pairs = sorted([v, i] for i, v in merged.items())
        return pairs[: self.k]

    def of_batch(self, index0, values):
        pairs = sorted(
            [value, index0 + off] for off, value in enumerate(values)
        )
        return pairs[: self.k]

    def found(self, acc):
        return bool(acc)

    def encode(self, acc):
        acc = acc or []
        if len(acc) > TOPK_SLOTS:
            raise ValueError("topk acc exceeds the slot table")
        flat = []
        for value, index in acc:
            if not (0 <= value < _U64 and 0 <= index < _U64):
                raise ValueError("topk acc out of u64 range")
            flat.extend((value, index))
        flat.extend([0] * (2 * TOPK_SLOTS - len(flat)))
        return seal_payload(_BIN_WTOPK.pack(_TAG_WTOPK, len(acc), *flat))

    def decode(self, data):
        fields = _open_payload(data, _BIN_WTOPK, _TAG_WTOPK)
        count = fields[1]
        if count > TOPK_SLOTS:
            raise ValueError("topk payload: count exceeds the slot table")
        return [
            [fields[2 + 2 * s], fields[3 + 2 * s]] for s in range(count)
        ]

    def describe(self, acc):
        if not acc:
            return "topk: empty range"
        rows = "\n".join(
            f"  #{rank + 1} value={v} index={i}"
            for rank, (v, i) in enumerate(acc)
        )
        return f"topk ({len(acc)}):\n{rows}"


class FirstMatch(Fold):
    """The earliest global index whose value is <= ``threshold``.
    ``is_final`` lets the coordinator finish the job on the first
    matching chunk and Cancel-broadcast the outstanding ones — the same
    early-retire path a found mining job takes.

    The accumulator is ``[index, value, probes]`` where a DRY scan is
    ``[None, None, probes]`` — the no-match partial still carries how
    many indices it evaluated, so combining a dry prefix batch with a
    matching one yields chunk-relative probes by construction
    (``probes == index - lo + 1`` is then a verifiable claim, and a dry
    chunk's ``probes == hi - lo + 1`` proves it scanned everything)."""

    name = "fmatch"

    def __init__(self, threshold: int):
        if not 0 <= threshold < _U64:
            raise ValueError("fmatch: threshold out of u64 range")
        self.threshold = threshold

    def combine(self, a, b):
        if a is None:
            return None if b is None else list(b)
        if b is None:
            return list(a)
        probes = a[2] + b[2]
        if a[0] is None:
            keep = b
        elif b[0] is None:
            keep = a
        else:
            keep = a if a[0] <= b[0] else b
        return [keep[0], keep[1], probes]

    def of_batch(self, index0, values):
        for off, value in enumerate(values):
            if value <= self.threshold:
                return [index0 + off, value, off + 1]
        return [None, None, len(values)] if values else None

    def is_final(self, acc):
        return acc is not None and acc[0] is not None

    def found(self, acc):
        return acc is not None and acc[0] is not None

    def encode(self, acc):
        if acc is None:
            acc = [None, None, 0]
        index, value, probes = acc
        if not 0 <= probes < _U64:
            raise ValueError("fmatch probes out of u64 range")
        if index is None:
            return seal_payload(
                _BIN_WMATCH.pack(_TAG_WMATCH, 0, 0, 0, probes)
            )
        if not (0 <= index < _U64 and 0 <= value < _U64):
            raise ValueError("fmatch acc out of u64 range")
        return seal_payload(
            _BIN_WMATCH.pack(_TAG_WMATCH, 1, index, value, probes)
        )

    def decode(self, data):
        _tag, has, index, value, probes = _open_payload(
            data, _BIN_WMATCH, _TAG_WMATCH
        )
        if has:
            return [index, value, probes]
        return [None, None, probes] if probes else None

    def describe(self, acc):
        if acc is None or acc[0] is None:
            return "fmatch: no match"
        return f"fmatch: index={acc[0]} value={acc[1]} probes={acc[2]}"


class FSum(Fold):
    """Map-reduce: ``[total, count]``. NOT idempotent — absorbing the
    same chunk twice double-counts — so exactly-once rests entirely on
    the coverage gate; the journal's interval subtraction and the gate
    see the same ranges, which the property tests pin."""

    name = "fsum"
    idempotent = False

    def initial(self):
        return [0, 0]

    def combine(self, a, b):
        a, b = a or [0, 0], b or [0, 0]
        return [a[0] + b[0], a[1] + b[1]]

    def of_batch(self, index0, values):
        return [sum(values), len(values)]

    def found(self, acc):
        return True

    def encode(self, acc):
        total, count = acc or [0, 0]
        if not (0 <= count < _U64 and 0 <= total < _U128):
            raise ValueError("fsum acc out of range (u128 total, u64 count)")
        return seal_payload(_BIN_WSUM.pack(
            _TAG_WSUM, total.to_bytes(16, "little"), count
        ))

    def decode(self, data):
        _tag, total, count = _open_payload(data, _BIN_WSUM, _TAG_WSUM)
        return [int.from_bytes(total, "little"), count]

    def describe(self, acc):
        acc = acc or [0, 0]
        return f"fsum: total={acc[0]} count={acc[1]}"
