"""Pluggable workload registry (ISSUE 15): the seam that turns the
mining control plane into a sharded-compute framework.

The Assign/Result plane — journaled, replicated, admission-controlled,
hedged — is generic infrastructure that happened to mine. This package
makes the *task type* a registered object instead of an assumption.
Each :class:`Workload` declares:

- a **params codec** — how a Request's opaque ``data`` bytes describe
  the job (tagged + CRC-trailed, same framing discipline as the wire
  codec, proven by the codec-conformance checker);
- a **fold discipline** (:mod:`tpuminter.workloads.folds`) — how chunk
  partials reduce to one answer, resolved per-Request from the params;
- a **verifier** — the off-loop executor check a WorkResult must pass
  before the coordinator journals its settle (the same seam scrypt
  verification uses);
- a **compute seam** — a cooperative generator the cpu/jax workers run
  per-Setup, yielding ``None`` between batches exactly like the mining
  generators, so one worker loop serves every workload.

The coordinator stays workload-blind: it resolves a discipline at
_on_request, then only ever calls the generic fold/coverage helpers
here. Workload-specific logic lives ONLY under this package — that
containment is ISSUE 15's acceptance criterion, diff-provable.

**Coverage-gated fold state.** A job's fold state is
``{"covered": [[lo, hi], ...], "acc": <fold acc>}``. :func:`absorb`
refuses a chunk whose range overlaps what is already covered, which is
what makes the NON-idempotent folds (sum) exactly-once under journal
replay, segmented-WAL merges, WAL re-shipping, and duplicate delivery:
replaying the same settle twice is a structural no-op, the same
guarantee interval subtraction gives the mining ledger.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Tuple

from tpuminter.workloads.folds import (  # noqa: F401  (re-exported)
    FMin, FirstMatch, Fold, FSum, TopK,
)

__all__ = [
    "Workload", "register", "get", "maybe", "by_wid", "names",
    "new_state", "absorb", "absorb_payload", "merge_states", "fold_of",
    "compute", "verify_claim", "window_for", "chunk_cap", "covered_span",
    "Fold", "FMin", "TopK", "FirstMatch", "FSum",
]


class Workload:
    """One registered task type. ``name`` rides Join advertisements,
    Request/Setup objects, and journal records; ``wid`` is the compact
    numeric id on binary WorkResult frames (collision-checked at
    register time and statically by the analysis suite)."""

    name: str = ""
    wid: int = 0

    def fold_for(self, request) -> Fold:
        """Resolve the fold discipline this Request's params ask for.
        Raises ValueError on malformed params (the coordinator turns
        that into a Refuse)."""
        raise NotImplementedError

    def compute(self, request, fold: Fold, engine: str = "cpu"):
        """Cooperative generator: yield ``None`` between batches (the
        worker loop's executor heartbeat), return ``(searched, acc)``."""
        raise NotImplementedError

    def verify(self, request, fold: Fold, acc: Any) -> bool:
        """Off-loop check of a decoded chunk partial against this
        chunk-Request's exact [lower, upper] range."""
        raise NotImplementedError

    def window(self, request, lo: int, hi: int) -> Optional[bytes]:
        """Opaque-domain chunking seam (ISSUE 20): return a params
        frame carrying ONLY what indices ``[lo, hi]`` need (a slice of
        a shipped candidate list), or None when this workload's params
        are already range-independent (the default) and the cached
        full-job Setup suffices. A non-None return makes the
        coordinator ship a per-chunk Setup whose ``data`` is the
        window, so a 100k-candidate catalog never rides one dispatch."""
        return None

    def chunk_cap(self, request) -> int:
        """Upper bound on indices per dispatch for this job (0 = no
        bound, the default). Opaque-domain workloads derive it from a
        per-window byte budget so windowed Setups stay datagram-sized."""
        return 0


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, Workload] = {}
_BY_WID: Dict[int, Workload] = {}


def register(workload: Workload) -> Workload:
    """Register a workload; collisions on name or wid are programming
    errors and fail loudly at import time."""
    if not workload.name:
        raise ValueError("workload needs a non-empty name")
    if not 1 <= workload.wid < 256:
        raise ValueError("workload wid must be a u8 in [1, 255]")
    have = _REGISTRY.get(workload.name)
    if have is not None and have is not workload:
        raise ValueError(f"workload name {workload.name!r} already taken")
    have = _BY_WID.get(workload.wid)
    if have is not None and have is not workload:
        raise ValueError(
            f"workload wid {workload.wid} already taken by {have.name!r}"
        )
    _REGISTRY[workload.name] = workload
    _BY_WID[workload.wid] = workload
    return workload


def get(name: str) -> Workload:
    return _REGISTRY[name]


def maybe(name: str) -> Optional[Workload]:
    return _REGISTRY.get(name)


def by_wid(wid: int) -> Optional[Workload]:
    return _BY_WID.get(wid)


def names() -> Tuple[str, ...]:
    """Sorted registered names — what a worker's Join advertises."""
    return tuple(sorted(_REGISTRY))


# ---------------------------------------------------------------------------
# coverage-gated fold state (the per-fold exactly-once mechanism)
# ---------------------------------------------------------------------------

def new_state(fold: Fold) -> dict:
    return {"covered": [], "acc": fold.initial()}


def _overlaps(covered: List[list], lo: int, hi: int) -> bool:
    return any(not (hi < a or b < lo) for a, b in covered)


def _cover(covered: List[list], lo: int, hi: int) -> List[list]:
    """Insert inclusive [lo, hi] and coalesce touching spans."""
    spans = sorted([list(s) for s in covered] + [[lo, hi]])
    out: List[list] = []
    for a, b in spans:
        if out and a <= out[-1][1] + 1:
            out[-1][1] = max(out[-1][1], b)
        else:
            out.append([a, b])
    return out


def _span(covered: List[list]) -> int:
    return sum(b - a + 1 for a, b in covered)


def absorb(fold: Fold, state: dict, lo: int, hi: int, acc: Any) -> bool:
    """Fold one chunk partial into ``state`` unless its range is
    already covered. Returns False (state untouched) on a duplicate —
    the gate that makes every discipline replay-idempotent."""
    if lo > hi or _overlaps(state["covered"], lo, hi):
        return False
    state["covered"] = _cover(state["covered"], lo, hi)
    state["acc"] = fold.combine(state["acc"], acc)
    return True


def merge_states(
    fold: Fold, a: Optional[dict], b: Optional[dict]
) -> Optional[dict]:
    """Merge two fold states from independent WAL segments
    (journal.merge_states' per-job rule, generalized). Idempotent folds
    combine unconditionally; for sum, overlapping coverage would
    double-count, so overlap degrades to keeping the larger-coverage
    state — the same conservative bias the mining merge takes (re-mine
    rather than corrupt)."""
    if a is None or not a["covered"]:
        return b if a is None else (b or a)
    if b is None or not b["covered"]:
        return a
    disjoint = all(
        not _overlaps(a["covered"], lo, hi) for lo, hi in b["covered"]
    )
    if fold.idempotent or disjoint:
        covered = a["covered"]
        for lo, hi in b["covered"]:
            covered = _cover(covered, lo, hi)
        return {"covered": covered, "acc": fold.combine(a["acc"], b["acc"])}
    return a if _span(a["covered"]) >= _span(b["covered"]) else b


# ---------------------------------------------------------------------------
# the three call sites outside this package: worker, coordinator, journal
# ---------------------------------------------------------------------------

def fold_of(request) -> Optional[Fold]:
    """Resolve the discipline a Request's workload + params name, or
    None when the workload is unknown or the params are malformed."""
    workload = _REGISTRY.get(getattr(request, "workload", "") or "")
    if workload is None:
        return None
    try:
        return workload.fold_for(request)
    except ValueError:
        return None


def compute(request, engine: str = "cpu") -> Iterator:
    """The worker-side seam: run the registered compute generator for
    one chunk-Request and yield its final WorkResult — a drop-in for
    ``miner.mine(request)`` in the worker's executor loop."""
    from tpuminter.protocol import WorkResult

    workload = get(request.workload)
    fold = workload.fold_for(request)
    searched, acc = yield from workload.compute(request, fold, engine)
    yield WorkResult(
        job_id=request.job_id,
        chunk_id=request.chunk_id,
        wid=workload.wid,
        searched=searched,
        payload=fold.encode(acc),
    )


def verify_claim(request, msg) -> bool:
    """The coordinator-side off-loop verifier: does this WorkResult's
    payload hold up against the chunk-Request it answers? Runs in the
    verification executor (same seam as scrypt), so recompute-grade
    verifiers (sum, first-match absence proofs) never stall the loop."""
    workload = _REGISTRY.get(getattr(request, "workload", "") or "")
    if workload is None or getattr(msg, "wid", None) != workload.wid:
        return False
    try:
        fold = workload.fold_for(request)
        acc = fold.decode(msg.payload)
    except ValueError:
        return False
    return workload.verify(request, fold, acc)


def window_for(request, lo: int, hi: int) -> Optional[bytes]:
    """The coordinator-side chunking seam: the params window covering
    ``[lo, hi]`` of this job, or None when the cached full-job template
    already serves every chunk (unknown workloads and malformed params
    also answer None — dispatch then proceeds classically and the
    worker refuses or fails verification downstream)."""
    workload = _REGISTRY.get(getattr(request, "workload", "") or "")
    if workload is None:
        return None
    try:
        return workload.window(request, lo, hi)
    except ValueError:
        return None


def chunk_cap(request) -> int:
    """Per-dispatch index cap for this job (0 = unbounded)."""
    workload = _REGISTRY.get(getattr(request, "workload", "") or "")
    if workload is None:
        return 0
    try:
        return max(0, int(workload.chunk_cap(request)))
    except ValueError:
        return 0


def covered_span(state: Optional[dict]) -> int:
    """Settled-index count of a fold state (0 for None) — the
    numerator of a streaming Emit's coverage fraction."""
    return _span(state["covered"]) if state else 0


def absorb_payload(
    request, state: Optional[dict], lo: int, hi: int, payload: bytes
) -> Tuple[Optional[dict], bool]:
    """The journal-side seam: absorb one settle record's ``"wp"`` bytes
    into a (possibly fresh) fold state, coverage-gated. Returns
    ``(state, absorbed)``; a duplicate or undecodable payload leaves
    the state untouched — replay never corrupts, it only skips."""
    workload = _REGISTRY.get(getattr(request, "workload", "") or "")
    if workload is None:
        return state, False
    try:
        fold = workload.fold_for(request)
        acc = fold.decode(payload)
    except ValueError:
        return state, False
    if state is None:
        state = new_state(fold)
    return state, absorb(fold, state, lo, hi, acc)


# built-in workloads self-register on import (bottom import: the
# registry API above must exist before hashcore's module body runs)
from tpuminter.workloads import hashcore  # noqa: E402,F401
from tpuminter.workloads import dictsearch  # noqa: E402,F401
