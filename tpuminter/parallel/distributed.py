"""Multi-process (multi-host) mesh support (SURVEY.md §5 comm-backend
row; VERDICT r3 missing #2).

Two pieces:

- **Bootstrap**: :func:`init_from_env` wires this process into a
  ``jax.distributed`` cluster. On real multi-host TPU slices
  ``jax.distributed.initialize()`` auto-detects the topology; on CPU
  (CI) the ``TPUMINTER_COORD_ADDR`` / ``TPUMINTER_NUM_PROCS`` /
  ``TPUMINTER_PROC_ID`` env triple pins the rendezvous explicitly and
  collectives run over Gloo. After init, ``jax.devices()`` is the
  GLOBAL device list, so ``parallel.make_mesh()`` builds a mesh spanning
  every host and the ``shard_map`` sweeps' or-reduce/argmin collectives
  ride ICI within a slice and DCN across — inserted by XLA from the
  same programs CI runs on the virtual mesh.

- **Leader→follower channel**: multi-process JAX is SPMD — every
  process must issue the same device programs in the same order. The
  worker role is asymmetric (only one process talks to the mining
  coordinator), so the leader (process 0) mirrors its request stream
  and per-step liveness to followers with the tiny broadcasts below,
  and followers replay the identical (deterministic) ``Miner``
  generator. See ``pod_worker.follower_loop``.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

ENV_ADDR = "TPUMINTER_COORD_ADDR"
ENV_NPROCS = "TPUMINTER_NUM_PROCS"
ENV_PID = "TPUMINTER_PROC_ID"

__all__ = [
    "init_from_env",
    "is_leader",
    "broadcast_flag",
    "broadcast_bytes",
    "ENV_ADDR",
    "ENV_NPROCS",
    "ENV_PID",
]


def init_from_env() -> bool:
    """Join the ``jax.distributed`` cluster the environment describes.

    Returns True iff this process is part of a multi-process mesh.
    Explicit ``TPUMINTER_*`` rendezvous wins; otherwise real multi-host
    TPU backends are left to ``jax.distributed``'s auto-detection (a
    no-op single process on CPU/CI).
    """
    import jax

    addr = os.environ.get(ENV_ADDR)
    if addr is not None:
        jax.distributed.initialize(
            addr,
            num_processes=int(os.environ[ENV_NPROCS]),
            process_id=int(os.environ[ENV_PID]),
            # an orphaned process (its peer crashed mid-collective) must
            # self-terminate promptly — the coordinator has already
            # requeued the pod's chunk, so a hung follower is pure leak;
            # jax's default 100 s is tuned for flaky DCN, not localhost
            heartbeat_timeout_seconds=int(
                os.environ.get("TPUMINTER_HEARTBEAT_S", "30")
            ),
        )
    return jax.process_count() > 1


def is_leader() -> bool:
    import jax

    return jax.process_index() == 0


def broadcast_flag(value: Optional[int] = None) -> int:
    """Broadcast one small int from the leader (followers pass None)."""
    from jax.experimental import multihost_utils as mhu

    v = np.int32(value if value is not None else 0)
    return int(mhu.broadcast_one_to_all(v))


def broadcast_bytes(data: Optional[bytes] = None) -> bytes:
    """Broadcast a byte string from the leader (followers pass None).

    Length travels first so every process agrees on the (power-of-two
    padded, to bound the jit cache) payload shape before the payload
    collective runs.
    """
    from jax.experimental import multihost_utils as mhu

    n = broadcast_flag(len(data) if data is not None else 0)
    if n == 0:
        return b""
    size = 1 << (n - 1).bit_length()
    buf = np.zeros(size, dtype=np.uint8)
    if data is not None:
        buf[:n] = np.frombuffer(data, dtype=np.uint8)
    out = np.asarray(mhu.broadcast_one_to_all(buf))
    return out[:n].tobytes()
