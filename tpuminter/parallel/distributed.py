"""Multi-process (multi-host) mesh support (SURVEY.md §5 comm-backend
row; VERDICT r3 missing #2).

Two pieces:

- **Bootstrap**: :func:`init_from_env` wires this process into a
  ``jax.distributed`` cluster. On real multi-host TPU slices
  ``jax.distributed.initialize()`` auto-detects the topology; on CPU
  (CI) the ``TPUMINTER_COORD_ADDR`` / ``TPUMINTER_NUM_PROCS`` /
  ``TPUMINTER_PROC_ID`` env triple pins the rendezvous explicitly and
  collectives run over Gloo. After init, ``jax.devices()`` is the
  GLOBAL device list, so ``parallel.make_mesh()`` builds a mesh spanning
  every host and the ``shard_map`` sweeps' or-reduce/argmin collectives
  ride ICI within a slice and DCN across — inserted by XLA from the
  same programs CI runs on the virtual mesh.

- **Leader→follower channel**: multi-process JAX is SPMD — every
  process must issue the same device programs in the same order. The
  worker role is asymmetric (only one process talks to the mining
  coordinator), so the leader (process 0) mirrors its request stream
  and per-step liveness to followers with the tiny broadcasts below,
  and followers replay the identical (deterministic) ``Miner``
  generator. See ``pod_worker.follower_loop``.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

ENV_ADDR = "TPUMINTER_COORD_ADDR"
ENV_NPROCS = "TPUMINTER_NUM_PROCS"
ENV_PID = "TPUMINTER_PROC_ID"

__all__ = [
    "init_from_env",
    "is_leader",
    "broadcast_flag",
    "broadcast_bytes",
    "ENV_ADDR",
    "ENV_NPROCS",
    "ENV_PID",
]


def init_from_env() -> bool:
    """Join the ``jax.distributed`` cluster the environment describes.

    Returns True iff this process is part of a multi-process mesh.
    Explicit ``TPUMINTER_*`` rendezvous wins; otherwise real multi-host
    TPU backends are left to ``jax.distributed``'s auto-detection (a
    no-op single process on CPU/CI).
    """
    import jax

    addr = os.environ.get(ENV_ADDR)
    if addr is not None:
        try:
            # jax 0.4.x: CPU cross-process collectives exist but are off
            # by default — without this, the first shard_map collective
            # dies with "Multiprocess computations aren't implemented on
            # the CPU backend". Newer jax defaults to gloo and drops the
            # knob, hence the guard.
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):
            pass
        kwargs = dict(
            num_processes=int(os.environ[ENV_NPROCS]),
            process_id=int(os.environ[ENV_PID]),
        )
        try:
            # an orphaned process (its peer crashed mid-collective) must
            # self-terminate promptly — the coordinator has already
            # requeued the pod's chunk, so a hung follower is pure leak;
            # jax's default 100 s is tuned for flaky DCN, not localhost
            jax.distributed.initialize(
                addr,
                heartbeat_timeout_seconds=int(
                    os.environ.get("TPUMINTER_HEARTBEAT_S", "30")
                ),
                **kwargs,
            )
        except TypeError:
            # older jax (0.4.x): no heartbeat knob — the runtime's baked
            # defaults govern orphan teardown instead (slower detection,
            # same cascade; tests deriving bounds from TPUMINTER_HEARTBEAT_S
            # must tolerate the default-timeout regime)
            jax.distributed.initialize(addr, **kwargs)
    return jax.process_count() > 1


def is_leader() -> bool:
    import jax

    return jax.process_index() == 0


#: bytes per broadcast collective. EVERY broadcast uses this one fixed
#: shape — one compiled computation, one collective channel — so
#: consecutive broadcasts can never be cross-matched by the transport.
#: (Observed on Gloo/jaxlib-0.4.37: a 4-byte flag collective and a
#: padded payload collective got matched to each other under load —
#: ``gloo::EnforceNotMet: op.preamble.length <= op.nbytes, 128 vs 4`` —
#: because separately-compiled CPU collectives can share a channel tag.
#: Fixed-shape frames make the stream self-synchronizing by
#: construction; a 4 KiB frame per generator step is noise against the
#: ≥100 ms device spans the steps gate.)
FRAME = 4096
_WORDS = FRAME // 4  # frames travel as int32 words: the broadcast's
# underlying psum would promote uint8 to int32 anyway (jnp.sum), which
# silently reshaped/corrupted byte frames — int32 in, int32 out is the
# dtype-stable contract


def _bcast(words: np.ndarray) -> np.ndarray:
    from jax.experimental import multihost_utils as mhu

    return np.asarray(mhu.broadcast_one_to_all(words)).astype(np.int32)


def broadcast_flag(value: Optional[int] = None) -> int:
    """Broadcast one small int from the leader (followers pass None)."""
    buf = np.zeros(_WORDS, dtype=np.int32)
    if value is not None:
        buf[0] = np.int32(value)
    return int(_bcast(buf)[0])


def broadcast_bytes(data: Optional[bytes] = None) -> bytes:
    """Broadcast a byte string from the leader (followers pass None).

    Length travels first (its own frame) so every process agrees on the
    frame count; the payload then streams in whole :data:`FRAME`-byte
    chunks."""
    n = broadcast_flag(len(data) if data is not None else 0)
    if n == 0:
        return b""
    out = bytearray()
    for off in range(0, n, FRAME):
        take = min(FRAME, n - off)
        buf = np.zeros(_WORDS, dtype=np.int32)
        if data is not None:
            padded = np.zeros(FRAME, dtype=np.uint8)
            padded[:take] = np.frombuffer(
                data[off:off + take], dtype=np.uint8
            )
            buf[:] = padded.view(np.int32)
        out += _bcast(buf).tobytes()[:take]
    return bytes(out)
