"""Multi-chip scale-out: mesh construction and pod-wide mining sweeps.

The reference scales by running more miner *processes* against the
coordinator (SURVEY.md §2 parallelism inventory); the TPU rebuild scales
*within* a worker by sharding the nonce axis across the chips of a slice
(BASELINE.json:5): ``shard_map`` over a 1-D device mesh, each chip owning
a contiguous nonce shard, with XLA collectives over ICI for the
found-flag or-reduce / argmin folds. Across slices (DCN), scale-out goes
back through the control plane: one worker process per slice, each
Joining the coordinator like any other miner.
"""

from tpuminter.parallel.mesh import (
    build_candidate_sweep,
    build_exact_sweep_pallas,
    build_min_fold,
    build_min_sweep_pallas,
    build_rolled_sweep,
    build_scrypt_sweep,
    build_target_sweep,
    make_mesh,
)

__all__ = [
    "make_mesh",
    "build_target_sweep",
    "build_min_fold",
    "build_min_sweep_pallas",
    "build_exact_sweep_pallas",
    "build_candidate_sweep",
    "build_rolled_sweep",
    "build_scrypt_sweep",
]
