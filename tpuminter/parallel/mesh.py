"""1-D nonce mesh: shard_map sweeps with ICI collectives (SURVEY.md §7
stage 5; BASELINE.json:5).

Layout: a sweep covers ``n_batches × batch_per_device`` nonces *per
device*, and device ``d`` owns the contiguous shard starting at
``start + d · n_batches · batch_per_device`` — contiguous per chip, as
the north-star specifies, so a found nonce pins down which chip searched
what without any gather.

Early exit: a ``lax.while_loop`` steps through batches; each iteration
ends with a pod-wide **or-reduce of the found flag over ICI**
(``lax.pmax`` on a u32 flag), so every chip stops within one batch of the
first sub-target hash anywhere on the pod — no host round-trip in the
loop. The winner is folded with a ``pmin`` on the winning nonce plus a
masked ``psum`` to broadcast its digest (disjoint shards ⇒ exactly one
contributor).

Everything compiles under ``jit`` with static shapes; the same code runs
on a real TPU slice and on the fake 8-device CPU mesh CI uses
(tests/conftest.py, ``__graft_entry__.dryrun_multichip``).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpuminter.ops import sha256 as ops

__all__ = [
    "make_mesh",
    "build_target_sweep",
    "build_min_fold",
    "build_min_sweep_pallas",
    "build_exact_sweep_pallas",
    "build_candidate_sweep",
    "build_rolled_sweep",
]

AXIS = "nonce"


def _shard_map(f, mesh, in_specs, out_specs):
    """One seam for the shard_map API across JAX vintages: newer
    releases expose ``jax.shard_map`` with ``check_vma``; older ones
    (e.g. 0.4.x) only have ``jax.experimental.shard_map.shard_map``
    with the ``check_rep`` spelling of the same knob. The replication
    check is disabled either way — the sweeps' collectives produce
    replicated outputs by construction, and the checker predates some
    of the collective patterns used here."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over all (or the given) devices, axis name "nonce"."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (AXIS,))


def build_target_sweep(
    mesh: Mesh,
    template: ops.NonceTemplate,
    *,
    batch_per_device: int,
    n_batches: int,
) -> Callable:
    """Compile a pod-wide TARGET-mode sweep with EXACT min tracking —
    the pod's ``--exact-min`` engine (PodMiner routes TARGET through it
    when fleets need CpuMiner-compatible exhausted-range minima; the
    fast candidate pipeline tracks minima only when a candidate
    surfaces).

    Returns ``sweep(start_u32, target_words_u32x8, limit_u32) ->
    (found_u32, nonce_u32, digest_words_u32x8, batches_done_u32)`` —
    replicated scalars/vectors, identical on every chip. Nonces past the
    inclusive ``limit`` are masked out of both the winner test and the
    min fold, so a ragged final span stays exact. ``batches_done`` tells
    the host how much of the sweep actually ran (early exit) for
    hash-rate accounting; when nothing is found the digest/nonce outputs
    are the pod-wide exact minimum over the covered (unmasked) nonces.
    """
    n_dev = mesh.devices.size
    per_dev_total = np.uint32(n_batches * batch_per_device)

    def per_device(start: jnp.ndarray, target_words: jnp.ndarray,
                   limit: jnp.ndarray):
        d = lax.axis_index(AXIS).astype(jnp.uint32)
        dev_start = start + d * per_dev_total

        def cond(state):
            b, found, _, _, _ = state
            return (b < n_batches) & (found == 0)

        def body(state):
            b, _, _, _, best = state
            best_words, best_nonce = best
            nonces = (
                dev_start
                + b.astype(jnp.uint32) * np.uint32(batch_per_device)
                + jnp.arange(batch_per_device, dtype=jnp.uint32)
            )
            digests = ops.double_sha256_header_batch(template, nonces)
            hw = ops.hash_words_be(digests)
            # ragged-end mask: out-of-range lanes neither win nor fold.
            # `nonces >= start` kills lanes whose u32 arithmetic wrapped
            # past 2^32 in a top-of-range chunk (they'd otherwise pass
            # the <= limit test with small wrapped values).
            valid = (nonces <= limit) & (nonces >= start)
            hw = jnp.where(valid[:, None], hw, np.uint32(0xFFFFFFFF))
            ok = ops.lex_le(hw, target_words) & valid
            local_found = ok.any()
            first = jnp.argmax(ok)
            # pod-wide or-reduce over ICI: the early-exit signal
            found = lax.pmax(local_found.astype(jnp.uint32), AXIS)
            # winner fold: lowest winning nonce wins; its digest comes via
            # a masked psum (shards are disjoint ⇒ one contributor)
            cand_nonce = jnp.where(local_found, nonces[first], np.uint32(0xFFFFFFFF))
            win_nonce = lax.pmin(cand_nonce, AXIS)
            is_winner = local_found & (cand_nonce == win_nonce)
            win_digest = lax.psum(
                jnp.where(is_winner, digests[first], np.uint32(0)), AXIS
            )
            # best-effort min fold (for the exhausted case): local lex-min
            # this batch vs carried best, in hash-value word order
            midx = ops.lex_argmin(hw)
            batch_best_words = hw[midx]
            batch_best_nonce = nonces[midx]
            keep = ops.lex_le(best_words, batch_best_words)
            new_best_words = jnp.where(keep, best_words, batch_best_words)
            new_best_nonce = jnp.where(keep, best_nonce, batch_best_nonce)
            return (
                b + 1,
                found,
                win_nonce,
                win_digest,
                (new_best_words, new_best_nonce),
            )

        init = (
            jnp.uint32(0),
            jnp.uint32(0),
            jnp.uint32(0xFFFFFFFF),
            jnp.zeros(8, dtype=jnp.uint32),
            (jnp.full(8, 0xFFFFFFFF, dtype=jnp.uint32), jnp.uint32(0)),
        )
        b, found, win_nonce, win_digest, (best_words, best_nonce) = lax.while_loop(
            cond, body, init
        )
        # exhausted: fold the per-device best across the pod. all_gather
        # of 8 u32 per chip is trivial ICI traffic; argmin on-replica.
        all_words = lax.all_gather(best_words, AXIS)      # (n_dev, 8)
        all_nonces = lax.all_gather(best_nonce, AXIS)     # (n_dev,)
        bi = ops.lex_argmin(all_words)
        # hash words (msb-first) → digest words for uniform host decoding
        fallback_digest = ops.hash_words_be(all_words[bi])
        nonce_out = jnp.where(found > 0, win_nonce, all_nonces[bi])
        digest_out = jnp.where(found > 0, win_digest, fallback_digest)
        return found, nonce_out, digest_out, b

    sharded = _shard_map(
        per_device, mesh, in_specs=(P(), P(), P()), out_specs=(P(), P(), P(), P())
    )
    return jax.jit(sharded)


def build_min_sweep_pallas(
    mesh: Mesh,
    template: ops.NonceTemplate,
    *,
    slab_per_device: int,
    tiles_per_step: int = 8,
) -> Callable:
    """Compile the PRODUCTION pod-wide MIN-mode (toy dialect) step: each
    chip folds its contiguous ``slab_per_device`` 64-bit nonces through
    the fused Pallas toy kernel (``kernels.pallas_min_toy`` — the same
    engine the single-chip TpuMiner runs, VERDICT r3 weak #3), then the
    per-chip ``(fold, argmin)`` candidates fold over ICI.

    Returns ``step(start_hi_u32, start_lo_u32) -> (fold_hi, fold_lo,
    nonce_hi, nonce_lo)`` — replicated. FULL spans only (the Pallas
    kernel's lane mask is static): the host runs ragged tails through
    the single-chip kernel. The jnp ``build_min_fold`` remains the CPU-
    mesh/CI engine (dynamic limit masking, small batches).
    """
    from tpuminter.kernels import pallas_min_toy

    def per_device(start_hi, start_lo):
        d = lax.axis_index(AXIS).astype(jnp.uint32)
        base_lo = start_lo + d * np.uint32(slab_per_device)
        base_hi = start_hi + (base_lo < start_lo).astype(jnp.uint32)
        fh, fl, off = pallas_min_toy(
            template, base_hi, base_lo, slab_per_device, tiles_per_step
        )
        n_lo = base_lo + off.astype(jnp.uint32)
        n_hi = base_hi + (n_lo < base_lo).astype(jnp.uint32)
        fold = jnp.stack([fh, fl])
        all_fold = lax.all_gather(fold, AXIS)     # (n_dev, 2)
        all_hi = lax.all_gather(n_hi, AXIS)
        all_lo = lax.all_gather(n_lo, AXIS)
        bi = ops.lex_argmin(all_fold)
        return all_fold[bi][0], all_fold[bi][1], all_hi[bi], all_lo[bi]

    sharded = _shard_map(
        per_device, mesh, in_specs=(P(), P()), out_specs=(P(), P(), P(), P())
    )
    return jax.jit(sharded)


def build_exact_sweep_pallas(
    mesh: Mesh,
    template: ops.NonceTemplate,
    target_words: Sequence[int],
    *,
    slab_per_device: int,
    tiles_per_step: int = 8,
) -> Callable:
    """Compile the PRODUCTION pod-wide exact-min TARGET step: each chip
    folds its contiguous ``slab_per_device`` nonces through the fused
    tracking kernel (``kernels.pallas_search_target`` — full in-kernel
    256-bit compare plus the running lexicographic-min fold, the same
    engine the single-chip ``--exact-min`` path runs), then the per-chip
    winner/minimum candidates fold over ICI. This is the
    ``build_min_sweep_pallas``/``build_min_fold`` split applied to
    exact-min (VERDICT r5 weak #1: the jnp ``build_target_sweep`` body
    at 2^16-nonce batches left the pod ~1000× below the chip's
    demonstrated tracking-kernel rate).

    Returns ``sweep(start_u32) -> (11,) u32`` — ONE replicated device
    array per call (resolving scalars separately costs one tunnel RTT
    each; cf. ``search.pack_handle``), laid out as
    ``[found, win_nonce, min_hash_words×8, min_nonce]``:

    - ``found != 0`` iff some chip's slab contains ``hash <= target``;
      ``win_nonce`` is then the lowest winning nonce *among the chips'
      in-kernel first hits* (each chip early-exits its own slab, so as
      in ``build_target_sweep`` a later chip's hit ends the sweep while
      lower unswept nonces wait for the host's next span — the host
      loop resolves spans in order, preserving the per-span-granular
      lowest-winner contract the jnp path has).
    - otherwise ``min_hash_words`` (msb-first hash-value words) /
      ``min_nonce`` are the pod-wide EXACT minimum over the whole
      ``n_dev × slab_per_device`` span.

    FULL spans only (the kernel specializes on ``n`` at compile time):
    the host runs ragged tails through the single-chip kernel, exactly
    like the MIN pallas path. ``target_words`` are baked static (the
    tracking kernel folds the compare into the instruction stream), so
    one compile serves one (header, target) pair — exact-min fleets
    mine one job at a time, where that is the right trade.
    """
    from tpuminter.kernels import pallas_search_target

    tw = tuple(int(t) for t in target_words)
    umax = np.uint32(0xFFFFFFFF)

    def per_device(start):
        d = lax.axis_index(AXIS).astype(jnp.uint32)
        base = start + d * np.uint32(slab_per_device)
        found, first, min_words, min_off = pallas_search_target(
            template, tw, base, slab_per_device, tiles_per_step
        )
        # winner fold: lowest first-hit nonce among this sweep's finders
        cand = jnp.where(found > 0, base + first, umax)
        pod_found = lax.pmax(found, AXIS)
        win_nonce = lax.pmin(cand, AXIS)
        # exact-min fold: all_gather of 9 u32 per chip is trivial ICI
        # traffic; lexicographic argmin on-replica
        all_words = lax.all_gather(min_words, AXIS)        # (n_dev, 8)
        all_nonces = lax.all_gather(base + min_off, AXIS)  # (n_dev,)
        bi = ops.lex_argmin(all_words)
        return jnp.concatenate([
            pod_found.reshape(1),
            win_nonce.reshape(1),
            all_words[bi],
            all_nonces[bi].reshape(1),
        ])

    sharded = _shard_map(
        per_device, mesh, in_specs=(P(),), out_specs=P()
    )
    return jax.jit(sharded)


def build_candidate_sweep(
    mesh: Mesh,
    template: ops.NonceTemplate,
    *,
    slab_per_device: int,
    n_slabs: int,
    tiles_per_step: int = 8,
    kernel: str = "auto",
    dynamic_header: bool = False,
) -> Callable:
    """Compile the PRODUCTION pod-wide candidate sweep (BASELINE.json:5;
    VERDICT r2 #3): the same early-reject candidate test the single-chip
    hot path runs (``kernels.pallas_search_candidates``), distributed
    over the mesh with a pod-wide **ICI or-reduce** between slabs so
    every chip stops within one slab of the first candidate anywhere.

    **Slab striping.** Work is assigned round-robin at slab granularity:
    in stripe ``b`` device ``d`` sweeps the contiguous slab starting at
    ``start + (b·n_dev + d)·slab_per_device``. Each chip's unit of work
    stays a contiguous multi-million-nonce slab (the north-star's
    contiguous-shard intent), but successive stripes interleave across
    the pod — that is what makes the early exit *exact*: when the
    or-reduce fires at stripe ``b``, every slab in stripes ``< b`` was
    fully swept on some chip, and within stripe ``b`` each chip swept
    up to its own first candidate, so the ``pmin`` of stripe-``b``
    candidates is the lowest candidate in the covered prefix and every
    nonce below it is provably candidate-free. With whole-range
    contiguous shards that claim would be false (a lower chip could
    still be mid-shard when a higher chip hits), and the exact
    lowest-winner contract ``search.CandidateSearch`` depends on would
    break.

    Returns ``sweep(start_u32, cap_biased_i32) -> (found_u32,
    first_off_u32, stripes_done_u32)`` — replicated scalars.
    ``first_off`` is the lowest candidate's offset FROM ``start``
    (valid iff ``found``) — offsets, not absolute nonces, so the fold
    order stays correct when a dispatched span wraps past 2^32 (a
    wrapped absolute nonce would compare below in-range ones) and a
    candidate at nonce 0xFFFFFFFF cannot collide with the not-found
    sentinel (``found`` travels as its own flag). ``cap_biased`` is
    the sign-biased hash-word-1 cap (see
    ``kernels.pallas_search_candidates``). The whole call covers
    ``n_dev × n_slabs × slab_per_device`` consecutive nonces from
    ``start`` with at most ``n_slabs`` ICI round-trips and ZERO host
    syncs.

    ``kernel`` selects the per-slab engine: ``"pallas"`` (the fused
    candidate kernel — the production TPU path), ``"jnp"`` (same
    candidate condition via the jnp ops — compiles on the CPU mesh, the
    CI path), or ``"auto"`` (pallas iff the default backend is not
    CPU).

    ``dynamic_header=True`` builds the extranonce-roll consumer
    (BASELINE.json:9-10 at pod scale): the sweep takes two extra
    replicated args ``(midstate8, tailw3)`` — the on-device roll's
    outputs — instead of baking ``template``, so ONE compiled pod
    program serves every extranonce (and every header-mining job).
    """
    if kernel == "auto":
        kernel = "jnp" if jax.default_backend() == "cpu" else "pallas"
    if kernel not in ("pallas", "jnp"):
        raise ValueError(f"unknown kernel {kernel!r}")
    n_dev = mesh.devices.size
    slab = slab_per_device
    umax = np.uint32(0xFFFFFFFF)

    if kernel == "pallas":
        from tpuminter.kernels import (
            pallas_search_candidates,
            pallas_search_candidates_hdr,
        )

        def slab_sweep(base, cap_biased, hdr):
            cap = jax.lax.bitcast_convert_type(
                cap_biased, jnp.uint32
            ) ^ jnp.uint32(0x80000000)
            if dynamic_header:
                return pallas_search_candidates_hdr(
                    hdr[0], hdr[1], base, slab, tiles_per_step, cap
                )
            return pallas_search_candidates(
                template, base, slab, tiles_per_step, cap
            )
    else:

        def slab_sweep(base, cap_biased, hdr):
            nonces = base + jnp.arange(slab, dtype=jnp.uint32)
            if dynamic_header:
                digests = ops.header_digest_dyn(hdr[0], hdr[1], nonces)
            else:
                digests = ops.double_sha256_header_batch(template, nonces)
            hw = ops.hash_words_be(digests)
            hw1b = jax.lax.bitcast_convert_type(
                hw[:, 1] ^ jnp.uint32(0x80000000), jnp.int32
            )
            ok = (hw[:, 0] == 0) & (hw1b <= cap_biased)
            return ok.any().astype(jnp.uint32), jnp.argmax(ok).astype(jnp.uint32)

    def per_device(start, cap_biased, *hdr):
        d = lax.axis_index(AXIS).astype(jnp.uint32)

        def cond(state):
            b, found, _ = state
            return (b < n_slabs) & (found == 0)

        def body(state):
            b, _, _ = state
            slab_idx = b * np.uint32(n_dev) + d
            base = start + slab_idx * np.uint32(slab)
            f, off = slab_sweep(base, cap_biased, hdr)
            local = (f > 0) & (off < slab)
            cand_off = slab_idx * np.uint32(slab) + off.astype(jnp.uint32)
            # pod-wide or-reduce over ICI: the early-exit signal; pmin
            # folds the stripe's lowest candidate offset in the same
            # round (offsets, not absolute nonces — see docstring)
            found = lax.pmax(local.astype(jnp.uint32), AXIS)
            first = lax.pmin(jnp.where(local, cand_off, umax), AXIS)
            return b + 1, found, first

        b, found, first = lax.while_loop(
            cond, body, (jnp.uint32(0), jnp.uint32(0), umax)
        )
        return found, first, b

    n_in = 4 if dynamic_header else 2
    sharded = _shard_map(
        per_device, mesh, in_specs=(P(),) * n_in, out_specs=(P(), P(), P())
    )
    return jax.jit(sharded)


def build_rolled_sweep(
    mesh: Mesh,
    *,
    width: int,
    rows: int,
    tiles_per_step: int = 8,
    kernel: str = "auto",
    cand_bits: int = 32,
) -> Callable:
    """Compile the pod-wide BATCHED rolled candidate sweep
    (``tpuminter.rolled`` at slice scale): one call sweeps ``rows`` roll
    ROWS — ``chain.rolled_tiles`` of a global window, each row up to
    ``width`` nonces of its own extranonce's header — sharded over the
    mesh, with the same stripe-synchronous ICI or-reduce early exit as
    :func:`build_candidate_sweep`.

    **Row striping.** ``rows`` must be a multiple of ``n_dev``; the
    caller lays rows out device-major (``rolled.plan_tiles(...,
    interleave=n_dev)``) so that stripe ``s`` = global-order rows
    ``[s·n_dev, (s+1)·n_dev)``, one per device. When the or-reduce
    fires at stripe ``s``, every row in earlier stripes was fully swept
    and within stripe ``s`` each device swept up to its own first
    candidate — so the ``pmin`` over per-row global offsets is the
    lowest candidate in the covered prefix, the exact-lowest-winner
    claim ``search.CandidateSearch`` depends on (the slab-striping
    argument of :func:`build_candidate_sweep`, row-shaped).

    Returns ``sweep(midstates (rows, 8), tailws (rows, 3), bases (rows,),
    valids (rows,), goffs (rows,), cap_biased) -> (found_u32,
    first_goff_u32, stripes_done_u32)`` — replicated scalars;
    ``first_goff`` is the lowest candidate's GLOBAL offset from the
    window start (valid iff ``found``). Rows are masked to their
    ``valids`` exactly: an over-swept or padding row can report a
    candidate past its valid count, which the fold drops — sound,
    because the row's valid prefix was then swept clean. Nothing
    job-specific is baked: one compiled program serves every job and
    every extranonce (``cand_bits`` is the jnp engine's test seam, 32 =
    production).
    """
    from tpuminter.rolled import _jnp_candidate_ok, _resolve_engine

    kernel = _resolve_engine(kernel)
    n_dev = mesh.devices.size
    if rows % n_dev != 0:
        raise ValueError(f"rows {rows} must be a multiple of n_dev {n_dev}")
    rows_pd = rows // n_dev
    umax = np.uint32(0xFFFFFFFF)

    if kernel == "pallas":
        if cand_bits != 32:
            raise ValueError("cand_bits is a jnp-engine test seam only")
        from tpuminter.kernels import pallas_search_candidates_hdr

        def row_sweep(mid, tw, base, cap_biased):
            cap = jax.lax.bitcast_convert_type(
                cap_biased, jnp.uint32
            ) ^ jnp.uint32(0x80000000)
            return pallas_search_candidates_hdr(
                mid, tw, base, width, tiles_per_step, cap
            )
    else:

        def row_sweep(mid, tw, base, cap_biased):
            nonces = base + jnp.arange(width, dtype=jnp.uint32)
            digests = ops.header_digest_dyn(mid, tw, nonces)
            # one source of truth for the candidate bar: un-bias the
            # cap back to u32 and apply tpuminter.rolled's test
            cap = jax.lax.bitcast_convert_type(
                cap_biased, jnp.uint32
            ) ^ jnp.uint32(0x80000000)
            ok = _jnp_candidate_ok(digests, cap, cand_bits)
            return ok.any().astype(jnp.uint32), jnp.argmax(ok).astype(jnp.uint32)

    def per_device(mids, tails, bases, valids, goffs, cap_biased):
        def cond(state):
            s, found, _ = state
            return (s < rows_pd) & (found == 0)

        def body(state):
            s, _, _ = state
            mid = lax.dynamic_index_in_dim(mids, s, 0, keepdims=False)
            tw = lax.dynamic_index_in_dim(tails, s, 0, keepdims=False)
            base = lax.dynamic_index_in_dim(bases, s, 0, keepdims=False)
            valid = lax.dynamic_index_in_dim(valids, s, 0, keepdims=False)
            goff = lax.dynamic_index_in_dim(goffs, s, 0, keepdims=False)
            f, off = row_sweep(mid, tw, base, cap_biased)
            local = (f > 0) & (off < valid)
            cand = jnp.where(local, goff + off, umax)
            # pod-wide or-reduce over ICI: the early-exit signal; pmin
            # folds the stripe's lowest GLOBAL candidate offset in the
            # same round
            found = lax.pmax(local.astype(jnp.uint32), AXIS)
            first = lax.pmin(cand, AXIS)
            return s + 1, found, first

        s, found, first = lax.while_loop(
            cond, body, (jnp.int32(0), jnp.uint32(0), umax)
        )
        return found, first, s.astype(jnp.uint32)

    sharded = _shard_map(
        per_device, mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P()),
        out_specs=(P(), P(), P()),
    )
    return jax.jit(sharded)


def build_scrypt_sweep(
    mesh: Mesh,
    *,
    batch_per_device: int,
    n_log2: int = 10,
) -> Callable:
    """Compile a pod-wide SCRYPT-mode batch step (BASELINE.json:11 at
    slice scale): device ``d`` hashes the contiguous batch starting at
    ``start + d · batch_per_device`` through the jnp scrypt pipeline
    (``ops.scrypt.scrypt_header_batch`` — header words are runtime
    values, one compile serves every job and extranonce), then the pod
    folds a winner flag (or-reduce), the first winning nonce (pmin),
    and the running lexicographic minimum (all_gather + argmin) over
    ICI.

    Returns ``step(header76w_u32x19, start_u32, target_words_u32x8) ->
    (found_u32, win_nonce_u32, win_digest_u32x8, min_digest_u32x8,
    min_nonce_u32)`` — replicated. The host loops steps across a chunk
    (scrypt has no candidate trick: the full hash is the test, so each
    step is an exact sweep of ``n_dev × batch_per_device`` nonces).
    Memory: ``batch_per_device × 128·2^n_log2`` bytes of V per chip.
    """

    def per_device(hw19, start, target_words):
        from tpuminter.ops import scrypt as scrypt_ops

        d = lax.axis_index(AXIS).astype(jnp.uint32)
        nonces = (
            start + d * np.uint32(batch_per_device)
            + jnp.arange(batch_per_device, dtype=jnp.uint32)
        )
        digests = scrypt_ops.scrypt_header_batch(hw19, nonces, n_log2)
        hw = ops.hash_words_be(digests)
        ok = ops.lex_le(hw, target_words)
        local_found = ok.any()
        first = jnp.argmax(ok)
        found = lax.pmax(local_found.astype(jnp.uint32), AXIS)
        cand = jnp.where(local_found, nonces[first], np.uint32(0xFFFFFFFF))
        win_nonce = lax.pmin(cand, AXIS)
        is_winner = local_found & (cand == win_nonce)
        win_digest = lax.psum(
            jnp.where(is_winner, digests[first], np.uint32(0)), AXIS
        )
        midx = ops.lex_argmin(hw)
        all_words = lax.all_gather(hw[midx], AXIS)       # (n_dev, 8)
        all_digests = lax.all_gather(digests[midx], AXIS)
        all_nonces = lax.all_gather(nonces[midx], AXIS)
        bi = ops.lex_argmin(all_words)
        return found, win_nonce, win_digest, all_digests[bi], all_nonces[bi]

    sharded = _shard_map(
        per_device, mesh, in_specs=(P(), P(), P()), out_specs=(P(),) * 5
    )
    return jax.jit(sharded)


def build_min_fold(
    mesh: Mesh,
    template: ops.NonceTemplate,
    *,
    batch_per_device: int,
) -> Callable:
    """Compile a pod-wide MIN-mode (toy dialect) batch step.

    Returns ``step(start_hi_u32, start_lo_u32, limit_hi_u32,
    limit_lo_u32) -> (fold_hi, fold_lo, nonce_hi, nonce_lo)`` — the
    pod-wide minimum toy fold over ``n_dev × batch_per_device``
    consecutive nonces from the 64-bit ``start``, device d owning the
    contiguous shard ``start + d · batch_per_device``. Nonces past the
    64-bit ``limit`` (inclusive) are masked out of the fold, so a
    ragged final step stays exact. Host loops this step across a chunk
    and folds (the toy dialect has no early exit to stop for).
    """

    def per_device(start_hi, start_lo, limit_hi, limit_lo):
        d = lax.axis_index(AXIS).astype(jnp.uint32)
        base_lo = start_lo + d * np.uint32(batch_per_device)
        carry = (base_lo < start_lo).astype(jnp.uint32)
        base_hi = start_hi + carry
        offs = jnp.arange(batch_per_device, dtype=jnp.uint32)
        lo = base_lo + offs
        hi = base_hi + (lo < base_lo).astype(jnp.uint32)
        digests = ops.sha256_batch(template, hi, lo)
        fold = digests[:, :2]  # (N, 2): toy fold (hi, lo) words
        over = (hi > limit_hi) | ((hi == limit_hi) & (lo > limit_lo))
        fold = jnp.where(over[:, None], np.uint32(0xFFFFFFFF), fold)
        idx = ops.lex_argmin(fold)
        # pod fold: gather each device's (fold, nonce) candidate
        all_fold = lax.all_gather(fold[idx], AXIS)            # (n_dev, 2)
        all_hi = lax.all_gather(hi[idx], AXIS)
        all_lo = lax.all_gather(lo[idx], AXIS)
        bi = ops.lex_argmin(all_fold)
        return all_fold[bi][0], all_fold[bi][1], all_hi[bi], all_lo[bi]

    sharded = _shard_map(
        per_device, mesh, in_specs=(P(), P(), P(), P()), out_specs=(P(), P(), P(), P())
    )
    return jax.jit(sharded)
