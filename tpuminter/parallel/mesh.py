"""1-D nonce mesh: shard_map sweeps with ICI collectives (SURVEY.md §7
stage 5; BASELINE.json:5).

Layout: a sweep covers ``n_batches × batch_per_device`` nonces *per
device*, and device ``d`` owns the contiguous shard starting at
``start + d · n_batches · batch_per_device`` — contiguous per chip, as
the north-star specifies, so a found nonce pins down which chip searched
what without any gather.

Early exit: a ``lax.while_loop`` steps through batches; each iteration
ends with a pod-wide **or-reduce of the found flag over ICI**
(``lax.pmax`` on a u32 flag), so every chip stops within one batch of the
first sub-target hash anywhere on the pod — no host round-trip in the
loop. The winner is folded with a ``pmin`` on the winning nonce plus a
masked ``psum`` to broadcast its digest (disjoint shards ⇒ exactly one
contributor).

Everything compiles under ``jit`` with static shapes; the same code runs
on a real TPU slice and on the fake 8-device CPU mesh CI uses
(tests/conftest.py, ``__graft_entry__.dryrun_multichip``).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpuminter.ops import sha256 as ops

__all__ = ["make_mesh", "build_target_sweep", "build_min_fold"]

AXIS = "nonce"


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over all (or the given) devices, axis name "nonce"."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (AXIS,))


def build_target_sweep(
    mesh: Mesh,
    template: ops.NonceTemplate,
    *,
    batch_per_device: int,
    n_batches: int,
) -> Callable:
    """Compile a pod-wide TARGET-mode sweep.

    Returns ``sweep(start_u32, target_words_u32x8) -> (found_u32,
    nonce_u32, digest_words_u32x8, batches_done_u32)`` — replicated
    scalars/vectors, identical on every chip. ``batches_done`` tells the
    host how much of the sweep actually ran (early exit) for hash-rate
    accounting; when nothing is found the digest/nonce outputs are the
    pod-wide *best effort* (lexicographic-min hash and its nonce), so the
    worker can still report a min-fold Result.
    """
    n_dev = mesh.devices.size
    per_dev_total = np.uint32(n_batches * batch_per_device)

    def per_device(start: jnp.ndarray, target_words: jnp.ndarray):
        d = lax.axis_index(AXIS).astype(jnp.uint32)
        dev_start = start + d * per_dev_total

        def cond(state):
            b, found, _, _, _ = state
            return (b < n_batches) & (found == 0)

        def body(state):
            b, _, _, _, best = state
            best_words, best_nonce = best
            nonces = (
                dev_start
                + b.astype(jnp.uint32) * np.uint32(batch_per_device)
                + jnp.arange(batch_per_device, dtype=jnp.uint32)
            )
            digests = ops.double_sha256_header_batch(template, nonces)
            hw = ops.hash_words_be(digests)
            ok = ops.lex_le(hw, target_words)
            local_found = ok.any()
            first = jnp.argmax(ok)
            # pod-wide or-reduce over ICI: the early-exit signal
            found = lax.pmax(local_found.astype(jnp.uint32), AXIS)
            # winner fold: lowest winning nonce wins; its digest comes via
            # a masked psum (shards are disjoint ⇒ one contributor)
            cand_nonce = jnp.where(local_found, nonces[first], np.uint32(0xFFFFFFFF))
            win_nonce = lax.pmin(cand_nonce, AXIS)
            is_winner = local_found & (cand_nonce == win_nonce)
            win_digest = lax.psum(
                jnp.where(is_winner, digests[first], np.uint32(0)), AXIS
            )
            # best-effort min fold (for the exhausted case): local lex-min
            # this batch vs carried best, in hash-value word order
            midx = ops.lex_argmin(hw)
            batch_best_words = hw[midx]
            batch_best_nonce = nonces[midx]
            keep = ops.lex_le(best_words, batch_best_words)
            new_best_words = jnp.where(keep, best_words, batch_best_words)
            new_best_nonce = jnp.where(keep, best_nonce, batch_best_nonce)
            return (
                b + 1,
                found,
                win_nonce,
                win_digest,
                (new_best_words, new_best_nonce),
            )

        init = (
            jnp.uint32(0),
            jnp.uint32(0),
            jnp.uint32(0xFFFFFFFF),
            jnp.zeros(8, dtype=jnp.uint32),
            (jnp.full(8, 0xFFFFFFFF, dtype=jnp.uint32), jnp.uint32(0)),
        )
        b, found, win_nonce, win_digest, (best_words, best_nonce) = lax.while_loop(
            cond, body, init
        )
        # exhausted: fold the per-device best across the pod. all_gather
        # of 8 u32 per chip is trivial ICI traffic; argmin on-replica.
        all_words = lax.all_gather(best_words, AXIS)      # (n_dev, 8)
        all_nonces = lax.all_gather(best_nonce, AXIS)     # (n_dev,)
        bi = ops.lex_argmin(all_words)
        # hash words (msb-first) → digest words for uniform host decoding
        fallback_digest = ops.hash_words_be(all_words[bi])
        nonce_out = jnp.where(found > 0, win_nonce, all_nonces[bi])
        digest_out = jnp.where(found > 0, win_digest, fallback_digest)
        return found, nonce_out, digest_out, b

    sharded = jax.shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(), P()),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded)


def build_min_fold(
    mesh: Mesh,
    template: ops.NonceTemplate,
    *,
    batch_per_device: int,
) -> Callable:
    """Compile a pod-wide MIN-mode (toy dialect) batch step.

    Returns ``step(start_hi_u32, start_lo_u32) -> (fold_hi, fold_lo,
    nonce_hi, nonce_lo)`` — the pod-wide minimum toy fold over
    ``n_dev × batch_per_device`` consecutive nonces from the 64-bit
    ``start``, device d owning the contiguous shard
    ``start + d · batch_per_device``. Host loops this step across a
    chunk and folds (the toy dialect has no early exit to stop for).
    """

    def per_device(start_hi: jnp.ndarray, start_lo: jnp.ndarray):
        d = lax.axis_index(AXIS).astype(jnp.uint32)
        base_lo = start_lo + d * np.uint32(batch_per_device)
        carry = (base_lo < start_lo).astype(jnp.uint32)
        base_hi = start_hi + carry
        offs = jnp.arange(batch_per_device, dtype=jnp.uint32)
        lo = base_lo + offs
        hi = base_hi + (lo < base_lo).astype(jnp.uint32)
        digests = ops.sha256_batch(template, hi, lo)
        fold = digests[:, :2]  # (N, 2): toy fold (hi, lo) words
        idx = ops.lex_argmin(fold)
        # pod fold: gather each device's (fold, nonce) candidate
        all_fold = lax.all_gather(fold[idx], AXIS)            # (n_dev, 2)
        all_hi = lax.all_gather(hi[idx], AXIS)
        all_lo = lax.all_gather(lo[idx], AXIS)
        bi = ops.lex_argmin(all_fold)
        return all_fold[bi][0], all_fold[bi][1], all_hi[bi], all_lo[bi]

    sharded = jax.shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(), P()),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded)
