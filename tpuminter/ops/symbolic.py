"""Partially-evaluated SHA-256: compression over a (const | vector) domain.

The mining hot path hashes messages that are constant except for a few
nonce bytes. Classic miner kernels exploit this with hand-derived
specializations (midstate reuse, precomputed ``K+W`` for constant
schedule words, skipping the first rounds of the tail block). This
module derives ALL of those automatically: values are either Python ints
(trace-time constants, folded mod 2^32 on the host) or u32 arrays
(device vectors), and every SHA-256 primitive constant-folds when its
inputs are constant. Feeding a :class:`~tpuminter.ops.sha256.NonceTemplate`
through :func:`compress_sym` therefore:

- folds the whole midstate prefix (done once, host-side),
- folds every schedule word until the first nonce byte enters it,
- folds the first rounds of the tail block (state stays constant until
  the first nonce-bearing ``w[i]`` is consumed),
- folds ``K[i] + w[i]`` into one scalar wherever ``w[i]`` is constant.

The same code serves the jnp path and the Pallas kernels: the array
branch uses only jnp u32 ops, which lower identically inside a Pallas
kernel body (VPU shift/or pairs for rotations) and in plain XLA.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from tpuminter.chain import SHA256_H0, SHA256_K

__all__ = [
    "Val",
    "compress_sym",
    "schedule_word",
    "inject_nonce_bytes",
    "compress_sym_e60_e61",
    "hash_sym_e60_e61",
    "double_sha256_e60_e61",
    "prepare_hdr",
    "hash_prepared_e60_e61",
    "CAND_E60",
    "DIGEST6_BIAS",
]

#: A symbolic u32: a Python int (trace-time constant) or a u32 array.
Val = Union[int, jnp.ndarray]

_M32 = 0xFFFFFFFF


def _is_const(x: Val) -> bool:
    return isinstance(x, int)


def add(*xs: Val) -> Val:
    """Sum mod 2^32, folding all constant terms into one scalar."""
    const = 0
    arrays = []
    for x in xs:
        if _is_const(x):
            const = (const + x) & _M32
        else:
            arrays.append(x)
    if not arrays:
        return const
    acc = arrays[0]
    for a in arrays[1:]:
        acc = acc + a
    if const:
        acc = acc + np.uint32(const)
    return acc


def xor(*xs: Val) -> Val:
    const = 0
    arrays = []
    for x in xs:
        if _is_const(x):
            const ^= x
        else:
            arrays.append(x)
    if not arrays:
        return const
    acc = arrays[0]
    for a in arrays[1:]:
        acc = acc ^ a
    if const:
        acc = acc ^ np.uint32(const)
    return acc


def and_(a: Val, b: Val) -> Val:
    if _is_const(a) and _is_const(b):
        return a & b
    if _is_const(a):
        a, b = b, a
    if _is_const(b):
        return a & np.uint32(b)
    return a & b


def or_(a: Val, b: Val) -> Val:
    if _is_const(a) and _is_const(b):
        return a | b
    if _is_const(a):
        a, b = b, a
    if _is_const(b):
        return a | np.uint32(b)
    return a | b


def not_(a: Val) -> Val:
    if _is_const(a):
        return a ^ _M32
    return ~a


def shr(x: Val, n: int) -> Val:
    if _is_const(x):
        return x >> n
    return x >> np.uint32(n)


def shl(x: Val, n: int) -> Val:
    if _is_const(x):
        return (x << n) & _M32
    return x << np.uint32(n)


def rotr(x: Val, n: int) -> Val:
    if _is_const(x):
        return ((x >> n) | (x << (32 - n))) & _M32
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _sigma0(x: Val) -> Val:
    return xor(rotr(x, 7), rotr(x, 18), shr(x, 3))


def _sigma1(x: Val) -> Val:
    return xor(rotr(x, 17), rotr(x, 19), shr(x, 10))


def _Sigma0(x: Val) -> Val:
    return xor(rotr(x, 2), rotr(x, 13), rotr(x, 22))


def _Sigma1(x: Val) -> Val:
    return xor(rotr(x, 6), rotr(x, 11), rotr(x, 25))


def _ch(e: Val, f: Val, g: Val) -> Val:
    # g ^ (e & (f ^ g)) ≡ (e & f) ^ (~e & g): one op fewer on the VPU
    return xor(g, and_(e, xor(f, g)))


def _maj(a: Val, b: Val, c: Val) -> Val:
    # (a & b) ^ (c & (a ^ b)) ≡ majority: one op fewer on the VPU
    return xor(and_(a, b), and_(c, xor(a, b)))


def schedule_word(w: Sequence[Val], i: int) -> Val:
    """w[i] for i >= 16 from the rolling window."""
    return add(w[i - 16], _sigma0(w[i - 15]), w[i - 7], _sigma1(w[i - 2]))


def compress_sym(state: Sequence[Val], block_w: Sequence[Val]) -> List[Val]:
    """One SHA-256 compression, fully unrolled, over the symbolic domain.

    ``state`` and ``block_w`` entries may be ints or u32 arrays; the
    result mixes accordingly. ≡ ``chain.sha256_compress`` when all inputs
    are ints (used by the tests as a self-check).
    """
    w: List[Val] = list(block_w)
    for i in range(16, 64):
        w.append(schedule_word(w, i))
    a, b, c, d, e, f, g, h = state
    for i in range(64):
        t1 = add(h, _Sigma1(e), _ch(e, f, g), SHA256_K[i], w[i])
        t2 = add(_Sigma0(a), _maj(a, b, c))
        h, g, f, e, d, c, b, a = g, f, e, add(d, t1), c, b, a, add(t1, t2)
    out = [a, b, c, d, e, f, g, h]
    return [add(s, v) for s, v in zip(state, out)]


def compress_sym_e60_e61(
    state: Sequence[Val], block_w: Sequence[Val]
) -> Tuple[Val, Val]:
    """Truncated compression: the ``e`` values after rounds 60 and 61.

    The classic miner early-reject (VERDICT.md round-1 #2), one word
    deeper: final digest word 7 is ``state[7] + e_60`` (``h_64 = g_63 =
    f_62 = e_61``, i.e. the ``e`` produced at round 60) and digest word
    6 is ``state[6] + e_61`` — so a candidate test over the hash's top
    64 bits stops 2 rounds early. Round ``i``'s ``e`` reads the ``a``
    produced at round ``i-4``, so rounds 58-61 skip the whole
    ``a``-chain (Σ0 + maj + add), and the message schedule stops at
    ``w[61]``. Relative to :func:`compress_sym` that drops 2 full
    rounds, 4 ``t2`` computations, 2 schedule words, the 8 final state
    adds — and lets the caller skip the remaining byteswaps and the
    256-bit compare entirely.
    """
    w: List[Val] = list(block_w)
    for i in range(16, 62):
        w.append(schedule_word(w, i))
    a, b, c, d, e, f, g, h = state
    e60: Val = 0
    for i in range(58):
        t1 = add(h, _Sigma1(e), _ch(e, f, g), SHA256_K[i], w[i])
        t2 = add(_Sigma0(a), _maj(a, b, c))
        h, g, f, e, d, c, b, a = g, f, e, add(d, t1), c, b, a, add(t1, t2)
    for i in range(58, 62):
        # e_i = a_{i-4} + t1_i: the a-chain beyond round 57 is dead, so
        # new ``a`` values are dummies (0) that nothing ever reads.
        t1 = add(h, _Sigma1(e), _ch(e, f, g), SHA256_K[i], w[i])
        h, g, f, e, d, c, b, a = g, f, e, add(d, t1), c, b, a, 0
        if i == 60:
            e60 = e
    return e60, e


#: ``e60 == CAND_E60``  ⟺  digest word 7 == 0  ⟺  the top 32 bits of the
#: 256-bit hash value are zero — a *necessary* condition for beating any
#: target whose top word is 0 (every real Bitcoin difficulty ≥ 1).
CAND_E60: int = (-SHA256_H0[7]) & _M32

#: digest word 6 (whose byteswap is hash word 1) = ``DIGEST6_BIAS + e61``
DIGEST6_BIAS: int = SHA256_H0[6]


def hash_sym_e60_e61(
    midstate: Sequence[Val],
    tail_blocks: Sequence[Sequence[Val]],
    positions: Sequence[tuple],
    nonce_hi: Val,
    nonce_lo: Val,
) -> Tuple[Val, Val]:
    """``(e60, e61)`` of the *second* compression of a double-SHA over a
    symbolic message: the minimal computation deciding the hash's top 64
    bits (digest word 7 == 0 via :data:`CAND_E60`; hash word 1 =
    byteswap(:data:`DIGEST6_BIAS` + e61)). First hash runs in full (its
    digest feeds the second block); the second stops at round 61.
    ``midstate``/``tail_blocks`` entries may be ints (baked templates) or
    traced u32 scalars (the on-device extranonce roll feeds the rolled
    midstate and merkle tail word here, BASELINE.json:9-10)."""
    state: List[Val] = list(midstate)
    for b, block in enumerate(tail_blocks):
        w = inject_nonce_bytes(list(block), positions, b, nonce_hi, nonce_lo)
        state = compress_sym(state, w)
    w2: List[Val] = list(state) + [0x80000000, 0, 0, 0, 0, 0, 0, 256]
    return compress_sym_e60_e61([int(x) for x in SHA256_H0], w2)


def double_sha256_e60_e61(
    template, nonce_hi: Val, nonce_lo: Val
) -> Tuple[Val, Val]:
    """Template wrapper over :func:`hash_sym_e60_e61` with everything
    constant (maximum folding — the baked kernels)."""
    if not template.double:
        raise ValueError("e60 early-reject only applies to double-SHA templates")
    return hash_sym_e60_e61(
        [int(x) for x in template.midstate],
        [[int(x) for x in blk] for blk in template.tail],
        template.positions,
        nonce_hi,
        nonce_lo,
    )


# ---------------------------------------------------------------------------
# Shared-schedule header hashing (the AsicBoost discipline, ISSUE 16)
# ---------------------------------------------------------------------------

#: constant schedule words 4..15 of an 80-byte header's tail block
#: (≡ ``ops.sha256.HEADER_TAIL_PAD``; duplicated here because this module
#: must stay importable from ``ops.sha256`` without a cycle)
_HDR_PAD: Tuple[int, ...] = (0x80000000, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 640)


def _bswap32(x: Val) -> Val:
    """u32 byte swap over the symbolic domain (the little-endian header
    nonce read as a big-endian schedule word)."""
    return xor(
        shl(and_(x, 0x000000FF), 24),
        shl(and_(x, 0x0000FF00), 8),
        shr(and_(x, 0x00FF0000), 8),
        shr(and_(x, 0xFF000000), 24),
    )


def prepare_hdr(
    midstate: Sequence[Val], t0: Val, t1: Val, t2: Val
) -> Tuple:
    """Stage-1 partial evaluation of a header's second block: fold every
    nonce-INDEPENDENT computation once, so a nonce sweep re-runs only the
    remainder (:func:`hash_prepared_e60_e61`).

    AsicBoost (arxiv 1604.00575) shares SHA-256 message-schedule work
    across candidates that collide on the final chunk; here every nonce
    of a sweep collides on ``(midstate, merkle word 7, time, bits)`` =
    ``(midstate, t0, t1, t2)``, and the shareable work is exactly:

    - rounds 0-2 (the nonce enters at word 3, so the whole a-h state
      through round 2 is nonce-free),
    - schedule words ``w16`` and ``w17`` (their σ-window stops at w1/w2),
    - the nonce-free partial sums of ``w18`` (missing only σ0(w3)) and
      ``w19`` (missing only w3).

    Inside a Pallas tile loop these are scalar-unit ops re-executed per
    tile without this split — Mosaic does not hoist them; the jnp engine
    gets the same effect for free from 0-d vs lane shapes. Returns an
    opaque tuple for :func:`hash_prepared_e60_e61`; entries may be ints
    (baked jobs) or traced u32 scalars (the extranonce-roll consumers).
    """
    state = list(midstate)
    a, b, c, d, e, f, g, h = state
    for i, wi in enumerate((t0, t1, t2)):
        r1 = add(h, _Sigma1(e), _ch(e, f, g), SHA256_K[i], wi)
        r2 = add(_Sigma0(a), _maj(a, b, c))
        h, g, f, e, d, c, b, a = g, f, e, add(d, r1), c, b, a, add(r1, r2)
    # w4..w15 are the _HDR_PAD constants: w9 = _HDR_PAD[5], w14 =
    # _HDR_PAD[10], etc. — the σ terms below fold to ints where possible
    w16 = add(t0, _sigma0(t1), _HDR_PAD[5], _sigma1(_HDR_PAD[10]))
    w17 = add(t1, _sigma0(t2), _HDR_PAD[6], _sigma1(_HDR_PAD[11]))
    p18 = add(t2, _HDR_PAD[7], _sigma1(w16))  # + σ0(w3) at sweep time
    p19 = add(_sigma0(_HDR_PAD[0]), _HDR_PAD[8], _sigma1(w17))  # + w3
    return (tuple(state), (a, b, c, d, e, f, g, h), w16, w17, p18, p19)


def hash_prepared_e60_e61(prep: Tuple, nonce: Val) -> Tuple[Val, Val]:
    """Stage-2 of the shared-schedule header hash: finish the first
    compression from a :func:`prepare_hdr` stage and run the truncated
    second compression. ≡ ``hash_sym_e60_e61(midstate, [tail],
    HEADER_NONCE_POSITIONS, 0, nonce)`` bit-for-bit (pinned by tier-1),
    with the stage-1 work amortized across every call sharing ``prep``.
    """
    midstate, vars8, w16, w17, p18, p19 = prep
    w3 = _bswap32(nonce)
    w: List[Val] = [
        None, None, None, w3, *_HDR_PAD,  # w0..w2 dead past round 2
        w16, w17, add(p18, _sigma0(w3)), add(p19, w3),
    ]
    for i in range(20, 64):
        w.append(schedule_word(w, i))
    a, b, c, d, e, f, g, h = vars8
    for i in range(3, 64):
        r1 = add(h, _Sigma1(e), _ch(e, f, g), SHA256_K[i], w[i])
        r2 = add(_Sigma0(a), _maj(a, b, c))
        h, g, f, e, d, c, b, a = g, f, e, add(d, r1), c, b, a, add(r1, r2)
    state = [add(s, v) for s, v in zip(midstate, (a, b, c, d, e, f, g, h))]
    w2: List[Val] = list(state) + [0x80000000, 0, 0, 0, 0, 0, 0, 256]
    return compress_sym_e60_e61([int(x) for x in SHA256_H0], w2)


def inject_nonce_bytes(
    tail_block: Sequence[Val],
    positions: Sequence[tuple],
    block_index: int,
    nonce_hi: Val,
    nonce_lo: Val,
) -> List[Val]:
    """Build one tail block's schedule words: template words with the
    nonce bytes OR'd in at their static positions (the nonce-shaped hole
    of a ``NonceTemplate``). Words may be Python ints (baked templates)
    or traced u32 scalars (the dynamic-header path, where the midstate
    and merkle tail word are produced on device by the extranonce roll);
    constant words stay Python ints through the injection.
    """
    w: List[Val] = list(tail_block)
    for blk, word, word_shift, nonce_shift in positions:
        if blk != block_index:
            continue
        src = nonce_hi if nonce_shift >= 32 else nonce_lo
        shift = nonce_shift - 32 if nonce_shift >= 32 else nonce_shift
        byte = and_(shr(src, shift), 0xFF)
        w[word] = or_(w[word], shl(byte, word_shift))
    return w


def hash_sym(
    midstate: Sequence[Val],
    tail_blocks: Sequence[Sequence[Val]],
    positions: Sequence[tuple],
    double: bool,
    nonce_hi: Val,
    nonce_lo: Val,
) -> List[Val]:
    """Full symbolic hash: midstate → tail block(s) with injected nonce
    bytes → optional second hash. Returns the 8 digest words.

    Message values may be Python ints (maximum folding — the baked
    kernels) or traced u32 *scalars* (one compiled kernel serves every
    job of the same shape — the production workers); the array branch of
    every primitive broadcasts scalars against the nonce tiles."""
    state: List[Val] = list(midstate)
    for b, block in enumerate(tail_blocks):
        w: List[Val] = list(block)
        for blk, word, word_shift, nonce_shift in positions:
            if blk != b:
                continue
            src = nonce_hi if nonce_shift >= 32 else nonce_lo
            shift = nonce_shift - 32 if nonce_shift >= 32 else nonce_shift
            byte = and_(shr(src, shift), 0xFF)
            w[word] = or_(w[word], shl(byte, word_shift))
        state = compress_sym(state, w)
    if double:
        w2: List[Val] = list(state) + [0x80000000, 0, 0, 0, 0, 0, 0, 256]
        state = compress_sym([int(x) for x in SHA256_H0], w2)
    return state


def double_sha256_sym(template, nonce_hi: Val, nonce_lo: Val) -> List[Val]:
    """Template wrapper over :func:`hash_sym` with everything constant."""
    return hash_sym(
        [int(x) for x in template.midstate],
        [[int(x) for x in blk] for blk in template.tail],
        template.positions,
        template.double,
        nonce_hi,
        nonce_lo,
    )
