"""Scrypt (RFC 7914) on device: the memory-hard PoW variant
(BASELINE.json:11, eval config 5; SURVEY.md §7 stage 7).

Litecoin-style header mining: ``scrypt(P=header80, S=header80, N=1024,
r=1, p=1, dkLen=32)``, the 32-byte output interpreted as a little-endian
uint256 and compared against the target exactly like Bitcoin's
double-SHA hash value. The reference has no scrypt (its toy PoW is a
folded single SHA); host ground truth is OpenSSL via
``chain.scrypt_hash`` / ``hashlib.scrypt``, which the batch function
here is pinned against bit-for-bit (tests/test_scrypt.py).

TPU-first design notes:

- **Everything is u32 vector ALU + one gather.** Salsa20/8 and the
  SHA-256 compressions are elementwise over the batch, so XLA tiles
  them onto the VPU like the SHA ops. The one irreducibly memory-hard
  step is ROMix phase 2's data-dependent read ``V[Integerify(X)]`` —
  that is scrypt's *point* (sequential memory hardness), and it lowers
  to a per-lane dynamic-slice/gather from the ``N × 128``-byte scratch
  ``V`` that XLA keeps in HBM. Throughput is therefore HBM-bandwidth
  bound by design: each hash writes and reads 128 KiB at N=1024/r=1.
- **No midstate tricks apply.** Unlike double-SHA mining, the nonce
  sits in the PBKDF2 *key* (P = the header itself), so every SHA state
  depends on the nonce from the first block; the whole pipeline is
  recomputed per nonce. Consequently the header travels as a *runtime*
  (19,) u32 array — nothing job-specific is baked, one compiled
  program serves every header-mining job and every extranonce.
- **Static shapes, static N.** ``n_log2`` is a static arg; both ROMix
  phases are ``lax.scan``s over tuples of per-word ``(B,)`` vectors
  (see :func:`romix` for the measured layout rationale). Batch size
  fixes the compile; memory is ``batch × 128·N`` bytes for V (32 MiB
  at batch=256, N=1024; 2 GiB at the TPU batch of 16384).

Word-order convention: SHA-256 words are big-endian reads of the byte
stream (as in ``ops.sha256``); salsa/BlockMix words are little-endian
(RFC 7914 §3). ``_bswap`` converts at the two seams (B after the first
PBKDF2, B' before the last).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpuminter.chain import SHA256_H0
from tpuminter.ops import sha256 as ops

__all__ = [
    "salsa20_8",
    "block_mix",
    "romix",
    "scrypt_header_batch",
    "HEADER_WORDS",
]

_H0 = np.array(SHA256_H0, dtype=np.uint32)
#: words of the 76-byte constant header prefix (the nonce completes it)
HEADER_WORDS = 19

#: outer-HMAC second block: 0x80 pad + bit length of opad(64) ‖ digest(32)
_OUTER_PAD = np.array([0x80000000, 0, 0, 0, 0, 0, 0, 768], dtype=np.uint32)


_bswap = ops.byteswap32  # the BE↔LE word seam (shared helper)

def _compress(state, block):
    # scanned rounds, never unrolled: the PBKDF2 walls embed 21
    # compressions in one program, and 21 × ~7k unrolled ops push XLA
    # compile time into minutes for ~2% of scrypt's runtime
    return ops.compress(state, block, unroll=False)



def _rotl(x: jnp.ndarray, n: int) -> jnp.ndarray:
    # no rotate ISA on TPU: shift/or pair, same as the SHA ops
    return (x << np.uint32(n)) | (x >> np.uint32(32 - n))


#: salsa20 quarter-round index pattern: (target, a, b, rot) meaning
#: ``x[target] ^= rotl(x[a] + x[b], rot)``; first the 4 column quarter-
#: rounds then the 4 row quarter-rounds = one double round (Salsa20 spec
#: §/RFC 7914 §2 reference code ordering).
_SALSA_STEPS: Tuple[Tuple[int, int, int, int], ...] = (
    # column round
    (4, 0, 12, 7), (8, 4, 0, 9), (12, 8, 4, 13), (0, 12, 8, 18),
    (9, 5, 1, 7), (13, 9, 5, 9), (1, 13, 9, 13), (5, 1, 13, 18),
    (14, 10, 6, 7), (2, 14, 10, 9), (6, 2, 14, 13), (10, 6, 2, 18),
    (3, 15, 11, 7), (7, 3, 15, 9), (11, 7, 3, 13), (15, 11, 7, 18),
    # row round
    (1, 0, 3, 7), (2, 1, 0, 9), (3, 2, 1, 13), (0, 3, 2, 18),
    (6, 5, 4, 7), (7, 6, 5, 9), (4, 7, 6, 13), (5, 4, 7, 18),
    (11, 10, 9, 7), (8, 11, 10, 9), (9, 8, 11, 13), (10, 9, 8, 18),
    (12, 15, 14, 7), (13, 12, 15, 9), (14, 13, 12, 13), (15, 14, 13, 18),
)


def _salsa20_8_words(w):
    """Salsa20/8 on 16 separate word vectors (the TPU-dense form: each
    word is a whole ``(B,)`` array, so every op is a full-vreg VPU op
    with no cross-lane extracts). Returns 16 new word vectors."""
    x = list(w)
    for _ in range(4):
        for tgt, a, b, rot in _SALSA_STEPS:
            x[tgt] = x[tgt] ^ _rotl(x[a] + x[b], rot)
    return [wi + xi for wi, xi in zip(w, x)]


def _block_mix_words(w32):
    """scryptBlockMix r=1 on 32 word vectors: ``Y0 = salsa(B1 ^ B0)``,
    ``Y1 = salsa(Y0 ^ B1)``, output ``Y0 ‖ Y1`` (RFC 7914 §4)."""
    b0, b1 = w32[:16], w32[16:]
    y0 = _salsa20_8_words([p ^ q for p, q in zip(b1, b0)])
    y1 = _salsa20_8_words([p ^ q for p, q in zip(y0, b1)])
    return y0 + y1


def salsa20_8(x: jnp.ndarray) -> jnp.ndarray:
    """Salsa20/8 core: ``(..., 16) u32`` little-endian words → same shape
    (RFC 7914 §2). 4 double rounds, then the feed-forward add."""
    return jnp.stack(
        _salsa20_8_words([x[..., i] for i in range(16)]), axis=-1
    )


def block_mix(x: jnp.ndarray) -> jnp.ndarray:
    """scryptBlockMix for r=1: ``(..., 32) u32`` LE words → same shape
    (RFC 7914 §4)."""
    return jnp.stack(
        _block_mix_words([x[..., i] for i in range(32)]), axis=-1
    )


@partial(jax.jit, static_argnums=1)
def romix(x: jnp.ndarray, n_log2: int) -> jnp.ndarray:
    """scryptROMix for r=1 (RFC 7914 §5), batched: ``(B, 32) u32`` LE
    words → same shape, with ``N = 2**n_log2``.

    Phase 1 (``lax.scan``) fills ``V[i] = BlockMix^i(X)``; phase 2 does
    the sequential data-dependent walk ``X = BlockMix(X ^
    V[Integerify(X) mod N])``. Integerify for r=1 = LE word 16 (first
    word of the last 64-byte block).

    Two TPU-measured layout choices carry the performance (each is
    ~100× over the naive form on a v5e through this image's tunnel):

    - **State lives as 32 separate ``(B,)`` word vectors**, not a
      ``(B, 32)`` array: on TPU the minor axis is the 128-lane dim, so
      ``x[:, i]`` word extracts inside salsa are strided cross-lane
      ops that dominate runtime; word-per-array makes every salsa op a
      dense full-vreg VPU op. The pack/unpack to ``(B, 32)`` happens
      once per step (V store / V load), not ~600× per BlockMix.
    - **V is flat ``(N·B, 32)`` and phase 2 gathers whole rows** via
      ``v[j·B + lane]``: XLA lowers this integer row-gather well
      (measured ~23 GB/s at B≥8192), while ``take_along_axis`` on the
      ``(N, B, 32)`` form lowers ~100× slower. Throughput remains
      HBM-gather bound — that is scrypt's design point (sequential
      memory hardness), and why a memory-hard PoW on any
      matmul-oriented part is bandwidth-, not ALU-, limited.
    """
    n = 1 << n_log2
    batch = x.shape[0]
    if n * batch >= 1 << 31:
        # the flat row index is computed in u32 and cast to int32; past
        # 2^31 rows it would wrap/clamp silently into wrong V reads
        raise ValueError(
            f"n*batch = {n * batch} exceeds the int32 row-index domain; "
            "shrink the batch or n_log2"
        )
    lane = jnp.arange(batch, dtype=jnp.uint32)
    words = tuple(x[:, i] for i in range(32))

    # unroll=2 on TPU: measured +11.5% at the shipping B=16384 (unroll=4
    # regresses); kept at 1 on the CPU mesh where CI would pay a doubled
    # scan-body compile for zero benefit (the knob only reschedules; the
    # math is identical). A fully-fused Pallas ROMix was prototyped and
    # rejected on measurement (scripts/romix_pallas_probe.py), and round
    # 5 measured SIX fused relayout+xor+salsa designs — pallas kernels
    # on every byte layout the gather can emit (incl. its native
    # sublane-interleaved tiles), a plane-major element gather, and an
    # MXU identity-dot transpose — all ~650 µs/step or worse: the walk
    # is floor-bound by the TPU gather emitter's custom-call/relayout
    # boundary, not by this scan body. See PERF.md's scrypt section and
    # scripts/walk_*_probe.py.
    unroll = 2 if jax.default_backend() != "cpu" else 1

    def fill(carry, _):
        return tuple(_block_mix_words(list(carry))), jnp.stack(carry, axis=-1)

    words, v = jax.lax.scan(fill, words, None, length=n, unroll=unroll)
    vflat = v.reshape(n * batch, 32)  # v: (N, B, 32)

    def walk(carry, _):
        j = carry[16] & np.uint32(n - 1)  # (B,) per-lane index into V
        vj = vflat[(j * np.uint32(batch) + lane).astype(jnp.int32)]
        mixed = [c ^ vj[:, i] for i, c in enumerate(carry)]
        return tuple(_block_mix_words(mixed)), None

    words, _ = jax.lax.scan(walk, words, None, length=n, unroll=unroll)
    return jnp.stack(words, axis=-1)


# ---------------------------------------------------------------------------
# PBKDF2-HMAC-SHA256 pieces (c=1, the only iteration count scrypt uses)
# ---------------------------------------------------------------------------

def _hmac_states(key8: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """HMAC-SHA256 inner/outer states for a 32-byte key (here always
    SHA256(header) — header80 > 64 bytes forces the key-hash path):
    ``(..., 8) u32`` → two ``(..., 8)`` states after the ipad/opad
    blocks."""
    shape = key8.shape[:-1] + (8,)
    h0 = jnp.broadcast_to(jnp.asarray(_H0), shape)
    ipad = jnp.concatenate(
        [key8 ^ np.uint32(0x36363636),
         jnp.full(shape, 0x36363636, jnp.uint32)], axis=-1
    )
    opad = jnp.concatenate(
        [key8 ^ np.uint32(0x5C5C5C5C),
         jnp.full(shape, 0x5C5C5C5C, jnp.uint32)], axis=-1
    )
    return _compress(h0, ipad), _compress(h0, opad)


def _hmac_finish(ostate: jnp.ndarray, inner_digest: jnp.ndarray) -> jnp.ndarray:
    """Outer hash: opad state + 32-byte inner digest → (..., 8) u32."""
    pad = jnp.broadcast_to(jnp.asarray(_OUTER_PAD), inner_digest.shape)
    return _compress(ostate, jnp.concatenate([inner_digest, pad], axis=-1))


def _const_row(shape, words) -> jnp.ndarray:
    return jnp.broadcast_to(
        jnp.asarray(np.array(words, dtype=np.uint32)), shape[:-1] + (len(words),)
    )


@partial(jax.jit, static_argnums=(2, 3))
def scrypt_header_batch(
    header76w: jnp.ndarray,
    nonces: jnp.ndarray,
    n_log2: int = 10,
    romix_impl=romix,
) -> jnp.ndarray:
    """Scrypt PoW hashes for a batch of header nonces:
    ``header76w (19,) u32`` (big-endian words of the 76 constant header
    bytes — a *runtime* value, nothing baked) × ``nonces (B,) u32`` →
    ``(B, 8) u32`` big-endian words of the 32-byte scrypt output, the
    same digest-word convention as ``ops.sha256_batch`` (so
    ``hash_words_be`` / ``digest_to_int`` / ``lex_le`` apply unchanged).

    ≡ ``hashlib.scrypt(hdr, salt=hdr, n=2**n_log2, r=1, p=1, dklen=32)``
    with ``hdr = header76 ‖ nonce_le`` (pinned by tests/test_scrypt.py).
    ``romix_impl`` is the kernel seam: the default is the jnp ROMix; a
    Pallas ROMix slots in underneath without touching the PBKDF2 walls.
    """
    b = nonces.shape[0]
    hw = jnp.broadcast_to(header76w, (b, HEADER_WORDS))
    nw = _bswap(nonces)[:, None]  # LE nonce bytes as a BE schedule word
    block0 = hw[:, :16]
    tail3 = hw[:, 16:]

    # key = SHA256(header80): 80 bytes → block0 + (tail ‖ nonce ‖ pad)
    h0 = jnp.broadcast_to(jnp.asarray(_H0), (b, 8))
    key_tail = jnp.concatenate(
        [tail3, nw, _const_row((b, 16), [0x80000000] + [0] * 10 + [640])],
        axis=-1,
    )
    key8 = _compress(_compress(h0, block0), key_tail)
    istate, ostate = _hmac_states(key8)

    # B = PBKDF2(P=hdr, S=hdr, c=1, dkLen=128): 4 HMAC blocks, inner
    # message = S ‖ INT_BE(i). The S-block0 compression is i-independent.
    mid = _compress(istate, block0)
    t_be = []
    for i in (1, 2, 3, 4):
        inner_tail = jnp.concatenate(
            [tail3, nw,
             _const_row((b, 16), [i, 0x80000000] + [0] * 9 + [1184])],
            axis=-1,
        )
        t_be.append(_hmac_finish(ostate, _compress(mid, inner_tail)))
    x = _bswap(jnp.concatenate(t_be, axis=-1))  # (B, 32) LE words

    x = romix_impl(x, n_log2)

    # out = PBKDF2(P=hdr, S=B', c=1, dkLen=32): one HMAC block, inner
    # message = B'(128 bytes) ‖ INT_BE(1)
    bp = _bswap(x)  # B' bytes as BE schedule words
    st = _compress(_compress(istate, bp[:, :16]), bp[:, 16:])
    last = _const_row((b, 16), [1, 0x80000000] + [0] * 13 + [1568])
    return _hmac_finish(ostate, _compress(st, last))


def header_to_words(header_prefix76: bytes) -> np.ndarray:
    """76-byte header prefix → the (19,) u32 big-endian word array
    :func:`scrypt_header_batch` consumes."""
    if len(header_prefix76) != 76:
        raise ValueError(f"header prefix must be 76 bytes, got {len(header_prefix76)}")
    return np.frombuffer(header_prefix76, dtype=">u4").astype(np.uint32)
