"""Device-lane splitmix64: the hashcore workload's compute engine on
u32-pair lanes (ISSUE 17).

The hashcore objective (``workloads.hashcore.objective``) is one
splitmix64 draw per global index — three 64-bit multiplies and three
xor-shifts.  The numpy host path runs it on native u64 lanes, but the
jax workers cannot: the tier-1 control-plane drills (and the production
CPU mesh) run ``JAX_PLATFORMS=cpu`` *without* ``jax_enable_x64``, so a
u64 jnp array does not exist there.  This module implements the same
arithmetic on **u32 pairs** — every u64 is a ``(hi, lo)`` word pair,
64-bit multiplies decompose into 16-bit-limb partial products, shifts
straddle the word boundary explicitly — which makes the objective
expressible on every backend jax has, TPU included (VaultxGPU,
arxiv 2606.14007, is the accelerator-side shape; HashCore itself,
arxiv 1902.00112, is explicitly a general-purpose-processor PoW).

Three layers:

- **pair primitives** (:func:`add64`, :func:`mul64`, :func:`xorshr64`)
  and :func:`splitmix64_pair` — pure jnp, usable inside Pallas kernel
  bodies (``tpuminter.kernels.splitmix`` is the kernel mirror);
- **the batched sweep** (:func:`sweep_program`) — one jitted program
  per ``(variant, width, rows, k, engine)``, ``lru_cache``'d per the
  PR 7 retrace rule: ``lax.scan`` over ``rows`` row-bases, ``width``
  lanes per row, folding **in-program** for all four registered fold
  disciplines (fmin / top-k / first-match / sum) so one device array
  crosses the host boundary per dispatch;
- **the dispatch seam** (:class:`LaneSweep`) — host-side span → device
  arguments → decoded chunk-partial accumulator, bit-for-bit equal to
  the host lanes' ``fold.of_batch``/``combine`` chain (the A/B
  contract tests/test_hashcore_dev.py pins).

Fold-equality notes (why bit-for-bit holds):

- every fold's ``combine`` is associative with deterministic
  index-tie-breaks, so window-granularity partials combine to the same
  accumulator as the host's ``_BATCH``-granularity ones;
- fmatch ``probes`` count full batches before the match plus the
  offset inside the matching one — granularity-independent by
  construction (``probes == index - lo + 1`` either way);
- fsum accumulates exactly: per-row lane sums split into 16-bit
  columns (``width <= 2^16`` keeps every column sum under 2^32), then
  8×16-bit-limb carry propagation — integer-exact u128, same as the
  host's Python-int ``sum``.

Width is autotuned like the rolled plane (:func:`autotune_lane_width`,
one-shot cached probe) but under its OWN cache keyed by
``(backend, workload, engine, ...)`` so the rolled and hashcore probes
never clobber each other.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "splitmix64_pair", "add64", "mul64", "xorshr64", "lane_objective",
    "sweep_program", "LaneSweep", "lane_sweep", "autotune_lane_width",
    "resolve_engine", "counters", "ROWS", "MAX_WIDTH",
]

_M32 = 0xFFFFFFFF
_M64 = (1 << 64) - 1
_UMAX = np.uint32(0xFFFFFFFF)

#: splitmix64 constants as (hi, lo) u32 pairs
_GOLDEN = (np.uint32(0x9E3779B9), np.uint32(0x7F4A7C15))
_MIX1 = (np.uint32(0xBF58476D), np.uint32(0x1CE4E5B9))
_MIX2 = (np.uint32(0x94D049BB), np.uint32(0x133111EB))

#: rows per dispatch window (the lax.scan length): amortizes dispatch
#: overhead across rows the way rolled.py's roll_batch amortizes rolls
ROWS = 8

#: fsum's 16-bit-column trick needs every per-row column sum to fit in
#: u32: width lanes × (2^16 - 1) < 2^32 ⟺ width <= 2^16
MAX_WIDTH = 1 << 16

#: device dispatch evidence (bench / loadgen drills read the deltas;
#: plain dict writes from the mining executor thread, GIL-atomic)
counters: Dict[str, int] = {"dispatches": 0}


# ---------------------------------------------------------------------------
# u32-pair primitives (usable inside Pallas kernel bodies)
# ---------------------------------------------------------------------------

def add64(ah, al, bh, bl):
    """``(ah‖al) + (bh‖bl) mod 2^64`` on u32 words: wrapping low add,
    carry by unsigned compare."""
    lo = al + bl
    return ah + bh + (lo < al).astype(jnp.uint32), lo


def _mulhilo32(a, b):
    """Full 32×32→64 product as (hi, lo) u32 via 16-bit limbs — the
    widest multiply XLA:CPU/Mosaic offer without an x64 dtype."""
    al, ah = a & 0xFFFF, a >> 16
    bl, bh = b & 0xFFFF, b >> 16
    ll = al * bl
    lh = al * bh
    hl = ah * bl
    # mid <= (2^16-1) + 2·(2^16-1) — never wraps u32
    mid = (ll >> 16) + (lh & 0xFFFF) + (hl & 0xFFFF)
    lo = (mid << 16) | (ll & 0xFFFF)
    hi = ah * bh + (lh >> 16) + (hl >> 16) + (mid >> 16)
    return hi, lo


def mul64(ah, al, bh, bl):
    """``(ah‖al) · (bh‖bl) mod 2^64``: one full 32×32 low-product plus
    the two wrapping cross terms (the high×high term is ≥ 2^64 and
    drops entirely)."""
    hi, lo = _mulhilo32(al, bl)
    return hi + al * bh + ah * bl, lo


def xorshr64(h, l, s: int):
    """``x ^ (x >> s)`` for ``0 < s < 32``: the high word shifts
    internally, the low word receives the straddle bits."""
    return h ^ (h >> s), l ^ ((l >> s) | (h << (32 - s)))


def splitmix64_pair(seed_h, seed_l, idx_h, idx_l):
    """The hashcore objective on u32-pair lanes: bit-for-bit
    ``workloads.hashcore.objective(seed, index)`` (pinned in
    tests/test_hashcore_dev.py across the u64 domain)."""
    ih, il = add64(idx_h, idx_l, jnp.uint32(0), jnp.uint32(1))
    zh, zl = mul64(ih, il, *_GOLDEN)
    zh, zl = add64(zh, zl, seed_h, seed_l)
    zh, zl = xorshr64(zh, zl, 30)
    zh, zl = mul64(zh, zl, *_MIX1)
    zh, zl = xorshr64(zh, zl, 27)
    zh, zl = mul64(zh, zl, *_MIX2)
    return xorshr64(zh, zl, 31)


def lane_objective(seed: int, indices) -> list:
    """Test/verification helper: objective values for an arbitrary
    index iterable through the u32-pair lane math (eager jnp — not a
    hot path; the sweep programs are)."""
    idx = [int(i) & _M64 for i in indices]
    ih = jnp.asarray(np.fromiter(
        ((i >> 32) for i in idx), np.uint32, len(idx)))
    il = jnp.asarray(np.fromiter(
        ((i & _M32) for i in idx), np.uint32, len(idx)))
    vh, vl = splitmix64_pair(
        jnp.uint32(seed >> 32), jnp.uint32(seed & _M32), ih, il)
    return [
        (int(h) << 32) | int(l)
        for h, l in zip(np.asarray(vh).tolist(), np.asarray(vl).tolist())
    ]


# ---------------------------------------------------------------------------
# in-program fold bodies (one lax.scan row each)
# ---------------------------------------------------------------------------

def _lex_lt(a, b):
    """Lexicographic ``a < b`` over equal-length u32 word tuples."""
    lt = jnp.bool_(False)
    eq = jnp.bool_(True)
    for x, y in zip(a, b):
        lt = lt | (eq & (x < y))
        eq = eq & (x == y)
    return lt


def _masked_row(row, width: int):
    """Unpack one row, sentinel-mask the invalid tail: masked lanes
    become ``(value, index) = (2^64-1, 2^64-1)`` which lose every fold
    (ties at value 2^64-1 still break to the real lane's lower index)."""
    vh, vl, ih, il, valid = row
    off = jnp.arange(width, dtype=jnp.uint32)
    mask = off < valid
    return (
        jnp.where(mask, vh, _UMAX), jnp.where(mask, vl, _UMAX),
        jnp.where(mask, ih, _UMAX), jnp.where(mask, il, _UMAX),
        off, valid, mask,
    )


def _select_min_pair(sel, h, l):
    """Min (hi, lo) pair over ``sel`` lanes (sentinel-max elsewhere):
    staged min — minimize hi, then lo among the hi-minimal lanes."""
    sh = jnp.where(sel, h, _UMAX)
    mh = sh.min()
    ml = jnp.where(sel & (sh == mh), l, _UMAX).min()
    return mh, ml


def _fmin_row(carry, row, width: int):
    vh, vl, ih, il, _off, _valid, _mask = _masked_row(row, width)
    mvh = vh.min()
    mvl = jnp.where(vh == mvh, vl, _UMAX).min()
    sel = (vh == mvh) & (vl == mvl)
    mih, mil = _select_min_pair(sel, ih, il)
    cand = (mvh, mvl, mih, mil)
    take = _lex_lt(cand, carry)
    return tuple(jnp.where(take, c, o) for c, o in zip(cand, carry)), None


def _topk_row(carry, row, width: int, k: int):
    vh, vl, ih, il, _off, _valid, _mask = _masked_row(row, width)
    ops = tuple(
        jnp.concatenate([lane, kept])
        for lane, kept in zip((vh, vl, ih, il), carry)
    )
    svh, svl, sih, sil = jax.lax.sort(ops, num_keys=4)
    return (svh[:k], svl[:k], sih[:k], sil[:k]), None


def _fmatch_row(carry, row, width: int):
    found, gih, gil, gvh, gvl, probes, th, tl = carry
    vh, vl, ih, il, off, valid, mask = _masked_row(row, width)
    # v <= thr  ⟺  not (thr < v); sentinel lanes only "match" a
    # threshold of 2^64-1, where every real (lower-index) lane matches
    # too, so they can never win the first-index fold
    le = mask & ~_lex_lt((th, tl), (vh, vl))
    first = jnp.where(le, off, _UMAX).min()
    row_found = first != _UMAX
    hit = off == first
    rih, ril = _select_min_pair(hit, ih, il)
    rvh, rvl = _select_min_pair(hit, vh, vl)
    already = found > 0
    # host probe accounting, row-granular: full valid counts for dry
    # rows, offset+1 inside the matching one, nothing after it
    probes = jnp.where(
        already, probes,
        probes + jnp.where(row_found, first + 1, valid),
    )
    take = (~already) & row_found
    out = (
        jnp.where(take, jnp.uint32(1), found),
        jnp.where(take, rih, gih), jnp.where(take, ril, gil),
        jnp.where(take, rvh, gvh), jnp.where(take, rvl, gvl),
        probes, th, tl,
    )
    return out, None


def _fsum_row(carry, row, width: int):
    vh, vl, ih, il, valid = row
    off = jnp.arange(width, dtype=jnp.uint32)
    mask = off < valid
    # 16-bit column sums: width <= 2^16 lanes × (2^16-1) < 2^32 each
    s0 = jnp.sum(jnp.where(mask, vl & 0xFFFF, 0), dtype=jnp.uint32)
    s1 = jnp.sum(jnp.where(mask, vl >> 16, 0), dtype=jnp.uint32)
    s2 = jnp.sum(jnp.where(mask, vh & 0xFFFF, 0), dtype=jnp.uint32)
    s3 = jnp.sum(jnp.where(mask, vh >> 16, 0), dtype=jnp.uint32)
    adds = (
        s0 & 0xFFFF,
        (s0 >> 16) + (s1 & 0xFFFF),
        (s1 >> 16) + (s2 & 0xFFFF),
        (s2 >> 16) + (s3 & 0xFFFF),
        s3 >> 16,
    )
    limbs = []
    c = jnp.uint32(0)
    for i in range(8):
        t = carry[i] + c + (adds[i] if i < len(adds) else jnp.uint32(0))
        limbs.append(t & 0xFFFF)
        c = t >> 16
    # the final carry is structurally zero: total < 2^96 << 2^128
    return tuple(limbs), None


# ---------------------------------------------------------------------------
# the jitted sweep programs (lru_cache'd factories — PR 7 retrace rule)
# ---------------------------------------------------------------------------

def resolve_engine(engine: str = "auto") -> str:
    """Mirror of ``rolled._resolve_engine``: jnp is the CPU-mesh engine,
    the Pallas kernel the on-silicon one."""
    if engine == "auto":
        return "jnp" if jax.default_backend() == "cpu" else "pallas"
    if engine not in ("jnp", "pallas"):
        raise ValueError(f"unknown engine {engine!r}")
    return engine


def _row_lanes(seed_h, seed_l, bh, bl, width: int):
    """In-program lane generation for one row: global index pairs from
    a scalar (hi, lo) base plus the lane iota, then the objective."""
    off = jnp.arange(width, dtype=jnp.uint32)
    il = bl + off
    ih = bh + (il < bl).astype(jnp.uint32)
    vh, vl = splitmix64_pair(seed_h, seed_l, ih, il)
    return vh, vl, ih, il


@lru_cache(maxsize=None)
def sweep_program(
    variant: str, width: int, rows: int, k: int, engine: str
):
    """One compiled sweep per job-constant tuple. Dynamic arguments —
    seed words, per-row base words, per-row valid counts, threshold
    words — are traced, so ONE program serves every (seed, range,
    threshold) at this shape; the output is ONE packed u32 array (one
    host sync per dispatch):

    - fmin  → ``(4,)``  best (value_hi, value_lo, index_hi, index_lo)
    - topk  → ``(4, k)`` the k best columns, (value, index)-sorted
    - fmatch→ ``(6,)``  (found, idx_hi, idx_lo, val_hi, val_lo, probes)
    - fsum  → ``(8,)``  16-bit limbs of the exact u128 total, LE
    """
    if not 128 <= width <= MAX_WIDTH or width % 128:
        raise ValueError(
            f"width must be a multiple of 128 in [128, {MAX_WIDTH}]"
        )
    if variant not in ("fmin", "topk", "fmatch", "fsum"):
        raise ValueError(f"unknown variant {variant!r}")

    def run(seed_h, seed_l, bh, bl, valid, th, tl):
        if engine == "pallas":
            from tpuminter.kernels.splitmix import pallas_splitmix_batch

            off = jnp.arange(width, dtype=jnp.uint32)
            il = bl[:, None] + off[None, :]
            ih = bh[:, None] + (il < bl[:, None]).astype(jnp.uint32)
            vh, vl = pallas_splitmix_batch(
                seed_h, seed_l, ih.reshape(-1), il.reshape(-1)
            )
            lanes = (vh.reshape(rows, width), vl.reshape(rows, width),
                     ih, il)
        else:
            def gen(_, b):
                return None, _row_lanes(seed_h, seed_l, b[0], b[1], width)

            _, lanes = jax.lax.scan(gen, None, (bh, bl))
        xs = lanes + (valid,)
        if variant == "fmin":
            init = (_UMAX,) * 4
            out, _ = jax.lax.scan(
                lambda c, r: _fmin_row(c, r, width), init, xs)
            return jnp.stack(out)
        if variant == "topk":
            init = tuple(jnp.full((k,), _UMAX) for _ in range(4))
            out, _ = jax.lax.scan(
                lambda c, r: _topk_row(c, r, width, k), init, xs)
            return jnp.stack(out)
        if variant == "fmatch":
            init = (jnp.uint32(0),) + (_UMAX,) * 4 + (
                jnp.uint32(0), th, tl)
            out, _ = jax.lax.scan(
                lambda c, r: _fmatch_row(c, r, width), init, xs)
            return jnp.stack(out[:6])
        init = (jnp.uint32(0),) * 8
        out, _ = jax.lax.scan(
            lambda c, r: _fsum_row(c, r, width), init, xs)
        return jnp.stack(out)

    return jax.jit(run)


# ---------------------------------------------------------------------------
# dispatch seam: span in, chunk-partial accumulator out
# ---------------------------------------------------------------------------

class LaneSweep:
    """Host face of one compiled sweep: :meth:`dispatch` is
    non-blocking (jax async dispatch — the ``search.pipeline_spans``
    contract), :meth:`resolve` is the single sync point and decodes the
    packed device array into the fold discipline's accumulator shape."""

    def __init__(self, variant: str, width: int, rows: int, k: int,
                 engine: str):
        self.variant = variant
        self.width = width
        self.rows = rows
        self.k = k
        self.engine = engine
        self.window = rows * width
        self._fn = sweep_program(variant, width, rows, k, engine)

    def dispatch(self, seed: int, lo: int, hi: int, threshold: int = 0):
        """Async sweep of global indices ``[lo, hi]`` (``hi - lo + 1 <=
        window``); returns the device handle."""
        total = hi - lo + 1
        if not 1 <= total <= self.window:
            raise ValueError("span must fit one dispatch window")
        bh = np.empty(self.rows, np.uint32)
        bl = np.empty(self.rows, np.uint32)
        valid = np.empty(self.rows, np.uint32)
        for r in range(self.rows):
            base = (lo + r * self.width) & _M64
            bh[r] = base >> 32
            bl[r] = base & _M32
            valid[r] = min(max(total - r * self.width, 0), self.width)
        counters["dispatches"] += 1
        return self._fn(
            np.uint32(seed >> 32), np.uint32(seed & _M32),
            bh, bl, valid,
            np.uint32(threshold >> 32), np.uint32(threshold & _M32),
        )

    def resolve(self, handle, lo: int, hi: int):
        """Block on ``handle`` and decode the window's chunk-partial
        accumulator — the exact value ``fold.of_batch``+``combine``
        produce on host lanes over the same span."""
        out = np.asarray(handle).astype(np.uint64)
        n = hi - lo + 1
        if self.variant == "fmin":
            return [int((out[0] << np.uint64(32)) | out[1]),
                    int((out[2] << np.uint64(32)) | out[3])]
        if self.variant == "topk":
            count = min(self.k, n)
            return [
                [int((out[0, s] << np.uint64(32)) | out[1, s]),
                 int((out[2, s] << np.uint64(32)) | out[3, s])]
                for s in range(count)
            ]
        if self.variant == "fmatch":
            probes = int(out[5])
            if not int(out[0]):
                return [None, None, probes]
            return [int((out[1] << np.uint64(32)) | out[2]),
                    int((out[3] << np.uint64(32)) | out[4]), probes]
        total = sum(int(out[i]) << (16 * i) for i in range(8))
        return [total, n]


@lru_cache(maxsize=None)
def lane_sweep(
    variant: str,
    *,
    k: int = 1,
    engine: str = "auto",
    width: Optional[int] = None,
    rows: int = ROWS,
) -> LaneSweep:
    """The factory the hashcore workload uses: resolves the engine and
    the (autotuned unless pinned) width once, then hands back the
    process-cached :class:`LaneSweep` for this job-constant tuple."""
    engine = resolve_engine(engine)
    if width is None:
        width = autotune_lane_width(engine, rows=rows)
    return LaneSweep(variant, int(width), rows,
                     k if variant == "topk" else 1, engine)


# ---------------------------------------------------------------------------
# width autotune: one-shot cached probe, hashcore's OWN cache
# ---------------------------------------------------------------------------

#: (backend, workload, engine, candidates, rows) -> winning width.
#: Deliberately a separate dict from rolled._autotune_cache — the key
#: spaces overlap in spirit (both are per-backend width probes) and a
#: shared cache would let one workload's winner shadow the other's.
_autotune_cache: Dict[Tuple, int] = {}


def autotune_lane_width(
    engine: str = "jnp",
    candidates: Tuple[int, ...] = (2048, 4096, 8192, 16384),
    *,
    rows: int = ROWS,
    reps: int = 3,
) -> int:
    """``rolled.autotune_width``'s shape, retargeted: time the fmin
    sweep program over dummy data at each candidate width, keep the
    best per-index rate, cache for the process lifetime. The probe
    compiles each candidate once — the winner's program is therefore
    already warm when the first real chunk dispatches."""
    from tpuminter.search import timed_call

    engine = resolve_engine(engine)
    key = (jax.default_backend(), "hashcore", engine,
           tuple(candidates), rows)
    hit = _autotune_cache.get(key)
    if hit is not None:
        return hit
    best_width, best_rate = candidates[0], -1.0
    for width in candidates:
        sweep = LaneSweep("fmin", width, rows, 1, engine)
        np.asarray(sweep.dispatch(0xA0701E, 0, sweep.window - 1))
        dt = min(
            timed_call(
                lambda w=sweep: np.asarray(
                    w.dispatch(0xA0701E, 0, w.window - 1)
                ),
                (),
            )
            for _ in range(max(1, reps))
        )
        rate = sweep.window / dt
        if rate > best_rate:
            best_width, best_rate = width, rate
    _autotune_cache[key] = best_width
    return best_width
