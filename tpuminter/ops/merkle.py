"""On-device extranonce → merkle-root → header-midstate roll.

The BASELINE.json:9-10 capability: when a worker exhausts the 32-bit
header nonce space it bumps the coinbase extranonce, which changes the
coinbase txid, which changes the merkle root, which changes the header —
and therefore the SHA midstate the hot search kernels specialize on.
The reference has no analogue (its toy PoW has no headers); stratum
miners do this on the host. Here the whole chain

    extranonce → coinbase txid → branch fold → merkle root
               → header midstate + variable tail words

runs as ONE jitted device program (:func:`make_extranonce_roll`), so a
>2^32 search never ships header bytes from the host: the roll's
``(midstate, tail_words)`` outputs stay on device and feed either the
jnp dynamic-header hash (``ops.sha256.header_digest_dyn``) or the
dynamic Pallas candidate kernel
(``kernels.pallas_search_candidates_hdr``) directly.

The roll is **batch-shaped**: :func:`make_extranonce_roll_batch` rolls
``B`` extranonces in ONE device call — ``(B,) u32 pairs → (B, 8)
midstates + (B, 3) tail batches`` — which is what lets a batched sweep
(``tpuminter.rolled``) cover many extranonce segments per dispatch
instead of re-entering host orchestration at every segment boundary.
The scalar :func:`make_extranonce_roll` is the same core at B-of-one.
:func:`roll_batch_deduped` layers the shared-compression discipline on
top (ISSUE 16): rows of a window that carry the same extranonce share
ONE roll evaluation, forked per row by a device gather.

Cost: ``3 + 3·len(branch)`` SHA-256 compressions per extranonce — per
2^32 nonces of search, i.e. ~1e-9 of the hot-loop work. The shared
sub-computations inside one roll are already single-evaluation: the
coinbase prefix blocks before the extranonce hole are compressed once
host-side into the template midstate, and the branch fold runs each
level as one batched :func:`_dsha256_pair` across all B rows.

Host reference semantics: ``chain.rolled_header`` /
``chain.CoinbaseTemplate`` (tests pin the device roll bit-equal).
"""

from __future__ import annotations

import struct
from functools import lru_cache
from typing import Callable, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpuminter.chain import HEADER_SIZE, SHA256_H0
from tpuminter.ops import sha256 as ops

__all__ = [
    "make_extranonce_roll",
    "make_extranonce_roll_batch",
    "roll_batch_deduped",
]

_H0 = np.array(SHA256_H0, dtype=np.uint32)
#: FIPS padding block for a 64-byte message (the merkle pair hash)
_PAD512 = np.array([0x80000000] + [0] * 14 + [512], dtype=np.uint32)
#: second-hash block words 8..15 for a 32-byte digest message
_PAD256 = np.array([0x80000000, 0, 0, 0, 0, 0, 0, 256], dtype=np.uint32)


def _bcast(const: np.ndarray, like: jnp.ndarray) -> jnp.ndarray:
    """Broadcast a (k,) constant over ``like``'s leading batch dims."""
    return jnp.broadcast_to(
        jnp.asarray(const), like.shape[:-1] + const.shape
    )


def _dsha256_pair(left8: jnp.ndarray, right8: jnp.ndarray) -> jnp.ndarray:
    """Double SHA-256 of the 64-byte concatenation of two 32-byte hashes
    given as (..., 8) u32 big-endian word batches — one merkle tree edge,
    elementwise over leading batch dims."""
    h0 = _bcast(_H0, left8)
    state = ops.compress(h0, jnp.concatenate([left8, right8], axis=-1))
    state = ops.compress(state, _bcast(_PAD512, left8))
    return ops.compress(h0, jnp.concatenate([state, _bcast(_PAD256, left8)], axis=-1))


def _build_roll(
    header80: bytes,
    coinbase_prefix: bytes,
    coinbase_suffix: bytes,
    extranonce_size: int,
    branch: Sequence[bytes],
) -> Callable[[jnp.ndarray, jnp.ndarray], Tuple[jnp.ndarray, jnp.ndarray]]:
    """The traceable batch roll body (un-jitted): ``(B,) u32 × 2 →
    ((B, 8), (B, 3))``. Shared by both public factories; callers that
    fuse the roll into a larger program trace this directly."""
    if len(header80) != HEADER_SIZE:
        raise ValueError(f"header must be {HEADER_SIZE} bytes, got {len(header80)}")
    if not 1 <= extranonce_size <= 8:
        raise ValueError("extranonce_size must be in [1, 8]")
    for sib in branch:
        if len(sib) != 32:
            raise ValueError("merkle branch entries must be 32 bytes")

    # coinbase txid as a NonceTemplate: the extranonce is the "nonce
    # hole" (little-endian bytes at the prefix/suffix seam), so all the
    # midstate/partial-eval machinery applies to the coinbase hash too
    cb_message = coinbase_prefix + b"\x00" * extranonce_size + coinbase_suffix
    cb_template = ops._build_template(
        cb_message,
        len(coinbase_prefix),
        [(j, 8 * j) for j in range(extranonce_size)],
        double=True,
    )
    branch_words = [
        np.frombuffer(sib, dtype=">u4").astype(np.uint32) for sib in branch
    ]
    # header constants: words 0..8 of block 1 (version ‖ prev_hash) and
    # the time/bits tail words — big-endian u32 reads of the serialized
    # bytes, merkle-root bytes excluded
    hdr_head9 = np.frombuffer(header80[:36], dtype=">u4").astype(np.uint32)
    w_time, w_bits = struct.unpack(">2I", header80[68:76])
    time_bits = np.array([w_time, w_bits], dtype=np.uint32)

    def roll(en_hi: jnp.ndarray, en_lo: jnp.ndarray):
        txid = ops.sha256_batch(
            cb_template, en_hi.astype(jnp.uint32), en_lo.astype(jnp.uint32)
        )  # (B, 8) coinbase txid words (big-endian u32 of txid bytes)
        node = txid
        for sib in branch_words:
            # coinbase is leaf 0: the running node is always the LEFT
            # input at every level (index path all zeros)
            node = _dsha256_pair(node, _bcast(sib, node))
        # merkle root bytes land in the header verbatim (internal byte
        # order == digest byte order), so root words ARE header words:
        # block 1 = version ‖ prev_hash ‖ root[0:28]
        midstate = ops.compress(
            _bcast(_H0, node),
            jnp.concatenate([_bcast(hdr_head9, node), node[..., :7]], axis=-1),
        )
        tail_words = jnp.concatenate(
            [node[..., 7:8], _bcast(time_bits, node)], axis=-1
        )
        return midstate, tail_words

    return roll


def make_extranonce_roll(
    header80: bytes,
    coinbase_prefix: bytes,
    coinbase_suffix: bytes,
    extranonce_size: int,
    branch: Sequence[bytes],
) -> Callable[[jnp.ndarray, jnp.ndarray], Tuple[jnp.ndarray, jnp.ndarray]]:
    """Compile the device roll for one job.

    Returns ``roll(en_hi_u32, en_lo_u32) -> (midstate (8,) u32,
    tail_words (3,) u32)``: the SHA-256 state after the rolled header's
    first 64 bytes, and the header tail words ``(merkle word 7, time,
    bits)`` — exactly what ``ops.header_digest_dyn`` and the dynamic
    Pallas kernel consume. ``header80``'s merkle-root field is ignored
    (it is what the roll recomputes); version/prev/time/bits are baked
    as constants. ≡ ``ops.header_template(chain.rolled_header(...).
    pack())``'s ``midstate``/``tail_words()`` for every extranonce
    (pinned by tests/test_extranonce.py).
    """
    return _cached_scalar_roll(
        header80, coinbase_prefix, coinbase_suffix, extranonce_size,
        tuple(branch),
    )


@lru_cache(maxsize=32)
def _cached_scalar_roll(header80, coinbase_prefix, coinbase_suffix,
                        extranonce_size, branch):
    """Jitted rolls are cached by their job constants: a re-submitted
    (or re-benchmarked) job must reuse the compiled program instead of
    re-tracing — a fresh ``jax.jit`` wrapper per call is a fresh jit
    cache entry, measured ~0.6 s per re-trace on the CPU engine."""
    batch = _build_roll(
        header80, coinbase_prefix, coinbase_suffix, extranonce_size, branch
    )

    @jax.jit
    def roll(en_hi: jnp.ndarray, en_lo: jnp.ndarray):
        mid, tail = batch(en_hi.reshape(1), en_lo.reshape(1))
        return mid[0], tail[0]

    return roll


def make_extranonce_roll_batch(
    header80: bytes,
    coinbase_prefix: bytes,
    coinbase_suffix: bytes,
    extranonce_size: int,
    branch: Sequence[bytes],
    *,
    jit: bool = True,
) -> Callable[[jnp.ndarray, jnp.ndarray], Tuple[jnp.ndarray, jnp.ndarray]]:
    """Batched twin of :func:`make_extranonce_roll`: ONE device call
    rolls a whole extranonce batch — ``roll(en_hi (B,), en_lo (B,)) ->
    (midstates (B, 8) u32, tail_words (B, 3) u32)``, row ``i`` ≡ the
    scalar roll of ``(en_hi[i], en_lo[i])`` (pinned bit-equal by
    tests/test_extranonce.py). This is the producer side of the batched
    rolled sweep (``tpuminter.rolled``): B segment midstates per
    dispatch instead of one host-orchestrated roll per segment.

    ``jit=False`` returns the traceable body for callers embedding the
    roll in their own jitted program.
    """
    if jit:
        return _cached_batch_roll(
            header80, coinbase_prefix, coinbase_suffix, extranonce_size,
            tuple(branch),
        )
    return _build_roll(
        header80, coinbase_prefix, coinbase_suffix, extranonce_size, branch
    )


@lru_cache(maxsize=32)
def _cached_batch_roll(header80, coinbase_prefix, coinbase_suffix,
                       extranonce_size, branch):
    return jax.jit(_build_roll(
        header80, coinbase_prefix, coinbase_suffix, extranonce_size, branch
    ))


def roll_batch_deduped(
    roll: Callable[[jnp.ndarray, jnp.ndarray], Tuple[jnp.ndarray, jnp.ndarray]],
    en_hi: np.ndarray,
    en_lo: np.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Roll each UNIQUE extranonce once and fork the result per row —
    the ISSUE 16 "compress the shared coinbase prefix once" discipline
    at whole-roll granularity: in a compute-bound window (nonce span ≥
    roll_batch × width) every row of the tile plan carries the SAME
    extranonce, and the plain batched roll re-computes the identical
    coinbase hash, branch fold, and midstate compress B times.

    Row ``i`` of the output is bit-for-bit the plain
    ``roll(en_hi, en_lo)`` row ``i``: the roll is elementwise over its
    batch dim, so rolling the unique set and gathering is the same u32
    arithmetic per lane (integer ops — no reassociation hazard).
    Uniques are padded to the next power of two so the jitted roll sees
    at most ``log2(B)+1`` distinct shapes instead of one per duplicate
    pattern (the shape-bucketing rule ``rolled.lean_plan`` established).

    Why not the fully-unrolled symbolic roll instead: measured 11x
    faster steady-state (0.675 → 0.061 ms/call) but ~40 s trace+compile
    PER JOB vs ~1 s — a job-change latency regression no steady-state
    win covers at ~30 compressions/window. Recorded as a PERF.md §Round
    14 rejection; this host-side dedup captures the duplicate-row share
    of that win with zero new compiled programs.
    """
    en = (en_hi.astype(np.uint64) << np.uint64(32)) | en_lo.astype(np.uint64)
    uniq, inv = np.unique(en, return_inverse=True)
    if len(uniq) == len(en):
        return roll(jnp.asarray(en_hi), jnp.asarray(en_lo))
    n = 1 << max(0, int(len(uniq) - 1).bit_length())
    padded = np.concatenate([uniq, np.repeat(uniq[:1], n - len(uniq))])
    mids, tails = roll(
        jnp.asarray((padded >> np.uint64(32)).astype(np.uint32)),
        jnp.asarray((padded & np.uint64(0xFFFFFFFF)).astype(np.uint32)),
    )
    idx = jnp.asarray(inv.astype(np.int32))
    return mids[idx], tails[idx]
