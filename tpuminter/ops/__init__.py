"""Device-side ops: jnp/XLA implementations of the mining hot path.

This is the data plane (SURVEY.md §7 stage 3): pure-functional SHA-256
on u32 vectors, vmappable over a nonce batch, plus the lexicographic
256-bit compare/argmin primitives the search needs. ``tpuminter.kernels``
holds the hand-written Pallas versions of the same contracts; everything
here also runs on the CPU backend for CI (tests/conftest.py).
"""

from tpuminter.ops.sha256 import (
    NonceTemplate,
    compress,
    digest_to_int,
    double_sha256_header_batch,
    hash_words_be,
    header_template,
    lex_argmin,
    lex_le,
    sha256_batch,
    target_to_words,
    toy_template,
)

__all__ = [
    "NonceTemplate",
    "compress",
    "digest_to_int",
    "double_sha256_header_batch",
    "hash_words_be",
    "header_template",
    "lex_argmin",
    "lex_le",
    "sha256_batch",
    "target_to_words",
    "toy_template",
]
