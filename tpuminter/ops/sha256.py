"""SHA-256 on device: jnp u32 vectors, vmapped over nonce batches.

The TPU-first design (SURVEY.md §7 stage 3-4; north-star BASELINE.json:5):

- **Midstate specialization.** A mining message is constant except for a
  few nonce bytes near the end. All 64-byte blocks before the first
  nonce-bearing word are compressed ONCE on the host
  (``chain.midstate``-style); the device only compresses the remaining
  "tail" block(s) per candidate. For an 80-byte Bitcoin header that is 1
  tail block + the 1-block second hash — 2 compressions per nonce
  instead of 3.
- **Trace-time message templates.** Where the nonce bytes land in the
  tail (block, word, intra-word shift) depends only on the job, never on
  the candidate, so a :class:`NonceTemplate` carries those positions as
  *Python ints* and the jitted batch functions close over them — all
  indexing is static, XLA sees straight-line u32 ALU code it can tile
  onto the VPU. No dynamic shapes, no data-dependent control flow.
- **64-bit nonces as u32 pairs.** The toy dialect's nonce space is
  2^64; JAX's default (and TPU-native) int width is 32, so nonces travel
  as ``(hi, lo)`` u32 vectors and 64-bit/256-bit comparisons are
  lexicographic over u32 lanes (:func:`lex_le`, :func:`lex_argmin`).

Everything is pure; no global state. Host-side reference semantics live
in ``tpuminter.chain`` (verified against hashlib / the genesis block);
the equivalence tests in tests/test_ops_sha256.py pin this module to it.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpuminter.chain import SHA256_H0, SHA256_K, sha256_compress

__all__ = [
    "compress",
    "NonceTemplate",
    "toy_template",
    "header_template",
    "sha256_batch",
    "double_sha256_header_batch",
    "HEADER_NONCE_POSITIONS",
    "HEADER_TAIL_PAD",
    "header_digest_dyn",
    "header_e60_e61_dyn",
    "byteswap32",
    "hash_words_be",
    "lex_le",
    "lex_argmin",
    "target_to_words",
    "digest_to_int",
]

_K = tuple(np.uint32(k) for k in SHA256_K)
_K_ARR = np.array(SHA256_K, dtype=np.uint32)
_H0 = np.array(SHA256_H0, dtype=np.uint32)


def _rotr(x: jnp.ndarray, n: int) -> jnp.ndarray:
    # TPUs have no rotate instruction; XLA lowers this shift/or pair onto
    # the VPU (pallas_guide: same form the hand kernel uses).
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _round_unroll() -> bool:
    """Unroll the 64 rounds at trace time only where it pays off.

    TPU: XLA handles the flat ~7k-op graph fine and straight-line code
    schedules best. CPU (the CI backend): compile time scales hard with
    unrolled program size — one or two compressions inside a scan body
    compile in ~3 s and run ~30x faster than the scanned form (the
    shared-schedule sweep, PERF.md §Round 14), but stacking their output
    into a trailing-axis array or chaining ~10 compressions straight-line
    (the roll: ~40 s/job; the tracking step: 15-42 s) blows the compile
    budget. The scanned default stays right for this general-purpose
    entry point, which callers embed many-at-a-time; the sweep-shaped
    winners opt into the unrolled symbolic form explicitly
    (:func:`header_e60_e61_dyn`).
    """
    return jax.default_backend() not in ("cpu",)


def compress(
    state: jnp.ndarray, block: jnp.ndarray, unroll: bool | None = None
) -> jnp.ndarray:
    """One SHA-256 compression: ``state (..., 8) u32``, ``block (..., 16)
    u32`` → ``(..., 8) u32``, elementwise over leading batch dims.

    ≡ ``chain.sha256_compress`` (FIPS 180-4). The message schedule is
    computed on the fly inside the round loop via the classic rolling
    16-word window (w[i+16] = w[i] + σ0(w[i+1]) + w[i+9] + σ1(w[i+14])),
    which keeps the scanned form O(1) state; the unrolled form emits the
    same dataflow flattened.

    ``unroll`` overrides the backend default (:func:`_round_unroll`).
    Callers that embed MANY compressions in one program (the scrypt
    PBKDF2 walls: 21 of them) pass ``False`` — 21 × ~7k unrolled ops
    bloat the XLA program into minutes of compile time for a stage
    that is ~2% of scrypt's runtime.
    """
    if _round_unroll() if unroll is None else unroll:
        return _compress_unrolled(state, block)
    return _compress_scanned(state, block)


def _schedule_next(win: jnp.ndarray) -> jnp.ndarray:
    """w[i+16] from the window w[i..i+15] (last axis)."""
    s0 = (
        _rotr(win[..., 1], 7) ^ _rotr(win[..., 1], 18) ^ (win[..., 1] >> np.uint32(3))
    )
    s1 = (
        _rotr(win[..., 14], 17)
        ^ _rotr(win[..., 14], 19)
        ^ (win[..., 14] >> np.uint32(10))
    )
    return win[..., 0] + s0 + win[..., 9] + s1


def _one_round(vars8, k_plus_w):
    a, b, c, d, e, f, g, h = vars8
    s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
    ch = (e & f) ^ (~e & g)
    t1 = h + s1 + ch + k_plus_w
    s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
    maj = (a & b) ^ (a & c) ^ (b & c)
    t2 = s0 + maj
    return (t1 + t2, a, b, c, d + t1, e, f, g)


def _compress_unrolled(state: jnp.ndarray, block: jnp.ndarray) -> jnp.ndarray:
    w = [block[..., i] for i in range(16)]
    for i in range(16, 64):
        s0 = _rotr(w[i - 15], 7) ^ _rotr(w[i - 15], 18) ^ (w[i - 15] >> np.uint32(3))
        s1 = _rotr(w[i - 2], 17) ^ _rotr(w[i - 2], 19) ^ (w[i - 2] >> np.uint32(10))
        w.append(w[i - 16] + s0 + w[i - 7] + s1)
    vars8 = tuple(state[..., i] for i in range(8))
    for i in range(64):
        vars8 = _one_round(vars8, _K[i] + w[i])
    return jnp.stack(
        [state[..., i] + vars8[i] for i in range(8)], axis=-1
    )


def _compress_scanned(state: jnp.ndarray, block: jnp.ndarray) -> jnp.ndarray:
    def step(carry, k):
        vars8, win = carry
        vars8 = _one_round(vars8, k + win[..., 0])
        win = jnp.concatenate(
            [win[..., 1:], _schedule_next(win)[..., None]], axis=-1
        )
        return (vars8, win), None

    init = (tuple(state[..., i] for i in range(8)), block)
    (vars8, _), _ = jax.lax.scan(step, init, jnp.asarray(_K_ARR))
    return jnp.stack([state[..., i] + vars8[i] for i in range(8)], axis=-1)


# ---------------------------------------------------------------------------
# Nonce templates: host-side message planning
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class NonceTemplate:
    """A padded SHA-256 message with a nonce-shaped hole.

    ``midstate``: state after the constant prefix blocks (8 u32).
    ``tail``: the remaining block(s), nonce bytes zeroed ((n, 16) u32).
    ``positions``: one entry per nonce byte —
    ``(block, word, word_shift, nonce_shift)`` meaning
    ``tail[block, word] |= ((nonce >> nonce_shift) & 0xFF) << word_shift``.
    All entries are Python ints: jitted code closes over them as static
    constants (this dataclass is hashable ⇒ usable as a jit cache key).
    ``double``: apply a second SHA-256 over the 32-byte digest (Bitcoin).
    """

    midstate: Tuple[int, ...]
    tail: Tuple[Tuple[int, ...], ...]
    positions: Tuple[Tuple[int, int, int, int], ...]
    double: bool = False

    @property
    def n_tail_blocks(self) -> int:
        return len(self.tail)

    def tail_array(self) -> np.ndarray:
        return np.array(self.tail, dtype=np.uint32)

    def midstate_array(self) -> np.ndarray:
        return np.array(self.midstate, dtype=np.uint32)


def _pad(message_len: int) -> bytes:
    """FIPS 180-4 padding for a ``message_len``-byte message."""
    pad = b"\x80" + b"\x00" * ((55 - message_len) % 64)
    return pad + struct.pack(">Q", message_len * 8)


def _build_template(
    message_with_hole: bytes,
    hole_offset: int,
    byte_map: Sequence[Tuple[int, int]],
    *,
    double: bool,
) -> NonceTemplate:
    """Plan a template: ``byte_map[j] = (offset_delta, nonce_shift)`` puts
    ``(nonce >> nonce_shift) & 0xFF`` at ``hole_offset + offset_delta``."""
    padded = message_with_hole + _pad(len(message_with_hole))
    assert len(padded) % 64 == 0
    first_hole_block = min(hole_offset + d for d, _ in byte_map) // 64
    state = tuple(SHA256_H0)
    for b in range(first_hole_block):
        state = sha256_compress(state, padded[b * 64 : (b + 1) * 64])
    tail_bytes = padded[first_hole_block * 64 :]
    tail = tuple(
        struct.unpack(">16I", tail_bytes[b * 64 : (b + 1) * 64])
        for b in range(len(tail_bytes) // 64)
    )
    positions = []
    for offset_delta, nonce_shift in byte_map:
        off = hole_offset + offset_delta - first_hole_block * 64
        positions.append((off // 64, (off % 64) // 4, 24 - 8 * (off % 4), nonce_shift))
    return NonceTemplate(
        midstate=state, tail=tail, positions=tuple(positions), double=double
    )


def toy_template(data: bytes) -> NonceTemplate:
    """Template for the toy dialect: SHA-256(data ‖ nonce_be8), any data
    length (≡ ``chain.toy_hash``). The 8 big-endian nonce bytes may be
    unaligned and may straddle a block boundary; the byte map handles
    both."""
    message = data + b"\x00" * 8
    byte_map = [(j, 56 - 8 * j) for j in range(8)]
    return _build_template(message, len(data), byte_map, double=False)


def header_template(header80: bytes) -> NonceTemplate:
    """Template for Bitcoin: double-SHA-256 over an 80-byte header whose
    final 4 bytes are the little-endian nonce (≡ ``BlockHeader`` +
    ``chain.dsha256``). One tail block; midstate covers bytes [0, 64)."""
    if len(header80) != 80:
        raise ValueError(f"header must be 80 bytes, got {len(header80)}")
    message = header80[:76] + b"\x00" * 4
    byte_map = [(j, 8 * j) for j in range(4)]  # little-endian
    return _build_template(message, 76, byte_map, double=True)


# ---------------------------------------------------------------------------
# Batched hashing
# ---------------------------------------------------------------------------

def _inject_nonces(
    template: NonceTemplate, nonce_hi: jnp.ndarray, nonce_lo: jnp.ndarray
) -> jnp.ndarray:
    """Broadcast the tail template over the batch and OR in the nonce
    bytes at their static positions → ``(N, n_blocks, 16) u32``."""
    n = nonce_lo.shape[0]
    tail = jnp.broadcast_to(
        jnp.asarray(template.tail_array()), (n,) + (template.n_tail_blocks, 16)
    )
    for block, word, word_shift, nonce_shift in template.positions:
        src = nonce_hi if nonce_shift >= 32 else nonce_lo
        shift = nonce_shift - 32 if nonce_shift >= 32 else nonce_shift
        byte = (src >> np.uint32(shift)) & np.uint32(0xFF)
        tail = tail.at[:, block, word].add(byte << np.uint32(word_shift))
    return tail


def sha256_batch(
    template: NonceTemplate, nonce_hi: jnp.ndarray, nonce_lo: jnp.ndarray
) -> jnp.ndarray:
    """Digests for a batch of nonces: ``(N,) u32 × 2 → (N, 8) u32``
    (digest as big-endian u32 words, i.e. ``struct.unpack('>8I', digest)``).

    Applies the template's second hash when ``template.double``.
    """
    n = nonce_lo.shape[0]
    tail = _inject_nonces(template, nonce_hi, nonce_lo)
    state = jnp.broadcast_to(jnp.asarray(template.midstate_array()), (n, 8))
    for b in range(template.n_tail_blocks):
        state = compress(state, tail[:, b, :])
    if template.double:
        # second message: 32-byte digest ‖ 0x80 ‖ zeros ‖ len(256 bits)
        block2 = jnp.concatenate(
            [
                state,
                jnp.broadcast_to(
                    jnp.asarray(
                        np.array(
                            [0x80000000, 0, 0, 0, 0, 0, 0, 256], dtype=np.uint32
                        )
                    ),
                    (n, 8),
                ),
            ],
            axis=-1,
        )
        state = compress(jnp.broadcast_to(jnp.asarray(_H0), (n, 8)), block2)
    return state


def double_sha256_header_batch(
    template: NonceTemplate, nonces: jnp.ndarray
) -> jnp.ndarray:
    """Convenience wrapper for header mining: u32 nonce vector → (N, 8)
    digest words of double-SHA-256(header with that nonce)."""
    zeros = jnp.zeros_like(nonces)
    return sha256_batch(template, zeros, nonces)


# ---------------------------------------------------------------------------
# Dynamic header hashing (the on-device extranonce-roll consumer)
# ---------------------------------------------------------------------------

#: nonce byte positions in an 80-byte header's tail block: little-endian
#: u32 at bytes 76..80, i.e. word 3 of the second block (what
#: ``header_template`` computes; pinned by tests against it)
HEADER_NONCE_POSITIONS: Tuple[Tuple[int, int, int, int], ...] = (
    (0, 3, 24, 0),
    (0, 3, 16, 8),
    (0, 3, 8, 16),
    (0, 3, 0, 24),
)

#: constant schedule words 4..15 of an 80-byte header's tail block
#: (FIPS 180-4 padding for an 80-byte message: 0x80 then the 640-bit len)
HEADER_TAIL_PAD: Tuple[int, ...] = (0x80000000, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 640)


def header_digest_dyn(
    midstate8: jnp.ndarray, tailw3: jnp.ndarray, nonces: jnp.ndarray
) -> jnp.ndarray:
    """Double-SHA-256 digests for a header whose midstate and variable
    tail words are *runtime values* (u32 arrays of shape (..., 8) and
    (..., 3)), not trace-time constants: ``(N,) u32 nonces → (N, 8)
    digest words`` — or, batched over roll rows, ``(B, 8) midstates +
    (B, 3) tails + (B, N) nonces → (B, N, 8)``: row ``i``'s nonces are
    hashed under row ``i``'s header. The batched form is the jnp engine
    of the batched rolled sweep (``tpuminter.rolled``): one dispatch
    sweeps every row of a ``make_extranonce_roll_batch`` output.

    This is the hash the on-device extranonce roll feeds
    (``ops.merkle.make_extranonce_roll`` produces exactly this
    ``(midstate, tail_words)`` pair from an extranonce, BASELINE.json:
    9-10): one compiled program serves every extranonce — and every
    header-mining job — because nothing job-specific is baked in.
    ``tailw3`` is ``(merkle_root word 7, time word, bits word)``, the
    three header tail words before the nonce. ≡ ``double_sha256_header_
    batch(header_template(header), nonces)`` for the equivalent header
    (tests pin them equal, batched rows included).

    Built on :func:`compress` (scanned on CPU, unrolled on TPU): this
    full-digest form feeds trailing-axis (N, 8) folds, and stacking the
    unrolled symbolic form's separate word values into that layout is a
    measured CPU loss (0.2-4x runtime at 15-42 s compile, PERF.md §Round
    14 rejection) — the truncated candidate twin
    (:func:`header_e60_e61_dyn`), which never materializes the stack, is
    where the unrolled form wins 34x. The little-endian nonce bytes at
    header offset 76 read as a big-endian schedule word are simply
    ``byteswap(nonce)``.
    """
    shape = nonces.shape
    tail = jnp.concatenate(
        [
            jnp.broadcast_to(tailw3[..., None, :], shape + (3,)),
            byteswap32(nonces)[..., None],
            jnp.broadcast_to(
                jnp.asarray(np.array(HEADER_TAIL_PAD, dtype=np.uint32)),
                shape + (12,),
            ),
        ],
        axis=-1,
    )
    state = compress(
        jnp.broadcast_to(midstate8[..., None, :], shape + (8,)), tail
    )
    block2 = jnp.concatenate(
        [
            state,
            jnp.broadcast_to(
                jnp.asarray(
                    np.array([0x80000000, 0, 0, 0, 0, 0, 0, 256], dtype=np.uint32)
                ),
                shape + (8,),
            ),
        ],
        axis=-1,
    )
    return compress(jnp.broadcast_to(jnp.asarray(_H0), shape + (8,)), block2)


def header_e60_e61_dyn(
    midstate8: jnp.ndarray, tailw3: jnp.ndarray, nonces: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``(e60, e61)`` of the double-SHA for one dynamic header row — the
    shared-schedule sweep engine (ISSUE 16): digest word 7 is
    ``SHA256_H0[7] + e60`` and word 6 is ``symbolic.DIGEST6_BIAS + e61``,
    so the candidate test over the hash's top 64 bits needs nothing else
    (bit-for-bit ≡ the same test on :func:`header_digest_dyn` output;
    tier-1 pins it).

    Unlike :func:`header_digest_dyn` this IS built on the symbolic
    unrolled form — the AsicBoost discipline (arxiv 1604.00575) expressed
    as lane-level common-subexpression scheduling: every nonce of the
    sweep collides on ``(midstate, merkle word 7, time, bits)``, so the
    nonce-free rounds 0-2, schedule words w16/w17, and the scalar parts
    of w18/w19 stay 0-d (computed once per row, not per lane), constants
    fold at trace time, the second compression truncates at round 61,
    and — decisively on this backend — the straight-line rounds dodge the
    per-round ``lax.scan`` overhead that dominates the scanned compress
    at sweep widths (measured 34x at 8x256, ~3 s one-time compile per
    (width, cand_bits) shape; PERF.md §Round 14). The inputs are 0-d u32
    scalars + a (N,) nonce vector: exactly one row of the batched rolled
    sweep's ``lax.scan``.
    """
    from tpuminter.ops import symbolic as sym

    mid = [midstate8[..., i] for i in range(8)]
    block = [
        tailw3[..., 0], tailw3[..., 1], tailw3[..., 2],
        byteswap32(nonces), *HEADER_TAIL_PAD,
    ]
    return sym.hash_sym_e60_e61(mid, [block], (), 0, 0)


# ---------------------------------------------------------------------------
# 256-bit comparisons in u32 lanes
# ---------------------------------------------------------------------------

def byteswap32(x: jnp.ndarray) -> jnp.ndarray:
    """Per-lane u32 byte swap (big-endian ↔ little-endian word reads);
    shared by the hash-value converters here and the scrypt word seams."""
    return (
        ((x & np.uint32(0x000000FF)) << np.uint32(24))
        | ((x & np.uint32(0x0000FF00)) << np.uint32(8))
        | ((x & np.uint32(0x00FF0000)) >> np.uint32(8))
        | ((x & np.uint32(0xFF000000)) >> np.uint32(24))
    )


def hash_words_be(digest_words: jnp.ndarray) -> jnp.ndarray:
    """Digest words → the 256-bit *hash value* as big-endian u32 words,
    most significant first: Bitcoin interprets the digest as a
    little-endian integer, so word j = byteswap(digest_word[7-j])."""
    return byteswap32(digest_words[..., ::-1])


def lex_le(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lexicographic ``a <= b`` over the last axis (msb-first u32 words);
    broadcasts, returns bool with the last axis reduced."""
    lt = jnp.zeros(jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1]), dtype=bool)
    eq = jnp.ones_like(lt)
    for k in range(a.shape[-1]):
        ak, bk = a[..., k], b[..., k]
        lt = lt | (eq & (ak < bk))
        eq = eq & (ak == bk)
    return lt | eq


def lex_argmin(words: jnp.ndarray) -> jnp.ndarray:
    """Index of the lexicographic minimum of ``words (N, W)`` (msb-first
    u32 words); ties resolve to the lowest index (= lowest nonce, the
    coordinator's fold order). O(W) min+mask passes — no 64-bit math."""
    n, w = words.shape
    mask = jnp.ones((n,), dtype=bool)
    big = np.uint32(0xFFFFFFFF)
    for k in range(w):
        col = jnp.where(mask, words[:, k], big)
        mask = mask & (col == col.min())
    return jnp.argmax(mask)


# ---------------------------------------------------------------------------
# Host-side converters
# ---------------------------------------------------------------------------

def target_to_words(target: int) -> np.ndarray:
    """256-bit target integer → msb-first u32 words, comparable against
    :func:`hash_words_be` output with :func:`lex_le`."""
    if not 0 <= target < 1 << 256:
        raise ValueError("target out of range")
    raw = target.to_bytes(32, "big")
    return np.frombuffer(raw, dtype=">u4").astype(np.uint32)


def digest_to_int(digest_words: np.ndarray) -> int:
    """(8,) digest words → Bitcoin's little-endian uint256 hash value
    (≡ ``chain.hash_to_int(digest_bytes)``)."""
    raw = b"".join(struct.pack(">I", int(w)) for w in digest_words)
    return int.from_bytes(raw, "little")
