"""Persistent XLA compilation cache for the production device paths.

A worker restart re-pays every program's XLA compile — 20–40 s each
through this image's remote-TPU tunnel (PERF.md environment table), and
`BENCH_r05` recorded `time_to_block_cold_ms = 23,380` vs 91 ms warm. The
fix has existed in-tree for CI subprocesses since round 4
(``__graft_entry__.virtual_cpu_env`` sets the env vars), but the worker
CLI and bench never enabled it for TPU (VERDICT r5 missing #1). With the
cache on, a respawned process's first dispatch loads the serialized
executable from disk and costs the ~100–200 ms dispatch floor, like the
reference's compiled Go worker's zero-warmup restart.

Env overrides: ``JAX_COMPILATION_CACHE_DIR`` relocates the cache (e.g.
onto a shared volume so a whole fleet warms from one compile);
``JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS`` tunes the persistence
threshold.
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["DEFAULT_CACHE_DIR", "enable_compilation_cache"]

DEFAULT_CACHE_DIR = "/tmp/tpuminter-jax-cache"


def enable_compilation_cache(
    path: Optional[str] = None, min_compile_secs: Optional[float] = None
) -> str:
    """Point JAX's persistent compilation cache at ``path`` (idempotent;
    safe before or after other JAX use — cache config is read per
    compile). Returns the directory used so callers can report it.

    The 0.5 s persistence threshold keeps throwaway CI micro-programs
    out while catching everything that hurts: the search kernels,
    scrypt's scanned pipeline, and the shard_map pod programs all
    compile in seconds to minutes.
    """
    import jax

    if path is None:
        path = os.environ.get("JAX_COMPILATION_CACHE_DIR", DEFAULT_CACHE_DIR)
    if min_compile_secs is None:
        min_compile_secs = float(
            os.environ.get("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
        )
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", min_compile_secs
    )
    return path
