"""Batched on-device extranonce rolling: one dispatch sweeps many rolls.

PR 1 moved the roll itself on device (``ops.merkle``), but the rolled
production path still paid a **host-orchestrated loop per extranonce**:
one synchronous ``roll()`` call per segment, then a fresh
``CandidateSearch`` drained to completion before the next extranonce
started — the depth-2 double buffering died at every segment boundary,
and at test/CI ``nonce_bits`` (≤ 20) the boundary cost dominated. This
module makes the rolled sweep batch- and pipeline-native end to end:

- **Tiles, not segments.** A dispatch window of ``roll_batch × width``
  global indices decomposes into ``chain.rolled_tiles`` — ``(segment,
  base, n)`` rows that never cross an extranonce boundary but whose
  *window* does. ``width`` divides the segment size (both powers of
  two), so a window needs at most ``roll_batch + 2`` rows
  (:func:`plan_tiles` pads to exactly that, keeping every dispatch the
  same compiled shape).
- **One roll call per window.** ``ops.merkle.make_extranonce_roll_batch``
  produces every row's ``(midstate, tail_words)`` in ONE device call;
  the outputs never visit the host.
- **One sweep call per window.** The per-row-midstate candidate sweep
  (``kernels.pallas_search_candidates_hdr_batch`` on TPU, its jnp
  mirror here on the CPU mesh) grids over (roll-row × nonce-slab), so
  one dispatch covers ``roll_batch · width`` global indices.
- **One search for the whole job.** ``search.CandidateSearch`` runs
  over *global* indices (``domain = 2^span_bits``) with windows as its
  slabs — depth-``k`` pipelining now spans segment boundaries, and the
  min-fold/candidate bookkeeping is keyed by global index exactly as
  before.

``roll_batch=1`` keeps the per-segment loop reachable as the A/B
baseline (:func:`mine_rolled_fast` routes to the segmented form — the
pre-batching production path, bit-for-bit).

The ``engine`` seam ("pallas" on TPU, "jnp" on the CPU mesh) is what
lets CI pin the whole batched path — and bench.py measure the A/B —
without a chip. ``cand_bits`` scales the candidate bar for tests ONLY:
production keeps 32 (top hash word zero + the hash-word-1 cap, the
necessary condition at every real difficulty); tests shrink it so a
CI-sized space contains candidates and the full surfacing/re-issue/
min-fold machinery gets exercised at toy difficulty.
"""

from __future__ import annotations

import struct
from functools import lru_cache, partial
from typing import Callable, Dict, Iterator, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from tpuminter import chain
from tpuminter.ops import sha256 as ops
from tpuminter.protocol import MIN_UNTRACKED, Request, Result
from tpuminter.search import (
    CandidateSearch, pack_handle, pipeline_spans, resolve_handle, timed_call,
)

__all__ = [
    "plan_tiles",
    "TilePlan",
    "tile_width",
    "span_bits",
    "rolled_verifier",
    "mine_rolled_fast",
    "mine_rolled_tracking",
    "autotune_width",
    "ProgressFn",
    "report_search_progress",
]

_UMAX = np.uint32(0xFFFFFFFF)

#: progress(high_water, best_nonce, best_hash): sub-chunk settled
#: high-water reporting for roll-budget chunks (ISSUE 14). Called at
#: window/segment boundaries from the mining (executor) thread with the
#: highest verifiably-swept GLOBAL index and the running min-fold pair
#: (``MIN_UNTRACKED`` when no candidate surfaced yet). The worker role
#: loop installs one to feed Beacon emission; None (the default
#: everywhere) keeps the paths bit-for-bit on their pre-beacon behavior.
ProgressFn = Callable[[int, int, int], None]


def span_bits(req: Request) -> int:
    """Bit width of a rolled job's global (extranonce × nonce) index
    space — the ``CandidateSearch`` domain (mirrors protocol
    validation)."""
    return min(64, req.nonce_bits + 8 * req.extranonce_size)


def tile_width(nonce_bits: int, cap: int) -> int:
    """Per-row sweep width: the segment size, capped at the largest
    power of two ≤ ``cap``. Power-of-two by construction, so it divides
    the segment size — the invariant :func:`plan_tiles`'s row bound
    rests on."""
    if cap < 1:
        raise ValueError("width cap must be >= 1")
    return min(1 << nonce_bits, 1 << (cap.bit_length() - 1))


class TilePlan(NamedTuple):
    """One dispatch window's rows, padded to a fixed count (host-side
    numpy, ready to feed the batched roll + sweep). ``goffs`` are global
    offsets relative to the window start (u32 — windows are < 2^32 by
    construction); padding rows have ``valids == 0`` and can never
    surface a candidate."""

    en_hi: np.ndarray
    en_lo: np.ndarray
    bases: np.ndarray
    valids: np.ndarray
    goffs: np.ndarray


def plan_tiles(
    start: int,
    n: int,
    nonce_bits: int,
    width: int,
    rows: int,
    hard_end: Optional[int] = None,
    interleave: int = 1,
) -> TilePlan:
    """Decompose the window ``[start, start + n)`` into ≤ ``rows``
    ``chain.rolled_tiles`` rows, padded to exactly ``rows``.

    ``hard_end`` clamps at the index domain's end (oversweep past a
    job's ``upper`` is fine — the search's clean-sweep accounting
    ignores it — but extranonces past the domain don't exist).
    ``interleave=k`` lays rows out device-major for a k-device sharded
    sweep: shard ``d``'s contiguous block holds global-order stripes
    ``{s·k + d}``, so stripe-synchronous early exit stays exact (the
    ``parallel.build_candidate_sweep`` striping argument, row-shaped).
    """
    end = start + n - 1
    if hard_end is not None:
        end = min(end, hard_end)
    if rows % interleave != 0:
        raise ValueError("rows must be a multiple of interleave")
    tiles = list(chain.rolled_tiles(start, end, nonce_bits, width))
    if len(tiles) > rows:
        raise ValueError(
            f"window [{start}, {end}] needs {len(tiles)} rows > {rows}; "
            "width must divide the segment size (tile_width does)"
        )
    en_hi = np.zeros(rows, np.uint32)
    en_lo = np.zeros(rows, np.uint32)
    bases = np.zeros(rows, np.uint32)
    valids = np.zeros(rows, np.uint32)
    goffs = np.zeros(rows, np.uint32)
    for i, (en, base, take, gbase) in enumerate(tiles):
        en_hi[i] = en >> 32
        en_lo[i] = en & 0xFFFFFFFF
        bases[i] = base
        valids[i] = take
        goffs[i] = gbase - start
    if interleave > 1:
        # device-major permutation: new[d·S + s] = old[s·k + d]
        perm = (
            np.arange(rows)
            .reshape(rows // interleave, interleave)
            .T.reshape(-1)
        )
        en_hi, en_lo = en_hi[perm], en_lo[perm]
        bases, valids, goffs = bases[perm], valids[perm], goffs[perm]
    return TilePlan(en_hi, en_lo, bases, valids, goffs)


def lean_plan(plan: TilePlan, rows: int) -> TilePlan:
    """Shape-bucket a padded plan: when the tail rows past ``rows`` are
    all padding (every steady-state aligned window — raggedness only
    appears at job edges and candidate re-issues), slice to the lean
    ``rows``-row shape. Two compiled shapes total, and the common case
    stops paying the pad rows' full-width compute (measured +25% on the
    fixed-shape jnp engine at roll_batch=8)."""
    if plan.valids[rows:].any():
        return plan
    return TilePlan(*(a[:rows] for a in plan))


def rolled_verifier(req: Request):
    """Host-side exact verifier over GLOBAL indices: re-rolls the
    header (LRU per extranonce — a sweep revisits few) and applies the
    full 256-bit compare. The ``CandidateSearch`` ``verify`` callable
    for every batched rolled path."""
    cb = chain.CoinbaseTemplate(
        req.coinbase_prefix, req.coinbase_suffix, req.extranonce_size
    )

    @lru_cache(maxsize=64)
    def prefix76(en: int) -> bytes:
        return chain.rolled_header(req.header, cb, req.branch, en).pack()[:76]

    def verify(g: int) -> Tuple[bool, int]:
        en, nonce = chain.split_global(g, req.nonce_bits)
        h = chain.hash_to_int(
            chain.dsha256(prefix76(en) + struct.pack("<I", nonce))
        )
        return h <= req.target, h

    return verify


def report_search_progress(search: CandidateSearch, fallback_nonce: int,
                           progress: Optional[ProgressFn]) -> None:
    """One :data:`ProgressFn` step for a running global-index
    ``CandidateSearch``: report its settled high-water and running
    min-fold. No-op while nothing is settled or once the search has an
    outcome (a found outcome means a winner sits inside the would-be
    prefix — the final Result covers it). Shared by every batched rolled
    path (here and ``pod_worker``)."""
    if progress is None or search.outcome is not None:
        return
    hw = search.settled_high_water()
    if hw is None:
        return
    cand = search.best_candidate()
    if cand is None:
        progress(hw, fallback_nonce, MIN_UNTRACKED)
    else:
        progress(hw, cand[1], cand[0])


def _resolve_engine(engine: str) -> str:
    if engine == "auto":
        return "jnp" if jax.default_backend() == "cpu" else "pallas"
    if engine not in ("pallas", "jnp"):
        raise ValueError(f"unknown engine {engine!r}")
    return engine


def _count(counters: Optional[Dict[str, int]], key: str) -> None:
    if counters is not None:
        counters[key] = counters.get(key, 0) + 1


# ---------------------------------------------------------------------------
# candidate engines (the fast path's per-dispatch programs)
# ---------------------------------------------------------------------------

def _jnp_candidate_ok(digests, cap, cand_bits: int):
    """The early-reject candidate test, jnp form: top ``cand_bits`` hash
    bits zero (+ the hash-word-1 cap at the production 32). ``cand_bits
    < 32`` is the TEST seam — a CI-sized space then contains candidates
    and the fast path's surfacing machinery is exercisable at toy
    difficulty (soundness needs ``target < 2^(256 - cand_bits)``, which
    those tests arrange exactly as production difficulties do for 32)."""
    hw0 = ops.byteswap32(digests[..., 7])
    if cand_bits == 32:
        hw1 = ops.byteswap32(digests[..., 6])
        return (hw0 == 0) & (hw1 <= cap)
    return (hw0 >> np.uint32(32 - cand_bits)) == 0


def _jnp_candidate_ok_sched(mid, tw, nonces, cap, cand_bits: int):
    """The same candidate test from the shared-schedule truncated hash
    (ISSUE 16): digest word 7 = ``H0[7] + e60`` and word 6 =
    ``DIGEST6_BIAS + e61``, so the two words :func:`_jnp_candidate_ok`
    byteswaps are recovered exactly — same booleans, bit for bit — while
    the sweep skips the final rounds, the a-chain of rounds 58-61, the
    8 digest adds, and the whole (N, 8) digest materialization."""
    from tpuminter.ops import symbolic as sym

    e60, e61 = ops.header_e60_e61_dyn(mid, tw, nonces)
    hw0 = ops.byteswap32(sym.add(e60, int(ops.SHA256_H0[7])))
    if cand_bits == 32:
        hw1 = ops.byteswap32(sym.add(e61, sym.DIGEST6_BIAS))
        return (hw0 == 0) & (hw1 <= cap)
    return (hw0 >> np.uint32(32 - cand_bits)) == 0


@partial(jax.jit, static_argnums=(6, 7, 8))
def _jnp_batched_candidate_sweep(
    mids, tails, bases, valids, goffs, cap, width: int, cand_bits: int,
    sched: bool = False,
):
    """jnp mirror of ``pallas_search_candidates_hdr_batch`` + the
    cross-row fold, one program: (R, width) nonces under R dynamic
    headers → ``[found, first_global_off]``. Compiled once per (width,
    cand_bits, sched) — nothing job-specific is baked.

    Rows run SEQUENTIALLY inside the program (``lax.scan``), mirroring
    the Pallas kernel's grid-over-rows: on the CPU engine a flat
    (R·width)-lane program blows the cache and costs ~50% more per hash
    (measured: 3.15 → 4.86 µs at 8×256), while per-row working sets
    stay cache-sized and the dispatch count still drops ~B×.

    ``sched=True`` swaps the per-row hash for the shared-schedule
    truncated form (:func:`_jnp_candidate_ok_sched`): identical fold,
    identical booleans, measured ~34× per-hash on this CPU at 8×256
    (PERF.md §Round 14). ``False`` is the bit-for-bit A/B baseline —
    the exact pre-ISSUE-16 program."""
    col = jnp.arange(width, dtype=jnp.uint32)

    def row(carry, x):
        mid, tw, base, valid, goff = x
        if sched:
            ok = _jnp_candidate_ok_sched(mid, tw, base + col, cap, cand_bits)
        else:
            digests = ops.header_digest_dyn(mid, tw, base + col)
            ok = _jnp_candidate_ok(digests, cap, cand_bits)
        ok = ok & (col < valid)
        g = jnp.where(ok, goff + col, _UMAX)
        found, first = carry
        return (found | ok.any(), jnp.minimum(first, jnp.min(g))), None

    (found, first), _ = jax.lax.scan(
        row, (jnp.bool_(False), jnp.uint32(_UMAX)),
        (mids, tails, bases, valids, goffs),
    )
    return jnp.stack([found.astype(jnp.uint32), first])


@partial(jax.jit, static_argnums=(6, 7, 8))
def _pallas_batched_candidate_sweep(
    mids, tails, bases, valids, goffs, cap, width: int, tiles_per_step: int,
    sched: bool = False,
):
    """Pallas engine: the batched dynamic-header kernel (one launch
    grids over roll rows) + the same cross-row fold. ``sched=True``
    selects the shared-schedule kernel variant (per-row scalar prefix
    hoisted out of the tile loop via ``sym.prepare_hdr``)."""
    from tpuminter.kernels import pallas_search_candidates_hdr_batch

    founds, firsts = pallas_search_candidates_hdr_batch(
        mids, tails, bases, valids, width, tiles_per_step, cap, sched=sched
    )
    ok = founds != 0
    g = jnp.where(ok, goffs + firsts, _UMAX)
    return jnp.stack([ok.any().astype(jnp.uint32), jnp.min(g)])


@partial(jax.jit, static_argnums=(4, 5))
def _jnp_segment_candidate_sweep(mid, tail, base, cap, width: int, cand_bits: int):
    """Singleton (per-segment baseline) jnp candidate sweep: one row,
    no valid masking — the ``CandidateSearch`` oversweep contract covers
    hits past the logical end."""
    nonces = base + jnp.arange(width, dtype=jnp.uint32)
    digests = ops.header_digest_dyn(mid, tail, nonces)
    ok = _jnp_candidate_ok(digests, cap, cand_bits)
    off = jnp.where(ok, jnp.arange(width, dtype=jnp.uint32), _UMAX)
    return jnp.stack([ok.any().astype(jnp.uint32), jnp.min(off)])


# ---------------------------------------------------------------------------
# width autotune: one-shot cached startup probe
# ---------------------------------------------------------------------------

#: (backend, candidates, cand_bits, sched_share, rows) -> winning width.
#: Process-lifetime cache: the probe costs one compile + a few dispatches
#: per candidate width, so it runs at most once per configuration.
_autotune_cache: Dict[Tuple, int] = {}


def autotune_width(
    candidates: Tuple[int, ...] = (128, 256, 512, 1024),
    *,
    cand_bits: int = 32,
    sched_share: bool = True,
    rows: int = 8,
    reps: int = 3,
) -> int:
    """One-shot startup probe: time :func:`_jnp_batched_candidate_sweep`
    over dummy data at each candidate ``width`` and return the one with
    the best per-hash rate. Cached per (backend, candidates, cand_bits,
    sched_share, rows) for the life of the process — callers pay the
    probe once, then every ``width="auto"`` miner reads the dict.

    The probe is deliberately tiny (min-of-``reps`` after one warm
    call): it ranks widths against each other on THIS backend rather
    than measuring absolute throughput, so a handful of dispatches is
    enough to separate cache-sized from cache-blowing row widths. The
    explicit ``width=`` knob on :func:`mine_rolled_fast` remains the
    A/B override — autotune never forces a choice on callers that pin
    one."""
    key = (jax.default_backend(), tuple(candidates), cand_bits,
           bool(sched_share), rows)
    hit = _autotune_cache.get(key)
    if hit is not None:
        return hit

    rng = np.random.RandomState(0)
    cap = jnp.uint32(0)
    best_width, best_rate = candidates[0], -1.0
    for width in candidates:
        mids = jnp.asarray(rng.randint(0, 1 << 32, (rows, 8), dtype=np.uint32))
        tails = jnp.asarray(rng.randint(0, 1 << 32, (rows, 3), dtype=np.uint32))
        bases = jnp.asarray(rng.randint(0, 1 << 20, rows, dtype=np.uint32))
        valids = jnp.asarray(np.full(rows, width, np.uint32))
        goffs = jnp.asarray((np.arange(rows, dtype=np.uint64) * width)
                            .astype(np.uint32))
        args = (mids, tails, bases, valids, goffs, cap, width, cand_bits,
                sched_share)
        _jnp_batched_candidate_sweep(*args).block_until_ready()  # compile
        dt = min(
            timed_call(_jnp_batched_candidate_sweep, args)
            for _ in range(max(1, reps))
        )
        rate = rows * width / dt
        if rate > best_rate:
            best_width, best_rate = width, rate
    _autotune_cache[key] = best_width
    return best_width


# ---------------------------------------------------------------------------
# fast path: candidate pipeline over global indices
# ---------------------------------------------------------------------------

def _fast_result(req: Request, found, nonce, hash_value, searched, candidates):
    if found:
        return Result(
            req.job_id, req.mode, nonce, hash_value, found=True,
            searched=searched, chunk_id=req.chunk_id,
        )
    best = min(((h, g) for g, h in candidates), default=None)
    hash_value, nonce = best if best else (MIN_UNTRACKED, req.lower)
    return Result(
        req.job_id, req.mode, nonce, hash_value, found=False,
        searched=searched, chunk_id=req.chunk_id,
    )


def mine_rolled_fast(
    req: Request,
    *,
    slab: int = 1 << 27,
    depth: int = 2,
    roll_batch: int = 8,
    engine: str = "auto",
    tiles_per_step: int = 8,
    cand_bits: int = 32,
    sched_share: bool = True,
    width: Optional[Union[int, str]] = None,
    counters: Optional[Dict[str, int]] = None,
    progress: Optional[ProgressFn] = None,
) -> Iterator[Optional[Result]]:
    """The production >2^32 search, batched: candidate sweeps over the
    whole rolled range through ONE ``CandidateSearch``, each dispatch
    covering ``roll_batch`` roll rows (one batched roll call + one
    batched sweep call per window — no header bytes ever cross the host
    boundary, BASELINE.json:9-10). ``roll_batch=1`` is the A/B
    baseline: the pre-batching per-segment loop, one ``CandidateSearch``
    and one scalar roll per extranonce segment.

    ``sched_share`` (ISSUE 16) turns on the AsicBoost-grade shared-
    schedule layer: the sweep hashes through the truncated unrolled
    second compression (:func:`_jnp_candidate_ok_sched`, ~34× per hash
    measured on CPU) and the batched roll dedupes identical extranonce
    rows before dispatch (:func:`tpuminter.ops.merkle.roll_batch_deduped`).
    ``sched_share=False`` is the bit-for-bit A/B baseline — the exact
    pre-ISSUE-16 programs (house rule since PR 7).

    ``width`` overrides the sweep row width: ``None`` keeps the legacy
    cap-derived ``tile_width(nonce_bits, slab)``; ``"auto"`` caps it at
    the :func:`autotune_width` probe winner; an int caps it explicitly
    (all still clamped by ``slab`` and the nonce space).

    ``counters`` (optional dict) accumulates ``rolls``/``sweeps`` —
    device dispatch evidence for bench.py's rolled A/B fields.
    ``progress`` (:data:`ProgressFn`) receives the settled global-index
    high-water after each resolved window — the roll-budget beacon feed.
    """
    assert req.rolled and req.header is not None and req.target is not None
    engine = _resolve_engine(engine)
    verify = rolled_verifier(req)
    hw1_cap = jnp.uint32(int(ops.target_to_words(req.target)[1]))
    from tpuminter.ops import merkle

    if roll_batch <= 1:
        yield from _mine_rolled_fast_segmented(
            req, verify, hw1_cap, slab=slab, depth=depth, engine=engine,
            tiles_per_step=tiles_per_step, cand_bits=cand_bits,
            counters=counters, progress=progress,
        )
        return

    cap = slab
    if width == "auto":
        cap = min(slab, autotune_width(
            cand_bits=cand_bits, sched_share=sched_share, rows=roll_batch))
    elif width is not None:
        cap = min(slab, int(width))
    width = tile_width(req.nonce_bits, cap)
    rows = roll_batch + 2
    window = roll_batch * width
    if window >= 1 << 32:
        raise ValueError("roll_batch × width must stay below 2^32")
    hard_end = (1 << span_bits(req)) - 1
    roll = merkle.make_extranonce_roll_batch(
        req.header, req.coinbase_prefix, req.coinbase_suffix,
        req.extranonce_size, req.branch,
    )

    def sweep(start: int, n: int):
        plan = lean_plan(
            plan_tiles(start, n, req.nonce_bits, width, rows, hard_end),
            roll_batch,
        )
        _count(counters, "rolls")
        _count(counters, "sweeps")
        if sched_share:
            mids, tails = merkle.roll_batch_deduped(
                roll, plan.en_hi, plan.en_lo)
        else:
            mids, tails = roll(
                jnp.asarray(plan.en_hi), jnp.asarray(plan.en_lo))
        args = (
            mids, tails, jnp.asarray(plan.bases), jnp.asarray(plan.valids),
            jnp.asarray(plan.goffs), hw1_cap,
        )
        if engine == "pallas":
            return _pallas_batched_candidate_sweep(
                *args, width, tiles_per_step, sched_share
            )
        return _jnp_batched_candidate_sweep(*args, width, cand_bits, sched_share)

    search = CandidateSearch(
        sweep, resolve_handle, verify, req.lower, req.upper,
        slab=window, depth=depth, domain=1 << span_bits(req),
    )
    for _ in search.events():
        report_search_progress(search, req.lower, progress)
        yield None  # heartbeat / Cancel window per resolved window
    out = search.outcome
    yield _fast_result(
        req, out.found, out.nonce, out.hash_value, out.searched,
        out.candidates,
    )


def _mine_rolled_fast_segmented(
    req, verify, hw1_cap, *, slab, depth, engine, tiles_per_step,
    cand_bits, counters, progress=None,
) -> Iterator[Optional[Result]]:
    """The pre-batching baseline (``roll_batch=1``): one scalar roll +
    one drained-to-completion ``CandidateSearch`` per extranonce
    segment. Kept bit-for-bit reachable so the batched path always has
    an in-tree A/B."""
    from tpuminter.ops import merkle

    roll = merkle.make_extranonce_roll(
        req.header, req.coinbase_prefix, req.coinbase_suffix,
        req.extranonce_size, req.branch,
    )
    # the pallas baseline keeps the full production slab (single-compile
    # policy); the jnp engine sizes dispatches like the batched rows so
    # the A/B isolates orchestration, not per-dispatch shape
    width = tile_width(req.nonce_bits, slab)
    seg_slab = slab if engine == "pallas" else width
    searched = 0
    candidates = []  # (global index, hash)
    best_hg = None  # (hash, global index) running min over candidates
    for en, base_g, n_lo, n_hi in chain.rolled_segments(
        req.lower, req.upper, req.nonce_bits
    ):
        mid, tailw = roll(jnp.uint32(en >> 32), jnp.uint32(en & 0xFFFFFFFF))
        _count(counters, "rolls")

        def sweep(base: int, n: int, _mid=mid, _tailw=tailw):
            _count(counters, "sweeps")
            if engine == "pallas":
                from tpuminter.kernels import pallas_search_candidates_hdr

                found, off = pallas_search_candidates_hdr(
                    _mid, _tailw, jnp.uint32(base), seg_slab,
                    tiles_per_step, hw1_cap,
                )
                return pack_handle(found, off)
            return _jnp_segment_candidate_sweep(
                _mid, _tailw, jnp.uint32(base), hw1_cap, seg_slab, cand_bits
            )

        def seg_verify(nonce: int, _base_g=base_g) -> Tuple[bool, int]:
            return verify(_base_g | nonce)

        search = CandidateSearch(
            sweep, resolve_handle, seg_verify, n_lo, n_hi,
            slab=seg_slab, depth=depth,
        )
        for _ in search.events():
            if progress is not None and search.outcome is None:
                local = search.settled_high_water()
                if local is not None:
                    hw = base_g | local
                elif base_g > req.lower:
                    hw = base_g - 1  # prior segments fully settled
                else:
                    hw = None
                if hw is not None:
                    seg_best = search.best_candidate()
                    pool = [b for b in (best_hg, seg_best and (
                        seg_best[0], base_g | seg_best[1])) if b]
                    bh, bg = min(pool) if pool else (MIN_UNTRACKED, req.lower)
                    progress(hw, bg, bh)
            yield None
        out = search.outcome
        searched += out.searched
        candidates += [(base_g | n, h) for n, h in out.candidates]
        best_hg = min(((h, g) for g, h in candidates), default=None)
        if out.found:
            yield _fast_result(
                req, True, base_g | out.nonce, out.hash_value, searched,
                candidates,
            )
            return
    yield _fast_result(req, False, None, None, searched, candidates)


# ---------------------------------------------------------------------------
# tracking path: exact exhausted-range minima (CpuMiner-compatible)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnums=(6,))
def _tracking_step(mids, tails, bases, valids, goffs, target_words, width: int):
    """Exact batched rolled step: full digests for every (row, nonce),
    in-program first-winner AND lexicographic-min folds over the masked
    grid. Returns 19 packed u32: ``[found, first_goff, min_goff,
    first_digest×8, min_digest×8]`` — one device array, one pull (the
    ``search.pack_handle`` rule). Ties fold to the lowest global index:
    rows scan in global order (strict-less carry updates keep the
    earlier row) and per-row argmins tie low. Rows run sequentially
    (``lax.scan``) for the same cache reason as the candidate sweep."""
    col = jnp.arange(width, dtype=jnp.uint32)

    def row(carry, x):
        mid, tw, base, valid, goff = x
        digests = ops.header_digest_dyn(mid, tw, base + col)  # (W, 8)
        hw = ops.hash_words_be(digests)
        valid_m = col < valid
        ok = ops.lex_le(hw, target_words) & valid_m
        g = jnp.where(ok, goff + col, _UMAX)
        fidx = jnp.argmin(g)
        masked_hw = jnp.where(valid_m[:, None], hw, _UMAX)
        midx = ops.lex_argmin(masked_hw)
        found, first, first_d, min_hw, min_d, min_g = carry
        take = g[fidx] < first
        first = jnp.where(take, g[fidx], first)
        first_d = jnp.where(take, digests[fidx], first_d)
        row_hw = masked_hw[midx]
        lt = ops.lex_le(row_hw, min_hw) & ~ops.lex_le(min_hw, row_hw)
        min_hw = jnp.where(lt, row_hw, min_hw)
        min_d = jnp.where(lt, digests[midx], min_d)
        min_g = jnp.where(lt, goff + col[midx], min_g)
        return (found | ok.any(), first, first_d, min_hw, min_d, min_g), None

    init = (
        jnp.bool_(False), jnp.uint32(_UMAX), jnp.zeros(8, jnp.uint32),
        jnp.full(8, _UMAX, jnp.uint32), jnp.zeros(8, jnp.uint32),
        jnp.uint32(_UMAX),
    )
    (found, first, first_d, _, min_d, min_g), _ = jax.lax.scan(
        row, init, (mids, tails, bases, valids, goffs)
    )
    return jnp.concatenate([
        jnp.stack([found.astype(jnp.uint32), first, min_g]),
        first_d, min_d,
    ])


def mine_rolled_tracking(
    req: Request,
    *,
    width_cap: int = 1 << 14,
    depth: int = 2,
    roll_batch: int = 8,
    sched_share: bool = True,
    counters: Optional[Dict[str, int]] = None,
    progress: Optional[ProgressFn] = None,
) -> Iterator[Optional[Result]]:
    """Exact rolled search (CpuMiner-compatible first winner AND
    exhausted minimum), batched: windows of ``roll_batch`` roll rows
    with full digests + on-device min folds, pipelined ``depth`` deep
    ACROSS segment boundaries (``search.pipeline_spans`` no longer dies
    at each one). jnp engine — compiles on every backend, one program
    for every job and extranonce (the dynamic-header property); the
    toy-easy-target correctness path plus JaxMiner's production rolled
    path. Batched rows ≡ the per-segment loop bit-for-bit
    (tests/test_extranonce.py pins it).

    ``sched_share`` here buys ONLY the roll-side dedup
    (:func:`tpuminter.ops.merkle.roll_batch_deduped`): the tracking
    step itself keeps the scanned full-digest compress. Sharing the
    unrolled schedule inside the full-digest + lexicographic-min fold
    was measured and REJECTED — every fold structure tried either lost
    outright or paid a 15-42 s compile per width (PERF.md §Round 14);
    the truncated e60/e61 trick doesn't apply when all 8 digest words
    feed the min fold. ``False`` restores the exact pre-ISSUE-16 roll
    dispatch for A/B.
    """
    assert req.rolled and req.target is not None
    from tpuminter.ops import merkle

    width = tile_width(req.nonce_bits, width_cap)
    rows = max(roll_batch, 1) + 2
    window = max(roll_batch, 1) * width
    hard_end = (1 << span_bits(req)) - 1
    roll = merkle.make_extranonce_roll_batch(
        req.header, req.coinbase_prefix, req.coinbase_suffix,
        req.extranonce_size, req.branch,
    )
    target_words = jnp.asarray(ops.target_to_words(req.target))

    def dispatch(start: int):
        # exact path: clamp the plan at the job's upper — oversweep
        # lanes must not leak into the min fold
        n = min(window, req.upper - start + 1)
        plan = lean_plan(
            plan_tiles(start, n, req.nonce_bits, width, rows, hard_end),
            max(roll_batch, 1),
        )
        _count(counters, "rolls")
        _count(counters, "sweeps")
        if sched_share:
            mids, tails = merkle.roll_batch_deduped(
                roll, plan.en_hi, plan.en_lo)
        else:
            mids, tails = roll(
                jnp.asarray(plan.en_hi), jnp.asarray(plan.en_lo))
        return _tracking_step(
            mids, tails, jnp.asarray(plan.bases), jnp.asarray(plan.valids),
            jnp.asarray(plan.goffs), target_words, width,
        )

    starts = range(req.lower, req.upper + 1, window)
    best: Optional[Tuple[int, int]] = None  # (hash, global index)
    for start, handle in pipeline_spans(starts, dispatch, depth=depth):
        row = np.asarray(handle)
        if int(row[0]):
            g = start + int(row[1])
            h = ops.digest_to_int(row[3:11])
            yield Result(
                req.job_id, req.mode, g, h, found=True,
                searched=g - req.lower + 1, chunk_id=req.chunk_id,
            )
            return
        cand = (ops.digest_to_int(row[11:19]), start + int(row[2]))
        if best is None or cand < best:
            best = cand
        if progress is not None:
            # windows resolve in dispatch order, so the settled prefix
            # ends exactly at this (clamped) window's last index
            progress(min(start + window, req.upper + 1) - 1, best[1], best[0])
        yield None
    yield Result(
        req.job_id, req.mode, best[1], best[0],
        found=best[0] <= req.target,
        searched=req.upper - req.lower + 1, chunk_id=req.chunk_id,
    )
