"""Pallas splitmix64: the hashcore objective on TPU lanes.

The kernel mirror of :mod:`tpuminter.ops.splitmix` — same u32-pair
word arithmetic (the pair primitives are imported, not re-derived, so
the two engines cannot drift), laid out as ``(rows, 128)`` u32 tiles
with a grid over row blocks, exactly like ``pallas_sha256_batch``.

Unlike the ~6k-op SHA bodies, splitmix64 is ~40 vector ops, so
``interpret=True`` on the CPU backend is *practical* here: tier-1 pins
this kernel bit-for-bit against the scalar objective at small shapes
(tests/test_hashcore_dev.py), and tests/test_kernels_tpu.py carries the
pre-staged on-silicon section for compiled-Mosaic shapes when the
tunnel returns.

The "pallas" sweep engine (``ops.splitmix.sweep_program``) uses this
kernel to materialize the window's value block, then runs the same
in-program jnp fold scan over it — the fold logic has exactly one
implementation.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpuminter.ops.splitmix import splitmix64_pair

__all__ = ["pallas_splitmix_batch", "LANES"]

LANES = 128


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _plan(n: int) -> Tuple[int, int, int]:
    """rows, block_rows, grid for an ``(n,)`` lane vector: the largest
    block height ≤ 8 that divides the row count, so every dispatch
    shape the sweep produces (width any multiple of 128) plans without
    padding."""
    if n % LANES:
        raise ValueError(f"batch {n} must be a multiple of {LANES}")
    rows = n // LANES
    block_rows = next(b for b in (8, 4, 2, 1) if rows % b == 0)
    return rows, block_rows, rows // block_rows


def _splitmix_kernel(seed_ref, ih_ref, il_ref, vh_ref, vl_ref):
    # seed words ride SMEM (scalar memory) — broadcast into the pair
    # math against the VMEM index tiles
    vh, vl = splitmix64_pair(
        seed_ref[0], seed_ref[1], ih_ref[...], il_ref[...]
    )
    vh_ref[...] = vh
    vl_ref[...] = vl


@jax.jit
def pallas_splitmix_batch(
    seed_hi: jnp.ndarray,
    seed_lo: jnp.ndarray,
    idx_hi: jnp.ndarray,
    idx_lo: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Objective values for a global-index batch: seed words (u32
    scalars) + ``(N,) u32 × 2`` index words → ``(N,) u32 × 2`` value
    words. Bit-identical to ``ops.splitmix.splitmix64_pair`` (and so to
    the scalar ``workloads.hashcore.objective``)."""
    n = idx_lo.shape[0]
    rows, block_rows, grid = _plan(n)
    seed = jnp.stack([seed_hi, seed_lo]).astype(jnp.uint32)
    vh, vl = pl.pallas_call(
        _splitmix_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((rows, LANES), jnp.uint32),
            jax.ShapeDtypeStruct((rows, LANES), jnp.uint32),
        ),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (block_rows, LANES), lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (block_rows, LANES), lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=(
            pl.BlockSpec(
                (block_rows, LANES), lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (block_rows, LANES), lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            ),
        ),
        interpret=_interpret(),
    )(seed, idx_hi.reshape(rows, LANES), idx_lo.reshape(rows, LANES))
    return vh.reshape(n), vl.reshape(n)
