"""Pallas double-SHA-256: the BASELINE.json:5 hot-loop kernels.

All generated from a :class:`~tpuminter.ops.sha256.NonceTemplate`
via the partial-evaluating symbolic compress (``ops.symbolic``), so every
message constant — midstate, padding, constant schedule words, constant
early rounds, ``K+W`` folds — is baked into the instruction stream at
trace time and the VPU only ever touches nonce-dependent values:

- :func:`pallas_sha256_batch` — digests for an explicit nonce vector
  (the correctness surface; bit-identical to ``ops.sha256_batch``).
- :func:`pallas_search_candidates` — the PRODUCTION search: nonces are
  generated *in-register* from a scalar base (zero HBM input traffic)
  and early-rejected on the hash's top 64 bits only, two rounds short
  of a full second compression (``sym.compress_sym_e60_e61``); rare
  survivors are verified host-side (``tpuminter.search``). This is the
  ≥1 GH/s/chip path.
- :func:`pallas_search_target` — full in-kernel 256-bit target compare
  plus the running lexicographic-min fold (exact exhausted-range
  minimum); slower, used when exact-min semantics are required.

Layout: work arrays are ``(32, 128)`` u32 tiles (see ``_TILE``) with a
``lax.while_loop`` striding tiles and ``tiles_per_step`` independent
dependency chains in flight. Rotations lower to shift/or pairs
(pallas_guide: TPUs have no rotate ISA).

The kernels set ``interpret=True`` on the CPU backend, but the unrolled
~6k-op bodies make interpreter-mode execution impractically slow beyond
tiny shapes (measured round 4: one minimum-size 1024-nonce
``pallas_sha256_batch`` did not finish in 400 s on this host); CPU CI
pins the *generator* (``ops.symbolic``) against the jnp path instead,
and tests/test_kernels_tpu.py exercises the compiled kernels on a real
chip (see that module's rationale).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpuminter.ops import sha256 as ops
from tpuminter.ops import symbolic as sym

__all__ = [
    "pallas_sha256_batch",
    "pallas_search_target",
    "pallas_search_candidates",
    "pallas_search_candidates_hdr",
    "pallas_search_candidates_hdr_batch",
]

LANES = 128


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _as_rows(n: int, block_rows: int) -> Tuple[int, int]:
    if n % (block_rows * LANES) != 0:
        raise ValueError(
            f"batch {n} must be a multiple of block_rows*128 = {block_rows * LANES}"
        )
    rows = n // LANES
    return rows, rows // block_rows


# ---------------------------------------------------------------------------
# digests kernel (correctness surface)
# ---------------------------------------------------------------------------

def _digest_kernel(template, hi_ref, lo_ref, out_ref):
    digest = sym.double_sha256_sym(template, hi_ref[...], lo_ref[...])
    for i in range(8):
        word = digest[i]
        if isinstance(word, int):  # nonce never reached this word
            word = jnp.full(hi_ref.shape, word, jnp.uint32)
        out_ref[i] = word


@partial(jax.jit, static_argnums=(0, 3))
def pallas_sha256_batch(
    template: ops.NonceTemplate,
    nonce_hi: jnp.ndarray,
    nonce_lo: jnp.ndarray,
    block_rows: int = 8,
) -> jnp.ndarray:
    """Digest words for a nonce batch: ``(N,) u32 × 2 → (N, 8) u32``.
    Drop-in equivalent of ``ops.sha256_batch`` (tests pin them equal)."""
    n = nonce_lo.shape[0]
    rows, grid = _as_rows(n, block_rows)
    out = pl.pallas_call(
        partial(_digest_kernel, template),
        out_shape=jax.ShapeDtypeStruct((8, rows, LANES), jnp.uint32),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(
                (block_rows, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
            )
        ]
        * 2,
        out_specs=pl.BlockSpec(
            (8, block_rows, LANES), lambda i: (0, i, 0), memory_space=pltpu.VMEM
        ),
        interpret=_interpret(),
    )(nonce_hi.reshape(rows, LANES), nonce_lo.reshape(rows, LANES))
    return out.transpose(1, 2, 0).reshape(n, 8)


# ---------------------------------------------------------------------------
# fused search kernel (performance surface)
# ---------------------------------------------------------------------------

#: summary row layout (one 128-lane row per call)
_FOUND, _FIRST_IDX, _MIN_HW0, _MIN_IDX = 0, 1, 2, 10

_U32MAX = np.uint32(0xFFFFFFFF)
_I32MAX = np.int32(0x7FFFFFFF)
#: work-array shape per "tile": 32 sublane rows × 128 lanes = 4096 nonces.
#: Taller-than-vreg tiles (4 native (8,128) vregs per op) measurably beat
#: 8-row tiles on v5e (~+8% GH/s): each traced op covers 4× the work, so
#: the unrolled SHA body has 4× fewer instructions to fetch/schedule,
#: while `tiles_per_step` still provides independent dependency chains.
_TILE = (32, LANES)


def _bias_const(t: int) -> np.int32:
    """u32 constant → the sign-biased int32 domain (order-preserving)."""
    b = int(t) ^ 0x80000000
    return np.int32(b - (1 << 32) if b >= (1 << 31) else b)


def _hash_words_biased(digest):
    """Digest words → hash-value words (msb-first), sign-biased int32.

    Mosaic has no unsigned reductions/compares; u32 order == i32 order
    after XOR 0x80000000, so all folding happens in the biased domain.
    """
    out = []
    for j in range(8):
        word = sym.xor(
            sym.shl(sym.and_(digest[7 - j], 0x000000FF), 24),
            sym.shl(sym.and_(digest[7 - j], 0x0000FF00), 8),
            sym.shr(sym.and_(digest[7 - j], 0x00FF0000), 8),
            sym.shr(sym.and_(digest[7 - j], 0xFF000000), 24),
        )
        out.append(
            jax.lax.bitcast_convert_type(sym.xor(word, 0x80000000), jnp.int32)
        )
    return out


def _search_kernel(template, target_words, n_tiles, tiles_per_step,
                   track_min, n_valid, base_ref, out_ref):
    """Whole-chunk search in ONE kernel invocation.

    A ``lax.while_loop`` sweeps ``n_tiles`` ``_TILE``-shaped tiles — 4096
    nonces each, ``tiles_per_step`` of them interleaved per iteration so the
    VPU has independent SHA dependency chains in flight (ILP) — with
    EARLY EXIT as soon as any step hits the target. A single call covers
    an arbitrarily large range with zero host syncs mid-sweep (the
    tunnel-latency killer) while the live register set stays a few tiles
    wide. All folds are elementwise per lane across tiles; the
    cross-lane reduction happens once, after the loop.
    """
    tgt = [_bias_const(t) for t in target_words]
    offs = (
        jax.lax.broadcasted_iota(jnp.int32, _TILE, 0) * np.int32(LANES)
        + jax.lax.broadcasted_iota(jnp.int32, _TILE, 1)
    )
    base = base_ref[0]
    limit = np.int32(n_valid)
    tile_sz = _TILE[0] * LANES

    def cond(carry):
        i, found, _, _ = carry
        return (i < n_tiles) & (found == 0)

    def body(carry):
        i, _, first_offs, (min_words, min_offs) = carry
        any_ok = jnp.zeros(_TILE, jnp.bool_)
        for t in range(tiles_per_step):
            offs_i = offs + (i + t) * np.int32(tile_sz)
            nonces = base + jax.lax.bitcast_convert_type(offs_i, jnp.uint32)
            # hi nonce half is constant 0 → its bytes fold out
            digest = sym.double_sha256_sym(template, 0, nonces)
            hwb = _hash_words_biased(digest)
            # target compare, lexicographic over baked constants
            lt = jnp.zeros(_TILE, jnp.bool_)
            eq = jnp.ones(_TILE, jnp.bool_)
            for j in range(8):
                lt = lt | (eq & (hwb[j] < tgt[j]))
                eq = eq & (hwb[j] == tgt[j])
            ok = (lt | eq) & (offs_i < limit)  # pad lanes can't win
            any_ok = any_ok | ok
            first_offs = jnp.where(
                ok & (offs_i < first_offs), offs_i, first_offs
            )
            if track_min:
                # elementwise lexicographic min fold vs carried best
                c_lt = jnp.zeros(_TILE, jnp.bool_)
                c_eq = jnp.ones(_TILE, jnp.bool_)
                for j in range(8):
                    c_lt = c_lt | (c_eq & (hwb[j] < min_words[j]))
                    c_eq = c_eq & (hwb[j] == min_words[j])
                c_lt = c_lt & (offs_i < limit)
                min_words = tuple(
                    jnp.where(c_lt, hwb[j], min_words[j]) for j in range(8)
                )
                min_offs = jnp.where(c_lt, offs_i, min_offs)
        # one cross-lane reduction per step, not per tile
        found = jnp.max(any_ok.astype(jnp.int32))
        return (
            i + tiles_per_step, found, first_offs, (min_words, min_offs)
        )

    init = (
        jnp.int32(0),
        jnp.int32(0),
        jnp.full(_TILE, _I32MAX, jnp.int32),
        (tuple(jnp.full(_TILE, _I32MAX, jnp.int32) for _ in range(8)),
         jnp.full(_TILE, _I32MAX, jnp.int32)),
    )
    _, found, first_offs, (min_words, min_offs) = jax.lax.while_loop(
        cond, body, init
    )
    first = jnp.min(first_offs)
    # cross-lane lexicographic argmin: 8 min+mask passes, then min-offset
    # tie-break (= lowest nonce; earlier tiles already won elementwise)
    mask = jnp.ones(_TILE, jnp.bool_)
    final_words = []
    for j in range(8):
        col = jnp.where(mask, min_words[j], _I32MAX)
        m = jnp.min(col)
        mask = mask & (col == m)
        final_words.append(m)
    min_idx = jnp.min(jnp.where(mask, min_offs, _I32MAX))
    # summary row via lane-index select (no scalar scatters); words are
    # un-biased back to u32 on the way out
    lane = jax.lax.broadcasted_iota(jnp.int32, _TILE, 1)
    row = jnp.zeros(_TILE, jnp.int32)
    for idx, val in (
        [(_FOUND, found), (_FIRST_IDX, first), (_MIN_IDX, min_idx)]
        + [(_MIN_HW0 + j, final_words[j] ^ np.int32(-0x80000000))
           for j in range(8)]
    ):
        row = jnp.where(lane == np.int32(idx), val, row)
    out_ref[...] = jax.lax.bitcast_convert_type(row, jnp.uint32)


@partial(jax.jit, static_argnums=(0, 1, 3, 4, 5))
def pallas_search_target(
    template: ops.NonceTemplate,
    target_words: Tuple[int, ...],
    base: jnp.ndarray,
    n: int,
    tiles_per_step: int = 8,
    track_min: bool = True,
):
    """Fused search over up to ``n`` consecutive nonces from scalar
    ``base`` (``n`` is rounded UP internally to a whole number of loop
    steps; lanes past the true ``n`` are masked out of every fold, so any
    ``n >= 1`` is valid).

    Returns ``(found, first_nonce_off, min_hash_words (8,), min_off)``;
    offsets are relative to ``base``. ``target_words`` are msb-first u32
    ints (``ops.target_to_words``), static so the compare folds into the
    kernel. One device call, one host sync, in-kernel early exit: when a
    hit occurs the loop stops within ``tiles_per_step × 4096`` nonces.
    ``first_nonce_off`` is exact (the lowest winning offset).
    """
    if not 1 <= n <= 1 << 30:
        raise ValueError("n must be in [1, 2^30] (int32 offset domain)")
    chunk = _TILE[0] * LANES * tiles_per_step
    n_tiles = -(-n // chunk) * tiles_per_step  # round up to whole steps
    summary = pl.pallas_call(
        partial(_search_kernel, template,
                tuple(int(t) for t in target_words), n_tiles,
                tiles_per_step, track_min, n),
        out_shape=jax.ShapeDtypeStruct(_TILE, jnp.uint32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(base.reshape(1).astype(jnp.uint32))
    row = summary[0]
    found = row[_FOUND]
    first_off = row[_FIRST_IDX]
    min_words = row[_MIN_HW0 : _MIN_HW0 + 8]
    min_off = row[_MIN_IDX]
    return found, first_off, min_words, min_off


# ---------------------------------------------------------------------------
# candidate kernel: the production TARGET hot path
# ---------------------------------------------------------------------------

def _cand_kernel(template, n_tiles, tiles_per_step, n_valid, mask_tail,
                 base_ref, cap_ref, out_ref):
    """Early-reject sweep: find the first offset whose double-SHA hash
    value's top 64 bits clear the bar — word 0 (byteswapped digest word
    7) must be ZERO (necessary for every real target) and word 1
    (byteswapped digest word 6) must be ≤ a *dynamic* cap carried in
    SMEM (the target's second word — dynamic so one compiled kernel
    serves every difficulty). Per nonce this computes only ``(e60,
    e61)`` of the second compression (``sym.double_sha256_e60_e61``),
    one equality against the baked :data:`sym.CAND_E60`, and one
    biased compare; no final adds, no 256-bit compare, no min fold —
    full evaluation happens host-side for the rare survivors. With the
    cap at the target's real word 1 the false-survivor rate is ~2^-64,
    so sweeps essentially never early-exit without a true win.
    Tail-lane masking is emitted only when ``n`` is not a whole number
    of steps (``mask_tail``), keeping the hot loop free of it for
    power-of-two slabs."""
    cand_c = np.uint32(sym.CAND_E60)
    offs = (
        jax.lax.broadcasted_iota(jnp.int32, _TILE, 0) * np.int32(LANES)
        + jax.lax.broadcasted_iota(jnp.int32, _TILE, 1)
    )
    base = base_ref[0]
    # hash word 1 cap: pre-biased into the signed-compare domain on the
    # host (Mosaic has no scalar bitcast)
    cap1 = cap_ref[0]
    limit = np.int32(n_valid)
    tile_sz = _TILE[0] * LANES

    def cond(carry):
        i, found, _ = carry
        return (i < n_tiles) & (found == 0)

    def body(carry):
        i, _, first_offs = carry
        any_ok = jnp.zeros(_TILE, jnp.bool_)
        for t in range(tiles_per_step):
            offs_i = offs + (i + t) * np.int32(tile_sz)
            nonces = base + jax.lax.bitcast_convert_type(offs_i, jnp.uint32)
            e60, e61 = sym.double_sha256_e60_e61(template, 0, nonces)
            digest6 = sym.add(sym.DIGEST6_BIAS, e61)
            hw1 = sym.xor(
                sym.shl(sym.and_(digest6, 0x000000FF), 24),
                sym.shl(sym.and_(digest6, 0x0000FF00), 8),
                sym.shr(sym.and_(digest6, 0x00FF0000), 8),
                sym.shr(sym.and_(digest6, 0xFF000000), 24),
                0x80000000,
            )
            hw1b = jax.lax.bitcast_convert_type(hw1, jnp.int32)
            ok = (e60 == cand_c) & (hw1b <= cap1)
            if mask_tail:
                ok = ok & (offs_i < limit)
            any_ok = any_ok | ok
            first_offs = jnp.where(
                ok & (offs_i < first_offs), offs_i, first_offs
            )
        found = jnp.max(any_ok.astype(jnp.int32))
        return (i + tiles_per_step, found, first_offs)

    init = (jnp.int32(0), jnp.int32(0), jnp.full(_TILE, _I32MAX, jnp.int32))
    _, found, first_offs = jax.lax.while_loop(cond, body, init)
    first = jnp.min(first_offs)
    lane = jax.lax.broadcasted_iota(jnp.int32, _TILE, 1)
    row = jnp.where(lane == np.int32(_FOUND), found, jnp.zeros(_TILE, jnp.int32))
    row = jnp.where(lane == np.int32(_FIRST_IDX), first, row)
    out_ref[...] = jax.lax.bitcast_convert_type(row, jnp.uint32)


@partial(jax.jit, static_argnums=(0, 2, 3))
def pallas_search_candidates(
    template: ops.NonceTemplate,
    base: jnp.ndarray,
    n: int,
    tiles_per_step: int = 8,
    hw1_cap: jnp.ndarray | None = None,
):
    """Fast sweep of ``n`` consecutive nonces from scalar ``base`` for
    *candidates*: nonces whose double-SHA-256 hash value has top word
    zero AND second word ≤ ``hw1_cap`` (a dynamic u32 scalar — pass the
    target's word 1 so a candidate is a true win up to a ~2^-64
    tail; defaults to 0xFFFFFFFF, i.e. the pure top-word-zero test).
    Top word zero is a necessary condition for ``hash <= target`` at
    every real difficulty (the Bitcoin target's top word is 0 from
    difficulty 1 up), so the sweep can never miss a winner.

    Returns ``(found, first_off)``: ``found != 0`` iff a candidate lies
    in range, ``first_off`` its lowest offset from ``base``. The kernel
    early-exits within ``tiles_per_step × 4096`` nonces of a candidate;
    offsets past the first candidate are NOT searched (the caller owns
    host-side verification + remainder re-issue —
    ``tpuminter.search.CandidateSearch``). The hot loop carries no
    byteswap/256-bit-compare/min-fold baggage — full evaluation happens
    host-side for the rare survivors."""
    if not 1 <= n <= 1 << 30:
        raise ValueError("n must be in [1, 2^30] (int32 offset domain)")
    if hw1_cap is None:
        hw1_cap = jnp.uint32(0xFFFFFFFF)
    chunk = _TILE[0] * LANES * tiles_per_step
    n_tiles = -(-n // chunk) * tiles_per_step
    cap_biased = jax.lax.bitcast_convert_type(
        hw1_cap.astype(jnp.uint32) ^ jnp.uint32(0x80000000), jnp.int32
    )
    summary = pl.pallas_call(
        partial(_cand_kernel, template, n_tiles, tiles_per_step, n,
                n % chunk != 0),
        out_shape=jax.ShapeDtypeStruct(_TILE, jnp.uint32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] * 2,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(base.reshape(1).astype(jnp.uint32), cap_biased.reshape(1))
    row = summary[0]
    return row[_FOUND], row[_FIRST_IDX]


# ---------------------------------------------------------------------------
# dynamic-header candidate kernel (the extranonce-roll consumer)
# ---------------------------------------------------------------------------

def _cand_hdr_kernel(n_tiles, tiles_per_step, n_valid, mask_tail,
                     mid_ref, tw_ref, base_ref, cap_ref, out_ref):
    """Early-reject sweep over a header whose midstate and variable tail
    words arrive in SMEM at *runtime* instead of being baked at trace
    time: the consumer of the on-device extranonce roll
    (``ops.merkle.make_extranonce_roll`` → this kernel, zero host
    round-trips per roll, BASELINE.json:9-10) — and, as a bonus, a
    single compiled kernel that serves EVERY header-mining job (no
    ~20-40 s per-job XLA compile through the remote-TPU tunnel).

    Identical candidate test to ``_cand_kernel``; the only cost of
    dynamism is the partial-eval folds the symbolic compress can no
    longer do (the first tail compression's early rounds and its
    constant-word ``K+W`` folds), a few percent of the instruction
    stream."""
    mid = [mid_ref[i] for i in range(8)]
    tail = [tw_ref[0], tw_ref[1], tw_ref[2], 0] + list(ops.HEADER_TAIL_PAD)
    cand_c = np.uint32(sym.CAND_E60)
    offs = (
        jax.lax.broadcasted_iota(jnp.int32, _TILE, 0) * np.int32(LANES)
        + jax.lax.broadcasted_iota(jnp.int32, _TILE, 1)
    )
    base = base_ref[0]
    cap1 = cap_ref[0]
    limit = np.int32(n_valid)
    tile_sz = _TILE[0] * LANES

    def cond(carry):
        i, found, _ = carry
        return (i < n_tiles) & (found == 0)

    def body(carry):
        i, _, first_offs = carry
        any_ok = jnp.zeros(_TILE, jnp.bool_)
        for t in range(tiles_per_step):
            offs_i = offs + (i + t) * np.int32(tile_sz)
            nonces = base + jax.lax.bitcast_convert_type(offs_i, jnp.uint32)
            e60, e61 = sym.hash_sym_e60_e61(
                mid, [tail], ops.HEADER_NONCE_POSITIONS, 0, nonces
            )
            digest6 = sym.add(sym.DIGEST6_BIAS, e61)
            hw1 = sym.xor(
                sym.shl(sym.and_(digest6, 0x000000FF), 24),
                sym.shl(sym.and_(digest6, 0x0000FF00), 8),
                sym.shr(sym.and_(digest6, 0x00FF0000), 8),
                sym.shr(sym.and_(digest6, 0xFF000000), 24),
                0x80000000,
            )
            hw1b = jax.lax.bitcast_convert_type(hw1, jnp.int32)
            ok = (e60 == cand_c) & (hw1b <= cap1)
            if mask_tail:
                ok = ok & (offs_i < limit)
            any_ok = any_ok | ok
            first_offs = jnp.where(
                ok & (offs_i < first_offs), offs_i, first_offs
            )
        found = jnp.max(any_ok.astype(jnp.int32))
        return (i + tiles_per_step, found, first_offs)

    init = (jnp.int32(0), jnp.int32(0), jnp.full(_TILE, _I32MAX, jnp.int32))
    _, found, first_offs = jax.lax.while_loop(cond, body, init)
    first = jnp.min(first_offs)
    lane = jax.lax.broadcasted_iota(jnp.int32, _TILE, 1)
    row = jnp.where(lane == np.int32(_FOUND), found, jnp.zeros(_TILE, jnp.int32))
    row = jnp.where(lane == np.int32(_FIRST_IDX), first, row)
    out_ref[...] = jax.lax.bitcast_convert_type(row, jnp.uint32)


@partial(jax.jit, static_argnums=(3, 4))
def pallas_search_candidates_hdr(
    midstate8: jnp.ndarray,
    tailw3: jnp.ndarray,
    base: jnp.ndarray,
    n: int,
    tiles_per_step: int = 8,
    hw1_cap: jnp.ndarray | None = None,
):
    """Dynamic-header twin of :func:`pallas_search_candidates`: the
    header midstate (8 u32) and variable tail words (merkle word 7,
    time, bits) are runtime device values — pass the outputs of
    ``ops.merkle.make_extranonce_roll`` straight in; they never visit
    the host. Same return contract: ``(found, first_off)``."""
    if not 1 <= n <= 1 << 30:
        raise ValueError("n must be in [1, 2^30] (int32 offset domain)")
    if hw1_cap is None:
        hw1_cap = jnp.uint32(0xFFFFFFFF)
    chunk = _TILE[0] * LANES * tiles_per_step
    n_tiles = -(-n // chunk) * tiles_per_step
    cap_biased = jax.lax.bitcast_convert_type(
        hw1_cap.astype(jnp.uint32) ^ jnp.uint32(0x80000000), jnp.int32
    )
    summary = pl.pallas_call(
        partial(_cand_hdr_kernel, n_tiles, tiles_per_step, n,
                n % chunk != 0),
        out_shape=jax.ShapeDtypeStruct(_TILE, jnp.uint32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] * 4,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(
        midstate8.astype(jnp.uint32),
        tailw3.astype(jnp.uint32),
        base.reshape(1).astype(jnp.uint32),
        cap_biased.reshape(1),
    )
    row = summary[0]
    return row[_FOUND], row[_FIRST_IDX]


def _cand_hdr_batch_kernel(n_tiles, tiles_per_step, sched,
                           mid_ref, tw_ref, base_ref, lim_ref, cap_ref,
                           out_ref):
    """One grid step = one roll ROW of the batched sweep: identical
    candidate test to ``_cand_hdr_kernel``, but the row's midstate, tail
    words, nonce base AND valid count all arrive per-row at runtime
    (BlockSpec-indexed SMEM rows of the ``make_extranonce_roll_batch``
    output). The valid count is dynamic because rows are the ragged
    ``chain.rolled_tiles`` of an arbitrary global window — the loop
    bound trims to it (a ``valid == 0`` padding row costs zero sweep
    iterations) and the candidate mask applies it exactly.

    ``sched=True`` (ISSUE 16) hoists the row's shared message-schedule
    prefix — rounds 0-2 plus the nonce-free parts of w16-w19 — out of
    the tile loop via ``sym.prepare_hdr``: everything that depends only
    on (midstate, merkle word 7, time, bits) is computed once per grid
    step as 0-d scalars instead of once per tile. Mosaic does not LICM
    scalar work out of ``while_loop`` bodies on its own, so the hoist
    must be structural. Same booleans bit for bit (the prepared finisher
    is pinned against ``hash_sym_e60_e61`` in tier-1)."""
    mid = [mid_ref[0, i] for i in range(8)]
    tail = [tw_ref[0, 0], tw_ref[0, 1], tw_ref[0, 2], 0] + list(
        ops.HEADER_TAIL_PAD
    )
    cand_c = np.uint32(sym.CAND_E60)
    offs = (
        jax.lax.broadcasted_iota(jnp.int32, _TILE, 0) * np.int32(LANES)
        + jax.lax.broadcasted_iota(jnp.int32, _TILE, 1)
    )
    base = base_ref[0]
    cap1 = cap_ref[0]
    limit = lim_ref[0]  # dynamic i32 valid count, NOT a trace constant
    tile_sz = _TILE[0] * LANES
    prep = sym.prepare_hdr(mid, tail[0], tail[1], tail[2]) if sched else None

    def cond(carry):
        i, found, _ = carry
        return (i < n_tiles) & (found == 0) & (i * np.int32(tile_sz) < limit)

    def body(carry):
        i, _, first_offs = carry
        any_ok = jnp.zeros(_TILE, jnp.bool_)
        for t in range(tiles_per_step):
            offs_i = offs + (i + t) * np.int32(tile_sz)
            nonces = base + jax.lax.bitcast_convert_type(offs_i, jnp.uint32)
            if sched:
                e60, e61 = sym.hash_prepared_e60_e61(prep, nonces)
            else:
                e60, e61 = sym.hash_sym_e60_e61(
                    mid, [tail], ops.HEADER_NONCE_POSITIONS, 0, nonces
                )
            digest6 = sym.add(sym.DIGEST6_BIAS, e61)
            hw1 = sym.xor(
                sym.shl(sym.and_(digest6, 0x000000FF), 24),
                sym.shl(sym.and_(digest6, 0x0000FF00), 8),
                sym.shr(sym.and_(digest6, 0x00FF0000), 8),
                sym.shr(sym.and_(digest6, 0xFF000000), 24),
                0x80000000,
            )
            hw1b = jax.lax.bitcast_convert_type(hw1, jnp.int32)
            ok = (e60 == cand_c) & (hw1b <= cap1) & (offs_i < limit)
            any_ok = any_ok | ok
            first_offs = jnp.where(
                ok & (offs_i < first_offs), offs_i, first_offs
            )
        found = jnp.max(any_ok.astype(jnp.int32))
        return (i + tiles_per_step, found, first_offs)

    init = (jnp.int32(0), jnp.int32(0), jnp.full(_TILE, _I32MAX, jnp.int32))
    _, found, first_offs = jax.lax.while_loop(cond, body, init)
    first = jnp.min(first_offs)
    lane = jax.lax.broadcasted_iota(jnp.int32, _TILE, 1)
    row = jnp.where(lane == np.int32(_FOUND), found, jnp.zeros(_TILE, jnp.int32))
    row = jnp.where(lane == np.int32(_FIRST_IDX), first, row)
    out_ref[0] = jax.lax.bitcast_convert_type(row, jnp.uint32)


@partial(jax.jit, static_argnums=(4, 5, 7))
def pallas_search_candidates_hdr_batch(
    midstates: jnp.ndarray,
    tailws: jnp.ndarray,
    bases: jnp.ndarray,
    valids: jnp.ndarray,
    width: int,
    tiles_per_step: int = 8,
    hw1_cap: jnp.ndarray | None = None,
    sched: bool = False,
):
    """Batched twin of :func:`pallas_search_candidates_hdr`: a grid over
    ``B`` roll rows, each sweeping up to ``width`` nonces of ITS OWN
    dynamic header — ``(B, 8)`` midstates, ``(B, 3)`` tail batches
    (``ops.merkle.make_extranonce_roll_batch`` outputs, straight from
    device memory), ``(B,)`` per-row nonce bases and valid counts. One
    dispatch sweeps ``B·width`` global indices; segment boundaries cost
    nothing because they are just row edges of the same launch.

    Returns ``(founds (B,) u32, first_offs (B,) u32)`` — per-row flags
    and lowest candidate offsets (relative to that row's base, valid iff
    the flag is set). Rows are masked to their ``valids`` count exactly
    (a ragged or padding row can never surface an out-of-tile
    candidate), so the caller's cross-row fold is a plain masked min
    over ``global_base[row] + first_offs[row]``.

    ``sched=True`` selects the shared-schedule kernel body (see
    ``_cand_hdr_batch_kernel``): per-row scalar schedule prefix hoisted
    out of the tile loop, identical results. ``False`` is the exact
    pre-ISSUE-16 kernel — the bit-for-bit A/B baseline.
    """
    if not 1 <= width <= 1 << 30:
        raise ValueError("width must be in [1, 2^30] (int32 offset domain)")
    if hw1_cap is None:
        hw1_cap = jnp.uint32(0xFFFFFFFF)
    b = midstates.shape[0]
    chunk = _TILE[0] * LANES * tiles_per_step
    n_tiles = -(-width // chunk) * tiles_per_step
    cap_biased = jax.lax.bitcast_convert_type(
        hw1_cap.astype(jnp.uint32) ^ jnp.uint32(0x80000000), jnp.int32
    )
    summary = pl.pallas_call(
        partial(_cand_hdr_batch_kernel, n_tiles, tiles_per_step, sched),
        out_shape=jax.ShapeDtypeStruct((b,) + _TILE, jnp.uint32),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, 8), lambda i: (i, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 3), lambda i: (i, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1,), lambda i: (i,), memory_space=pltpu.SMEM),
            pl.BlockSpec((1,), lambda i: (i,), memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (1,) + _TILE, lambda i: (i, 0, 0), memory_space=pltpu.VMEM
        ),
        interpret=_interpret(),
    )(
        midstates.astype(jnp.uint32),
        tailws.astype(jnp.uint32),
        bases.astype(jnp.uint32),
        valids.astype(jnp.int32),
        cap_biased.reshape(1),
    )
    return summary[:, 0, _FOUND], summary[:, 0, _FIRST_IDX]


# ---------------------------------------------------------------------------
# toy-dialect (MIN) fold kernel
# ---------------------------------------------------------------------------

def _min_kernel(template, n_tiles, tiles_per_step, n_valid,
                base_ref, out_ref):
    """Whole-chunk toy-dialect fold in one invocation: minimize the
    64-bit fold (digest words 0, 1) over ``n_valid`` consecutive 64-bit
    nonces. Same tile/ILP structure as the search kernel, no early exit
    (a min has none)."""
    offs = (
        jax.lax.broadcasted_iota(jnp.int32, _TILE, 0) * np.int32(LANES)
        + jax.lax.broadcasted_iota(jnp.int32, _TILE, 1)
    )
    base_hi, base_lo = base_ref[0], base_ref[1]
    limit = np.int32(n_valid)
    tile_sz = _TILE[0] * LANES

    def body(i, carry):
        min_hi, min_lo, min_offs = carry
        for t in range(tiles_per_step):
            offs_i = offs + (i + t) * np.int32(tile_sz)
            lo = base_lo + jax.lax.bitcast_convert_type(offs_i, jnp.uint32)
            hi = base_hi + (lo < base_lo).astype(jnp.uint32)  # 64-bit carry
            digest = sym.double_sha256_sym(template, hi, lo)
            fh = jax.lax.bitcast_convert_type(
                sym.xor(digest[0], 0x80000000), jnp.int32
            )
            fl = jax.lax.bitcast_convert_type(
                sym.xor(digest[1], 0x80000000), jnp.int32
            )
            c_lt = (fh < min_hi) | ((fh == min_hi) & (fl < min_lo))
            c_lt = c_lt & (offs_i < limit)
            min_hi = jnp.where(c_lt, fh, min_hi)
            min_lo = jnp.where(c_lt, fl, min_lo)
            min_offs = jnp.where(c_lt, offs_i, min_offs)
        return min_hi, min_lo, min_offs

    init = (
        jnp.full(_TILE, _I32MAX, jnp.int32),
        jnp.full(_TILE, _I32MAX, jnp.int32),
        jnp.full(_TILE, _I32MAX, jnp.int32),
    )
    min_hi, min_lo, min_offs = jax.lax.fori_loop(
        0, n_tiles // tiles_per_step,
        lambda s, c: body(s * tiles_per_step, c), init
    )
    # cross-lane argmin (2 words), lowest-offset tie-break
    m_hi = jnp.min(min_hi)
    mask = min_hi == m_hi
    m_lo = jnp.min(jnp.where(mask, min_lo, _I32MAX))
    mask = mask & (min_lo == m_lo)
    m_off = jnp.min(jnp.where(mask, min_offs, _I32MAX))
    lane = jax.lax.broadcasted_iota(jnp.int32, _TILE, 1)
    unbias = np.int32(-0x80000000)
    row = jnp.zeros(_TILE, jnp.int32)
    for idx, val in ((0, m_hi ^ unbias), (1, m_lo ^ unbias), (2, m_off)):
        row = jnp.where(lane == np.int32(idx), val, row)
    out_ref[...] = jax.lax.bitcast_convert_type(row, jnp.uint32)


@partial(jax.jit, static_argnums=(0, 3, 4))
def pallas_min_toy(
    template: ops.NonceTemplate,
    base_hi: jnp.ndarray,
    base_lo: jnp.ndarray,
    n: int,
    tiles_per_step: int = 8,
):
    """Toy-dialect fold over ``n`` consecutive 64-bit nonces from
    ``(base_hi, base_lo)``: returns ``(fold_hi, fold_lo, argmin_off)`` —
    the minimum ``toy_hash`` value as u32 halves and the offset of its
    nonce. Lanes past ``n`` are masked; ties resolve to the lowest
    nonce."""
    if not 1 <= n <= 1 << 30:
        raise ValueError("n must be in [1, 2^30] (int32 offset domain)")
    chunk = _TILE[0] * LANES * tiles_per_step
    n_tiles = -(-n // chunk) * tiles_per_step
    summary = pl.pallas_call(
        partial(_min_kernel, template, n_tiles, tiles_per_step, n),
        out_shape=jax.ShapeDtypeStruct(_TILE, jnp.uint32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(jnp.stack([base_hi.astype(jnp.uint32).reshape(()),
                 base_lo.astype(jnp.uint32).reshape(())]))
    row = summary[0]
    return row[0], row[1], row[2]
