"""Hand-written Pallas TPU kernels for the mining hot path.

Same contracts as ``tpuminter.ops`` (the jnp/XLA reference path), but the
inner loops are Pallas kernels: nonces generated in-register, message
constants baked into the kernel at trace time via the symbolic compress
(``tpuminter.ops.symbolic``), digests never touching HBM in the fused
search. On the CPU backend everything runs in interpreter mode so CI can
pin kernels to the jnp path bit-for-bit without a TPU (SURVEY.md §4(c)).
"""

from tpuminter.kernels.sha256 import (
    pallas_min_toy,
    pallas_search_candidates,
    pallas_search_candidates_hdr,
    pallas_search_candidates_hdr_batch,
    pallas_search_target,
    pallas_sha256_batch,
)
from tpuminter.kernels.splitmix import pallas_splitmix_batch

__all__ = [
    "pallas_sha256_batch",
    "pallas_search_target",
    "pallas_search_candidates",
    "pallas_search_candidates_hdr",
    "pallas_search_candidates_hdr_batch",
    "pallas_min_toy",
    "pallas_splitmix_batch",
]
