#!/usr/bin/env python
"""Round-5 follow-up: unroll the FILL scan harder than the walk.

PERF.md: the walk rejected unroll=4 (gather-overlap pairing breaks) and
the shipping compromise is unroll=2 on BOTH scans. But the fill scan
has no gather — its ~100 us/step is mostly the ~90 us axon loop floor,
and 1024 fill steps are ~13% of the whole scrypt pipeline. A higher
fill-only unroll halves that floor share without touching the walk.

Times full ROMix (fill+walk, B=16384, N=1024) for (fill_unroll,
walk_unroll) in {(2,2) shipping, (4,2), (8,2)}; exactness pinned
against the shipping output.

Run on the real chip: ``python scripts/romix_fill_unroll_probe.py``.
"""

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/tpuminter-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from tpuminter.ops.scrypt import _block_mix_words  # noqa: E402

B = 16384
N_LOG2 = 10
N = 1 << N_LOG2


def sync(x):
    np.asarray(jax.tree.leaves(x)[0])


def timed(fn, *args, reps=3):
    out = fn(*args)
    sync(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        sync(out)
        best = min(best, time.perf_counter() - t0)
    return best


@partial(jax.jit, static_argnums=(1, 2))
def romix_u(x, fill_unroll, walk_unroll):
    batch = x.shape[0]
    lane = jnp.arange(batch, dtype=jnp.uint32)
    words = tuple(x[:, i] for i in range(32))

    def fill(carry, _):
        return tuple(_block_mix_words(list(carry))), jnp.stack(carry, axis=-1)

    words, v = jax.lax.scan(fill, words, None, length=N, unroll=fill_unroll)
    vflat = v.reshape(N * batch, 32)

    def walk(carry, _):
        j = carry[16] & np.uint32(N - 1)
        vj = vflat[(j * np.uint32(batch) + lane).astype(jnp.int32)]
        mixed = [c ^ vj[:, i] for i, c in enumerate(carry)]
        return tuple(_block_mix_words(mixed)), None

    words, _ = jax.lax.scan(walk, words, None, length=N, unroll=walk_unroll)
    return jnp.stack(words, axis=-1)


def main():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 2**32, (B, 32), dtype=np.uint32))

    ref = None
    for fill_u, walk_u in [(2, 2), (4, 2), (8, 2)]:
        t = timed(romix_u, x, fill_u, walk_u)
        out = np.asarray(romix_u(x, fill_u, walk_u)[:64])  # small pull
        if ref is None:
            ref = out
        exact = bool((out == ref).all())
        rate = B / t
        print(f"fill={fill_u} walk={walk_u}: {t * 1e3:7.1f} ms "
              f"({rate / 1e3:.1f} kH/s-equiv romix-only) exact={exact}")


if __name__ == "__main__":
    main()
