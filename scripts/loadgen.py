#!/usr/bin/env python
"""Control-plane load generator: the first benchmark of the scheduler path.

Every data-plane number in BENCH_r0x measures hashes/s; nothing measured
the loop the ROADMAP north-star actually runs through at fleet scale —
coordinator message handling, dispatch, host verification, and the LSP
stack under a sustained assign/result churn. This harness drives a REAL
:class:`~tpuminter.coordinator.Coordinator` over the REAL LSP/UDP stack
on loopback with N *instant* miners (answer every Assign immediately
with a verifiable Result — zero mining time, so the measurement is pure
control plane) and M closed-loop clients, and reports:

- ``results_per_s``   — chunk Results accepted by the coordinator
- ``assigns_per_s``   — chunk dispatches written by the coordinator
- ``p50_ms``/``p99_ms`` — assign→result round trip (dispatch write to
  accepted Result, ``Coordinator.latencies``)
- ``max_stall_ms``    — worst event-loop stall observed by a 1 ms
  sampler; heartbeats/epochs miss deadlines iff the loop stalls, so
  this bounds "no heartbeat deadline missed"
- ``frames_sent``/``frames_received``/``acks_coalesced`` — datagram and
  ack-coalescing counters at the coordinator's transport seam

All miners/clients are in-process asyncio tasks (the same way the e2e
suite fakes multi-node on localhost), so the figure is a whole-stack
number: both ends' CPU shares one core, exactly like the CI host.

CLI:  ``python scripts/loadgen.py [--miners N] [--clients M]
[--duration S] [--smoke] [--json]``.  ``--smoke`` runs a short fleet-64
burst and exits nonzero on any event-loop stall above one FAST epoch or
any miner declared lost — the tier-1 liveness gate
(tests/test_control_plane.py).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import statistics
import sys
import time
from typing import Optional

# allow `python scripts/loadgen.py` from a source checkout
sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))
))

from tpuminter import chain  # noqa: E402
from tpuminter.coordinator import Coordinator  # noqa: E402
from tpuminter.lsp import LspClient, LspConnectionLost, Params  # noqa: E402
from tpuminter.lsp.params import FAST  # noqa: E402
from tpuminter.protocol import (  # noqa: E402
    Assign,
    Cancel,
    Join,
    PowMode,
    Request,
    Result,
    Setup,
    decode_msg,
    encode_msg,
)


async def _instant_miner(port: int, params: Params) -> None:
    """Join, then answer every Assign instantly with a *verifiable*
    Result (the real toy hash of the range's first nonce). The
    coordinator's per-result verification cost is therefore the
    production cost; the miner's own cost is one host SHA-256."""
    w = await LspClient.connect("127.0.0.1", port, params)
    w.write(encode_msg(Join(backend="instant", lanes=1)))
    templates = {}

    def handle(raw: bytes) -> None:
        msg = decode_msg(raw)
        if isinstance(msg, Setup):
            templates[msg.request.job_id] = msg.request
        elif isinstance(msg, Cancel):
            templates.pop(msg.job_id, None)
        elif isinstance(msg, Assign):
            req = templates.get(msg.job_id)
            if req is None:
                return
            w.write(encode_msg(Result(
                msg.job_id, req.mode, nonce=msg.lower,
                hash_value=chain.toy_hash(req.data, msg.lower),
                found=True, searched=msg.upper - msg.lower + 1,
                chunk_id=msg.chunk_id,
            )))

    try:
        while True:
            raw = await w.read()
            # drain the delivered burst without a task wakeup per message
            while raw is not None:
                handle(raw)
                raw = (
                    w.read_nowait() if hasattr(w, "read_nowait") else None
                )
    except (LspConnectionLost, asyncio.CancelledError):
        pass
    finally:
        await w.close(drain_timeout=0.2)


async def _client_loop(port: int, params: Params, cid: int, upper: int,
                       counter: dict) -> None:
    """Closed-loop client: submit a MIN job, await its Result, repeat —
    one LSP connection for the whole run (the reference's one-shot
    connect/submit would measure dial latency, not the scheduler)."""
    c = await LspClient.connect("127.0.0.1", port, params)
    try:
        jid = 0
        while True:
            jid += 1
            c.write(encode_msg(Request(
                job_id=jid, mode=PowMode.MIN, lower=0, upper=upper,
                data=b"loadgen-%d-%d" % (cid, jid),
            )))
            while True:
                msg = decode_msg(await c.read())
                if isinstance(msg, Result) and msg.job_id == jid:
                    break
            counter["jobs"] += 1
    except (LspConnectionLost, asyncio.CancelledError):
        pass
    finally:
        await c.close(drain_timeout=0.2)


async def _stall_sampler(sample: float, out: dict) -> None:
    """Record the worst event-loop stall: a sleep(d) that wakes late by
    s means every timer (epoch ticks, heartbeats) was delayed by s."""
    loop = asyncio.get_running_loop()
    while True:
        t0 = loop.time()
        await asyncio.sleep(sample)
        late = loop.time() - t0 - sample
        if late > out["max_stall"]:
            out["max_stall"] = late


async def run_load(
    n_miners: int = 8,
    n_clients: int = 4,
    duration: float = 3.0,
    *,
    chunk_size: int = 1024,
    chunks_per_job: Optional[int] = None,
    params: Params = FAST,
    warmup: float = 0.5,
) -> dict:
    """Drive the fleet for ``duration`` seconds (after ``warmup``) and
    return the metrics dict described in the module docstring."""
    coord = await Coordinator.create(params=params, chunk_size=chunk_size)
    serve = asyncio.ensure_future(coord.serve())
    # jobs long enough that every miner stays busy between completions
    if chunks_per_job is None:
        chunks_per_job = max(8, 4 * n_miners)
    upper = chunk_size * chunks_per_job - 1
    lost_events = {"n": 0}
    # count loss events at the server seam: a healthy loopback run must
    # declare nobody dead (a stalled loop shows up here first)
    orig_handle_lost = coord._server._handle_lost

    def counting_handle_lost(conn_id: int) -> None:
        lost_events["n"] += 1
        orig_handle_lost(conn_id)

    coord._server._handle_lost = counting_handle_lost

    miners = [
        asyncio.ensure_future(_instant_miner(coord.port, params))
        for _ in range(n_miners)
    ]
    counter = {"jobs": 0}
    clients = [
        asyncio.ensure_future(
            _client_loop(coord.port, params, i, upper, counter)
        )
        for i in range(n_clients)
    ]
    stall = {"max_stall": 0.0}
    sampler = asyncio.ensure_future(_stall_sampler(0.001, stall))
    try:
        await asyncio.sleep(warmup)
        ep = coord.server.endpoint
        t0 = time.monotonic()
        chunks0 = coord._next_chunk_id
        # churn-proof cumulative counters (per-miner sums would lose a
        # lost miner's whole history from the delta)
        results0 = (
            coord.stats["results_accepted"] + coord.stats["results_rejected"]
        )
        rejected0 = coord.stats["results_rejected"]
        lat_seen0 = len(coord.latencies)
        sent0, recv0 = ep.sent, ep.received
        jobs0 = counter["jobs"]
        stall["max_stall"] = 0.0  # warmup stalls (connect burst) excluded
        await asyncio.sleep(duration)
        dt = time.monotonic() - t0
        assigns = coord._next_chunk_id - chunks0
        results = (
            coord.stats["results_accepted"] + coord.stats["results_rejected"]
            - results0
        )
        lats = list(coord.latencies)[lat_seen0:] or [0.0]
        lats_ms = sorted(1e3 * x for x in lats)
        ack_stats = getattr(coord.server, "ack_stats", lambda: {})()
        return {
            "fleet": n_miners,
            "clients": n_clients,
            "duration_s": round(dt, 3),
            "results_per_s": round(results / dt, 1),
            "assigns_per_s": round(assigns / dt, 1),
            "jobs_per_s": round((counter["jobs"] - jobs0) / dt, 2),
            "p50_ms": round(statistics.median(lats_ms), 3),
            "p99_ms": round(
                lats_ms[max(0, int(len(lats_ms) * 0.99) - 1)], 3
            ),
            "max_stall_ms": round(stall["max_stall"] * 1e3, 3),
            "frames_sent": ep.sent - sent0,
            "frames_received": ep.received - recv0,
            "acks_sent": ack_stats.get("acks_sent", 0),
            "acks_coalesced": ack_stats.get("acks_coalesced", 0),
            "miners_lost": lost_events["n"],
            "results_rejected": coord.stats["results_rejected"] - rejected0,
        }
    finally:
        sampler.cancel()
        for t in clients + miners:
            t.cancel()
        await asyncio.gather(*clients, *miners, return_exceptions=True)
        serve.cancel()
        await asyncio.gather(serve, return_exceptions=True)
        await coord.close()


def smoke_check(metrics: dict, params: Params = FAST) -> list:
    """The liveness assertions behind ``--smoke`` (returned as a list of
    violation strings so tests can show all of them at once): the
    coordinator must sustain the fleet with zero loss events, make real
    progress, and never stall the event loop past one epoch — the bound
    past which heartbeats start missing their deadlines."""
    bad = []
    if metrics["results_per_s"] <= 0:
        bad.append(f"no results accepted: {metrics}")
    if metrics["miners_lost"] > 0:
        bad.append(
            f"{metrics['miners_lost']} connection(s) declared lost on a "
            f"healthy loopback fleet"
        )
    if metrics["max_stall_ms"] >= params.epoch_millis:
        bad.append(
            f"event-loop stall {metrics['max_stall_ms']:.1f} ms >= one "
            f"{params.epoch_millis} ms epoch: heartbeat deadlines missed"
        )
    return bad


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="tpuminter control-plane load generator"
    )
    parser.add_argument("--miners", type=int, default=8)
    parser.add_argument("--clients", type=int, default=4)
    parser.add_argument("--duration", type=float, default=3.0)
    parser.add_argument("--chunk-size", type=int, default=1024)
    parser.add_argument(
        "--smoke", action="store_true",
        help="fleet-64 burst with liveness assertions: exit 1 on any "
        "event-loop stall >= one epoch or any lost connection",
    )
    parser.add_argument("--json", action="store_true", help="JSON output")
    args = parser.parse_args(argv)
    if args.smoke:
        args.miners, args.clients = 64, 4
        args.duration = min(args.duration, 2.0)
    metrics = asyncio.run(run_load(
        args.miners, args.clients, args.duration,
        chunk_size=args.chunk_size,
    ))
    print(json.dumps(metrics) if args.json else
          "\n".join(f"{k}: {v}" for k, v in metrics.items()))
    if args.smoke:
        violations = smoke_check(metrics)
        for v in violations:
            print(f"SMOKE FAIL: {v}", file=sys.stderr)
        return 1 if violations else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
