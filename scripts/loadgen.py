#!/usr/bin/env python
"""Control-plane load generator: the first benchmark of the scheduler path.

Every data-plane number in BENCH_r0x measures hashes/s; nothing measured
the loop the ROADMAP north-star actually runs through at fleet scale —
coordinator message handling, dispatch, host verification, and the LSP
stack under a sustained assign/result churn. This harness drives a REAL
:class:`~tpuminter.coordinator.Coordinator` over the REAL LSP/UDP stack
on loopback with N *instant* miners (answer every Assign immediately
with a verifiable Result — zero mining time, so the measurement is pure
control plane) and M closed-loop clients, and reports:

- ``results_per_s``   — chunk Results accepted by the coordinator
- ``assigns_per_s``   — chunk dispatches written by the coordinator
- ``p50_ms``/``p99_ms`` — assign→result round trip (dispatch write to
  accepted Result, ``Coordinator.latencies``)
- ``max_stall_ms``    — worst event-loop stall observed by a 1 ms
  sampler; heartbeats/epochs miss deadlines iff the loop stalls, so
  this bounds "no heartbeat deadline missed"
- ``frames_sent``/``frames_received``/``acks_coalesced`` — datagram and
  ack-coalescing counters at the coordinator's transport seam
- ``wire_bytes_per_result`` / ``msgs_json`` / ``msgs_binary`` — wire
  volume per accepted result and the process-wide codec mix, so the
  Round 7 "~16% JSON codec" profile claim stays re-checkable
- ``dispatches_pipelined`` / ``pipeline_depth_mean`` / ``_max`` /
  ``miner_idle_gap_p50_ms`` / ``_p99_ms`` — pipelining evidence: how
  often a dispatch topped up a non-empty per-miner queue, the sampled
  fill level, and the result→next-assign bubble at the miners (a full
  round trip at depth 1; ~0 once the pipeline hides it)

``--codec {binary,json}`` and ``--pipeline N`` are the Round 9 A/B
knobs: ``--codec json --pipeline 1`` reproduces the PR 3 baseline
stack in the same build, which is what makes paired per-stage
measurement possible on this noisy host (PERF.md §Round 8 protocol).

All miners/clients are in-process asyncio tasks (the same way the e2e
suite fakes multi-node on localhost), so the figure is a whole-stack
number: both ends' CPU shares one core, exactly like the CI host.

CLI:  ``python scripts/loadgen.py [--miners N] [--clients M]
[--duration S] [--smoke] [--json]``.  ``--smoke`` runs a short fleet-64
burst and exits nonzero on any event-loop stall above one FAST epoch or
any miner declared lost — the tier-1 liveness gate
(tests/test_control_plane.py).

``--scenario crash`` (ISSUE 3) instead drives the DURABLE control
plane: the coordinator journals to a write-ahead log
(``tpuminter.journal``), gets killed mid-burst (socket closed with no
drain, buffered journal records lost — the in-process equivalent of
``kill -9``), and is restarted from the journal on the same port while
the fleet (redialing miners, re-submitting clients) resumes on its own.
Reported: ``restart_to_first_assign_ms`` (restart to the first chunk
dispatched to a redialed miner), ``dip_window_ms`` (crash until
results/s recovers to half its pre-crash mean), ``answers_lost`` /
``answers_duplicated`` (the exactly-once ledger — both must be 0), and
the journal's record/byte/flush counters. A small-fleet variant is the
tier-1 crash gate (tests/test_recovery.py).

``--scenario failover`` (ISSUE 5) drives the REPLICATED control plane:
the primary ships its WAL to a live in-process hot standby
(``tpuminter.replication``) and dies mid-burst — its journal file is
never read again (machine loss, not process loss). The standby detects
the silence, promotes with a fenced epoch (replay-free: its live
shadow state becomes the coordinator), and the fleet — miners and
durable clients configured with BOTH addresses — rotates onto it
unattended. Reported: ``detect_ms`` / ``takeover_ms`` /
``blackout_ms``, the exactly-once ledger across the machine loss, and
shipping counters. ``--smoke`` is the tier-1 failover gate
(tests/test_replication.py).

``--scenario zipf`` / ``--scenario churn`` (ISSUE 13) drive the
ADMISSION-CONTROLLED control plane with open-loop demand (seeded
Poisson arrivals that never wait for answers — production traffic does
not self-throttle). zipf: one whale tenant at 10x everyone's demand
against a quota'd coordinator, gated on the small tenants' p99
surviving the whale and on exactly-once (a Refuse must delay, never
lose). churn: thousands of short-lived clients — 40% abandoning
mid-job without a goodbye — through a tightly capped coordinator with
a kill -9 mid-storm, gated on every table's high-water plateauing at
its cap-derived bound, zero residue after the wash, and replay landing
within the same caps. ``--scenario churn --smoke`` is the tier-1
admission gate (tests/test_control_plane.py).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import statistics
import sys
import tempfile
import time
from typing import Optional

# allow `python scripts/loadgen.py` from a source checkout
sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))
))

from tpuminter import chain  # noqa: E402
from tpuminter.analysis import affinity  # noqa: E402
from tpuminter.coordinator import (  # noqa: E402
    QUOTA_BUCKETS_CAP,
    Coordinator,
)
from tpuminter.lsp import (  # noqa: E402
    LspClient,
    LspConnectError,
    LspConnectionLost,
    Params,
)
from tpuminter.lsp.params import FAST, jittered_backoff  # noqa: E402
from tpuminter.protocol import (  # noqa: E402
    MIN_UNTRACKED,
    Assign,
    Beacon,
    Cancel,
    Join,
    PowMode,
    Refuse,
    Request,
    Result,
    RollAssign,
    Setup,
    WorkResult,
    codec_stats,
    decode_msg,
    encode_msg,
    payload_is_binary,
)


async def make_coordinator(
    port: int = 0, *, loops: int = 1, procs: int = 1, io_batch=None,
    journal_mode: str = "writer", recover_from=None,
    threaded: bool = False, **kwargs
):
    """The one place the harness constructs a coordinator: ``loops >= 2``
    builds the multi-loop sharded group (``tpuminter.multiloop``) — and
    FAILS LOUDLY if it cannot (no silent single-loop fallback: a smoke
    gate that asked for 2 loops must never accidentally measure 1).
    ``procs >= 2`` builds the multi-PROCESS group instead
    (``tpuminter.multiproc``, ISSUE 19) — same no-fallback rule, and
    mutually exclusive with ``loops`` (a shard is either a loop or a
    process, never both).
    ``threaded=True`` with ``loops=1`` runs the ONE shard off the
    caller's loop too — the A/B baseline that isolates the partitioning
    seam from the cost of the coordinator simply not sharing the
    drivers' loop (PERF.md §Round 11)."""
    if procs > 1:
        if loops > 1 or threaded:
            raise ValueError("procs>1 is exclusive with loops/threaded")
        from tpuminter.multiproc import MultiProcCoordinator

        return await MultiProcCoordinator.create(
            port, procs=procs, io_batch=io_batch,
            recover_from=recover_from, **kwargs
        )
    if loops <= 1 and not threaded:
        return await Coordinator.create(
            port, io_batch=io_batch, recover_from=recover_from, **kwargs
        )
    from tpuminter.multiloop import MultiLoopCoordinator

    return await MultiLoopCoordinator.create(
        port, loops=loops, io_batch=io_batch, journal_mode=journal_mode,
        recover_from=recover_from, **kwargs
    )


def _servers(coord) -> list:
    return list(coord.servers) if hasattr(coord, "servers") else [
        coord.server
    ]


def _endpoints(coord) -> list:
    return [srv.endpoint for srv in _servers(coord)]


def _ep_totals(coord) -> tuple:
    """(sent, received, bytes) summed over every shard socket."""
    eps = _endpoints(coord)
    return (
        sum(ep.sent for ep in eps),
        sum(ep.received for ep in eps),
        sum(ep.sent_bytes + ep.received_bytes for ep in eps),
    )


def _ack_totals(coord) -> dict:
    out = {"acks_sent": 0, "acks_coalesced": 0}
    for srv in _servers(coord):
        st = srv.ack_stats()
        out["acks_sent"] += st.get("acks_sent", 0)
        out["acks_coalesced"] += st.get("acks_coalesced", 0)
    return out


def _hook_lost_events(coord, counter: dict) -> None:
    """Count loss events at every shard's server seam."""
    for srv in _servers(coord):
        orig = srv._handle_lost

        def counting(conn_id: int, _orig=orig) -> None:
            counter["n"] += 1
            _orig(conn_id)

        srv._handle_lost = counting


def _lat_baseline(coord):
    if hasattr(coord, "shards"):
        return [len(sh.coordinator.latencies) for sh in coord.shards]
    return len(coord.latencies)


def _lat_new(coord, baseline) -> list:
    if hasattr(coord, "shards"):
        return [
            x
            for sh, b in zip(coord.shards, baseline)
            for x in list(sh.coordinator.latencies)[b:]
        ]
    return list(coord.latencies)[baseline:]


async def _crash_coordinator(coord) -> None:
    """kill -9 either coordinator shape and wait until its socket(s)
    actually released the port (a real kill -9 has the OS do this at
    process exit, before any restart could bind)."""
    res = coord.crash()
    if asyncio.iscoroutine(res):
        await res  # multiloop: joins the shard threads, port is free
        return
    for ep in _endpoints(coord):
        await ep.wait_closed()


async def _instant_miner(
    port: int, params: Params, *, binary: bool = True,
    idle_gaps: Optional[list] = None, delay: float = 0.0,
    connect_epochs: Optional[int] = None, on_session=None,
) -> None:
    """Join, then answer every Assign instantly with a *verifiable*
    Result (the real toy hash of the range's first nonce). The
    coordinator's per-result verification cost is therefore the
    production cost; the miner's own cost is one host SHA-256.

    ``binary`` advertises the struct-packed codec (the worker role's
    negotiation: Results flip to binary after the first binary payload
    arrives from the coordinator). ``idle_gaps`` collects this miner's
    result→next-assign gaps in seconds — the round-trip bubble the
    pipelining tentpole exists to remove: at depth 1 every gap is a
    full assign→result round trip; at depth ≥ 2 the next Assign is
    already queued when the Result is written and the gap collapses.

    ``delay`` sleeps that many seconds before answering each Assign —
    the SlowMiner fleet for the pipeline-depth sweep: with per-chunk
    compute time on the books, deeper queues can (or cannot) keep the
    miner busy across coordinator scheduling latency, which is exactly
    what the sweep measures. Chunks queue FIFO and answer one at a
    time, like a real single-device worker."""
    w = await LspClient.connect(
        "127.0.0.1", port, params, connect_epochs=connect_epochs
    )
    w.write(encode_msg(Join(
        backend="instant", lanes=1, codec="bin" if binary else "json",
    )))
    if on_session is not None:
        # chaos cells that target links by source port (on localhost
        # the port IS the identity) learn this session's address here
        on_session(w)
    templates = {}
    speak = {"binary": False}
    answered_at = {"t": None}  # time of the last Result write, gap-armed
    backlog: "asyncio.Queue" = asyncio.Queue()  # delay-mode work queue

    def answer(msg: Assign) -> None:
        req = templates.get(msg.job_id)
        if req is None:
            return
        w.write(encode_msg(Result(
            msg.job_id, req.mode, nonce=msg.lower,
            hash_value=chain.toy_hash(req.data, msg.lower),
            found=True, searched=msg.upper - msg.lower + 1,
            chunk_id=msg.chunk_id,
        ), binary=speak["binary"]))
        answered_at["t"] = time.monotonic()

    def handle(raw) -> None:
        if binary and not speak["binary"] and payload_is_binary(raw):
            speak["binary"] = True
        msg = decode_msg(raw)
        if isinstance(msg, Setup):
            templates[msg.request.job_id] = msg.request
        elif isinstance(msg, Cancel):
            templates.pop(msg.job_id, None)
        elif isinstance(msg, Assign):
            if answered_at["t"] is not None:
                if idle_gaps is not None and len(idle_gaps) < 200_000:
                    idle_gaps.append(time.monotonic() - answered_at["t"])
                answered_at["t"] = None
            if delay > 0:
                backlog.put_nowait(msg)
            else:
                answer(msg)

    async def slow_answerer() -> None:
        while True:
            msg = await backlog.get()
            await asyncio.sleep(delay)
            answer(msg)

    answerer = (
        asyncio.ensure_future(slow_answerer()) if delay > 0 else None
    )
    try:
        while True:
            raw = await w.read()
            # drain the delivered burst without a task wakeup per message
            while raw is not None:
                handle(raw)
                raw = (
                    w.read_nowait() if hasattr(w, "read_nowait") else None
                )
    except LspConnectionLost:
        pass  # CancelledError propagates: redial wrappers must see it
    finally:
        if answerer is not None:
            answerer.cancel()
            await asyncio.gather(answerer, return_exceptions=True)
        await w.close(drain_timeout=0.2)


async def _resilient_instant_miner(ports, params: Params,
                                   seed: int, *,
                                   binary: bool = True,
                                   on_session=None,
                                   clock=None) -> None:
    """An instant miner that survives coordinator restarts: when the
    connection is lost it redials with jittered exponential backoff and
    re-Joins (the crash scenario's fleet). ``ports`` may be one port or
    a list — the failover scenario's address rotation: each failure
    moves to the next port, so the fleet lands on a promoted standby
    (an un-promoted one rejects the dial, which just advances the
    rotation).

    ``clock`` is this miner's retry/backoff clock seam (ISSUE 20): the
    clock_skew chaos cell installs a per-miner ``ClockSkewPlan.fork``
    here so BOTH ends of the conversation lie about time, differently —
    a drifting worker clock stretches or shrinks the real redial wait,
    which may only ever degrade to a delayed redial."""
    import random as _random

    from tpuminter.worker import _sleep_on

    if isinstance(ports, int):
        ports = [ports]
    from tpuminter.replication import dial_patience

    rng = _random.Random(seed)
    delays = jittered_backoff(0.05, 1.0, rng)
    ce = dial_patience(ports)
    attempt = 0
    while True:
        port = ports[attempt % len(ports)]
        attempt += 1
        try:
            await _instant_miner(
                port, params, binary=binary, connect_epochs=ce,
                on_session=on_session,
            )
            delays = jittered_backoff(0.05, 1.0, rng)  # had a session
        except LspConnectError:
            pass
        await _sleep_on(clock, next(delays))


async def _client_loop(port: int, params: Params, cid: int, upper: int,
                       counter: dict) -> None:
    """Closed-loop client: submit a MIN job, await its Result, repeat —
    one LSP connection for the whole run (the reference's one-shot
    connect/submit would measure dial latency, not the scheduler).
    Every answered job id is remembered so a SECOND answer for it is
    booked in ``counter['dup_answers']`` — the cross-shard duplication
    evidence the multi-loop smoke gate asserts zero of."""
    c = await LspClient.connect("127.0.0.1", port, params)
    answered: set = set()
    try:
        jid = 0
        while True:
            jid += 1
            c.write(encode_msg(Request(
                job_id=jid, mode=PowMode.MIN, lower=0, upper=upper,
                data=b"loadgen-%d-%d" % (cid, jid),
            )))
            while True:
                msg = decode_msg(await c.read())
                if not isinstance(msg, Result):
                    continue
                if msg.job_id in answered:
                    counter["dup_answers"] += 1
                    continue
                if msg.job_id == jid:
                    answered.add(msg.job_id)
                    break
            counter["jobs"] += 1
    except (LspConnectionLost, asyncio.CancelledError):
        pass
    finally:
        await c.close(drain_timeout=0.2)


async def _stall_sampler(sample: float, out: dict) -> None:
    """Record the worst event-loop stall: a sleep(d) that wakes late by
    s means every timer (epoch ticks, heartbeats) was delayed by s."""
    loop = asyncio.get_running_loop()
    while True:
        t0 = loop.time()
        await asyncio.sleep(sample)
        late = loop.time() - t0 - sample
        if late > out["max_stall"]:
            out["max_stall"] = late


async def run_load(
    n_miners: int = 8,
    n_clients: int = 4,
    duration: float = 3.0,
    *,
    chunk_size: int = 1024,
    chunks_per_job: Optional[int] = None,
    params: Params = FAST,
    warmup: float = 0.5,
    journal_path: Optional[str] = None,
    binary: bool = True,
    pipeline_depth: int = 2,
    journal_tick_flush: bool = True,
    standby: bool = False,
    standby_sink: bool = False,
    chain: int = 0,
    replicate_to_addr=None,
    replica_ack: bool = False,
    miner_delay: float = 0.0,
    loops: int = 1,
    io_batch=None,
    journal_mode: str = "writer",
    journal_group_commit: Optional[bool] = None,
    threaded: bool = False,
) -> dict:
    """Drive the fleet for ``duration`` seconds (after ``warmup``) and
    return the metrics dict described in the module docstring.
    ``journal_path`` enables write-ahead journaling — the knob behind
    the ``recovery_journal_overhead_pct`` bench field. ``binary`` and
    ``pipeline_depth`` are the Round 9 A/B knobs: ``binary=False,
    pipeline_depth=1`` reproduces the PR 3 baseline stack, and the four
    combinations give the per-stage decomposition PERF.md quotes.

    Round 10 knobs: ``journal_tick_flush=False`` restores the PR 3/4
    flusher task (the serve-tick fold's A/B baseline); ``standby=True``
    attaches an in-process hot standby and ships the WAL to it (the
    ``replication_*`` overhead measurement — requires a journal);
    ``replica_ack`` additionally gates winner acks on standby
    confirmation; ``miner_delay`` makes every miner take that many
    seconds per chunk (the SlowMiner fleet for the pipeline-depth
    sweep)."""
    stby = None
    replicate_to = None
    chain_hops: list = []
    if replicate_to_addr is not None:
        # ship to an EXTERNAL standby (e.g. a --scenario chain-host
        # process): the two-process topology the chain-replication
        # bench measures — none of the replica work shares this loop
        if journal_path is None:
            raise ValueError("replicate_to_addr requires a journal_path")
        replicate_to = list(replicate_to_addr)
    elif standby:
        if journal_path is None:
            raise ValueError("standby=True requires a journal_path")
        from tpuminter.replication import ReplicationStandby

        # chain replication (ISSUE 18): `chain` extra hops BELOW the
        # hot standby, built tail-first so each hop knows where to
        # re-ship — the primary still pays for exactly one stream
        chain_to = None
        for hop in range(chain, 0, -1):
            tail = await ReplicationStandby.create(
                journal_path + ".chain%d" % hop, params=params,
                apply_shadow=not standby_sink, chain_to=chain_to,
            )
            chain_hops.insert(0, (tail, asyncio.ensure_future(tail.run())))
            chain_to = [("127.0.0.1", tail.port)]
        stby = await ReplicationStandby.create(
            journal_path + ".standby", params=params,
            # sink mode: persist+ack but no live shadow replay — the
            # per-stage decomposition seam (PERF.md §Round 10)
            apply_shadow=not standby_sink, chain_to=chain_to,
        )
        stby_task = asyncio.ensure_future(stby.run())
        replicate_to = [("127.0.0.1", stby.port)]
    coord = await make_coordinator(
        params=params, chunk_size=chunk_size, recover_from=journal_path,
        binary_codec=binary, pipeline_depth=pipeline_depth,
        journal_tick_flush=journal_tick_flush,
        replicate_to=replicate_to, replica_ack=replica_ack,
        loops=loops, io_batch=io_batch, journal_mode=journal_mode,
        threaded=threaded,
    )
    if journal_group_commit is not None and coord._journal is not None:
        # cross-job group-commit A/B knob (PERF.md §Round 11): False
        # restores the fsync-per-batch PR 3–5 behavior
        for j in getattr(coord._journal, "_journals", [coord._journal]):
            j.group_commit = journal_group_commit
    serve = asyncio.ensure_future(coord.serve())
    # jobs long enough that every miner stays busy between completions
    if chunks_per_job is None:
        chunks_per_job = max(8, 4 * n_miners)
    upper = chunk_size * chunks_per_job - 1
    lost_events = {"n": 0}
    # count loss events at the server seam: a healthy loopback run must
    # declare nobody dead (a stalled loop shows up here first)
    _hook_lost_events(coord, lost_events)

    idle_gaps: list = []
    miners = [
        asyncio.ensure_future(_instant_miner(
            coord.port, params, binary=binary, idle_gaps=idle_gaps,
            delay=miner_delay,
        ))
        for _ in range(n_miners)
    ]
    counter = {"jobs": 0, "dup_answers": 0}
    clients = [
        asyncio.ensure_future(
            _client_loop(coord.port, params, i, upper, counter)
        )
        for i in range(n_clients)
    ]
    stall = {"max_stall": 0.0}
    sampler = asyncio.ensure_future(_stall_sampler(0.001, stall))
    # outstanding-depth samples across busy miners (the pipeline's
    # live fill level; the gate reads dispatches_pipelined instead —
    # a counter cannot miss between samples)
    depth_samples: list = []

    async def depth_sampler() -> None:
        while True:
            await asyncio.sleep(0.005)
            if len(depth_samples) >= 100_000:
                continue
            busy = [
                len(m.chunks) for m in coord._miners.values() if m.chunks
            ]
            if busy:
                depth_samples.append(
                    (sum(busy) / len(busy), max(busy))
                )

    depth_task = asyncio.ensure_future(depth_sampler())
    try:
        await asyncio.sleep(warmup)
        t0 = time.monotonic()
        chunks0 = coord._next_chunk_id
        # churn-proof cumulative counters (per-miner sums would lose a
        # lost miner's whole history from the delta)
        stats0 = coord.stats
        results0 = (
            stats0["results_accepted"] + stats0["results_rejected"]
        )
        rejected0 = stats0["results_rejected"]
        pipelined0 = stats0["dispatches_pipelined"]
        lat_seen0 = _lat_baseline(coord)
        sent0, recv0, bytes0 = _ep_totals(coord)
        codec0 = dict(codec_stats)
        jobs0 = counter["jobs"]
        dups0 = counter["dup_answers"]
        stall["max_stall"] = 0.0  # warmup stalls (connect burst) excluded
        depth_samples.clear()
        idle_gaps.clear()
        await asyncio.sleep(duration)
        dt = time.monotonic() - t0
        assigns = coord._next_chunk_id - chunks0
        stats1 = coord.stats
        results = (
            stats1["results_accepted"] + stats1["results_rejected"]
            - results0
        )
        lats = _lat_new(coord, lat_seen0) or [0.0]
        lats_ms = sorted(1e3 * x for x in lats)
        ack_stats = _ack_totals(coord)
        gaps_ms = sorted(1e3 * g for g in idle_gaps) or [0.0]
        sent1, recv1, bytes1 = _ep_totals(coord)
        wire_bytes = bytes1 - bytes0
        return {
            "fleet": n_miners,
            "clients": n_clients,
            "duration_s": round(dt, 3),
            "codec": "binary" if binary else "json",
            "loops": getattr(coord, "loops", 1),
            "io_batch": _endpoints(coord)[0].sock is not None,
            "pipeline_depth_configured": pipeline_depth,
            "results_per_s": round(results / dt, 1),
            "assigns_per_s": round(assigns / dt, 1),
            "jobs_per_s": round((counter["jobs"] - jobs0) / dt, 2),
            "p50_ms": round(statistics.median(lats_ms), 3),
            "p99_ms": round(
                lats_ms[max(0, int(len(lats_ms) * 0.99) - 1)], 3
            ),
            "max_stall_ms": round(stall["max_stall"] * 1e3, 3),
            "frames_sent": sent1 - sent0,
            "frames_received": recv1 - recv0,
            "acks_sent": ack_stats.get("acks_sent", 0),
            "acks_coalesced": ack_stats.get("acks_coalesced", 0),
            "miners_lost": lost_events["n"],
            "dup_answers": counter["dup_answers"] - dups0,
            "results_rejected": stats1["results_rejected"] - rejected0,
            # -- codec accounting (satellite: the 16%-JSON-codec claim
            #    stays re-checkable from a shipped JSON). Message counts
            #    are process-wide (both ends run in this process, so an
            #    Assign counts once encoded and once decoded).
            "wire_bytes_per_result": (
                round(wire_bytes / results, 1) if results else 0.0
            ),
            "msgs_json": (
                codec_stats["json_encoded"] + codec_stats["json_decoded"]
                - codec0["json_encoded"] - codec0["json_decoded"]
            ),
            "msgs_binary": (
                codec_stats["binary_encoded"] + codec_stats["binary_decoded"]
                - codec0["binary_encoded"] - codec0["binary_decoded"]
            ),
            # -- pipelining evidence: dispatches that found work already
            #    outstanding, the sampled fill level, and the
            #    result→next-assign bubble at the miners
            "dispatches_pipelined": (
                stats1["dispatches_pipelined"] - pipelined0
            ),
            "pipeline_depth_mean": round(
                statistics.mean(s[0] for s in depth_samples), 2
            ) if depth_samples else 0.0,
            "pipeline_depth_max": max(
                (s[1] for s in depth_samples), default=0
            ),
            "miner_idle_gap_p50_ms": round(statistics.median(gaps_ms), 3),
            "miner_idle_gap_p99_ms": round(
                gaps_ms[max(0, int(len(gaps_ms) * 0.99) - 1)], 3
            ),
            # -- per-loop balance (the multi-loop satellite): results,
            #    datagrams, connections, handoffs, and stall per shard
            **(
                {
                    "steer_kernel": coord.steer_kernel,
                    "loop_metrics": coord.shard_metrics(),
                }
                if hasattr(coord, "shard_metrics") else {}
            ),
            **(
                {"journal": dict(coord._journal.stats)}
                if coord._journal is not None else {}
            ),
            **(
                {
                    "replication_batches": stby.stats["batches"],
                    "replication_records_applied": (
                        stby.stats["records_applied"]
                    ),
                    "replication_bytes": stby.stats["bytes"],
                    "replication_lag_bytes": (
                        (coord._journal.size if coord._journal else 0)
                        - stby.size
                    ),
                    **(
                        {
                            "chain_tail_bytes": chain_hops[-1][0].size,
                            "chain_tail_lag_bytes": (
                                stby.size - chain_hops[-1][0].size
                            ),
                        }
                        if chain_hops else {}
                    ),
                }
                if stby is not None else {}
            ),
        }
    finally:
        sampler.cancel()
        depth_task.cancel()
        for t in clients + miners:
            t.cancel()
        await asyncio.gather(*clients, *miners, return_exceptions=True)
        serve.cancel()
        await asyncio.gather(serve, return_exceptions=True)
        await coord.close()
        if stby is not None:
            stby_task.cancel()
            await asyncio.gather(stby_task, return_exceptions=True)
            await stby.close()
        for hop, hop_task in chain_hops:
            hop_task.cancel()
            await asyncio.gather(hop_task, return_exceptions=True)
            await hop.close()


def smoke_check(metrics: dict, params: Params = FAST) -> list:
    """The liveness assertions behind ``--smoke`` (returned as a list of
    violation strings so tests can show all of them at once): the
    coordinator must sustain the fleet with zero loss events, make real
    progress, and never stall the event loop past one epoch — the bound
    past which heartbeats start missing their deadlines."""
    bad = []
    if metrics["results_per_s"] <= 0:
        bad.append(f"no results accepted: {metrics}")
    if metrics["miners_lost"] > 0:
        bad.append(
            f"{metrics['miners_lost']} connection(s) declared lost on a "
            f"healthy loopback fleet"
        )
    if metrics["max_stall_ms"] >= params.epoch_millis:
        bad.append(
            f"event-loop stall {metrics['max_stall_ms']:.1f} ms >= one "
            f"{params.epoch_millis} ms epoch: heartbeat deadlines missed"
        )
    # Round 9 gate: when the run is configured with the shipping
    # defaults (pipelining depth >= 2, binary codec) the features must
    # demonstrably be ON — a silent fallback to JSON or depth-1
    # dispatch would pass the liveness checks while measuring nothing.
    if (
        metrics.get("pipeline_depth_configured", 1) >= 2
        and metrics.get("dispatches_pipelined", 0) <= 0
    ):
        bad.append(
            "pipelining configured but no dispatch ever topped up a "
            "non-empty pipeline"
        )
    if metrics.get("codec") == "binary" and metrics.get("msgs_binary", 0) <= 0:
        bad.append("binary codec configured but no binary messages flowed")
    # multi-loop gates (ISSUE 6 satellite): answers must never duplicate
    # across shards, and with a fleet large enough that an empty shard
    # is statistically impossible, every loop must actually carry peers
    if metrics.get("dup_answers", 0) > 0:
        bad.append(
            f"{metrics['dup_answers']} duplicate answer(s) reached a "
            f"client — cross-shard answer duplication"
        )
    loops = metrics.get("loops", 1)
    if loops > 1:
        shards = metrics.get("loop_metrics", [])
        if len(shards) != loops:
            bad.append(
                f"{loops} loops requested but {len(shards)} reported — "
                f"a silent single-loop fallback"
            )
        elif metrics.get("fleet", 0) >= 8 * loops and any(
            s["conns"] == 0 and s["handoff_in"] == 0 for s in shards
        ):
            bad.append(
                f"a shard carried no connections at fleet "
                f"{metrics['fleet']}: partitioning is not spreading "
                f"({shards})"
            )
    return bad


# ---------------------------------------------------------------------------
# rolled scenario (ISSUE 14): roll-budget chunking, paired A/B


async def _instant_roll_miner(
    port: int, params: Params, *, binary: bool = True,
    beacon_every: int = 25, sent: Optional[dict] = None,
) -> None:
    """An instant miner that speaks the roll dialect: Join with
    ``roll=True``, cache Setup templates, and settle every Assign AND
    RollAssign immediately with the ``found=False, MIN_UNTRACKED``
    exhaustion sentinel (the fast-path "swept, no winner, min
    untracked" claim the coordinator's verifier accepts for targeted
    modes). Jobs therefore finish by exhaustion and the run measures
    pure dispatch accounting — no mining, no host hashing.

    Every ``beacon_every``-th RollAssign additionally ships one
    mid-chunk :class:`Beacon` (settled prefix = the chunk's lower
    half) BEFORE its final Result, so the run books the beacon path's
    real verify/journal/advance cost at a known <= 1/beacon_every
    cadence. ``sent['n']`` counts beacons written, so a check can pin
    accepted == sent (none dropped as stale/unverifiable)."""
    w = await LspClient.connect("127.0.0.1", port, params)
    w.write(encode_msg(Join(
        backend="instant", lanes=1, codec="bin" if binary else "json",
        roll=True,
    )))
    templates = {}
    speak = {"binary": False}
    rolls = {"n": 0}

    def settle(job_id, chunk_id, lower, upper, mode) -> None:
        w.write(encode_msg(Result(
            job_id, mode, nonce=lower, hash_value=MIN_UNTRACKED,
            found=False, searched=upper - lower + 1, chunk_id=chunk_id,
        ), binary=speak["binary"]))

    def handle(raw) -> None:
        if binary and not speak["binary"] and payload_is_binary(raw):
            speak["binary"] = True
        msg = decode_msg(raw)
        if isinstance(msg, Setup):
            templates[msg.request.job_id] = msg.request
        elif isinstance(msg, Cancel):
            templates.pop(msg.job_id, None)
        elif isinstance(msg, Assign):
            req = templates.get(msg.job_id)
            if req is not None:
                settle(msg.job_id, msg.chunk_id, msg.lower, msg.upper,
                       req.mode)
        elif isinstance(msg, RollAssign):
            req = templates.get(msg.job_id)
            if req is None:
                return
            lower, upper = chain.roll_span(
                msg.extranonce0, msg.count, req.nonce_bits
            )
            rolls["n"] += 1
            if rolls["n"] % beacon_every == 0:
                mid = lower + (upper - lower) // 2
                w.write(encode_msg(Beacon(
                    msg.job_id, msg.chunk_id, mid, lower, MIN_UNTRACKED,
                ), binary=speak["binary"]))
                if sent is not None:
                    sent["n"] += 1
            settle(msg.job_id, msg.chunk_id, lower, upper, req.mode)

    try:
        while True:
            raw = await w.read()
            while raw is not None:
                handle(raw)
                raw = (
                    w.read_nowait() if hasattr(w, "read_nowait") else None
                )
    except LspConnectionLost:
        pass
    finally:
        await w.close(drain_timeout=0.2)


async def _rolled_client(port: int, params: Params, cid: int,
                         upper: int, counter: dict,
                         nonce_bits: int = 32) -> None:
    """Closed-loop client submitting production-shaped rolled TARGET
    jobs: unreachable ``target=1`` (no instant-fleet sentinel can ever
    claim a win), so every job runs to exhaustion and its answer is
    the coordinator's own coverage bookkeeping."""
    c = await LspClient.connect("127.0.0.1", port, params)
    try:
        jid = 0
        while True:
            jid += 1
            c.write(encode_msg(Request(
                job_id=jid, mode=PowMode.TARGET, lower=0, upper=upper,
                header=bytes(80), target=1,
                coinbase_prefix=b"loadgen-roll-%d" % cid,
                coinbase_suffix=b"-cb", extranonce_size=4,
                nonce_bits=nonce_bits,
            )))
            while True:
                msg = decode_msg(await c.read())
                if isinstance(msg, Result) and msg.job_id == jid:
                    break
            counter["jobs"] += 1
    except (LspConnectionLost, asyncio.CancelledError):
        pass
    finally:
        await c.close(drain_timeout=0.2)


async def _run_rolled_arm(
    n_miners: int, n_clients: int, duration: float, *,
    chunk_size: int, roll_budget: int, segments: int,
    beacon_every: int, binary: bool, pipeline_depth: int,
    nonce_bits: int = 32, warmup: float = 0.4,
) -> dict:
    """One arm of the rolled A/B: a real coordinator with the given
    ``roll_budget`` (0 = the global-index-chunk baseline) under a
    roll-capable instant fleet and rolled closed-loop clients. Reports
    control messages and wire bytes NORMALIZED per settled extranonce
    SEGMENT (2^nonce_bits indices — 2^32 in production), which is what
    makes the two arms comparable: at ``nonce_bits=32`` the baseline
    settles a fraction of a segment per second at ``chunk_size``
    granularity while the rolled arm settles thousands."""
    coord = await make_coordinator(
        params=FAST, chunk_size=chunk_size, binary_codec=binary,
        pipeline_depth=pipeline_depth, roll_budget=roll_budget,
    )
    serve = asyncio.ensure_future(coord.serve())
    lost = {"n": 0}
    _hook_lost_events(coord, lost)
    sent = {"n": 0}
    miners = [
        asyncio.ensure_future(_instant_roll_miner(
            coord.port, FAST, binary=binary, beacon_every=beacon_every,
            sent=sent,
        ))
        for _ in range(n_miners)
    ]
    counter = {"jobs": 0}
    upper = segments * (1 << nonce_bits) - 1
    clients = [
        asyncio.ensure_future(
            _rolled_client(coord.port, FAST, i, upper, counter,
                           nonce_bits=nonce_bits)
        )
        for i in range(n_clients)
    ]
    try:
        await asyncio.sleep(warmup)
        t0 = time.monotonic()
        stats0 = dict(coord.stats)
        chunks0 = coord._next_chunk_id
        _, _, bytes0 = _ep_totals(coord)
        codec0 = dict(codec_stats)
        jobs0, sent0 = counter["jobs"], sent["n"]
        await asyncio.sleep(duration)
        dt = time.monotonic() - t0
        stats1 = coord.stats
        hashes = stats1["hashes"] - stats0["hashes"]
        results = (
            stats1["results_accepted"] - stats0["results_accepted"]
        )
        beacons = (
            stats1["beacons_accepted"] - stats0["beacons_accepted"]
        )
        _, _, bytes1 = _ep_totals(coord)
        msgs = sum(
            codec_stats[k] - codec0[k]
            for k in ("json_encoded", "json_decoded",
                      "binary_encoded", "binary_decoded")
        )
        # work unit: one full 2^nonce_bits extranonce segment
        units = hashes / float(1 << nonce_bits)
        return {
            "roll_budget": roll_budget,
            "duration_s": round(dt, 3),
            "results_per_s": round(results / dt, 1),
            "jobs_completed": counter["jobs"] - jobs0,
            "assigns": coord._next_chunk_id - chunks0,
            "chunks_roll_dispatched": (
                stats1["chunks_roll_dispatched"]
                - stats0["chunks_roll_dispatched"]
            ),
            "beacons_sent": sent["n"] - sent0,
            "beacons_accepted": beacons,
            "beacon_overhead_pct": (
                round(100.0 * beacons / results, 2) if results else 0.0
            ),
            "results_rejected": (
                stats1["results_rejected"] - stats0["results_rejected"]
            ),
            "miners_lost": lost["n"],
            "indices_settled": hashes,
            "segments_settled": round(units, 4),
            "ctrl_msgs": msgs,
            "ctrl_msgs_per_segment": (
                round(msgs / units, 3) if units else 0.0
            ),
            "wire_bytes": bytes1 - bytes0,
            "wire_bytes_per_segment": (
                round((bytes1 - bytes0) / units, 1) if units else 0.0
            ),
        }
    finally:
        for t in clients + miners:
            t.cancel()
        await asyncio.gather(*clients, *miners, return_exceptions=True)
        serve.cancel()
        await asyncio.gather(serve, return_exceptions=True)
        await coord.close()


async def run_rolled(
    n_miners: int = 8,
    n_clients: int = 4,
    duration: float = 1.5,
    *,
    chunk_size: int = 16384,
    roll_budget: int = 16,
    segments: int = 64,
    beacon_every: int = 25,
    binary: bool = True,
    pipeline_depth: int = 2,
    nonce_bits: int = 32,
) -> dict:
    """Paired A/B of roll-budget chunking (ISSUE 14): the SAME fleet,
    clients, and 64-segment rolled job shape, first with
    ``roll_budget`` armed and then with the global-index-chunk
    baseline (``roll_budget=0``) — one invocation, one ratio. The
    headline ``collapse_ratio_msgs`` is control messages per settled
    segment (2^nonce_bits indices; production is ``nonce_bits=32``),
    baseline over rolled; the rolled arm also books beacon cost at a
    1/``beacon_every`` cadence, so the overhead stays on the ledger.
    The normalization is conservative toward the rolled arm: its
    completed jobs keep paying Setup + client answer traffic while
    the baseline's never-finishing jobs pay almost none."""
    roll = await _run_rolled_arm(
        n_miners, n_clients, duration, chunk_size=chunk_size,
        roll_budget=roll_budget, segments=segments,
        beacon_every=beacon_every, binary=binary,
        pipeline_depth=pipeline_depth, nonce_bits=nonce_bits,
    )
    classic = await _run_rolled_arm(
        n_miners, n_clients, duration, chunk_size=chunk_size,
        roll_budget=0, segments=segments, beacon_every=beacon_every,
        binary=binary, pipeline_depth=pipeline_depth,
        nonce_bits=nonce_bits,
    )

    def ratio(key: str) -> float:
        denom = roll[key]
        return round(classic[key] / denom, 1) if denom else 0.0

    return {
        "nonce_bits": nonce_bits,
        "segments_per_job": segments,
        "chunk_size": chunk_size,
        "codec": "binary" if binary else "json",
        "collapse_ratio_msgs": ratio("ctrl_msgs_per_segment"),
        "collapse_ratio_bytes": ratio("wire_bytes_per_segment"),
        "roll": roll,
        "classic": classic,
    }


def rolled_check(metrics: dict) -> list:
    """The rolled scenario IS its assertions (like chaos/zipf): the
    dispatch-count collapse must demonstrably ENGAGE — a silent
    fallback to classic Assigns would pass every liveness check while
    measuring nothing — and hold the ISSUE 14 bar of >= 1000x fewer
    control messages per 2^32-index segment at beacon overhead <= 5%.
    The collapse scales with the segment size, so shrunken
    ``nonce_bits`` runs (the bench's 2^20 leg) gate at a
    proportionally lower floor."""
    bad = []
    roll, classic = metrics["roll"], metrics["classic"]
    for arm, m in (("roll", roll), ("classic", classic)):
        if m["indices_settled"] <= 0:
            bad.append(f"{arm} arm settled no indices: {m}")
        if m["miners_lost"] > 0:
            bad.append(f"{arm} arm lost {m['miners_lost']} miner(s)")
        if m["results_rejected"] > 0:
            bad.append(
                f"{arm} arm rejected {m['results_rejected']} result(s)"
            )
    if roll["chunks_roll_dispatched"] <= 0:
        bad.append(
            "roll budget configured but no RollAssign ever dispatched "
            "— silent fallback to classic chunking"
        )
    if classic["chunks_roll_dispatched"] > 0:
        bad.append(
            "baseline arm dispatched RollAssigns at roll_budget=0 — "
            "the arms are not isolated"
        )
    if roll["beacons_accepted"] <= 0:
        bad.append("rolled arm produced no accepted beacons")
    if roll["beacons_accepted"] != roll["beacons_sent"]:
        bad.append(
            f"beacons sent {roll['beacons_sent']} != accepted "
            f"{roll['beacons_accepted']}: some were dropped as "
            f"stale/unverifiable"
        )
    if roll["beacon_overhead_pct"] > 5.0:
        bad.append(
            f"beacon overhead {roll['beacon_overhead_pct']}% of "
            f"results/s exceeds the 5% budget"
        )
    floor = 1000.0 if metrics["nonce_bits"] >= 32 else 100.0
    if metrics["collapse_ratio_msgs"] < floor:
        bad.append(
            f"control-message collapse {metrics['collapse_ratio_msgs']}x "
            f"< {floor}x per 2^{metrics['nonce_bits']}-index segment "
            f"(roll {roll['ctrl_msgs_per_segment']} vs classic "
            f"{classic['ctrl_msgs_per_segment']})"
        )
    return bad


# ---------------------------------------------------------------------------
# crash scenario (ISSUE 3): kill the coordinator mid-burst, recover
# ---------------------------------------------------------------------------

async def _durable_client_loop(
    ports, params: Params, cid: int, upper: int, ledger: dict,
    *, verify: bool = False,
) -> None:
    """Closed-loop client that survives coordinator restarts: one LSP
    connection reused across jobs; on loss it redials with jittered
    backoff and RE-SUBMITS the in-flight request under its durable
    client_key and original job_id (the coordinator deduplicates).
    Every Result received is booked in ``ledger['answers']`` keyed by
    (cid, job_id) — the exactly-once evidence the crash metrics read.
    ``ports`` may be a list (failover address rotation, like the
    resilient miners). ``verify=True`` spot-checks every awaited answer
    (``toy_hash(data, nonce) == hash_value``) and books mismatches in
    ``ledger['poisoned']`` — the byzantine-containment evidence: a
    forged Result that reached a client."""
    import random as _random

    from tpuminter.replication import dial_patience

    if isinstance(ports, int):
        ports = [ports]
    rng = _random.Random(1000 + cid)
    ckey = f"loadgen-{cid}"
    answers = ledger["answers"]
    jid = 0
    attempt = 0
    pending: Optional[Request] = None
    client: Optional[LspClient] = None
    delays = jittered_backoff(0.05, 1.0, rng)
    try:
        while True:
            if client is None:
                port = ports[attempt % len(ports)]
                attempt += 1
                try:
                    client = await LspClient.connect(
                        "127.0.0.1", port, params,
                        connect_epochs=dial_patience(ports),
                    )
                    delays = jittered_backoff(0.05, 1.0, rng)
                except LspConnectError:
                    await asyncio.sleep(next(delays))
                    continue
                if pending is not None:
                    # same client_key + job_id: the restarted
                    # coordinator re-binds or answers from its journal
                    client.write(encode_msg(pending))
            try:
                if pending is None:
                    if ledger.get("stop"):
                        return
                    jid += 1
                    pending = Request(
                        job_id=jid, mode=PowMode.MIN, lower=0, upper=upper,
                        data=b"crash-%d-%d" % (cid, jid), client_key=ckey,
                    )
                    ledger["submitted"] += 1
                    client.write(encode_msg(pending))
                msg = decode_msg(await client.read())
                if isinstance(msg, Result):
                    # book EVERY Result (duplicate detection), not just
                    # the awaited one
                    key = (cid, msg.job_id)
                    answers[key] = answers.get(key, 0) + 1
                    if pending is not None and msg.job_id == pending.job_id:
                        if verify and (
                            not msg.found
                            or chain.toy_hash(pending.data, msg.nonce)
                            != msg.hash_value
                        ):
                            ledger["poisoned"] = (
                                ledger.get("poisoned", 0) + 1
                            )
                        pending = None
                elif (
                    isinstance(msg, Refuse)
                    and msg.retry_after_ms > 0
                    and pending is not None
                    and msg.job_id == pending.job_id
                ):
                    # admission backpressure (ISSUE 13): the coordinator
                    # said "not now, retry in N ms" — wait it out with
                    # 0.5–1.5x jitter (so a refused cohort does not
                    # re-stampede in phase) and re-submit the SAME
                    # request; a Refuse delays, it never loses
                    ledger["retry_after_honored"] = (
                        ledger.get("retry_after_honored", 0) + 1
                    )
                    await asyncio.sleep(
                        msg.retry_after_ms / 1000.0 * (0.5 + rng.random())
                    )
                    client.write(encode_msg(pending))
            except LspConnectionLost:
                await client.close(drain_timeout=0.1)
                client = None
                await asyncio.sleep(next(delays))
    finally:
        ledger["unanswered"] = ledger.get("unanswered", 0) + (
            1 if pending is not None else 0
        )
        if client is not None:
            await client.close(drain_timeout=0.2)


async def run_crash(
    n_miners: int = 8,
    n_clients: int = 2,
    *,
    journal_path: Optional[str] = None,
    chunk_size: int = 1024,
    chunks_per_job: Optional[int] = None,
    params: Params = FAST,
    pre: float = 1.5,
    post: float = 3.0,
    drain: float = 10.0,
    binary: bool = True,
    pipeline_depth: int = 2,
    loops: int = 1,
    io_batch=None,
    journal_mode: str = "writer",
    loop_affinity: bool = False,
) -> dict:
    """The crash-recovery drill: journaled coordinator + resilient
    fleet; kill the coordinator mid-burst (socket closed, no drain,
    buffered journal records lost — in-process ``kill -9``); restart it
    from the journal on the SAME port; let the fleet resume on its own.

    Returns the exactly-once ledger plus recovery latency metrics (see
    the module docstring). ``pre``/``post`` bound the burst before and
    after the kill; ``drain`` bounds the final wait for in-flight
    requests to answer (anything still unanswered then counts lost).
    """
    import shutil

    affinity_was_on = affinity.enabled()
    if loop_affinity:
        # runtime race detector (tpuminter.analysis.affinity): stamp
        # coordinator/journal/replication objects and record every
        # cross-loop mutation across the whole drill
        affinity.reset()
        affinity.enable()
    tmpdir = None
    if journal_path is None:
        tmpdir = tempfile.mkdtemp(prefix="tpuminter-loadgen-")
        journal_path = os.path.join(tmpdir, "coordinator.wal")
    coord = await make_coordinator(
        params=params, chunk_size=chunk_size, recover_from=journal_path,
        binary_codec=binary, pipeline_depth=pipeline_depth,
        loops=loops, io_batch=io_batch, journal_mode=journal_mode,
    )
    port = coord.port
    serve = asyncio.ensure_future(coord.serve())
    state = {"coord": coord, "carried": 0}
    t0 = time.monotonic()
    buckets = []  # (t_rel, results_accepted delta) per 100 ms

    async def sampler() -> None:
        last = 0
        while True:
            await asyncio.sleep(0.1)
            c = state["coord"]
            cur = state["carried"] + (
                c.stats["results_accepted"] if c is not None else 0
            )
            buckets.append((time.monotonic() - t0, cur - last))
            last = cur

    if chunks_per_job is None:
        chunks_per_job = max(8, 2 * n_miners)
    upper = chunk_size * chunks_per_job - 1
    ledger = {"answers": {}, "submitted": 0, "stop": False}
    miners = [
        asyncio.ensure_future(
            _resilient_instant_miner(port, params, i, binary=binary)
        )
        for i in range(n_miners)
    ]
    clients = [
        asyncio.ensure_future(
            _durable_client_loop(port, params, i, upper, ledger)
        )
        for i in range(n_clients)
    ]
    sample_task = asyncio.ensure_future(sampler())
    metrics: dict = {
        "fleet": n_miners, "clients": n_clients,
        "chunk_size": chunk_size, "loops": loops,
    }
    try:
        await asyncio.sleep(pre)
        # -- kill -9 ----------------------------------------------------
        t_crash = time.monotonic() - t0
        state["carried"] += coord.stats["results_accepted"]
        state["coord"] = None
        serve.cancel()
        await asyncio.gather(serve, return_exceptions=True)
        # a real kill -9 has the OS release the port at process exit,
        # before any restart could bind — wait it out, then bind it
        await _crash_coordinator(coord)
        pre_results = state["carried"]
        # -- restart from the journal on the same port -------------------
        t_restart0 = time.monotonic()
        for attempt in range(50):
            try:
                coord = await make_coordinator(
                    port, params=params, chunk_size=chunk_size,
                    recover_from=journal_path,
                    binary_codec=binary, pipeline_depth=pipeline_depth,
                    loops=loops, io_batch=io_batch,
                    journal_mode=journal_mode,
                )
                break
            except OSError:
                if attempt == 49:
                    raise
                await asyncio.sleep(0.02)
        metrics["recovered_jobs"] = len(coord._jobs)
        metrics["recovered_winners"] = len(coord._winners)
        metrics["replay_ms"] = round(
            (time.monotonic() - t_restart0) * 1e3, 3
        )
        serve = asyncio.ensure_future(coord.serve())
        state["coord"] = coord
        # first assign after restart = the moment a redialed miner got
        # work again (includes the fleet's backoff, the re-Joins, and
        # the re-dispatch of recovered/re-submitted jobs)
        while coord._next_chunk_id == 1:
            if time.monotonic() - t_restart0 > max(post, 10.0):
                break
            await asyncio.sleep(0.001)
        metrics["restart_to_first_assign_ms"] = round(
            (time.monotonic() - t_restart0) * 1e3, 3
        )
        await asyncio.sleep(post)
        # -- drain: no new jobs; in-flight ones get `drain` s to answer --
        ledger["stop"] = True
        done, pending_tasks = await asyncio.wait(clients, timeout=drain)
        for t in pending_tasks:
            t.cancel()
        await asyncio.gather(*clients, return_exceptions=True)
        # -- ledger -----------------------------------------------------
        answers = ledger["answers"]
        metrics["submitted"] = ledger["submitted"]
        metrics["answered"] = sum(1 for c in answers.values() if c >= 1)
        metrics["answers_duplicated"] = sum(
            c - 1 for c in answers.values() if c > 1
        )
        # a request is lost only if it was submitted and never answered
        # even after the drain window (clients that timed out above)
        metrics["answers_lost"] = ledger["submitted"] - metrics["answered"]
        metrics["results_accepted_pre_crash"] = pre_results
        metrics["results_accepted_total"] = state["carried"] + (
            coord.stats["results_accepted"]
        )
        # -- dip window: crash → results/s back to half its pre rate ----
        pre_rates = [d for (t, d) in buckets if t_crash - 1.0 <= t < t_crash]
        pre_mean = (sum(pre_rates) / len(pre_rates)) if pre_rates else 0.0
        dip_end = next(
            (t for (t, d) in buckets
             if t > t_crash and pre_mean > 0 and d >= 0.5 * pre_mean),
            None,
        )
        metrics["dip_window_ms"] = (
            round((dip_end - t_crash) * 1e3, 1) if dip_end is not None
            else round(post * 1e3, 1)
        )
        if coord._journal is not None:
            metrics["journal"] = dict(coord._journal.stats)
        return metrics
    finally:
        sample_task.cancel()
        for t in clients + miners:
            t.cancel()
        await asyncio.gather(
            sample_task, *clients, *miners, return_exceptions=True
        )
        serve.cancel()
        await asyncio.gather(serve, return_exceptions=True)
        if state["coord"] is not None:
            await state["coord"].close()
        if loop_affinity:
            # harvest after teardown so close-path mutations count too
            vio = affinity.violations()
            try:
                metrics["affinity_violations"] = len(vio)
                metrics["affinity_sample"] = vio[:8]
            except NameError:
                pass  # drill died before the metrics dict existed
            if not affinity_was_on:
                affinity.disable()
        if tmpdir is not None:
            shutil.rmtree(tmpdir, ignore_errors=True)


def crash_check(metrics: dict) -> list:
    """The crash scenario's pass/fail assertions (tier-1 gate shape,
    like :func:`smoke_check`): the fleet resumed without manual
    intervention and the answer ledger is exactly-once."""
    bad = []
    if metrics.get("answered", 0) <= 0:
        bad.append(f"no requests answered at all: {metrics}")
    if metrics.get("answers_duplicated", 0) > 0:
        bad.append(
            f"{metrics['answers_duplicated']} duplicate answer(s): a "
            f"client saw the same request id answered twice"
        )
    if metrics.get("answers_lost", 0) > 0:
        bad.append(
            f"{metrics['answers_lost']} request(s) never answered "
            f"despite the drain window"
        )
    if metrics.get("restart_to_first_assign_ms", 1e9) > 10_000:
        bad.append(
            "fleet did not resume within 10 s of the restart: "
            f"{metrics.get('restart_to_first_assign_ms')} ms"
        )
    if metrics.get("affinity_violations", 0) > 0:
        bad.append(
            f"{metrics['affinity_violations']} cross-loop mutation(s) "
            f"caught by the runtime affinity detector: "
            f"{metrics.get('affinity_sample')}"
        )
    return bad


# ---------------------------------------------------------------------------
# multi-process scenario (ISSUE 19): one OS process per shard
# ---------------------------------------------------------------------------

async def _dial_shard(port: int, want: int, procs: int, params: Params):
    """Redial until the client's ephemeral source port hashes to shard
    ``want`` — the drills need to choose which PROCESS owns the
    connection. Hash the address the SERVER sees (loopback), not the
    0.0.0.0 bind address getsockname reports."""
    from tpuminter.multiloop import shard_of

    for _ in range(128):
        c = await LspClient.connect("127.0.0.1", port, params)
        addr = ("127.0.0.1", c._endpoint.local_addr[1])
        if shard_of(addr, procs) == want:
            return c
        await c.close(drain_timeout=0.1)
    raise RuntimeError(f"could not land a connection on shard {want}")


async def _drain_results(client, *, first_timeout: float,
                         dup_window: float = 2.0) -> list:
    """Collect every Result on ``client`` until silence: the drills
    count answers, so the read keeps going for ``dup_window`` after the
    first one — a duplicate that was going to arrive, arrives."""
    answers = []
    timeout = first_timeout
    try:
        while True:
            msg = decode_msg(await asyncio.wait_for(client.read(), timeout))
            if isinstance(msg, Result):
                answers.append(msg)
                timeout = dup_window
    except asyncio.TimeoutError:
        pass
    return answers


async def run_multiproc(
    n_miners: int = 8,
    n_clients: int = 4,
    duration: float = 1.5,
    *,
    procs: int = 2,
    chunk_size: int = 1024,
    chunks_per_job: Optional[int] = None,
    params: Params = FAST,
    warmup: float = 0.5,
    journal_path: Optional[str] = None,
    quota_burst: int = 6,
    drills: bool = True,
) -> dict:
    """The multi-process drill suite (ISSUE 19): throughput phase under
    the full fleet, then — with ``drills`` — the two cross-shard
    correctness gates the issue names, each against a fresh
    incarnation:

    1. **rebind drill**: a durable job LIVE at kill -9 recovers on its
       home shard process; the client's re-submit lands on a FOREIGN
       shard process and must settle exactly once, answered across the
       seam (registry consult → park → home-shard re-bind → answer
       frame), never re-mined into a second answer.
    2. **quota drill**: one tenant ckey alternating submissions across
       two shard processes gets ONE budget — cumulative-counter gossip
       keeps total admissions at ``quota_burst`` (±1 for one in-flight
       gossip datagram), where unshared buckets would admit 2x.

    Unlike :func:`run_load` nothing here can introspect coordinator
    internals — every shard is another PROCESS — so the ledgers are
    harness-side (the clients book every Result they see) and the
    per-shard counters arrive over the supervisor's control channel."""
    import shutil

    from tpuminter.multiproc import MultiProcCoordinator

    tmpdir = None
    if journal_path is None and drills:
        tmpdir = tempfile.mkdtemp(prefix="tpuminter-multiproc-")
        journal_path = os.path.join(tmpdir, "coordinator.wal")

    try:
        cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        cores = os.cpu_count() or 1

    metrics: dict = {
        "procs": procs, "fleet": n_miners, "clients": n_clients,
        "cores_available": cores,
    }

    # -- phase 1: throughput under the full fleet ------------------------
    coord = await MultiProcCoordinator.create(
        0, procs=procs, params=params, chunk_size=chunk_size,
    )
    if chunks_per_job is None:
        chunks_per_job = max(8, 4 * n_miners)
    upper = chunk_size * chunks_per_job - 1
    counter = {"jobs": 0, "dup_answers": 0}
    miners = [
        asyncio.ensure_future(_instant_miner(coord.port, params))
        for _ in range(n_miners)
    ]
    clients = [
        asyncio.ensure_future(
            _client_loop(coord.port, params, i, upper, counter)
        )
        for i in range(n_clients)
    ]
    try:
        await asyncio.sleep(warmup)
        before = await coord.stats_all()
        jobs0, dups0 = counter["jobs"], counter["dup_answers"]
        t0 = time.monotonic()
        await asyncio.sleep(duration)
        dt = time.monotonic() - t0
        after = await coord.stats_all()
        # a miner whose task already finished was disconnected mid-run
        # (the harness-side stand-in for run_load's loss-event hook:
        # _instant_miner only returns when its connection is lost)
        metrics["miners_lost"] = sum(1 for m in miners if m.done())
        metrics["dup_answers"] = counter["dup_answers"] - dups0

        def _results(snap: dict) -> int:
            st = snap.get("stats", {})
            return (st.get("results_accepted", 0)
                    + st.get("results_rejected", 0))

        b = {s["shard"]: s for s in before}
        shard_results = {
            s["shard"]: _results(s) - _results(b.get(s["shard"], {}))
            for s in after
        }
        metrics.update({
            "duration_s": round(dt, 3),
            "results_per_s": round(sum(shard_results.values()) / dt, 1),
            "jobs_per_s": round((counter["jobs"] - jobs0) / dt, 2),
            "steer_kernel": coord.steer_kernel,
            "shard_results": [shard_results.get(k, 0)
                              for k in range(procs)],
            "seam_fwd_in": sum(
                s.get("seam", {}).get("fwd_in", 0) for s in after
            ),
            "shards_replied": len(after),
        })
    finally:
        for t in clients + miners:
            t.cancel()
        await asyncio.gather(*clients, *miners, return_exceptions=True)
        await coord.close()

    if not drills:
        if tmpdir is not None:
            shutil.rmtree(tmpdir, ignore_errors=True)
        return metrics

    # -- phase 2: cross-shard rebind drill (live job through kill -9) ----
    # procs may be 1 (the A/B baseline): the drill needs two shards to
    # cross, so it pins the pair (0, 1) only when there are two
    home, foreign = (0, 1) if procs >= 2 else (0, 0)
    coord = await MultiProcCoordinator.create(
        0, procs=procs, params=params, chunk_size=chunk_size,
        recover_from=journal_path,
    )
    req = Request(
        job_id=11, mode=PowMode.MIN, lower=0, upper=upper,
        data=b"multiproc-rebind", client_key="multiproc-drill",
    )
    c = await _dial_shard(coord.port, home, procs, params)
    c.write(encode_msg(req))
    # no miners connected: the job stays LIVE; give the open+bind
    # records one tick-flush before the kill
    await asyncio.sleep(0.6)
    await c.close(drain_timeout=0.1)
    await coord.crash()

    coord = await MultiProcCoordinator.create(
        0, procs=procs, params=params, chunk_size=chunk_size,
        recover_from=journal_path,
    )
    miners = [
        asyncio.ensure_future(_instant_miner(coord.port, params))
        for _ in range(n_miners)
    ]
    try:
        await asyncio.sleep(warmup)
        c = await _dial_shard(coord.port, foreign, procs, params)
        c.write(encode_msg(req))
        answers = await _drain_results(c, first_timeout=15.0)
        await c.close(drain_timeout=0.1)
        snaps = await coord.stats_all()
        metrics.update({
            "rebind_settled": len(answers),
            "rebind_seam_honored": sum(
                s.get("stats", {}).get("seam_rebinds_honored", 0)
                for s in snaps
            ),
            "rebind_seam_sent": sum(
                s.get("seam", {}).get("rebinds_sent", 0) for s in snaps
            ),
        })
    finally:
        for t in miners:
            t.cancel()
        await asyncio.gather(*miners, return_exceptions=True)
        await coord.close()

    # -- phase 3: shared quota drill (one budget across processes) -------
    if procs >= 2 and quota_burst > 0:
        coord = await MultiProcCoordinator.create(
            0, procs=procs, params=params, chunk_size=chunk_size,
            quota_rate=0.001, quota_burst=quota_burst,
        )
        miners = [
            asyncio.ensure_future(_instant_miner(coord.port, params))
            for _ in range(n_miners)
        ]
        try:
            await asyncio.sleep(warmup)
            ca = await _dial_shard(coord.port, 0, procs, params)
            cb = await _dial_shard(coord.port, 1, procs, params)
            admitted = refused = 0
            for i in range(2 * quota_burst):
                qc = ca if i % 2 == 0 else cb
                qreq = Request(
                    job_id=i + 1, mode=PowMode.MIN, lower=0,
                    upper=chunk_size - 1, data=b"q-%d" % i,
                    client_key="multiproc-tenant",
                )
                qc.write(encode_msg(qreq))
                while True:
                    msg = decode_msg(
                        await asyncio.wait_for(qc.read(), 15.0)
                    )
                    if isinstance(msg, Refuse):
                        refused += 1
                        break
                    if (isinstance(msg, Result)
                            and msg.job_id == qreq.job_id):
                        admitted += 1
                        break
                # one loop tick of headroom so the admission gossip
                # lands before the next submission flips shards
                await asyncio.sleep(0.05)
            await ca.close(drain_timeout=0.1)
            await cb.close(drain_timeout=0.1)
            snaps = await coord.stats_all()
            metrics.update({
                "quota_burst": quota_burst,
                "quota_admitted": admitted,
                "quota_refused": refused,
                "quota_foreign_debits": sum(
                    s.get("stats", {}).get("quota_foreign_debits", 0)
                    for s in snaps
                ),
            })
        finally:
            for t in miners:
                t.cancel()
            await asyncio.gather(*miners, return_exceptions=True)
            await coord.close()

    if tmpdir is not None:
        shutil.rmtree(tmpdir, ignore_errors=True)
    return metrics


def multiproc_check(metrics: dict) -> list:
    """The multi-process gates (tier-1 shape, like
    :func:`smoke_check`): throughput with zero loss and zero duplicate
    answers, every shard process reporting, the rebind drill settling
    exactly once, and — when the quota drill ran — one shared budget."""
    bad = []
    if metrics.get("results_per_s", 0) <= 0:
        bad.append(f"no results at all: {metrics}")
    if metrics.get("dup_answers", 0) > 0:
        bad.append(
            f"{metrics['dup_answers']} duplicate answer(s) across the "
            f"shard processes"
        )
    if metrics.get("miners_lost", 0) > 0:
        bad.append(
            f"{metrics['miners_lost']} miner connection(s) lost on a "
            f"healthy loopback run"
        )
    if metrics.get("shards_replied") != metrics.get("procs"):
        bad.append(
            f"only {metrics.get('shards_replied')} of "
            f"{metrics.get('procs')} shard processes answered stats"
        )
    if "rebind_settled" in metrics and metrics["rebind_settled"] != 1:
        bad.append(
            f"rebind drill settled {metrics['rebind_settled']} times "
            f"(want exactly 1)"
        )
    if (metrics.get("procs", 0) >= 2
            and "rebind_seam_honored" in metrics
            and metrics["rebind_seam_honored"] < 1):
        bad.append("re-submit never crossed the rebind registry seam")
    if "quota_admitted" in metrics:
        burst = metrics.get("quota_burst", 0)
        if metrics["quota_admitted"] > burst + 1:
            bad.append(
                f"shared tenant admitted {metrics['quota_admitted']} "
                f"jobs across processes (budget {burst}): quota "
                f"buckets are not shared"
            )
    return bad


# ---------------------------------------------------------------------------
# chain-host scenario (ISSUE 18): a replica process hosting a standby chain
# ---------------------------------------------------------------------------

async def run_chain_host(
    hops: int,
    wal_dir: str,
    port_file: str,
    params: Params = FAST,
) -> None:
    """Host ``hops`` chained standbys in THIS process and serve until
    killed. The entry hop's port is written to ``port_file`` once the
    whole chain is listening; a primary in another process points
    ``replicate_to`` at it — the two-process topology the chain-
    replication bench measures, where none of the replica-side work
    (persist, shadow replay, re-ship) shares the primary's core."""
    from tpuminter.replication import ReplicationStandby

    chain_to = None
    standbys = []
    for hop in range(hops, 0, -1):  # tail hop first
        s = await ReplicationStandby.create(
            os.path.join(wal_dir, "hop%d.wal" % hop), params=params,
            chain_to=chain_to,
        )
        standbys.insert(0, (s, asyncio.ensure_future(s.run())))
        chain_to = [("127.0.0.1", s.port)]
    def publish_port(port: int) -> None:
        tmp = port_file + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(str(port))
        os.replace(tmp, port_file)  # atomic: never a torn port

    await asyncio.get_running_loop().run_in_executor(
        None, publish_port, standbys[0][0].port
    )
    try:
        await asyncio.Event().wait()
    finally:
        for s, task in standbys:
            task.cancel()
            await asyncio.gather(task, return_exceptions=True)
            await s.close()


# ---------------------------------------------------------------------------
# failover scenario (ISSUE 5): kill the primary machine, promote the standby
# ---------------------------------------------------------------------------

async def run_failover(
    n_miners: int = 8,
    n_clients: int = 2,
    *,
    chunk_size: int = 1024,
    chunks_per_job: Optional[int] = None,
    params: Params = FAST,
    pre: float = 1.5,
    post: float = 3.0,
    drain: float = 10.0,
    binary: bool = True,
    pipeline_depth: int = 2,
    replica_ack: bool = True,
    loops: int = 1,
    io_batch=None,
    loop_affinity: bool = False,
) -> dict:
    """The replicated-coordinator drill: primary journals AND ships its
    WAL to a live hot standby; mid-burst the primary machine "dies"
    (socket closed with no drain, journal crashed, shipping lane cut —
    and, unlike ``--scenario crash``, the primary's journal file is
    NEVER read again: the takeover runs exclusively on what was
    shipped). The standby detects the loss, promotes with a fenced
    epoch, and the address-listed fleet (miners rotating their redial,
    clients re-submitting under durable keys) lands on it unattended.

    Reported: ``detect_ms`` (kill → standby declares the primary
    lost), ``takeover_ms`` (promotion start → first chunk dispatched
    by the new coordinator), ``blackout_ms`` (kill → first dispatch,
    the end-to-end gap), ``dip_window_ms``, and the exactly-once
    answer ledger — every submitted request answered exactly once
    across the machine loss."""
    import shutil

    from tpuminter.replication import ReplicationStandby

    affinity_was_on = affinity.enabled()
    if loop_affinity:
        affinity.reset()
        affinity.enable()
    tmpdir = tempfile.mkdtemp(prefix="tpuminter-failover-")
    primary_wal = os.path.join(tmpdir, "primary.wal")
    standby_wal = os.path.join(tmpdir, "standby.wal")
    standby = await ReplicationStandby.create(standby_wal, params=params)
    standby_task = asyncio.ensure_future(standby.run())
    coord = await make_coordinator(
        params=params, chunk_size=chunk_size, recover_from=primary_wal,
        binary_codec=binary, pipeline_depth=pipeline_depth,
        replicate_to=[("127.0.0.1", standby.port)],
        replica_ack=replica_ack,
        loops=loops, io_batch=io_batch,
    )
    ports = [coord.port, standby.port]
    serve = asyncio.ensure_future(coord.serve())
    state = {"coord": coord, "carried": 0}
    t0 = time.monotonic()
    buckets = []  # (t_rel, results_accepted delta) per 100 ms

    async def sampler() -> None:
        last = 0
        while True:
            await asyncio.sleep(0.1)
            c = state["coord"]
            cur = state["carried"] + (
                c.stats["results_accepted"] if c is not None else 0
            )
            buckets.append((time.monotonic() - t0, cur - last))
            last = cur

    if chunks_per_job is None:
        chunks_per_job = max(8, 2 * n_miners)
    upper = chunk_size * chunks_per_job - 1
    ledger = {"answers": {}, "submitted": 0, "stop": False}
    miners = [
        asyncio.ensure_future(
            _resilient_instant_miner(ports, params, i, binary=binary)
        )
        for i in range(n_miners)
    ]
    clients = [
        asyncio.ensure_future(
            _durable_client_loop(ports, params, i, upper, ledger)
        )
        for i in range(n_clients)
    ]
    sample_task = asyncio.ensure_future(sampler())
    metrics: dict = {
        "fleet": n_miners, "clients": n_clients,
        "chunk_size": chunk_size, "replica_ack": replica_ack,
        "loops": loops,
    }
    coord2 = None
    serve2 = None
    try:
        await asyncio.sleep(pre)
        # shipping must have actually flowed pre-kill, or the drill
        # would silently measure an empty takeover
        metrics["replicated_records_pre_kill"] = (
            standby.stats["records_applied"]
        )
        metrics["replication_lag_bytes_at_kill"] = (
            coord._journal.size - standby.size
        )
        # -- the primary machine dies -----------------------------------
        t_crash = time.monotonic()
        metrics["t_crash_rel_s"] = round(t_crash - t0, 3)
        state["carried"] += coord.stats["results_accepted"]
        state["coord"] = None
        serve.cancel()
        await asyncio.gather(serve, return_exceptions=True)
        await _crash_coordinator(coord)
        pre_results = state["carried"]
        # -- the standby notices on its own (loss horizon) ---------------
        await asyncio.wait_for(
            standby.primary_lost.wait(),
            10 * params.epoch_limit * params.epoch_seconds,
        )
        t_detect = time.monotonic()
        metrics["detect_ms"] = round((t_detect - t_crash) * 1e3, 1)
        # -- fenced promotion: replay-free takeover ----------------------
        coord2 = await standby.promote(
            chunk_size=chunk_size, binary_codec=binary,
            pipeline_depth=pipeline_depth,
        )
        metrics["promote_ms"] = round(
            (time.monotonic() - t_detect) * 1e3, 3
        )
        metrics["promoted_epoch"] = coord2.boot_epoch
        metrics["recovered_jobs"] = len(coord2._jobs)
        metrics["recovered_winners"] = len(coord2._winners)
        serve2 = asyncio.ensure_future(coord2.serve())
        state["coord"] = coord2
        # takeover = promotion start → first chunk dispatched by the
        # new coordinator (includes the fleet's rotation + re-Joins)
        while coord2._next_chunk_id == 1:
            if time.monotonic() - t_detect > max(post, 10.0):
                break
            await asyncio.sleep(0.001)
        t_first = time.monotonic()
        metrics["takeover_ms"] = round((t_first - t_detect) * 1e3, 1)
        metrics["blackout_ms"] = round((t_first - t_crash) * 1e3, 1)
        await asyncio.sleep(post)
        # -- drain: no new jobs; in-flight ones get `drain` s to answer --
        ledger["stop"] = True
        done, pending_tasks = await asyncio.wait(clients, timeout=drain)
        for t in pending_tasks:
            t.cancel()
        await asyncio.gather(*clients, return_exceptions=True)
        # -- exactly-once ledger ----------------------------------------
        answers = ledger["answers"]
        metrics["submitted"] = ledger["submitted"]
        metrics["answered"] = sum(1 for c in answers.values() if c >= 1)
        metrics["answers_duplicated"] = sum(
            c - 1 for c in answers.values() if c > 1
        )
        metrics["answers_lost"] = ledger["submitted"] - metrics["answered"]
        metrics["results_accepted_pre_crash"] = pre_results
        metrics["results_accepted_total"] = state["carried"] + (
            coord2.stats["results_accepted"]
        )
        metrics["fenced_rejections"] = coord2.stats["replication_fenced"]
        # -- dip window: crash → results/s back to half its pre rate ----
        tc = t_crash - t0
        pre_rates = [d for (t, d) in buckets if tc - 1.0 <= t < tc]
        pre_mean = (sum(pre_rates) / len(pre_rates)) if pre_rates else 0.0
        dip_end = next(
            # t > tc + 0.15: the 100 ms bucket straddling the kill still
            # holds pre-crash results and must not read as "recovered"
            (t for (t, d) in buckets
             if t > tc + 0.15 and pre_mean > 0 and d >= 0.5 * pre_mean),
            None,
        )
        metrics["dip_window_ms"] = (
            round((dip_end - tc) * 1e3, 1) if dip_end is not None
            else round(post * 1e3, 1)
        )
        if coord2._journal is not None:
            metrics["journal"] = dict(coord2._journal.stats)
        return metrics
    finally:
        sample_task.cancel()
        standby_task.cancel()
        for t in clients + miners:
            t.cancel()
        await asyncio.gather(
            sample_task, standby_task, *clients, *miners,
            return_exceptions=True,
        )
        if serve2 is not None:
            serve2.cancel()
            await asyncio.gather(serve2, return_exceptions=True)
        if coord2 is not None:
            await coord2.close()
        elif not standby.promoted:
            await standby.close()
        if loop_affinity:
            vio = affinity.violations()
            try:
                metrics["affinity_violations"] = len(vio)
                metrics["affinity_sample"] = vio[:8]
            except NameError:
                pass  # drill died before the metrics dict existed
            if not affinity_was_on:
                affinity.disable()
        shutil.rmtree(tmpdir, ignore_errors=True)


def failover_check(metrics: dict, params: Params = FAST) -> list:
    """The failover drill's pass/fail assertions (the tier-1 gate
    shape): shipping actually flowed, the fleet landed on the promoted
    standby unattended, takeover stayed under one loss horizon, and
    the answer ledger is exactly-once across the machine loss."""
    horizon_ms = params.epoch_limit * params.epoch_millis
    bad = []
    if metrics.get("replicated_records_pre_kill", 0) <= 0:
        bad.append(
            "no records were replicated before the kill: the drill "
            "measured an empty takeover"
        )
    if metrics.get("answered", 0) <= 0:
        bad.append(f"no requests answered at all: {metrics}")
    if metrics.get("answers_duplicated", 0) > 0:
        bad.append(
            f"{metrics['answers_duplicated']} duplicate answer(s): a "
            f"client saw the same request id answered twice"
        )
    if metrics.get("answers_lost", 0) > 0:
        bad.append(
            f"{metrics['answers_lost']} request(s) never answered "
            f"despite the drain window"
        )
    if metrics.get("takeover_ms", 1e9) > horizon_ms:
        bad.append(
            f"takeover took {metrics.get('takeover_ms')} ms, over one "
            f"loss horizon ({horizon_ms} ms): the promoted standby did "
            f"not pick the fleet up promptly"
        )
    if metrics.get("affinity_violations", 0) > 0:
        bad.append(
            f"{metrics['affinity_violations']} cross-loop mutation(s) "
            f"caught by the runtime affinity detector: "
            f"{metrics.get('affinity_sample')}"
        )
    return bad


# ---------------------------------------------------------------------------
# workload scenario (ISSUE 15): the second workload through crash + failover
# ---------------------------------------------------------------------------

#: The pluggable-workload drill's job seed — every shape below derives
#: its exact expected answer from it locally, so the ledger checks
#: VALUES per fold, not just exactly-once delivery.
_WL_SEED = 0xD1CE


def _wl_shapes(upper: int, k: int = 4) -> list:
    """One submission template per fold discipline — ``(name, params
    bytes, checker, workload, upper)`` — each checker judging the
    decoded job-level accumulator against the locally-computed exact
    answer.

    ``fmatch`` ships twice: a guaranteed hit (threshold = the global
    minimum, so the first match IS the argmin and the early-cancel
    broadcast fires on every job) and a guaranteed dry scan (threshold
    0 — the objective is a splitmix64 draw; the precompute faults the
    drill if a zero ever lands in range). A matched first-match pins
    only (index, value): its job-level probe count depends on which
    in-flight chunks the cancel broadcast beat to the settle, by
    design. The dry one pins the full probe count — every index in the
    job provably scanned exactly once across failover AND crash."""
    from tpuminter.workloads import hashcore as hc

    vals = [hc.objective(_WL_SEED, i) for i in range(upper + 1)]
    lo_val, lo_idx = min((v, i) for i, v in enumerate(vals))
    if lo_val == 0:
        raise RuntimeError(
            "degenerate _WL_SEED: the dry first-match shape is impossible"
        )
    topk = sorted((v, i) for i, v in enumerate(vals))[:k]
    total = sum(vals)
    return [
        ("fmin", hc.pack_params("fmin", _WL_SEED),
         lambda acc: list(acc or ()) == [lo_val, lo_idx],
         "hashcore", upper),
        ("topk", hc.pack_params("topk", _WL_SEED, k=k),
         lambda acc: [tuple(p) for p in acc or ()] == topk,
         "hashcore", upper),
        ("fmatch_hit", hc.pack_params("fmatch", _WL_SEED, threshold=lo_val),
         lambda acc: acc is not None and acc[0] == lo_idx
         and acc[1] == lo_val, "hashcore", upper),
        ("fmatch_dry", hc.pack_params("fmatch", _WL_SEED, threshold=0),
         lambda acc: acc is not None and acc[0] is None
         and acc[2] == upper + 1, "hashcore", upper),
        ("fsum", hc.pack_params("fsum", _WL_SEED),
         lambda acc: list(acc or ()) == [total, upper + 1],
         "hashcore", upper),
    ]


def _dict_shapes(n: int = 3000) -> list:
    """Opaque-domain shapes for the workload drill (ISSUE 20): a
    ``dict`` catalog big enough that the coordinator MUST window it
    (``len(data) > dictsearch.WINDOW_BYTES`` → per-chunk Setups carry
    only each chunk's slice), pushed through the same crash + failover
    legs as the hashcore shapes. ``dict_fsum`` is the exactly-once
    probe in its sharpest form: its accumulator is ``[Σ score, count]``
    over the whole catalog, so a candidate scored zero times or twice
    — a lost window, a replayed settle double-fold — lands on the
    exact-value check, not just on delivery bookkeeping."""
    from tpuminter.workloads import dictsearch as ds

    seed = _WL_SEED & 0xFFFFFFFF
    cands = [b"cand-%06d-tpuminter" % i for i in range(n)]
    data_fmin = ds.pack_params("fmin", seed, cands)
    if len(data_fmin) <= ds.WINDOW_BYTES:
        raise RuntimeError(
            "dict drill catalog too small to exercise windowed dispatch"
        )
    scores = [ds.score(seed, c) for c in cands]
    lo_val, lo_idx = min((v, i) for i, v in enumerate(scores))
    total = sum(scores)
    return [
        ("dict_fmin", data_fmin,
         lambda acc: list(acc or ()) == [lo_val, lo_idx],
         "dict", n - 1),
        ("dict_fsum", ds.pack_params("fsum", seed, cands),
         lambda acc: list(acc or ()) == [total, n],
         "dict", n - 1),
    ]


async def _workload_client_loop(
    ports, params: Params, cid: int, shapes, ledger: dict,
) -> None:
    """The durable client loop (:func:`_durable_client_loop`) for
    pluggable-workload jobs: cycles through ``shapes`` (one Request
    template per fold discipline, staggered per client so a short
    drill still covers every fold), survives coordinator restarts
    under a durable client_key, books every answer in the exactly-once
    ledger AND checks each decoded accumulator against the shape's
    ground truth — a wrong value books ``ledger['answers_wrong']``, a
    strictly stronger claim than exactly-once delivery."""
    import random as _random

    from tpuminter import workloads
    from tpuminter.replication import dial_patience

    if isinstance(ports, int):
        ports = [ports]
    rng = _random.Random(3000 + cid)
    ckey = f"loadgen-wl-{cid}"
    answers = ledger["answers"]
    by_fold = ledger["by_fold"]
    jid = 0
    attempt = 0
    pending = None  # (Request, shape name, checker)
    client: Optional[LspClient] = None
    delays = jittered_backoff(0.05, 1.0, rng)
    try:
        while True:
            if client is None:
                port = ports[attempt % len(ports)]
                attempt += 1
                try:
                    client = await LspClient.connect(
                        "127.0.0.1", port, params,
                        connect_epochs=dial_patience(ports),
                    )
                    delays = jittered_backoff(0.05, 1.0, rng)
                except LspConnectError:
                    await asyncio.sleep(next(delays))
                    continue
                if pending is not None:
                    # same client_key + job_id: the restarted
                    # coordinator re-binds or answers from its journal
                    client.write(encode_msg(pending[0]))
            try:
                if pending is None:
                    if ledger.get("stop"):
                        return
                    name, data, check, wl, hi = (
                        shapes[(cid + jid) % len(shapes)]
                    )
                    jid += 1
                    req = Request(
                        job_id=jid, mode=PowMode.MIN, lower=0, upper=hi,
                        data=data, client_key=ckey, workload=wl,
                    )
                    pending = (req, name, check)
                    ledger["submitted"] += 1
                    client.write(encode_msg(req))
                msg = decode_msg(await client.read())
                if isinstance(msg, (Result, WorkResult)):
                    # book EVERY answer (duplicate detection), not just
                    # the awaited one
                    key = (cid, msg.job_id)
                    answers[key] = answers.get(key, 0) + 1
                    if (
                        pending is not None
                        and msg.job_id == pending[0].job_id
                    ):
                        req, name, check = pending
                        ok = isinstance(msg, WorkResult)
                        if ok:
                            try:
                                acc = workloads.fold_of(req).decode(
                                    bytes(msg.payload)
                                )
                            except ValueError:
                                ok = False
                            else:
                                ok = bool(check(acc))
                        if not ok:
                            ledger["answers_wrong"] = (
                                ledger.get("answers_wrong", 0) + 1
                            )
                            ledger.setdefault("wrong_sample", []).append(
                                name
                            )
                        by_fold[name] = by_fold.get(name, 0) + 1
                        pending = None
                elif (
                    isinstance(msg, Refuse)
                    and pending is not None
                    and msg.job_id == pending[0].job_id
                ):
                    if msg.retry_after_ms > 0:
                        # admission backpressure: wait it out, re-submit
                        await asyncio.sleep(
                            msg.retry_after_ms / 1000.0
                            * (0.5 + rng.random())
                        )
                        client.write(encode_msg(pending[0]))
                    else:
                        # fail-fast Refuse: the coordinator rejected the
                        # workload itself. Never expected here (hashcore
                        # is registered everywhere) — book it fatal
                        ledger["refused_fatal"] = (
                            ledger.get("refused_fatal", 0) + 1
                        )
                        pending = None
            except LspConnectionLost:
                await client.close(drain_timeout=0.1)
                client = None
                await asyncio.sleep(next(delays))
    finally:
        if client is not None:
            await client.close(drain_timeout=0.2)


async def run_workload(
    n_miners: int = 4,
    n_clients: int = 2,
    *,
    journal_path: Optional[str] = None,
    chunk_size: int = 1024,
    chunks_per_job: Optional[int] = None,
    params: Params = FAST,
    pre: float = 1.5,
    post: float = 2.0,
    drain: float = 10.0,
    binary: bool = True,
    pipeline_depth: int = 2,
    dev_lanes: bool = False,
) -> dict:
    """The pluggable-workload drill (ISSUE 15): REAL CpuMiner workers
    (the hashcore compute seam, not the instant-answer fleet) serve
    hashcore jobs across every registered fold discipline while the
    drill applies BOTH legs of the exactly-once story:

    - **worker failover**: one worker is killed abruptly mid-burst
      (its in-flight chunks die with it) — the coordinator requeues on
      the epoch horizon and the remaining fleet absorbs the work;
    - **coordinator crash**: in-process ``kill -9`` of the journaled
      coordinator, restart from the journal on the SAME port, fleet
      and clients resume unattended (the ``--scenario crash`` shape,
      now carrying workload settle records and wstate snapshots).

    The ledger is stricter than the mining drills': every answer's
    decoded accumulator is checked against the exact locally-computed
    answer for its fold, so a replayed settle, a lost partial, or a
    double-counted non-idempotent fold (fsum) surfaces as
    ``answers_wrong`` even when delivery itself was exactly-once.

    ``dev_lanes=True`` runs the SAME drill with the hashcore compute
    forced onto the u32-pair device-lane engine (ISSUE 17): the fleet's
    answers must be identical — the ledger's exact-value checks ARE the
    device/host equality gate, now under crash + failover — and the
    drill additionally proves the device engine actually ran
    (``dev_dispatches`` from ``ops.splitmix.counters``)."""
    import shutil

    from tpuminter.worker import CpuMiner, run_miner_reconnect
    from tpuminter.workloads import hashcore as _hc

    dev_prior = None
    dev_dispatch0 = 0
    if dev_lanes:
        # pinned small width = one cheap compile per variant per
        # process (the tests reuse the same shape); rows=2 keeps the
        # window smaller than a chunk so pipelining actually engages
        dev_prior = _hc.set_dev_lanes("on", width=512, rows=2)
        from tpuminter.ops import splitmix as _sm

        dev_dispatch0 = _sm.counters["dispatches"]

    tmpdir = None
    if journal_path is None:
        tmpdir = tempfile.mkdtemp(prefix="tpuminter-workload-")
        journal_path = os.path.join(tmpdir, "coordinator.wal")
    coord = await make_coordinator(
        params=params, chunk_size=chunk_size, recover_from=journal_path,
        binary_codec=binary, pipeline_depth=pipeline_depth,
    )
    port = coord.port
    serve = asyncio.ensure_future(coord.serve())
    if chunks_per_job is None:
        chunks_per_job = max(4, n_miners)
    upper = chunk_size * chunks_per_job - 1
    # the hashcore discipline cycle plus the opaque-domain dict shapes
    # (ISSUE 20): every client interleaves both families, so the crash
    # and failover legs below hit windowed dict catalogs too
    shapes = _wl_shapes(upper) + _dict_shapes()
    ledger = {"answers": {}, "by_fold": {}, "submitted": 0, "stop": False}

    def spawn_miner(i: int):
        import random as _random

        return asyncio.ensure_future(run_miner_reconnect(
            "127.0.0.1", port, CpuMiner(), params=params,
            base_backoff=0.05, max_backoff=1.0,
            rng=_random.Random(7000 + i), binary=binary,
        ))

    miners = [spawn_miner(i) for i in range(n_miners)]
    clients = [
        asyncio.ensure_future(
            _workload_client_loop(port, params, i, shapes, ledger)
        )
        for i in range(n_clients)
    ]
    metrics: dict = {
        "fleet": n_miners, "clients": n_clients, "chunk_size": chunk_size,
        "folds": [s[0] for s in shapes],
    }
    state = {"coord": coord}
    try:
        await asyncio.sleep(pre)
        # -- leg 1: worker failover (one worker dies, no goodbye) --------
        miners[0].cancel()
        await asyncio.gather(miners[0], return_exceptions=True)
        metrics["worker_killed"] = True
        await asyncio.sleep(max(0.5, pre / 2))
        # -- leg 2: kill -9 the coordinator mid-burst --------------------
        state["coord"] = None
        serve.cancel()
        await asyncio.gather(serve, return_exceptions=True)
        await _crash_coordinator(coord)
        # -- restart from the journal on the same port -------------------
        t_restart0 = time.monotonic()
        for att in range(50):
            try:
                coord = await make_coordinator(
                    port, params=params, chunk_size=chunk_size,
                    recover_from=journal_path, binary_codec=binary,
                    pipeline_depth=pipeline_depth,
                )
                break
            except OSError:
                if att == 49:
                    raise
                await asyncio.sleep(0.02)
        state["coord"] = coord
        metrics["recovered_jobs"] = len(coord._jobs)
        metrics["recovered_winners"] = len(coord._winners)
        metrics["replay_ms"] = round(
            (time.monotonic() - t_restart0) * 1e3, 3
        )
        serve = asyncio.ensure_future(coord.serve())
        while coord._next_chunk_id == 1:
            if time.monotonic() - t_restart0 > max(post, 10.0):
                break
            await asyncio.sleep(0.001)
        metrics["restart_to_first_assign_ms"] = round(
            (time.monotonic() - t_restart0) * 1e3, 3
        )
        await asyncio.sleep(post)
        # -- drain: no new jobs; in-flight ones get `drain` s to answer --
        ledger["stop"] = True
        done, pending_tasks = await asyncio.wait(clients, timeout=drain)
        for t in pending_tasks:
            t.cancel()
        await asyncio.gather(*clients, return_exceptions=True)
        # -- the per-fold exact-answer ledger ----------------------------
        answers = ledger["answers"]
        metrics["submitted"] = ledger["submitted"]
        metrics["answered"] = sum(1 for c in answers.values() if c >= 1)
        metrics["answers_duplicated"] = sum(
            c - 1 for c in answers.values() if c > 1
        )
        metrics["answers_lost"] = ledger["submitted"] - metrics["answered"]
        metrics["answers_wrong"] = ledger.get("answers_wrong", 0)
        metrics["wrong_sample"] = ledger.get("wrong_sample", [])[:8]
        metrics["refused_fatal"] = ledger.get("refused_fatal", 0)
        metrics["answered_by_fold"] = dict(
            sorted(ledger["by_fold"].items())
        )
        metrics["results_accepted"] = coord.stats["results_accepted"]
        metrics["results_rejected"] = coord.stats["results_rejected"]
        if coord._journal is not None:
            metrics["journal"] = dict(coord._journal.stats)
        metrics["dev_lanes"] = dev_lanes
        if dev_lanes:
            from tpuminter.ops import splitmix as _sm

            metrics["dev_dispatches"] = (
                _sm.counters["dispatches"] - dev_dispatch0
            )
        return metrics
    finally:
        if dev_prior is not None:
            _hc.set_dev_lanes(
                dev_prior["mode"], width=dev_prior["width"],
                rows=dev_prior["rows"], engine=dev_prior["engine"],
            )
        for t in clients + miners:
            t.cancel()
        await asyncio.gather(*clients, *miners, return_exceptions=True)
        serve.cancel()
        await asyncio.gather(serve, return_exceptions=True)
        if state["coord"] is not None:
            await state["coord"].close()
        if tmpdir is not None:
            shutil.rmtree(tmpdir, ignore_errors=True)


def workload_check(metrics: dict) -> list:
    """The workload drill's pass/fail assertions (tier-1 gate shape):
    jobs flowed across every fold discipline, every answer carried the
    exact locally-computed value for its fold, the ledger is
    exactly-once, and the fleet resumed after the crash unattended."""
    bad = []
    if metrics.get("answered", 0) <= 0:
        bad.append(f"no workload requests answered at all: {metrics}")
    folds = metrics.get("folds", [])
    # a client answers its shapes in cycle order, so `clients * folds`
    # total answers guarantee some client finished a full cycle
    if metrics.get("answered", 0) >= len(folds) * metrics.get("clients", 1):
        missing = [
            name for name in folds
            if metrics.get("answered_by_fold", {}).get(name, 0) <= 0
        ]
        if missing:
            bad.append(
                f"fold discipline(s) never answered despite a full "
                f"cycle's worth of answers: {missing}"
            )
    if metrics.get("answers_wrong", 0) > 0:
        bad.append(
            f"{metrics['answers_wrong']} answer(s) decoded to the WRONG "
            f"value for their fold (shapes: {metrics.get('wrong_sample')})"
            f" — a broken settle/replay, not a delivery failure"
        )
    if metrics.get("answers_duplicated", 0) > 0:
        bad.append(
            f"{metrics['answers_duplicated']} duplicate answer(s): a "
            f"client saw the same request id answered twice"
        )
    if metrics.get("answers_lost", 0) > 0:
        bad.append(
            f"{metrics['answers_lost']} request(s) never answered "
            f"despite the drain window"
        )
    if metrics.get("refused_fatal", 0) > 0:
        bad.append(
            f"{metrics['refused_fatal']} fail-fast Refuse(s) for a "
            f"registered workload"
        )
    if metrics.get("restart_to_first_assign_ms", 1e9) > 10_000:
        bad.append(
            "fleet did not resume within 10 s of the restart: "
            f"{metrics.get('restart_to_first_assign_ms')} ms"
        )
    if metrics.get("dev_lanes") and metrics.get("dev_dispatches", 0) <= 0:
        bad.append(
            "dev_lanes drill never dispatched a device-lane sweep — the "
            "answers above were computed by the host fallback, so the "
            "device/host equality claim is vacuous"
        )
    return bad


# ---------------------------------------------------------------------------
# compute-fabric scenarios (ISSUE 20): streaming folds, weighted-fair
# admission under a greedy flood, and the leak-hunting soak
# ---------------------------------------------------------------------------


async def run_stream(
    n_miners: int = 3,
    *,
    candidates: int = 60000,
    # small chunks on purpose: the drill must be CONTROL-PLANE-bound
    # (hundreds of journaled settles, each a potential Emit), not
    # compute-bound — a CPU fleet scores a smoke-sized catalog in tens
    # of milliseconds, faster than the killed client can rebind
    chunk_size: int = 32,
    params: Params = FAST,
    seed: int = 0,
    drain: float = 30.0,
) -> dict:
    """The streaming-fold drill (ISSUE 20): a windowed dict catalog is
    submitted with ``stream=True`` against a journaled coordinator
    (``emit_interval=0`` — every durable settle emits), the coordinator
    is ``kill -9``'d after the first partial lands and restarted from
    its journal on the same port, and the reconnecting client keeps
    collecting partials. Gates (``stream_check``):

    - ≥ 3 partials, and the RAW observed coverage sequence — across
      the crash, with NO client-side gating — is strictly increasing:
      a replayed coordinator's first Emit already covers at least
      everything it ever emitted before dying, because Emits are gated
      on journaled settles;
    - the streamed job's final payload is brute-force-exact AND
      bit-identical to a non-streaming submission of the same job.
    """
    import shutil
    from dataclasses import replace as dc_replace

    from tpuminter.client import submit
    from tpuminter.worker import CpuMiner, run_miner_reconnect
    from tpuminter import workloads
    from tpuminter.workloads import dictsearch as ds

    dseed = (0xFAB0 + seed) & 0xFFFFFFFF
    # short entries: the catalog must be big enough that the REPLAYED
    # incarnation still has well over a client-rebind's worth of
    # scoring left after the kill (a tiny catalog finishes before the
    # reconnecting client rebinds — the across-the-replay leg of the
    # gate would be vacuous), yet still fit one Request message
    cands = [b"s%07d" % i for i in range(candidates)]
    data = ds.pack_params("fmin", dseed, cands)
    if len(data) <= ds.WINDOW_BYTES:
        raise RuntimeError("stream catalog too small to window")
    scores = [ds.score(dseed, c) for c in cands]
    truth = min((v, i) for i, v in enumerate(scores))

    tmpdir = tempfile.mkdtemp(prefix="tpuminter-stream-")
    journal_path = os.path.join(tmpdir, "stream.wal")
    coord = await make_coordinator(
        params=params, chunk_size=chunk_size, recover_from=journal_path,
        emit_interval=0.0,
    )
    port = coord.port
    serve = asyncio.ensure_future(coord.serve())
    miners = [
        asyncio.ensure_future(run_miner_reconnect(
            "127.0.0.1", port, CpuMiner(), params=params,
            base_backoff=0.05, max_backoff=0.5,
        ))
        for _ in range(n_miners)
    ]
    partials: list = []  # (covered, total, t) — RAW, unfiltered
    t0 = time.monotonic()
    req = Request(
        job_id=1, mode=PowMode.MIN, lower=0, upper=candidates - 1,
        data=data, client_key="loadgen-stream", workload="dict",
        stream=True,
    )
    task = asyncio.ensure_future(submit(
        "127.0.0.1", port, req, params=params,
        client_key="loadgen-stream", reconnect=True,
        on_emit=lambda e: partials.append(
            (e.covered, e.total, time.monotonic() - t0)
        ),
    ))
    metrics: dict = {
        "candidates": candidates, "chunk_size": chunk_size,
        "fleet": n_miners, "seed": seed,
    }
    state = {"coord": coord}
    try:
        # wait for the first partial, then kill -9 mid-stream (but only
        # while real coverage remains — a crash after the final Result
        # would test nothing)
        while not partials and not task.done():
            if time.monotonic() - t0 > drain:
                break
            await asyncio.sleep(0.002)
        t_mark = time.monotonic() - t0
        metrics["crashed_mid_stream"] = bool(partials) and not task.done()
        if metrics["crashed_mid_stream"]:
            state["coord"] = None
            serve.cancel()
            await asyncio.gather(serve, return_exceptions=True)
            await _crash_coordinator(coord)
            for att in range(50):
                try:
                    coord = await make_coordinator(
                        port, params=params, chunk_size=chunk_size,
                        recover_from=journal_path, emit_interval=0.0,
                    )
                    break
                except OSError:
                    if att == 49:
                        raise
                    await asyncio.sleep(0.02)
            state["coord"] = coord
            serve = asyncio.ensure_future(coord.serve())
            # partials stamped after THIS point are from the replayed
            # incarnation (pre-crash datagrams still in the client's
            # socket buffer decode before the restart completes)
            t_mark = time.monotonic() - t0
        res = await asyncio.wait_for(task, drain)
        metrics["time_to_first_partial_ms"] = (
            round(partials[0][2] * 1e3, 3) if partials else None
        )
        metrics["time_to_final_ms"] = round(
            (time.monotonic() - t0) * 1e3, 3
        )
        covs = [c for c, _t, _s in partials]
        metrics["partials"] = len(covs)
        metrics["partials_pre_crash"] = sum(
            1 for _c, _t, s in partials if s <= t_mark
        )
        metrics["partials_post_crash"] = sum(
            1 for _c, _t, s in partials if s > t_mark
        )
        metrics["coverage_seq"] = covs[:64]
        metrics["monotone"] = all(a < b for a, b in zip(covs, covs[1:]))
        fold = workloads.fold_of(req)
        acc = fold.decode(bytes(res.payload))
        metrics["final_exact"] = list(acc) == list(truth)
        # the non-streaming arm: same catalog, fresh job id, no crash —
        # the final answer must be BIT-identical
        plain = await asyncio.wait_for(submit(
            "127.0.0.1", port, dc_replace(req, job_id=2, stream=False),
            params=params, client_key="loadgen-stream-plain",
        ), drain)
        metrics["bit_identical_final"] = (
            bytes(plain.payload) == bytes(res.payload)
        )
        # the RESTARTED coordinator's own counter: > 0 proves the
        # replayed incarnation emitted, independent of client timing
        metrics["emits_post_crash"] = state["coord"].stats["emits_sent"]
        return metrics
    finally:
        task.cancel()
        for t in miners:
            t.cancel()
        await asyncio.gather(task, *miners, return_exceptions=True)
        serve.cancel()
        await asyncio.gather(serve, return_exceptions=True)
        if state["coord"] is not None:
            await state["coord"].close()
        shutil.rmtree(tmpdir, ignore_errors=True)


def stream_check(metrics: dict) -> list:
    """The streaming gate (ISSUE 20): ≥ 3 monotone partials, a crash
    actually landed mid-stream, coverage never regressed across the
    replay, and the final answer is exact and bit-identical to the
    non-streaming run."""
    bad = []
    if metrics.get("partials", 0) < 3:
        bad.append(
            f"only {metrics.get('partials', 0)} partial(s) observed — "
            f"the streaming gate wants >= 3 before the final answer"
        )
    if not metrics.get("crashed_mid_stream"):
        bad.append(
            "the coordinator was never killed mid-stream (the job "
            "finished before the first partial was processed) — the "
            "replay-non-regression claim went untested"
        )
    elif (
        metrics.get("partials_post_crash", 0) < 1
        or metrics.get("emits_post_crash", 0) < 1
    ):
        bad.append(
            "the replayed incarnation never streamed to the rebound "
            "client (partials_post_crash="
            f"{metrics.get('partials_post_crash')}, emits_post_crash="
            f"{metrics.get('emits_post_crash')}) — the job finished "
            "before the client reconnected, so the across-the-replay "
            "monotonicity leg is vacuous"
        )
    if not metrics.get("monotone", False):
        bad.append(
            f"RAW partial coverage regressed (seq: "
            f"{metrics.get('coverage_seq')}) — a replayed Emit claimed "
            f"less coverage than one the client already saw"
        )
    if not metrics.get("final_exact", False):
        bad.append("streamed final answer != brute-force ground truth")
    if not metrics.get("bit_identical_final", False):
        bad.append(
            "streamed final payload differs from the non-streaming "
            "submission's — partial emission changed the fold"
        )
    return bad


async def _starve_tenant(
    port: int, params: Params, cid: int, *,
    workload: Optional[str], data: Optional[bytes], upper: int,
    inflight: int, out: dict, stop: dict, shed_pause: float = 0.01,
) -> None:
    """Open-loop tenant for the starvation drill: holds ``inflight``
    submissions on one connection, replacing every answer (or shed
    Refuse) immediately. A parked submission answers late — the park
    path sends nothing until the DRR drain mints it — so the per-job
    latency list IS the starvation probe. ``workload=None`` is the
    background mining tenant; ``workload='dict'`` the greedy flood."""
    c = await LspClient.connect("127.0.0.1", port, params)
    ckey = f"starve-{workload or 'mine'}-{cid}"
    jid = 0
    t0: dict = {}
    lat = out.setdefault("lat", [])

    def fire() -> None:
        nonlocal jid
        jid += 1
        t0[jid] = time.monotonic()
        c.write(encode_msg(Request(
            job_id=jid, mode=PowMode.MIN, lower=0, upper=upper,
            data=data if data is not None else b"starve-%d-%d" % (cid, jid),
            client_key=ckey, workload=workload,
        )))

    try:
        for _ in range(inflight):
            fire()
        while t0:
            msg = decode_msg(await c.read())
            if isinstance(msg, (Result, WorkResult)) and msg.job_id in t0:
                lat.append(time.monotonic() - t0.pop(msg.job_id))
                out["done"] = out.get("done", 0) + 1
                if not stop["stop"]:
                    fire()
            elif isinstance(msg, Refuse) and msg.job_id in t0:
                t0.pop(msg.job_id)
                out["shed"] = out.get("shed", 0) + 1
                if not stop["stop"]:
                    # greedy: replace a shed submission near-immediately
                    # (the pause only keeps the Refuse loop from
                    # saturating the event loop, it is far inside any
                    # retry_after the coordinator asked for)
                    await asyncio.sleep(shed_pause)
                    fire()
    except (LspConnectionLost, asyncio.CancelledError):
        pass
    finally:
        await c.close(drain_timeout=0.2)


async def _starve_arm(
    flood: bool, *, n_miners: int, params: Params, duration: float,
    weights: dict, park_capacity: int, max_jobs: int,
    retry_after_ms: int, chunk_size: int, mine_upper: int,
    dict_data: bytes, dict_upper: int, mine_inflight: int,
    flood_inflight: int, drain: float = 15.0,
) -> dict:
    """One arm of the starvation A/B: the background mining tenants
    always run; ``flood=True`` adds the greedy dict tenants. Identical
    coordinator config both arms — the baseline measures the same park
    machinery without contention."""
    from tpuminter.worker import CpuMiner, run_miner_reconnect

    coord = await make_coordinator(
        params=params, chunk_size=chunk_size, max_jobs=max_jobs,
        retry_after_ms=retry_after_ms, park_capacity=park_capacity,
        workload_weights=dict(weights),
    )
    port = coord.port
    serve = asyncio.ensure_future(coord.serve())
    miners = [
        asyncio.ensure_future(run_miner_reconnect(
            "127.0.0.1", port, CpuMiner(), params=params,
            base_backoff=0.05, max_backoff=0.5,
        ))
        for _ in range(n_miners)
    ]
    stop = {"stop": False}
    mine_out: dict = {}
    flood_out: dict = {}
    tenants = [
        asyncio.ensure_future(_starve_tenant(
            port, params, i, workload=None, data=None, upper=mine_upper,
            inflight=mine_inflight, out=mine_out, stop=stop,
        ))
        for i in range(2)
    ]
    if flood:
        tenants += [
            asyncio.ensure_future(_starve_tenant(
                port, params, i, workload="dict", data=dict_data,
                upper=dict_upper, inflight=flood_inflight,
                out=flood_out, stop=stop,
            ))
            for i in range(2)
        ]
    try:
        await asyncio.sleep(duration)
        stop["stop"] = True
        await asyncio.wait(tenants, timeout=drain)
        lat = mine_out.get("lat", [])
        arm = {
            "mining_jobs": len(lat),
            "mine_p50_ms": _pct_ms(lat, 50),
            "mine_p99_ms": _pct_ms(lat, 99),
            "flood_done": flood_out.get("done", 0),
            "flood_shed": flood_out.get("shed", 0),
            "jobs_parked": coord.stats["jobs_parked"],
            "parked_shed": coord.stats["parked_shed"],
            "parked_drained": coord.stats["parked_drained"],
            "park_queue_high_water": coord.stats[
                "park_queue_high_water"
            ],
            "drained_by_class": dict(coord.parked_drained_by_class),
        }
        return arm
    finally:
        for t in tenants + miners:
            t.cancel()
        await asyncio.gather(*tenants, *miners, return_exceptions=True)
        serve.cancel()
        await asyncio.gather(serve, return_exceptions=True)
        await coord.close()


async def run_starve(
    n_miners: int = 4,
    *,
    duration: float = 2.0,
    seed: int = 0,
    params: Params = FAST,
    chunk_size: int = 512,
    max_jobs: int = 6,
    # small on purpose: the flood holds 2 x flood_inflight submissions
    # live, so a per-class capacity below that forces the LRU shed +
    # explicit Refuse path the overflow gate demands
    park_capacity: int = 16,
    retry_after_ms: int = 100,
    mine_upper: int = 8191,
    # 2 tenants x 4 > the mine class's slot share: the mining backlog
    # stays non-empty under flood, so the drain-count ratio measures
    # the scheduler's weight split rather than work-conserving
    # leftovers handed to the only backlogged class
    mine_inflight: int = 4,
    flood_inflight: int = 12,
) -> dict:
    """The starvation A/B (ISSUE 20): paired arms on an identically
    configured coordinator — weights ``mine=2, dict=1``, a bounded
    park queue, a small job table — once with only the background
    mining tenants (the flood-free baseline) and once with greedy dict
    tenants holding ``2 × flood_inflight`` submissions open. Gates
    (``starve_check``): the flood demonstrably parked and shed, the
    mining tenants' p99 stayed within 2× the baseline, and the DRR
    drain counts track the weight share."""
    from tpuminter.workloads import dictsearch as ds

    weights = {"mine": 2.0, "dict": 1.0}
    dseed = (0x57A7 + seed) & 0xFFFFFFFF
    cands = [b"starve-%05d" % i for i in range(256)]
    dict_data = ds.pack_params("fmin", dseed, cands)
    kwargs = dict(
        n_miners=n_miners, params=params, duration=duration,
        weights=weights, park_capacity=park_capacity, max_jobs=max_jobs,
        retry_after_ms=retry_after_ms, chunk_size=chunk_size,
        mine_upper=mine_upper, dict_data=dict_data,
        dict_upper=len(cands) - 1, mine_inflight=mine_inflight,
        flood_inflight=flood_inflight,
    )
    base = await _starve_arm(False, **kwargs)
    flood = await _starve_arm(True, **kwargs)
    d = flood.get("drained_by_class", {})
    mine_d, dict_d = d.get("mine", 0), d.get("dict", 0)
    fairness = None
    if mine_d > 0 and dict_d > 0:
        fairness = round(
            (dict_d / weights["dict"]) / (mine_d / weights["mine"]), 3
        )
    return {
        "seed": seed, "fleet": n_miners, "weights": weights,
        "max_jobs": max_jobs, "park_capacity": park_capacity,
        "baseline": base, "flood": flood,
        "drr_fairness_ratio": fairness,
    }


def starve_check(metrics: dict) -> list:
    """The starvation gate (ISSUE 20): the flood actually parked and
    overflowed, parked mining submissions kept draining at their DRR
    share, and the background tenants' latency survived the flood."""
    bad = []
    base, flood = metrics.get("baseline", {}), metrics.get("flood", {})
    if flood.get("jobs_parked", 0) <= 0:
        bad.append(
            "the greedy flood never parked a submission — the drill "
            "measured an uncontended coordinator"
        )
    if flood.get("parked_shed", 0) <= 0:
        bad.append(
            "the park queue never overflowed: the flood was not "
            "greedy enough to exercise the LRU shed + Refuse bound"
        )
    if flood.get("park_queue_high_water", 0) > (
        metrics.get("park_capacity", 0) * 2  # per-class bound, 2 classes
    ):
        bad.append(
            f"park high-water {flood.get('park_queue_high_water')} "
            f"exceeded the per-class capacity bound"
        )
    for arm_name, arm in (("baseline", base), ("flood", flood)):
        if arm.get("mining_jobs", 0) <= 0:
            bad.append(f"{arm_name} arm answered no mining jobs at all")
    p99b, p99f = base.get("mine_p99_ms"), flood.get("mine_p99_ms")
    if p99b is not None and p99f is not None:
        # the +100 ms grace absorbs two DRR drain ticks of scheduling
        # quantum on a smoke-sized sample; the 2x factor is the gate
        if p99f > 2.0 * p99b + 100.0:
            bad.append(
                f"mining p99 under flood ({p99f} ms) blew past 2x the "
                f"flood-free baseline ({p99b} ms) — the greedy tenant "
                f"starved the background one"
            )
    ratio = metrics.get("drr_fairness_ratio")
    if ratio is None:
        bad.append(
            "one class never drained from the park queue — the DRR "
            "fairness ratio is unmeasurable"
        )
    elif not (1 / 3 <= ratio <= 3.0):
        bad.append(
            f"weight-normalized drain ratio {ratio} is outside [1/3, 3]"
            f" — the DRR drain does not track the configured weights"
        )
    return bad


def _hw_gauges(coord) -> dict:
    return {
        k: v for k, v in sorted(coord.stats.items())
        if k.endswith("_high_water")
    }


async def _soak_churn_client(
    port: int, params: Params, pool: list, out: dict, stop: dict,
) -> None:
    """Short-lived one-job clients cycling through a fixed identity
    pool: the session/bucket churn half of the soak — tables must
    plateau at the pool size, not grow with the connection count."""
    i = 0
    while not stop["stop"]:
        i += 1
        try:
            c = await LspClient.connect("127.0.0.1", port, params)
        except LspConnectError:
            await asyncio.sleep(0.05)
            continue
        try:
            req = Request(
                job_id=i, mode=PowMode.MIN, lower=0, upper=255,
                data=b"soak-churn-%d" % i,
                client_key=pool[i % len(pool)],
            )
            c.write(encode_msg(req))
            deadline = time.monotonic() + 3.0
            while time.monotonic() < deadline:
                msg = decode_msg(await asyncio.wait_for(c.read(), 3.0))
                if isinstance(msg, Result) and msg.job_id == i:
                    out["done"] = out.get("done", 0) + 1
                    break
                if isinstance(msg, Refuse) and msg.job_id == i:
                    if msg.retry_after_ms <= 0:
                        break
                    await asyncio.sleep(msg.retry_after_ms / 1000.0)
                    c.write(encode_msg(req))
        except (LspConnectionLost, asyncio.TimeoutError):
            pass
        finally:
            await c.close(drain_timeout=0.1)


async def run_soak(
    *,
    duration: float = 4.0,
    seed: int = 0,
    params: Params = FAST,
    compact_bytes: int = 96 * 1024,
    n_miners: int = 3,
) -> dict:
    """The leak-hunting soak (ISSUE 20): every bounded-state feature
    armed at once — quotas, winner TTL + cap, UNBOUND reaper, the park
    queue, a journal with a small live-compaction threshold — under a
    steady mixed load (durable mining tenants, a dict workload tenant,
    churning short-lived clients) plus a warmup park pulse. Every
    ``*_high_water`` gauge is snapshotted at half-time and at the end:
    ZERO growth in the second half is the leak gate — each table
    provably plateaued — and the WAL must stay bounded by live
    compaction (``compactions >= 1``, final bytes-on-disk within a
    small multiple of the threshold)."""
    import shutil

    from tpuminter.worker import CpuMiner, run_miner_reconnect
    from tpuminter.workloads import dictsearch as ds

    tmpdir = tempfile.mkdtemp(prefix="tpuminter-soak-")
    journal_path = os.path.join(tmpdir, "soak.wal")
    coord = await make_coordinator(
        params=params, chunk_size=256, recover_from=journal_path,
        quota_rate=50.0, quota_burst=8, max_jobs=12,
        retry_after_ms=100, winners_cap=128, winners_ttl=1.0,
        unbound_ttl=1.0, park_capacity=32,
        workload_weights={"mine": 1.0, "dict": 1.0},
    )
    # a small live-compaction threshold (the production default is
    # 4 MiB — far past a short soak): installed directly, like chaos
    # plans, so the WAL-bounded gate actually runs compactions
    coord._journal._compact_bytes = compact_bytes
    port = coord.port
    serve = asyncio.ensure_future(coord.serve())
    miners = [
        asyncio.ensure_future(run_miner_reconnect(
            "127.0.0.1", port, CpuMiner(), params=params,
            base_backoff=0.05, max_backoff=0.5,
        ))
        for _ in range(n_miners)
    ]
    dseed = (0x50AC + seed) & 0xFFFFFFFF
    cands = [b"soak-%04d" % i for i in range(200)]
    scores = [ds.score(dseed, c) for c in cands]
    lo = min((v, i) for i, v in enumerate(scores))
    dict_shapes = [
        ("dict_fmin", ds.pack_params("fmin", dseed, cands),
         lambda acc: list(acc or ()) == list(lo), "dict", len(cands) - 1),
        ("dict_fsum", ds.pack_params("fsum", dseed, cands),
         lambda acc: list(acc or ()) == [sum(scores), len(cands)],
         "dict", len(cands) - 1),
    ]
    mine_ledger = {"answers": {}, "submitted": 0, "stop": False}
    wl_ledger = {"answers": {}, "by_fold": {}, "submitted": 0,
                 "stop": False}
    churn_out: dict = {}
    stop = {"stop": False}
    pool = [f"soak-pool-{i}" for i in range(6)]
    tasks = [
        asyncio.ensure_future(_durable_client_loop(
            port, params, i, 2047, mine_ledger, verify=True
        ))
        for i in range(2)
    ] + [
        asyncio.ensure_future(_workload_client_loop(
            port, params, 0, dict_shapes, wl_ledger
        )),
        asyncio.ensure_future(_soak_churn_client(
            port, params, pool, churn_out, stop
        )),
    ]
    metrics: dict = {
        "seed": seed, "fleet": n_miners,
        "duration": duration, "compact_bytes": compact_bytes,
    }
    try:
        # warmup park pulse: one connection fires a burst far past its
        # quota burst, pinning park_queue_high_water DURING the warmup
        # half — the second half must never exceed it
        await asyncio.sleep(0.3)
        pulse = await LspClient.connect("127.0.0.1", port, params)
        for j in range(24):
            pulse.write(encode_msg(Request(
                job_id=j + 1, mode=PowMode.MIN, lower=0,
                upper=len(cands) - 1, data=dict_shapes[0][1],
                client_key="soak-pulse", workload="dict",
            )))
        await asyncio.sleep(0.3)
        await pulse.close(drain_timeout=0.1)
        # -- half-time snapshot ------------------------------------------
        await asyncio.sleep(max(0.1, duration / 2 - 0.6))
        hw_mid = _hw_gauges(coord)
        wal_mid = os.path.getsize(journal_path)
        # -- second half: identical steady load --------------------------
        await asyncio.sleep(duration / 2)
        hw_end = _hw_gauges(coord)
        wal_end = os.path.getsize(journal_path)
        stop["stop"] = True
        mine_ledger["stop"] = True
        wl_ledger["stop"] = True
        await asyncio.wait(tasks, timeout=10.0)
        metrics["hw_mid"] = hw_mid
        metrics["hw_end"] = hw_end
        metrics["hw_growth"] = {
            k: hw_end[k] - hw_mid.get(k, 0) for k in hw_end
            if hw_end[k] != hw_mid.get(k, 0)
        }
        metrics["wal_mid_bytes"] = wal_mid
        metrics["wal_end_bytes"] = wal_end
        metrics["journal"] = dict(coord._journal.stats)
        answers = mine_ledger["answers"]
        metrics["mining_answered"] = sum(
            1 for c in answers.values() if c >= 1
        )
        metrics["answers_duplicated"] = sum(
            c - 1 for c in answers.values() if c > 1
        )
        metrics["poisoned_answers"] = mine_ledger.get("poisoned", 0)
        metrics["dict_answered"] = sum(
            wl_ledger["by_fold"].values()
        )
        metrics["answers_wrong"] = wl_ledger.get("answers_wrong", 0)
        metrics["churn_done"] = churn_out.get("done", 0)
        metrics["jobs_parked"] = coord.stats["jobs_parked"]
        return metrics
    finally:
        for t in tasks + miners:
            t.cancel()
        await asyncio.gather(*tasks, *miners, return_exceptions=True)
        serve.cancel()
        await asyncio.gather(serve, return_exceptions=True)
        await coord.close()
        shutil.rmtree(tmpdir, ignore_errors=True)


def soak_check(metrics: dict) -> list:
    """The soak gate (ISSUE 20): zero second-half growth in EVERY
    high-water gauge, live compaction demonstrably bounding the WAL,
    and the steady load actually flowed (a soak over an idle
    coordinator proves nothing)."""
    bad = []
    growth = metrics.get("hw_growth", {})
    if growth:
        bad.append(
            f"high-water gauge(s) grew in the second half: {growth} — "
            f"a table is still growing at steady state (leak)"
        )
    j = metrics.get("journal", {})
    if j.get("compactions", 0) < 1:
        bad.append(
            "the journal never compacted — the WAL-bounded claim went "
            "untested"
        )
    cap = 4 * metrics.get("compact_bytes", 1)
    if metrics.get("wal_end_bytes", 0) > cap:
        bad.append(
            f"WAL ended at {metrics.get('wal_end_bytes')} bytes, past "
            f"{cap} (4x the compaction threshold) — compaction is not "
            f"keeping the disk bounded"
        )
    for k, floor in (
        ("mining_answered", 1), ("dict_answered", 1), ("churn_done", 1),
        ("jobs_parked", 1),
    ):
        if metrics.get(k, 0) < floor:
            bad.append(f"soak load never exercised {k}")
    if metrics.get("answers_duplicated", 0) > 0:
        bad.append("duplicate answer(s) under soak")
    if metrics.get("answers_wrong", 0) > 0:
        bad.append("wrong dict answer(s) under soak")
    if metrics.get("poisoned_answers", 0) > 0:
        bad.append("unverifiable mining answer(s) under soak")
    return bad


# ---------------------------------------------------------------------------
# chaos scenario (ISSUE 12): the deterministic fault-plan matrix
# ---------------------------------------------------------------------------

#: the full matrix, one named cell per degradation class. Order matters
#: only for reproducibility: cell seeds derive from (--seed, index).
CHAOS_CELLS = (
    "netsplit", "asym_loss", "delay_reorder",
    "fsync_stall", "enospc", "byzantine",
    "fleet_partition", "flapping_link", "slow_loris",
    "clock_skew",
)
#: the tier-1 smoke subset: one partition cell + one byzantine cell +
#: the slow-loris reaping cell (ISSUE 18) + the lying-clock cell
#: (ISSUE 19 satellite)
CHAOS_SMOKE_CELLS = ("netsplit", "byzantine", "slow_loris", "clock_skew")


async def _byzantine_session(
    port: int, params: Params, *, behavior: str, binary: bool = True,
    connect_epochs: Optional[int] = None,
) -> None:
    """One hostile-worker session (the 15-440 untrusted-worker lineage
    made concrete): Joins like an honest miner, then misbehaves per
    ``behavior``:

    - ``forge``  — answers every Assign with a Result whose hash_value
      verifies against nothing (wrong-preimage claim); the coordinator
      must reject it, requeue the chunk, and evict after
      MAX_REJECTIONS.
    - ``refuse`` — Refuses every Assign (a flood); the coordinator must
      evict after MAX_REFUSALS instead of ping-ponging chunks forever.
    - ``replay`` — answers honestly but re-sends its PREVIOUS Result
      after each new one (stale/duplicate submissions, the post-
      reconnect replay shape); the coordinator must ignore the stale
      chunk ids without penalizing anyone.
    """
    w = await LspClient.connect(
        "127.0.0.1", port, params, connect_epochs=connect_epochs
    )
    w.write(encode_msg(Join(
        backend=f"byz-{behavior}", lanes=1,
        codec="bin" if binary else "json",
    )))
    templates = {}
    speak = {"binary": False}
    last = {"msg": None}

    def handle(raw) -> None:
        if binary and not speak["binary"] and payload_is_binary(raw):
            speak["binary"] = True
        msg = decode_msg(raw)
        if isinstance(msg, Setup):
            templates[msg.request.job_id] = msg.request
        elif isinstance(msg, Cancel):
            templates.pop(msg.job_id, None)
        elif isinstance(msg, Assign):
            req = templates.get(msg.job_id)
            if req is None or behavior == "refuse":
                w.write(encode_msg(
                    Refuse(msg.job_id, msg.chunk_id), binary=speak["binary"]
                ))
                return
            if behavior == "forge":
                # claim the range's first nonce but report a hash that
                # matches no nonce at all: verification MUST fail
                res = Result(
                    msg.job_id, req.mode, nonce=msg.lower,
                    hash_value=chain.toy_hash(req.data, msg.upper) ^ 1,
                    found=True, searched=msg.upper - msg.lower + 1,
                    chunk_id=msg.chunk_id,
                )
                w.write(encode_msg(res, binary=speak["binary"]))
                return
            res = Result(
                msg.job_id, req.mode, nonce=msg.lower,
                hash_value=chain.toy_hash(req.data, msg.lower),
                found=True, searched=msg.upper - msg.lower + 1,
                chunk_id=msg.chunk_id,
            )
            w.write(encode_msg(res, binary=speak["binary"]))
            if last["msg"] is not None:
                # stale replay: the previous chunk's Result again
                w.write(encode_msg(last["msg"], binary=speak["binary"]))
            last["msg"] = res

    try:
        while True:
            raw = await w.read()
            while raw is not None:
                handle(raw)
                raw = (
                    w.read_nowait() if hasattr(w, "read_nowait") else None
                )
    except LspConnectionLost:
        pass  # evicted (or coordinator gone): the redial wrapper returns
    finally:
        await w.close(drain_timeout=0.2)


async def _byzantine_miner(
    ports, params: Params, seed: int, *, behavior: str, binary: bool = True,
) -> None:
    """A byzantine actor that redials after eviction — repeat offenders
    keep coming back, which is exactly what the containment has to
    absorb (each re-Join restarts the offender's rejection budget)."""
    import random as _random

    if isinstance(ports, int):
        ports = [ports]
    from tpuminter.replication import dial_patience

    rng = _random.Random(seed)
    delays = jittered_backoff(0.05, 1.0, rng)
    ce = dial_patience(ports)
    attempt = 0
    while True:
        port = ports[attempt % len(ports)]
        attempt += 1
        try:
            await _byzantine_session(
                port, params, behavior=behavior, binary=binary,
                connect_epochs=ce,
            )
            delays = jittered_backoff(0.05, 1.0, rng)
        except LspConnectError:
            pass
        await asyncio.sleep(next(delays))


async def _slow_loris_actor(
    ports, params: Params, seed: int, *, drops: dict,
    behavior: str = "drip", binary: bool = True,
) -> None:
    """A slow-loris actor (ISSUE 18 satellite: handshake/read
    deadlines): instead of starving the accept queue it starves the
    coordinator's REASSEMBLY buffer —

    - ``mute``: completes the transport handshake, then never speaks a
      single app message; only the server-side first-message deadline
      can reap it (liveness pings flow, so silence detectors never
      fire).
    - ``drip``: Joins honestly — so it LOOKS like a miner and soaks up
      Assigns — then starts a message it never finishes, feeding one
      more-fragments frame per epoch. Every epoch makes one byte of
      progress, which defeats any stall-reset deadline by design; only
      the TOTAL-time read deadline bounds it.

    Counts each server-side reap in ``drops["n"]`` and redials (repeat
    offenders come back, same loop shape as ``_byzantine_miner``)."""
    import random as _random

    from tpuminter.lsp.connection import _MORE
    from tpuminter.replication import dial_patience

    if isinstance(ports, int):
        ports = [ports]
    rng = _random.Random(seed)
    delays = jittered_backoff(0.05, 1.0, rng)
    ce = dial_patience(ports)
    attempt = 0
    while True:
        port = ports[attempt % len(ports)]
        attempt += 1
        try:
            w = await LspClient.connect(
                "127.0.0.1", port, params, connect_epochs=ce
            )
            try:
                if behavior == "drip":
                    w.write(encode_msg(Join(
                        backend="loris", lanes=1,
                        codec="bin" if binary else "json",
                    )))
                while not w.is_lost:
                    if behavior == "drip":
                        w._conn._send_data(_MORE + b"z")
                    await asyncio.sleep(params.epoch_seconds)
                drops["n"] += 1
            finally:
                await w.close(drain_timeout=0.0)
        except (LspConnectError, LspConnectionLost, ConnectionError):
            pass
        await asyncio.sleep(next(delays))


async def _chaos_fleet_cell(
    name: str,
    seed: int,
    *,
    n_miners: int = 6,
    n_clients: int = 2,
    chunk_size: int = 1024,
    chunks_per_job: Optional[int] = None,
    params: Params = FAST,
    pre: float = 0.8,
    fault: float = 1.2,
    post: float = 1.0,
    drain: float = 10.0,
    binary: bool = True,
    pipeline_depth: int = 2,
) -> dict:
    """One single-coordinator matrix cell: journaled coordinator +
    resilient fleet + verifying durable clients; the cell's fault is
    installed mid-burst, held for ``fault`` seconds, healed, and the
    exactly-once ledger is settled after a drain. Cells:

    - ``asym_loss``     — 25% inbound-only loss (A→B dies, B→A flows)
    - ``delay_reorder`` — delay + jitter + reorder + duplication, both
      directions (the WAN-weather cell; must cause no false evictions)
    - ``fsync_stall``   — every fsync sleeps 20 ms (slow disk; must trip
      the slow-fsync executor fallback, not kill the journal)
    - ``enospc``        — one write fails ENOSPC (full disk; must trip
      the journal's loud availability-over-durability path)
    - ``byzantine``     — forge/refuse/replay actors join the fleet
      (verifier rejects → eviction → poisoned chunks re-mine)
    - ``fleet_partition`` — HALF the miner links (picked by source
      port) go totally dark past the loss horizon while the other half
      keeps flowing: the cut miners' chunks must requeue onto the
      survivors, exactly-once intact (ISSUE 13)
    - ``flapping_link`` — every link oscillates dark/light FASTER than
      the loss horizon (dark windows of horizon/4): retransmission must
      ride it out with zero loss declarations and zero evictions
      (ISSUE 13)
    - ``slow_loris`` — drip-feeding actors that Join then never finish
      a message (one more-fragments frame per epoch: byte progress
      every epoch, so liveness never trips) plus mute actors that
      handshake and never speak; the read/first-message deadlines must
      reap both while the honest ledger settles exactly once (ISSUE 18)
    - ``clock_skew`` — BOTH ends' clocks lie, differently (ISSUE 19
      satellite + ISSUE 20): the coordinator's monotonic rate drifts
      ±50% per seeded segment and wall time takes ±30 s NTP-style
      steps, installed mid-burst on the clock seam, while each worker
      runs an independently-seeded ``ClockSkewPlan.fork`` on its
      retry/backoff clock; a blackout past the loss horizon forces the
      fleet to redial through those skewed backoffs. Everything
      downstream of ``_mono``/``_wall`` — token-bucket refill,
      retry_after accrual, the winners age bound, the UNBOUND reaper —
      and the workers' redial pacing must degrade to DELAYS, never to
      losses, duplicates, or evictions; healing is the operator fixing
      the coordinator clock, after which the ledger settles on honest
      time (the worker forks keep lying, which must not matter)
    """
    import dataclasses
    import shutil

    from tpuminter.chaos import ClockSkewPlan, DiskFaultPlan, FaultPlan

    if name == "slow_loris":
        # arm the deadlines the cell exercises: generous next to honest
        # traffic (a full app message lands within an epoch on
        # loopback) yet well inside the fault window
        params = dataclasses.replace(
            params, read_deadline_epochs=params.epoch_limit + 2
        )
    coord_kwargs: dict = {}
    if name == "clock_skew":
        # arm every time-trusting subsystem the skew will lie to:
        # per-ckey token buckets (refill + retry_after accrual), a
        # winners age bound short enough for the wall steps to cross,
        # and the UNBOUND-residue reaper
        coord_kwargs = dict(
            quota_rate=8.0, quota_burst=4, winners_ttl=5.0,
            unbound_ttl=2.0,
        )
    tmpdir = tempfile.mkdtemp(prefix="tpuminter-chaos-")
    journal_path = os.path.join(tmpdir, "chaos.wal")
    coord = await make_coordinator(
        params=params, chunk_size=chunk_size, recover_from=journal_path,
        binary_codec=binary, pipeline_depth=pipeline_depth,
        **coord_kwargs,
    )
    port = coord.port
    serve = asyncio.ensure_future(coord.serve())
    if chunks_per_job is None:
        chunks_per_job = max(8, 2 * n_miners)
    upper = chunk_size * chunks_per_job - 1
    ledger = {"answers": {}, "submitted": 0, "stop": False, "poisoned": 0}
    byz_behaviors = []
    honest = n_miners
    if name == "byzantine":
        byz_behaviors = ["forge", "forge", "refuse", "replay"]
        honest = max(2, n_miners - len(byz_behaviors))
    miner_ports: dict = {}

    def _port_keeper(i: int):
        def keep(w) -> None:
            miner_ports[i] = w.endpoint.local_addr[1]
        return keep

    # clock_skew lies to BOTH ends (ISSUE 20): each worker's
    # retry/backoff clock seam gets an independently-seeded fork of the
    # cell's plan — decorrelated streams, so the two sides disagree
    # about how fast time passes, not just its value. The coordinator's
    # own plan is installed mid-burst below, like every other fault.
    worker_plans: list = []
    if name == "clock_skew":
        _base = ClockSkewPlan(seed)
        worker_plans = [_base.fork(i + 1) for i in range(honest)]
    miners = [
        asyncio.ensure_future(_resilient_instant_miner(
            port, params, seed * 100 + i, binary=binary,
            on_session=(
                _port_keeper(i) if name == "fleet_partition" else None
            ),
            clock=worker_plans[i].mono if worker_plans else None,
        ))
        for i in range(honest)
    ]
    lost_events = {"n": 0}
    loris_drops = {"n": 0}
    if name in ("flapping_link", "slow_loris"):
        _hook_lost_events(coord, lost_events)
    clients = [
        asyncio.ensure_future(_durable_client_loop(
            port, params, i, upper, ledger, verify=True
        ))
        for i in range(n_clients)
    ]
    byz: list = []
    metrics: dict = {
        "cell": name, "cell_seed": seed, "fleet": honest,
        "byzantine": len(byz_behaviors), "clients": n_clients,
    }
    plan = None
    clock_plan = None
    fault_hold = fault
    try:
        await asyncio.sleep(pre)
        stats0 = dict(coord.stats)
        t_fault = time.monotonic()
        if name == "asym_loss":
            plan = FaultPlan(seed).link(peer="*", direction="in", drop=0.25)
            for ep in _endpoints(coord):
                ep.set_fault_plan(plan)
        elif name == "delay_reorder":
            plan = FaultPlan(seed).link(
                peer="*", direction="both", dup=0.1, reorder=0.25,
                reorder_delay=0.02, delay=0.005, delay_jitter=0.01,
            )
            for ep in _endpoints(coord):
                ep.set_fault_plan(plan)
        elif name == "fsync_stall":
            coord._journal.fault_plan = DiskFaultPlan(fsync_stall_s=0.02)
        elif name == "enospc":
            coord._journal.fault_plan = DiskFaultPlan(enospc_once=True)
        elif name == "byzantine":
            byz = [
                asyncio.ensure_future(_byzantine_miner(
                    port, params, seed * 100 + 50 + i, behavior=b,
                    binary=binary,
                ))
                for i, b in enumerate(byz_behaviors)
            ]
        elif name == "slow_loris":
            byz = [
                asyncio.ensure_future(_slow_loris_actor(
                    port, params, seed * 100 + 50 + i, drops=loris_drops,
                    behavior=b, binary=binary,
                ))
                for i, b in enumerate(("drip", "drip", "mute", "mute"))
            ]
            metrics["byzantine"] = len(byz)
            metrics["deadline_epochs"] = params.read_deadline_epochs
            # hold the window past the deadline plus slack: a reap
            # cannot land before the deadline's epochs have elapsed
            fault_hold = max(fault, (
                params.read_deadline_epochs + 3
            ) * params.epoch_seconds)
        elif name == "fleet_partition":
            # cut HALF the fleet's links — by source port, the identity
            # on localhost — and hold the blackout PAST the loss
            # horizon: the cut miners must be declared lost and their
            # in-flight chunks requeued onto the half that kept flowing
            horizon = params.epoch_limit * params.epoch_seconds
            deadline = time.monotonic() + 5.0
            while len(miner_ports) < honest:
                if time.monotonic() > deadline:
                    break  # a straggler never joined; cut who we know
                await asyncio.sleep(0.01)
            cut = [
                miner_ports[i]
                for i in sorted(miner_ports)[: max(1, honest // 2)]
            ]
            plan = FaultPlan(seed)
            for p in cut:
                plan.partition(peer=p, direction="both")
            for ep in _endpoints(coord):
                ep.set_fault_plan(plan)
            metrics["cut_links"] = len(cut)
            fault_hold = max(fault, 2.5 * horizon)
        elif name == "flapping_link":
            # every link oscillates: dark for horizon/4, light for
            # horizon/4, repeating across the whole window — silence
            # never approaches the loss horizon, so the LSP layer's
            # retransmission must absorb it with ZERO loss declarations
            horizon = params.epoch_limit * params.epoch_seconds
            flap = horizon / 4.0
            plan = FaultPlan(seed)
            t = 0.0
            windows = 0
            while t < fault:
                plan.partition(
                    peer="*", direction="both", start=t, duration=flap
                )
                t += 2.0 * flap
                windows += 1
            for ep in _endpoints(coord):
                ep.set_fault_plan(plan)
            metrics["flap_windows"] = windows
            metrics["flap_dark_s"] = round(flap, 3)
        elif name == "clock_skew":
            # the same mid-run installation as fault plans on
            # endpoints, but on the CLOCK seam: from here every
            # coordinator time-read drifts (mono) and steps (wall)
            clock_plan = ClockSkewPlan(seed)
            coord._mono = clock_plan.mono
            coord._wall = clock_plan.wall
            # ...and knock every link dark past the loss horizon
            # (ISSUE 20): the fleet must redial THROUGH its per-miner
            # forked backoff clocks — both ends now lying about time,
            # differently — and resume; in-flight chunks requeue on the
            # horizon like any connection loss, so two-sided skew may
            # only ever degrade to delays, never to a broken ledger
            horizon = params.epoch_limit * params.epoch_seconds
            plan = FaultPlan(seed)
            plan.partition(
                peer="*", direction="both", start=0.0,
                duration=1.5 * horizon,
            )
            for ep in _endpoints(coord):
                ep.set_fault_plan(plan)
            fault_hold = max(fault, 3.0 * horizon)
        else:
            raise ValueError(f"unknown chaos cell {name!r}")
        if name == "byzantine":
            # eviction latency: hostile actors join → first eviction
            while (
                coord.stats["miners_evicted"] == stats0["miners_evicted"]
            ):
                if time.monotonic() - t_fault > 10.0:
                    break
                await asyncio.sleep(0.005)
            metrics["eviction_ms"] = round(
                (time.monotonic() - t_fault) * 1e3, 1
            )
        await asyncio.sleep(fault_hold)
        # heal: every chaos fault is a WINDOW — the drain below settles
        # the ledger on a healthy link, so anything still missing then
        # was really lost, not merely late
        for ep in _endpoints(coord):
            ep.set_fault_plan(None)
        if name == "flapping_link":
            # read the probe BEFORE the drain/teardown: only losses
            # declared while the link was flapping count against it
            metrics["lost_during_flap"] = lost_events["n"]
        if name == "slow_loris":
            # server-side reaps (deadline declare_lost events): honest
            # traffic produces none (graceful closes are suppressed, as
            # the flapping_link cell pins), so every event here is a
            # loris kill. Actor-observed drops ride along as a probe.
            metrics["lorises_dropped"] = lost_events["n"]
            metrics["loris_self_observed"] = loris_drops["n"]
        if clock_plan is not None:
            # heal = the operator fixed the clock: restore the honest
            # time sources so the drain settles the ledger on real
            # time — anything still missing then was truly lost to the
            # skew window, not merely delayed by a still-lying clock
            coord._mono = time.monotonic
            coord._wall = time.time
            metrics["clock_stats"] = dict(clock_plan.stats)
            # the worker forks keep lying through the drain (there is
            # no operator on that side); the probe is whether the seam
            # was demonstrably READ — a fork that never advanced means
            # no miner ever redialed through its skewed backoff
            metrics["worker_clock_stats"] = {
                "forks": len(worker_plans),
                "segments": sum(
                    p.stats["segments"] for p in worker_plans
                ),
                "max_skew_s": max(
                    (p.stats["max_skew_s"] for p in worker_plans),
                    default=0.0,
                ),
            }
        if plan is not None:
            metrics["plan_stats"] = dict(plan.stats)
        if coord._journal is not None:
            if coord._journal.fault_plan is not None:
                metrics["disk_stats"] = dict(
                    coord._journal.fault_plan.stats
                )
                coord._journal.fault_plan = None
            metrics["fsync_slow_flipped"] = bool(
                getattr(coord._journal, "_fsync_slow", False)
            )
            metrics["journal_failed"] = bool(
                getattr(coord._journal, "_failed", False)
            )
        await asyncio.sleep(post)
        for t in byz:
            t.cancel()
        await asyncio.gather(*byz, return_exceptions=True)
        ledger["stop"] = True
        done, pending_tasks = await asyncio.wait(clients, timeout=drain)
        for t in pending_tasks:
            t.cancel()
        await asyncio.gather(*clients, return_exceptions=True)
        answers = ledger["answers"]
        metrics["submitted"] = ledger["submitted"]
        metrics["answered"] = sum(1 for c in answers.values() if c >= 1)
        metrics["answers_duplicated"] = sum(
            c - 1 for c in answers.values() if c > 1
        )
        metrics["answers_lost"] = (
            metrics["submitted"] - metrics["answered"]
        )
        metrics["poisoned_answers"] = ledger.get("poisoned", 0)
        metrics["retry_after_honored"] = ledger.get(
            "retry_after_honored", 0
        )
        st = coord.stats
        metrics["results_rejected"] = (
            st["results_rejected"] - stats0["results_rejected"]
        )
        metrics["miners_evicted"] = (
            st["miners_evicted"] - stats0["miners_evicted"]
        )
        metrics["chunks_requeued"] = (
            st["chunks_requeued"] - stats0["chunks_requeued"]
        )
        return metrics
    finally:
        for t in clients + miners + byz:
            t.cancel()
        await asyncio.gather(
            *clients, *miners, *byz, return_exceptions=True
        )
        serve.cancel()
        await asyncio.gather(serve, return_exceptions=True)
        await coord.close()
        shutil.rmtree(tmpdir, ignore_errors=True)


async def _chaos_netsplit_cell(
    seed: int,
    *,
    n_miners: int = 6,
    n_clients: int = 2,
    chunk_size: int = 1024,
    chunks_per_job: Optional[int] = None,
    params: Params = FAST,
    pre: float = 1.0,
    post: float = 1.5,
    drain: float = 12.0,
    binary: bool = True,
    pipeline_depth: int = 2,
) -> dict:
    """The netsplit cell: a replicated primary+standby, and mid-burst
    the primary↔standby link — and ONLY that link — goes dark (a
    declarative ``FaultPlan.partition`` on the standby's endpoint; the
    fleet keeps talking to the primary throughout). The standby detects
    the silence and promotes: a SPLIT BRAIN, two live coordinators.
    The netsplit heals right after promotion, the old primary's
    shipping lane gets fenced off by the promoted standby, and — the
    ISSUE 12 containment fix — the fenced lane now fences the WHOLE old
    coordinator, which drops its fleet so everyone rotates onto the
    promoted standby. The cell asserts the containment end-to-end plus
    the exactly-once ledger across the whole ordeal."""
    import shutil

    from tpuminter.chaos import FaultPlan
    from tpuminter.replication import ReplicationStandby

    tmpdir = tempfile.mkdtemp(prefix="tpuminter-netsplit-")
    standby = await ReplicationStandby.create(
        os.path.join(tmpdir, "standby.wal"), params=params
    )
    standby_task = asyncio.ensure_future(standby.run())
    coord = await make_coordinator(
        params=params, chunk_size=chunk_size,
        recover_from=os.path.join(tmpdir, "primary.wal"),
        binary_codec=binary, pipeline_depth=pipeline_depth,
        replicate_to=[("127.0.0.1", standby.port)], replica_ack=True,
    )
    ports = [coord.port, standby.port]
    serve = asyncio.ensure_future(coord.serve())
    if chunks_per_job is None:
        chunks_per_job = max(8, 2 * n_miners)
    upper = chunk_size * chunks_per_job - 1
    ledger = {"answers": {}, "submitted": 0, "stop": False, "poisoned": 0}
    miners = [
        asyncio.ensure_future(_resilient_instant_miner(
            ports, params, seed * 100 + i, binary=binary
        ))
        for i in range(n_miners)
    ]
    clients = [
        asyncio.ensure_future(_durable_client_loop(
            ports, params, i, upper, ledger, verify=True
        ))
        for i in range(n_clients)
    ]
    metrics: dict = {
        "cell": "netsplit", "cell_seed": seed, "fleet": n_miners,
        "clients": n_clients,
    }
    coord2 = None
    serve2 = None
    try:
        await asyncio.sleep(pre)
        metrics["replicated_records_pre_split"] = (
            standby.stats["records_applied"]
        )
        # -- the link dies: one declarative rule, nothing else changes --
        plan = FaultPlan(seed).partition(peer="*", direction="both")
        standby.server.endpoint.set_fault_plan(plan)
        t_split = time.monotonic()
        await asyncio.wait_for(
            standby.primary_lost.wait(),
            10 * params.epoch_limit * params.epoch_seconds,
        )
        metrics["detect_ms"] = round(
            (time.monotonic() - t_split) * 1e3, 1
        )
        # -- the standby promotes: split brain, two live coordinators --
        coord2 = await standby.promote(
            chunk_size=chunk_size, binary_codec=binary,
            pipeline_depth=pipeline_depth,
        )
        serve2 = asyncio.ensure_future(coord2.serve())
        metrics["promoted_epoch"] = coord2.boot_epoch
        # -- the netsplit heals --
        plan.heal()
        t_heal = time.monotonic()
        metrics["netsplit_ms"] = round((t_heal - t_split) * 1e3, 1)
        # the old primary's shipping lane redials the promoted standby,
        # gets its epoch fenced off, and (the ISSUE 12 fix) fences the
        # whole old coordinator — without it the split brain persists
        while not coord.fenced and time.monotonic() - t_heal < 15.0:
            await asyncio.sleep(0.01)
        metrics["old_primary_fenced"] = coord.fenced
        metrics["fence_ms"] = round(
            (time.monotonic() - t_heal) * 1e3, 1
        )
        # fleet lands on the promoted coordinator (first dispatch)
        while coord2._next_chunk_id == 1:
            if time.monotonic() - t_heal > 15.0:
                break
            await asyncio.sleep(0.005)
        metrics["takeover_ms"] = round(
            (time.monotonic() - t_split) * 1e3, 1
        )
        await asyncio.sleep(post)
        ledger["stop"] = True
        done, pending_tasks = await asyncio.wait(clients, timeout=drain)
        for t in pending_tasks:
            t.cancel()
        await asyncio.gather(*clients, return_exceptions=True)
        answers = ledger["answers"]
        metrics["submitted"] = ledger["submitted"]
        metrics["answered"] = sum(1 for c in answers.values() if c >= 1)
        metrics["answers_duplicated"] = sum(
            c - 1 for c in answers.values() if c > 1
        )
        metrics["answers_lost"] = (
            metrics["submitted"] - metrics["answered"]
        )
        metrics["poisoned_answers"] = ledger.get("poisoned", 0)
        metrics["fenced_rejections"] = (
            coord2.stats["replication_fenced"]
        )
        return metrics
    finally:
        standby_task.cancel()
        for t in clients + miners:
            t.cancel()
        await asyncio.gather(
            standby_task, *clients, *miners, return_exceptions=True
        )
        serve.cancel()
        await asyncio.gather(serve, return_exceptions=True)
        await coord.close()
        if serve2 is not None:
            serve2.cancel()
            await asyncio.gather(serve2, return_exceptions=True)
        if coord2 is not None:
            await coord2.close()
        elif not standby.promoted:
            await standby.close()
        shutil.rmtree(tmpdir, ignore_errors=True)


async def run_chaos(
    cells=None,
    *,
    seed: int = 0,
    n_miners: int = 6,
    n_clients: int = 2,
    duration: float = 1.2,
    params: Params = FAST,
    binary: bool = True,
    pipeline_depth: int = 2,
) -> dict:
    """Sweep the chaos matrix: run each named cell with a seed derived
    from (``seed``, cell index) — the whole grid of fault draws and
    partition windows is reproducible from ``--seed`` — and return the
    per-cell metrics. ``chaos_check`` holds the assertions."""
    if cells is None:
        cells = CHAOS_CELLS
    out: dict = {"seed": seed, "cells": list(cells), "results": {}}
    for i, cell in enumerate(cells):
        cell_seed = (seed * 1000003 + i * 101) & 0x7FFFFFFF
        if cell == "netsplit":
            m = await _chaos_netsplit_cell(
                cell_seed, n_miners=n_miners, n_clients=n_clients,
                params=params, pre=min(duration, 1.0), post=duration,
                binary=binary, pipeline_depth=pipeline_depth,
            )
        else:
            m = await _chaos_fleet_cell(
                cell, cell_seed, n_miners=n_miners, n_clients=n_clients,
                params=params, pre=min(duration, 0.8), fault=duration,
                post=min(duration, 1.0), binary=binary,
                pipeline_depth=pipeline_depth,
            )
        out["results"][cell] = m
    return out


def chaos_check(metrics: dict, params: Params = FAST) -> list:
    """The matrix's pass/fail assertions, applied after EVERY cell (the
    tier-1 gate shape): the exactly-once ledger holds under every
    degradation, forged answers never reach a client, byzantine actors
    are evicted and their chunks re-mined, a netsplit's split brain is
    contained, and disk faults degrade exactly as designed."""
    bad = []
    for cell, m in metrics.get("results", {}).items():
        pre = f"[{cell}] "
        if m.get("answered", 0) <= 0:
            bad.append(pre + f"no requests answered at all: {m}")
        if m.get("answers_duplicated", 0) > 0:
            bad.append(
                pre + f"{m['answers_duplicated']} duplicate answer(s): "
                f"the exactly-once ledger broke"
            )
        if m.get("answers_lost", 0) > 0:
            bad.append(
                pre + f"{m['answers_lost']} request(s) never answered "
                f"despite the post-heal drain window"
            )
        if m.get("poisoned_answers", 0) > 0:
            bad.append(
                pre + f"{m['poisoned_answers']} FORGED answer(s) "
                f"reached a client — byzantine containment broke"
            )
        if cell == "netsplit":
            if m.get("replicated_records_pre_split", 0) <= 0:
                bad.append(
                    pre + "no records replicated before the split: the "
                    "cell measured an empty takeover"
                )
            if not m.get("old_primary_fenced"):
                bad.append(
                    pre + "the old primary kept serving after the heal "
                    "— split brain uncontained"
                )
            if m.get("takeover_ms", 1e9) > 20_000:
                bad.append(
                    pre + f"takeover took {m.get('takeover_ms')} ms: "
                    f"the fleet never landed on the promoted standby"
                )
        elif cell == "byzantine":
            if m.get("miners_evicted", 0) <= 0:
                bad.append(pre + "no byzantine miner was evicted")
            if m.get("results_rejected", 0) <= 0:
                bad.append(pre + "no forged result was rejected")
            if m.get("chunks_requeued", 0) <= 0:
                bad.append(pre + "no poisoned chunk was requeued")
        elif cell == "delay_reorder":
            if m.get("miners_evicted", 0) > 0:
                bad.append(
                    pre + "transport faults alone got a miner evicted "
                    "— duplicate/reordered datagrams read as byzantine"
                )
        elif cell == "fsync_stall":
            if not m.get("fsync_slow_flipped"):
                bad.append(
                    pre + "a 20 ms fsync stall never tripped the "
                    "slow-fsync executor fallback"
                )
            if m.get("journal_failed"):
                bad.append(
                    pre + "a slow disk must degrade latency, not kill "
                    "the journal"
                )
        elif cell == "enospc":
            if not m.get("journal_failed"):
                bad.append(
                    pre + "ENOSPC did not trip the journal's loud "
                    "availability-over-durability path"
                )
        elif cell == "fleet_partition":
            if m.get("cut_links", 0) <= 0:
                bad.append(
                    pre + "no miner link was ever cut: the cell "
                    "measured an empty partition"
                )
            if m.get("chunks_requeued", 0) <= 0:
                bad.append(
                    pre + "no chunk from a cut miner was requeued onto "
                    "the surviving half of the fleet"
                )
        elif cell == "slow_loris":
            if m.get("lorises_dropped", 0) <= 0:
                bad.append(
                    pre + "no slow-loris connection was ever reaped: "
                    "the read/first-message deadlines never fired"
                )
            if m.get("deadline_epochs", 0) <= 0:
                bad.append(
                    pre + "the cell ran with the deadline disarmed — "
                    "it measured nothing"
                )
        elif cell == "clock_skew":
            cs = m.get("clock_stats", {})
            if cs.get("max_skew_s", 0.0) <= 0.0:
                bad.append(
                    pre + "the clock never diverged from true time: "
                    "the cell measured an honest clock"
                )
            if cs.get("segments", 0) < 1:
                bad.append(
                    pre + "no drift segment ever elapsed — the skewed "
                    "clock was installed but never read"
                )
            if m.get("retry_after_honored", 0) <= 0:
                bad.append(
                    pre + "no Refuse{retry_after_ms} was ever issued/"
                    "honored: the token-bucket accrual math under skew "
                    "went unexercised"
                )
            if m.get("miners_evicted", 0) > 0:
                bad.append(
                    pre + "a lying coordinator clock got an honest "
                    "miner evicted"
                )
            ws = m.get("worker_clock_stats", {})
            if ws.get("segments", 0) < 1:
                bad.append(
                    pre + "no worker ever read its forked backoff "
                    "clock — the cell skewed only ONE end (ISSUE 20 "
                    "wants both lying, differently)"
                )
            if ws.get("max_skew_s", 0.0) <= 0.0:
                bad.append(
                    pre + "the worker-side clock forks never diverged "
                    "from true time"
                )
        elif cell == "flapping_link":
            if m.get("lost_during_flap", 0) > 0:
                bad.append(
                    pre + f"{m['lost_during_flap']} connection(s) "
                    f"declared lost by flaps SHORTER than the loss "
                    f"horizon — retransmission failed to ride it out"
                )
            if m.get("miners_evicted", 0) > 0:
                bad.append(
                    pre + "flapping transport alone got a miner evicted"
                )
    return bad


# ---------------------------------------------------------------------------
# admission scenarios (ISSUE 13): skewed open-loop demand + client churn
# ---------------------------------------------------------------------------

def _pct_ms(xs: list, p: float):
    """p-th percentile of a latency list, in milliseconds (None when
    empty — a cell that measured nothing must fail loudly, not report
    a flattering zero)."""
    if not xs:
        return None
    xs = sorted(xs)
    i = min(len(xs) - 1, int(len(xs) * p / 100.0))
    return round(xs[i] * 1e3, 3)


async def _open_loop_tenant(
    port: int, params: Params, cid: int, upper: int, ledger: dict,
    lat: Optional[list], *, rate: float, stop: dict,
    tier: Optional[str] = None, seed: int = 0,
) -> None:
    """One open-loop tenant: arrivals are a seeded Poisson process at
    ``rate`` req/s, submitted WITHOUT waiting for the previous answer —
    the open-loop shape where demand does not politely slow down when
    the service does, which is what makes overload real (closed-loop
    clients self-throttle and can never show the whale problem).

    Every Result is booked in the exactly-once ledger; ``lat`` collects
    submit→answer latency per answered job, measured from the FIRST
    submission (so admission backpressure counts against the tenant
    that earned it). Refuse{retry_after_ms} is honored with 0.5–1.5x
    jitter and the same request re-submitted: refusals delay, they
    never lose."""
    import random as _random

    rng = _random.Random(seed * 7919 + cid)
    ckey = f"{tier}:{cid}" if tier else f"tenant:{cid}"
    client = await LspClient.connect("127.0.0.1", port, params)
    pending: dict = {}  # jid -> (Request, t_first_submit)
    answers = ledger["answers"]
    resubmits: list = []

    async def _resubmit(req: Request, wait: float) -> None:
        await asyncio.sleep(wait)
        if not client.is_lost:
            client.write(encode_msg(req))

    async def reader() -> None:
        while True:
            msg = decode_msg(await client.read())
            if isinstance(msg, Result):
                key = (cid, msg.job_id)
                answers[key] = answers.get(key, 0) + 1
                entry = pending.pop(msg.job_id, None)
                if entry is not None and lat is not None:
                    lat.append(time.monotonic() - entry[1])
            elif isinstance(msg, Refuse) and msg.retry_after_ms > 0:
                entry = pending.get(msg.job_id)
                if entry is None:
                    continue  # answered while the Refuse was in flight
                ledger["retry_after_honored"] = (
                    ledger.get("retry_after_honored", 0) + 1
                )
                wait = msg.retry_after_ms / 1000.0 * (0.5 + rng.random())
                resubmits.append(
                    asyncio.ensure_future(_resubmit(entry[0], wait))
                )

    rd = asyncio.ensure_future(reader())
    jid = 0
    try:
        while not stop["flag"]:
            await asyncio.sleep(rng.expovariate(rate))
            if stop["flag"]:
                break
            jid += 1
            req = Request(
                job_id=jid, mode=PowMode.MIN, lower=0, upper=upper,
                data=b"zipf-%d-%d" % (cid, jid), client_key=ckey,
            )
            pending[jid] = (req, time.monotonic())
            ledger["submitted"] += 1
            client.write(encode_msg(req))
        # drain: no new arrivals; the refused backlog keeps re-
        # submitting until the bucket refills and everything answers
        t_end = time.monotonic() + stop.get("drain", 10.0)
        while pending and time.monotonic() < t_end:
            await asyncio.sleep(0.05)
    except LspConnectionLost:
        pass
    finally:
        ledger["unanswered"] = ledger.get("unanswered", 0) + len(pending)
        rd.cancel()
        for t in resubmits:
            t.cancel()
        await asyncio.gather(rd, *resubmits, return_exceptions=True)
        await client.close(drain_timeout=0.2)


async def run_zipf(
    n_small: int = 8,
    *,
    n_miners: int = 4,
    chunk_size: int = 1024,
    params: Params = FAST,
    duration: float = 1.5,
    drain: float = 10.0,
    rate: float = 12.0,
    whale_mult: float = 10.0,
    quota_rate: Optional[float] = None,
    quota_burst: int = 6,
    seed: int = 0,
    binary: bool = True,
    pipeline_depth: int = 2,
) -> dict:
    """The heavy-tail (zipf-head) overload drill: paired A/B runs of
    the SAME small-tenant population — baseline without, then with, one
    whale demanding ``whale_mult``x a small tenant's open-loop arrival
    rate. Both runs arm per-ckey token-bucket quotas plus a 'whale'
    priority tier at 2x (generous, still far under its demand), so
    admission clips the whale to its quota instead of letting it eat
    the fleet. The headline pair: small-tenant p99 with vs without the
    whale — the ISSUE 13 acceptance bound is a <= 2x degradation."""
    if quota_rate is None:
        quota_rate = 2.0 * rate  # per-tenant headroom over its demand

    async def one_run(with_whale: bool) -> dict:
        coord = await make_coordinator(
            params=params, chunk_size=chunk_size, binary_codec=binary,
            pipeline_depth=pipeline_depth,
            quota_rate=quota_rate, quota_burst=quota_burst,
            quota_tiers={"whale": 2.0},
        )
        port = coord.port
        serve = asyncio.ensure_future(coord.serve())
        upper = chunk_size * 2 - 1
        ledger = {"answers": {}, "submitted": 0}
        stop = {"flag": False, "drain": drain}
        small_lat: list = []
        whale_lat: list = []
        miners = [
            asyncio.ensure_future(
                _instant_miner(port, params, binary=binary)
            )
            for _ in range(n_miners)
        ]
        tenants = [
            asyncio.ensure_future(_open_loop_tenant(
                port, params, cid, upper, ledger, small_lat,
                rate=rate, stop=stop, tier="small", seed=seed,
            ))
            for cid in range(n_small)
        ]
        if with_whale:
            tenants.append(asyncio.ensure_future(_open_loop_tenant(
                port, params, 1000, upper, ledger, whale_lat,
                rate=rate * whale_mult, stop=stop, tier="whale",
                seed=seed,
            )))
        try:
            await asyncio.sleep(duration)
            stop["flag"] = True
            done, pending_t = await asyncio.wait(
                tenants, timeout=drain + 2.0
            )
            for t in pending_t:
                t.cancel()
            await asyncio.gather(*tenants, return_exceptions=True)
            answers = ledger["answers"]
            m = {
                "submitted": ledger["submitted"],
                "answered": sum(1 for c in answers.values() if c >= 1),
                "answers_duplicated": sum(
                    c - 1 for c in answers.values() if c > 1
                ),
                "unanswered": ledger.get("unanswered", 0),
                "retry_after_honored": ledger.get(
                    "retry_after_honored", 0
                ),
                "refused_admission": coord.stats["refused_admission"],
                "quota_buckets_high_water": coord.stats[
                    "quota_buckets_high_water"
                ],
                "small_p50_ms": _pct_ms(small_lat, 50),
                "small_p99_ms": _pct_ms(small_lat, 99),
            }
            if with_whale:
                m["whale_p50_ms"] = _pct_ms(whale_lat, 50)
                m["whale_p99_ms"] = _pct_ms(whale_lat, 99)
            return m
        finally:
            for t in tenants + miners:
                t.cancel()
            await asyncio.gather(
                *tenants, *miners, return_exceptions=True
            )
            serve.cancel()
            await asyncio.gather(serve, return_exceptions=True)
            await coord.close()

    base = await one_run(False)
    whale = await one_run(True)
    return {
        "scenario": "zipf", "tenants": n_small, "rate": rate,
        "whale_mult": whale_mult, "quota_rate": quota_rate,
        "quota_burst": quota_burst, "seed": seed,
        "baseline": base, "whale": whale,
    }


def zipf_check(metrics: dict) -> list:
    """The skewed-demand assertions (tier-1 gate shape): quotas engaged
    against the whale, Refuse{retry_after_ms} honored, nothing lost or
    duplicated, and the small tenants' p99 survived the whale."""
    bad = []
    base = metrics.get("baseline", {})
    whale = metrics.get("whale", {})
    for name, m in (("baseline", base), ("whale", whale)):
        if m.get("answered", 0) <= 0:
            bad.append(f"[{name}] no requests answered at all: {m}")
        if m.get("answers_duplicated", 0) > 0:
            bad.append(
                f"[{name}] {m['answers_duplicated']} duplicate "
                f"answer(s): the exactly-once ledger broke"
            )
        if m.get("unanswered", 0) > 0:
            bad.append(
                f"[{name}] {m['unanswered']} request(s) never answered "
                f"despite the drain window — a Refuse must delay, "
                f"never lose"
            )
    p_base = base.get("small_p99_ms")
    p_whale = whale.get("small_p99_ms")
    if p_base is None or p_whale is None:
        bad.append("small-tenant p99 missing from a run")
    elif p_whale > 2.0 * p_base and p_whale - p_base > 25.0:
        # the 2x acceptance bound, with a 25 ms absolute floor so a
        # 3 ms -> 7 ms wobble on a loaded CI host is not a failure
        bad.append(
            f"small-tenant p99 degraded more than 2x under the whale: "
            f"{p_base} ms -> {p_whale} ms"
        )
    if whale.get("refused_admission", 0) <= 0:
        bad.append(
            "the whale was never refused admission: quotas did not "
            "engage against 10x demand"
        )
    if whale.get("retry_after_honored", 0) <= 0:
        bad.append(
            "no Refuse{retry_after_ms} was honored: the backpressure "
            "loop never closed"
        )
    return bad


async def _churn_client(
    port: int, params: Params, cid: int, upper: int, ledger: dict,
    *, abandon: bool, seed: int = 0, deadline: float = 8.0,
) -> None:
    """One short-lived churn client: connect, submit ONE job under a
    durable ckey, then either await the answer (booked in the exactly-
    once ledger) or vanish immediately (``abandon`` — the ghost shape
    that, uncapped, would leak a _Job, a _bound entry and a session set
    per client). Awaiters survive a coordinator kill -9 mid-wait by
    redialing and re-submitting the SAME (ckey, job_id) — the restarted
    coordinator deduplicates from its journal."""
    import random as _random

    rng = _random.Random(seed * 104729 + cid)
    ckey = f"churn-{cid}"
    req = Request(
        job_id=1, mode=PowMode.MIN, lower=0, upper=upper,
        data=b"churn-%d" % cid, client_key=ckey,
    )
    ledger["submitted"] += 1
    t_end = time.monotonic() + deadline
    delays = jittered_backoff(0.05, 0.5, rng)
    answers = ledger["answers"]
    while time.monotonic() < t_end:
        # every dial ATTEMPT can mint a server-side session (the server
        # creates one on the first datagram even if the client times the
        # handshake out and redials), so the session-table bound is
        # derived from these timestamps, not from client count
        ledger.setdefault("dial_times", []).append(time.monotonic())
        try:
            client = await LspClient.connect(
                "127.0.0.1", port, params, connect_epochs=2
            )
        except LspConnectError:
            await asyncio.sleep(next(delays))
            continue
        try:
            client.write(encode_msg(req))
            if abandon:
                ledger["abandoned"] = ledger.get("abandoned", 0) + 1
                return  # vanish: no read, no goodbye — pure residue
            while time.monotonic() < t_end:
                msg = decode_msg(await client.read())
                if isinstance(msg, Result) and msg.job_id == req.job_id:
                    key = (cid, req.job_id)
                    answers[key] = answers.get(key, 0) + 1
                    return
                if (
                    isinstance(msg, Refuse)
                    and msg.retry_after_ms > 0
                    and msg.job_id == req.job_id
                ):
                    ledger["retry_after_honored"] = (
                        ledger.get("retry_after_honored", 0) + 1
                    )
                    await asyncio.sleep(
                        msg.retry_after_ms / 1000.0
                        * (0.5 + rng.random())
                    )
                    client.write(encode_msg(req))
        except LspConnectionLost:
            await asyncio.sleep(next(delays))
        finally:
            await client.close(drain_timeout=0.05)
    ledger["unanswered"] = ledger.get("unanswered", 0) + 1


async def run_churn(
    n_clients: int = 5000,
    *,
    concurrency: int = 160,
    n_miners: int = 4,
    chunk_size: int = 1024,
    params: Params = FAST,
    drain: float = 12.0,
    abandon_frac: float = 0.4,
    max_jobs: int = 128,
    winners_cap: int = 256,
    winners_ttl: float = 1.0,
    unbound_ttl: float = 0.25,
    quota_rate: float = 50.0,
    quota_burst: int = 16,
    crash: bool = True,
    journal_path: Optional[str] = None,
    seed: int = 0,
    binary: bool = True,
    pipeline_depth: int = 2,
) -> dict:
    """The churn drill: ``n_clients`` short-lived clients (at most
    ``concurrency`` alive at once) wash over a coordinator whose every
    table is capped — ``max_jobs`` with LRU shedding, the winner/dedup
    table bounded by ``winners_cap``/``winners_ttl``, quota buckets LRU-
    capped, and UNBOUND residue reaped after ``unbound_ttl``. A seeded
    ``abandon_frac`` of the clients submit a WIDE job and vanish without
    a goodbye (ghosts); the rest submit a small job and await the
    answer. Mid-churn (``crash=True``) the coordinator is killed -9 and
    restarted from its journal with the same caps — the ISSUE 13 claim
    that replay rebuilds the same BOUNDED view, not the unbounded
    history. The pass/fail bounds live in :func:`churn_check`: every
    table high-water must plateau at a constant independent of
    ``n_clients``, with the exactly-once ledger intact."""
    import random as _random
    import shutil

    rng = _random.Random(seed)
    tmpdir = None
    if journal_path is None:
        tmpdir = tempfile.mkdtemp(prefix="tpuminter-churn-")
        journal_path = os.path.join(tmpdir, "churn.wal")
    knobs = dict(
        params=params, chunk_size=chunk_size, binary_codec=binary,
        pipeline_depth=pipeline_depth, recover_from=journal_path,
        max_jobs=max_jobs, winners_cap=winners_cap,
        winners_ttl=winners_ttl, unbound_ttl=unbound_ttl,
        quota_rate=quota_rate, quota_burst=quota_burst,
        stats_interval=0.2,  # bounded-state sweeps tick 5x/s
    )
    coord = await make_coordinator(**knobs)
    port = coord.port
    serve = asyncio.ensure_future(coord.serve())
    state = {"coord": coord}
    #: counters survive the restart by carrying the pre-crash snapshot:
    #: sum the counting stats, max the high-water stats
    carried: dict = {}
    peaks = {"jobs": 0, "winners": 0, "sessions": 0, "buckets": 0}

    async def sampler() -> None:
        while True:
            await asyncio.sleep(0.05)
            c = state["coord"]
            if c is None:
                continue
            peaks["jobs"] = max(peaks["jobs"], len(c._jobs))
            peaks["winners"] = max(peaks["winners"], len(c._winners))
            peaks["sessions"] = max(peaks["sessions"], len(c._clients))
            peaks["buckets"] = max(peaks["buckets"], len(c._buckets))

    upper_small = chunk_size * 2 - 1
    upper_wide = chunk_size * 64 - 1  # ghosts leave WIDE pending work
    ledger = {"answers": {}, "submitted": 0, "dial_times": []}
    miners = [
        asyncio.ensure_future(_resilient_instant_miner(
            port, params, seed * 100 + i, binary=binary
        ))
        for i in range(n_miners)
    ]
    sample_task = asyncio.ensure_future(sampler())
    sem = asyncio.Semaphore(concurrency)
    launched = {"n": 0}

    async def spawn(cid: int, abandon: bool) -> None:
        async with sem:
            launched["n"] += 1
            await _churn_client(
                port, params, cid,
                upper_wide if abandon else upper_small,
                ledger, abandon=abandon, seed=seed,
            )

    clients = [
        asyncio.ensure_future(
            spawn(cid, rng.random() < abandon_frac)
        )
        for cid in range(n_clients)
    ]
    t_launch = time.monotonic()
    metrics: dict = {
        "scenario": "churn", "clients": n_clients,
        "concurrency": concurrency, "fleet": n_miners, "seed": seed,
        "max_jobs": max_jobs, "winners_cap": winners_cap,
        "winners_ttl": winners_ttl, "unbound_ttl": unbound_ttl,
    }
    try:
        if crash:
            # -- kill -9 mid-churn, restart from the journal ------------
            while launched["n"] < n_clients // 2:
                await asyncio.sleep(0.01)
            carried = dict(coord.stats)
            state["coord"] = None
            serve.cancel()
            await asyncio.gather(serve, return_exceptions=True)
            await _crash_coordinator(coord)
            t_restart0 = time.monotonic()
            for attempt in range(50):
                try:
                    coord = await make_coordinator(port, **knobs)
                    break
                except OSError:
                    if attempt == 49:
                        raise
                    await asyncio.sleep(0.02)
            metrics["recovered_jobs"] = len(coord._jobs)
            metrics["recovered_winners"] = len(coord._winners)
            metrics["replay_ms"] = round(
                (time.monotonic() - t_restart0) * 1e3, 3
            )
            serve = asyncio.ensure_future(coord.serve())
            state["coord"] = coord
        done, pending_t = await asyncio.wait(
            clients, timeout=max(60.0, n_clients * 0.05)
        )
        for t in pending_t:
            t.cancel()
        await asyncio.gather(*clients, return_exceptions=True)
        elapsed = max(0.05, time.monotonic() - t_launch)
        metrics["elapsed_s"] = round(elapsed, 3)
        # a session is evicted one loss horizon after its last datagram,
        # so at any instant the table holds at most the LIVE connections
        # (<= concurrency, one per in-flight client) plus every
        # connection dialed within the last horizon — and every dial
        # ATTEMPT can mint one (handshake timeouts redial, abandoned
        # dials linger).  Bound from the MEASURED peak dial rate inside
        # a sliding horizon-sized window, not the whole-run average: the
        # early burst dials far faster than n_clients / elapsed (backoff
        # waits and the drain tail inflate elapsed), yet the peak stays
        # a constant in n_clients because it is rate-limited by
        # concurrency and the dial/backoff cadence.
        horizon = params.epoch_limit * params.epoch_seconds
        dial_times = sorted(ledger.get("dial_times", []))
        window = horizon + 0.5  # + session-sweep tick granularity
        peak_dials = 0
        lo = 0
        for hi, t_hi in enumerate(dial_times):
            while t_hi - dial_times[lo] > window:
                lo += 1
            peak_dials = max(peak_dials, hi - lo + 1)
        metrics["dials"] = len(dial_times)
        metrics["dials_peak_window"] = peak_dials
        metrics["session_bound"] = int(
            concurrency + 2.0 * peak_dials + 16
        )
        # -- final reap: wait for the residue to hit zero ---------------
        t_end = time.monotonic() + max(drain, 4 * unbound_ttl)
        while time.monotonic() < t_end:
            if not coord._jobs and not coord._clients:
                break
            await asyncio.sleep(0.1)
        answers = ledger["answers"]
        metrics["submitted"] = ledger["submitted"]
        metrics["abandoned"] = ledger.get("abandoned", 0)
        metrics["answered"] = sum(1 for c in answers.values() if c >= 1)
        metrics["answers_duplicated"] = sum(
            c - 1 for c in answers.values() if c > 1
        )
        metrics["unanswered"] = ledger.get("unanswered", 0)
        metrics["retry_after_honored"] = ledger.get(
            "retry_after_honored", 0
        )
        st = coord.stats
        for k in (
            "refused_admission", "jobs_shed", "unbound_reaped",
            "winners_evicted",
        ):
            metrics[k] = st[k] + carried.get(k, 0)
        for k in (
            "jobs_high_water", "winners_high_water",
            "sessions_high_water", "quota_buckets_high_water",
        ):
            metrics[k] = max(st[k], carried.get(k, 0))
        metrics["jobs_peak"] = peaks["jobs"]
        metrics["winners_peak"] = peaks["winners"]
        metrics["sessions_peak"] = peaks["sessions"]
        metrics["buckets_peak"] = peaks["buckets"]
        metrics["final_jobs"] = len(coord._jobs)
        metrics["final_winners"] = len(coord._winners)
        metrics["final_sessions"] = len(coord._clients)
        metrics["final_buckets"] = len(coord._buckets)
        if coord._journal is not None:
            metrics["journal"] = dict(coord._journal.stats)
        return metrics
    finally:
        sample_task.cancel()
        for t in clients + miners:
            t.cancel()
        await asyncio.gather(
            sample_task, *clients, *miners, return_exceptions=True
        )
        serve.cancel()
        await asyncio.gather(serve, return_exceptions=True)
        if state["coord"] is not None:
            await state["coord"].close()
        if tmpdir is not None:
            shutil.rmtree(tmpdir, ignore_errors=True)


def churn_check(metrics: dict) -> list:
    """The churn drill's pass/fail bounds (tier-1 gate shape). The
    plateau claim is literal: every high-water is bounded by a constant
    derived from the CAPS and the live-concurrency window — never from
    ``n_clients`` — so 10x the churn cannot move the ceilings."""
    bad = []
    conc = metrics.get("concurrency", 0)
    if metrics.get("answered", 0) <= 0:
        bad.append(f"no awaiting client was ever answered: {metrics}")
    if metrics.get("answers_duplicated", 0) > 0:
        bad.append(
            f"{metrics['answers_duplicated']} duplicate answer(s): the "
            f"exactly-once ledger broke under churn"
        )
    if metrics.get("unanswered", 0) > 0:
        bad.append(
            f"{metrics['unanswered']} awaiting client(s) never "
            f"answered within their deadline"
        )
    if metrics.get("jobs_high_water", 0) > metrics.get("max_jobs", 0):
        bad.append(
            f"job table burst its cap: high water "
            f"{metrics['jobs_high_water']} > max_jobs "
            f"{metrics.get('max_jobs')}"
        )
    w_cap = metrics.get("winners_cap", 0)
    if metrics.get("winners_high_water", 0) > w_cap + conc + 32:
        # un-acked winners (finish records still in flight to disk) are
        # never evicted, so the table may briefly exceed the cap by the
        # in-flight window — bounded by live concurrency, not churn
        bad.append(
            f"winner/dedup table burst its bound: high water "
            f"{metrics['winners_high_water']} > cap {w_cap} + "
            f"in-flight window {conc + 32}"
        )
    session_bound = metrics.get("session_bound", conc + 16)
    if metrics.get("sessions_high_water", 0) > session_bound:
        bad.append(
            f"session table grew past the live-concurrency + loss-"
            f"horizon window: high water "
            f"{metrics['sessions_high_water']} > {session_bound}"
        )
    if metrics.get("quota_buckets_high_water", 0) > QUOTA_BUCKETS_CAP:
        bad.append(
            f"quota-bucket table burst its LRU cap: high water "
            f"{metrics['quota_buckets_high_water']} > "
            f"{QUOTA_BUCKETS_CAP}"
        )
    if (
        metrics.get("abandoned", 0) > 0
        and metrics.get("unbound_reaped", 0) <= 0
    ):
        bad.append(
            "ghosts abandoned jobs but the UNBOUND-residue reaper "
            "never fired: churned clients are leaving residue"
        )
    if metrics.get("final_sessions", 0) > 0:
        bad.append(
            f"{metrics['final_sessions']} session(s) survived every "
            f"client leaving — per-session state was not reclaimed"
        )
    if metrics.get("final_jobs", 0) > 0:
        bad.append(
            f"{metrics['final_jobs']} job(s) survived the drain + reap "
            f"window — the job table does not return to empty"
        )
    if "recovered_jobs" in metrics:
        if metrics["recovered_jobs"] > metrics.get("max_jobs", 0):
            bad.append(
                f"journal replay resurrected {metrics['recovered_jobs']} "
                f"jobs, more than max_jobs "
                f"{metrics.get('max_jobs')} — recovery is not cap-aware"
            )
        if metrics.get("recovered_winners", 0) > w_cap:
            bad.append(
                f"journal replay resurrected "
                f"{metrics['recovered_winners']} winners, more than "
                f"winners_cap {w_cap} — recovery is not cap-aware"
            )
    return bad


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="tpuminter control-plane load generator"
    )
    parser.add_argument("--miners", type=int, default=8)
    parser.add_argument("--clients", type=int, default=4)
    parser.add_argument("--duration", type=float, default=3.0)
    parser.add_argument("--chunk-size", type=int, default=1024)
    parser.add_argument(
        "--smoke", action="store_true",
        help="fleet-64 burst with liveness assertions: exit 1 on any "
        "event-loop stall >= one epoch or any lost connection "
        "(with --scenario crash: exit 1 on any lost/duplicated answer "
        "or a fleet that fails to resume)",
    )
    parser.add_argument(
        "--scenario",
        choices=(
            "steady", "crash", "failover", "chaos", "zipf", "churn",
            "rolled", "workload", "chain-host", "multiproc",
            "stream", "starve", "soak",
        ),
        default="steady",
        help="steady: the sustained-burst benchmark; crash: kill the "
        "journaled coordinator mid-burst, restart it from the journal "
        "on the same port, and report recovery latency plus the "
        "exactly-once answer ledger; failover: primary ships its WAL "
        "to a live hot standby, dies mid-burst WITHOUT its journal "
        "ever being re-read, the standby promotes with a fenced epoch "
        "and the address-listed fleet lands on it — reports "
        "detect/takeover/blackout latency plus the same ledger; "
        "chaos: sweep the deterministic fault-plan matrix (netsplit, "
        "asymmetric loss, delay/reorder, fsync stall, ENOSPC, "
        "byzantine fleet, fleet partition, flapping link) and assert "
        "the exactly-once ledger plus containment after every cell — "
        "--smoke runs the netsplit + byzantine subset (the tier-1 "
        "gate), --seed picks the grid; zipf: paired open-loop runs of "
        "a small-tenant population with and without a whale at 10x "
        "demand, quotas armed — asserts the small tenants' p99 "
        "degrades <= 2x and the whale is clipped by "
        "Refuse{retry_after_ms}; churn: thousands of seeded short-"
        "lived clients (a ghost fraction abandons jobs mid-flight) "
        "against a fully capped coordinator, kill -9 mid-churn — "
        "asserts every table high-water plateaus at a constant "
        "independent of client count, zero residue after the wash, "
        "and cap-aware journal replay; rolled: paired A/B of "
        "roll-budget chunking — the same roll-capable instant fleet "
        "and 64-segment nonce_bits=32 rolled jobs run once with "
        "--roll-budget armed and once at budget 0 (global-index "
        "chunks), gated on the RollAssign path demonstrably engaging, "
        ">= 1000x fewer control messages per 2^32 settled indices, "
        "and beacon overhead <= 5% of results/s; workload: the "
        "pluggable-workload drill (ISSUE 15) — a real CpuMiner fleet "
        "serves hashcore jobs across every registered fold discipline "
        "(fmin, top-k, first-match hit + dry, map-reduce sum) through "
        "a worker kill AND a coordinator kill -9 + journal restart, "
        "gated on a per-fold EXACT-ANSWER exactly-once ledger; "
        "stream: the streaming-fold drill (ISSUE 20) — a windowed dict "
        "catalog with stream=True, kill -9 after the first partial, "
        "gated on >= 3 strictly-monotone raw partials across the "
        "replay and a brute-force-exact, bit-identical final; starve: "
        "the weighted-fair A/B (ISSUE 20) — a greedy dict flood vs "
        "background mining tenants, gated on the flood parking + "
        "shedding, mining p99 <= 2x the flood-free baseline, and DRR "
        "drain counts tracking the weight share; soak: every bounded-"
        "state feature armed under steady mixed load (ISSUE 20), "
        "gated on ZERO second-half growth in every *_high_water gauge "
        "and a WAL bounded by live compaction",
    )
    parser.add_argument(
        "--roll-budget", type=int, default=16, metavar="N",
        help="rolled scenario: extranonce segments per RollAssign in "
        "the armed arm (the baseline arm always runs at 0; default 16)",
    )
    parser.add_argument(
        "--seed", type=int, default=0,
        help="chaos scenario: the fault-plan grid seed — every cell's "
        "drop/dup/delay draws and partition windows derive from it, so "
        "a failing matrix replays cell-for-cell",
    )
    parser.add_argument(
        "--dev-lanes", action="store_true",
        help="workload scenario: force the hashcore fleet onto the "
        "u32-pair device-lane engine (ops.splitmix) — same drill, same "
        "exact-answer ledger, plus a gate that the device engine "
        "demonstrably dispatched (ISSUE 17's crash-safe equality leg)",
    )
    parser.add_argument(
        "--journal", metavar="PATH", default=None,
        help="journal file (steady: measures journaling overhead; "
        "crash: defaults to a temp file)",
    )
    parser.add_argument(
        "--journal-flush", choices=("tick", "task"), default="tick",
        help="journal flush scheduling: 'tick' folds the flusher into "
        "the serve loop's burst cadence (Round 10 default), 'task' "
        "restores the PR 3/4 batch-window flusher task for A/B runs",
    )
    parser.add_argument(
        "--standby", action="store_true",
        help="steady scenario: attach an in-process hot standby and "
        "ship the journal to it (measures replication overhead; "
        "requires --journal)",
    )
    parser.add_argument(
        "--replica-ack", action="store_true",
        help="with --standby (or in the failover drill): gate winner "
        "acknowledgements on standby confirmation",
    )
    parser.add_argument(
        "--miner-delay", type=float, default=0.0, metavar="SECONDS",
        help="every miner takes this long per chunk (a SlowMiner "
        "fleet — the pipeline-depth sweep's workload; default 0 = "
        "instant)",
    )
    parser.add_argument(
        "--codec", choices=("binary", "json"), default="binary",
        help="app-message codec (binary = the struct-packed fast path "
        "negotiated via Join; json = the PR 3 baseline for A/B runs)",
    )
    parser.add_argument(
        "--loops", type=int, default=1, metavar="N",
        help="event loops the coordinator shards across (SO_REUSEPORT "
        "multi-loop, tpuminter.multiloop; 1 = the classic single-loop "
        "coordinator). Requesting N > 1 on a host that cannot shard "
        "FAILS — never a silent single-loop fallback",
    )
    parser.add_argument(
        "--procs", type=int, default=2, metavar="N",
        help="multiproc scenario: shard PROCESSES to fork "
        "(tpuminter.multiproc — each shard its own OS process, GIL, "
        "journal segment, and verifier executor; cross-shard rebind "
        "registry and shared quota buckets gossip over the seam "
        "channel)",
    )
    parser.add_argument(
        "--io-batch", choices=("on", "off"), default="on",
        help="batched socket I/O: 'on' drains a bounded recvfrom burst "
        "per epoll wakeup and groups each tick's sends (default); "
        "'off' restores the stdlib asyncio datagram transport — the "
        "PERF.md Round 11 A/B baseline",
    )
    parser.add_argument(
        "--journal-mode", choices=("writer", "segments"), default="writer",
        help="multi-loop journal seam: 'writer' = one WAL on the "
        "writer loop fed by per-shard queues (default; required for "
        "replication), 'segments' = one WAL file per loop, merged at "
        "recovery (cannot ship to a standby)",
    )
    parser.add_argument(
        "--pipeline", type=int, default=2, metavar="N",
        help="chunks kept outstanding per miner (2 = shipping default; "
        "1 = the PR 3 round-trip-per-chunk baseline for A/B runs)",
    )
    parser.add_argument(
        "--group-commit", choices=("on", "off"), default="off",
        help="cross-job group commit of winner fsyncs (journal runs "
        "only). Default off — measured a LOSS on this fast-fsync "
        "host (the window's latency costs closed-loop clients more "
        "than the saved fsyncs are worth, PERF.md Round 11); 'on' is "
        "the knob for slow-disk deployments and A/B runs",
    )
    parser.add_argument(
        "--loop-affinity", action="store_true",
        help="enable the runtime loop-affinity race detector "
             "(tpuminter.analysis.affinity) for the crash/failover "
             "drills; --smoke then fails on any cross-loop mutation",
    )
    parser.add_argument(
        "--hops", type=int, default=2,
        help="chain-host scenario: chained standby hops to serve",
    )
    parser.add_argument(
        "--wal-dir", default=None,
        help="chain-host scenario: directory for the hop WAL files",
    )
    parser.add_argument(
        "--port-file", default=None,
        help="chain-host scenario: file the entry hop's port is "
        "written to once the chain is listening",
    )
    parser.add_argument("--json", action="store_true", help="JSON output")
    args = parser.parse_args(argv)
    if args.scenario == "chain-host":
        if not args.wal_dir or not args.port_file:
            parser.error("chain-host requires --wal-dir and --port-file")
        try:
            asyncio.run(run_chain_host(
                args.hops, args.wal_dir, args.port_file
            ))
        except KeyboardInterrupt:
            pass
        return 0
    knobs = dict(
        binary=args.codec == "binary", pipeline_depth=args.pipeline,
        loops=args.loops, io_batch=args.io_batch == "on",
    )
    if args.scenario == "rolled":
        metrics = asyncio.run(run_rolled(
            8 if args.smoke else args.miners,
            max(2, args.clients),
            duration=min(args.duration, 1.5) if args.smoke
            else args.duration,
            # production chunk-size default unless explicitly overridden
            chunk_size=(
                args.chunk_size if args.chunk_size != 1024 else 16384
            ),
            roll_budget=args.roll_budget,
            binary=args.codec == "binary",
            pipeline_depth=args.pipeline,
        ))
        print(json.dumps(metrics) if args.json else
              "\n".join(
                  [f"{k}: {v}" for k, v in metrics.items()
                   if not isinstance(v, dict)]
                  + [f"{arm}.{k}: {v}"
                     for arm in ("roll", "classic")
                     for k, v in metrics.get(arm, {}).items()]
              ))
        # the A/B IS its assertions, --smoke or not (like chaos/zipf)
        violations = rolled_check(metrics)
        for v in violations:
            print(f"ROLLED FAIL: {v}", file=sys.stderr)
        return 1 if violations else 0
    if args.scenario == "zipf":
        metrics = asyncio.run(run_zipf(
            4 if args.smoke else max(4, args.clients),
            duration=min(args.duration, 1.2) if args.smoke
            else args.duration,
            rate=10.0 if args.smoke else 12.0,
            seed=args.seed, binary=args.codec == "binary",
            pipeline_depth=args.pipeline,
        ))
        print(json.dumps(metrics) if args.json else
              "\n".join(
                  [f"{k}: {v}" for k, v in metrics.items()
                   if not isinstance(v, dict)]
                  + [f"{run}.{k}: {v}"
                     for run in ("baseline", "whale")
                     for k, v in metrics.get(run, {}).items()]
              ))
        # the drill IS its assertions, --smoke or not (like chaos)
        violations = zipf_check(metrics)
        for v in violations:
            print(f"ZIPF FAIL: {v}", file=sys.stderr)
        return 1 if violations else 0
    if args.scenario == "churn":
        metrics = asyncio.run(run_churn(
            300 if args.smoke else 5000,
            concurrency=48 if args.smoke else 160,
            seed=args.seed, binary=args.codec == "binary",
            pipeline_depth=args.pipeline,
        ))
        print(json.dumps(metrics) if args.json else
              "\n".join(
                  f"{k}: {v}" for k, v in metrics.items()
                  if not isinstance(v, dict)
              ))
        violations = churn_check(metrics)
        for v in violations:
            print(f"CHURN FAIL: {v}", file=sys.stderr)
        return 1 if violations else 0
    if args.scenario == "chaos":
        cells = CHAOS_SMOKE_CELLS if args.smoke else CHAOS_CELLS
        metrics = asyncio.run(run_chaos(
            cells, seed=args.seed, n_miners=min(args.miners, 8),
            n_clients=max(2, args.clients // 2),
            duration=min(args.duration, 1.2) if args.smoke
            else args.duration,
            binary=args.codec == "binary",
            pipeline_depth=args.pipeline,
        ))
        print(json.dumps(metrics) if args.json else
              "\n".join(
                  f"{cell}.{k}: {v}"
                  for cell, m in metrics["results"].items()
                  for k, v in m.items()
              ))
        # the matrix IS its assertions: check after every cell whether
        # or not --smoke asked (a chaos run that doesn't gate is noise)
        violations = chaos_check(metrics)
        for v in violations:
            print(f"CHAOS FAIL: {v}", file=sys.stderr)
        return 1 if violations else 0
    if args.scenario == "failover":
        if args.smoke:
            # 2+ loops need a fleet big enough that an empty shard is
            # statistically impossible (hash partition, see smoke_check)
            args.miners = min(args.miners, 8) if args.loops <= 1 else max(
                args.miners, 8 * args.loops
            )
            args.duration = min(args.duration, 2.0)
        metrics = asyncio.run(run_failover(
            args.miners, max(2, args.clients // 2),
            chunk_size=args.chunk_size,
            pre=min(args.duration, 2.0), post=args.duration,
            replica_ack=True, loop_affinity=args.loop_affinity, **knobs,
        ))
        print(json.dumps(metrics) if args.json else
              "\n".join(f"{k}: {v}" for k, v in metrics.items()))
        violations = failover_check(metrics) if args.smoke else []
        for v in violations:
            print(f"FAILOVER FAIL: {v}", file=sys.stderr)
        return 1 if violations else 0
    if args.scenario == "workload":
        metrics = asyncio.run(run_workload(
            4 if args.smoke else max(4, args.miners),
            2 if args.smoke else max(2, args.clients // 2),
            journal_path=args.journal, chunk_size=args.chunk_size,
            pre=min(args.duration, 1.5) if args.smoke
            else max(1.0, args.duration / 2),
            post=min(args.duration, 2.0) if args.smoke
            else args.duration,
            binary=args.codec == "binary",
            pipeline_depth=args.pipeline,
            dev_lanes=args.dev_lanes,
        ))
        print(json.dumps(metrics) if args.json else
              "\n".join(f"{k}: {v}" for k, v in metrics.items()))
        # the drill IS its assertions, --smoke or not (like chaos/zipf)
        violations = workload_check(metrics)
        for v in violations:
            print(f"WORKLOAD FAIL: {v}", file=sys.stderr)
        return 1 if violations else 0
    if args.scenario == "stream":
        metrics = asyncio.run(run_stream(
            3 if args.smoke else max(3, args.miners),
            candidates=20000 if args.smoke else 60000,
            seed=args.seed,
        ))
        print(json.dumps(metrics) if args.json else
              "\n".join(f"{k}: {v}" for k, v in metrics.items()))
        # the drill IS its assertions, --smoke or not (like workload)
        violations = stream_check(metrics)
        for v in violations:
            print(f"STREAM FAIL: {v}", file=sys.stderr)
        return 1 if violations else 0
    if args.scenario == "starve":
        metrics = asyncio.run(run_starve(
            4 if args.smoke else max(4, args.miners),
            duration=min(args.duration, 1.5) if args.smoke
            else max(2.0, args.duration),
            seed=args.seed,
        ))
        print(json.dumps(metrics) if args.json else
              "\n".join(f"{k}: {v}" for k, v in metrics.items()))
        violations = starve_check(metrics)
        for v in violations:
            print(f"STARVE FAIL: {v}", file=sys.stderr)
        return 1 if violations else 0
    if args.scenario == "soak":
        metrics = asyncio.run(run_soak(
            duration=min(args.duration, 3.0) if args.smoke
            else max(8.0, args.duration),
            seed=args.seed,
        ))
        print(json.dumps(metrics) if args.json else
              "\n".join(f"{k}: {v}" for k, v in metrics.items()))
        violations = soak_check(metrics)
        for v in violations:
            print(f"SOAK FAIL: {v}", file=sys.stderr)
        return 1 if violations else 0
    if args.scenario == "multiproc":
        metrics = asyncio.run(run_multiproc(
            args.miners, args.clients, min(args.duration, 3.0),
            procs=args.procs, chunk_size=args.chunk_size,
            journal_path=args.journal,
        ))
        print(json.dumps(metrics) if args.json else
              "\n".join(f"{k}: {v}" for k, v in metrics.items()))
        violations = multiproc_check(metrics) if args.smoke else []
        for v in violations:
            print(f"MULTIPROC FAIL: {v}", file=sys.stderr)
        return 1 if violations else 0
    if args.scenario == "crash":
        if args.smoke and args.loops > 1:
            args.miners = max(args.miners, 8 * args.loops)
        metrics = asyncio.run(run_crash(
            args.miners, max(2, args.clients // 2),
            journal_path=args.journal, chunk_size=args.chunk_size,
            pre=min(args.duration, 2.0), post=args.duration,
            journal_mode=args.journal_mode,
            loop_affinity=args.loop_affinity, **knobs,
        ))
        print(json.dumps(metrics) if args.json else
              "\n".join(f"{k}: {v}" for k, v in metrics.items()))
        violations = crash_check(metrics) if args.smoke else []
        for v in violations:
            print(f"CRASH FAIL: {v}", file=sys.stderr)
        return 1 if violations else 0
    if args.smoke:
        args.miners, args.clients = 64, 4
        args.duration = min(args.duration, 2.0)
    metrics = asyncio.run(run_load(
        args.miners, args.clients, args.duration,
        chunk_size=args.chunk_size, journal_path=args.journal,
        journal_tick_flush=args.journal_flush == "tick",
        standby=args.standby, replica_ack=args.replica_ack,
        miner_delay=args.miner_delay, journal_mode=args.journal_mode,
        journal_group_commit=args.group_commit == "on",
        **knobs,
    ))
    print(json.dumps(metrics) if args.json else
          "\n".join(f"{k}: {v}" for k, v in metrics.items()))
    if args.smoke:
        violations = smoke_check(metrics)
        for v in violations:
            print(f"SMOKE FAIL: {v}", file=sys.stderr)
        return 1 if violations else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
