#!/usr/bin/env python
"""The outer graph is byte-clean (walknt.hlo) yet the step still costs
~690 us.  Last suspects: (a) the 32 in-kernel sublane extracts
``vjg_ref[g, :, s, :]`` lower as Mosaic relayouts (~17 us each), or
(b) the gather fusion / custom-call machinery itself.

Same outer scan as walk_native_tile_probe, kernel body varies:

  one_extract  — out[w] = xw[w] ^ vjg[0,:,0,:] (single sublane extract,
                 32 dense xors).  Fast => extracts are the cost.
  all_extracts — out[w] = xw[w] ^ vjg[g,:,s,:] (32 extracts, no salsa).
  extracts_salsa — full body (baseline ~690).
  null_kernel  — out[w] = xw[w] (vjg still an operand, never read).
                 Fast => custom-call machinery fine, gather fine.

Run on the real chip: ``python scripts/kernel_body_probe.py``.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

jax.config.update("jax_compilation_cache_dir", "/tmp/tpuminter-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from tpuminter.ops.scrypt import _block_mix_words  # noqa: E402

B = 16384
N = 1024
LANES = 128
ROWS = B // LANES
BLOCK_RB = 16
STEPS = N
UNROLL = 2


def sync(x):
    np.asarray(jax.tree.leaves(x)[0])


def timed(fn, *args, reps=3):
    out = fn(*args)
    sync(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        sync(out)
        best = min(best, time.perf_counter() - t0)
    return best


def k_one_extract(xw_ref, vjg_ref, out_ref):
    p = vjg_ref[0, :, 0, :]
    for w in range(32):
        out_ref[w] = xw_ref[w] ^ p


def k_all_extracts(xw_ref, vjg_ref, out_ref):
    for w in range(32):
        g, s = divmod(w, 8)
        out_ref[w] = xw_ref[w] ^ vjg_ref[g, :, s, :]


def k_extracts_salsa(xw_ref, vjg_ref, out_ref):
    words = []
    for w in range(32):
        g, s = divmod(w, 8)
        words.append(xw_ref[w] ^ vjg_ref[g, :, s, :])
    mixed = _block_mix_words(words)
    for w in range(32):
        out_ref[w] = mixed[w]


def k_null(xw_ref, vjg_ref, out_ref):
    for w in range(32):
        out_ref[w] = xw_ref[w] ^ np.uint32(1)


def make_call(kernel):
    wm = pl.BlockSpec((32, BLOCK_RB, LANES), lambda i: (0, i, 0),
                      memory_space=pltpu.VMEM)
    gr = pl.BlockSpec((4, BLOCK_RB, 8, LANES), lambda i: (0, i, 0, 0),
                      memory_space=pltpu.VMEM)

    def call(xw, vjg):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((32, ROWS, LANES), jnp.uint32),
            grid=(ROWS // BLOCK_RB,),
            in_specs=[wm, gr],
            out_specs=wm,
        )(xw, vjg)

    return call


def main():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 2**32, (B, 32), dtype=np.uint32))

    @jax.jit
    def make_v():
        i = jnp.arange(N * B, dtype=jnp.uint32)[:, None]
        j = jnp.arange(32, dtype=jnp.uint32)[None, :]
        h = i * np.uint32(2654435761) + j * np.uint32(0x9E3779B9)
        h ^= h >> 16
        h *= np.uint32(0x85EBCA6B)
        h ^= h >> 13
        return h

    vflat = make_v()
    sync(vflat)
    lane = jnp.arange(B, dtype=jnp.uint32)

    def scan_with(call):
        @jax.jit
        def run(x, v):
            xw = jnp.transpose(x).reshape(32, ROWS, LANES)

            def body(carry, _):
                j = carry[16].reshape(B) & np.uint32(N - 1)
                vj = v[(j * np.uint32(B) + lane).astype(jnp.int32)]
                vjg = jnp.transpose(
                    jnp.transpose(vj).reshape(4, 8, ROWS, LANES),
                    (0, 2, 1, 3))
                return call(carry, vjg), None

            xw, _ = jax.lax.scan(body, xw, None, length=STEPS, unroll=UNROLL)
            return xw[0, 0]

        return run

    for name, kern in [
        ("null_kernel", k_null),
        ("one_extract", k_one_extract),
        ("all_extracts", k_all_extracts),
        ("extracts_salsa", k_extracts_salsa),
    ]:
        try:
            t = timed(scan_with(make_call(kern)), x, vflat) / STEPS
            print(f"{name:15s} {t * 1e6:8.1f} us/step")
        except Exception as e:  # noqa: BLE001
            print(f"{name:15s} FAILED: {type(e).__name__}: {str(e)[:160]}")


if __name__ == "__main__":
    main()
