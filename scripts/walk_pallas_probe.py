#!/usr/bin/env python
"""Probe the round-5 scrypt lever: a Pallas kernel fusing the
(B,32)->32x(B,) relayout + xor + salsa at VMEM rates.

PERF.md's walk-step decomposition (round 4) shows the shipping walk
step (680 us at B=16384) is 80% the strided unpack: XLA lowers each of
the 32 ``vj[:, i]`` column extracts as a strided HBM pass (3.6 GB/s
effective).  The fix under test: the gather stays in XLA (its row
gather is near-free, 29 us), but the gathered ``(B, 32)`` rows are
handed to a Mosaic kernel that transposes them in VMEM (verified
bit-exact and cost-free relative to launch noise by
transpose_micro_probe), xors with a word-major ``(32, B/128, 128)``
carry, and runs BlockMix on dense full-vreg word planes.  HBM traffic
per step drops to three linear passes.

Measurement notes (hard-won, see pallas_launch_overhead_probe):
- per-pallas-call overhead inside lax.scan is < ~25 us — invisible
  under the 67-119 ms tunnel dispatch jitter, so only long scans with
  real work (hundreds of ms totals) measure anything;
- sync on SMALL outputs: pulling a 2 MB array back through the tunnel
  costs ~200 ms and swamps everything;
- V must be a jit ARGUMENT (a captured 2 GiB constant stalls lowering)
  and must be GENERATED ON DEVICE: pushing 2 GiB through the ~5 MB/s
  tunnel takes ~7 minutes.

Stages:
  1. fused walk-step kernel: bit-exactness vs the shipping jnp walk
     body over a 4-step data-dependent chain (transpose + xor + salsa
     + gather-index handoff all covered).
  2. 1024-step walk scan: fused vs shipping, us/step.

Run on the real chip: ``python scripts/walk_pallas_probe.py``.
"""

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

jax.config.update("jax_compilation_cache_dir", "/tmp/tpuminter-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from tpuminter.ops.scrypt import _block_mix_words  # noqa: E402

B = 16384
N = 1024
LANES = 128
BLOCK_B = 2048
SUB_B = BLOCK_B // LANES
UNROLL = 2
STEPS = N


def sync(x):
    np.asarray(jax.tree.leaves(x)[0])


def timed(fn, *args, reps=3):
    out = fn(*args)
    sync(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        sync(out)
        best = min(best, time.perf_counter() - t0)
    return best


def _wm_spec():
    return pl.BlockSpec((32, SUB_B, LANES), lambda i: (0, i, 0),
                        memory_space=pltpu.VMEM)


def _transpose_kernel(vj_ref, out_ref):
    out_ref[...] = jnp.transpose(vj_ref[...]).reshape(32, SUB_B, LANES)


@jax.jit
def to_wm(x):
    """(B, 32) row-major -> (32, B/128, 128) word-major, via Mosaic."""
    return pl.pallas_call(
        _transpose_kernel,
        out_shape=jax.ShapeDtypeStruct((32, B // LANES, LANES), jnp.uint32),
        grid=(B // BLOCK_B,),
        in_specs=[
            pl.BlockSpec((BLOCK_B, 32), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
        ],
        out_specs=_wm_spec(),
    )(x)


def _walk_kernel(xw_ref, vj_ref, out_ref):
    vjt = jnp.transpose(vj_ref[...]).reshape(32, SUB_B, LANES)
    words = [xw_ref[i] ^ vjt[i] for i in range(32)]
    mixed = _block_mix_words(words)
    for i in range(32):
        out_ref[i] = mixed[i]


def fused_step(xw, vj):
    return pl.pallas_call(
        _walk_kernel,
        out_shape=jax.ShapeDtypeStruct((32, B // LANES, LANES), jnp.uint32),
        grid=(B // BLOCK_B,),
        in_specs=[
            _wm_spec(),
            pl.BlockSpec((BLOCK_B, 32), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=_wm_spec(),
    )(xw, vj)


def main():
    rng = np.random.default_rng(0)
    x_np = rng.integers(0, 2**32, (B, 32), dtype=np.uint32)
    x = jnp.asarray(x_np)

    @jax.jit
    def make_v():
        # device-side pseudo-random V (values irrelevant — both paths
        # read the SAME array); murmur-style integer mix of the index
        i = jnp.arange(N * B, dtype=jnp.uint32)[:, None]
        j = jnp.arange(32, dtype=jnp.uint32)[None, :]
        h = i * np.uint32(2654435761) + j * np.uint32(0x9E3779B9)
        h ^= h >> 16
        h *= np.uint32(0x85EBCA6B)
        h ^= h >> 13
        return h

    vflat = make_v()
    sync(vflat)
    lane = jnp.arange(B, dtype=jnp.uint32)

    def gather(v, j):
        return v[(j * np.uint32(B) + lane).astype(jnp.int32)]

    # ---- stage 1: bit-exactness over a 4-step data-dependent chain ----
    @partial(jax.jit, static_argnums=2)
    def ref_steps(x, v, k):
        words = tuple(x[:, i] for i in range(32))
        for _ in range(k):
            j = words[16] & np.uint32(N - 1)
            vjk = gather(v, j)
            mixed = [c ^ vjk[:, i] for i, c in enumerate(words)]
            words = tuple(_block_mix_words(mixed))
        return jnp.stack(words, axis=-1)

    @partial(jax.jit, static_argnums=2)
    def fused_steps(x, v, k):
        xw = to_wm(x)
        for _ in range(k):
            j = xw[16].reshape(B) & np.uint32(N - 1)
            xw = fused_step(xw, gather(v, j))
        return jnp.transpose(xw.reshape(32, B))

    ref = np.asarray(ref_steps(x, vflat, 4))
    got = np.asarray(fused_steps(x, vflat, 4))
    exact = bool((ref == got).all())
    print(f"stage1 fused 4-step chain: exact={exact}")
    if not exact:
        bad = np.argwhere(ref != got)
        print(f"  first mismatches (row, word): {bad[:5]}")
        raise SystemExit("fused kernel wrong — stop here")

    # ---- stage 2: 1024-step walk scan timing ----
    @jax.jit
    def walk_ref(x, v):
        words = tuple(x[:, i] for i in range(32))

        def body(carry, _):
            j = carry[16] & np.uint32(N - 1)
            vjk = gather(v, j)
            mixed = [c ^ vjk[:, i] for i, c in enumerate(carry)]
            return tuple(_block_mix_words(mixed)), None

        words, _ = jax.lax.scan(body, words, None, length=STEPS, unroll=UNROLL)
        return words[0]

    @jax.jit
    def walk_fused(x, v):
        xw = to_wm(x)

        def body(carry, _):
            j = carry[16].reshape(B) & np.uint32(N - 1)
            return fused_step(carry, gather(v, j)), None

        xw, _ = jax.lax.scan(body, xw, None, length=STEPS, unroll=UNROLL)
        return xw[0, 0]  # (128,): small pull

    t_ref = timed(walk_ref, x, vflat) / STEPS
    t_fused = timed(walk_fused, x, vflat) / STEPS
    print(f"stage2 walk scan: shipping {t_ref * 1e6:8.1f} us/step")
    print(f"                  fused    {t_fused * 1e6:8.1f} us/step "
          f"({t_ref / t_fused:.2f}x)")


if __name__ == "__main__":
    main()
