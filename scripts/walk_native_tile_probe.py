#!/usr/bin/env python
"""Round-5 scrypt lever, take 4: consume the gather's NATIVE TILES.

The all_planes HLO (walk_isolate_probe + /tmp/allplanes.hlo) finally
named the 550 us/step: ONE op, ``copy(4,8,128,128){3,1,2,0}->
{3,2,1,0}`` — a 2 MB sublane re-tiling.  The TPU gather emitter's
native output interleaves each 8-word group across SUBLANES: bytes are
ordered [word_group(4), row_block(128), word_in_group(8), lane(128)].
Every previous probe demanded plane-contiguous or row-contiguous bytes
and paid the re-tiling; this take demands the NATIVE bytes:

  vjg = vj.T.reshape(4, 8, 128, 128).transpose(0, 2, 1, 3)

whose result (4,128,8,128) in DEFAULT layout is byte-identical to the
gather's native output — the whole chain is bitcasts.  The pallas
kernel extracts word planes as ``vjg_ref[g, :, s, :]`` — sublane
slices, single-vreg ops in VMEM — then xor + BlockMix on dense planes.

Stages: 1. bit-exactness (4 chained steps); 2. 1024-step walk timing.

Run on the real chip: ``python scripts/walk_native_tile_probe.py``.
"""

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

jax.config.update("jax_compilation_cache_dir", "/tmp/tpuminter-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from tpuminter.ops.scrypt import _block_mix_words  # noqa: E402

B = 16384
N = 1024
LANES = 128
ROWS = B // LANES            # 128 row blocks
BLOCK_RB = 16                # row blocks per grid step (2048 rows)
STEPS = N
UNROLL = 2


def sync(x):
    np.asarray(jax.tree.leaves(x)[0])


def timed(fn, *args, reps=3):
    out = fn(*args)
    sync(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        sync(out)
        best = min(best, time.perf_counter() - t0)
    return best


def _xs_kernel(xw_ref, vjg_ref, out_ref):
    words = []
    for w in range(32):
        g, s = divmod(w, 8)
        words.append(xw_ref[w] ^ vjg_ref[g, :, s, :])
    mixed = _block_mix_words(words)
    for w in range(32):
        out_ref[w] = mixed[w]


def fused_xor_salsa(xw, vjg):
    wm = pl.BlockSpec((32, BLOCK_RB, LANES), lambda i: (0, i, 0),
                      memory_space=pltpu.VMEM)
    gr = pl.BlockSpec((4, BLOCK_RB, 8, LANES), lambda i: (0, i, 0, 0),
                      memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _xs_kernel,
        out_shape=jax.ShapeDtypeStruct((32, ROWS, LANES), jnp.uint32),
        grid=(ROWS // BLOCK_RB,),
        in_specs=[wm, gr],
        out_specs=wm,
    )(xw, vjg)


def main():
    rng = np.random.default_rng(0)
    x_np = rng.integers(0, 2**32, (B, 32), dtype=np.uint32)
    x = jnp.asarray(x_np)

    @jax.jit
    def make_v():
        i = jnp.arange(N * B, dtype=jnp.uint32)[:, None]
        j = jnp.arange(32, dtype=jnp.uint32)[None, :]
        h = i * np.uint32(2654435761) + j * np.uint32(0x9E3779B9)
        h ^= h >> 16
        h *= np.uint32(0x85EBCA6B)
        h ^= h >> 13
        return h

    vflat = make_v()
    sync(vflat)
    lane = jnp.arange(B, dtype=jnp.uint32)

    def wm_body(carry, v):
        j = carry[16].reshape(B) & np.uint32(N - 1)
        vj = v[(j * np.uint32(B) + lane).astype(jnp.int32)]
        vjg = jnp.transpose(
            jnp.transpose(vj).reshape(4, 8, ROWS, LANES), (0, 2, 1, 3))
        return fused_xor_salsa(carry, vjg)

    # ---- stage 1: bit-exactness over 4 chained steps ----
    @partial(jax.jit, static_argnums=2)
    def ref_steps(x, v, k):
        words = tuple(x[:, i] for i in range(32))
        for _ in range(k):
            j = words[16] & np.uint32(N - 1)
            vjk = v[(j * np.uint32(B) + lane).astype(jnp.int32)]
            mixed = [c ^ vjk[:, i] for i, c in enumerate(words)]
            words = tuple(_block_mix_words(mixed))
        return jnp.stack(words, axis=-1)

    @partial(jax.jit, static_argnums=2)
    def fused_steps(x, v, k):
        xw = jnp.transpose(x).reshape(32, ROWS, LANES)
        for _ in range(k):
            xw = wm_body(xw, v)
        return jnp.transpose(xw.reshape(32, B))

    ref = np.asarray(ref_steps(x, vflat, 4))
    got = np.asarray(fused_steps(x, vflat, 4))
    exact = bool((ref == got).all())
    print(f"stage1 fused 4-step chain: exact={exact}")
    if not exact:
        bad = np.argwhere(ref != got)
        print(f"  first mismatches (row, word): {bad[:5]}")
        raise SystemExit("fused kernel wrong — stop here")

    # ---- stage 2: 1024-step walk scan timing ----
    @jax.jit
    def walk_ref(x, v):
        words = tuple(x[:, i] for i in range(32))

        def body(carry, _):
            j = carry[16] & np.uint32(N - 1)
            vjk = v[(j * np.uint32(B) + lane).astype(jnp.int32)]
            mixed = [c ^ vjk[:, i] for i, c in enumerate(carry)]
            return tuple(_block_mix_words(mixed)), None

        words, _ = jax.lax.scan(body, words, None, length=STEPS, unroll=UNROLL)
        return words[0]

    @jax.jit
    def walk_fused(x, v):
        xw = jnp.transpose(x).reshape(32, ROWS, LANES)

        def body(carry, _):
            return wm_body(carry, v), None

        xw, _ = jax.lax.scan(body, xw, None, length=STEPS, unroll=UNROLL)
        return xw[0, 0]

    t_ref = timed(walk_ref, x, vflat) / STEPS
    t_fused = timed(walk_fused, x, vflat) / STEPS
    print(f"stage2 walk scan: shipping {t_ref * 1e6:8.1f} us/step")
    print(f"                  fused    {t_fused * 1e6:8.1f} us/step "
          f"({t_ref / t_fused:.2f}x)")


if __name__ == "__main__":
    main()
