#!/usr/bin/env python
"""Round-5 scrypt lever, take 5: ONE plane-major element gather.

kernel_body_probe closed the pallas route: a null kernel that never
reads the gathered operand still costs 676 us/step — the gather
fusion's materialization AS A CUSTOM-CALL OPERAND is the expense, and
XLA-side plane extraction (all_planes) costs the same 550 us.  The
fast consumers are the ones that FUSE into the gather emitter.

So: make the gather itself produce the planes.  Store V plane-major
per step — the fill scan's ys stacked as (N, 32, B), flat view
(N*32*B,) — and fetch all 32 planes with ONE element gather:

    idx[w, b] = j[b]*32*B + w*B + b        # (32, B) int32
    planes    = V1d[idx]                   # one gather op

Each output plane is then a contiguous slice (free extracts), writes
are linear, and the only cost over the row-gather is HBM burst
amplification on 4-byte random reads (32 B bursts -> ~8x of 2 MB =
~16 MB/step).  Variants:

  walk_ref  — shipping row-gather body (baseline ~670).
  walk_eg   — element-gather walk, xor+salsa on (B,) words (pure XLA).

Both bit-checked against each other over 4 chained steps first.

Run on the real chip: ``python scripts/walk_element_gather_probe.py``.
"""

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/tpuminter-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from tpuminter.ops.scrypt import _block_mix_words  # noqa: E402

B = 16384
N = 1024
STEPS = N
UNROLL = 2


def sync(x):
    np.asarray(jax.tree.leaves(x)[0])


def timed(fn, *args, reps=3):
    out = fn(*args)
    sync(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        sync(out)
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    rng = np.random.default_rng(0)
    x_np = rng.integers(0, 2**32, (B, 32), dtype=np.uint32)
    x = jnp.asarray(x_np)

    @jax.jit
    def make_v_rows():
        i = jnp.arange(N * B, dtype=jnp.uint32)[:, None]
        j = jnp.arange(32, dtype=jnp.uint32)[None, :]
        h = i * np.uint32(2654435761) + j * np.uint32(0x9E3779B9)
        h ^= h >> 16
        h *= np.uint32(0x85EBCA6B)
        h ^= h >> 13
        return h

    vrows = make_v_rows()           # (N*B, 32) row-major semantics
    sync(vrows)

    @jax.jit
    def to_plane_major(vr):
        # (N*B, 32) -> (N, B, 32) -> (N, 32, B) -> flat (N*32*B,)
        return jnp.transpose(vr.reshape(N, B, 32), (0, 2, 1)).reshape(-1)

    v1d = to_plane_major(vrows)     # same data, plane-major per step
    sync(v1d)
    lane = jnp.arange(B, dtype=jnp.uint32)
    word_off = (jnp.arange(32, dtype=jnp.uint32) * np.uint32(B))[:, None]

    def eg_body(carry, v1):
        j = carry[16] & np.uint32(N - 1)
        base = (j * np.uint32(32 * B) + lane)[None, :]       # (1, B)
        planes = v1[(base + word_off).astype(jnp.int32)]      # (32, B)
        mixed = [c ^ planes[i] for i, c in enumerate(carry)]
        return tuple(_block_mix_words(mixed))

    def ref_body(carry, vr):
        j = carry[16] & np.uint32(N - 1)
        vj = vr[(j * np.uint32(B) + lane).astype(jnp.int32)]
        return tuple(_block_mix_words(
            [c ^ vj[:, i] for i, c in enumerate(carry)]))

    # ---- bit-exactness: 4 chained steps, both bodies ----
    @partial(jax.jit, static_argnums=(2,))
    def chain(x, v, body_name):
        words = tuple(x[:, i] for i in range(32))
        body = {"eg": eg_body, "ref": ref_body}[body_name]
        for _ in range(4):
            words = body(words, v)
        return jnp.stack(words, axis=-1)

    ref = np.asarray(chain(x, vrows, "ref"))
    got = np.asarray(chain(x, v1d, "eg"))
    exact = bool((ref == got).all())
    print(f"stage1 element-gather 4-step chain: exact={exact}")
    if not exact:
        raise SystemExit("element-gather body wrong — stop here")

    # ---- 1024-step scans ----
    def scan(body):
        @jax.jit
        def run(x, v):
            words = tuple(x[:, i] for i in range(32))

            def step(carry, _):
                return body(carry, v), None

            words, _ = jax.lax.scan(step, words, None, length=STEPS,
                                    unroll=UNROLL)
            return words[0]

        return run

    t_ref = timed(scan(ref_body), x, vrows) / STEPS
    t_eg = timed(scan(eg_body), x, v1d) / STEPS
    print(f"stage2 walk scan: shipping {t_ref * 1e6:8.1f} us/step")
    print(f"                  eg       {t_eg * 1e6:8.1f} us/step "
          f"({t_ref / t_eg:.2f}x)")


if __name__ == "__main__":
    main()
