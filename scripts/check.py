#!/usr/bin/env python3
"""Run the project's static-analysis suite over the tree.

    python scripts/check.py                 # all five checkers + ruff
    python scripts/check.py --json          # machine-readable findings
    python scripts/check.py --checker loop-blocker tpuminter/journal.py

Exit status: 0 when clean (every finding allowlisted with a reason, no
stale allowlist entries), 1 otherwise. Ruff rides the same entry point
when the binary is on PATH; when it is not (the pinned CI image ships
without it) the ruff leg is reported as skipped, loudly, and does not
affect the exit status.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from tpuminter.analysis.core import (  # noqa: E402
    CHECKERS,
    Allowlist,
    run_project,
)


def run_ruff(root: str) -> dict:
    """Generic lint leg: ruff over the configured tree, gated on the
    binary being installed."""
    ruff = shutil.which("ruff")
    if ruff is None:
        return {
            "ran": False,
            "ok": True,
            "note": "ruff not installed — generic-lint leg SKIPPED "
                    "(pip install ruff to enable; config lives in "
                    "pyproject.toml [tool.ruff])",
        }
    proc = subprocess.run(
        [ruff, "check", "--no-fix", "tpuminter", "scripts", "tests"],
        cwd=root, capture_output=True, text=True,
    )
    return {
        "ran": True,
        "ok": proc.returncode == 0,
        "note": (proc.stdout + proc.stderr).strip(),
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "targets", nargs="*", default=[],
        help="dirs/files to check (default: tpuminter scripts)",
    )
    parser.add_argument(
        "--checker", action="append", choices=CHECKERS, default=None,
        help="run only this checker (repeatable; default: all five)",
    )
    parser.add_argument(
        "--allowlist", default=None,
        help="allowlist path (default: tpuminter/analysis/allowlist.json)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit a JSON report instead of human-readable lines",
    )
    parser.add_argument(
        "--no-ruff", action="store_true",
        help="skip the generic-lint (ruff) leg",
    )
    args = parser.parse_args(argv)

    targets = tuple(args.targets) or ("tpuminter", "scripts")
    allowlist = Allowlist.load(args.allowlist)
    report = run_project(
        REPO_ROOT, targets, allowlist=allowlist, checkers=args.checker
    )
    ruff = (
        {"ran": False, "ok": True, "note": "skipped (--no-ruff)"}
        if args.no_ruff else run_ruff(REPO_ROOT)
    )
    ok = report.clean and ruff["ok"]

    if args.as_json:
        print(json.dumps({
            "ok": ok,
            "findings": [f.as_dict() for f in report.findings],
            "suppressed": [f.as_dict() for f in report.suppressed],
            "stale_allowlist_entries": report.stale_entries,
            "ruff": ruff,
        }, indent=2))
    else:
        for line in report.render():
            print(line)
        if not ruff["ok"] or not ruff["ran"]:
            print(f"ruff: {ruff['note']}", file=sys.stderr)
        n_supp = len(report.suppressed)
        n_find = len(report.findings)
        print(
            f"check: {n_find} finding(s), {n_supp} allowlisted, "
            f"{len(report.stale_entries)} stale allowlist entr(ies) — "
            f"{'clean' if ok else 'FAIL'}",
            file=sys.stderr,
        )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
