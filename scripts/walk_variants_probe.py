#!/usr/bin/env python
"""Decompose the fused walk kernel's 672 us/step (walk_pallas_probe):
which in-kernel stage eats the time?

Variants (all run as 1024-step scans with the real data-dependent
gather chain, so totals are far above the 67-119 ms RTT jitter; all
keep the carry dependent on vj so nothing hoists; "wrong math" variants
still chain j through their output):

  tr_only    — kernel writes transpose(vj) only (no xor/salsa):
               isolates the padded-block (2048,32)->(32,2048) transpose.
  tr_dense   — kernel transposes vj bitcast as (512,128) full tiles ->
               (128,512) (Mosaic's optimal XLU path), xors into carry
               rows: is full-tile transpose the fast alternative?
  xor_only   — kernel xors carry with vj BITCAST to word-plane shape
               (free relayout, wrong values): isolates IO + xor at
               dense layouts, no transpose at all.
  salsa_only — kernel runs BlockMix on the carry, vj folded in by one
               dense xor on the packed shape: isolates in-kernel salsa.
  full       — the walk_pallas_probe kernel (transpose + xor + salsa).
  full_g1    — same but grid=1 (one 2 MB block): per-grid-step cost?

Run on the real chip: ``python scripts/walk_variants_probe.py``.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

jax.config.update("jax_compilation_cache_dir", "/tmp/tpuminter-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from tpuminter.ops.scrypt import _block_mix_words  # noqa: E402

B = 16384
N = 1024
LANES = 128
STEPS = N
UNROLL = 2


def sync(x):
    np.asarray(jax.tree.leaves(x)[0])


def timed(fn, *args, reps=3):
    out = fn(*args)
    sync(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        sync(out)
        best = min(best, time.perf_counter() - t0)
    return best


def wm_call(kernel, block_b, n_in=2):
    """pallas_call over word-major carry (32, B/128, 128) + row-major
    vj (B, 32) -> word-major out, grid along the batch."""
    sub_b = block_b // LANES
    specs = [
        pl.BlockSpec((32, sub_b, LANES), lambda i: (0, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((block_b, 32), lambda i: (i, 0),
                     memory_space=pltpu.VMEM),
    ][:n_in]

    def call(*args):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((32, B // LANES, LANES),
                                           jnp.uint32),
            grid=(B // block_b,),
            in_specs=specs,
            out_specs=pl.BlockSpec((32, sub_b, LANES), lambda i: (0, i, 0),
                                   memory_space=pltpu.VMEM),
        )(*args)

    return call


def main():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 2**32, (B, 32), dtype=np.uint32))

    @jax.jit
    def make_v():
        i = jnp.arange(N * B, dtype=jnp.uint32)[:, None]
        j = jnp.arange(32, dtype=jnp.uint32)[None, :]
        h = i * np.uint32(2654435761) + j * np.uint32(0x9E3779B9)
        h ^= h >> 16
        h *= np.uint32(0x85EBCA6B)
        h ^= h >> 13
        return h

    vflat = make_v()
    sync(vflat)
    lane = jnp.arange(B, dtype=jnp.uint32)

    BB = 2048
    SUB = BB // LANES

    # ---- kernels ----
    def k_tr_only(xw_ref, vj_ref, out_ref):
        out_ref[...] = jnp.transpose(vj_ref[...]).reshape(32, SUB, LANES)

    def k_xor_only(xw_ref, vj_ref, out_ref):
        vjp = vj_ref[...].reshape(32, SUB, LANES)  # bitcast, wrong values
        for i in range(32):
            out_ref[i] = xw_ref[i] ^ vjp[i]

    def k_salsa_only(xw_ref, vj_ref, out_ref):
        vjp = vj_ref[...].reshape(32, SUB, LANES)
        words = [xw_ref[i] ^ vjp[i] for i in range(32)]
        mixed = _block_mix_words(words)
        for i in range(32):
            out_ref[i] = mixed[i]

    def k_full(xw_ref, vj_ref, out_ref):
        vjt = jnp.transpose(vj_ref[...]).reshape(32, SUB, LANES)
        words = [xw_ref[i] ^ vjt[i] for i in range(32)]
        mixed = _block_mix_words(words)
        for i in range(32):
            out_ref[i] = mixed[i]

    SUBG1 = B // LANES

    def k_full_g1(xw_ref, vj_ref, out_ref):
        vjt = jnp.transpose(vj_ref[...]).reshape(32, SUBG1, LANES)
        words = [xw_ref[i] ^ vjt[i] for i in range(32)]
        mixed = _block_mix_words(words)
        for i in range(32):
            out_ref[i] = mixed[i]

    # tr_dense works on a different carry shape: (128, B/4)
    def k_tr_dense(xw_ref, vj_ref, out_ref):
        out_ref[...] = xw_ref[...] ^ jnp.transpose(vj_ref[...])

    def tr_dense_call(xw, vj):
        return pl.pallas_call(
            k_tr_dense,
            out_shape=jax.ShapeDtypeStruct((LANES, B // 4), jnp.uint32),
            grid=(B // BB,),
            in_specs=[
                pl.BlockSpec((LANES, BB // 4), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((BB // 4, LANES), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((LANES, BB // 4), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
        )(xw, vj)

    # ---- scans ----
    def scan_wm(call):
        @jax.jit
        def run(x, v):
            def to_wm_bitcast(a):  # free relayout, just to shape the carry
                return a.reshape(32, B // LANES, LANES)

            xw = to_wm_bitcast(x)

            def body(carry, _):
                j = carry[16].reshape(B) & np.uint32(N - 1)
                vj = v[(j * np.uint32(B) + lane).astype(jnp.int32)]
                return call(carry, vj), None

            xw, _ = jax.lax.scan(body, xw, None, length=STEPS, unroll=UNROLL)
            return xw[0, 0]

        return run

    @jax.jit
    def run_tr_dense(x, v):
        xw = x.reshape(B // 4, LANES)
        xw = jnp.transpose(xw)  # (128, B/4) carry

        def body(carry, _):
            j = carry[16].reshape(B // 4)[:B].astype(jnp.uint32)  # junk-but-
            j = j & np.uint32(N - 1)  # data-dependent chain
            j = jnp.concatenate([j, j, j, j])[:B]
            vj = v[(j * np.uint32(B) + lane).astype(jnp.int32)]
            return tr_dense_call(carry, vj.reshape(B // 4, LANES)), None

        carry, _ = jax.lax.scan(body, xw, None, length=STEPS, unroll=UNROLL)
        return carry[0]

    cases = [
        ("tr_only", scan_wm(wm_call(k_tr_only, BB))),
        ("xor_only", scan_wm(wm_call(k_xor_only, BB))),
        ("salsa_only", scan_wm(wm_call(k_salsa_only, BB))),
        ("full", scan_wm(wm_call(k_full, BB))),
        ("full_g1", scan_wm(wm_call(k_full_g1, B))),
        ("tr_dense", run_tr_dense),
    ]
    for name, fn in cases:
        try:
            t = timed(fn, x, vflat) / STEPS
            print(f"{name:12s} {t * 1e6:8.1f} us/step")
        except Exception as e:  # noqa: BLE001
            print(f"{name:12s} FAILED: {type(e).__name__}: {str(e)[:200]}")


if __name__ == "__main__":
    main()
