#!/usr/bin/env python
"""Round-5 scrypt lever, take 6: transpose the gathered rows on the MXU.

Every data-movement spelling of the (B,32)->32x(B,) unpack costs
~550 us/step on this toolchain (takes 1-5: XLA extracts, pallas
operands in any byte layout including the gather's native tiles — even
a null kernel, and a plane-major element gather at 7 ms).  The one
engine not yet tried: the MXU.  Transposition is a matmul with the
identity —

    planes_f32 = dot(I_32, vj_f32, contract dim1 x dim1) -> (32, B)

u32 words split into two 16-bit halves (exact in f32: each partial
product has ONE nonzero term), transposed as two dots, recombined with
a shift+or.  16.7M MACs per half = ~1 us of MXU time; converts are
elementwise (fusible into the gather); dot output layouts are the
compiler's happy path.

Variants (1024-step scans, us/step):
  walk_ref — shipping body (~670 baseline)
  walk_mxu — gather -> split/convert -> 2 identity dots -> recombine ->
             xor + BlockMix on dense (B,) plane vectors

Bit-exactness checked over 4 chained steps first.

Run on the real chip: ``python scripts/walk_mxu_transpose_probe.py``.
"""

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/tpuminter-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from tpuminter.ops.scrypt import _block_mix_words  # noqa: E402

B = 16384
N = 1024
STEPS = N
UNROLL = 2

_DOT_DN = (((1,), (1,)), ((), ()))  # contract dim1 x dim1, no batch


def sync(x):
    np.asarray(jax.tree.leaves(x)[0])


def timed(fn, *args, reps=3):
    out = fn(*args)
    sync(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        sync(out)
        best = min(best, time.perf_counter() - t0)
    return best


def mxu_transpose_u32(vj, eye32):
    """(B, 32) u32 -> (32, B) u32 via two exact f32 identity dots."""
    lo = (vj & np.uint32(0xFFFF)).astype(jnp.float32)
    hi = (vj >> np.uint32(16)).astype(jnp.float32)
    # HIGHEST: the MXU's default bf16 input truncation (8-bit mantissa)
    # mangles 16-bit chunks; the 3-pass decomposition is exact here
    lo_t = jax.lax.dot_general(eye32, lo, _DOT_DN,
                               precision=jax.lax.Precision.HIGHEST,
                               preferred_element_type=jnp.float32)
    hi_t = jax.lax.dot_general(eye32, hi, _DOT_DN,
                               precision=jax.lax.Precision.HIGHEST,
                               preferred_element_type=jnp.float32)
    return (hi_t.astype(jnp.uint32) << np.uint32(16)) | lo_t.astype(jnp.uint32)


def main():
    rng = np.random.default_rng(0)
    x_np = rng.integers(0, 2**32, (B, 32), dtype=np.uint32)
    x = jnp.asarray(x_np)
    eye32 = jnp.eye(32, dtype=jnp.float32)

    @jax.jit
    def make_v():
        i = jnp.arange(N * B, dtype=jnp.uint32)[:, None]
        j = jnp.arange(32, dtype=jnp.uint32)[None, :]
        h = i * np.uint32(2654435761) + j * np.uint32(0x9E3779B9)
        h ^= h >> 16
        h *= np.uint32(0x85EBCA6B)
        h ^= h >> 13
        return h

    vflat = make_v()
    sync(vflat)
    lane = jnp.arange(B, dtype=jnp.uint32)

    def mxu_body(carry, v):
        j = carry[16] & np.uint32(N - 1)
        vj = v[(j * np.uint32(B) + lane).astype(jnp.int32)]
        planes = mxu_transpose_u32(vj, eye32)       # (32, B)
        mixed = [c ^ planes[i] for i, c in enumerate(carry)]
        return tuple(_block_mix_words(mixed))

    def ref_body(carry, v):
        j = carry[16] & np.uint32(N - 1)
        vj = v[(j * np.uint32(B) + lane).astype(jnp.int32)]
        return tuple(_block_mix_words(
            [c ^ vj[:, i] for i, c in enumerate(carry)]))

    @partial(jax.jit, static_argnums=(2,))
    def chain(x, v, which):
        words = tuple(x[:, i] for i in range(32))
        body = {"mxu": mxu_body, "ref": ref_body}[which]
        for _ in range(4):
            words = body(words, v)
        return jnp.stack(words, axis=-1)

    ref = np.asarray(chain(x, vflat, "ref"))
    got = np.asarray(chain(x, vflat, "mxu"))
    exact = bool((ref == got).all())
    print(f"stage1 mxu-transpose 4-step chain: exact={exact}")
    if not exact:
        bad = np.argwhere(ref != got)
        print(f"  first mismatches: {bad[:5]}")
        raise SystemExit("mxu body wrong — stop here")

    def scan(body):
        @jax.jit
        def run(x, v):
            words = tuple(x[:, i] for i in range(32))

            def step(carry, _):
                return body(carry, v), None

            words, _ = jax.lax.scan(step, words, None, length=STEPS,
                                    unroll=UNROLL)
            return words[0]

        return run

    t_ref = timed(scan(ref_body), x, vflat) / STEPS
    t_mxu = timed(scan(mxu_body), x, vflat) / STEPS
    print(f"stage2 walk scan: shipping {t_ref * 1e6:8.1f} us/step")
    print(f"                  mxu      {t_mxu * 1e6:8.1f} us/step "
          f"({t_ref / t_mxu:.2f}x)")


if __name__ == "__main__":
    main()
