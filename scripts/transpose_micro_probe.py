#!/usr/bin/env python
"""Where do 325 ms go? Decompose the Pallas transpose kernel's cost.

walk_pallas_probe stage 1 measured the (16384,32)->(32,16384) u32
transpose kernel at ~325 ms warm — 150x over the ~2 MB HBM floor.  Two
suspects: (a) the lane-padded (block_b, 32) input block (minor dim 32
of 128 lanes -> strided/packed DMA), (b) Mosaic's jnp.transpose
lowering itself.  Each variant below isolates one; all are timed as a
K-rep in-jit scan (carry-chained through the kernel so nothing hoists)
so the ~100 ms tunnel RTT amortizes away.

Variants:
  copy_padded     — (block_b,32) block in, (block_b,32) out; xor carry.
                    Measures the padded-block DMA + launch floor.
  copy_dense      — same data bitcast to (B/4,128) dense blocks.
                    Measures the unpadded floor.
  transpose_pad   — (block_b,32) in, transpose, (32,sub,128) out.
                    The walk kernel's relayout as probed in stage 1.
  transpose_dense — (B/4,128) bitcast in, (128,B/4) transposed out
                    (full 128x128-tile transposes, no padding).

Run on the real chip: ``python scripts/transpose_micro_probe.py``.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

jax.config.update("jax_compilation_cache_dir", "/tmp/tpuminter-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

B = 16384
LANES = 128
BLOCK_B = 2048
SUB_B = BLOCK_B // LANES
REPS = 64


def sync(x):
    np.asarray(jax.tree.leaves(x)[0])


def timed(fn, *args, reps=3):
    out = fn(*args)
    sync(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        sync(out)
        best = min(best, time.perf_counter() - t0)
    return best / REPS


def scan_reps(step):
    """Chain `step` REPS times through the carry inside one jit."""

    @jax.jit
    def run(x):
        def body(c, _):
            return step(c), None

        c, _ = jax.lax.scan(body, x, None, length=REPS)
        # sync on a scalar: pulling the 2 MB carry through the ~10-20
        # MB/s tunnel would dominate the measurement (first probe's bug)
        return c.sum(dtype=jnp.uint32)

    return run


def main():
    rng = np.random.default_rng(0)
    x_np = rng.integers(0, 2**32, (B, 32), dtype=np.uint32)

    # ---- copy_padded: (block_b, 32) blocks, carry-chained xor ----
    def _copy_pad_kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] ^ np.uint32(1)

    def copy_padded(x):
        return pl.pallas_call(
            _copy_pad_kernel,
            out_shape=jax.ShapeDtypeStruct((B, 32), jnp.uint32),
            grid=(B // BLOCK_B,),
            in_specs=[
                pl.BlockSpec((BLOCK_B, 32), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
            ],
            out_specs=pl.BlockSpec((BLOCK_B, 32), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
        )(x)

    # ---- copy_dense: same bytes as (B/4, 128) blocks ----
    def copy_dense(x):
        xd = x.reshape(B // 4, LANES)
        out = pl.pallas_call(
            _copy_pad_kernel,
            out_shape=jax.ShapeDtypeStruct((B // 4, LANES), jnp.uint32),
            grid=(B // BLOCK_B,),
            in_specs=[
                pl.BlockSpec((BLOCK_B // 4, LANES), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
            ],
            out_specs=pl.BlockSpec((BLOCK_B // 4, LANES), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
        )(xd)
        return out.reshape(B, 32)

    # ---- transpose_pad: stage-1 kernel, carry-chained via transpose back
    # in XLA would re-measure the strided unpack, so chain on a word slice:
    # out word-major -> feed next rep by bitcasting (free reshape) ----
    def _tr_pad_kernel(x_ref, o_ref):
        o_ref[...] = jnp.transpose(x_ref[...]).reshape(32, SUB_B, LANES)

    def transpose_pad(x):
        out = pl.pallas_call(
            _tr_pad_kernel,
            out_shape=jax.ShapeDtypeStruct((32, B // LANES, LANES), jnp.uint32),
            grid=(B // BLOCK_B,),
            in_specs=[
                pl.BlockSpec((BLOCK_B, 32), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
            ],
            out_specs=pl.BlockSpec((32, SUB_B, LANES), lambda i: (0, i, 0),
                                   memory_space=pltpu.VMEM),
        )(x)
        # free relayout back to (B, 32) shape for the next rep: NOT a
        # mathematical inverse, but keeps bytes flowing through the kernel
        return out.reshape(B, 32)

    # ---- transpose_dense: full-tile (B/4,128) -> (128,B/4) ----
    def _tr_dense_kernel(x_ref, o_ref):
        o_ref[...] = jnp.transpose(x_ref[...])

    def transpose_dense(x):
        xd = x.reshape(B // 4, LANES)
        out = pl.pallas_call(
            _tr_dense_kernel,
            out_shape=jax.ShapeDtypeStruct((LANES, B // 4), jnp.uint32),
            grid=(B // BLOCK_B,),
            in_specs=[
                pl.BlockSpec((BLOCK_B // 4, LANES), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
            ],
            out_specs=pl.BlockSpec((LANES, BLOCK_B // 4), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
        )(xd)
        return out.reshape(B, 32)

    x = jnp.asarray(x_np)
    for name, step in [
        ("copy_padded", copy_padded),
        ("copy_dense", copy_dense),
        ("transpose_pad", transpose_pad),
        ("transpose_dense", transpose_dense),
    ]:
        try:
            t0 = time.perf_counter()
            fn = scan_reps(step)
            out = fn(x)
            sync(out)
            compile_s = time.perf_counter() - t0
            t = timed(fn, x)
            print(f"{name:16s} {t * 1e6:9.1f} us/call "
                  f"({2 * B * 32 * 4 / t / 1e9:6.1f} GB/s r+w, "
                  f"compile {compile_s:.0f}s)")
        except Exception as e:  # noqa: BLE001 — print and keep probing
            print(f"{name:16s} FAILED: {type(e).__name__}: {e}")


if __name__ == "__main__":
    main()
