#!/usr/bin/env python
"""The measured rejection of a fused Pallas ROMix (VERDICT r3 next #4).

scryptROMix phase 2 needs, per step, for every batch lane b, the
128-byte row ``V[j_b]`` at a data-dependent index. Inside a Pallas TPU
kernel that can only be a per-lane scalar-issued DMA (Mosaic has no
vectorized cross-lane HBM gather), and Mosaic's 128-element minor-slice
alignment forces rows padded to 512 bytes (or packed tiles + in-VMEM
dynamic selects). This probe measures exactly that primitive: a
pipelined ring of row DMAs (NSEM outstanding), rep-scaled inside the
kernel so the ~100 ms tunnel RTT cancels out of the slope.

Measured on the v5e (2026-07-30, reps 32 vs 256 at B=8192):
**38.7 ns per row** (13.2 GB/s on the padded 512-byte rows). The
shipping jnp path's XLA row gather moves the same logical rows at
~5.5 ns each (23 GB/s on unpadded 128-byte rows, PERF.md), and the
WHOLE shipping ROMix step — gather + unpack + BlockMix + pack — costs
~28 ns/row. The fused kernel's gather alone is 1.4× the entire current
step with zero compute attached, so the design is rejected on
measurement, not estimate. The ~2× hoped for in PERF.md's sketch would
have required ~10 ns/row scalar DMA issue; the hardware does 4× worse.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NSEM = 8  # DMA ring depth


def build_gather_kernel(rows: int, batch: int, reps: int):
    """One call = ``reps`` passes of ``batch`` random-row DMAs."""

    def kernel(idx_ref, vflat_ref, out_ref, scratch, sems):
        def pass_body(r, acc):
            def issue(b, _):
                row = (idx_ref[b] + r * 977) % rows
                pltpu.make_async_copy(
                    vflat_ref.at[pl.ds(row, 1), :],
                    scratch.at[pl.ds(b % (2 * NSEM), 1), :],
                    sems.at[b % NSEM],
                ).start()
                return 0

            def body(b, _):
                pltpu.make_async_copy(
                    vflat_ref.at[pl.ds(0, 1), :],
                    scratch.at[pl.ds(b % (2 * NSEM), 1), :],
                    sems.at[b % NSEM],
                ).wait()
                row = (idx_ref[b + NSEM] + r * 977) % rows
                pltpu.make_async_copy(
                    vflat_ref.at[pl.ds(row, 1), :],
                    scratch.at[pl.ds((b + NSEM) % (2 * NSEM), 1), :],
                    sems.at[(b + NSEM) % NSEM],
                ).start()
                return 0

            def drain(b, _):
                pltpu.make_async_copy(
                    vflat_ref.at[pl.ds(0, 1), :],
                    scratch.at[pl.ds(b % (2 * NSEM), 1), :],
                    sems.at[b % NSEM],
                ).wait()
                return 0

            jax.lax.fori_loop(0, NSEM, issue, 0)
            jax.lax.fori_loop(0, batch - NSEM, body, 0)
            jax.lax.fori_loop(batch - NSEM, batch, drain, 0)
            return acc + scratch[0, 0]

        out_ref[0, 0] = jax.lax.fori_loop(0, reps, pass_body, jnp.uint32(0))

    return pl.pallas_call(
        kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((2 * NSEM, 128), jnp.uint32),
            pltpu.SemaphoreType.DMA((NSEM,)),
        ],
    )


def main():
    assert jax.default_backend() != "cpu", "needs the real chip"
    n, batch = 256, 8192
    rows = n * batch
    # fill V on device: a 1 GiB host upload through the tunnel takes
    # minutes and measures nothing
    vflat = jax.jit(
        lambda: (jnp.arange(rows, dtype=jnp.uint32)[:, None]
                 * jnp.uint32(2654435761)
                 + jnp.arange(128, dtype=jnp.uint32)[None, :])
    )()
    vflat.block_until_ready()
    rng = np.random.default_rng(0)
    idx = jnp.asarray(rng.integers(0, rows, batch, dtype=np.int32))

    def best(k, nrun=6):
        ts = []
        for _ in range(nrun):
            t0 = time.perf_counter()
            int(k(idx, vflat)[0, 0])
            ts.append(time.perf_counter() - t0)
        return min(ts)

    reps_lo, reps_hi = 32, 256
    k_lo = build_gather_kernel(rows, batch, reps_lo)
    k_hi = build_gather_kernel(rows, batch, reps_hi)
    int(k_lo(idx, vflat)[0, 0])
    int(k_hi(idx, vflat)[0, 0])
    b_lo, b_hi = best(k_lo), best(k_hi)
    per_pass = (b_hi - b_lo) / (reps_hi - reps_lo)
    per_row = per_pass / batch
    print(f"reps={reps_lo}: {b_lo*1e3:.1f} ms   reps={reps_hi}: {b_hi*1e3:.1f} ms")
    print(
        f"per {batch}-row pass: {per_pass*1e6:.1f} us   "
        f"per-row: {per_row*1e9:.2f} ns   "
        f"({512/per_row/1e9:.1f} GB/s on 512B-padded rows, "
        f"{128/per_row/1e9:.1f} GB/s useful)"
    )
    print(
        "shipping jnp step (gather+unpack+BlockMix+pack) is ~28 ns/row; "
        "XLA row gather alone ~5.5 ns/row (PERF.md) — "
        f"verdict: {'REJECT' if per_row > 28e-9 else 'VIABLE'} fused Pallas ROMix"
    )


if __name__ == "__main__":
    main()
