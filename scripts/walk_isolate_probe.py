#!/usr/bin/env python
"""Isolate WHICH consumer of the word-major gather bytes costs 550 us.

Facts so far (round-5 probes):
- gather + barrier + transpose/reshape + read ONE word plane = 86 us
  (gather_materialize_probe barrier_tr) — materializing the gather's
  word-major bytes is free;
- every FULL walk variant (XLA extracts, pallas with in-kernel
  transpose, pallas on byte-clean word-major operands) = 650-690 us.

Somewhere between "read one plane" and "full body" sits a ~550 us op.
Incremental scans (1024 steps, us/step):

  one_plane   — barrier_tr reproduction (baseline, ~86).
  all_planes  — xor-fold ALL 32 planes into the carry words; no salsa.
  plus_salsa  — all_planes + BlockMix (the full pure-XLA word-major
                walk body).
  pallas_xs   — barrier-pinned word-major bytes -> pallas xor+salsa
                kernel (the take-2 design on the proven-cheap bytes).

Run on the real chip: ``python scripts/walk_isolate_probe.py``.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

jax.config.update("jax_compilation_cache_dir", "/tmp/tpuminter-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from tpuminter.ops.scrypt import _block_mix_words  # noqa: E402

B = 16384
N = 1024
LANES = 128
ROWS = B // LANES
BLOCK_B = 2048
SUB = BLOCK_B // LANES
STEPS = N
UNROLL = 2


def sync(x):
    np.asarray(jax.tree.leaves(x)[0])


def timed(fn, *args, reps=3):
    out = fn(*args)
    sync(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        sync(out)
        best = min(best, time.perf_counter() - t0)
    return best


def _xs_kernel(xw_ref, vj_ref, out_ref):
    words = [xw_ref[i] ^ vj_ref[i] for i in range(32)]
    mixed = _block_mix_words(words)
    for i in range(32):
        out_ref[i] = mixed[i]


def fused_xor_salsa(xw, vjt):
    spec = pl.BlockSpec((32, SUB, LANES), lambda i: (0, i, 0),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _xs_kernel,
        out_shape=jax.ShapeDtypeStruct((32, ROWS, LANES), jnp.uint32),
        grid=(B // BLOCK_B,),
        in_specs=[spec, spec],
        out_specs=spec,
    )(xw, vjt)


def main():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 2**32, (B, 32), dtype=np.uint32))

    @jax.jit
    def make_v():
        i = jnp.arange(N * B, dtype=jnp.uint32)[:, None]
        j = jnp.arange(32, dtype=jnp.uint32)[None, :]
        h = i * np.uint32(2654435761) + j * np.uint32(0x9E3779B9)
        h ^= h >> 16
        h *= np.uint32(0x85EBCA6B)
        h ^= h >> 13
        return h

    vflat = make_v()
    sync(vflat)
    lane = jnp.arange(B, dtype=jnp.uint32)

    def scan32(body):
        @jax.jit
        def run(x, v):
            words = tuple(x[:, i] for i in range(32))

            def step(carry, _):
                return body(carry, v), None

            words, _ = jax.lax.scan(step, words, None, length=STEPS,
                                    unroll=UNROLL)
            return words[0]

        return run

    def gather_wm(v, carry):
        j = carry[16] & np.uint32(N - 1)
        vj = v[(j * np.uint32(B) + lane).astype(jnp.int32)]
        vj = jax.lax.optimization_barrier(vj)
        return jnp.transpose(vj).reshape(32, ROWS, LANES)

    def body_one_plane(carry, v):
        vjt = gather_wm(v, carry)
        out = list(carry)
        out[16] = out[16] ^ vjt[16].reshape(B)
        return tuple(out)

    def body_all_planes(carry, v):
        vjt = gather_wm(v, carry)
        return tuple(c ^ vjt[i].reshape(B) for i, c in enumerate(carry))

    def body_plus_salsa(carry, v):
        vjt = gather_wm(v, carry)
        mixed = [c ^ vjt[i].reshape(B) for i, c in enumerate(carry)]
        return tuple(_block_mix_words(mixed))

    def scan_pallas():
        @jax.jit
        def run(x, v):
            xw = jnp.transpose(x).reshape(32, ROWS, LANES)

            def step(carry, _):
                vjt = gather_wm(v, [carry[16].reshape(B)] * 17)
                return fused_xor_salsa(carry, vjt), None

            xw, _ = jax.lax.scan(step, xw, None, length=STEPS, unroll=UNROLL)
            return xw[0, 0]

        return run

    cases = [
        ("one_plane", scan32(body_one_plane)),
        ("all_planes", scan32(body_all_planes)),
        ("plus_salsa", scan32(body_plus_salsa)),
        ("pallas_xs", scan_pallas()),
    ]
    for name, fn in cases:
        try:
            t = timed(fn, x, vflat) / STEPS
            print(f"{name:12s} {t * 1e6:8.1f} us/step")
        except Exception as e:  # noqa: BLE001
            print(f"{name:12s} FAILED: {type(e).__name__}: {str(e)[:160]}")


if __name__ == "__main__":
    main()
