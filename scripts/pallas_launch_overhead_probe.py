#!/usr/bin/env python
"""Pin down the per-pallas-call overhead inside lax.scan on this backend.

transpose_micro_probe measured ~1.54 ms/call for EVERY kernel variant —
including a plain dense 2 MB copy (should be ~5 us at HBM rates) — with
the transpose itself free.  That smells like a fixed per-launch cost.
This probe varies the knobs that distinguish the candidate causes:

  xla_xor          — scan body is pure-XLA (c ^ 1) on the same 2 MB:
                     the known ~90 us/iter axon scan floor (control).
  grid8 / grid1    — dense 2 MB copy kernel with an 8-step vs 1-step
                     grid: is the cost per grid step or per launch?
  tiny             — (8,128) 4 KiB copy kernel: is it size-dependent?
  grid1_reps16/128 — REPS scaling at fixed variant: confirms the
                     per-call (not per-run) attribution.

Run on the real chip: ``python scripts/pallas_launch_overhead_probe.py``.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

jax.config.update("jax_compilation_cache_dir", "/tmp/tpuminter-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

B = 16384
LANES = 128


def sync(x):
    np.asarray(jax.tree.leaves(x)[0])


def run_case(name, step, x, reps_lo=512, reps_hi=2048):
    """Slope timing: t(reps_hi) - t(reps_lo) over the rep delta, so the
    ~100 ms tunnel dispatch RTT (which swamped the first two probes'
    small-rep totals) cancels exactly."""

    def make(reps):
        @jax.jit
        def run(x):
            def body(c, _):
                return step(c), None

            c, _ = jax.lax.scan(body, x, None, length=reps)
            return c.sum(dtype=jnp.uint32)

        return run

    times = {}
    for reps in (reps_lo, reps_hi):
        run = make(reps)
        sync(run(x))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            sync(run(x))
            best = min(best, time.perf_counter() - t0)
        times[reps] = best
    slope = (times[reps_hi] - times[reps_lo]) / (reps_hi - reps_lo)
    print(f"{name:16s} {slope * 1e6:9.1f} us/call  "
          f"(totals {times[reps_lo] * 1e3:.0f} / {times[reps_hi] * 1e3:.0f} ms)")


def _xor_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] ^ np.uint32(1)


def copy_grid(n_grid):
    rows = B // 4

    def step(c):
        return pl.pallas_call(
            _xor_kernel,
            out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.uint32),
            grid=(n_grid,),
            in_specs=[
                pl.BlockSpec((rows // n_grid, LANES), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
            ],
            out_specs=pl.BlockSpec((rows // n_grid, LANES), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
        )(c)

    return step


def tiny_step(c):
    return pl.pallas_call(
        _xor_kernel,
        out_shape=jax.ShapeDtypeStruct((8, LANES), jnp.uint32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )(c)


def main():
    rng = np.random.default_rng(0)
    big = jnp.asarray(rng.integers(0, 2**32, (B // 4, LANES), dtype=np.uint32))
    small = jnp.asarray(rng.integers(0, 2**32, (8, LANES), dtype=np.uint32))

    run_case("xla_xor", lambda c: c ^ np.uint32(1), big)
    run_case("grid8", copy_grid(8), big)
    run_case("grid1", copy_grid(1), big)
    run_case("tiny", tiny_step, small)


if __name__ == "__main__":
    main()
