#!/usr/bin/env python
"""Round-5 scrypt lever, take 2: hand Pallas the WORD-MAJOR view.

hlo_layout_check found the smoking gun: XLA's row-gather naturally
produces its (B,32) output in layout {0,1} — word-major bytes — and
the 550 us/step was %copy.5, the layout conversion to the row-major
{1,0} operand layout pallas demands.  The data is already word-major
in memory; asking for it row-major un-transposes it at 3.6 GB/s.

Fix under test: transpose the gather output LOGICALLY in XLA
(``vj.T.reshape(32, B//128, 128)``) so the pallas operand's default
{2,1,0} layout lands on the same bytes the gather already wrote (a
bitcast, if layout assignment cooperates), and the kernel does pure
xor + BlockMix on dense word planes — no transpose anywhere.

Stages:
  1. bit-exactness of the fused walk vs the shipping body (4 chained
     steps, real data-dependent gathers).
  2. 1024-step walk scan: fused vs shipping, us/step.
  3. grep the compiled HLO: is there still a >64 KiB copy in the body?

Run on the real chip: ``python scripts/walk_wm_probe.py``.
"""

import re
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

jax.config.update("jax_compilation_cache_dir", "/tmp/tpuminter-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from tpuminter.ops.scrypt import _block_mix_words  # noqa: E402

B = 16384
N = 1024
LANES = 128
ROWS = B // LANES
BLOCK_B = 2048
SUB = BLOCK_B // LANES
STEPS = N
UNROLL = 2


def sync(x):
    np.asarray(jax.tree.leaves(x)[0])


def timed(fn, *args, reps=3):
    out = fn(*args)
    sync(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        sync(out)
        best = min(best, time.perf_counter() - t0)
    return best


def _xs_kernel(xw_ref, vj_ref, out_ref):
    words = [xw_ref[i] ^ vj_ref[i] for i in range(32)]
    mixed = _block_mix_words(words)
    for i in range(32):
        out_ref[i] = mixed[i]


def fused_xor_salsa(xw, vjt):
    spec = pl.BlockSpec((32, SUB, LANES), lambda i: (0, i, 0),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _xs_kernel,
        out_shape=jax.ShapeDtypeStruct((32, ROWS, LANES), jnp.uint32),
        grid=(B // BLOCK_B,),
        in_specs=[spec, spec],
        out_specs=spec,
    )(xw, vjt)


def main():
    rng = np.random.default_rng(0)
    x_np = rng.integers(0, 2**32, (B, 32), dtype=np.uint32)
    x = jnp.asarray(x_np)

    @jax.jit
    def make_v():
        i = jnp.arange(N * B, dtype=jnp.uint32)[:, None]
        j = jnp.arange(32, dtype=jnp.uint32)[None, :]
        h = i * np.uint32(2654435761) + j * np.uint32(0x9E3779B9)
        h ^= h >> 16
        h *= np.uint32(0x85EBCA6B)
        h ^= h >> 13
        return h

    vflat = make_v()
    sync(vflat)
    lane = jnp.arange(B, dtype=jnp.uint32)

    def gather(v, j):
        return v[(j * np.uint32(B) + lane).astype(jnp.int32)]

    def wm_body(carry, vj):
        # the barrier pins the gather output to its NATIVE {0,1}
        # (word-major) layout; without it layout assignment propagates
        # the custom call's default-layout preference back into the
        # gather and materializes {1,0} first — a 550 us transpose
        # (gather_materialize_probe: barrier_tr 86 us vs 670 without)
        vj = jax.lax.optimization_barrier(vj)
        vjt = jnp.transpose(vj).reshape(32, ROWS, LANES)
        return fused_xor_salsa(carry, vjt)

    # ---- stage 1: bit-exactness over 4 chained steps ----
    @partial(jax.jit, static_argnums=2)
    def ref_steps(x, v, k):
        words = tuple(x[:, i] for i in range(32))
        for _ in range(k):
            j = words[16] & np.uint32(N - 1)
            vjk = gather(v, j)
            mixed = [c ^ vjk[:, i] for i, c in enumerate(words)]
            words = tuple(_block_mix_words(mixed))
        return jnp.stack(words, axis=-1)

    @partial(jax.jit, static_argnums=2)
    def fused_steps(x, v, k):
        xw = jnp.transpose(x).reshape(32, ROWS, LANES)
        for _ in range(k):
            j = xw[16].reshape(B) & np.uint32(N - 1)
            xw = wm_body(xw, gather(v, j))
        return jnp.transpose(xw.reshape(32, B))

    ref = np.asarray(ref_steps(x, vflat, 4))
    got = np.asarray(fused_steps(x, vflat, 4))
    exact = bool((ref == got).all())
    print(f"stage1 fused 4-step chain: exact={exact}")
    if not exact:
        raise SystemExit("fused kernel wrong — stop here")

    # ---- stage 2: 1024-step walk scan timing ----
    @jax.jit
    def walk_ref(x, v):
        words = tuple(x[:, i] for i in range(32))

        def body(carry, _):
            j = carry[16] & np.uint32(N - 1)
            vjk = gather(v, j)
            mixed = [c ^ vjk[:, i] for i, c in enumerate(carry)]
            return tuple(_block_mix_words(mixed)), None

        words, _ = jax.lax.scan(body, words, None, length=STEPS, unroll=UNROLL)
        return words[0]

    @jax.jit
    def walk_fused(x, v):
        xw = jnp.transpose(x).reshape(32, ROWS, LANES)

        def body(carry, _):
            j = carry[16].reshape(B) & np.uint32(N - 1)
            return wm_body(carry, gather(v, j)), None

        xw, _ = jax.lax.scan(body, xw, None, length=STEPS, unroll=UNROLL)
        return xw[0, 0]

    t_ref = timed(walk_ref, x, vflat) / STEPS
    t_fused = timed(walk_fused, x, vflat) / STEPS
    print(f"stage2 walk scan: shipping {t_ref * 1e6:8.1f} us/step")
    print(f"                  fused    {t_fused * 1e6:8.1f} us/step "
          f"({t_ref / t_fused:.2f}x)")

    # ---- stage 3: any big copies left in the loop body? ----
    txt = jax.jit(walk_fused).lower(x, vflat).compile().as_text()
    big = [l.strip()[:160] for l in txt.splitlines()
           if re.search(r"= \S*u32\[(16384,32|32,16384|32,128,128)\]\S* "
                        r"(copy|transpose)\(", l.strip())]
    print(f"stage3 body-sized copies/transposes in HLO: {len(big)}")
    for l in big[:6]:
        print("  ", l)


if __name__ == "__main__":
    main()
