#!/usr/bin/env python
"""Dump the compiled HLO of the fused-walk scan body and hunt for
layout-conversion copies XLA inserts around the Pallas custom call.

walk_variants_probe showed EVERY kernel variant costs ~650 us/step —
including transpose-only and dense-transpose — while bare copy kernels
in a scan cost <40 us/call.  Prime suspect: the scan's loop-carried
(32,128,128) buffer gets a layout the custom call doesn't accept, so
layout assignment inserts a per-iteration copy (2 MB at the known
3.6 GB/s strided rate = the observed ~550 us).

Prints every `copy`/`transpose`/`bitcast` op in the while-body with its
operand/result layouts.  CPU-safe: only lowers/compiles, never runs —
but compile for the TPU target so the real layout assignment runs.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

B = 16384
N = 1024
LANES = 128
BB = 2048
SUB = BB // LANES


def k_tr_only(xw_ref, vj_ref, out_ref):
    out_ref[...] = jnp.transpose(vj_ref[...]).reshape(32, SUB, LANES)


def call(xw, vj):
    return pl.pallas_call(
        k_tr_only,
        out_shape=jax.ShapeDtypeStruct((32, B // LANES, LANES), jnp.uint32),
        grid=(B // BB,),
        in_specs=[
            pl.BlockSpec((32, SUB, LANES), lambda i: (0, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BB, 32), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((32, SUB, LANES), lambda i: (0, i, 0),
                               memory_space=pltpu.VMEM),
    )(xw, vj)


@jax.jit
def run(x, v):
    lane = jnp.arange(B, dtype=jnp.uint32)
    xw = x.reshape(32, B // LANES, LANES)

    def body(carry, _):
        j = carry[16].reshape(B) & np.uint32(N - 1)
        vj = v[(j * np.uint32(B) + lane).astype(jnp.int32)]
        return call(carry, vj), None

    xw, _ = jax.lax.scan(body, xw, None, length=N, unroll=1)
    return xw[0, 0]


def main():
    x = jnp.zeros((B, 32), jnp.uint32)
    v = jnp.zeros((N * B, 32), jnp.uint32)
    txt = jax.jit(run).lower(x, v).compile().as_text()
    # find the while-body computation and print copy-ish ops with layouts
    interesting = []
    for line in txt.splitlines():
        if re.search(r"=\s+\S+\s+(copy|transpose|bitcast)\(", line):
            interesting.append(line.strip())
    print(f"{len(interesting)} copy/transpose/bitcast ops:")
    for line in interesting:
        print("  ", line[:240])
    # also show the custom-call signature lines (operand layouts)
    for line in txt.splitlines():
        if "custom-call" in line and "tpu" in line.lower():
            print("CC:", line.strip()[:300])


if __name__ == "__main__":
    main()
