#!/usr/bin/env python
"""Measure the CPU baselines BASELINE.md calls for (SURVEY.md §6: "get a
*measured* CPU baseline ... so speedups are grounded"; VERDICT r2 #6).

Two measurements, printed as one JSON line:

1. ``single_worker_mhs`` — one ``CpuMiner`` (the reference-style
   hashlib hot loop) exhausting a fixed TARGET range in-process, driven
   through its real generator interface.
2. ``aggregate_8_workers_mhs`` — the reference's distributed config
   (BASELINE.json:8): a real coordinator process and EIGHT worker
   *processes* (separate interpreters — the GIL forbids measuring an
   aggregate inside one process) mining one exhaustion job end-to-end
   through the LSP control plane, timed at the client.

Both use an unbeatable target (1) so the sweep never early-exits and
``searched`` is exactly the range size. Also records the single-core
scrypt rate (``hashlib.scrypt``) for the memory-hard dialect's
denominator.

Usage: ``python scripts/cpu_baseline.py [--range-log2 21]``
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import struct
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpuminter import chain  # noqa: E402
from tpuminter.protocol import PowMode, Request  # noqa: E402
from tpuminter.worker import CpuMiner  # noqa: E402

HDR = chain.GENESIS_HEADER.pack()


def bench_single(range_log2: int) -> float:
    n = 1 << range_log2
    req = Request(job_id=1, mode=PowMode.TARGET, lower=0, upper=n - 1,
                  header=HDR, target=1)
    t0 = time.perf_counter()
    result = None
    for item in CpuMiner(batch=65536).mine(req):
        if item is not None:
            result = item
    dt = time.perf_counter() - t0
    assert result is not None and result.searched == n
    return n / dt


def bench_scrypt_single(samples: int = 512) -> float:
    prefix = HDR[:76]
    t0 = time.perf_counter()
    for i in range(samples):
        chain.scrypt_hash(prefix + struct.pack("<I", i))
    return samples / (time.perf_counter() - t0)


def bench_cluster(range_log2: int, n_workers: int = 8,
                  port: int = 47421) -> float:
    n = 1 << range_log2
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # PREPEND: clobbering PYTHONPATH would drop site hooks the image
    # relies on (e.g. the TPU plugin registration dir)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, env.get("PYTHONPATH")) if p
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "tpuminter.coordinator", str(port)],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
    ]
    try:
        time.sleep(1.0)
        procs += [
            subprocess.Popen(
                [sys.executable, "-m", "tpuminter.worker", f"127.0.0.1:{port}"],
                env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            for _ in range(n_workers)
        ]
        time.sleep(2.0)  # workers join

        async def run_job() -> float:
            from tpuminter.client import submit

            req = Request(job_id=1, mode=PowMode.TARGET, lower=0,
                          upper=n - 1, header=HDR, target=1)
            t0 = time.perf_counter()
            result = await submit("127.0.0.1", port, req)
            dt = time.perf_counter() - t0
            assert result.searched == n, f"short search: {result.searched}"
            return n / dt

        return asyncio.run(run_job())
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--range-log2", type=int, default=21,
                    help="single-worker range; the cluster job uses 8x this")
    args = ap.parse_args()
    single = bench_single(args.range_log2)
    aggregate = bench_cluster(args.range_log2 + 3)
    scrypt = bench_scrypt_single()
    print(json.dumps({
        "single_worker_mhs": round(single / 1e6, 4),
        "aggregate_8_workers_mhs": round(aggregate / 1e6, 4),
        "scrypt_single_core_khs": round(scrypt / 1e3, 3),
    }))


if __name__ == "__main__":
    main()
