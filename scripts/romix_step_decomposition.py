#!/usr/bin/env python
"""Decompose the scrypt walk step's wall-clock on the real chip.

PERF.md records the shipping ROMix at ~380 us per step AVERAGED over
fill+walk (B=16384, unroll=2); this probe splits one WALK step (the
expensive kind) into its additive components via five scan variants:

  loop      — scan body = carry + 1 (per-iteration floor of lax.scan
              on this backend)
  gather    — loop + the flat row-gather, folded into the carry via a
              dense row-reduce (no per-word extracts)
  extracts  — loop + gather + the 32 ``vj[:, i]`` column extracts + xor
              (the (B,32)->32x(B,) "unpack"; strided cross-lane ops)
  salsa     — loop + _block_mix_words on the carry (no gather at all)
  full      — the shipping walk body (gather + extracts + xor + salsa)

All variants keep the carry data-dependent on their own work so XLA
cannot hoist anything out of the scan. Additivity check: full should
be close to extracts + salsa - loop.

Run on the real chip: ``python scripts/romix_step_decomposition.py``.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/tpuminter-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from tpuminter.ops.scrypt import _block_mix_words  # noqa: E402

B = 16384
N = 1024
UNROLL = 2
STEPS = N  # one walk phase's worth


def timed(fn, x, vflat, reps=3):
    out = fn(x, vflat)
    np.asarray(jax.tree.leaves(out)[0])  # hard warmup sync, same as below
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(x, vflat)
        np.asarray(jax.tree.leaves(out)[0])  # hard sync (PERF.md: block_until_ready unreliable)
        best = min(best, time.perf_counter() - t0)
    return best / STEPS


def main():
    rng = np.random.default_rng(0)
    x0 = jnp.asarray(rng.integers(0, 2**32, (B, 32), dtype=np.uint32))
    # V as a jit ARGUMENT, not a captured constant (a 2 GiB closure
    # constant explodes lowering time and memory)
    vflat = jnp.asarray(rng.integers(0, 2**32, (N * B, 32), dtype=np.uint32))
    lane = jnp.arange(B, dtype=jnp.uint32)

    def scan(body):
        @jax.jit
        def run(x, v):
            words = tuple(x[:, i] for i in range(32))
            words, _ = jax.lax.scan(
                lambda c, _: body(c, v), words, None,
                length=STEPS, unroll=UNROLL,
            )
            return words[0]
        return run

    def body_loop(carry, v):
        return tuple(c + np.uint32(1) for c in carry), None

    def gather_row(carry, v):
        j = carry[16] & np.uint32(N - 1)
        return v[(j * np.uint32(B) + lane).astype(jnp.int32)]

    def body_gather(carry, v):
        vj = gather_row(carry, v)
        s = vj.sum(axis=1, dtype=jnp.uint32)  # dense row fold, no extracts
        out = list(carry)
        # fold into word 16 so the NEXT step's gather index chases this
        # step's data — a loop-invariant j would measure constant-address
        # gathers (and invite hoisting), not the pointer walk
        out[16] = out[16] ^ s
        return tuple(out), None

    def body_extracts(carry, v):
        vj = gather_row(carry, v)
        return tuple(c ^ vj[:, i] for i, c in enumerate(carry)), None

    def body_salsa(carry, v):
        return tuple(_block_mix_words(list(carry))), None

    def body_full(carry, v):
        vj = gather_row(carry, v)
        mixed = [c ^ vj[:, i] for i, c in enumerate(carry)]
        return tuple(_block_mix_words(mixed)), None

    results = {}
    for name, body in [
        ("loop", body_loop),
        ("gather", body_gather),
        ("extracts", body_extracts),
        ("salsa", body_salsa),
        ("full", body_full),
    ]:
        t = timed(scan(body), x0, vflat)
        results[name] = t
        print(f"{name:9s} {t * 1e6:8.1f} us/step")

    loop = results["loop"]
    print("\ncomponents (us/step):")
    print(f"  loop floor       {loop * 1e6:8.1f}")
    print(f"  row gather       {(results['gather'] - loop) * 1e6:8.1f}")
    print(f"  32 col extracts  {(results['extracts'] - results['gather']) * 1e6:8.1f}")
    print(f"  blockmix (salsa) {(results['salsa'] - loop) * 1e6:8.1f}")
    additive = results["extracts"] + results["salsa"] - loop
    print(f"  additivity: extracts+salsa-loop = {additive * 1e6:.1f} "
          f"vs full = {results['full'] * 1e6:.1f}")


if __name__ == "__main__":
    main()
