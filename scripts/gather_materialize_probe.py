#!/usr/bin/env python
"""Is the 550 us the gather's MATERIALIZATION, not a layout copy?

Round 4 measured the walk's row-gather at 29 us — but in that variant
the gather FUSED into a row-reduce (vj.sum(axis=1)) and its (B,32)
output never materialized as a buffer.  Every pallas variant since
(probes round 5) pays ~550 us regardless of kernel content, and the
HLO always shows the gather materializing a 2 MB buffer plus a layout
op.  Hypothesis H-mat: writing the gathered rows out as a standalone
(B,32) buffer is itself the 3.6 GB/s-class op; H-layout: the write is
fine and the layout conversion to the custom call's default layout is
the cost.

Scans (1024 steps, carry-chained, us/step):
  fused_reduce  — gather + vj.sum(axis=1) folded into the carry
                  (round-4 baseline; no materialization).
  barrier_mat   — gather -> optimization_barrier (forces a buffer) ->
                  sum folded into carry.  H-mat predicts ~670.
  barrier_tr    — gather -> barrier -> transpose -> reshape ->
                  (32,128,128) -> sum: materialize THEN the logical
                  transpose; if barrier output stays {0,1} and the
                  transpose bitcasts, H-layout predicts ~= barrier_mat.

Run on the real chip: ``python scripts/gather_materialize_probe.py``.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/tpuminter-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

B = 16384
N = 1024
STEPS = N
UNROLL = 2


def sync(x):
    np.asarray(jax.tree.leaves(x)[0])


def timed(fn, *args, reps=3):
    out = fn(*args)
    sync(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        sync(out)
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 2**32, (B, 32), dtype=np.uint32))

    @jax.jit
    def make_v():
        i = jnp.arange(N * B, dtype=jnp.uint32)[:, None]
        j = jnp.arange(32, dtype=jnp.uint32)[None, :]
        h = i * np.uint32(2654435761) + j * np.uint32(0x9E3779B9)
        h ^= h >> 16
        h *= np.uint32(0x85EBCA6B)
        h ^= h >> 13
        return h

    vflat = make_v()
    sync(vflat)
    lane = jnp.arange(B, dtype=jnp.uint32)

    def scan32(body):
        @jax.jit
        def run(x, v):
            words = tuple(x[:, i] for i in range(32))

            def step(carry, _):
                return body(carry, v), None

            words, _ = jax.lax.scan(step, words, None, length=STEPS,
                                    unroll=UNROLL)
            return words[0]

        return run

    def gather(v, carry):
        j = carry[16] & np.uint32(N - 1)
        return v[(j * np.uint32(B) + lane).astype(jnp.int32)]

    def fold(carry, s):
        out = list(carry)
        out[16] = out[16] ^ s
        return tuple(out)

    def body_fused_reduce(carry, v):
        vj = gather(v, carry)
        return fold(carry, vj.sum(axis=1, dtype=jnp.uint32))

    def body_barrier_mat(carry, v):
        vj = gather(v, carry)
        vj = jax.lax.optimization_barrier(vj)
        return fold(carry, vj.sum(axis=1, dtype=jnp.uint32))

    def body_barrier_tr(carry, v):
        vj = gather(v, carry)
        vj = jax.lax.optimization_barrier(vj)
        vjt = jnp.transpose(vj).reshape(32, B // 128, 128)
        return fold(carry, vjt[16].reshape(B))

    for name, body in [
        ("fused_reduce", body_fused_reduce),
        ("barrier_mat", body_barrier_mat),
        ("barrier_tr", body_barrier_tr),
    ]:
        try:
            t = timed(scan32(body), x, vflat) / STEPS
            print(f"{name:14s} {t * 1e6:8.1f} us/step")
        except Exception as e:  # noqa: BLE001
            print(f"{name:14s} FAILED: {type(e).__name__}: {str(e)[:160]}")


if __name__ == "__main__":
    main()
