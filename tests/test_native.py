"""Native C++ miner tests: the compiled core (native/sha256d.cc) is
pinned bit-for-bit to the Python/hashlib reference semantics across
every dialect, then driven end-to-end through the real cluster.

The shared library is built on demand (``make -C native``); tests skip
only if no C++ toolchain exists (it does in this image).
"""

import struct
import subprocess

import numpy as np
import pytest

from tpuminter import chain
from tpuminter.protocol import PowMode, Request
from tpuminter.worker import CpuMiner

GEN = chain.GENESIS_HEADER


@pytest.fixture(scope="module")
def native_miner():
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        subprocess.run(
            ["make", "-C", os.path.join(root, "native")],
            check=True, capture_output=True, timeout=120,
        )
    except (FileNotFoundError, subprocess.CalledProcessError) as exc:
        pytest.skip(f"cannot build native core: {exc}")
    from tpuminter.native_worker import NativeMiner

    return NativeMiner(batch=1 << 14)


def _drain(gen):
    result = None
    for item in gen:
        if item is not None:
            result = item
    return result


def test_native_finds_genesis(native_miner):
    req = Request(
        job_id=1, mode=PowMode.TARGET, lower=GEN.nonce - 20_000,
        upper=GEN.nonce + 20_000, header=GEN.pack(),
        target=chain.bits_to_target(GEN.bits),
    )
    result = _drain(native_miner.mine(req))
    assert result.found
    assert result.nonce == GEN.nonce
    assert result.hash_value == GEN.block_hash_int()
    assert result.searched == 20_001  # first-winner early exit


def test_native_exhausted_matches_cpu(native_miner):
    req = Request(job_id=2, mode=PowMode.TARGET, lower=100, upper=5099,
                  header=GEN.pack(), target=1)
    want = _drain(CpuMiner(batch=1024).mine(req))
    got = _drain(native_miner.mine(req))
    assert not got.found
    assert (got.nonce, got.hash_value) == (want.nonce, want.hash_value)
    assert got.searched == want.searched == 5000


def test_native_min_matches_cpu(native_miner):
    req = Request(job_id=3, mode=PowMode.MIN, lower=7, upper=9006,
                  data=b"native parity")
    want = _drain(CpuMiner(batch=1024).mine(req))
    got = _drain(native_miner.mine(req))
    assert (got.nonce, got.hash_value) == (want.nonce, want.hash_value)


def test_native_min_data_straddles_block(native_miner):
    """Toy data >64 bytes: the midstate path in toy_min_search."""
    data = bytes(range(100))
    req = Request(job_id=4, mode=PowMode.MIN, lower=0, upper=2000, data=data)
    want = _drain(CpuMiner(batch=512).mine(req))
    got = _drain(native_miner.mine(req))
    assert (got.nonce, got.hash_value) == (want.nonce, want.hash_value)


def test_native_rolled_matches_cpu(native_miner):
    rng = np.random.RandomState(3)
    prefix, suffix = rng.bytes(41), rng.bytes(60)
    branch = (rng.bytes(32), rng.bytes(32))
    nb, ens = 9, 3
    base = dict(
        job_id=5, mode=PowMode.TARGET, lower=10, upper=(ens << nb) - 5,
        header=GEN.pack(), coinbase_prefix=prefix, coinbase_suffix=suffix,
        extranonce_size=4, branch=branch, nonce_bits=nb,
    )
    # exhausted: exact min over the rolled space
    want = _drain(CpuMiner(batch=256).mine(Request(target=1, **base)))
    got = _drain(native_miner.mine(Request(target=1, **base)))
    assert (got.nonce, got.hash_value) == (want.nonce, want.hash_value)
    # found: first winner at the known min
    req = Request(target=want.hash_value, **base)
    got = _drain(native_miner.mine(req))
    assert got.found
    assert (got.nonce, got.hash_value) == (want.nonce, want.hash_value)


def test_native_scrypt_delegates(native_miner):
    hdr = GEN.pack()
    h_min, n_min = min(
        (chain.hash_to_int(chain.scrypt_hash(hdr[:76] + struct.pack("<I", n))), n)
        for n in range(51)
    )
    req = Request(job_id=6, mode=PowMode.SCRYPT, lower=0, upper=50,
                  header=hdr, target=h_min)
    result = _drain(native_miner.mine(req))
    assert result.found
    assert (result.nonce, result.hash_value) == (n_min, h_min)


def test_native_through_cluster(native_miner):
    from tests.test_e2e import FAST, Cluster, run
    from tpuminter.client import submit

    async def scenario():
        cluster = await Cluster.create(
            n_miners=1, chunk_size=16384,
            miner_factory=lambda: native_miner,
        )
        try:
            req = Request(
                job_id=9, mode=PowMode.TARGET, lower=GEN.nonce - 30_000,
                upper=GEN.nonce + 30_000, header=GEN.pack(),
                target=chain.bits_to_target(GEN.bits),
            )
            result = await submit(
                "127.0.0.1", cluster.coord.port, req, params=FAST
            )
            assert result.found and result.nonce == GEN.nonce
            assert cluster.coord.stats["results_rejected"] == 0
            stats = cluster.coord.worker_stats()
            assert list(s["backend"] for s in stats.values()) == ["native"]
        finally:
            await cluster.close()

    run(scenario())


def test_batch_verify_matches_hashlib(native_miner):
    """The coordinator's verification entry point (sha256d_hash_batch,
    bound via tpuminter.native_verify): hash values for a mixed batch
    of (header76, nonce) pairs — different headers per item, the
    verification-burst shape — must equal hashlib's double-SHA exactly,
    genesis winner included."""
    import random

    from tpuminter import native_verify

    assert native_verify.available()  # the fixture built the library
    rng = random.Random(7)
    headers = [GEN.pack()[:76]]
    nonces = [GEN.nonce]
    for i in range(33):
        hdr = GEN.with_nonce(0).with_merkle_root(
            bytes(rng.randrange(256) for _ in range(32))
        ).pack()[:76]
        headers.append(hdr)
        nonces.append(rng.randrange(1 << 32))
    want = [
        chain.hash_to_int(chain.dsha256(h + struct.pack("<I", n)))
        for h, n in zip(headers, nonces)
    ]
    assert native_verify.dsha256_header_batch(headers, nonces) == want
    # the count=1 path the per-result verifier uses
    assert native_verify.dsha256_header(headers[0], nonces[0]) == want[0]
    assert want[0] == GEN.block_hash_int()
    # shape errors are loud, not silent corruption
    with pytest.raises(ValueError):
        native_verify.dsha256_header_batch(headers[:2], nonces[:1])
    with pytest.raises(ValueError):
        native_verify.dsha256_header_batch([b"short"], [1])
