"""The driver's bench contract: ``python bench.py`` must print exactly
ONE JSON line with the agreed shape, whatever backend it lands on. A
stray print, an import error, or a schema drift here would silently
void the round's recorded benchmark, so CI pins the smoke path
(``BENCH_SMOKE=1`` forces the CPU measurement; the TPU path shares all
the surrounding plumbing and is exercised on the real chip)."""

import json
import os
import subprocess
import sys


def test_bench_smoke_emits_one_json_line():
    env = dict(os.environ)
    env["BENCH_SMOKE"] = "1"
    proc = subprocess.run(
        [sys.executable, "bench.py"],
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True,
        text=True,
        timeout=420,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line: {lines}"
    obj = json.loads(lines[0])
    assert obj["metric"] == "double_sha256_ghs_per_chip"
    assert obj["unit"] == "GH/s"
    assert obj["value"] > 0
    assert obj["vs_baseline"] == obj["value"]  # target denominator is 1.0
    assert obj["extra"]["scrypt_khs_per_chip"] > 0
    # the rolled A/B section rides every capture (ISSUE 7): both sides
    # of the pair measured, and the dispatch-count evidence present
    assert obj["extra"]["rolled_fast_mhs_batched_nb8"] > 0
    assert obj["extra"]["rolled_fast_mhs_segmented_nb8"] > 0
    assert (
        obj["extra"]["rolled_dispatches_per_segment_batched_nb8"]
        < obj["extra"]["rolled_dispatches_per_segment_segmented_nb8"]
    )
    # the schedule-sharing A/B rides every capture (ISSUE 16): both
    # sides of the sched on/off pair measured on the SAME batched job,
    # the layer cannot change how many dispatches cover a segment, and
    # the autotune probe picked a real candidate width
    assert obj["extra"]["rolled_sched_mhs_on_nb8"] > 0
    assert obj["extra"]["rolled_sched_mhs_off_nb8"] > 0
    assert isinstance(
        obj["extra"]["rolled_sched_speedup_pct_median_nb8"], (int, float)
    )
    assert (
        obj["extra"]["rolled_sched_dispatches_per_segment_on_nb8"]
        == obj["extra"]["rolled_sched_dispatches_per_segment_off_nb8"]
    )
    assert obj["extra"]["rolled_autotune_width"] in (128, 256, 512, 1024)
    # the roll-budget control-plane A/B rides every capture too
    # (ISSUE 14): both arms measured at both nonce_bits points, every
    # rolled_check gate held, and the production-shape collapse at or
    # beyond the 1000x acceptance bar
    for nb in (20, 32):
        assert (
            obj["extra"][f"rolled_cp_msgs_per_segment_budget_nb{nb}"]
            < obj["extra"][f"rolled_cp_msgs_per_segment_classic_nb{nb}"]
        )
        assert (
            obj["extra"][f"rolled_cp_bytes_per_segment_budget_nb{nb}"]
            < obj["extra"][f"rolled_cp_bytes_per_segment_classic_nb{nb}"]
        )
        assert obj["extra"][f"rolled_cp_violations_nb{nb}"] == 0
        assert (
            obj["extra"][f"rolled_cp_beacon_overhead_pct_nb{nb}"] <= 5.0
        )
    assert obj["extra"]["rolled_cp_collapse_ratio_msgs_nb32"] >= 1000.0
    # the pluggable-workload pairing rides every capture (ISSUE 15):
    # both arms of the seam-cost A/B measured on the same plane, and
    # every fold discipline actually flowed end to end
    assert obj["extra"]["workload_jobs_per_s_mining"] > 0
    assert obj["extra"]["workload_jobs_per_s_hashcore"] > 0
    assert obj["extra"]["workload_indices_per_s_hashcore"] > 0
    assert obj["extra"]["workload_folds_covered"] == 4
    # the device-lane hashcore A/B rides every capture (ISSUE 17):
    # host and device arms both measured at BOTH batch shapes, the
    # paired outputs verified bit-for-bit during the measurement, and
    # the resolved sweep shape recorded
    for n in (4096, 16384):
        assert obj["extra"][f"workload_dev_host_ips_{n}"] > 0
        assert obj["extra"][f"workload_dev_ips_{n}"] > 0
        assert isinstance(
            obj["extra"][f"workload_dev_speedup_pct_{n}"], (int, float)
        )
    assert obj["extra"]["workload_dev_equal"] is True
    assert obj["extra"]["workload_dev_width"] % 128 == 0
    assert obj["extra"]["workload_dev_engine"] in ("jnp", "pallas")
    # the federation section rides every capture (ISSUE 18): the
    # parent's control cost per settled segment stays within 2x as the
    # fleet behind one aggregator grows (the merged-beacon flattening),
    # the chain-replication primary paid for exactly ONE stream, and
    # the two-process end-to-end overhead was measured (its value
    # carries this one-core host's ambient swing, like
    # replication_overhead_pct, so only its presence is gated)
    assert obj["extra"]["fed_parent_msgs_per_segment_fleet1"] > 0
    assert obj["extra"]["fed_fanin_msgs_ratio"] <= 2.0
    assert obj["extra"]["fed_chain_one_primary_stream"] is True
    assert isinstance(
        obj["extra"]["fed_chain_overhead_pct"], (int, float)
    )
    # the multi-process section rides every capture (ISSUE 19): both
    # arms of the 1-proc vs 2-proc pair measured, the deterministic
    # invariants held on whatever host ran it (exactly-once across the
    # process seam, the rebind drill settled once, the shared tenant
    # stayed inside its fleet-wide budget), and the one-core caveat
    # recorded so a multi-core re-capture knows the seam-overhead
    # number here carries serialization, not the seam
    assert obj["extra"]["multiproc_cores_available"] >= 1
    assert obj["extra"]["multiproc_results_per_s_1proc"] > 0
    assert obj["extra"]["multiproc_results_per_s_2proc"] > 0
    assert isinstance(
        obj["extra"]["multiproc_seam_overhead_pct"], (int, float)
    )
    assert obj["extra"]["multiproc_one_core_caveat"] == (
        obj["extra"]["multiproc_cores_available"] < 2
    )
    assert obj["extra"]["multiproc_dup_answers"] == 0
    assert obj["extra"]["multiproc_miners_lost"] == 0
    assert obj["extra"]["multiproc_rebind_settled"] == 1
    assert (
        obj["extra"]["multiproc_quota_admitted"]
        <= obj["extra"]["multiproc_quota_burst"] + 1
    )
    # the compute-fabric section rides every capture (ISSUE 20): the
    # opaque-domain pairing measured both arms on the same plane, the
    # streaming drill put a first partial strictly before the exact
    # final, the starvation A/B measured a real weight split under a
    # real flood, and every stream/starve check verdict held
    assert obj["extra"]["fabric_violations"] == 0
    assert obj["extra"]["fabric_jobs_per_s_hashcore"] > 0
    assert obj["extra"]["fabric_jobs_per_s_dict"] > 0
    assert (
        0
        < obj["extra"]["fabric_time_to_first_partial_ms"]
        < obj["extra"]["fabric_time_to_final_ms"]
    )
    assert obj["extra"]["fabric_stream_partials"] >= 3
    assert 1 / 3 <= obj["extra"]["fabric_drr_fairness_ratio"] <= 3.0
    assert obj["extra"]["fabric_flood_parked"] > 0
    assert obj["extra"]["fabric_flood_shed"] > 0
