"""Production pod-path tests on the fake 8-device CPU mesh (VERDICT r2
#3; BASELINE.json:5): the striped candidate sweep's ICI early exit and
exact-lowest contract, and PodMiner end-to-end through the Miner
interface and the real cluster.

Candidate-validity note: the candidate test (top 32 hash bits zero) only
fires for real-difficulty hashes, which CI cannot brute-force — except
for the genesis block, whose known diff-1 winner IS a candidate. Every
found-path test therefore mines windows around the genesis nonce; the
rolled pod path (whose fixtures can't contain candidates) is exercised
on its exhausted path: segment iteration, the on-device roll feeding the
dynamic-header pod sweep, and searched accounting.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuminter import chain
from tpuminter.ops import sha256 as ops
from tpuminter.parallel import build_candidate_sweep, make_mesh
from tpuminter.pod_worker import PodMiner, _biased_cap
from tpuminter.protocol import MIN_UNTRACKED, PowMode, Request
from tpuminter.worker import CpuMiner

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the fake 8-device CPU mesh"
)

GEN = chain.GENESIS_HEADER
TARGET = chain.bits_to_target(GEN.bits)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(jax.devices()[:8])


@pytest.fixture(scope="module")
def sweep(mesh):
    template = ops.header_template(GEN.pack())
    return build_candidate_sweep(
        mesh, template, slab_per_device=256, n_slabs=4, kernel="jnp"
    )


def _drain(gen):
    result = None
    for item in gen:
        if item is not None:
            result = item
    return result


def test_candidate_sweep_finds_genesis(sweep):
    # span = 8 dev × 4 stripes × 256 = 8192; winner 2500 past start sits
    # in stripe 1 → the or-reduce must stop the pod after stripe 1
    start = GEN.nonce - 2500
    found, first, stripes = sweep(jnp.uint32(start), _biased_cap(TARGET))
    assert int(found) == 1
    assert int(first) == 2500  # offset from start, not an absolute nonce
    assert int(stripes) == 2  # stripes 0 and 1 ran, 2 and 3 never did


def test_candidate_sweep_offset_survives_u32_wrap(sweep):
    """A span that wraps past 2^32: the winner's OFFSET must still be
    exact (absolute-nonce folding would mis-order wrapped candidates —
    the r3 review's wrap bug)."""
    start = (GEN.nonce - 2500) % (1 << 32)
    # place the window so the wrap boundary sits inside the span but
    # below the winner: start near 2^32, winner offset unchanged
    hi_start = (1 << 32) - 1000  # span covers [2^32-1000, 2^32) ∪ [0, 7192)
    found, first, stripes = sweep(jnp.uint32(hi_start), _biased_cap(TARGET))
    # no candidate lives in that window: must be clean, all stripes run
    assert int(found) == 0
    assert int(stripes) == 4
    # and the genesis window still reports the same offset as unwrapped
    found, first, _ = sweep(jnp.uint32(start), _biased_cap(TARGET))
    assert (int(found), int(first)) == (1, 2500)


def test_candidate_sweep_clean_window(sweep):
    # a window with no candidate: all stripes run, nothing found
    found, _, stripes = sweep(jnp.uint32(12345), _biased_cap(TARGET))
    assert int(found) == 0
    assert int(stripes) == 4


def test_pod_miner_finds_genesis(mesh):
    miner = PodMiner(mesh=mesh, slab_per_device=256, n_slabs=2, kernel="jnp")
    req = Request(
        job_id=7, mode=PowMode.TARGET, lower=GEN.nonce - 3000,
        upper=GEN.nonce + 3000, header=GEN.pack(), target=TARGET,
    )
    result = _drain(miner.mine(req))
    assert result.found
    assert result.nonce == GEN.nonce
    assert result.hash_value == GEN.block_hash_int()
    # ordered acceptance: everything below the winner was searched
    assert result.searched >= GEN.nonce - req.lower + 1


def test_pod_miner_exhausted_reports_candidate_min(mesh):
    """Target one below the genesis hash: the genesis nonce is a
    candidate (clears the hash-word-1 cap) but not a winner — the job
    exhausts and the surfaced candidate IS the exact range minimum."""
    miner = PodMiner(mesh=mesh, slab_per_device=256, n_slabs=2, kernel="jnp")
    req = Request(
        job_id=8, mode=PowMode.TARGET, lower=GEN.nonce - 1000,
        upper=GEN.nonce + 1000, header=GEN.pack(),
        target=GEN.block_hash_int() - 1,
    )
    result = _drain(miner.mine(req))
    assert not result.found
    assert (result.nonce, result.hash_value) == (GEN.nonce, GEN.block_hash_int())
    assert result.searched == 2001


def test_pod_miner_exhausted_no_candidates_sentinel(mesh):
    miner = PodMiner(mesh=mesh, slab_per_device=256, n_slabs=2, kernel="jnp")
    req = Request(
        job_id=9, mode=PowMode.TARGET, lower=0, upper=4000,
        header=GEN.pack(), target=1,
    )
    result = _drain(miner.mine(req))
    assert not result.found
    assert result.hash_value == MIN_UNTRACKED
    assert result.searched == 4001


def test_pod_miner_min_matches_cpu(mesh):
    miner = PodMiner(mesh=mesh, slab_per_device=512, n_slabs=2, kernel="jnp")
    req = Request(job_id=3, mode=PowMode.MIN, lower=5, upper=6001, data=b"pod")
    want = _drain(CpuMiner(batch=512).mine(req))
    got = _drain(miner.mine(req))
    assert (got.nonce, got.hash_value) == (want.nonce, want.hash_value)
    assert got.searched == want.searched


def test_pod_miner_rolled_exhausted_path(mesh):
    """Rolled pod job over a candidate-free space: the on-device roll
    feeds the dynamic-header pod sweep per segment; the exhausted Result
    carries the sentinel and exact searched count."""
    rng = np.random.RandomState(5)
    prefix, suffix = rng.bytes(41), rng.bytes(60)
    branch = (rng.bytes(32), rng.bytes(32))
    nb, ens = 11, 3  # 2048-nonce segments, 3 extranonces
    miner = PodMiner(mesh=mesh, slab_per_device=64, n_slabs=2, kernel="jnp")
    req = Request(
        job_id=11, mode=PowMode.TARGET, lower=100,
        upper=(ens << nb) - 50, header=GEN.pack(),
        target=chain.bits_to_target(GEN.bits),
        coinbase_prefix=prefix, coinbase_suffix=suffix,
        extranonce_size=4, branch=branch, nonce_bits=nb,
    )
    result = _drain(miner.mine(req))
    assert not result.found
    assert result.hash_value == MIN_UNTRACKED
    assert result.searched == req.upper - req.lower + 1


def test_pod_miner_easy_target_delegates(mesh):
    """Toy-easy targets are not the candidate regime: PodMiner must
    still return the correct first winner (via the delegate)."""
    import struct

    target = (1 << 250) - 1
    want = None
    prefix = GEN.pack()[:76]
    for n in range(0, 5000):
        h = chain.hash_to_int(chain.dsha256(prefix + struct.pack("<I", n)))
        if h <= target:
            want = (n, h)
            break
    assert want is not None
    miner = PodMiner(mesh=mesh, slab_per_device=256, n_slabs=2, kernel="jnp")
    req = Request(job_id=4, mode=PowMode.TARGET, lower=0, upper=5000,
                  header=GEN.pack(), target=target)
    result = _drain(miner.mine(req))
    assert result.found
    assert (result.nonce, result.hash_value) == want


def test_pod_miner_through_cluster(mesh):
    """The role layer drives a whole slice: one PodMiner Joins the real
    coordinator and mines the genesis window end-to-end."""
    from tests.test_e2e import FAST, Cluster, run
    from tpuminter.client import submit

    async def scenario():
        cluster = await Cluster.create(
            n_miners=1, chunk_size=16384,
            miner_factory=lambda: PodMiner(
                mesh=mesh, slab_per_device=256, n_slabs=2, kernel="jnp"
            ),
        )
        try:
            req = Request(
                job_id=77, mode=PowMode.TARGET, lower=GEN.nonce - 3000,
                upper=GEN.nonce + 3000, header=GEN.pack(), target=TARGET,
            )
            result = await submit(
                "127.0.0.1", cluster.coord.port, req, params=FAST
            )
            assert result.found
            assert result.nonce == GEN.nonce
            assert cluster.coord.stats["results_rejected"] == 0
        finally:
            await cluster.close()

    run(scenario())


def test_pod_miner_scrypt_sharded(mesh):
    """SCRYPT sharded over the mesh: pod result ≡ CpuMiner, winner and
    exhausted-minimum both, including a ragged tail below one pod span."""
    import struct

    hdr = GEN.pack()
    prefix = hdr[:76]
    upper = 8 * 64 + 37  # one full pod span (8 dev × 64) + ragged tail
    all_h = [
        (chain.hash_to_int(chain.scrypt_hash(prefix + struct.pack("<I", n))), n)
        for n in range(upper + 1)
    ]
    h_min, n_min = min(all_h)
    miner = PodMiner(mesh=mesh, slab_per_device=256, n_slabs=2, kernel="jnp")

    req = Request(job_id=21, mode=PowMode.SCRYPT, lower=0, upper=upper,
                  header=hdr, target=h_min)
    result = _drain(miner.mine(req))
    assert result.found
    assert (result.nonce, result.hash_value) == (n_min, h_min)

    req = Request(job_id=22, mode=PowMode.SCRYPT, lower=0, upper=upper,
                  header=hdr, target=1)
    result = _drain(miner.mine(req))
    assert not result.found
    assert (result.hash_value, result.nonce) == (h_min, n_min)
    assert result.searched == upper + 1


def _rolled_fixture(nb=10, ens=4, seed=5):
    rng = np.random.RandomState(seed)
    prefix, suffix = rng.bytes(41), rng.bytes(60)
    branch = (rng.bytes(32), rng.bytes(32))
    import struct

    cb = chain.CoinbaseTemplate(prefix, suffix, 4)
    all_h = []
    for en in range(ens):
        p76 = chain.rolled_header(GEN.pack(), cb, branch, en).pack()[:76]
        for n in range(1 << nb):
            h = chain.hash_to_int(chain.dsha256(p76 + struct.pack("<I", n)))
            all_h.append((h, (en << nb) | n))
    return prefix, suffix, branch, all_h


def test_pod_miner_rolled_batched_matches_per_segment_baseline(mesh):
    """`--roll-batch 1` reproduces today's per-segment pod loop
    bit-for-bit; the batched sweep (device-major row stripes through
    build_rolled_sweep) returns the identical Result."""
    prefix, suffix, branch, _ = _rolled_fixture()
    nb, ens = 11, 3
    req = Request(
        job_id=21, mode=PowMode.TARGET, lower=100,
        upper=(ens << nb) - 50, header=GEN.pack(),
        target=chain.bits_to_target(GEN.bits),
        coinbase_prefix=prefix, coinbase_suffix=suffix,
        extranonce_size=4, branch=branch, nonce_bits=nb,
    )
    results = []
    for rb in (1, 6):
        miner = PodMiner(
            mesh=mesh, slab_per_device=64, n_slabs=2, kernel="jnp",
            roll_batch=rb,
        )
        results.append(_drain(miner.mine(req)))
    base, batched = results
    assert (base.found, base.nonce, base.hash_value, base.searched) == (
        batched.found, batched.nonce, batched.hash_value, batched.searched
    )
    assert not base.found and base.hash_value == MIN_UNTRACKED
    assert base.searched == req.upper - req.lower + 1


def test_pod_miner_rolled_batched_finds_exact_first_winner(mesh):
    """The batched pod sweep's found path at a CI-reachable candidate
    bar (the jnp engine's `cand_bits` test seam, 8 bits): the winner is
    the exact lowest GLOBAL winning index — the stripe-interleaved
    early exit never skips a lower row — and the exhausted path
    surfaces the exact candidate minimum."""
    prefix, suffix, branch, all_h = _rolled_fixture()
    nb, ens = 10, 4
    cands = [(h, g) for h, g in all_h if h >> 248 == 0]
    h_c, g_c = min(cands)
    mk = lambda target, jid: Request(
        job_id=jid, mode=PowMode.TARGET, lower=0, upper=(ens << nb) - 1,
        header=GEN.pack(), target=target, coinbase_prefix=prefix,
        coinbase_suffix=suffix, extranonce_size=4, branch=branch,
        nonce_bits=nb,
    )
    miner = PodMiner(
        mesh=mesh, slab_per_device=128, n_slabs=2, kernel="jnp",
        roll_batch=6,
    )
    miner._cand_bits = 8
    r = _drain(miner.mine(mk(h_c, 22)))
    assert r.found and (r.nonce, r.hash_value) == (g_c, h_c)
    assert r.nonce >> nb >= 1  # the roll actually happened
    r2 = _drain(miner.mine(mk(1, 23)))
    assert not r2.found and (r2.hash_value, r2.nonce) == (h_c, g_c)
    assert r2.searched == ens << nb
