"""Device-lane hashcore engine (ISSUE 17): the u32-pair splitmix64
sweep (``ops.splitmix``) and its Pallas mirror against the two shipped
references — the scalar ``objective`` and the numpy host-lane path.

The A/B contract under test: with the ``dev_lanes`` knob on, every
``HashCore.compute`` output — the accumulator AND ``searched``,
including first-match's early-stop rounding — is bit-for-bit what the
host path produces, at every fold discipline, every ragged tail, and
both sweep engines. All tests run under the tier-1 JAX_PLATFORMS=cpu
config with NO ``jax_enable_x64``: proving the pair arithmetic needs no
u64 dtype is the point.

Shapes are deliberately shared (width 256/512, rows 2) so each
``lru_cache``'d sweep program compiles once per pytest process.
"""

from __future__ import annotations

import random

import pytest

np = pytest.importorskip("numpy")
pytest.importorskip("jax")

from tpuminter.ops import splitmix as sm
from tpuminter.protocol import PowMode, Request
from tpuminter.workloads import folds
from tpuminter.workloads import hashcore as hc

_M64 = (1 << 64) - 1


@pytest.fixture(autouse=True)
def _restore_dev_cfg():
    prior = hc.dev_lanes_config()
    yield
    hc.set_dev_lanes(
        prior["mode"], width=prior["width"], rows=prior["rows"],
        engine=prior["engine"],
    )


def _drive(gen):
    while True:
        try:
            next(gen)
        except StopIteration as stop:
            return stop.value


def _req(variant, seed, lo, hi, thr=0, k=1):
    return Request(
        job_id=1, mode=PowMode.MIN, lower=lo, upper=hi,
        data=hc.pack_params(variant, seed, thr, k),
        workload="hashcore", chunk_id=0,
    )


# ---------------------------------------------------------------------------
# the pair primitives vs the scalar objective
# ---------------------------------------------------------------------------

def test_lane_objective_matches_scalar_across_domain():
    rng = random.Random(0xD17)
    idx = [rng.getrandbits(rng.choice([8, 32, 63, 64])) for _ in range(64)]
    for seed in (0, 1, rng.getrandbits(64)):
        assert sm.lane_objective(seed, idx) == [
            hc.objective(seed, i) for i in idx
        ]


def test_lane_objective_word_boundaries():
    """The cases u32-pair arithmetic gets wrong when a carry or a
    cross-word shift is off by one: around 2^32 and the u64 wrap."""
    edges = [0, 1, (1 << 32) - 1, 1 << 32, (1 << 32) + 1,
             _M64 - 1, _M64]
    for seed in (0, _M64, 0x9E3779B97F4A7C15):
        assert sm.lane_objective(seed, edges) == [
            hc.objective(seed, i) for i in edges
        ]


def test_pallas_kernel_matches_scalar():
    """The kernel mirror, interpret mode (splitmix is small enough to
    interpret, unlike the SHA bodies — see kernels/splitmix.py)."""
    from tpuminter.kernels.splitmix import pallas_splitmix_batch

    rng = random.Random(5)
    idx = [rng.getrandbits(64) for _ in range(256)]
    ih = np.array([i >> 32 for i in idx], np.uint32)
    il = np.array([i & 0xFFFFFFFF for i in idx], np.uint32)
    vh, vl = pallas_splitmix_batch(np.uint32(7), np.uint32(13), ih, il)
    got = [
        (int(h) << 32) | int(l)
        for h, l in zip(np.asarray(vh), np.asarray(vl))
    ]
    assert got == [hc.objective((7 << 32) | 13, i) for i in idx]


# ---------------------------------------------------------------------------
# sweep programs: every fold ≡ the host of_batch/combine chain
# ---------------------------------------------------------------------------

def _host_acc(fold, seed, lo, hi, batch=2048):
    acc = fold.initial()
    i = lo
    while i <= hi:
        j = min(i + batch - 1, hi)
        vals = [hc.objective(seed, g) for g in range(i, j + 1)]
        acc = fold.combine(acc, fold.of_batch(i, vals))
        if fold.is_final(acc):
            break
        i = j + 1
    return acc


def _dev_acc(fold, variant, seed, lo, hi, engine, thr=0, k=1, width=256):
    sweep = sm.LaneSweep(variant, width, 2, k, engine)
    acc = fold.initial()
    g = lo
    while g <= hi:
        e = min(g + sweep.window - 1, hi)
        acc = fold.combine(
            acc, sweep.resolve(sweep.dispatch(seed, g, e, thr), g, e)
        )
        if fold.is_final(acc):
            break
        g = e + 1
    return acc


def test_sweeps_equal_host_folds_all_variants_ragged():
    """Random (seed, range, threshold, k) at window-misaligned ranges:
    the jnp sweep's window-granular partials combine to the exact host
    accumulator for all four disciplines."""
    rng = random.Random(0xAB)
    for trial in range(8):
        seed = rng.getrandbits(64)
        lo = rng.getrandbits(rng.choice([10, 40, 63]))
        hi = lo + rng.randint(0, 1400)
        k = rng.randint(1, folds.TOPK_SLOTS)
        thr = rng.getrandbits(rng.choice([60, 62, 64]))
        cases = [
            (folds.FMin(), "fmin", 0, 1),
            (folds.TopK(k), "topk", 0, k),
            (folds.FirstMatch(thr), "fmatch", thr, 1),
            (folds.FSum(), "fsum", 0, 1),
        ]
        for fold, variant, t, kk in cases:
            want = _host_acc(fold, seed, lo, hi)
            got = _dev_acc(fold, variant, seed, lo, hi, "jnp", t, kk)
            assert got == want, (variant, seed, lo, hi, t, kk)


def test_pallas_engine_equals_jnp_engine():
    """Same sweep, engine='pallas' (interpret mode): the kernel-backed
    value block feeds the same fold scan to the same bits."""
    rng = random.Random(0xCD)
    for trial in range(2):
        seed = rng.getrandbits(64)
        lo = rng.getrandbits(40)
        hi = lo + rng.randint(0, 900)
        f = folds.FMin()
        assert (
            _dev_acc(f, "fmin", seed, lo, hi, "pallas")
            == _dev_acc(f, "fmin", seed, lo, hi, "jnp")
            == _host_acc(f, seed, lo, hi)
        )


def test_fsum_exact_at_max_values():
    """The 16-bit-limb accumulator carries exactly even when every lane
    is near 2^64 (the column sums' worst case)."""
    f = folds.FSum()
    seed, lo = 0xFFFF_FFFF_FFFF_FFFF, (1 << 63) - 17
    hi = lo + 700
    assert _dev_acc(f, "fsum", seed, lo, hi, "jnp") == _host_acc(
        f, seed, lo, hi
    )


# ---------------------------------------------------------------------------
# the compute seam: knob, searched, fallback
# ---------------------------------------------------------------------------

def test_compute_seam_device_equals_host_including_searched():
    """End to end through ``HashCore.compute``: (searched, acc) equal
    under the knob for every variant — including first-match's
    early-stop ``searched``, the one granularity-dependent output,
    which the device path must reproduce at host _BATCH rounding."""
    core = hc.HashCore()
    rng = random.Random(0xEF)
    for trial in range(4):
        seed = rng.getrandbits(64)
        lo = rng.getrandbits(rng.choice([8, 40]))
        hi = lo + rng.randint(0, 5000)
        for variant, thr, k in (
            ("fmin", 0, 1),
            ("topk", 0, rng.randint(1, 8)),
            ("fmatch", rng.getrandbits(rng.choice([61, 63])) or 1, 1),
            ("fsum", 0, 1),
        ):
            r = _req(variant, seed, lo, hi, thr, k)
            fold = core.fold_for(r)
            hc.set_dev_lanes("off")
            host = _drive(core.compute(r, fold, engine="jax"))
            hc.set_dev_lanes("on", width=512, rows=2)
            dev = _drive(core.compute(r, fold, engine="cpu"))
            assert dev == host, (variant, seed, lo, hi, thr, k)


def test_fmatch_early_stop_searched_rounding():
    """A guaranteed first-window match: host counts whole _BATCH
    batches through the matching index, device must report the same
    count even though its window size differs."""
    core = hc.HashCore()
    seed = 3
    # find a real match early in the range so both paths early-stop
    lo, hi = 0, 50_000
    vals = [hc.objective(seed, i) for i in range(0, 4096)]
    thr = sorted(vals)[2]
    r = _req("fmatch", seed, lo, hi, thr)
    fold = core.fold_for(r)
    hc.set_dev_lanes("off")
    host = _drive(core.compute(r, fold, engine="jax"))
    hc.set_dev_lanes("on", width=256, rows=2)
    dev = _drive(core.compute(r, fold, engine="cpu"))
    assert dev == host
    searched, acc = dev
    assert acc[0] is not None and searched < hi - lo + 1


def test_knob_off_never_dispatches_on_forces_device():
    core = hc.HashCore()
    r = _req("fmin", 9, 0, 4000)
    fold = core.fold_for(r)
    hc.set_dev_lanes("off")
    before = sm.counters["dispatches"]
    _drive(core.compute(r, fold, engine="jax"))
    assert sm.counters["dispatches"] == before
    hc.set_dev_lanes("on", width=512, rows=2)
    _drive(core.compute(r, fold, engine="cpu"))
    assert sm.counters["dispatches"] > before


def test_knob_auto_routes_jax_family_only():
    hc.set_dev_lanes("auto")
    assert not hc._use_dev_lanes("cpu")
    assert not hc._use_dev_lanes("native")
    for eng in ("jax", "tpu", "pod"):
        assert hc._use_dev_lanes(eng)
    hc.set_dev_lanes("on")
    assert hc._use_dev_lanes("cpu")
    hc.set_dev_lanes("off")
    assert not hc._use_dev_lanes("tpu")


def test_setup_failure_falls_back_to_host_lanes():
    """A bad pinned width (not a multiple of 128) makes device setup
    fail; compute must still answer — on host lanes, bit-for-bit."""
    core = hc.HashCore()
    r = _req("fmin", 21, 0, 3000)
    fold = core.fold_for(r)
    hc.set_dev_lanes("off")
    want = _drive(core.compute(r, fold, engine="jax"))
    hc.set_dev_lanes("on", width=100, rows=2)
    before = sm.counters["dispatches"]
    assert _drive(core.compute(r, fold, engine="jax")) == want
    assert sm.counters["dispatches"] == before


# ---------------------------------------------------------------------------
# factories, caching, autotune
# ---------------------------------------------------------------------------

def test_sweep_program_is_cached_per_job_constants():
    """The PR 7 retrace rule: same constants, same compiled program
    object — a fresh jit per job would retrace per chunk."""
    a = sm.sweep_program("fmin", 256, 2, 1, "jnp")
    b = sm.sweep_program("fmin", 256, 2, 1, "jnp")
    c = sm.sweep_program("fmin", 512, 2, 1, "jnp")
    assert a is b and a is not c


def test_sweep_program_rejects_bad_shapes():
    with pytest.raises(ValueError):
        sm.sweep_program("fmin", 100, 2, 1, "jnp")
    with pytest.raises(ValueError):
        sm.sweep_program("fmin", sm.MAX_WIDTH * 2, 2, 1, "jnp")
    with pytest.raises(ValueError):
        sm.sweep_program("nope", 256, 2, 1, "jnp")
    with pytest.raises(ValueError):
        sm.resolve_engine("cuda")


def test_autotune_cache_is_keyed_separately_from_rolled():
    """The probe caches per (backend, 'hashcore', engine, ...) in its
    OWN dict — rolled's cache and key space are untouched, so the two
    autotunes can never clobber each other."""
    from tpuminter import rolled

    key = ("cpu-test", "hashcore", "jnp", (256,), 2)
    sm._autotune_cache[key] = 256
    try:
        assert key not in rolled._autotune_cache
        # a cache hit returns without probing (no timing, no compile)
        sm._autotune_cache[
            ("cpu", "hashcore", "jnp", (256,), 2)
        ] = 256
        assert sm.autotune_lane_width("jnp", (256,), rows=2) == 256
    finally:
        sm._autotune_cache.pop(key, None)


def test_autotune_probes_and_caches_winner():
    key = ("cpu", "hashcore", "jnp", (256, 512), 2)
    sm._autotune_cache.pop(key, None)
    try:
        w = sm.autotune_lane_width("jnp", (256, 512), rows=2, reps=1)
        assert w in (256, 512)
        assert sm._autotune_cache[key] == w
    finally:
        sm._autotune_cache.pop(key, None)


def test_dev_sweep_clamps_autotuned_width_to_chunk():
    """A 4096-index chunk must not pay for an autotuned 16384-lane
    window: the clamp sizes one window to the chunk (bench measured
    16× masked-lane waste without it). Pinned widths are honored."""
    key = ("cpu", "hashcore", "jnp", (2048, 4096, 8192, 16384), 2)
    sm._autotune_cache[key] = 16384
    try:
        hc.set_dev_lanes("on", width=None, rows=2)
        p = hc.parse_params(hc.pack_params("fmin", 1))
        sweep = hc._dev_sweep(p, 4096)
        assert sweep.width == 2048 and sweep.window == 4096
        hc.set_dev_lanes("on", width=512, rows=2)
        assert hc._dev_sweep(p, 4096).width == 512
    finally:
        sm._autotune_cache.pop(key, None)
