"""CandidateSearch driver logic, on a scripted fake device.

The fake reproduces the real kernel's contract exactly — first
candidate offset in the swept range, early exit, pad-lane quirk — so
the pipelining/ordering/remainder logic is pinned without a TPU
(SURVEY.md §4's own-the-seam test idea, applied to the device seam).
"""

import random

import pytest

from tpuminter.search import CandidateSearch


class FakeChip:
    """Emulates pallas_search_candidates + host verify.

    ``candidates``: sorted nonces whose digest word 7 "is zero".
    ``winners``: subset that also beats the target.
    """

    def __init__(self, candidates, winners):
        self.candidates = sorted(candidates)
        self.winners = set(winners)
        assert self.winners <= set(self.candidates)
        self.sweeps = []  # (base, n) log, dispatch order
        self.verifies = []

    def sweep(self, base, n):
        self.sweeps.append((base, n))
        hit = next(
            (c for c in self.candidates if base <= c < base + n), None
        )
        return (0, 0) if hit is None else (1, hit - base)

    def resolve(self, handle):
        return handle

    def verify(self, nonce):
        self.verifies.append(nonce)
        assert nonce in self.candidates, "verified a non-candidate"
        # fake hash: winners tiny, losers just above-target
        return nonce in self.winners, (1 << 200) if nonce in self.winners else (1 << 230)

    def search(self, lower, upper, slab=100, depth=2):
        s = CandidateSearch(
            self.sweep, self.resolve, self.verify, lower, upper,
            slab=slab, depth=depth,
        )
        for _ in s.events():
            pass
        return s.outcome


def test_clean_exhaustion_counts_everything():
    chip = FakeChip([], [])
    out = chip.search(0, 999)
    assert not out.found and out.nonce is None
    assert out.searched == 1000
    assert chip.verifies == []


def test_true_win_is_exact_and_prunes_later_work():
    chip = FakeChip([350], [350])
    out = chip.search(0, 999)
    assert out.found and out.nonce == 350
    assert out.hash_value == 1 << 200
    # pruning: after the win resolves, no new ranges above it are
    # issued — only calls already in flight (≤ depth of them) may sit
    # above the winning nonce
    above = [base for base, _ in chip.sweeps if base > 350]
    assert len(above) <= 2  # the pipeline depth


def test_false_positive_reissues_remainder():
    chip = FakeChip([50], [])
    out = chip.search(0, 299)
    assert not out.found
    # the remainder [51, 99] was searched despite the early exit —
    # dispatched as a full slab (single compiled kernel size)
    assert (51, 100) in chip.sweeps
    assert out.searched == 300
    assert out.candidates == [(50, 1 << 230)]


def test_win_in_remainder_beats_later_range_win():
    # A[0,99] false-positives at 50; B[100,199] wins at 150 and resolves
    # BEFORE the remainder, which holds the true lowest winner at 70.
    chip = FakeChip([50, 70, 150], [70, 150])
    out = chip.search(0, 999, slab=100, depth=2)
    assert out.found and out.nonce == 70


def test_later_win_held_until_remainder_clears():
    # remainder has no candidate: B's win at 150 must still only be
    # reported after the remainder sweep confirms [51,99] is clean.
    chip = FakeChip([50, 150], [150])
    out = chip.search(0, 999, slab=100, depth=2)
    assert out.found and out.nonce == 150
    assert (51, 100) in chip.sweeps  # remainder was actually swept


def test_exhausted_best_is_min_candidate():
    chip = FakeChip([20, 80], [])
    out = chip.search(0, 99, slab=10)
    assert not out.found
    assert out.best == (1 << 230, 20)
    assert out.searched == 100


def test_pad_lane_hit_past_range_is_clean_cover():
    class PadChip(FakeChip):
        def sweep(self, base, n):
            self.sweeps.append((base, n))
            return (1, n + 7)  # fired past the real range

    chip = PadChip([], [])
    out = chip.search(0, 999)
    assert not out.found and out.searched == 1000
    assert chip.verifies == []


@pytest.mark.parametrize("seed", range(20))
def test_randomized_matches_bruteforce(seed):
    rng = random.Random(seed)
    lower, upper = 0, rng.randrange(200, 2000)
    space = range(lower, upper + 1)
    candidates = sorted(rng.sample(space, rng.randrange(0, 12)))
    winners = [c for c in candidates if rng.random() < 0.4]
    chip = FakeChip(candidates, winners)
    out = chip.search(
        lower, upper,
        slab=rng.choice([37, 100, 256, 4096]),
        depth=rng.choice([1, 2, 3]),
    )
    if winners:
        assert out.found and out.nonce == min(winners)
    else:
        assert not out.found
        assert out.searched == upper - lower + 1
        if candidates:
            assert out.best == (1 << 230, min(candidates))


# -- pipeline_spans: the generic double-buffer (MIN/scrypt/exact-min) ----


def test_pipeline_spans_keeps_depth_in_flight():
    from tpuminter.search import pipeline_spans

    dispatched = []

    def dispatch(s):
        dispatched.append(s)
        return f"h{s}"

    gen = pipeline_spans(range(5), dispatch, depth=2)
    first = next(gen)
    # at the first yield exactly one EXTRA dispatch is outstanding:
    # the consumer blocks on span 0 while span 1 computes
    assert first == (0, "h0")
    assert dispatched == [0, 1]
    rest = list(gen)
    assert [first] + rest == [(i, f"h{i}") for i in range(5)]
    assert dispatched == list(range(5))


def test_pipeline_spans_depth_one_is_the_synchronous_loop():
    from tpuminter.search import pipeline_spans

    dispatched = []
    gen = pipeline_spans(range(3), lambda s: dispatched.append(s) or s, 1)
    assert next(gen) == (0, 0)
    assert dispatched == [0]  # nothing speculative at depth 1
    assert list(gen) == [(1, 1), (2, 2)]


def test_pipeline_spans_abandon_leaves_inflight_unresolved():
    """The Cancel/early-exit contract: a consumer that stops leaves at
    most ``depth`` handles dispatched beyond what it consumed, and the
    generator never touches them again (JAX async arrays are simply
    garbage-collected — same as CandidateSearch's abandoned handles)."""
    from tpuminter.search import pipeline_spans

    dispatched = []
    gen = pipeline_spans(range(100), lambda s: dispatched.append(s) or s, 3)
    for span, handle in gen:
        assert span == handle
        if span == 4:
            gen.close()  # winner found / Cancel landed
            break
    # consumed 0..4; speculative dispatches are bounded by depth - 1
    # beyond the last yielded span (span 4 was yielded right after
    # span 4 + depth - 1 = 6 was dispatched)
    assert dispatched == list(range(7))


def test_pipeline_spans_rejects_bad_depth():
    from tpuminter.search import pipeline_spans

    with pytest.raises(ValueError):
        list(pipeline_spans([1], lambda s: s, 0))


def test_global_domain_search_crosses_segment_boundaries():
    """The rolled generalization (ISSUE 7): one CandidateSearch over a
    >2^32 GLOBAL index domain, slabs crossing extranonce boundaries —
    same exact-lowest-winner contract, bookkeeping keyed by global
    index. (The batched sweep itself is pinned in test_extranonce; this
    pins the driver's queueing/ordering over the wide domain.)"""
    base_g = 1 << 34  # far beyond the 32-bit nonce space
    chip = FakeChip(
        candidates=[base_g + 150, base_g + 9050],
        winners=[base_g + 9050],
    )
    s = CandidateSearch(
        chip.sweep, chip.resolve, chip.verify,
        base_g - 1000, base_g + 20_000,
        slab=4096, depth=2, domain=1 << 40,
    )
    for _ in s.events():
        pass
    out = s.outcome
    assert out.found and out.nonce == base_g + 9050
    assert out.candidates[0] == (base_g + 150, 1 << 230)
    # the false positive's remainder was re-issued before later ranges
    assert chip.verifies == [base_g + 150, base_g + 9050]
    # without the widened domain, the same range is rejected loudly
    with pytest.raises(ValueError):
        CandidateSearch(
            chip.sweep, chip.resolve, chip.verify,
            base_g - 1000, base_g + 20_000, slab=4096,
        )
