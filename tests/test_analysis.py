"""Tier-1 gate for the static-analysis suite (ISSUE 9).

Three layers: (1) the whole tree must be clean under the committed
allowlist — any new finding, or any allowlist entry that stopped
matching, fails CI; (2) the fixture corpus under
``tests/fixtures/analysis/`` reconstructs each checker's historical bug
class and must keep being flagged — the suite is pinned to its reason
for existing; (3) the runtime loop-affinity detector catches a seeded
deliberate cross-loop mutation and stays silent for the sanctioned
executor seam.
"""

import asyncio
import json
import os
import subprocess
import sys
import threading

import pytest

from tpuminter.analysis import affinity
from tpuminter.analysis.core import (
    Allowlist,
    parse_module,
    run_project,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join("tests", "fixtures", "analysis")


def _fixture_findings(name, checkers):
    src = parse_module(REPO_ROOT, os.path.join(FIXTURES, name))
    findings = []
    from tpuminter.analysis import (
        bounded_state,
        codec_conformance,
        loop_blocker,
        proc_seam,
        retrace,
        thread_seam,
    )
    registry = {
        "loop-blocker": loop_blocker,
        "retrace-hazard": retrace,
        "thread-seam": thread_seam,
        "codec-conformance": codec_conformance,
        "bounded-state": bounded_state,
        "proc-seam": proc_seam,
    }
    for checker in checkers:
        findings.extend(registry[checker].check_module(src))
    return findings


# ---------------------------------------------------------------------------
# (1) the tree is clean under the committed allowlist
# ---------------------------------------------------------------------------

def test_tree_clean_under_allowlist():
    report = run_project(REPO_ROOT)
    assert report.clean, "\n" + "\n".join(report.render())
    # the allowlist is doing real work (first-run findings were all
    # justified, not deleted) and every entry carries a reason
    assert report.suppressed, "allowlist suppressed nothing — stale suite?"
    for entry in Allowlist.load().entries:
        assert entry["reason"].strip()


def test_check_cli_json_mode():
    proc = subprocess.run(
        [sys.executable, os.path.join("scripts", "check.py"),
         "--json", "--no-ruff"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert payload["findings"] == []
    assert payload["suppressed"]
    assert payload["stale_allowlist_entries"] == []


def test_stale_allowlist_entry_is_reported():
    stale = Allowlist([{
        "checker": "loop-blocker", "path": "tpuminter/nowhere.py",
        "qualname": "gone", "symbol": "os.fsync",
        "reason": "this code was deleted long ago",
    }])
    report = run_project(REPO_ROOT, allowlist=stale)
    assert not report.clean
    assert len(report.stale_entries) == 1


def test_allowlist_rejects_empty_reason():
    with pytest.raises(ValueError):
        Allowlist([{
            "checker": "loop-blocker", "path": "x.py",
            "qualname": "f", "symbol": "open", "reason": "  ",
        }])


# ---------------------------------------------------------------------------
# (2) the fixture corpus: each checker still catches its bug class
# ---------------------------------------------------------------------------

def test_loop_blocker_catches_pre_pr2_on_loop_verify():
    findings = _fixture_findings(
        "pre_pr2_on_loop_verify.py", ["loop-blocker"]
    )
    symbols = {f.symbol for f in findings}
    assert "chain.scrypt_hash" in symbols     # the PR 2 bug itself
    assert "time.sleep" in symbols
    assert "os.fsync" in symbols              # propagated two hops deep
    quals = {f.qualname for f in findings if f.symbol == "os.fsync"}
    assert "Coordinator._settle" in quals


def test_retrace_catches_pre_pr7_uncached_jit():
    findings = _fixture_findings("pre_pr7_uncached_jit.py", ["retrace-hazard"])
    symbols = {f.symbol for f in findings}
    assert "jax.jit" in symbols
    assert "pl.pallas_call" in symbols
    # the cached factory itself must NOT be flagged...
    assert not any(f.qualname == "build_sweep" for f in findings)
    # ...but the list literal passed to it must be
    assert any(
        f.qualname == "dispatch" and "unhashable" in f.message
        for f in findings
    )


def test_retrace_catches_uncached_sched_factory():
    """ISSUE 16 hazard variant: a shared-schedule sweep whose jit
    wrapper is rebuilt per dispatch re-traces the whole unrolled
    compression every window — `tpuminter.analysis` must flag it (the
    production factories are lru_cached precisely for this)."""
    findings = _fixture_findings(
        "uncached_sched_factory.py", ["retrace-hazard"]
    )
    assert any(
        f.qualname == "sched_sweep" and f.symbol == "jax.jit"
        for f in findings
    )
    # the cached factory is the FIX — it must stay quiet...
    assert not any(f.qualname == "build_sched_sweep" for f in findings)
    # ...but the list literal defeating it at the call site must be loud
    assert any(
        f.qualname == "dispatch_window" and "unhashable" in f.message
        for f in findings
    )


def test_retrace_catches_uncached_splitmix_factory():
    """ISSUE 17 hazard variant: a device-lane sweep whose jit program
    (or Pallas lane kernel) is rebuilt per window re-traces the scan
    body on every dispatch — `tpuminter.analysis` must flag it (the
    splitmix engine's sweep_program/pallas_splitmix_batch are cached
    precisely for this)."""
    findings = _fixture_findings(
        "uncached_splitmix_factory.py", ["retrace-hazard"]
    )
    assert any(
        f.qualname == "lane_dispatch" and f.symbol == "jax.jit"
        for f in findings
    )
    assert any(
        f.qualname == "lane_kernel" and f.symbol == "pl.pallas_call"
        for f in findings
    )
    # the cached factory is the FIX — it must stay quiet...
    assert not any(f.qualname == "build_lane_sweep" for f in findings)
    # ...but the list literal defeating it at the call site must be loud
    assert any(
        f.qualname == "resolve_window" and "unhashable" in f.message
        for f in findings
    )


def test_thread_seam_catches_cross_loop_write():
    findings = _fixture_findings("cross_loop_write.py", ["thread-seam"])
    assert any(
        f.qualname == "Group.rebalance" and f.symbol == "worker.backlog"
        for f in findings
    )
    # seam-respecting code stays quiet: the thread body owns its writes,
    # shutdown hops via call_soon_threadsafe
    assert not any(f.qualname == "Group._shard_thread" for f in findings)
    assert not any(f.qualname == "Group.shutdown" for f in findings)


def test_codec_conformance_catches_bad_table():
    findings = _fixture_findings("codec_bad.py", ["codec-conformance"])
    violations = {f.symbol.split(":", 1)[0] for f in findings if ":" in f.symbol}
    assert "duplicate-tag" in violations
    assert "json-collision" in violations
    assert "length-collision" in violations
    assert "missing-crc" in violations
    assert "tag-not-first" in violations
    assert any(
        f.qualname == "encode_ping" and f.symbol == "_PING"
        for f in findings
    )


def test_codec_conformance_catches_bad_roll_dialect_table():
    """The ISSUE 14 bug class: a careless RollAssign/Beacon port that
    reuses the Result tag (a beacon would decode as a full-chunk settle
    — silent over-settling), collides on packed length, skips the CRC
    trailer, and packs u64 fields unguarded must fail lint."""
    findings = _fixture_findings("roll_dialect_bad.py", ["codec-conformance"])
    violations = {f.symbol.split(":", 1)[0] for f in findings if ":" in f.symbol}
    assert "duplicate-tag" in violations
    assert "length-collision" in violations
    assert "missing-crc" in violations
    assert any(
        f.symbol.startswith("duplicate-tag:")
        and "_BIN_BEACON" in f.message
        for f in findings
    )
    assert any(
        f.symbol == "length-collision:_BIN_ASSIGN_ROLL" for f in findings
    )
    assert any(
        f.qualname == "encode_roll" and f.symbol == "_BIN_ASSIGN_ROLL"
        for f in findings
    )


def test_codec_conformance_catches_bad_workload_port():
    """The ISSUE 15 bug class: a second-workload port that reuses the
    hashcore params tag, collides on packed length, skips the CRC
    trailer, and packs u64 params unguarded must fail lint — and its
    colliding ``*_WID`` constants must trip the workload-id namespace
    rule, both within the fixture and cross-module against the real
    ``HASHCORE_WID``."""
    from tpuminter.analysis import codec_conformance

    findings = _fixture_findings("workload_bad.py", ["codec-conformance"])
    violations = {
        f.symbol.split(":", 1)[0] for f in findings if ":" in f.symbol
    }
    assert "length-collision" in violations
    assert "missing-crc" in violations
    assert any(
        f.qualname == "pack_params" and f.symbol == "_BIN_BCPARAMS"
        for f in findings
    )
    fixture = parse_module(
        REPO_ROOT, os.path.join(FIXTURES, "workload_bad.py")
    )
    hashcore = parse_module(
        REPO_ROOT, os.path.join("tpuminter", "workloads", "hashcore.py")
    )
    project = codec_conformance.check_project([fixture, hashcore])
    symbols = {f.symbol for f in project}
    # tag 0xC0 claimed by both modules: one wire namespace (every
    # claimant after the first sorted one is flagged)
    assert "cross-module-tag:_BIN_HCPARAMS" in symbols
    # wid 1 claimed three times (twice in the fixture, once for real):
    # the first claimant keeps the id, the other two are flagged
    assert "workload-id-collision:OTHERCORE_WID" in symbols
    assert "workload-id-collision:HASHCORE_WID" in symbols
    assert "workload-id-collision:BADCORE_WID" not in symbols


def test_codec_conformance_catches_bad_fabric_dialect():
    """The ISSUE 20 bug class: a compute-fabric port that reuses the
    dict params tag (in-module AND against the real dictsearch module),
    collides on packed length, skips the CRC trailer, packs u64
    emission counters unguarded, and claims dictsearch's workload id
    must fail lint."""
    from tpuminter.analysis import codec_conformance

    findings = _fixture_findings(
        "fabric_dialect_bad.py", ["codec-conformance"]
    )
    violations = {
        f.symbol.split(":", 1)[0] for f in findings if ":" in f.symbol
    }
    assert "duplicate-tag" in violations
    assert "length-collision" in violations
    assert "missing-crc" in violations
    assert any(
        f.qualname == "encode_emit" and f.symbol == "_BIN_FABEMIT"
        for f in findings
    )
    fixture = parse_module(
        REPO_ROOT, os.path.join(FIXTURES, "fabric_dialect_bad.py")
    )
    dictsearch = parse_module(
        REPO_ROOT, os.path.join("tpuminter", "workloads", "dictsearch.py")
    )
    project = codec_conformance.check_project([fixture, dictsearch])
    symbols = {f.symbol for f in project}
    # tag 0xC5 claimed by both modules: one wire namespace (every
    # claimant after the first sorted one is flagged)
    assert "cross-module-tag:_BIN_DICTPARAMS_HEAD" in symbols
    # wid 2 claimed three times (twice in the fixture, once for real):
    # the first claimant keeps the id, the other two are flagged
    assert "workload-id-collision:FABCORE2_WID" in symbols
    assert "workload-id-collision:DICT_WID" in symbols
    assert "workload-id-collision:FABCORE_WID" not in symbols


def test_codec_conformance_covers_the_live_fabric_dialect():
    """The shipped fabric frames are under the checker's eye — the Emit
    streaming partial (0xBE, protocol.py) and the dict params frame
    (0xC5, dictsearch.py) parse out with the right tags, the variable-
    length ``_HEAD`` marking, and the CRC seal; the merged table and
    the cross-module tag/wid namespaces stay clean — so a regression
    to either dialect fails lint, not just this suite."""
    from tpuminter.analysis.codec_conformance import (
        check_project,
        check_table,
        extract_kinds,
        extract_wids,
        struct_size,
    )

    proto = parse_module(REPO_ROOT, os.path.join("tpuminter", "protocol.py"))
    dicts = parse_module(
        REPO_ROOT, os.path.join("tpuminter", "workloads", "dictsearch.py")
    )
    hashcore = parse_module(
        REPO_ROOT, os.path.join("tpuminter", "workloads", "hashcore.py")
    )
    kinds = {
        k["name"]: k for k in extract_kinds(proto) + extract_kinds(dicts)
    }
    emit = kinds["_BIN_EMIT_HEAD"]
    assert emit["tag"] == 0xBE
    assert emit["has_crc"] and emit["variable"]
    assert struct_size(emit["fmt"]) == 33  # 37 on the wire with the CRC
    dp = kinds["_BIN_DICTPARAMS_HEAD"]
    assert dp["tag"] == 0xC5
    assert dp["has_crc"] and dp["variable"]
    assert struct_size(dp["fmt"]) == 31
    assert check_table(list(kinds.values())) == []
    assert check_project([proto, dicts, hashcore]) == []
    assert [w["name"] for w in extract_wids(dicts)] == ["DICT_WID"]


def test_codec_conformance_covers_the_live_workload_codecs():
    """The registry-declared workload codecs are under the checker's
    eye: the hashcore params frame and every fold accumulator layout
    parse out of ``tpuminter/workloads/`` with distinct tags, distinct
    packed lengths, and the CRC seal — and the live table is clean."""
    from tpuminter.analysis.codec_conformance import (
        check_table,
        extract_kinds,
        extract_wids,
    )

    hashcore = parse_module(
        REPO_ROOT, os.path.join("tpuminter", "workloads", "hashcore.py")
    )
    folds = parse_module(
        REPO_ROOT, os.path.join("tpuminter", "workloads", "folds.py")
    )
    kinds = {
        k["name"]: k
        for src in (hashcore, folds)
        for k in extract_kinds(src)
    }
    assert kinds["_BIN_HCPARAMS"]["tag"] == 0xC0
    fold_layouts = ("_BIN_WMIN", "_BIN_WTOPK", "_BIN_WMATCH", "_BIN_WSUM")
    tags = {kinds[name]["tag"] for name in fold_layouts}
    assert len(tags) == len(fold_layouts)  # distinct accumulator tags
    assert all(kinds[name]["has_crc"] for name in fold_layouts)
    assert check_table(list(kinds.values())) == []
    wids = extract_wids(hashcore)
    assert [w["name"] for w in wids] == ["HASHCORE_WID"]


def test_codec_conformance_covers_the_live_roll_dialect():
    """The shipped 0xB9/0xBA kinds are under the checker's eye — parsed
    out of tpuminter/protocol.py with the right tags, distinct packed
    lengths, and the CRC seal — so a future regression to any of them
    fails lint rather than relying on this test suite alone."""
    from tpuminter.analysis.codec_conformance import (
        check_table,
        extract_kinds,
        struct_size,
    )

    src = parse_module(REPO_ROOT, os.path.join("tpuminter", "protocol.py"))
    kinds = {k["name"]: k for k in extract_kinds(src)}
    roll = kinds["_BIN_ASSIGN_ROLL"]
    beacon = kinds["_BIN_BEACON"]
    assert roll["tag"] == 0xB9 and beacon["tag"] == 0xBA
    assert roll["has_crc"] and beacon["has_crc"]
    # 29- and 65-byte bodies (33/69 with the CRC trailer on the wire)
    assert struct_size(roll["fmt"]) == 29
    assert struct_size(beacon["fmt"]) == 65
    assert check_table(list(kinds.values())) == []


def test_bounded_state_catches_unbounded_table():
    findings = _fixture_findings("unbounded_table.py", ["bounded-state"])
    symbols = {f.symbol for f in findings}
    assert "self._ledger" in symbols   # dict, no eviction seam
    assert "self._backlog" in symbols  # deque, no maxlen, never drained
    # attributes WITH a seam or bound, and unstamped classes, stay quiet
    assert "self._winners" not in symbols   # popped in retire()
    assert "self._recent" not in symbols    # deque(maxlen=...)
    assert "self._seeded" not in symbols    # non-empty construction
    assert not any(f.qualname.startswith("Scratch") for f in findings)
    assert all(f.qualname == "Registry.__init__" for f in findings)


def test_proc_seam_catches_boundary_violations():
    """ISSUE 19: every shortcut the process seam forbids — unpicklable
    spawn targets (lambda and nested def), a lambda smuggled through
    ``args=``, a module-level mutable passed as if it stayed shared,
    and the fork start method in an asyncio-using module."""
    findings = _fixture_findings("proc_seam_bad.py", ["proc-seam"])
    symbols = {f.symbol for f in findings}
    assert "target=lambda" in symbols
    assert "target=shard_body" in symbols        # nested def target
    assert "args-lambda" in symbols
    assert "shared-mutable:SHARED_REGISTRY" in symbols
    assert "fork-start-method" in symbols
    assert len(findings) == 5, [f.render() for f in findings]


def test_proc_seam_quiet_on_the_real_process_seam():
    """The production multi-process module is the checker's negative
    control: spawn context, module-level ``_child_main`` target, plain
    picklable cfg dict — zero findings, with NO allowlist help."""
    from tpuminter.analysis import proc_seam

    src = parse_module(REPO_ROOT, os.path.join("tpuminter", "multiproc.py"))
    assert proc_seam.check_module(src) == []


def test_bounded_state_covers_the_aggregator_tables():
    """ISSUE 18: the aggregator is stamped, so the checker's lifetime
    oracle puts its lease/beacon/template tables IN SCOPE — and each
    one carries a real eviction seam in the class body. If a future
    table lands without its seam, the tree-clean gate above fails; this
    test pins that the coverage itself can't silently lapse (an
    unstamped Aggregator would pass tree-clean by being invisible)."""
    import ast

    from tpuminter.analysis import bounded_state

    src = parse_module(
        REPO_ROOT, os.path.join("tpuminter", "federation", "aggregator.py")
    )
    agg = next(
        n for n in ast.walk(src.tree)
        if isinstance(n, ast.ClassDef) and n.name == "Aggregator"
    )
    init = next(
        n for n in agg.body
        if isinstance(n, ast.FunctionDef) and n.name == "__init__"
    )
    assert bounded_state._calls_stamp(init), "Aggregator lost its stamp"
    seams = bounded_state._evicted_attrs(agg)
    for table in ("_templates", "_leases", "_lease_tasks", "_beacon_hw"):
        assert table in seams, f"{table} lost its eviction seam"
    assert bounded_state.check_module(src) == []


# ---------------------------------------------------------------------------
# (3) runtime loop-affinity detector
# ---------------------------------------------------------------------------

class _Victim:
    def __init__(self):
        self.counter = 0


def _run_loop_in_thread(coro_fn, *args):
    """Run ``coro_fn(*args)`` inside a fresh loop on a fresh thread."""
    box = {}

    def runner():
        loop = asyncio.new_event_loop()
        try:
            box["result"] = loop.run_until_complete(coro_fn(*args))
        except BaseException as exc:  # noqa: BLE001 — re-raised below
            box["error"] = exc
        finally:
            loop.close()

    t = threading.Thread(target=runner)
    t.start()
    t.join(30)
    if "error" in box:
        raise box["error"]
    return box.get("result")


@pytest.fixture
def detector():
    affinity.reset()
    affinity.enable()
    yield affinity
    affinity.disable()
    affinity.reset()


def test_affinity_catches_seeded_cross_loop_mutation(detector):
    victim = _Victim()

    async def owner_side():
        affinity.stamp(victim)
        victim.counter += 1  # own-loop write: fine

    async def intruder_side():
        victim.counter = 99  # deliberate cross-loop mutation

    _run_loop_in_thread(owner_side)
    _run_loop_in_thread(intruder_side)
    bad = affinity.violations()
    assert len(bad) == 1
    assert bad[0]["cls"] == "_Victim"
    assert bad[0]["attr"] == "counter"
    assert victim.counter == 99  # non-strict mode records, never alters


def test_affinity_strict_raises(detector):
    affinity.enable(strict=True)
    victim = _Victim()

    async def owner_side():
        affinity.stamp(victim)

    async def intruder_side():
        victim.counter = 7

    _run_loop_in_thread(owner_side)
    with pytest.raises(affinity.LoopAffinityError):
        _run_loop_in_thread(intruder_side)


def test_affinity_exempts_executor_threads(detector):
    victim = _Victim()

    async def owner_side():
        affinity.stamp(victim)

        def executor_write():
            victim.counter = 42  # sanctioned offload: no loop running

        await asyncio.get_running_loop().run_in_executor(
            None, executor_write
        )

    _run_loop_in_thread(owner_side)
    assert affinity.violations() == []
    assert victim.counter == 42


def test_affinity_rebind_transfers_ownership(detector):
    victim = _Victim()

    async def owner_side():
        affinity.stamp(victim)

    async def adopter_side():
        affinity.rebind(victim)
        victim.counter = 5  # now a home write

    _run_loop_in_thread(owner_side)
    _run_loop_in_thread(adopter_side)
    assert affinity.violations() == []


def test_affinity_disabled_is_inert():
    affinity.disable()
    affinity.reset()
    victim = _Victim()
    assert affinity.stamp(victim) is victim
    assert type(victim) is _Victim  # no class swap when disabled
    victim.counter = 1
    assert affinity.violations() == []


# ---------------------------------------------------------------------------
# (4) deterministic mirror of the hypothesis table properties
# (tests/test_properties.py carries the shrinking versions; this image
# lacks hypothesis, so tier-1 drives the same oracle with a seeded RNG)
# ---------------------------------------------------------------------------

import random

from tpuminter.analysis.codec_conformance import (
    JSON_SNIFF_BYTE,
    check_table,
    struct_size,
)


def _random_table(rng):
    kinds = []
    for i in range(rng.randint(1, 8)):
        body = "".join(
            rng.choice("BHIQ") for _ in range(rng.randint(1, 5))
        )
        kinds.append({
            "name": f"_K{i}",
            "module": rng.choice(["a.py", "b.py"]),
            "line": i + 1,
            "tag": rng.choice([None, rng.randint(0, 255), 0x7B]),
            "fmt": "<" + body,
            "variable": rng.random() < 0.3,
            "has_crc": rng.random() < 0.7,
        })
    return kinds


def _oracle(kinds):
    expected = set()
    by_tag = {}
    for k in kinds:
        if k["tag"] is not None:
            by_tag.setdefault(k["tag"], []).append(k)
    for tag, group in by_tag.items():
        for k in group[1:]:
            expected.add(("duplicate-tag", k["name"]))
        if tag == JSON_SNIFF_BYTE:
            for k in group:
                expected.add(("json-collision", k["name"]))
    by_mod = {}
    for k in kinds:
        if k["fmt"] and not k["variable"]:
            by_mod.setdefault(k["module"], []).append(k)
    for group in by_mod.values():
        by_size = {}
        for k in group:
            size = struct_size(k["fmt"])
            if size is not None:
                by_size.setdefault(size, []).append(k)
        for clash in by_size.values():
            for k in sorted(clash, key=lambda k: k["line"])[1:]:
                expected.add(("length-collision", k["name"]))
    for k in kinds:
        if k["tag"] is not None and not k["fmt"][1:].startswith("B"):
            expected.add(("tag-not-first", k["name"]))
        if not k["has_crc"]:
            expected.add(("missing-crc", k["name"]))
    return expected


def test_codec_table_core_matches_oracle_seeded():
    rng = random.Random(0x9E3779B9)
    for _ in range(400):
        kinds = _random_table(rng)
        got = {(v["violation"], v["kind"]) for v in check_table(kinds)}
        assert got == _oracle(kinds), kinds


def test_codec_table_core_accepts_repaired_tables_seeded():
    rng = random.Random(0xC0FFEE)
    for _ in range(100):
        kinds = _random_table(rng)
        for i, k in enumerate(kinds):
            k["tag"] = 0xA0 + i
            k["fmt"] = "<B" + "B" * i
            k["variable"] = False
            k["has_crc"] = True
        assert check_table(kinds) == []
