"""Durability + crash-recovery tests (ISSUE 3's fault-injection layer).

Three strata, mirroring how the LSP stack is tested:

- **Pure journal properties** (deterministic seeded drives, the
  bundled-codec corruption properties of tests/test_properties.py
  applied to the on-disk record stream): a torn/truncated tail
  truncates cleanly, a corrupted record can only look like loss of a
  suffix (never like different records), and replay is idempotent
  (double replay, and snapshot-compaction equivalence).
- **Journal runtime**: append/flush/reopen round-trips state; ``kill
  -9`` via :meth:`Journal.crash` loses at most the unflushed tail.
- **Role e2e**: the LSP boot-epoch regression (a server restarted on
  the same port is a FRESH session — stale sequence state is never
  resumed), the coordinator crash drill (kill -9 mid-epoch with miners
  and ≥2 bound clients; restart from the journal; no acknowledged
  winner lost, exactly one answer per request, fleet resumes
  unattended), winner dedup across restarts, and the loadgen crash
  scenario's tier-1 gate.
"""

import asyncio
import json as _json
import os
import random
import sys
import time

import pytest

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ),
)

import loadgen  # noqa: E402  (scripts/ is not a package)

from tpuminter import chain  # noqa: E402
from tpuminter.client import submit  # noqa: E402
from tpuminter.coordinator import Coordinator  # noqa: E402
from tpuminter.journal import (  # noqa: E402
    Journal,
    encode_record,
    merge_ranges,
    replay,
    scan,
    subtract_range,
)
from tpuminter.lsp import (  # noqa: E402
    LspClient,
    LspConnectionLost,
    LspServer,
    Params,
)
from tpuminter.protocol import (  # noqa: E402
    PowMode,
    Request,
    request_to_obj,
)
from tpuminter.worker import CpuMiner, run_miner_reconnect  # noqa: E402

from tests.test_e2e import FAST, brute_min, run  # noqa: E402


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _mk_request(jid=1, upper=4095, ckey="", data=b"x"):
    return Request(
        job_id=jid, mode=PowMode.MIN, lower=0, upper=upper, data=data,
        client_key=ckey,
    )


def _record_stream(rng, n_jobs=4):
    """A plausible journal tail: jobs, interleaved settles, some
    finishes/abandons — seeded, so failures reproduce."""
    records = [{"k": "boot", "epoch": 1}]
    live = []
    for j in range(1, n_jobs + 1):
        upper = rng.randrange(1000, 5000)
        req = _mk_request(jid=j, upper=upper, ckey=f"c{j % 2}")
        records.append({"k": "job", "id": j, "req": request_to_obj(req)})
        live.append((j, upper))
    for _ in range(30):
        j, upper = rng.choice(live)
        lo = rng.randrange(0, upper)
        hi = min(upper, lo + rng.randrange(1, 512))
        records.append({
            "k": "settle", "id": j, "lo": lo, "hi": hi,
            "h": f"{rng.getrandbits(64):x}", "n": rng.randrange(lo, hi + 1),
            "s": hi - lo + 1,
        })
    j, _ = live[0]
    records.append({
        "k": "finish", "id": j, "ckey": "c1", "cjid": j, "mode": "min",
        "n": 7, "h": "ab", "found": True, "s": 100,
    })
    records.append({"k": "abandon", "id": live[1][0]})
    return records


def _state_key(state):
    """Canonical comparable view of a RecoveredState."""
    return {
        "epoch": state.boot_epoch,
        "next": state.next_job_id,
        "jobs": {
            jid: (tuple(j.remaining), j.best, j.hashes_done,
                  request_to_obj(j.request))
            for jid, j in state.jobs.items()
        },
        "winners": {k: dict(v) for k, v in state.winners.items()},
    }


# ---------------------------------------------------------------------------
# interval arithmetic
# ---------------------------------------------------------------------------

def test_subtract_and_merge_ranges():
    assert subtract_range([(0, 9)], 3, 5) == ([(0, 2), (6, 9)], 3)
    assert subtract_range([(0, 9)], 0, 9) == ([], 10)
    assert subtract_range([(0, 4)], 7, 9) == ([(0, 4)], 0)
    # idempotent: subtracting again removes nothing
    r, n = subtract_range([(0, 2), (6, 9)], 3, 5)
    assert (r, n) == ([(0, 2), (6, 9)], 0)
    assert merge_ranges([(5, 9), (0, 4), (20, 25)]) == [(0, 9), (20, 25)]


def test_subtract_range_randomized_against_set_model():
    rng = random.Random(7)
    for _ in range(200):
        universe = set()
        ranges = []
        cursor = 0
        for _ in range(rng.randrange(1, 5)):
            cursor += rng.randrange(1, 20)
            size = rng.randrange(1, 30)
            ranges.append((cursor, cursor + size - 1))
            universe |= set(range(cursor, cursor + size))
            cursor += size
        lo = rng.randrange(0, cursor + 10)
        hi = lo + rng.randrange(0, 40)
        new, removed = subtract_range(ranges, lo, hi)
        expect = universe - set(range(lo, hi + 1))
        got = set()
        for a, b in new:
            got |= set(range(a, b + 1))
        assert got == expect
        assert removed == len(universe) - len(expect)


# ---------------------------------------------------------------------------
# record codec: corruption can only look like loss of a suffix
# (the bundled-codec properties of test_properties.py, applied to disk)
# ---------------------------------------------------------------------------

def test_journal_records_roundtrip():
    rng = random.Random(1)
    records = _record_stream(rng)
    blob = b"".join(encode_record(r) for r in records)
    got, clean = scan(blob)
    assert got == records
    assert clean == len(blob)


def test_torn_tail_truncates_to_a_clean_prefix():
    """Truncation at EVERY byte boundary yields an exact prefix of the
    original records — a torn write can only lose a suffix."""
    rng = random.Random(2)
    records = _record_stream(rng, n_jobs=2)
    blob = b"".join(encode_record(r) for r in records)
    for keep in range(len(blob)):
        got, clean = scan(blob[:keep])
        assert got == records[: len(got)]
        assert len(got) < len(records)
        assert clean <= keep


def test_corrupted_record_loses_only_a_suffix():
    """A single-byte flip anywhere in the stream: whatever still
    decodes is an exact prefix of the original records — corruption is
    indistinguishable from a shorter journal, never a different one
    (CRC-32 over size‖payload per record)."""
    rng = random.Random(3)
    records = _record_stream(rng, n_jobs=2)
    blob = bytearray(b"".join(encode_record(r) for r in records))
    for _ in range(300):
        i = rng.randrange(len(blob))
        flip = rng.randrange(1, 256)
        blob[i] ^= flip
        got, _ = scan(bytes(blob))
        assert len(got) < len(records)
        assert got == records[: len(got)]
        blob[i] ^= flip  # restore for the next trial


def test_double_replay_is_idempotent():
    rng = random.Random(4)
    records = _record_stream(rng)
    once = replay(records)
    twice = replay(records + records)
    assert _state_key(once) == _state_key(twice)


def test_snapshot_compaction_is_replay_equivalent():
    """Replaying [boot, snapshot] (what compaction writes) plus a
    residual tail equals replaying the full original stream — and a
    duplicated tail after the snapshot (the records compaction may
    leave buffered) changes nothing."""
    rng = random.Random(5)
    records = _record_stream(rng)
    cut = len(records) - 6
    head, tail = records[:cut], records[cut:]
    state = replay(head)
    compacted = [{"k": "boot", "epoch": state.boot_epoch},
                 state.snapshot_obj()]
    assert _state_key(replay(records)) == _state_key(
        replay(compacted + tail)
    )
    # records already covered by the snapshot may ride after it too
    assert _state_key(replay(compacted + head[1:] + tail)) == _state_key(
        replay(records)
    )


def test_settle_replay_rebuilds_remaining_ranges_and_fold():
    req = _mk_request(jid=9, upper=999, ckey="k")
    records = [
        {"k": "boot", "epoch": 1},
        {"k": "job", "id": 1, "req": request_to_obj(req)},
        {"k": "settle", "id": 1, "lo": 0, "hi": 99, "h": "50", "n": 42,
         "s": 100},
        {"k": "settle", "id": 1, "lo": 300, "hi": 999, "h": "20", "n": 400,
         "s": 700},
    ]
    state = replay(records)
    job = state.jobs[1]
    assert job.remaining == [(100, 299)]
    assert job.best == (0x20, 400)
    assert job.hashes_done == 800
    # the finish retires the job and registers the winner for dedup
    records.append({
        "k": "finish", "id": 1, "ckey": "k", "cjid": 9, "mode": "min",
        "n": 400, "h": "20", "found": True, "s": 1000,
    })
    state = replay(records)
    assert not state.jobs
    assert state.winners[("k", 9)]["n"] == 400


# ---------------------------------------------------------------------------
# journal runtime: reopen, torn-tail repair, crash loses only the tail
# ---------------------------------------------------------------------------

def test_journal_reopen_replays_appends(tmp_path):
    path = str(tmp_path / "j.wal")

    async def session_one():
        journal, state = Journal.open(path)
        assert state.boot_epoch == 1
        req = _mk_request(jid=5, upper=100, ckey="me")
        journal.append("job", {"id": 1, "req": request_to_obj(req)})
        journal.append_encoded(
            b'{"id":1,"lo":0,"hi":49,"h":"aa","n":3,"s":50,"k":"settle"}'
        )
        fired = []
        journal.append(
            "finish",
            {"id": 2, "ckey": "me", "cjid": 6, "mode": "min", "n": 1,
             "h": "bb", "found": True, "s": 10},
            on_durable=lambda: fired.append(1),
        )
        await journal.flush()
        assert fired == [1]
        await journal.aclose()

    asyncio.run(session_one())
    journal2, state2 = Journal.open(path)
    assert state2.boot_epoch == 2  # monotone across incarnations
    assert state2.jobs[1].remaining == [(50, 100)]
    assert state2.winners[("me", 6)]["found"] is True

    # torn tail on disk: garbage after the valid prefix is repaired
    with open(path, "ab") as fh:
        fh.write(b"\xde\xad\xbe\xef-torn-write")
    journal3, state3 = Journal.open(path)
    assert state3.boot_epoch == 3
    assert state3.jobs[1].remaining == [(50, 100)]
    # the file is a clean record stream again (garbage truncated away,
    # then the new boot record appended)
    with open(path, "rb") as fh:
        data = fh.read()
    records, clean = scan(data)
    assert clean == len(data)
    assert records[-1] == {"k": "boot", "epoch": 3}


def test_journal_crash_loses_at_most_the_unflushed_tail(tmp_path):
    path = str(tmp_path / "j.wal")

    async def scenario():
        journal, _ = Journal.open(path)
        req = _mk_request(jid=1, upper=10)
        journal.append("job", {"id": 1, "req": request_to_obj(req)})
        await journal.flush()
        # buffered but never flushed: must vanish, not corrupt
        journal.append("abandon", {"id": 1})
        journal.crash()

    asyncio.run(scenario())
    _, state = Journal.open(path)
    assert 1 in state.jobs  # the flushed job survived; the tail is gone


def test_journal_disk_failure_fails_loudly_but_never_wedges_replies(
    tmp_path, monkeypatch
):
    """If the WAL's disk dies mid-flight (ENOSPC, yanked volume), the
    journal must stop journaling LOUDLY — but every on_durable callback
    (the thing that releases client replies) still fires, both for the
    batch that hit the error and for all later appends."""
    path = str(tmp_path / "j.wal")

    async def scenario():
        journal, _ = Journal.open(path)

        def boom(blob, need_sync):
            raise OSError(28, "No space left on device")

        monkeypatch.setattr(journal, "_write_sync", boom)
        fired = []
        journal.append(
            "finish", {"id": 1, "ckey": "", "cjid": 1, "mode": "min",
                       "n": 0, "h": "0", "found": True, "s": 1},
            on_durable=lambda: fired.append("first"),
        )
        await journal.flush()
        assert fired == ["first"]
        assert journal._failed
        # later appends short-circuit but still release their replies
        journal.append(
            "finish", {"id": 2, "ckey": "", "cjid": 2, "mode": "min",
                       "n": 0, "h": "0", "found": True, "s": 1},
            on_durable=lambda: fired.append("second"),
        )
        assert fired == ["first", "second"]
        await journal.aclose()

    asyncio.run(scenario())


def test_journal_compaction_preserves_state(tmp_path):
    path = str(tmp_path / "j.wal")

    async def scenario():
        journal, state = Journal.open(path, compact_bytes=512)
        journal.snapshot_provider = lambda: state.snapshot_obj()
        req = _mk_request(jid=2, upper=9999, ckey="cc")
        state.apply({"k": "job", "id": 1, "req": request_to_obj(req)})
        journal.append("job", {"id": 1, "req": request_to_obj(req)})
        for i in range(40):
            rec = {
                "k": "settle", "id": 1, "lo": 100 * i,
                "hi": 100 * i + 49, "h": "ff", "n": 100 * i, "s": 50,
            }
            state.apply(rec)
            journal.append("settle", dict(rec))
            await asyncio.sleep(0)
        await journal.flush()
        assert journal.stats["compactions"] >= 1
        await journal.aclose()
        return state

    state = asyncio.run(scenario())
    _, recovered = Journal.open(path)
    assert (
        recovered.jobs[1].remaining == state.jobs[1].remaining
        and recovered.jobs[1].hashes_done == state.jobs[1].hashes_done
    )


# ---------------------------------------------------------------------------
# LSP boot epoch: a restarted server is a FRESH session (satellite #1)
# ---------------------------------------------------------------------------

def test_server_restart_mid_connection_is_a_fresh_session():
    """Regression (issue satellite): a client whose server restarts on
    the SAME port must never resume old sequence state — the old
    connection dies promptly via the reset epoch-ack (long before its
    own silence timeout, which this test's params push out to seconds),
    no stale DATA is ever delivered to the new incarnation, and a
    redial sees a different boot epoch with sequence numbering starting
    over."""
    # epoch_limit high enough that silence-detection CANNOT explain the
    # loss — only the boot-epoch reset can
    params = Params(
        epoch_limit=60, epoch_millis=50, window_size=8,
        max_backoff_interval=2, max_unacked_messages=8,
    )

    async def scenario():
        server1 = await LspServer.create(0, params)
        port = server1.port
        epoch1 = server1.boot_epoch
        client = await LspClient.connect("127.0.0.1", port, params)
        assert client.server_epoch == epoch1 != 0
        client.write(b"hello")
        conn_id, payload = await asyncio.wait_for(server1.read(), 5)
        assert payload == b"hello"
        # kill -9 the server: socket closed, no drain, no goodbyes
        server1.crash()
        await server1.endpoint.wait_closed()
        # same port, new incarnation
        server2 = None
        for _ in range(50):
            try:
                server2 = await LspServer.create(port, params)
                break
            except OSError:
                await asyncio.sleep(0.02)
        assert server2 is not None
        assert server2.boot_epoch != epoch1
        # the old client keeps talking (data + heartbeats). server2
        # must deliver NONE of it, and the reset ack must kill the old
        # session fast (well under the 3 s silence horizon).
        client.write(b"stale-data-for-the-old-incarnation")
        t0 = time.monotonic()
        with pytest.raises(LspConnectionLost) as exc_info:
            await asyncio.wait_for(client.read(), 2.5)
        assert time.monotonic() - t0 < 2.0
        assert "restarted" in str(exc_info.value)
        assert server2.read_nowait() is None  # no stale delivery
        # redial: fresh session against the new epoch, seq starts over
        client2 = await LspClient.connect("127.0.0.1", port, params)
        assert client2.server_epoch == server2.boot_epoch
        client2.write(b"fresh")
        conn_id2, payload2 = await asyncio.wait_for(server2.read(), 5)
        assert payload2 == b"fresh"
        await client.close(drain_timeout=0.2)
        await client2.close(drain_timeout=0.2)
        await server2.close(drain_timeout=0.2)

    run(scenario(), timeout=30.0)


# ---------------------------------------------------------------------------
# coordinator crash e2e (the acceptance drill)
# ---------------------------------------------------------------------------

class SlowMiner(CpuMiner):
    """CpuMiner throttled enough that jobs are reliably mid-flight when
    the coordinator dies (generator steps run on the executor thread,
    so the sleep never blocks the event loop)."""

    def __init__(self, batch=256, nap=0.003):
        super().__init__(batch=batch)
        self._nap = nap

    def mine(self, request):
        for item in super().mine(request):
            time.sleep(self._nap)
            yield item


async def _restart_coordinator(port, wal, **kwargs):
    for attempt in range(100):
        try:
            return await Coordinator.create(
                port, params=FAST, recover_from=wal, **kwargs
            )
        except OSError:
            await asyncio.sleep(0.02)
    raise AssertionError("could not rebind the coordinator port")


def test_crash_recovery_exactly_once_with_bound_clients(tmp_path):
    """The acceptance drill: kill -9 the coordinator mid-epoch with a
    miner fleet and two bound clients in flight, restart from the
    journal — both clients get exactly one answer each, the answers
    equal brute force (no acknowledged work lost, no corruption), and
    the fleet resumes with zero manual intervention."""
    wal = str(tmp_path / "coord.wal")
    upper = 8191
    payloads = [b"crash-client-a", b"crash-client-b"]

    async def scenario():
        coord = await Coordinator.create(
            params=FAST, chunk_size=512, recover_from=wal
        )
        port = coord.port
        serve = asyncio.ensure_future(coord.serve())
        miners = [
            asyncio.ensure_future(run_miner_reconnect(
                "127.0.0.1", port, SlowMiner(), params=FAST,
                base_backoff=0.05, max_backoff=0.4,
                rng=random.Random(100 + i),
            ))
            for i in range(3)
        ]
        await asyncio.sleep(0.2)
        subs = [
            asyncio.ensure_future(submit(
                "127.0.0.1", port,
                Request(job_id=70 + i, mode=PowMode.MIN, lower=0,
                        upper=upper, data=payloads[i]),
                params=FAST, client_key=f"crash-client-{i}",
                reconnect=True, base_backoff=0.05,
                rng=random.Random(i),
            ))
            for i in range(2)
        ]
        try:
            # both jobs mid-flight: some chunks settled, none finished
            t0 = time.monotonic()
            while coord.stats["results_accepted"] < 4:
                assert time.monotonic() - t0 < 20, "no progress pre-crash"
                await asyncio.sleep(0.01)
            assert coord.stats["jobs_done"] == 0, (
                "crash must land mid-job; slow the miners down"
            )
            # -- kill -9 -------------------------------------------------
            serve.cancel()
            await asyncio.gather(serve, return_exceptions=True)
            endpoint = coord.server.endpoint
            coord.crash()
            await endpoint.wait_closed()
            # -- restart from the journal on the same port ---------------
            coord2 = await _restart_coordinator(port, wal, chunk_size=512)
            assert len(coord2._jobs) == 2, (
                "both mid-flight jobs must replay from the journal"
            )
            # settled coverage survived (a settle buffered inside the
            # batch window at the instant of death may be lost — that
            # range just re-mines), and work remains on both jobs
            assert sum(j.hashes_done for j in coord2._jobs.values()) > 0
            for job in coord2._jobs.values():
                assert job.ranges
            serve = asyncio.ensure_future(coord2.serve())
            # -- the fleet resumes unattended ----------------------------
            results = await asyncio.wait_for(asyncio.gather(*subs), 60.0)
            for i, res in enumerate(results):
                expect = brute_min(payloads[i], 0, upper)
                assert (res.hash_value, res.nonce) == expect
                assert res.found
                assert res.searched >= upper + 1 - 512 * 4  # sanity
            assert not coord2._jobs  # both retired
            return coord2
        finally:
            for t in miners + subs:
                t.cancel()
            await asyncio.gather(*miners, *subs, return_exceptions=True)
            serve.cancel()
            await asyncio.gather(serve, return_exceptions=True)
            # coord (crashed) holds no resources; close the live one
            try:
                await coord2.close()
            except UnboundLocalError:
                await coord.close()

    run(scenario(), timeout=120.0)


def test_winner_survives_restart_and_dedups(tmp_path):
    """An ACKNOWLEDGED winner is never lost and never re-mined: answer
    a job, kill -9, restart from the journal, re-submit the same
    (client_key, job_id) — the identical Result comes straight from the
    journaled winners table with zero hashes spent."""
    wal = str(tmp_path / "coord.wal")
    upper = 2047
    data = b"dedup-me"
    req = Request(
        job_id=31, mode=PowMode.MIN, lower=0, upper=upper, data=data,
        client_key="dedup-client",
    )

    async def scenario():
        coord = await Coordinator.create(
            params=FAST, chunk_size=1024, recover_from=wal
        )
        port = coord.port
        serve = asyncio.ensure_future(coord.serve())
        miner = asyncio.ensure_future(run_miner_reconnect(
            "127.0.0.1", port, CpuMiner(), params=FAST, base_backoff=0.05,
        ))
        try:
            await asyncio.sleep(0.15)
            first = await asyncio.wait_for(
                submit("127.0.0.1", port, req, params=FAST), 30.0
            )
            assert (first.hash_value, first.nonce) == brute_min(
                data, 0, upper
            )
            # -- kill -9 + restart ---------------------------------------
            serve.cancel()
            await asyncio.gather(serve, return_exceptions=True)
            endpoint = coord.server.endpoint
            coord.crash()
            await endpoint.wait_closed()
            coord2 = await _restart_coordinator(port, wal, chunk_size=1024)
            serve = asyncio.ensure_future(coord2.serve())
            assert not coord2._jobs  # nothing to re-mine
            again = await asyncio.wait_for(
                submit("127.0.0.1", port, req, params=FAST), 30.0
            )
            assert again == first
            assert coord2.stats["hashes"] == 0  # answered from the table
            return coord2
        finally:
            miner.cancel()
            await asyncio.gather(miner, return_exceptions=True)
            serve.cancel()
            await asyncio.gather(serve, return_exceptions=True)
            try:
                await coord2.close()
            except UnboundLocalError:
                await coord.close()

    run(scenario(), timeout=90.0)


def test_client_rebind_mid_job_no_duplicate(tmp_path):
    """A durable client that dies and redials MID-JOB re-binds to the
    running job (no duplicate job is mined) and still gets its answer."""
    wal = str(tmp_path / "coord.wal")
    upper = 8191
    data = b"rebind-me"

    async def scenario():
        coord = await Coordinator.create(
            params=FAST, chunk_size=512, recover_from=wal
        )
        port = coord.port
        serve = asyncio.ensure_future(coord.serve())
        miner = asyncio.ensure_future(run_miner_reconnect(
            "127.0.0.1", port, SlowMiner(), params=FAST, base_backoff=0.05,
        ))
        try:
            await asyncio.sleep(0.15)
            req = Request(
                job_id=5, mode=PowMode.MIN, lower=0, upper=upper,
                data=data, client_key="rebinder",
            )
            # first client dies mid-job (hard: no goodbye)
            c1 = await LspClient.connect("127.0.0.1", port, FAST)
            from tpuminter.protocol import encode_msg
            c1.write(encode_msg(req))
            t0 = time.monotonic()
            while coord.stats["results_accepted"] < 2:
                assert time.monotonic() - t0 < 20
                await asyncio.sleep(0.01)
            c1.endpoint.close()  # kill -9 the client
            # second incarnation re-submits the same (ckey, job_id)
            result = await asyncio.wait_for(
                submit("127.0.0.1", port, req, params=FAST), 60.0
            )
            assert (result.hash_value, result.nonce) == brute_min(
                data, 0, upper
            )
            # exactly one job ever existed for the key
            assert coord.stats["jobs_done"] == 1
            assert coord._next_job_id == 2
        finally:
            miner.cancel()
            await asyncio.gather(miner, return_exceptions=True)
            serve.cancel()
            await asyncio.gather(serve, return_exceptions=True)
            await coord.close()

    run(scenario(), timeout=90.0)


# ---------------------------------------------------------------------------
# loadgen crash scenario: the tier-1 gate (issue satellite)
# ---------------------------------------------------------------------------

def test_loadgen_crash_scenario_smoke(capsys):
    """Small-fleet crash drill wired into tier-1 next to the steady
    ``--smoke`` gate: kill the journaled coordinator mid-burst, restart
    from the journal, and require an exactly-once answer ledger plus an
    unattended fleet resumption."""
    rc = loadgen.main([
        "--scenario", "crash", "--miners", "4", "--clients", "4",
        "--duration", "1.5", "--smoke", "--json",
    ])
    out = capsys.readouterr().out
    assert rc == 0, f"crash gate failed: {out}"
    metrics = _json.loads(out.splitlines()[0])
    assert metrics["answered"] > 0
    assert metrics["answers_lost"] == 0
    assert metrics["answers_duplicated"] == 0
    assert metrics["restart_to_first_assign_ms"] < 10_000
    # the journal actually carried state across the restart
    assert metrics["recovered_winners"] > 0
    assert metrics["journal"]["records"] > 0


# ---------------------------------------------------------------------------
# pipelined dispatch × crash recovery (ISSUE 4 satellite): kill -9 with
# depth-2 queues in flight; replay re-mines exactly the un-settled ranges
# ---------------------------------------------------------------------------

def test_pipelined_crash_replay_remines_exactly_the_unsettled_ranges(
    tmp_path,
):
    """A depth-2 miner holds TWO chunks when the coordinator dies, one
    of them settled pre-crash. Replay must (a) show the pipeline really
    was ≥2 deep, (b) rebuild remaining coverage as full-range minus the
    settled chunk ONLY (in-flight pipeline chunks re-mine — they never
    settled), and (c) a recovered coordinator + fresh miner then
    re-mines exactly those nonces, no more, no fewer, with the final
    fold brute-force exact across the crash."""
    from tpuminter.protocol import (
        Assign, Join, Result, Setup, decode_msg, encode_msg,
    )

    wal = str(tmp_path / "coordinator.wal")
    data = b"pipelined crash"
    upper = 4095
    chunk = 1024

    async def scenario():
        coord = await Coordinator.create(
            params=FAST, chunk_size=chunk, recover_from=wal
        )
        serve = asyncio.ensure_future(coord.serve())
        w = await LspClient.connect("127.0.0.1", coord.port, FAST)
        w.write(encode_msg(Join(backend="manual", lanes=1, codec="bin")))
        client = await LspClient.connect("127.0.0.1", coord.port, FAST)
        client.write(encode_msg(Request(
            job_id=31, mode=PowMode.MIN, lower=0, upper=upper, data=data,
            client_key="pipeline-ck",
        )))
        # the single miner must receive a Setup and TWO Assigns before
        # answering anything — the depth-2 pipeline in flight
        assigns = []
        while len(assigns) < 2:
            msg = decode_msg(await asyncio.wait_for(w.read(), 10))
            if isinstance(msg, Assign):
                assigns.append(msg)
            else:
                assert isinstance(msg, Setup)
        a1, a2 = assigns
        assert (a1.lower, a1.upper) == (0, chunk - 1)
        assert (a2.lower, a2.upper) == (chunk, 2 * chunk - 1)
        # settle ONLY the first chunk (a verifiable claim: the true
        # minimum of its range)
        h1, n1 = brute_min(data, a1.lower, a1.upper)
        w.write(encode_msg(Result(
            a1.job_id, PowMode.MIN, n1, h1, found=True,
            searched=a1.upper - a1.lower + 1, chunk_id=a1.chunk_id,
        ), binary=True))
        # wait for the settle record to reach the OS (crash() drops the
        # in-memory buffer; a flushed record survives kill -9)
        deadline = time.monotonic() + 5
        while coord._journal._buffer or coord._journal.stats["records"] < 3:
            assert time.monotonic() < deadline, coord._journal.stats
            await asyncio.sleep(0.01)
        assert coord.stats["dispatches_pipelined"] >= 1
        # -- kill -9 ----------------------------------------------------
        serve.cancel()
        await asyncio.gather(serve, return_exceptions=True)
        coord.crash()
        await coord.server.endpoint.wait_closed()
        await w.close(drain_timeout=0.1)

        # -- pure replay: coverage is full minus the settled chunk -----
        with open(wal, "rb") as fh:
            records, _ = scan(fh.read())
        state = replay(records)
        [job] = state.jobs.values()
        assert merge_ranges(job.remaining) == [(chunk, upper)]
        assert job.best == (h1, n1)
        assert job.hashes_done == chunk

        # -- recovered coordinator re-mines EXACTLY the rest -----------
        coord2 = await Coordinator.create(
            params=FAST, chunk_size=chunk, recover_from=wal
        )
        serve2 = asyncio.ensure_future(coord2.serve())
        miner2 = asyncio.ensure_future(run_miner_reconnect(
            "127.0.0.1", coord2.port, CpuMiner(), params=FAST, max_dials=1,
        ))
        try:
            result = await asyncio.wait_for(submit(
                "127.0.0.1", coord2.port,
                Request(job_id=31, mode=PowMode.MIN, lower=0, upper=upper,
                        data=data, client_key="pipeline-ck"),
                params=FAST,
            ), 30.0)
            assert (result.hash_value, result.nonce) == brute_min(
                data, 0, upper
            )
            assert result.searched == upper + 1  # pre-crash + re-mined
            # the re-mine covered exactly the un-settled nonces
            assert coord2.stats["hashes"] == upper + 1 - chunk
        finally:
            miner2.cancel()
            serve2.cancel()
            await asyncio.gather(miner2, serve2, return_exceptions=True)
            await coord2.close()
            await client.close(drain_timeout=0.1)

    run(scenario(), timeout=60.0)


def test_rolled_job_survives_crash_with_batched_path(tmp_path):
    """Rolled e2e through the durable coordinator (ISSUE 7): a rolled
    job at brute-force-checkable difficulty survives a mid-job kill -9
    + journal replay with the BATCHED sweep on (JaxMiner roll_batch >
    1), and the reconnecting client gets exactly one answer — the exact
    global minimum, equal to hashlib brute force."""
    import struct

    import numpy as np

    from tpuminter.jax_worker import JaxMiner

    wal = str(tmp_path / "rolled.wal")
    nb, ens = 9, 4  # 2048 global indices, 512-nonce segments
    rng = np.random.RandomState(11)
    prefix, suffix = rng.bytes(41), rng.bytes(60)
    branch = (rng.bytes(32), rng.bytes(32))
    hdr80 = chain.GENESIS_HEADER.pack()
    cb = chain.CoinbaseTemplate(prefix, suffix, 4)
    want = min(
        (
            chain.hash_to_int(chain.dsha256(
                chain.rolled_header(hdr80, cb, branch, en).pack()[:76]
                + struct.pack("<I", n)
            )),
            (en << nb) | n,
        )
        for en in range(ens)
        for n in range(1 << nb)
    )
    req = Request(
        job_id=77, mode=PowMode.TARGET, lower=0, upper=(ens << nb) - 1,
        header=hdr80, target=1,  # unbeatable: must exhaust + min-fold
        coinbase_prefix=prefix, coinbase_suffix=suffix,
        extranonce_size=4, branch=branch, nonce_bits=nb,
    )

    class SlowJaxMiner(JaxMiner):
        """Batched rolled miner throttled so the crash lands mid-job."""

        def mine(self, request):
            for item in super().mine(request):
                time.sleep(0.05)
                yield item

    async def scenario():
        coord = await Coordinator.create(
            params=FAST, chunk_size=256, recover_from=wal
        )
        port = coord.port
        serve = asyncio.ensure_future(coord.serve())
        miners = [
            asyncio.ensure_future(run_miner_reconnect(
                "127.0.0.1", port,
                SlowJaxMiner(batch=128, roll_batch=3, lanes=1),
                params=FAST, base_backoff=0.05, max_backoff=0.4,
                rng=random.Random(200 + i),
            ))
            for i in range(2)
        ]
        await asyncio.sleep(0.2)
        sub = asyncio.ensure_future(submit(
            "127.0.0.1", port, req, params=FAST,
            client_key="rolled-crash-client", reconnect=True,
            base_backoff=0.05, rng=random.Random(42),
        ))
        coord2 = None
        try:
            t0 = time.monotonic()
            while coord.stats["results_accepted"] < 2:
                assert time.monotonic() - t0 < 30, "no progress pre-crash"
                await asyncio.sleep(0.01)
            assert coord.stats["jobs_done"] == 0, (
                "crash must land mid-job; slow the miners down"
            )
            # -- kill -9 -------------------------------------------------
            serve.cancel()
            await asyncio.gather(serve, return_exceptions=True)
            endpoint = coord.server.endpoint
            coord.crash()
            await endpoint.wait_closed()
            coord2 = await _restart_coordinator(port, wal, chunk_size=256)
            assert len(coord2._jobs) == 1  # the rolled job replayed
            serve = asyncio.ensure_future(coord2.serve())
            res = await asyncio.wait_for(sub, 90.0)
            assert not res.found
            assert (res.hash_value, res.nonce) == want
            assert res.searched >= (ens << nb) - 256 * 2  # replay re-mines
            assert not coord2._jobs
        finally:
            for t in miners + [sub]:
                t.cancel()
            await asyncio.gather(*miners, sub, return_exceptions=True)
            serve.cancel()
            await asyncio.gather(serve, return_exceptions=True)
            if coord2 is not None:
                await coord2.close()
            else:
                await coord.close()

    run(scenario(), timeout=150.0)


# ---------------------------------------------------------------------------
# admission state is durable (ISSUE 19 satellite)
# ---------------------------------------------------------------------------

def test_quota_buckets_survive_crash_recovery(tmp_path):
    """A tenant's token bucket is part of the recovered state: admit 4
    of a burst-6 budget, kill -9, restart from the journal — the tenant
    resumes at ~2 tokens (never a fresh burst: a crash must not be a
    quota-reset button), its strike count rides along, and an identity
    the journal never saw still gets the full burst. The refill clock
    restarting at boot only under-grants (rate here is ~0 anyway)."""
    from tpuminter.journal import scan_file
    from tpuminter.protocol import encode_msg

    wal = str(tmp_path / "quota.wal")

    async def scenario():
        coord = await Coordinator.create(
            params=FAST, chunk_size=512, recover_from=wal,
            quota_rate=0.001, quota_burst=6,
        )
        port = coord.port
        serve = asyncio.ensure_future(coord.serve())
        coord2 = None
        client = None
        try:
            # no miners on purpose: admission happens at submission,
            # the jobs just queue — this test is about the bucket
            client = await LspClient.connect("127.0.0.1", port, FAST)
            for jid in range(1, 5):
                client.write(encode_msg(Request(
                    job_id=jid, mode=PowMode.MIN, lower=0, upper=4095,
                    data=b"quota-%d" % jid, client_key="tenant-q",
                )))
            t0 = time.monotonic()
            while len(coord._jobs) < 4:
                assert time.monotonic() - t0 < 10, "submissions lost"
                await asyncio.sleep(0.01)
            tok, _, strikes = coord._buckets["tenant-q"]
            assert tok == pytest.approx(2.0, abs=0.01)
            # flush the dirty bucket the way the rate ticker does, then
            # hold the crash until the record is REALLY on disk
            coord._journal_quota()
            t0 = time.monotonic()
            while not replay(scan_file(wal)).quota:
                assert time.monotonic() - t0 < 10, "quota record unwritten"
                await asyncio.sleep(0.02)
            # -- kill -9 -------------------------------------------------
            serve.cancel()
            await asyncio.gather(serve, return_exceptions=True)
            endpoint = coord.server.endpoint
            coord.crash()
            await endpoint.wait_closed()
            # -- restart from the journal --------------------------------
            coord2 = await _restart_coordinator(
                port, wal, quota_rate=0.001, quota_burst=6
            )
            assert "tenant-q" in coord2._buckets, (
                "the tenant's bucket must survive the crash"
            )
            tok2, _, strikes2 = coord2._buckets["tenant-q"]
            assert tok2 == pytest.approx(tok, abs=0.01)
            assert strikes2 == strikes
            assert "tenant-fresh" not in coord2._buckets  # full burst due
        finally:
            if client is not None:
                await client.close(drain_timeout=0.1)
            serve.cancel()
            await asyncio.gather(serve, return_exceptions=True)
            if coord2 is not None:
                await coord2.close()

    run(scenario(), timeout=60.0)
