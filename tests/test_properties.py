"""Property-based tests (hypothesis) over the framework's pure seams.

SURVEY.md §4's load-bearing test idea is "own the transport seam, inject
faults at it"; the asyncio suite (tests/test_lsp.py, tests/test_fuzz.py)
does that with real sockets and timers. This module pushes the same
invariants through *deterministic, timer-free* state-machine drives so
hypothesis can shrink any violation to a minimal schedule:

- the frame codec round-trips arbitrary frames and rejects every
  single-byte corruption (CRC-32 catches all ≤32-bit bursts);
- two :class:`~tpuminter.lsp.connection.ConnState` machines wired
  through an in-memory channel deliver every written message exactly
  once, in order, under arbitrary drop/duplicate/reorder schedules and
  arbitrary message sizes (fragmentation boundaries included);
- ``chain.rolled_segments`` tiles any global-index range exactly;
- the app-protocol codec round-trips every message type, rolled
  Requests included;
- the coordinator journal's record stream obeys the same corruption
  contract as the bundled frame codec (corruption/truncation can only
  look like loss of a suffix) and its replay is idempotent.
  (tests/test_recovery.py carries deterministic seeded versions of the
  same properties, since this image lacks hypothesis.)
"""

import random
from collections import deque

import pytest

# optional test extra (pyproject [test]); a loud skip beats a collection
# error when the image lacks it
pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st

from tpuminter import chain
from tpuminter.lsp.connection import FRAGMENT_SIZE, ConnState
from tpuminter.lsp.message import (
    MAX_PAYLOAD,
    Frame,
    MsgType,
    decode,
    decode_all,
    encode,
)
from tpuminter.lsp.params import Params
from tpuminter.protocol import (
    Assign,
    Cancel,
    Join,
    PowMode,
    Refuse,
    Request,
    Result,
    Setup,
    decode_msg,
    encode_msg,
)

# ---------------------------------------------------------------------------
# frame codec
# ---------------------------------------------------------------------------

frames = st.builds(
    Frame,
    type=st.sampled_from(list(MsgType)),
    conn_id=st.integers(0, 2**32 - 1),
    seq=st.integers(0, 2**32 - 1),
    payload=st.binary(max_size=MAX_PAYLOAD),
)


@given(frames)
def test_codec_roundtrip(frame):
    assert decode(encode(frame)) == frame


@given(frames, st.data())
def test_codec_rejects_any_single_byte_corruption(frame, data):
    wire = bytearray(encode(frame))
    i = data.draw(st.integers(0, len(wire) - 1))
    flip = data.draw(st.integers(1, 255))
    wire[i] ^= flip
    assert decode(bytes(wire)) is None


@given(frames, st.integers(0, MAX_PAYLOAD + 14))
def test_codec_rejects_any_truncation(frame, keep):
    wire = encode(frame)
    if keep < len(wire):
        assert decode(wire[:keep]) is None


# ---------------------------------------------------------------------------
# bundled datagrams (decode_all): several frames per datagram
# ---------------------------------------------------------------------------

@settings(max_examples=80)
@given(st.lists(frames, min_size=1, max_size=5))
def test_bundle_roundtrip(frs):
    wire = b"".join(bytes(encode(f)) for f in frs)
    assert list(decode_all(wire)) == frs


@settings(max_examples=80)
@given(st.lists(frames, min_size=1, max_size=4), st.data())
def test_bundle_corruption_yields_only_a_clean_prefix(frs, data):
    """A 1-byte flip anywhere in a bundled datagram may unframe
    everything after it, but what DOES decode must be an exact prefix
    of the original frames — corruption can only look like loss, never
    like different frames (CRC-32 per frame)."""
    wire = bytearray(b"".join(bytes(encode(f)) for f in frs))
    i = data.draw(st.integers(0, len(wire) - 1))
    wire[i] ^= data.draw(st.integers(1, 255))
    got = list(decode_all(bytes(wire)))
    assert len(got) < len(frs) or got != frs  # the flip cost something
    assert got == frs[: len(got)]


@settings(max_examples=80)
@given(st.lists(frames, min_size=1, max_size=4), st.data())
def test_bundle_truncation_yields_only_a_clean_prefix(frs, data):
    wire = b"".join(bytes(encode(f)) for f in frs)
    keep = data.draw(st.integers(0, len(wire) - 1))
    got = list(decode_all(wire[:keep]))
    assert len(got) < len(frs)
    assert got == frs[: len(got)]


# ---------------------------------------------------------------------------
# ConnState pair under hostile frame schedules (timer-free model drive)
# ---------------------------------------------------------------------------

#: Message sizes that cross every fragmentation boundary.
_SIZES = st.one_of(
    st.integers(0, 64),
    st.sampled_from(
        [FRAGMENT_SIZE - 1, FRAGMENT_SIZE, FRAGMENT_SIZE + 1,
         2 * FRAGMENT_SIZE, 2 * FRAGMENT_SIZE + 1, 3500]
    ),
)


def _payload(size: int, seed: int) -> bytes:
    return random.Random(seed).randbytes(size)


@settings(max_examples=60, deadline=None)
@given(
    msgs_a=st.lists(st.tuples(_SIZES, st.integers(0, 2**16)), max_size=8),
    msgs_b=st.lists(st.tuples(_SIZES, st.integers(0, 2**16)), max_size=8),
    window=st.integers(1, 8),
    max_backoff=st.integers(0, 3),
    drop=st.floats(0.0, 0.5),
    dup=st.floats(0.0, 0.3),
    reorder=st.floats(0.0, 0.3),
    seed=st.integers(0, 2**32),
)
def test_connstate_exactly_once_in_order_under_faults(
    msgs_a, msgs_b, window, max_backoff, drop, dup, reorder, seed
):
    """Exactly-once in-order delivery under hostile schedules — now
    including the COALESCED-ACK machine: acks only leave via
    ``flush_acks`` (driven at arbitrary model-chosen points + the
    on_epoch backstop), one cumulative frame may cover many DATA
    frames, and SACK payload words cover the out-of-order tail. The
    final conservation check pins the coalescing accounting: every
    received DATA frame is acknowledged by exactly one flushed ack
    datagram or rides a coalesced one."""
    rng = random.Random(seed)
    params = Params(
        epoch_limit=10**9,  # liveness is not under test; loss must not fire
        epoch_millis=1,
        window_size=window,
        max_backoff_interval=max_backoff,
        max_unacked_messages=window,
    )
    channel = deque()  # (dest_name, Frame) in flight
    recv = {"a": [], "b": []}
    data_frames_rx = {"a": 0, "b": 0}  # DATA frames handed to on_frame

    def make(name, peer_name):
        return ConnState(
            conn_id=7,
            params=params,
            send_frame=lambda f, d=peer_name: channel.append((d, f)),
            deliver=recv[name].append,
            on_lost=lambda reason: (_ for _ in ()).throw(
                AssertionError(f"conn lost during model drive: {reason}")
            ),
        )

    conns = {}
    conns["a"] = make("a", "b")
    conns["b"] = make("b", "a")

    def feed(dest, frame):
        if frame.type == MsgType.DATA:
            data_frames_rx[dest] += 1
        conns[dest].on_frame(frame)

    sent_a = [_payload(s, sd) for s, sd in msgs_a]
    sent_b = [_payload(s, sd) for s, sd in msgs_b]
    # per-side write order is the delivery contract; the rng interleaves
    # WHICH side writes next, never the order within a side
    todo = {"a": deque(sent_a), "b": deque(sent_b)}

    def pump_one_faulty():
        dest, frame = channel.popleft()
        r = rng.random()
        if r < drop:
            return
        if r < drop + dup:
            feed(dest, frame)
            feed(dest, frame)
            return
        if r < drop + dup + reorder and channel:
            channel.append((dest, frame))  # overtaken by everything queued
            return
        feed(dest, frame)

    # Phase 1 — hostile: interleave writes, faulty delivery, ack
    # flushes at arbitrary points, epochs.
    steps = 0
    while todo["a"] or todo["b"] or channel:
        steps += 1
        assert steps < 100_000
        act = rng.random()
        sides = [s for s in "ab" if todo[s]]
        if sides and act < 0.3:
            side = rng.choice(sides)
            conns[side].write(todo[side].popleft())
        elif channel and act < 0.75:
            pump_one_faulty()
        elif act < 0.85:
            conns[rng.choice("ab")].flush_acks()
        else:
            conns[rng.choice("ab")].on_epoch()

    # Phase 2 — drain faithfully: every queued frame delivered, epochs
    # tick so retransmit backoff elapses and pending acks flush.
    # Quiesce = nothing in flight.
    for _ in range(10_000):
        while channel:
            dest, frame = channel.popleft()
            feed(dest, frame)
        if not conns["a"].in_flight and not conns["b"].in_flight:
            if not conns["a"]._pending and not conns["b"]._pending:
                if not channel:
                    break
        conns["a"].on_epoch()
        conns["b"].on_epoch()
    else:
        raise AssertionError("model drive failed to quiesce")

    assert recv["b"] == sent_a
    assert recv["a"] == sent_b
    assert not conns["a"].lost and not conns["b"].lost
    for side in "ab":
        conn = conns[side]
        # coalescing conservation: after a final flush every DATA frame
        # this side ever received (duplicates included) was covered by
        # exactly one flushed ack emission or coalesced into one
        conn.flush_acks()
        assert not conn.acks_pending
        assert conn.acks_sent + conn.acks_coalesced == data_frames_rx[side]


# ---------------------------------------------------------------------------
# rolled-segment arithmetic
# ---------------------------------------------------------------------------

@given(
    nonce_bits=st.integers(1, 32),
    en_lo=st.integers(0, 1000),
    en_span=st.integers(0, 6),
    data=st.data(),
)
def test_rolled_segments_tile_the_range_exactly(nonce_bits, en_lo, en_span, data):
    mask = (1 << nonce_bits) - 1
    lo_off = data.draw(st.integers(0, mask))
    hi_off = data.draw(st.integers(0, mask))
    lower = (en_lo << nonce_bits) | lo_off
    upper = ((en_lo + en_span) << nonce_bits) | hi_off
    if upper < lower:
        upper = lower
    segs = list(chain.rolled_segments(lower, upper, nonce_bits))
    # segments are contiguous, cover [lower, upper] exactly, and each
    # (en, base, n_lo, n_hi) is internally consistent
    expect = lower
    for en, base, n_lo, n_hi in segs:
        assert base == en << nonce_bits
        assert 0 <= n_lo <= n_hi <= mask
        assert base | n_lo == expect
        expect = (base | n_hi) + 1
    assert expect == upper + 1


@given(
    nonce_bits=st.integers(1, 32),
    e0=st.integers(0, 10**6),
    count=st.integers(1, 4096),
)
def test_roll_span_is_exactly_count_whole_segments(nonce_bits, e0, count):
    """The RollAssign expansion (ISSUE 14): ``roll_span(e0, count)`` is
    exactly ``count`` WHOLE extranonce segments — aligned at both ends
    and tiled by ``rolled_segments`` with full nonce sweeps. The
    coordinator's carve and the worker's expansion share this one
    function; any disagreement double-counts the range ledger.
    (tests/test_roll_budget.py carries a deterministic seeded mirror,
    since this image lacks hypothesis.)"""
    lower, upper = chain.roll_span(e0, count, nonce_bits)
    mask = (1 << nonce_bits) - 1
    assert lower == e0 << nonce_bits
    assert lower & mask == 0 and (upper + 1) & mask == 0
    assert upper - lower + 1 == count << nonce_bits
    segs = list(chain.rolled_segments(lower, upper, nonce_bits))
    assert [en for en, _, _, _ in segs] == list(range(e0, e0 + count))
    assert all(n_lo == 0 and n_hi == mask for _, _, n_lo, n_hi in segs)


# ---------------------------------------------------------------------------
# app-protocol codec
# ---------------------------------------------------------------------------

_GENESIS80 = chain.GENESIS_HEADER.pack()

#: Durable client identities (protocol.Request.client_key): empty =
#: anonymous, else an opaque token that must round-trip the codec.
_client_keys = st.one_of(
    st.just(""), st.text(min_size=1, max_size=24)
)

plain_requests = st.builds(
    Request,
    job_id=st.integers(0, 2**31),
    mode=st.just(PowMode.TARGET),
    lower=st.integers(0, 1000),
    upper=st.integers(1000, 2**32 - 1),
    header=st.just(_GENESIS80),
    target=st.integers(1, 2**256 - 1),
    chunk_id=st.integers(0, 2**31),
    client_key=_client_keys,
)

min_requests = st.builds(
    Request,
    job_id=st.integers(0, 2**31),
    mode=st.just(PowMode.MIN),
    lower=st.integers(0, 1000),
    upper=st.integers(1000, 2**64 - 1),
    data=st.binary(max_size=64),
    client_key=_client_keys,
)

rolled_requests = st.builds(
    Request,
    job_id=st.integers(0, 2**31),
    mode=st.just(PowMode.TARGET),
    lower=st.just(0),
    upper=st.integers(0, 2**32 - 1),
    header=st.just(_GENESIS80),
    target=st.integers(1, 2**256 - 1),
    coinbase_prefix=st.binary(min_size=1, max_size=300),
    coinbase_suffix=st.binary(max_size=300),
    extranonce_size=st.integers(1, 4),
    branch=st.lists(st.binary(min_size=32, max_size=32), max_size=13).map(tuple),
)

messages = st.one_of(
    st.builds(
        Join,
        backend=st.text(max_size=16),
        lanes=st.integers(1, 2**20),
        span=st.integers(0, 2**32),
    ),
    plain_requests,
    min_requests,
    rolled_requests,
    st.builds(
        Result,
        job_id=st.integers(0, 2**31),
        mode=st.sampled_from([PowMode.MIN, PowMode.TARGET, PowMode.SCRYPT]),
        nonce=st.integers(0, 2**64 - 1),
        hash_value=st.integers(0, 2**256 - 1),
        found=st.booleans(),
        searched=st.integers(0, 2**64 - 1),
        chunk_id=st.integers(0, 2**31),
    ),
    plain_requests.map(Setup),
    rolled_requests.map(Setup),
    st.builds(
        Assign,
        job_id=st.integers(0, 2**31),
        chunk_id=st.integers(0, 2**31),
        lower=st.integers(0, 2**32 - 1),
        upper=st.integers(0, 2**64 - 1),
    ),
    st.builds(
        Refuse, job_id=st.integers(0, 2**31), chunk_id=st.integers(0, 2**31),
        retry_after_ms=st.integers(0, 2**32 - 1),
    ),
    st.builds(Cancel, job_id=st.integers(0, 2**31)),
)


@settings(max_examples=200)
@given(messages)
def test_protocol_roundtrip(msg):
    assert decode_msg(encode_msg(msg)) == msg


# ---------------------------------------------------------------------------
# binary fast-path codec (ISSUE 4): hot messages round-trip through the
# struct-packed encoding, binary and JSON peers agree on meaning, and
# corruption/truncation of a binary payload is a ProtocolError — never a
# mis-parse (tests/test_codec.py carries the deterministic golden-vector
# and exhaustive-corruption versions, since this image lacks hypothesis)
# ---------------------------------------------------------------------------

from tpuminter.protocol import ProtocolError, payload_is_binary  # noqa: E402

hot_messages = st.one_of(
    st.builds(
        Join,
        backend=st.sampled_from(
            ["cpu", "jax", "tpu", "pod", "native", "instant", ""]
        ),
        lanes=st.integers(1, 2**32 - 1),
        span=st.integers(0, 2**64 - 1),
        codec=st.sampled_from(["json", "bin"]),
    ),
    st.builds(
        Result,
        job_id=st.integers(0, 2**64 - 1),
        mode=st.sampled_from([PowMode.MIN, PowMode.TARGET, PowMode.SCRYPT]),
        nonce=st.integers(0, 2**64 - 1),
        hash_value=st.integers(0, 2**256 - 1),
        found=st.booleans(),
        searched=st.integers(0, 2**64 - 1),
        chunk_id=st.integers(0, 2**64 - 1),
    ),
    st.builds(
        Assign,
        job_id=st.integers(0, 2**64 - 1),
        chunk_id=st.integers(0, 2**64 - 1),
        lower=st.integers(0, 2**32 - 1),
        upper=st.integers(0, 2**64 - 1),
    ),
    st.builds(
        Refuse,
        job_id=st.integers(0, 2**64 - 1),
        chunk_id=st.integers(0, 2**64 - 1),
        retry_after_ms=st.integers(0, 2**32 - 1),
    ),
    st.builds(Cancel, job_id=st.integers(0, 2**64 - 1)),
)


@settings(max_examples=200)
@given(hot_messages)
def test_binary_codec_roundtrip_and_cross_codec_agreement(msg):
    wire = encode_msg(msg, binary=True)
    assert payload_is_binary(wire)
    assert decode_msg(wire) == msg
    assert decode_msg(memoryview(wire)) == msg  # the zero-copy path
    # a JSON peer describing the same message decodes identically
    assert decode_msg(encode_msg(msg)) == msg


@settings(max_examples=200)
@given(hot_messages, st.data())
def test_binary_codec_corruption_raises_never_misparses(msg, data):
    wire = bytearray(encode_msg(msg, binary=True))
    i = data.draw(st.integers(0, len(wire) - 1))
    wire[i] ^= data.draw(st.integers(1, 255))
    with pytest.raises(ProtocolError):
        decode_msg(bytes(wire))


@settings(max_examples=200)
@given(hot_messages, st.data())
def test_binary_codec_truncation_raises_never_misparses(msg, data):
    wire = encode_msg(msg, binary=True)
    keep = data.draw(st.integers(0, len(wire) - 1))
    with pytest.raises(ProtocolError):
        decode_msg(wire[:keep])


# ---------------------------------------------------------------------------
# journal record stream (tpuminter.journal): the bundled-codec
# corruption contract applied to disk, plus replay idempotency
# ---------------------------------------------------------------------------

from tpuminter.journal import encode_record, replay, scan  # noqa: E402
from tpuminter.protocol import request_to_obj  # noqa: E402

_journal_records = st.lists(
    st.one_of(
        st.builds(lambda e: {"k": "boot", "epoch": e}, st.integers(1, 50)),
        st.builds(
            lambda i, req: {"k": "job", "id": i, "req": request_to_obj(req)},
            st.integers(1, 6), min_requests,
        ),
        st.builds(
            lambda i, lo, size, h, s: {
                "k": "settle", "id": i, "lo": lo, "hi": lo + size,
                "h": f"{h:x}", "n": lo, "s": s,
            },
            st.integers(1, 6), st.integers(0, 900), st.integers(0, 200),
            st.integers(0, 2**64 - 1), st.integers(1, 500),
        ),
        st.builds(
            lambda i: {
                "k": "finish", "id": i, "ckey": "c", "cjid": i,
                "mode": "min", "n": 1, "h": "aa", "found": True, "s": 9,
            },
            st.integers(1, 6),
        ),
        st.builds(lambda i: {"k": "abandon", "id": i}, st.integers(1, 6)),
    ),
    min_size=1,
    max_size=12,
)


@settings(max_examples=80)
@given(_journal_records, st.data())
def test_journal_corruption_yields_only_a_clean_prefix(records, data):
    """Mirror of the bundled-codec property: a 1-byte flip anywhere in
    the journal may unframe everything after it, but what DOES decode
    is an exact prefix of the original records — corruption can only
    look like loss of a suffix, never like different records."""
    blob = bytearray(b"".join(encode_record(r) for r in records))
    i = data.draw(st.integers(0, len(blob) - 1))
    blob[i] ^= data.draw(st.integers(1, 255))
    got, _ = scan(bytes(blob))
    assert len(got) < len(records)
    assert got == records[: len(got)]


@settings(max_examples=80)
@given(_journal_records, st.data())
def test_journal_truncation_yields_only_a_clean_prefix(records, data):
    blob = b"".join(encode_record(r) for r in records)
    keep = data.draw(st.integers(0, len(blob) - 1))
    got, clean = scan(blob[:keep])
    assert len(got) < len(records)
    assert got == records[: len(got)]
    assert clean <= keep


def _state_key(state):
    return (
        state.boot_epoch, state.next_job_id,
        {j: (tuple(job.remaining), job.best, job.hashes_done)
         for j, job in state.jobs.items()},
        dict(state.winners),
    )


@settings(max_examples=60, deadline=None)
@given(_journal_records)
def test_journal_double_replay_idempotent(records):
    assert _state_key(replay(records)) == _state_key(
        replay(records + records)
    )


@settings(max_examples=60, deadline=None)
@given(
    nonce_bits=st.integers(2, 10),
    segs=st.integers(1, 8),
    data=st.data(),
)
def test_beacon_partial_settles_subtract_exactly(nonce_bits, segs, data):
    """Beacon recovery (ISSUE 14): sub-chunk progress beacons journal as
    ordinary settle records over a PREFIX of an in-flight chunk — zero
    journal-format change — so replaying any mix of beacon prefixes and
    whole-chunk settles must leave exactly the set-model's un-settled
    indices remaining, with ``hashes_done`` matching the covered count.
    (tests/test_roll_budget.py carries a deterministic seeded mirror,
    since this image lacks hypothesis.)"""
    from tpuminter.journal import merge_ranges
    from tpuminter.protocol import PowMode as _PM, Request as _Req

    total = segs << nonce_bits
    req = _Req(
        job_id=1, mode=_PM.TARGET, lower=0, upper=total - 1,
        header=_GENESIS80, target=1, coinbase_prefix=b"p",
        coinbase_suffix=b"s", extranonce_size=4, nonce_bits=nonce_bits,
    )
    records = [{"k": "job", "id": 1, "req": request_to_obj(req)}]
    covered = set()
    cuts = sorted(data.draw(st.sets(st.integers(1, total - 1), max_size=4)))
    for lo, hi in zip([0] + cuts, [c - 1 for c in cuts] + [total - 1]):
        for _ in range(data.draw(st.integers(0, 2))):
            if lo > hi - 1:
                break
            hw = data.draw(st.integers(lo, hi - 1))
            records.append({
                "k": "settle", "id": 1, "lo": lo, "hi": hw,
                "n": lo, "s": hw - lo + 1, "h": "ff",
            })
            covered.update(range(lo, hw + 1))
            lo = hw + 1  # the live chunk advances past the beacon
        if data.draw(st.booleans()) and lo <= hi:
            records.append({
                "k": "settle", "id": 1, "lo": lo, "hi": hi,
                "n": lo, "s": hi - lo + 1, "h": "ff",
            })
            covered.update(range(lo, hi + 1))
    state = replay(records)
    want, g = [], 0
    while g < total:
        if g in covered:
            g += 1
            continue
        start = g
        while g < total and g not in covered:
            g += 1
        want.append((start, g - 1))
    assert merge_ranges(state.jobs[1].remaining) == want
    assert state.jobs[1].hashes_done == len(covered)


# ---------------------------------------------------------------------------
# WAL shipping stream (tpuminter.replication): the journal corruption
# contract over the wire, plus standby ingestion invariants
# (deterministic mirrors live in tests/test_replication.py — this image
# lacks hypothesis)
# ---------------------------------------------------------------------------

from tpuminter.journal import RecoveredState, scan_with_cursor  # noqa: E402
from tpuminter.protocol import WalBatch  # noqa: E402


@settings(max_examples=80)
@given(_journal_records, st.data())
def test_shipped_batch_corruption_applies_only_an_exact_prefix(
    records, data
):
    """The standby scans every shipped batch before touching its
    shadow: a 1-byte flip anywhere in the batch must yield an exact
    record prefix (corruption on the link can only look like loss of a
    suffix — the resumed stream re-ships the rest)."""
    blob = bytearray(b"".join(encode_record(r) for r in records))
    i = data.draw(st.integers(0, len(blob) - 1))
    blob[i] ^= data.draw(st.integers(1, 255))
    got, clean, _last = scan_with_cursor(bytes(blob))
    assert got == records[: len(got)]
    assert clean <= len(blob)


@settings(max_examples=60, deadline=None)
@given(_journal_records, st.data())
def test_incremental_shadow_apply_equals_full_replay(records, data):
    """Standby ingestion applies records batch-by-batch as they ship;
    wherever the batch boundaries fall, the shadow must equal replaying
    the stream at once — so a cursor-resumed standby that replays no
    record twice converges on the same state (and min-folds keep the
    double-apply case idempotent regardless)."""
    shadow = RecoveredState()
    i = 0
    while i < len(records):
        step = data.draw(st.integers(1, 4))
        for rec in records[i : i + step]:
            shadow.apply(rec)
        i += step
    assert _state_key(shadow) == _state_key(replay(records))


@settings(max_examples=60)
@given(
    st.integers(0, 2**64 - 1), st.binary(max_size=600), st.data()
)
def test_walbatch_envelope_corruption_raises_never_misparses(
    offset, payload, data
):
    """The shipping envelope itself (binary tag 0xB8) is under the same
    corruption contract as every other binary message: any single-byte
    flip raises ProtocolError, never a different batch."""
    wire = bytearray(encode_msg(WalBatch(offset, payload), binary=True))
    i = data.draw(st.integers(0, len(wire) - 1))
    wire[i] ^= data.draw(st.integers(1, 255))
    with pytest.raises(ProtocolError):
        decode_msg(bytes(wire))


# ---------------------------------------------------------------------------
# codec-conformance checker (ISSUE 9): the static analyzer's table core
# must flag random kind tables iff they violate the PR 4 invariants
# ---------------------------------------------------------------------------

from tpuminter.analysis.codec_conformance import (  # noqa: E402
    JSON_SNIFF_BYTE,
    check_table,
    struct_size,
)

_fmt_field = st.sampled_from(list("BHIQ"))


@st.composite
def _kind_tables(draw):
    n = draw(st.integers(1, 8))
    kinds = []
    for i in range(n):
        body = "".join(draw(st.lists(_fmt_field, min_size=1, max_size=5)))
        kinds.append({
            "name": f"_K{i}",
            "module": draw(st.sampled_from(["a.py", "b.py"])),
            "line": i + 1,  # unique: the length-collision tiebreak
            "tag": draw(st.one_of(st.none(), st.integers(0, 255))),
            "fmt": "<" + body,
            "variable": draw(st.booleans()),
            "has_crc": draw(st.booleans()),
        })
    return kinds


def _expected_violations(kinds):
    """Independent oracle for check_table: the set of
    ``(violation, kind_name)`` pairs the invariants demand."""
    expected = set()
    by_tag = {}
    for k in kinds:
        if k["tag"] is not None:
            by_tag.setdefault(k["tag"], []).append(k)
    for tag, group in by_tag.items():
        for k in group[1:]:
            expected.add(("duplicate-tag", k["name"]))
        if tag == JSON_SNIFF_BYTE:
            for k in group:
                expected.add(("json-collision", k["name"]))
    by_mod = {}
    for k in kinds:
        if k["fmt"] and not k["variable"]:
            by_mod.setdefault(k["module"], []).append(k)
    for group in by_mod.values():
        by_size = {}
        for k in group:
            size = struct_size(k["fmt"])
            if size is not None:
                by_size.setdefault(size, []).append(k)
        for clash in by_size.values():
            for k in sorted(clash, key=lambda k: k["line"])[1:]:
                expected.add(("length-collision", k["name"]))
    for k in kinds:
        body = k["fmt"][1:]
        if k["tag"] is not None and not body.startswith("B"):
            expected.add(("tag-not-first", k["name"]))
        if not k["has_crc"]:
            expected.add(("missing-crc", k["name"]))
    return expected


@settings(max_examples=200)
@given(_kind_tables())
def test_codec_checker_flags_iff_invariant_violated(kinds):
    """Soundness AND completeness of the table core: a random kind
    table is flagged exactly where the distinct-length / CRC / tag
    invariants are broken — no false alarms, no misses."""
    got = {(v["violation"], v["kind"]) for v in check_table(kinds)}
    assert got == _expected_violations(kinds)


@settings(max_examples=60)
@given(_kind_tables())
def test_codec_checker_clean_table_stays_clean(kinds):
    """Repairing every violation yields a table the checker accepts:
    distinct tags, distinct lengths, CRC everywhere, tag byte first."""
    for i, k in enumerate(kinds):
        k["tag"] = 0xA0 + i              # distinct, never 0x7B
        k["fmt"] = "<B" + "B" * i        # distinct sizes, tag first
        k["variable"] = False
        k["has_crc"] = True
    assert check_table(kinds) == []


# ---------------------------------------------------------------------------
# jittered_backoff (lsp.params): the redial-delay contract every
# reconnect loop leans on under a long partition (deterministic mirrors
# live in tests/test_chaos.py — this image lacks hypothesis)
# ---------------------------------------------------------------------------

from tpuminter.lsp.params import jittered_backoff  # noqa: E402


@settings(max_examples=120)
@given(
    base=st.floats(0.001, 2.0),
    factor=st.floats(1.0, 64.0),
    seed=st.integers(0, 2**32),
    n=st.integers(1, 64),
)
def test_backoff_every_draw_within_jittered_envelope(base, factor, seed, n):
    """Each draw is the doubling envelope value ``min(base·2^k, cap)``
    under a uniform [0.5, 1.5) jitter — so no wait ever exceeds
    ``cap · 1.5``, the ceiling bounding every redial loop's patience,
    and no wait collapses below half the envelope (lockstep-free but
    never a hot spin)."""
    cap = base * factor
    gen = jittered_backoff(base, cap, random.Random(seed))
    envelope = base
    for _ in range(n):
        got = next(gen)
        assert envelope * 0.5 <= got <= envelope * 1.5
        assert got <= cap * 1.5
        # the unjittered envelope is monotone and capped — the next
        # draw's bounds can only move up, never past the cap
        envelope = min(envelope * 2, cap)
        assert envelope <= cap


@settings(max_examples=80)
@given(
    base=st.floats(0.001, 2.0),
    factor=st.floats(1.0, 64.0),
    seed=st.integers(0, 2**32),
)
def test_backoff_saturates_at_cap_and_is_seed_deterministic(
    base, factor, seed
):
    """After ``ceil(log2(cap/base))`` doublings every draw comes from
    the capped regime ``[cap/2, cap·1.5]`` — a partition that outlives
    the ramp gets a steady bounded redial cadence, not unbounded growth
    — and the whole sequence replays from the rng seed."""
    import math

    cap = base * factor
    ramp = max(0, math.ceil(math.log2(max(factor, 1.0)))) + 1
    gen = jittered_backoff(base, cap, random.Random(seed))
    for _ in range(ramp):
        next(gen)
    tail = [next(gen) for _ in range(20)]
    assert all(cap * 0.5 <= d <= cap * 1.5 for d in tail)
    gen_a = jittered_backoff(base, cap, random.Random(seed))
    gen_b = jittered_backoff(base, cap, random.Random(seed))
    assert [next(gen_a) for _ in range(30)] == [
        next(gen_b) for _ in range(30)
    ]


# ---------------------------------------------------------------------------
# winner/dedup-table bound (ISSUE 13): the eviction policy may shrink
# the table, never break exactly-once (deterministic seeded mirror
# lives in tests/test_control_plane.py — this image lacks hypothesis)
# ---------------------------------------------------------------------------

import time as _time  # noqa: E402
from collections import OrderedDict  # noqa: E402

from tpuminter.coordinator import Coordinator, _Winner  # noqa: E402

from tests.test_control_plane import _trim_oracle  # noqa: E402

_dummy_result = Result(
    1, PowMode.MIN, nonce=1, hash_value=1, found=True, searched=1,
    chunk_id=0,
)

_winner_entries = st.lists(
    st.tuples(
        st.booleans(),                 # durable (finish record fsynced)
        st.booleans(),                 # has parked re-submitters
        st.booleans(),                 # older than any ttl
    ),
    max_size=24,
)


@settings(max_examples=200)
@given(
    _winner_entries,
    st.integers(0, 16),                # winners_cap
    st.sampled_from([0.0, 100.0]),     # winners_ttl (0 = size-only)
)
def test_winner_trim_never_evicts_unacked(entries, cap, ttl):
    """Whatever the size/age pressure, ``_trim_winners`` removes
    exactly the oracle's evictable set and never an un-acknowledged
    entry (not durable yet, or with waiters parked on the durability
    callback) — the bound may be exceeded, exactly-once may not."""
    now = _time.time()
    table = OrderedDict()
    for i, (durable, waiter, stale) in enumerate(entries):
        table[("ck%d" % i, i)] = _Winner(
            _dummy_result, durable=durable,
            waiters=[7] if waiter else [],
            ts=now - (1000.0 if stale else 0.0),
        )
    unacked = {k for k, w in table.items() if not w.durable or w.waiters}
    expected = _trim_oracle(table, cap, ttl, now)

    coord = Coordinator.__new__(Coordinator)
    coord._winners = OrderedDict(table)
    coord._winners_cap = cap
    coord._winners_ttl = ttl
    coord.stats = {"winners_evicted": 0}
    coord._trim_winners()

    survivors = set(coord._winners)
    assert unacked <= survivors
    assert set(table) - survivors == expected
    assert coord.stats["winners_evicted"] == len(expected)


# ---------------------------------------------------------------------------
# fold disciplines (ISSUE 15): the coverage-gated fold state must make
# every discipline — the non-idempotent sum included — exactly-once
# under arbitrary chunk partitions, delivery orders, duplicate
# deliveries, and beacon-style prefix splits (deterministic seeded
# mirrors live in tests/test_workloads.py — this image lacks
# hypothesis)
# ---------------------------------------------------------------------------

from tpuminter.workloads import (  # noqa: E402
    FMin,
    FSum,
    FirstMatch,
    TopK,
    absorb,
    new_state,
)
from tpuminter.workloads import hashcore as _hc  # noqa: E402

_FOLD_MAKERS = (
    lambda: FMin(),
    lambda: TopK(3),
    lambda: FirstMatch(1 << 60),
    lambda: FSum(),
)


def _fold_vals(seed, lo, hi):
    return [_hc.objective(seed, i) for i in range(lo, hi + 1)]


@st.composite
def _chunk_schedules(draw):
    """A partition of [0, hi] into chunks, a shuffled delivery order,
    and a set of duplicate deliveries injected at arbitrary points."""
    hi = draw(st.integers(5, 200))
    n_cuts = draw(st.integers(0, 8))
    cuts = sorted(draw(st.sets(st.integers(1, hi), max_size=n_cuts)))
    spans, at = [], 0
    for c in list(cuts) + [hi + 1]:
        spans.append((at, c - 1))
        at = c
    order = draw(st.permutations(list(range(len(spans)))))
    dups = draw(st.lists(
        st.integers(0, len(spans) - 1), max_size=3,
    ))
    return spans, list(order) + dups


@settings(max_examples=80, deadline=None)
@given(
    fold_i=st.integers(0, len(_FOLD_MAKERS) - 1),
    seed=st.integers(0, 2**32 - 1),
    sched=_chunk_schedules(),
)
def test_fold_state_is_schedule_independent(fold_i, seed, sched):
    """Any delivery order with any duplicates lands on the in-order,
    exactly-once state: absorb's coverage gate + the folds' assoc/comm
    combine are jointly what lets replay, out-of-order settles, and WAL
    merges share one mechanism."""
    fold = _FOLD_MAKERS[fold_i]()
    spans, order = sched
    settles = [
        (a, b, fold.of_batch(a, _fold_vals(seed, a, b))) for a, b in spans
    ]
    baseline = new_state(fold)
    for a, b, acc in settles:
        assert absorb(fold, baseline, a, b, acc)
    state = new_state(fold)
    for i in order:
        a, b, acc = settles[i]
        absorb(fold, state, a, b, acc)   # duplicates must bounce
    assert state == baseline


@settings(max_examples=80, deadline=None)
@given(
    fold_i=st.integers(0, len(_FOLD_MAKERS) - 1),
    seed=st.integers(0, 2**32 - 1),
    hi=st.integers(1, 150),
    data=st.data(),
)
def test_fold_beacon_prefix_split_settles_exactly(fold_i, seed, hi, data):
    """A chunk settled as prefix-beacon + remainder equals the whole
    chunk at once, and replaying the beacon is a no-op — ISSUE 14's
    sub-chunk progress shape is safe on every discipline. First-match
    probes are schedule-relative under early-cancel, so only its
    decided (index, value) must agree."""
    fold = _FOLD_MAKERS[fold_i]()
    cut = data.draw(st.integers(0, hi - 1))
    whole = new_state(fold)
    assert absorb(fold, whole, 0, hi, fold.of_batch(0, _fold_vals(seed, 0, hi)))
    beacon = fold.of_batch(0, _fold_vals(seed, 0, cut))
    rest = fold.of_batch(cut + 1, _fold_vals(seed, cut + 1, hi))
    split = new_state(fold)
    assert absorb(fold, split, 0, cut, beacon)
    assert absorb(fold, split, cut + 1, hi, rest)
    assert not absorb(fold, split, 0, cut, beacon)
    assert split["covered"] == whole["covered"] == [[0, hi]]
    if isinstance(fold, FirstMatch):
        assert split["acc"][:2] == whole["acc"][:2]
    else:
        assert split["acc"] == whole["acc"]


@settings(max_examples=120)
@given(
    v=st.integers(0, 2**64 - 1),
    i=st.integers(0, 2**64 - 1),
    probes=st.integers(1, 2**64 - 1),
    total=st.integers(0, 2**128 - 1),
    count=st.integers(0, 2**64 - 1),
    pairs=st.lists(
        st.tuples(st.integers(0, 2**64 - 1), st.integers(0, 2**64 - 1)),
        max_size=8, unique_by=lambda p: p[1],
    ),
    data=st.data(),
)
def test_fold_payload_roundtrip_and_corruption(
    v, i, probes, total, count, pairs, data
):
    """Every discipline's chunk-partial frame round-trips any in-range
    accumulator, and any single-byte corruption is a loud ValueError —
    the CRC trailer is the ONLY corruption check these bytes get on the
    JSON fallback, so it must hold unconditionally."""
    cases = [
        (FMin(), [v, i]),
        (TopK(8), sorted([list(p) for p in pairs])),
        (FirstMatch(0), [i, v, probes]),
        (FSum(), [total, count]),
    ]
    for fold, acc in cases:
        wire = fold.encode(acc)
        assert fold.decode(wire) == acc
        pos = data.draw(st.integers(0, len(wire) - 1))
        flip = data.draw(st.integers(1, 255))
        bad = bytearray(wire)
        bad[pos] ^= flip
        with pytest.raises(ValueError):
            fold.decode(bytes(bad))


# ---------------------------------------------------------------------------
# device-lane hashcore engine (ISSUE 17): the u32-pair sweep must be
# bit-for-bit the host fold chain on ARBITRARY (seed, range, fold) —
# the hypothesis mirror of tests/test_hashcore_dev.py's seeded pins
# ---------------------------------------------------------------------------

from tpuminter.ops import splitmix as _sm  # noqa: E402


@settings(max_examples=100, deadline=None)
@given(seed=st.integers(0, 2**64 - 1), index=st.integers(0, 2**64 - 1))
def test_u32_pair_objective_matches_scalar(seed, index):
    """The hi/lo-word splitmix64 (carry adds, 16-bit-limb multiplies,
    cross-word shifts) agrees with the Python-int scalar at every
    (seed, index) hypothesis can throw at it — shrinking lands on the
    exact carry/shift boundary if one is off."""
    assert _sm.lane_objective(seed, [index]) == [
        _hc.objective(seed, index)
    ]


_DEV_MAKERS = (
    lambda thr, k: (FMin(), "fmin", 1),
    lambda thr, k: (TopK(k), "topk", k),
    lambda thr, k: (FirstMatch(thr), "fmatch", 1),
    lambda thr, k: (FSum(), "fsum", 1),
)


@settings(max_examples=25, deadline=None)
@given(
    fold_i=st.integers(0, len(_DEV_MAKERS) - 1),
    seed=st.integers(0, 2**64 - 1),
    lo=st.integers(0, 2**63),
    n=st.integers(1, 900),
    thr=st.integers(0, 2**64 - 1),
    k=st.integers(1, 8),
)
def test_device_sweep_equals_host_fold(fold_i, seed, lo, n, thr, k):
    """Window-granular device partials combined across ragged windows
    equal one host ``of_batch`` over the whole range, every discipline
    (first-match early-stops on device; its accumulator is granularity-
    independent by the probes construction). The shared (256, 2) shape
    means one compile per variant per process."""
    fold, variant, kk = _DEV_MAKERS[fold_i](thr, k)
    hi = lo + n - 1
    sweep = _sm.LaneSweep(variant, 256, 2, kk, "jnp")
    dev = fold.initial()
    g = lo
    while g <= hi:
        e = min(g + sweep.window - 1, hi)
        dev = fold.combine(
            dev, sweep.resolve(sweep.dispatch(seed, g, e, thr), g, e)
        )
        if fold.is_final(dev):
            break
        g = e + 1
    assert dev == fold.of_batch(lo, _fold_vals(seed, lo, hi))


@settings(max_examples=100)
@given(
    seed=st.integers(0, 2**32 - 1),
    lo=st.integers(0, 1000),
    span=st.integers(0, 40),
    k=st.integers(1, 8),
)
def test_topk_ties_always_rank_the_lowest_global_index(seed, lo, span, k):
    """However a range is chunked, top-k's answer is the first k pairs
    of the (value, index)-sorted scan — equal values resolve to the
    LOWER global index, one deterministic list per job."""
    hi = lo + span
    fold = TopK(k)
    values = _fold_vals(seed, lo, hi)
    want = sorted([val, lo + off] for off, val in enumerate(values))[:k]
    mid = lo + span // 2
    acc = fold.combine(
        fold.of_batch(lo, values[: mid - lo + 1]),
        fold.of_batch(mid + 1, values[mid - lo + 1:]),
    )
    assert acc == want


# ---------------------------------------------------------------------------
# shared-compression scheduling (ISSUE 16; seeded mirrors in
# tests/test_sched_share.py since this image lacks hypothesis)
# ---------------------------------------------------------------------------

@settings(max_examples=15, deadline=None)
@given(
    seed=st.integers(0, 2**32 - 1),
    b=st.integers(1, 6),
    width=st.sampled_from([32, 64]),
    cand_bits=st.sampled_from([8, 32]),
)
def test_batched_sweep_sched_share_bit_equal(seed, b, width, cand_bits):
    """The shared-schedule sweep (``sched=True``) returns the identical
    ``[found, first_goff]`` pair as the full-digest baseline on any row
    set — random midstates/tails/bases, ragged valid counts included."""
    import numpy as np
    import jax.numpy as jnp

    from tpuminter import rolled

    rng = np.random.RandomState(seed)
    mids = jnp.asarray(rng.randint(0, 1 << 32, (b, 8), dtype=np.uint32))
    tails = jnp.asarray(rng.randint(0, 1 << 32, (b, 3), dtype=np.uint32))
    bases = jnp.asarray(rng.randint(0, 1 << 20, b, dtype=np.uint32))
    valids = jnp.asarray(rng.randint(0, width + 1, b).astype(np.uint32))
    goffs = jnp.asarray((np.arange(b, dtype=np.uint64) * width)
                        .astype(np.uint32))
    cap = jnp.uint32(rng.randint(0, 1 << 32))
    args = (mids, tails, bases, valids, goffs, cap, width, cand_bits)
    assert np.array_equal(
        np.asarray(rolled._jnp_batched_candidate_sweep(*args, False)),
        np.asarray(rolled._jnp_batched_candidate_sweep(*args, True)),
    )


@settings(max_examples=20, deadline=None)
@given(ens=st.lists(st.integers(0, 2**64 - 1), min_size=1, max_size=12))
def test_roll_batch_deduped_any_row_multiset(ens):
    """Dedup-then-gather ≡ rolling every row, for ANY multiset of
    64-bit extranonces (duplicates, all-equal, all-distinct)."""
    import numpy as np
    import jax.numpy as jnp

    from tpuminter.ops import merkle

    rng = np.random.RandomState(16)
    prefix, suffix = rng.bytes(41), rng.bytes(60)
    roll = merkle.make_extranonce_roll_batch(
        chain.GENESIS_HEADER.pack(), prefix, suffix, 8, ()
    )
    en = np.asarray(ens, dtype=np.uint64)
    en_hi = (en >> np.uint64(32)).astype(np.uint32)
    en_lo = (en & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    want_m, want_t = roll(jnp.asarray(en_hi), jnp.asarray(en_lo))
    got_m, got_t = merkle.roll_batch_deduped(roll, en_hi, en_lo)
    assert np.array_equal(np.asarray(want_m), np.asarray(got_m))
    assert np.array_equal(np.asarray(want_t), np.asarray(got_t))


@settings(max_examples=40)
@given(
    mid=st.lists(st.integers(0, 2**32 - 1), min_size=8, max_size=8),
    tail=st.lists(st.integers(0, 2**32 - 1), min_size=3, max_size=3),
    nonce=st.integers(0, 2**32 - 1),
)
def test_prepared_schedule_folds_like_unshared(mid, tail, nonce):
    """prepare_hdr + hash_prepared_e60_e61 ≡ hash_sym_e60_e61 over the
    all-int domain — both fully const-fold, and agree on every bit."""
    from tpuminter.ops import sha256 as ops
    from tpuminter.ops import symbolic as sym

    bswap = lambda x: int.from_bytes(x.to_bytes(4, "little"), "big")
    block = [*tail, bswap(nonce), *ops.HEADER_TAIL_PAD]
    want = sym.hash_sym_e60_e61(mid, [block], (), 0, 0)
    got = sym.hash_prepared_e60_e61(sym.prepare_hdr(mid, *tail), nonce)
    assert isinstance(got[0], int) and isinstance(got[1], int)
    assert got == want
