"""Binary app-codec tests (ISSUE 4): golden vectors, cross-codec
agreement, and the corruption contract — deterministic versions of the
hypothesis properties in tests/test_properties.py, since this image
lacks hypothesis (the wire layout must be pinned by tier-1 either way:
a golden vector is the only thing that catches an accidental layout
change, which round-trip properties are blind to)."""

import random
import struct
import zlib

import pytest

from tpuminter.journal import decode_settle, encode_settle
from tpuminter.protocol import (
    MIN_UNTRACKED,
    Assign,
    Beacon,
    Cancel,
    Join,
    PowMode,
    ProtocolError,
    Refuse,
    Result,
    RollAssign,
    Setup,
    Request,
    WalBatch,
    decode_msg,
    encode_msg,
    payload_is_binary,
)

# ---------------------------------------------------------------------------
# golden vectors: the v1 layout, byte for byte. If any of these fail,
# the wire format changed — that needs NEW tags, not edited vectors
# (protocol module docstring: tags 0xB1-0xB5 ARE version 1).
# ---------------------------------------------------------------------------


def _crc(body: bytes) -> bytes:
    return struct.pack("<I", zlib.crc32(body))


GOLDEN = [
    (
        Assign(job_id=3, chunk_id=7, lower=0, upper=4095),
        struct.pack("<BQQQQ", 0xB1, 3, 7, 0, 4095),
    ),
    (
        Result(
            job_id=3, mode=PowMode.TARGET, nonce=0xDEADBEEF,
            hash_value=0x1234, found=True, searched=4096, chunk_id=7,
        ),
        struct.pack(
            "<BBQQ32sBQQ", 0xB2, 1, 3, 0xDEADBEEF,
            (0x1234).to_bytes(32, "little"), 1, 4096, 7,
        ),
    ),
    (
        Result(
            job_id=1, mode=PowMode.MIN, nonce=2**64 - 1,
            hash_value=MIN_UNTRACKED, found=False,
        ),
        struct.pack(
            "<BBQQ32sBQQ", 0xB2, 0, 1, 2**64 - 1,
            MIN_UNTRACKED.to_bytes(32, "little"), 0, 0, 0,
        ),
    ),
    (
        Refuse(job_id=3, chunk_id=7),
        struct.pack("<BQQ", 0xB3, 3, 7),
    ),
    (
        Cancel(job_id=9),
        struct.pack("<BQ", 0xB4, 9),
    ),
    (
        Join(backend="instant", lanes=4, span=1 << 30, codec="bin"),
        struct.pack("<BBIQ16s", 0xB5, 1, 4, 1 << 30, b"instant"),
    ),
    (
        Join(backend="cpu"),  # codec defaults to "json" → flags 0
        struct.pack("<BBIQ16s", 0xB5, 0, 1, 0, b"cpu"),
    ),
    # WAL-shipping batch (ISSUE 5): the one variable-length kind —
    # tag ‖ offset:u64 ‖ raw journal bytes ‖ crc32. Riding in GOLDEN
    # puts it under the same exhaustive corruption/truncation sweeps.
    (
        WalBatch(offset=13, data=b"\x01\x02raw-journal-bytes"),
        struct.pack("<BQ", 0xB8, 13) + b"\x01\x02raw-journal-bytes",
    ),
    (
        WalBatch(offset=2**64 - 1, data=b""),
        struct.pack("<BQ", 0xB8, 2**64 - 1),
    ),
    # roll dialect (ISSUE 14): tags 0xB9/0xBA and the Join roll flag.
    # Riding in GOLDEN puts both new kinds under the same exhaustive
    # corruption/truncation sweeps as the v1 tags.
    (
        RollAssign(job_id=3, chunk_id=7, extranonce0=5, count=16),
        struct.pack("<BQQQI", 0xB9, 3, 7, 5, 16),
    ),
    (
        Beacon(job_id=3, chunk_id=7, high_water=(5 << 32) | 99,
               nonce=(5 << 32) | 42, hash_value=0xFEED),
        struct.pack("<BQQQQ32s", 0xBA, 3, 7, (5 << 32) | 99,
                    (5 << 32) | 42, (0xFEED).to_bytes(32, "little")),
    ),
    (
        Join(backend="cpu", codec="bin", roll=True),  # flags 0x01 | 0x02
        struct.pack("<BBIQ16s", 0xB5, 3, 1, 0, b"cpu"),
    ),
]


def test_golden_vectors_encode_exactly():
    for msg, body in GOLDEN:
        assert encode_msg(msg, binary=True) == body + _crc(body), msg


def test_golden_vectors_decode_exactly():
    for msg, body in GOLDEN:
        assert decode_msg(body + _crc(body)) == msg
        # and from a memoryview, the LSP layer's zero-copy delivery type
        assert decode_msg(memoryview(body + _crc(body))) == msg


def test_kind_lengths_are_distinct():
    """Every binary kind has a unique total length, so a corrupted tag
    can never alias another kind even before the CRC check (the
    corruption property below leans on this)."""
    lengths = {len(encode_msg(m, binary=True)) for m, _ in GOLDEN[:6]}
    assert len(lengths) == 5  # assign, result, refuse, cancel, join


# ---------------------------------------------------------------------------
# cross-codec agreement: binary and JSON describe the SAME message
# ---------------------------------------------------------------------------


def _hot_messages():
    rng = random.Random(0xC0DEC)
    msgs = []
    for _ in range(200):
        kind = rng.randrange(5)
        if kind == 0:
            msgs.append(Assign(
                rng.randrange(2**64), rng.randrange(2**64),
                0, rng.randrange(2**64),
            ))
        elif kind == 1:
            msgs.append(Result(
                rng.randrange(2**64),
                rng.choice([PowMode.MIN, PowMode.TARGET, PowMode.SCRYPT]),
                rng.randrange(2**64), rng.randrange(2**256),
                rng.random() < 0.5, searched=rng.randrange(2**64),
                chunk_id=rng.randrange(2**64),
            ))
        elif kind == 2:
            msgs.append(Refuse(rng.randrange(2**64), rng.randrange(2**64)))
        elif kind == 3:
            msgs.append(Cancel(rng.randrange(2**64)))
        else:
            msgs.append(Join(
                backend=rng.choice(["cpu", "jax", "tpu", "pod", "native",
                                    "instant", ""]),
                lanes=rng.randrange(2**32), span=rng.randrange(2**64),
                codec=rng.choice(["json", "bin"]),
            ))
    return msgs


def test_binary_roundtrip_and_cross_codec_agreement():
    """Every hot message round-trips binary↔dataclass, and a
    binary-encoding peer and a JSON-encoding peer describe the same
    message to a decoder (the mixed-fleet invariant: codec choice can
    never change meaning)."""
    for msg in _hot_messages():
        b = encode_msg(msg, binary=True)
        assert payload_is_binary(b), msg
        assert decode_msg(b) == msg, msg
        assert decode_msg(encode_msg(msg)) == msg, msg


def test_binary_falls_back_to_json_when_unrepresentable():
    for msg in [
        Join(backend="x" * 20, codec="bin"),        # backend > 16 bytes
        Join(backend="nul\x00", codec="bin"),       # NUL collides with pad
        Cancel(job_id=2**64),                       # out of u64
        Setup(Request(job_id=1, mode=PowMode.MIN, lower=0, upper=9)),
    ]:
        raw = encode_msg(msg, binary=True)
        assert not payload_is_binary(raw)
        assert decode_msg(raw) == msg


# ---------------------------------------------------------------------------
# corruption contract: corruption/truncation of a binary payload raises
# ProtocolError — never a mis-parse, never a different exception
# ---------------------------------------------------------------------------


def test_every_single_byte_corruption_raises_protocol_error():
    """EXHAUSTIVE over every byte × all 255 flips for every golden
    vector (the CRC32 catches every burst ≤ 32 bits, so single-byte
    flips are fully covered; a flip landing in the tag also trips the
    per-kind length check)."""
    for msg, body in GOLDEN:
        wire = bytearray(body + _crc(body))
        for i in range(len(wire)):
            orig = wire[i]
            for flip in range(1, 256):
                wire[i] = orig ^ flip
                with pytest.raises(ProtocolError):
                    decode_msg(bytes(wire))
            wire[i] = orig
        assert decode_msg(bytes(wire)) == msg  # sanity: vector intact


def test_every_truncation_raises_protocol_error():
    for msg, body in GOLDEN:
        wire = body + _crc(body)
        for keep in range(len(wire)):
            if keep == 0:
                with pytest.raises(ProtocolError):
                    decode_msg(b"")
                continue
            with pytest.raises(ProtocolError):
                decode_msg(wire[:keep])


def test_unknown_tags_raise():
    for tag in range(256):
        # 0xB8 (WalBatch) is variable-length and a 17-byte body with a
        # valid CRC IS a well-formed (if empty-ish) batch — covered by
        # its own golden vector + corruption sweep below
        if tag in (0xB1, 0xB2, 0xB3, 0xB4, 0xB5, 0xB8, 0x7B):
            continue
        body = bytes([tag]) + b"\x00" * 16
        with pytest.raises(ProtocolError):
            decode_msg(body + _crc(body))


# ---------------------------------------------------------------------------
# packed journal settle record (tag 0xB7): same discipline on disk
# ---------------------------------------------------------------------------


def test_settle_record_roundtrips_to_replay_shape():
    rng = random.Random(7)
    for _ in range(100):
        job_id = rng.randrange(2**64)
        lo = rng.randrange(2**63)
        hi = lo + rng.randrange(2**10)
        nonce = rng.randrange(lo, hi + 1)
        searched = hi - lo + 1
        h = rng.randrange(2**256)
        payload = encode_settle(job_id, lo, hi, nonce, searched, h)
        rec = decode_settle(payload)
        assert rec == {
            "k": "settle", "id": job_id, "lo": lo, "hi": hi,
            "n": nonce, "s": searched, "h": f"{h:x}",
        }


def test_settle_record_golden_vector():
    payload = encode_settle(1, 0, 1023, 17, 1024, 0xABCD)
    assert payload == struct.pack(
        "<BQQQQQ32s", 0xB7, 1, 0, 1023, 17, 1024,
        (0xABCD).to_bytes(32, "little"),
    )
    # any resize/retag reads as not-a-settle (→ scan treats the record
    # as corruption, ending the readable prefix; never a mis-parse)
    assert decode_settle(payload[:-1]) is None
    assert decode_settle(b"\xb6" + payload[1:]) is None


def test_settle_records_replay_like_json_settles():
    """A journal whose settles are packed replays to the same state as
    one whose settles are JSON — the formats are interchangeable on
    disk (old journals keep replaying after the upgrade)."""
    from tpuminter.journal import encode_record, frame_payload, replay, scan
    from tpuminter.protocol import request_to_obj

    req = Request(job_id=5, mode=PowMode.MIN, lower=0, upper=4095,
                  data=b"x")
    job = {"k": "job", "id": 1, "req": request_to_obj(req)}
    settles = [(0, 1023, 7, 0x10), (1024, 2047, 1030, 0x20)]
    blob_json = encode_record(job) + b"".join(
        encode_record({
            "k": "settle", "id": 1, "lo": lo, "hi": hi, "n": n,
            "s": hi - lo + 1, "h": f"{h:x}",
        })
        for lo, hi, n, h in settles
    )
    blob_bin = encode_record(job) + b"".join(
        frame_payload(encode_settle(1, lo, hi, n, hi - lo + 1, h))
        for lo, hi, n, h in settles
    )
    recs_json, _ = scan(blob_json)
    recs_bin, _ = scan(blob_bin)
    assert recs_json == recs_bin
    s1, s2 = replay(recs_json), replay(recs_bin)
    assert s1.jobs[1].remaining == s2.jobs[1].remaining == [(2048, 4095)]
    assert s1.jobs[1].best == s2.jobs[1].best == (0x10, 7)


# ---------------------------------------------------------------------------
# rolled-job wire shape (ISSUE 7): the baseline a future codec v2 will
# be measured against
# ---------------------------------------------------------------------------

def test_rolled_assign_wire_shape_baseline():
    """Pin the rolled-job dispatch economics: the ragged ~1.5 kB
    template (mainnet-shape coinbase + 12-deep branch) rides the JSON
    long tail ONCE per (worker, job) inside Setup; every per-chunk
    Assign stays the fixed 37-byte binary record with ZERO template
    bytes; and binary Results carry 64-bit GLOBAL nonces, so the binary
    codec still negotiates on rolled jobs. These numbers are the
    recorded baseline for a codec v2 (packed Setup)."""
    from tpuminter import chain

    rng = random.Random(7)
    prefix = bytes(rng.randrange(256) for _ in range(120))
    suffix = bytes(rng.randrange(256) for _ in range(126))
    branch = tuple(
        bytes(rng.randrange(256) for _ in range(32)) for _ in range(12)
    )
    assert len(prefix) + 4 + len(suffix) == 250  # the realistic coinbase
    req = Request(
        job_id=9, mode=PowMode.TARGET, lower=0,
        upper=(3 << 32) | 0xFFFFFFFF, header=chain.GENESIS_HEADER.pack(),
        target=chain.bits_to_target(chain.GENESIS_HEADER.bits),
        coinbase_prefix=prefix, coinbase_suffix=suffix,
        extranonce_size=4, branch=branch,
    )
    # Setup: JSON long tail even when the connection negotiated binary
    setup = encode_msg(Setup(req), binary=True)
    assert setup[:1] == b"{"
    assert 1200 <= len(setup) <= 2200, len(setup)  # ~1.5 kB mainnet shape
    assert decode_msg(setup) == Setup(req)
    # Assign: fixed binary width, no template bytes — sent per chunk
    assign = Assign(9, 3, 5 << 32, (5 << 32) + (1 << 20))
    raw = encode_msg(assign, binary=True)
    assert raw[0] == 0xB1 and len(raw) == 37
    assert decode_msg(raw) == assign
    assert prefix not in raw and suffix not in raw
    # Result: a rolled win's 64-bit global nonce fits the binary record
    res = Result(
        9, PowMode.TARGET, nonce=(3 << 32) | 123,
        hash_value=(1 << 220) - 7, found=True,
        searched=(3 << 32) | 124, chunk_id=3,
    )
    raw_res = encode_msg(res, binary=True)
    assert payload_is_binary(raw_res) and raw_res[0] == 0xB2
    assert decode_msg(raw_res) == res
    # the per-job template cost amortizes: 100 chunks of a rolled job
    # cost one Setup + 100 fixed Assigns, not 100 template re-sends
    assert len(setup) + 100 * len(raw) < 100 * len(setup) // 10


# ---------------------------------------------------------------------------
# roll dialect (ISSUE 14): tags 0xB9/0xBA, the Join roll flag, and the
# guards that keep a bad count off the wire
# ---------------------------------------------------------------------------


def test_roll_dialect_lengths_are_distinct():
    """ALL fixed-width binary kinds — v1 plus the roll dialect — keep
    unique total lengths, so a corrupted tag can never alias another
    kind even before the CRC check."""
    fixed = [
        Assign(1, 2, 3, 4),
        Result(1, PowMode.TARGET, 2, 3),
        Refuse(1, 2),
        Cancel(1),
        Join(codec="bin"),
        RollAssign(1, 2, 3, 4),
        Beacon(1, 2, 3, 4, 5),
    ]
    lengths = [len(encode_msg(m, binary=True)) for m in fixed]
    assert len(set(lengths)) == len(lengths), lengths


def test_roll_dialect_cross_codec_agreement():
    """RollAssign/Beacon mean the same thing from either codec, and a
    rolled Join's advertisement survives both codecs — the mixed-fleet
    invariant extends to the new dialect."""
    rng = random.Random(0xB9BA)
    for _ in range(100):
        for msg in (
            RollAssign(
                rng.randrange(2**64), rng.randrange(2**64),
                rng.randrange(2**64), rng.randrange(1, 2**32),
            ),
            Beacon(
                rng.randrange(2**64), rng.randrange(2**64),
                rng.randrange(2**64), rng.randrange(2**64),
                rng.randrange(2**256),
            ),
            Join(backend="cpu", codec=rng.choice(["json", "bin"]),
                 roll=rng.random() < 0.5),
        ):
            b = encode_msg(msg, binary=True)
            j = encode_msg(msg)
            assert payload_is_binary(b), msg
            assert decode_msg(b) == msg, msg
            assert decode_msg(j) == msg, msg


def test_join_roll_flag_is_invisible_when_off():
    """A non-rolling Join encodes to EXACTLY the pre-dialect bytes in
    both codecs (the golden Join vectors above already pin binary):
    old decoders see nothing new, which is what makes the roll
    advertisement deployable with no flag day."""
    import json as _json

    off = _json.loads(encode_msg(Join(backend="cpu")))
    assert "roll" not in off
    on = _json.loads(encode_msg(Join(backend="cpu", roll=True)))
    assert on["roll"] == 1


def test_roll_assign_count_guards():
    """count=0 (an empty sweep) and count >= 2^32 (wider than the
    binary field) cannot be REPRESENTED in binary — encode falls back
    to JSON like every unrepresentable message — and NO decoder, JSON
    or hand-crafted binary, accepts a count below 1."""
    assert not payload_is_binary(encode_msg(RollAssign(1, 2, 3, 0),
                                            binary=True))
    assert not payload_is_binary(encode_msg(RollAssign(1, 2, 3, 1 << 32),
                                            binary=True))
    with pytest.raises(ProtocolError):
        decode_msg(encode_msg(RollAssign(1, 2, 3, 0)))
    body = struct.pack("<BQQQI", 0xB9, 1, 2, 3, 0)
    with pytest.raises(ProtocolError):
        decode_msg(body + _crc(body))
