"""Shared-compression scheduling pins (ISSUE 16): the AsicBoost-grade
layer — one message schedule serving every colliding rolled row — is
bit-for-bit equal to the scalar/baseline paths it replaces, across
random (en_size, branch depth, B, width), ragged tails, candidate-
bearing windows, and tie-breaking on the exact tracking fold.

Seeded-deterministic versions run everywhere (this image lacks
hypothesis; tests/test_properties.py carries the hypothesis mirrors of
the same invariants for images that have it). The equality pins are the
A/B contract behind ``sched_share`` (house rule since PR 7): flipping
the knob may change SPEED, never a single output bit.
"""

import struct

import numpy as np
import jax.numpy as jnp
import pytest

from tpuminter import chain, rolled
from tpuminter.ops import merkle
from tpuminter.ops import sha256 as ops
from tpuminter.ops import symbolic as sym
from tpuminter.protocol import PowMode, Request

SEED = 1604  # arxiv 1604.00575


def _drain(gen):
    result = None
    for item in gen:
        if item is not None:
            result = item
    return result


def _rand_rows(rng, b, width, ragged=True):
    mids = jnp.asarray(rng.randint(0, 1 << 32, (b, 8), dtype=np.uint32))
    tails = jnp.asarray(rng.randint(0, 1 << 32, (b, 3), dtype=np.uint32))
    bases = jnp.asarray(rng.randint(0, 1 << 20, b, dtype=np.uint32))
    if ragged:
        valids = np.where(
            np.arange(b) < b - 2, np.uint32(width),
            rng.randint(0, width + 1, b).astype(np.uint32),
        )
    else:
        valids = np.full(b, width, np.uint32)
    goffs = (np.arange(b, dtype=np.uint64) * width).astype(np.uint32)
    return mids, tails, bases, jnp.asarray(valids), jnp.asarray(goffs)


# ---------------------------------------------------------------------------
# the truncated shared-schedule hash vs the full digest
# ---------------------------------------------------------------------------

def test_header_e60_e61_matches_full_digest_words():
    """The two digest words the candidate test reads are recovered
    exactly from (e60, e61): word 7 = H0[7] + e60, word 6 =
    DIGEST6_BIAS + e61 — over random dynamic headers and nonces."""
    rng = np.random.RandomState(SEED)
    for _ in range(4):
        mid = jnp.asarray(rng.randint(0, 1 << 32, 8, dtype=np.uint32))
        tw = jnp.asarray(rng.randint(0, 1 << 32, 3, dtype=np.uint32))
        nonces = jnp.asarray(rng.randint(0, 1 << 32, 64, dtype=np.uint32))
        digests = np.asarray(ops.header_digest_dyn(mid, tw, nonces))
        e60, e61 = ops.header_e60_e61_dyn(mid, tw, nonces)
        w7 = (np.uint32(ops.SHA256_H0[7]) + np.asarray(e60))
        w6 = (np.uint32(sym.DIGEST6_BIAS) + np.asarray(e61))
        assert np.array_equal(digests[:, 7], w7)
        assert np.array_equal(digests[:, 6], w6)


def test_prepare_hdr_finisher_matches_hash_sym():
    """prepare_hdr + hash_prepared_e60_e61 ≡ hash_sym_e60_e61 — on
    traced u32 inputs AND on all-int inputs (where both must const-fold
    to plain Python ints: the Pallas kernels' baked-template regime)."""
    rng = np.random.RandomState(SEED + 1)
    mid = [jnp.uint32(x) for x in rng.randint(0, 1 << 32, 8, dtype=np.uint32)]
    t0, t1, t2 = (jnp.uint32(x) for x in rng.randint(0, 1 << 32, 3,
                                                     dtype=np.uint32))
    nonces = jnp.asarray(rng.randint(0, 1 << 32, 32, dtype=np.uint32))
    block = [t0, t1, t2, ops.byteswap32(nonces), *ops.HEADER_TAIL_PAD]
    want = sym.hash_sym_e60_e61(mid, [block], (), 0, 0)
    prep = sym.prepare_hdr(mid, t0, t1, t2)
    got = sym.hash_prepared_e60_e61(prep, nonces)
    assert np.array_equal(np.asarray(want[0]), np.asarray(got[0]))
    assert np.array_equal(np.asarray(want[1]), np.asarray(got[1]))

    imid = [int(x) for x in np.asarray(jnp.stack(mid))]
    it = [int(t0), int(t1), int(t2)]
    for n in (0, 1, 0xDEADBEEF):
        iblock = [*it, int(np.asarray(ops.byteswap32(jnp.uint32(n)))),
                  *ops.HEADER_TAIL_PAD]
        want = sym.hash_sym_e60_e61(imid, [iblock], (), 0, 0)
        got = sym.hash_prepared_e60_e61(
            sym.prepare_hdr(imid, *it), n
        )
        assert isinstance(got[0], int) and isinstance(got[1], int)
        assert got == want


# ---------------------------------------------------------------------------
# the batched sweep: sched on ≡ sched off
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("cand_bits", [8, 32])
def test_batched_sweep_sched_bit_equal(cand_bits):
    """_jnp_batched_candidate_sweep(sched=True) ≡ (sched=False) across
    random rows, ragged valids, and both candidate-test arms."""
    rng = np.random.RandomState(SEED + cand_bits)
    for b, width in ((4, 64), (8, 64), (3, 256)):
        args = _rand_rows(rng, b, width)
        cap = jnp.uint32(rng.randint(0, 1 << 32))
        base = np.asarray(rolled._jnp_batched_candidate_sweep(
            *args, cap, width, cand_bits, False))
        sched = np.asarray(rolled._jnp_batched_candidate_sweep(
            *args, cap, width, cand_bits, True))
        assert np.array_equal(base, sched), (b, width)


def test_batched_sweep_sched_equal_on_candidate_bearing_window():
    """Equality must hold where it matters: windows that actually
    surface a candidate (found=1, exact first global offset)."""
    rng = np.random.RandomState(SEED + 2)
    width, b, cand_bits = 64, 4, 4  # 4-bit bar: hits are plentiful
    hits = 0
    for _ in range(8):
        args = _rand_rows(rng, b, width, ragged=False)
        cap = jnp.uint32(0xFFFFFFFF)
        base = np.asarray(rolled._jnp_batched_candidate_sweep(
            *args, cap, width, cand_bits, False))
        sched = np.asarray(rolled._jnp_batched_candidate_sweep(
            *args, cap, width, cand_bits, True))
        assert np.array_equal(base, sched)
        hits += int(base[0])
    assert hits > 0  # the pin exercised the found arm, not just misses


# ---------------------------------------------------------------------------
# the roll dedup: gathered uniques ≡ rolling every row
# ---------------------------------------------------------------------------

def test_roll_batch_deduped_bit_equal():
    """roll_batch_deduped ≡ the plain batched roll — duplicate-heavy,
    all-unique, and all-identical extranonce row sets."""
    rng = np.random.RandomState(SEED + 3)
    prefix, suffix = rng.bytes(41), rng.bytes(60)
    branch = (rng.bytes(32), rng.bytes(32))
    hdr80 = chain.GENESIS_HEADER.pack()
    roll = merkle.make_extranonce_roll_batch(hdr80, prefix, suffix, 4, branch)
    cases = [
        np.array([5, 5, 5, 6, 6, 7, 5, 6], np.uint32),   # dup-heavy
        np.arange(8, dtype=np.uint32),                    # all unique
        np.full(8, 9, np.uint32),                         # one extranonce
        np.array([2, 2, 2], np.uint32),                   # non-pow2 rows
    ]
    for en_lo in cases:
        en_hi = np.zeros_like(en_lo)
        want_m, want_t = roll(jnp.asarray(en_hi), jnp.asarray(en_lo))
        got_m, got_t = merkle.roll_batch_deduped(roll, en_hi, en_lo)
        assert np.array_equal(np.asarray(want_m), np.asarray(got_m))
        assert np.array_equal(np.asarray(want_t), np.asarray(got_t))


def test_roll_batch_deduped_wide_extranonce():
    """The (hi, lo) u32 pair reassembles into the dedup key correctly:
    rows equal in lo but different in hi must NOT collapse."""
    rng = np.random.RandomState(SEED + 4)
    prefix, suffix = rng.bytes(41), rng.bytes(60)
    hdr80 = chain.GENESIS_HEADER.pack()
    roll = merkle.make_extranonce_roll_batch(hdr80, prefix, suffix, 8, ())
    en_hi = np.array([0, 1, 0, 1], np.uint32)
    en_lo = np.array([7, 7, 7, 7], np.uint32)
    want_m, want_t = roll(jnp.asarray(en_hi), jnp.asarray(en_lo))
    got_m, got_t = merkle.roll_batch_deduped(roll, en_hi, en_lo)
    assert np.array_equal(np.asarray(want_m), np.asarray(got_m))
    assert np.array_equal(np.asarray(want_t), np.asarray(got_t))
    assert not np.array_equal(np.asarray(want_m)[0], np.asarray(want_m)[1])


# ---------------------------------------------------------------------------
# end-to-end: the sched_share knob is output-invisible
# ---------------------------------------------------------------------------

def _random_rolled_request(rng, nb, en_size, depth, target):
    prefix = rng.bytes(int(rng.randint(2, 64)))
    suffix = rng.bytes(int(rng.randint(2, 64)))
    branch = tuple(rng.bytes(32) for _ in range(depth))
    return Request(
        job_id=1, mode=PowMode.TARGET, lower=0, upper=(4 << nb) - 1,
        header=chain.GENESIS_HEADER.pack(), target=target,
        coinbase_prefix=prefix, coinbase_suffix=suffix,
        extranonce_size=en_size, branch=branch, nonce_bits=nb,
    )


@pytest.mark.parametrize("nb,en_size,depth", [(8, 4, 2), (9, 8, 0), (8, 4, 3)])
def test_mine_rolled_fast_sched_on_off_equal(nb, en_size, depth):
    """mine_rolled_fast results are bit-identical with sched_share on vs
    off, across random jobs varying (nonce_bits, extranonce size, branch
    depth) — found, exhausted-with-candidates, and searched counts."""
    rng = np.random.RandomState(SEED + nb + en_size + depth)
    for target in (1 << 250, 1):  # candidate-findable and unbeatable
        req = _random_rolled_request(rng, nb, en_size, depth, target)
        kw = dict(slab=256, roll_batch=4, engine="jnp", cand_bits=8)
        off = _drain(rolled.mine_rolled_fast(req, sched_share=False, **kw))
        on = _drain(rolled.mine_rolled_fast(req, sched_share=True, **kw))
        assert (on.found, on.nonce, on.hash_value, on.searched) == (
            off.found, off.nonce, off.hash_value, off.searched
        ), (nb, en_size, depth, target)


def test_mine_rolled_tracking_sched_on_off_equal_with_dup_ties():
    """The exact tracking fold is unchanged by the roll dedup — on a job
    whose windows span whole segments (every row of a dispatch shares
    one extranonce, the dedup's maximal case) the first-winner AND
    lexicographic-min results, tie-breaks included, match bit-for-bit."""
    rng = np.random.RandomState(SEED + 5)
    req = _random_rolled_request(rng, 8, 4, 2, target=1)
    kw = dict(width_cap=256, roll_batch=4)
    off = _drain(rolled.mine_rolled_tracking(req, sched_share=False, **kw))
    on = _drain(rolled.mine_rolled_tracking(req, sched_share=True, **kw))
    assert (on.found, on.nonce, on.hash_value, on.searched) == (
        off.found, off.nonce, off.hash_value, off.searched
    )
    # found regime too (winner surfaced through the deduped rows)
    req2 = _random_rolled_request(rng, 8, 4, 1, target=1 << 252)
    off = _drain(rolled.mine_rolled_tracking(req2, sched_share=False, **kw))
    on = _drain(rolled.mine_rolled_tracking(req2, sched_share=True, **kw))
    assert (on.found, on.nonce, on.hash_value) == (
        off.found, off.nonce, off.hash_value
    )
    assert on.found


def test_width_knob_overrides_and_preserves_results():
    """The explicit width= override and width="auto" both reach the same
    answers as the legacy cap-derived width (different shapes, same
    outputs) — the A/B override contract of the autotune satellite."""
    rng = np.random.RandomState(SEED + 6)
    req = _random_rolled_request(rng, 8, 4, 2, target=1)
    kw = dict(slab=256, roll_batch=4, engine="jnp", cand_bits=8)
    legacy = _drain(rolled.mine_rolled_fast(req, **kw))
    narrow = _drain(rolled.mine_rolled_fast(req, width=64, **kw))
    assert (narrow.found, narrow.nonce, narrow.hash_value) == (
        legacy.found, legacy.nonce, legacy.hash_value
    )


def test_autotune_width_picks_candidate_and_caches():
    """The probe returns a member of its candidate set and memoizes per
    configuration (one probe per process, the startup-cost contract)."""
    cands = (64, 128)
    key_count = len(rolled._autotune_cache)
    w1 = rolled.autotune_width(cands, cand_bits=8, rows=2, reps=1)
    assert w1 in cands
    assert len(rolled._autotune_cache) == key_count + 1
    w2 = rolled.autotune_width(cands, cand_bits=8, rows=2, reps=1)
    assert w2 == w1
    assert len(rolled._autotune_cache) == key_count + 1  # cache hit, no probe
