"""Chain primitive tests: pure functions vs hashlib + published vectors.

SURVEY.md §4 rebuild plan item (a): the reference can't help here (its PoW
is a toy min-hash); real Bitcoin semantics are validated against the real
genesis block and hashlib.
"""

import hashlib
import os
import struct

import pytest

from tpuminter import chain


def test_sha256_compress_matches_hashlib_one_block():
    # A 55-byte message fits one padded block: len ≤ 55 → block = msg ‖ 0x80 ‖ zeros ‖ len64.
    msg = bytes(range(55))
    block = msg + b"\x80" + b"\x00" * (64 - 55 - 1 - 8) + struct.pack(">Q", len(msg) * 8)
    state = chain.sha256_compress(chain.SHA256_H0, block)
    digest = struct.pack(">8I", *state)
    assert digest == hashlib.sha256(msg).digest()


def test_sha256_compress_matches_hashlib_multi_block():
    msg = os.urandom(64 * 3)  # exactly 3 blocks + 1 padding block
    state = chain.SHA256_H0
    for i in range(3):
        state = chain.sha256_compress(state, msg[64 * i : 64 * (i + 1)])
    pad = b"\x80" + b"\x00" * (64 - 1 - 8) + struct.pack(">Q", len(msg) * 8)
    state = chain.sha256_compress(state, pad)
    assert struct.pack(">8I", *state) == hashlib.sha256(msg).digest()


def test_midstate_continues_to_header_hash():
    header = chain.GENESIS_HEADER.pack()
    mid = chain.midstate(header[:64])
    # second block: last 16 header bytes + padding for an 80-byte message
    tail = header[64:] + b"\x80" + b"\x00" * (64 - 16 - 1 - 8) + struct.pack(">Q", 80 * 8)
    state = chain.sha256_compress(mid, tail)
    assert struct.pack(">8I", *state) == hashlib.sha256(header).digest()


def test_genesis_block_hash():
    assert chain.GENESIS_HEADER.pack().__len__() == 80
    assert chain.hash_to_hex(chain.GENESIS_HEADER.block_hash()) == chain.GENESIS_HASH_HEX
    assert chain.GENESIS_HEADER.meets_target()


def test_genesis_wrong_nonce_fails_target():
    assert not chain.GENESIS_HEADER.with_nonce(0).meets_target()


def test_header_roundtrip():
    h = chain.GENESIS_HEADER
    assert chain.BlockHeader.unpack(h.pack()) == h


def test_bits_to_target_difficulty_one():
    target = chain.bits_to_target(0x1D00FFFF)
    assert target == 0xFFFF * (1 << (8 * (0x1D - 3)))
    assert f"{target:064x}".startswith("00000000ffff")
    assert chain.target_to_bits(target) == 0x1D00FFFF


def test_target_to_bits_mantissa_carry():
    # A target whose top mantissa byte has the sign bit set must re-normalize.
    bits = chain.target_to_bits(0x80FFFF << 8)
    assert chain.bits_to_target(bits) <= 0x80FFFF << 8
    assert not (bits & 0x00800000)


def test_tail_words_match_packed_bytes():
    h = chain.GENESIS_HEADER
    raw = h.pack()
    w0, w1, w2 = h.tail_words()
    assert struct.pack(">3I", w0, w1, w2) == raw[64:76]
    # word 3 of the second block is the byte-swapped nonce
    (w3,) = struct.unpack(">I", raw[76:80])
    assert w3 == int.from_bytes(struct.pack("<I", h.nonce), "big")


def test_merkle_root_basics():
    a, b, c = (bytes([i]) * 32 for i in (1, 2, 3))
    assert chain.merkle_root([a]) == a
    assert chain.merkle_root([a, b]) == chain.dsha256(a + b)
    # odd level duplicates the last element
    assert chain.merkle_root([a, b, c]) == chain.dsha256(
        chain.dsha256(a + b) + chain.dsha256(c + c)
    )


@pytest.mark.parametrize("n", [1, 2, 3, 5, 8, 13])
@pytest.mark.parametrize("index", [0, 1])
def test_merkle_branch_folds_to_root(n, index):
    if index >= n:
        pytest.skip("leaf index out of range")
    txids = [os.urandom(32) for _ in range(n)]
    root = chain.merkle_root(txids)
    branch = chain.merkle_branch(txids, index=index)
    assert chain.merkle_root_from_branch(txids[index], branch, index=index) == root


def test_coinbase_template_rolls_merkle_root():
    cb = chain.CoinbaseTemplate(prefix=b"\x01" * 40, suffix=b"\x02" * 60)
    others = [os.urandom(32) for _ in range(3)]
    for extranonce in (0, 1, 0xDEADBEEF):
        txids = [cb.txid(extranonce)] + others
        branch = chain.merkle_branch(txids, index=0)
        assert cb.merkle_root(extranonce, branch) == chain.merkle_root(txids)


def test_toy_hash_matches_definition():
    data = b"hello mining"
    nonce = 12345
    digest = hashlib.sha256(data + struct.pack(">Q", nonce)).digest()
    assert chain.toy_hash(data, nonce) == int.from_bytes(digest[:8], "big")
    # deterministic + spread
    assert chain.toy_hash(data, nonce) != chain.toy_hash(data, nonce + 1)
