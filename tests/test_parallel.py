"""Pod-scale sharding tests on the fake 8-device CPU mesh (SURVEY.md §4
rebuild plan (d)): the shard_map sweep's early exit, winner fold,
exhausted min-fold, and the toy-dialect pod argmin must be exact."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuminter import chain
from tpuminter.ops import sha256 as ops
from tpuminter.parallel import build_min_fold, build_target_sweep, make_mesh

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the fake 8-device CPU mesh"
)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(jax.devices()[:8])


@pytest.fixture(scope="module")
def genesis_sweep(mesh):
    template = ops.header_template(chain.GENESIS_HEADER.pack())
    return build_target_sweep(mesh, template, batch_per_device=256, n_batches=4)


def test_sweep_finds_genesis_nonce(mesh, genesis_sweep):
    target_words = jnp.asarray(
        ops.target_to_words(chain.bits_to_target(0x1D00FFFF))
    )
    # window chosen so the winner sits mid-shard on a middle device
    start = chain.GENESIS_HEADER.nonce - 2500
    found, nonce, digest, batches = genesis_sweep(jnp.uint32(start), target_words)
    assert int(found) == 1
    assert int(nonce) == chain.GENESIS_HEADER.nonce
    assert ops.digest_to_int(np.asarray(digest)) == chain.GENESIS_HEADER.block_hash_int()


def test_sweep_early_exits_on_easy_target(mesh, genesis_sweep):
    # ~every 16th hash wins: the or-reduce must stop the loop on batch 1
    easy = jnp.asarray(ops.target_to_words((1 << 252) - 1))
    found, nonce, digest, batches = genesis_sweep(jnp.uint32(0), easy)
    assert int(found) == 1
    assert int(batches) == 1
    # winner is verifiable host-side
    h = chain.hash_to_int(
        chain.GENESIS_HEADER.with_nonce(int(nonce)).block_hash()
    )
    assert h == ops.digest_to_int(np.asarray(digest))
    assert h <= (1 << 252) - 1


def test_sweep_exhausted_reports_exact_pod_minimum(mesh, genesis_sweep):
    target_words = jnp.asarray(
        ops.target_to_words(chain.bits_to_target(0x1D00FFFF))
    )
    found, nonce, digest, batches = genesis_sweep(jnp.uint32(0), target_words)
    assert int(found) == 0
    assert int(batches) == 4
    total = 8 * 4 * 256
    want = min(
        (chain.hash_to_int(chain.GENESIS_HEADER.with_nonce(i).block_hash()), i)
        for i in range(total)
    )
    assert (ops.digest_to_int(np.asarray(digest)), int(nonce)) == want


NO_LIMIT = (jnp.uint32(0xFFFFFFFF), jnp.uint32(0xFFFFFFFF))


def test_min_fold_is_exact_across_devices(mesh):
    template = ops.toy_template(b"pod fold")
    fold = build_min_fold(mesh, template, batch_per_device=128)
    fh, fl, nh, nl = fold(jnp.uint32(0), jnp.uint32(0), *NO_LIMIT)
    got = ((int(fh) << 32) | int(fl), (int(nh) << 32) | int(nl))
    want = min((chain.toy_hash(b"pod fold", i), i) for i in range(8 * 128))
    assert got == want


def test_min_fold_64bit_start_carry(mesh):
    """Device shard offsets near a 32-bit boundary must carry into hi."""
    template = ops.toy_template(b"carry")
    fold = build_min_fold(mesh, template, batch_per_device=128)
    start = (1 << 32) - 300  # shards straddle the 2^32 boundary
    fh, fl, nh, nl = fold(
        jnp.uint32(start >> 32), jnp.uint32(start & 0xFFFFFFFF), *NO_LIMIT
    )
    got = ((int(fh) << 32) | int(fl), (int(nh) << 32) | int(nl))
    want = min(
        (chain.toy_hash(b"carry", start + i), start + i) for i in range(8 * 128)
    )
    assert got == want


def test_min_fold_limit_masks_ragged_tail(mesh):
    """Nonces past the 64-bit limit must not win the fold — the ragged
    final step of a chunk stays exact."""
    template = ops.toy_template(b"ragged")
    fold = build_min_fold(mesh, template, batch_per_device=128)
    limit = 700  # mask the last 324 of the 1024-nonce span
    fh, fl, nh, nl = fold(
        jnp.uint32(0), jnp.uint32(0), jnp.uint32(0), jnp.uint32(limit)
    )
    got = ((int(fh) << 32) | int(fl), (int(nh) << 32) | int(nl))
    want = min((chain.toy_hash(b"ragged", i), i) for i in range(limit + 1))
    assert got == want


def test_graft_entry_contract():
    """The driver's contract: entry() compiles single-chip; the multichip
    dry run executes the full sharded program on 8 devices."""
    import __graft_entry__ as graft

    fn, args = graft.entry()
    found, first, digest = jax.jit(fn)(*args)
    assert found.shape == ()
    graft.dryrun_multichip(8)
