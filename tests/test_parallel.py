"""Pod-scale sharding tests on the fake 8-device CPU mesh (SURVEY.md §4
rebuild plan (d)): the shard_map sweep's early exit, winner fold,
exhausted min-fold, and the toy-dialect pod argmin must be exact."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuminter import chain
from tpuminter.ops import sha256 as ops
from tpuminter.parallel import build_min_fold, build_target_sweep, make_mesh

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the fake 8-device CPU mesh"
)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(jax.devices()[:8])


@pytest.fixture(scope="module")
def genesis_sweep(mesh):
    template = ops.header_template(chain.GENESIS_HEADER.pack())
    return build_target_sweep(mesh, template, batch_per_device=256, n_batches=4)


def test_sweep_finds_genesis_nonce(mesh, genesis_sweep):
    target_words = jnp.asarray(
        ops.target_to_words(chain.bits_to_target(0x1D00FFFF))
    )
    # window chosen so the winner sits mid-shard on a middle device
    start = chain.GENESIS_HEADER.nonce - 2500
    found, nonce, digest, batches = genesis_sweep(
        jnp.uint32(start), target_words, jnp.uint32(0xFFFFFFFF)
    )
    assert int(found) == 1
    assert int(nonce) == chain.GENESIS_HEADER.nonce
    assert ops.digest_to_int(np.asarray(digest)) == chain.GENESIS_HEADER.block_hash_int()


def test_sweep_early_exits_on_easy_target(mesh, genesis_sweep):
    # ~every 16th hash wins: the or-reduce must stop the loop on batch 1
    easy = jnp.asarray(ops.target_to_words((1 << 252) - 1))
    found, nonce, digest, batches = genesis_sweep(
        jnp.uint32(0), easy, jnp.uint32(0xFFFFFFFF)
    )
    assert int(found) == 1
    assert int(batches) == 1
    # winner is verifiable host-side
    h = chain.hash_to_int(
        chain.GENESIS_HEADER.with_nonce(int(nonce)).block_hash()
    )
    assert h == ops.digest_to_int(np.asarray(digest))
    assert h <= (1 << 252) - 1


def test_sweep_exhausted_reports_exact_pod_minimum(mesh, genesis_sweep):
    target_words = jnp.asarray(
        ops.target_to_words(chain.bits_to_target(0x1D00FFFF))
    )
    found, nonce, digest, batches = genesis_sweep(
        jnp.uint32(0), target_words, jnp.uint32(0xFFFFFFFF)
    )
    assert int(found) == 0
    assert int(batches) == 4
    total = 8 * 4 * 256
    want = min(
        (chain.hash_to_int(chain.GENESIS_HEADER.with_nonce(i).block_hash()), i)
        for i in range(total)
    )
    assert (ops.digest_to_int(np.asarray(digest)), int(nonce)) == want


def test_target_sweep_limit_masks_ragged_tail(mesh, genesis_sweep):
    """Nonces past the inclusive u32 limit must neither win nor fold —
    the exact-min pod path's final ragged span stays exact."""
    target_words = jnp.asarray(
        ops.target_to_words(chain.bits_to_target(0x1D00FFFF))
    )
    limit = 1500  # mask most of the 8×4×256 = 8192-nonce span
    found, nonce, digest, batches = genesis_sweep(
        jnp.uint32(0), target_words, jnp.uint32(limit)
    )
    assert int(found) == 0
    want = min(
        (chain.hash_to_int(chain.GENESIS_HEADER.with_nonce(i).block_hash()), i)
        for i in range(limit + 1)
    )
    assert (ops.digest_to_int(np.asarray(digest)), int(nonce)) == want


def test_target_sweep_masks_u32_wraparound(mesh, genesis_sweep):
    """A sweep launched near the top of the u32 nonce space must not let
    wrapped-around lanes (small nonces the chunk never asked for) win or
    fold (code-review r4)."""
    target_words = jnp.asarray(
        ops.target_to_words(chain.bits_to_target(0x1D00FFFF))
    )
    start = 0xFFFFFFFF - 1000  # span 8192 ⇒ most lanes wrap past 2^32
    found, nonce, digest, batches = genesis_sweep(
        jnp.uint32(start), target_words, jnp.uint32(0xFFFFFFFF)
    )
    assert int(found) == 0
    want = min(
        (chain.hash_to_int(chain.GENESIS_HEADER.with_nonce(i).block_hash()), i)
        for i in range(start, 1 << 32)
    )
    assert (ops.digest_to_int(np.asarray(digest)), int(nonce)) == want


def test_pod_exact_min_matches_cpu_miner(mesh):
    """--exact-min parity (VERDICT r3 weak #4): a PodMiner with
    exact_min reports the same exhausted-range minimum as CpuMiner,
    including across a ragged final span, and still finds winners."""
    from tpuminter.pod_worker import PodMiner
    from tpuminter.protocol import PowMode, Request
    from tpuminter.worker import CpuMiner

    def drain(gen):
        out = None
        for item in gen:
            if item is not None:
                out = item
        return out

    miner = PodMiner(
        mesh=mesh, slab_per_device=128, n_slabs=2, kernel="jnp",
        exact_min=True,
    )
    # 8×2×128 = 2048-nonce spans; 3000 nonces ⇒ one full + one ragged
    req = Request(
        job_id=1, mode=PowMode.TARGET, lower=0, upper=2999,
        header=chain.GENESIS_HEADER.pack(),
        target=1,  # unbeatable: exhaust and report the exact minimum
    )
    got = drain(miner.mine(req))
    want = drain(CpuMiner(batch=1024).mine(req))
    assert not got.found
    assert (got.hash_value, got.nonce) == (want.hash_value, want.nonce)
    # and the winner path: a window around the genesis nonce
    req2 = Request(
        job_id=2, mode=PowMode.TARGET,
        lower=chain.GENESIS_HEADER.nonce - 1000,
        upper=chain.GENESIS_HEADER.nonce + 1000,
        header=chain.GENESIS_HEADER.pack(),
        target=chain.bits_to_target(0x1D00FFFF),
    )
    got2 = drain(miner.mine(req2))
    assert got2.found and got2.nonce == chain.GENESIS_HEADER.nonce
    digest = got2.hash_value.to_bytes(32, "little")
    assert chain.hash_to_hex(digest) == chain.GENESIS_HASH_HEX


NO_LIMIT = (jnp.uint32(0xFFFFFFFF), jnp.uint32(0xFFFFFFFF))


def test_min_fold_is_exact_across_devices(mesh):
    template = ops.toy_template(b"pod fold")
    fold = build_min_fold(mesh, template, batch_per_device=128)
    fh, fl, nh, nl = fold(jnp.uint32(0), jnp.uint32(0), *NO_LIMIT)
    got = ((int(fh) << 32) | int(fl), (int(nh) << 32) | int(nl))
    want = min((chain.toy_hash(b"pod fold", i), i) for i in range(8 * 128))
    assert got == want


def test_min_fold_64bit_start_carry(mesh):
    """Device shard offsets near a 32-bit boundary must carry into hi."""
    template = ops.toy_template(b"carry")
    fold = build_min_fold(mesh, template, batch_per_device=128)
    start = (1 << 32) - 300  # shards straddle the 2^32 boundary
    fh, fl, nh, nl = fold(
        jnp.uint32(start >> 32), jnp.uint32(start & 0xFFFFFFFF), *NO_LIMIT
    )
    got = ((int(fh) << 32) | int(fl), (int(nh) << 32) | int(nl))
    want = min(
        (chain.toy_hash(b"carry", start + i), start + i) for i in range(8 * 128)
    )
    assert got == want


def test_min_fold_limit_masks_ragged_tail(mesh):
    """Nonces past the 64-bit limit must not win the fold — the ragged
    final step of a chunk stays exact."""
    template = ops.toy_template(b"ragged")
    fold = build_min_fold(mesh, template, batch_per_device=128)
    limit = 700  # mask the last 324 of the 1024-nonce span
    fh, fl, nh, nl = fold(
        jnp.uint32(0), jnp.uint32(0), jnp.uint32(0), jnp.uint32(limit)
    )
    got = ((int(fh) << 32) | int(fl), (int(nh) << 32) | int(nl))
    want = min((chain.toy_hash(b"ragged", i), i) for i in range(limit + 1))
    assert got == want


def test_exact_min_engine_split(mesh):
    """The exact-min engine routing (VERDICT r5 weak #1): ``auto``
    resolves to the jnp CI engine on the CPU backend, and the advertised
    ``exact_min_span`` tracks the engine — one pod slab per chip for the
    Pallas tracking sweep, the memory-capped small batches for jnp. The
    bench/test loop strides come from this property, so a drift here
    silently desynchronizes coverage accounting."""
    from tpuminter.pod_worker import PodMiner

    auto = PodMiner(mesh=mesh, slab_per_device=128, n_slabs=2,
                    exact_min=True)
    assert auto._resolved_kernel() == "jnp"  # CPU backend
    assert auto.exact_min_span == 8 * 2 * 128

    pallas = PodMiner(mesh=mesh, slab_per_device=128, n_slabs=2,
                      kernel="pallas", exact_min=True)
    assert pallas.exact_min_span == 8 * 128  # one slab per chip per call

    # the jnp engine caps its per-chip batch at 2^16 regardless of slab
    big = PodMiner(mesh=mesh, slab_per_device=1 << 20, n_slabs=2,
                   kernel="jnp", exact_min=True)
    assert big.exact_min_span == 8 * 2 * (1 << 16)


def test_graft_entry_contract():
    """The driver's contract: entry() compiles single-chip; the multichip
    dry run executes the full sharded program on 8 devices."""
    import __graft_entry__ as graft

    fn, args = graft.entry()
    found, first, digest = jax.jit(fn)(*args)
    assert found.shape == ()
    graft.dryrun_multichip(8)
