"""Multi-process mesh tests (VERDICT r3 missing #2): the data plane's
collectives crossing a PROCESS boundary, on CPU, with no real multi-host
hardware — 2 processes × 4 virtual devices joined by
``jax.distributed`` with Gloo collectives standing in for DCN.

Everything runs in subprocesses because a ``jax.distributed`` cluster
must be initialized before any other JAX use, and the test process's
JAX is already pinned to the single-process 8-device mesh.
"""

import textwrap

import pytest


def _run_pair(script: str, timeout: float = 420.0):
    """Run `script` in 2 rendezvoused processes via the shared launcher."""
    import __graft_entry__ as graft

    return graft.run_rendezvoused(
        script, n_procs=2, local_devices=4, timeout=timeout
    )


def _spawn_pod_workers(port: int, n_procs: int = 2, local_devices: int = 4,
                       extra_env: dict = None):
    """Spawn the REAL worker CLI (``--backend pod``) in rendezvoused
    processes pointed at a live coordinator port."""
    import __graft_entry__ as graft

    script = (
        "import jax; jax.config.update('jax_platforms', 'cpu');"
        "from tpuminter.worker import main;"
        f"main(['127.0.0.1:{port}', '--backend', 'pod', '--slab', '256'])"
    )
    return graft.spawn_rendezvoused(script, n_procs, local_devices,
                                    extra_env=extra_env)


def _reap(procs, grace: float = 30.0):
    """Give each process ``grace`` seconds for its own exit path, then
    kill. Cleanup must fit well inside the calling test's outer budget
    so a wedged fleet cannot leak live jax subprocesses."""
    import subprocess

    for p in procs:
        try:
            p.communicate(timeout=grace)
        except subprocess.TimeoutExpired:
            p.kill()
            p.communicate()


def test_multiprocess_dryrun_crosses_process_boundary():
    """The full multichip dryrun assertions (candidate sweep or-reduce,
    min fold, PodMiner pipeline, sharded scrypt) over a 2-process ×
    4-device global mesh — every collective spans both processes."""
    import __graft_entry__ as graft

    graft.dryrun_multiprocess(n_procs=2, local_devices=4)


def test_multiprocess_pod_worker_leader_follower():
    """The worker-role protocol for multi-host pods: the leader mirrors
    its request stream and step flags (``PodMiner._spmd_mine``) and a
    follower replays them (``follower_loop``) — including a chunk
    abandoned mid-mine (Cancel) and a clean shutdown."""
    script = textwrap.dedent("""
        import jax
        jax.config.update('jax_platforms', 'cpu')
        from tpuminter.parallel import distributed as dist
        assert dist.init_from_env()
        import jax.numpy as jnp
        from tpuminter import chain
        from tpuminter.parallel import make_mesh
        from tpuminter.pod_worker import PodMiner, follower_loop
        from tpuminter.protocol import PowMode, Request

        leader = dist.is_leader()
        mesh = make_mesh(jax.devices())  # 8 global devices, 2 processes
        miner = PodMiner(mesh=mesh, slab_per_device=256, n_slabs=2,
                         kernel="jnp", spmd_leader=leader)
        if not leader:
            follower_loop(miner)
            print("follower done")
        else:
            win = chain.GENESIS_HEADER.nonce
            req = Request(job_id=1, mode=PowMode.TARGET, lower=win - 3000,
                          upper=win + 3000, header=chain.GENESIS_HEADER.pack(),
                          target=chain.bits_to_target(0x1D00FFFF))
            result = None
            for item in miner.mine(req):
                if item is not None:
                    result = item
            assert result is not None and result.found
            assert result.nonce == win
            assert result.hash_value == chain.GENESIS_HEADER.block_hash_int()

            # abandon a chunk mid-mine (the Cancel path): step twice,
            # close, then mine another chunk to prove resync
            req2 = Request(job_id=2, mode=PowMode.MIN, lower=0, upper=99_999,
                           data=b"abandoned chunk")
            gen = miner.mine(req2)
            next(gen); next(gen)
            gen.close()

            req3 = Request(job_id=3, mode=PowMode.MIN, lower=0, upper=4095,
                           data=b"after cancel")
            result3 = None
            for item in miner.mine(req3):
                if item is not None:
                    result3 = item
            want = min((chain.toy_hash(b"after cancel", i), i)
                       for i in range(4096))
            assert (result3.hash_value, result3.nonce) == want

            miner.close()
            print("leader done")
    """)
    outs = _run_pair(script)
    for pid, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"proc {pid} rc={rc}\n{out}\n{err[-3000:]}"
    assert "leader done" in outs[0][1]
    assert "follower done" in outs[1][1]


def test_multihost_worker_cli_full_stack():
    """The whole multi-host story through the REAL role surfaces: an
    in-process coordinator, TWO processes running the actual worker CLI
    (``tpuminter.worker main`` with ``--backend pod``: process 0 joins
    the control plane as SPMD leader, process 1 enters
    ``follower_loop``), and a client submitting a genesis-window TARGET
    job. The winner must come back exact — proving Setup/Assign,
    leader→follower mirroring, and the cross-process collectives compose
    end to end, not just at the PodMiner API."""
    import asyncio

    from tpuminter import chain
    from tpuminter.client import submit
    from tpuminter.coordinator import Coordinator
    from tpuminter.lsp.params import FAST as LSP_FAST  # the CLI roles' default
    from tpuminter.protocol import PowMode, Request

    from tests.test_e2e import run

    async def scenario():
        # the worker CLI runs the lsp FAST profile (250 ms epochs); the
        # coordinator must speak the same cadence or its 5-epoch
        # deadline undercuts the workers' heartbeat interval
        coord = await Coordinator.create(params=LSP_FAST, chunk_size=4096)
        serve_task = asyncio.ensure_future(coord.serve())
        procs = _spawn_pod_workers(coord.port)
        try:
            win = chain.GENESIS_HEADER.nonce
            req = Request(
                job_id=11, mode=PowMode.TARGET,
                lower=win - 3000, upper=win + 3000,
                header=chain.GENESIS_HEADER.pack(),
                target=chain.bits_to_target(0x1D00FFFF),
            )
            result = await asyncio.wait_for(
                submit("127.0.0.1", coord.port, req, params=LSP_FAST),
                timeout=240,
            )
            assert result.found and result.nonce == win
            assert result.hash_value == chain.GENESIS_HEADER.block_hash_int()
        finally:
            serve_task.cancel()
            await asyncio.gather(serve_task, return_exceptions=True)
            await coord.close()
            _reap(procs)  # grace for the workers' own exit-on-loss path

    run(scenario(), timeout=420)


def test_multihost_leader_death_requeues_to_survivor():
    """Multi-host failure story (SURVEY.md §5: slice failure = worker
    failure): kill the pod LEADER process mid-job with no goodbye. The
    coordinator's epoch liveness must requeue its chunk onto a surviving
    CPU miner and the job must still finish exact. The orphaned follower
    is eventually torn down by jax.distributed's coordination layer (the
    leader hosted the service; its heartbeat/poll failures are fatal —
    ``init_from_env`` shortens the timeout to 30 s), but the exact
    latency is platform-dependent gRPC backoff, so this test reaps it
    in cleanup rather than asserting the timing."""
    import asyncio

    from tpuminter.client import submit
    from tpuminter.coordinator import Coordinator
    from tpuminter.lsp.params import FAST as LSP_FAST
    from tpuminter.protocol import PowMode, Request
    from tpuminter.worker import CpuMiner, run_miner

    from tests.test_e2e import brute_min, run

    async def scenario():
        coord = await Coordinator.create(params=LSP_FAST, chunk_size=65536)
        serve_task = asyncio.ensure_future(coord.serve())
        procs = _spawn_pod_workers(coord.port)
        cpu_task = asyncio.ensure_future(run_miner(
            "127.0.0.1", coord.port, CpuMiner(), params=LSP_FAST
        ))
        try:
            data = b"leader death"
            upper = (1 << 22) - 1
            job = asyncio.ensure_future(submit(
                "127.0.0.1", coord.port,
                Request(job_id=5, mode=PowMode.MIN, lower=0, upper=upper,
                        data=data),
                params=LSP_FAST,
            ))
            # kill the leader with no goodbye (≙ a crashed host) — but
            # only once it is observably joined AND mining a chunk, so
            # the requeue path provably runs (a fixed sleep could fire
            # before the Join and the test would pass vacuously)
            loop = asyncio.get_running_loop()
            deadline = loop.time() + 120
            while True:
                ws = coord.worker_stats()
                if any(w["backend"] == "pod" and w["busy"]
                       for w in ws.values()):
                    break
                assert loop.time() < deadline, f"pod never got busy: {ws}"
                assert not job.done(), "job finished before the pod joined"
                await asyncio.sleep(0.25)
            procs[0].kill()
            result = await asyncio.wait_for(job, timeout=240)
            assert (result.hash_value, result.nonce) == brute_min(
                data, 0, upper
            )
            assert result.searched >= upper + 1
        finally:
            cpu_task.cancel()
            serve_task.cancel()
            await asyncio.gather(cpu_task, serve_task, return_exceptions=True)
            await coord.close()
            # the dead leader reaps instantly; the orphaned follower
            # gets no grace (it exits on the coordination layer's
            # schedule, not ours) — kill it now
            _reap(procs, grace=1.0)

    run(scenario(), timeout=420)


def test_multihost_follower_death_kills_stuck_leader_and_requeues():
    """VERDICT r4 missing #2 — the NASTIER failure topology: kill a
    FOLLOWER mid-job. Unlike leader death (which the coordinator sees
    directly as a lost connection), the leader now blocks inside a Gloo
    collective whose peer is gone, so the coordinator sees a live-but-
    stuck worker. The cascade under test: the ``jax.distributed``
    heartbeat (shortened via ``TPUMINTER_HEARTBEAT_S``) detects the dead
    participant and tears the leader down from below → the leader's LSP
    connection drops → epoch liveness fires → the chunk requeues onto
    the surviving CPU miner → the job completes exact. The detection→
    completion latency is measured and bounded."""
    import asyncio
    import time

    from tpuminter.client import submit
    from tpuminter.coordinator import Coordinator
    from tpuminter.lsp.params import FAST as LSP_FAST
    from tpuminter.protocol import PowMode, Request
    from tpuminter.worker import CpuMiner, run_miner

    from tests.test_e2e import brute_min, run

    HEARTBEAT_S = 10  # CI-friendly stand-in for the 30 s production default

    async def scenario():
        coord = await Coordinator.create(params=LSP_FAST, chunk_size=65536)
        serve_task = asyncio.ensure_future(coord.serve())
        procs = _spawn_pod_workers(
            coord.port, extra_env={"TPUMINTER_HEARTBEAT_S": str(HEARTBEAT_S)}
        )
        cpu_task = asyncio.ensure_future(run_miner(
            "127.0.0.1", coord.port, CpuMiner(), params=LSP_FAST
        ))
        try:
            data = b"follower death"
            upper = (1 << 22) - 1
            job = asyncio.ensure_future(submit(
                "127.0.0.1", coord.port,
                Request(job_id=6, mode=PowMode.MIN, lower=0, upper=upper,
                        data=data),
                params=LSP_FAST,
            ))
            # kill only once the pod is observably joined AND mining, so
            # the stuck-leader cascade provably runs (not a pre-join race)
            loop = asyncio.get_running_loop()
            deadline = loop.time() + 120
            while True:
                ws = coord.worker_stats()
                if any(w["backend"] == "pod" and w["busy"]
                       for w in ws.values()):
                    break
                assert loop.time() < deadline, f"pod never got busy: {ws}"
                assert not job.done(), "job finished before the pod joined"
                await asyncio.sleep(0.25)
            t_kill = time.monotonic()
            procs[1].kill()  # the FOLLOWER — the leader keeps its LSP up
            result = await asyncio.wait_for(job, timeout=300)
            latency = time.monotonic() - t_kill
            requeues = coord.stats["chunks_requeued"]
            print(f"follower-death: kill→completion {latency:.1f}s "
                  f"(heartbeat {HEARTBEAT_S}s), chunks_requeued={requeues}")
            assert (result.hash_value, result.nonce) == brute_min(
                data, 0, upper
            )
            assert result.searched >= upper + 1
            # the cascade must fit the heartbeat + LSP epoch budget plus
            # the survivor's re-mining time. Each term is DERIVED, not
            # hardcoded (ADVICE r5 #3: the former flat 100 s assumed a
            # ~30 s re-mine, which a loaded 1-core CI host can exceed):
            # jax.distributed death detection is HEARTBEAT_S plus ~2
            # missed-tick grace + gRPC teardown backoff (budgeted 10 s),
            # LSP epoch liveness comes from the actual params, and the
            # re-mine term is the whole job at a toy-hash rate measured
            # HERE, on this host, right now — 2x slack on top.
            from tpuminter import chain as _chain

            t_cal = time.monotonic()
            n_cal = 0
            while time.monotonic() - t_cal < 0.25:
                _chain.toy_hash(data, n_cal)
                n_cal += 1
            cpu_rate = n_cal / (time.monotonic() - t_cal)
            remine_s = (upper + 1) / cpu_rate  # worst case: the whole job
            # TPUMINTER_HEARTBEAT_S only takes effect when this jax's
            # initialize() accepts the heartbeat knob (distributed.py
            # falls back without it on older vintages); budget jax's own
            # ~100 s flaky-DCN default in that regime instead of a
            # shortened value the runtime never saw
            import inspect

            import jax.distributed as _jd

            hb_effective = (
                HEARTBEAT_S
                if "heartbeat_timeout_seconds"
                in inspect.signature(_jd.initialize).parameters
                else 100
            )
            detect_s = (
                hb_effective + 10
                + LSP_FAST.epoch_limit * LSP_FAST.epoch_seconds
            )
            bound = 2 * (detect_s + remine_s)
            print(f"follower-death bound: {bound:.1f}s "
                  f"(detect {detect_s:.1f}s + remine {remine_s:.1f}s "
                  f"at {cpu_rate:.0f} H/s)")
            assert latency < bound, (latency, detect_s, remine_s)
            # the stuck leader was torn down and its chunk requeued (the
            # survivor could not otherwise have covered the full range)
            assert requeues >= 1
        finally:
            cpu_task.cancel()
            serve_task.cancel()
            await asyncio.gather(cpu_task, serve_task, return_exceptions=True)
            await coord.close()
            _reap(procs, grace=1.0)  # proc 1 is dead; proc 0 was torn down

    run(scenario(), timeout=420)


def test_multiprocess_dryrun_4_procs_leader_minority():
    """VERDICT r4 next-round #8: the multi-host stand-in at >2
    processes — 4 processes × 2 devices, where the leader owns a 1/4
    minority of the mesh — through the full dryrun assertions
    (candidate-sweep or-reduce, MIN fold, PodMiner pipeline, sharded
    scrypt), so rendezvous and every collective are exercised on a
    topology where leader ≠ majority."""
    import __graft_entry__ as graft

    graft.dryrun_multiprocess(n_procs=4, local_devices=2)
