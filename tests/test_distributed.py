"""Multi-process mesh tests (VERDICT r3 missing #2): the data plane's
collectives crossing a PROCESS boundary, on CPU, with no real multi-host
hardware — 2 processes × 4 virtual devices joined by
``jax.distributed`` with Gloo collectives standing in for DCN.

Everything runs in subprocesses because a ``jax.distributed`` cluster
must be initialized before any other JAX use, and the test process's
JAX is already pinned to the single-process 8-device mesh.
"""

import textwrap

import pytest


def _run_pair(script: str, timeout: float = 420.0):
    """Run `script` in 2 rendezvoused processes via the shared launcher."""
    import __graft_entry__ as graft

    return graft.run_rendezvoused(
        script, n_procs=2, local_devices=4, timeout=timeout
    )


def test_multiprocess_dryrun_crosses_process_boundary():
    """The full multichip dryrun assertions (candidate sweep or-reduce,
    min fold, PodMiner pipeline, sharded scrypt) over a 2-process ×
    4-device global mesh — every collective spans both processes."""
    import __graft_entry__ as graft

    graft.dryrun_multiprocess(n_procs=2, local_devices=4)


def test_multiprocess_pod_worker_leader_follower():
    """The worker-role protocol for multi-host pods: the leader mirrors
    its request stream and step flags (``PodMiner._spmd_mine``) and a
    follower replays them (``follower_loop``) — including a chunk
    abandoned mid-mine (Cancel) and a clean shutdown."""
    script = textwrap.dedent("""
        import jax
        jax.config.update('jax_platforms', 'cpu')
        from tpuminter.parallel import distributed as dist
        assert dist.init_from_env()
        import jax.numpy as jnp
        from tpuminter import chain
        from tpuminter.parallel import make_mesh
        from tpuminter.pod_worker import PodMiner, follower_loop
        from tpuminter.protocol import PowMode, Request

        leader = dist.is_leader()
        mesh = make_mesh(jax.devices())  # 8 global devices, 2 processes
        miner = PodMiner(mesh=mesh, slab_per_device=256, n_slabs=2,
                         kernel="jnp", spmd_leader=leader)
        if not leader:
            follower_loop(miner)
            print("follower done")
        else:
            win = chain.GENESIS_HEADER.nonce
            req = Request(job_id=1, mode=PowMode.TARGET, lower=win - 3000,
                          upper=win + 3000, header=chain.GENESIS_HEADER.pack(),
                          target=chain.bits_to_target(0x1D00FFFF))
            result = None
            for item in miner.mine(req):
                if item is not None:
                    result = item
            assert result is not None and result.found
            assert result.nonce == win
            assert result.hash_value == chain.GENESIS_HEADER.block_hash_int()

            # abandon a chunk mid-mine (the Cancel path): step twice,
            # close, then mine another chunk to prove resync
            req2 = Request(job_id=2, mode=PowMode.MIN, lower=0, upper=99_999,
                           data=b"abandoned chunk")
            gen = miner.mine(req2)
            next(gen); next(gen)
            gen.close()

            req3 = Request(job_id=3, mode=PowMode.MIN, lower=0, upper=4095,
                           data=b"after cancel")
            result3 = None
            for item in miner.mine(req3):
                if item is not None:
                    result3 = item
            want = min((chain.toy_hash(b"after cancel", i), i)
                       for i in range(4096))
            assert (result3.hash_value, result3.nonce) == want

            miner.close()
            print("leader done")
    """)
    outs = _run_pair(script)
    for pid, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"proc {pid} rc={rc}\n{out}\n{err[-3000:]}"
    assert "leader done" in outs[0][1]
    assert "follower done" in outs[1][1]
