"""The MIN dialect's Result contract, pinned explicitly across backends
(VERDICT r5 next #7): a MIN chunk has no early exit and no sentinel
path, so its Result must ALWAYS carry ``found=True`` with the exhausted
range's exact minimum and full ``searched`` accounting. The bench
harness (``bench._drain_pod(want_found=True)``) and the coordinator's
min folds rely on this; until now it was asserted only implicitly.

TpuMiner cannot construct on the CPU backend (its kernels need a TPU);
its copy of this contract is asserted in the real-chip suite
(tests/test_kernels_tpu.py, "miner" and "pod" sections).
"""

import jax
import pytest

from tpuminter import chain
from tpuminter.protocol import PowMode, Request

DATA = b"min contract"

#: (lower, upper): batch-aligned, ragged, sub-batch, and single-nonce
#: ranges — the shapes that have historically hidden fold bugs
RANGES = [(0, 2047), (5, 3003), (17, 40), (99, 99)]


def _drain(gen):
    out = None
    for item in gen:
        if item is not None:
            out = item
    return out


def _make(backend):
    if backend == "cpu":
        from tpuminter.worker import CpuMiner

        return CpuMiner(batch=512)
    if backend == "native":
        import os
        import subprocess

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        try:
            subprocess.run(
                ["make", "-C", os.path.join(root, "native")],
                check=True, capture_output=True, timeout=120,
            )
        except (FileNotFoundError, subprocess.CalledProcessError) as exc:
            pytest.skip(f"cannot build native core: {exc}")
        from tpuminter.native_worker import NativeMiner

        return NativeMiner(batch=1 << 12)
    if backend == "jax":
        from tpuminter.jax_worker import JaxMiner

        return JaxMiner(batch=1024)
    if backend == "pod":
        if len(jax.devices()) < 8:
            pytest.skip("needs the fake 8-device CPU mesh")
        from tpuminter.parallel import make_mesh
        from tpuminter.pod_worker import PodMiner

        return PodMiner(
            mesh=make_mesh(jax.devices()[:8]), slab_per_device=128,
            n_slabs=2, kernel="jnp",
        )
    if backend == "tpu":
        pytest.skip(
            "TpuMiner needs a TPU backend; the contract runs on silicon "
            "in tests/test_kernels_tpu.py ('miner'/'pod' sections)"
        )
    raise AssertionError(backend)


@pytest.mark.parametrize("backend", ["cpu", "native", "jax", "pod", "tpu"])
@pytest.mark.parametrize("lo,hi", RANGES)
def test_min_result_always_found_with_exhausted_min(backend, lo, hi):
    miner = _make(backend)
    req = Request(job_id=1, mode=PowMode.MIN, lower=lo, upper=hi, data=DATA)
    result = _drain(miner.mine(req))
    assert result is not None
    assert result.found is True  # the contract under test
    want = min((chain.toy_hash(DATA, n), n) for n in range(lo, hi + 1))
    assert (result.hash_value, result.nonce) == want
    assert result.searched == hi - lo + 1
