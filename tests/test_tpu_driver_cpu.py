"""CPU smoke tests for the TPU-gated driver logic (r3 review: a
NameError in ``_mine_rolled_fast``'s search wiring hid behind the TPU
gate because the Pallas kernels only compile on a real chip).

The KERNELS stay TPU-only (tests/test_kernels_tpu.py pins them on
hardware); here they are monkeypatched with CPU fakes so the DRIVERS —
segment iteration, CandidateSearch wiring, pack/resolve handles,
result assembly — execute on every CI run.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tpuminter import chain, tpu_worker
from tpuminter.protocol import MIN_UNTRACKED, PowMode, Request


def _bare_tpu_miner(slab=1 << 12, roll_batch=8):
    """TpuMiner without __init__ (which refuses the CPU backend)."""
    miner = tpu_worker.TpuMiner.__new__(tpu_worker.TpuMiner)
    miner.slab = slab
    miner.depth = 2
    miner.exact_min = False
    miner.roll_batch = roll_batch
    miner.sched_share = True
    miner._scrypt_delegate = None
    miner.lanes = 1
    return miner


def _drain(gen):
    result = None
    for item in gen:
        if item is not None:
            result = item
    return result


def _clean_kernel(*_args, **_kw):
    """A kernel fake reporting 'no candidate anywhere' (found=0)."""
    return jnp.uint32(0), jnp.uint32(0x7FFFFFFF)


def test_target_fast_driver_runs_on_cpu(monkeypatch):
    monkeypatch.setattr(
        tpu_worker, "pallas_search_candidates", _clean_kernel
    )
    miner = _bare_tpu_miner()
    req = Request(
        job_id=1, mode=PowMode.TARGET, lower=0, upper=10_000,
        header=chain.GENESIS_HEADER.pack(),
        target=chain.bits_to_target(0x1D00FFFF),
    )
    result = _drain(miner._mine_target_fast(req))
    assert not result.found
    assert result.hash_value == MIN_UNTRACKED
    assert result.searched == 10_001


def test_rolled_fast_driver_runs_on_cpu(monkeypatch):
    """The production >2^32 driver: window planning × batched roll ×
    resolve (and the roll_batch=1 per-segment baseline's wiring). This
    exact test catches the r3 resolve NameError class — now with the
    Pallas engines faked at their tpuminter.rolled seams."""
    import tpuminter.kernels as kernels
    from tpuminter import rolled

    monkeypatch.setattr(
        rolled, "_pallas_batched_candidate_sweep",
        lambda *a, **k: jnp.asarray(
            np.array([0, 0xFFFFFFFF], np.uint32)
        ),
    )
    monkeypatch.setattr(
        kernels, "pallas_search_candidates_hdr", _clean_kernel
    )
    rng = np.random.RandomState(1)
    nb, ens = 11, 3
    req = Request(
        job_id=2, mode=PowMode.TARGET, lower=5, upper=(ens << nb) - 9,
        header=chain.GENESIS_HEADER.pack(),
        target=chain.bits_to_target(0x1D00FFFF),
        coinbase_prefix=rng.bytes(41), coinbase_suffix=rng.bytes(60),
        extranonce_size=4, branch=(rng.bytes(32),), nonce_bits=nb,
    )
    for roll_batch in (8, 1):
        miner = _bare_tpu_miner(slab=1 << 10, roll_batch=roll_batch)
        result = _drain(miner._mine_rolled_fast(req))
        assert not result.found, roll_batch
        assert result.hash_value == MIN_UNTRACKED, roll_batch
        assert result.searched == req.upper - req.lower + 1, roll_batch


def test_target_fast_driver_finds_scripted_candidate(monkeypatch):
    """A kernel fake that plants one candidate: the driver must verify
    it host-side, accept the win, and report exact coverage."""
    win = 7_777  # a real winner for an easy-but-capped scripted flow
    header = chain.GENESIS_HEADER.pack()
    import struct

    h_win = chain.hash_to_int(
        chain.dsha256(header[:76] + struct.pack("<I", win))
    )

    def planted_kernel(template, base, n, tiles, cap):
        b = int(base)
        if b <= win < b + int(n):
            return jnp.uint32(1), jnp.uint32(win - b)
        return jnp.uint32(0), jnp.uint32(0x7FFFFFFF)

    monkeypatch.setattr(
        tpu_worker, "pallas_search_candidates", planted_kernel
    )
    miner = _bare_tpu_miner(slab=1 << 11)
    req = Request(
        job_id=3, mode=PowMode.TARGET, lower=0, upper=20_000,
        header=header, target=h_win,  # the planted candidate wins exactly
    )
    result = _drain(miner._mine_target_fast(req))
    assert result.found
    assert (result.nonce, result.hash_value) == (win, h_win)
    assert result.searched == win + 1
