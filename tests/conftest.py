"""Test configuration.

Per SURVEY.md §4(d)'s rebuild test plan, CI needs no TPU: the JAX test
suite runs on the CPU backend with 8 fake devices so multi-chip sharding
logic (or-reduce, shard_map meshes) is exercised the same way
``__graft_entry__.dryrun_multichip`` validates it. These env vars MUST be
set before jax imports.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
