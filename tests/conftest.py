"""Test configuration.

Per SURVEY.md §4(d)'s rebuild test plan, CI needs no TPU: the JAX test
suite runs on the CPU backend with 8 fake devices so multi-chip sharding
logic (or-reduce, shard_map meshes) is exercised the same way
``__graft_entry__.dryrun_multichip`` validates it. These env vars MUST be
set before jax imports.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Env-var platform selection (JAX_PLATFORMS=cpu) is NOT enough in this
# image: a sitecustomize hook registers the experimental TPU-tunnel
# backend at interpreter start and wins the selection. Forcing the config
# key after import reliably pins tests to the fake-8-device CPU mesh.
import jax  # noqa: E402  (after XLA_FLAGS above, by design)

jax.config.update("jax_platforms", "cpu")

# The unrolled SHA-256 graphs are trace-heavy; cache compiled executables
# across test runs so only the first run pays the compile bill.
jax.config.update("jax_compilation_cache_dir", "/tmp/tpuminter-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

# Property tests: this box has a single CPU core (BASELINE.md), so a
# scheduling hiccup under load can blow hypothesis's default 200 ms
# per-example deadline on tests that are microseconds-fast when quiet.
# Deadlines guard against slow *examples*, not slow *hosts* — disable.
try:
    from hypothesis import settings

    settings.register_profile("tpuminter", deadline=None)
    settings.load_profile("tpuminter")
except ImportError:  # hypothesis is an optional test extra
    pass
