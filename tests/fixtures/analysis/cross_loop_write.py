"""True-positive fixture: a cross-loop shard mutation outside the
seams.

Reconstructs the race class the PR 6 ownership rules exist to prevent:
the control loop pokes attributes on a shard-homed object directly
instead of hopping through ``call_soon_threadsafe``. Parsed by
tests/test_analysis.py, never imported.
"""

import asyncio
import threading


class _Worker:
    def __init__(self, index):
        self.index = index
        self.loop = None
        self.backlog = 0


class Group:
    def __init__(self, n):
        self._shards = []
        for k in range(n):
            worker = _Worker(k)
            t = threading.Thread(target=self._shard_thread, args=(worker,))
            t.start()
            self._shards.append(worker)

    def _shard_thread(self, worker):
        worker.loop = asyncio.new_event_loop()
        worker.loop.run_forever()

    def rebalance(self):
        # control loop writing a shard-homed attribute: the bug
        for worker in self._shards:
            worker.backlog = 0

    def shutdown(self):
        for worker in self._shards:
            worker.loop.call_soon_threadsafe(worker.loop.stop)
