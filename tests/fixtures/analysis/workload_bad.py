"""True-positive fixture: a careless second-workload port (ISSUE 15).

A new workload's params codec reuses the hashcore params tag 0xC0 (a
Request.data frame for one workload would parse as the other's — the
coordinator would verify claims against the wrong objective), its
accumulator layout collides on packed length with the params layout,
nothing is sealed with the CRC trailer every workloads codec carries,
a u64 field packs unguarded, and TWO ``*_WID`` constants claim
workload id 1 — the dispatch key on binary WorkResult frames and
recovered winner records, where a collision decodes a winner under
the wrong workload. Parsed by tests/test_analysis.py, never imported.
"""

import struct

BADCORE_WID = 1
OTHERCORE_WID = 1           # workload-id collision (and with hashcore)

_TAG_BCPARAMS = 0xC0        # collides with hashcore's params tag
_BIN_BCPARAMS = struct.Struct("<BBQQB")

_TAG_BCACC = 0xC5           # same calcsize as params: length collision
_BIN_BCACC = struct.Struct("<BHQQ")


def pack_params(seed: int, threshold: int) -> bytes:
    # u64 fields packed with no _U64 range guard, no CRC trailer
    return _BIN_BCPARAMS.pack(_TAG_BCPARAMS, 0, seed, threshold, 1)
