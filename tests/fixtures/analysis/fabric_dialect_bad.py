"""True-positive fixture: a careless compute-fabric port (ISSUE 20).

A second opaque-domain workload's params codec reuses the dictsearch
params tag 0xC5 (a Request.data frame for one workload would parse as
the other's — the coordinator would fold indices against the wrong
catalog), its streaming-partial layout claims the SAME tag in-module
and collides on packed length with the params layout, nothing is
sealed with the CRC trailer every fabric frame carries, u64 emission
counters pack unguarded, and TWO ``*_WID`` constants claim workload
id 2 — dictsearch's id, the dispatch key on binary WorkResult frames
and recovered winner records. Parsed by tests/test_analysis.py, never
imported.
"""

import struct

FABCORE_WID = 2             # collides with dictsearch's DICT_WID
FABCORE2_WID = 2            # and with its sibling in-module

_TAG_FABPARAMS = 0xC5       # reuses the dict params tag
_BIN_FABPARAMS = struct.Struct("<BBQQB")

_TAG_FABEMIT = 0xC5         # duplicate tag in-module too
_BIN_FABEMIT = struct.Struct("<BQQBB")  # same calcsize: length collision


def encode_emit(job: int, seq: int, covered: int) -> bytes:
    # u64 fields packed with no _U64 range guard, no CRC trailer
    return _BIN_FABEMIT.pack(_TAG_FABEMIT, job, seq, covered & 0xFF, 0)
