"""True-positive fixture: a broken roll-budget dialect table (ISSUE 14).

The shapes a careless RollAssign/Beacon port would produce: the beacon
tag reuses the wire Result tag 0xB7 (a beacon would decode as a full
chunk settle — silent over-settling), the roll-assign layout's total
packed length collides with another fixed kind (length is the
secondary dispatch key), nothing is sealed with a CRC, and the u64
extranonce0 / high_water fields are packed with no range guard.
Parsed by tests/test_analysis.py, never imported.
"""

import struct

_TAG_RESULT = 0xB7
_BIN_RESULT = struct.Struct("<BQQ")

_TAG_BEACON = 0xB7          # reuses the Result tag: duplicate-tag
_BIN_BEACON = struct.Struct("<BQQQ")

_TAG_ASSIGN_ROLL = 0xB9     # same calcsize as _BIN_BEACON: length-collision
_BIN_ASSIGN_ROLL = struct.Struct("<BQQII")


def encode_roll(job_id: int, extranonce0: int) -> bytes:
    # u64 fields packed with no _U64 range guard, no CRC trailer
    return _BIN_ASSIGN_ROLL.pack(
        _TAG_ASSIGN_ROLL, job_id, extranonce0, 1, 0
    )


def encode_beacon(job_id: int, high_water: int) -> bytes:
    return _BIN_BEACON.pack(_TAG_BEACON, job_id, high_water, 0)
