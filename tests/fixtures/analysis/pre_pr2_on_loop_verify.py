"""True-positive fixture: the pre-PR-2 on-loop verify.

Reconstructs the bug class PR 2 fixed — the coordinator settled every
scrypt result inline on the event loop (~301 µs each), plus the classic
``time.sleep``-in-a-coroutine and the fsync-on-the-loop that PR 3's
adaptive seam removed. Parsed by tests/test_analysis.py, never
imported; the names mirror the real coordinator so the checker's
intra-module propagation is exercised (async serve → sync handler →
blocking call).
"""

import os
import time

from tpuminter import chain


class Coordinator:
    async def serve(self):
        while True:
            msg = await self._next()
            self._on_result(msg)
            time.sleep(0.001)  # "pacing"

    def _on_result(self, msg):
        # inline memory-hard verify on the loop: the PR 2 bug
        digest = chain.scrypt_hash(msg.header)
        self._settle(msg, digest)

    def _settle(self, msg, digest):
        self._journal.append(msg, digest)
        os.fsync(self._journal_fd)

    async def _next(self):
        return await self._queue.get()
