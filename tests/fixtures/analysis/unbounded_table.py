"""Fixture: the ISSUE 13 bug class — a long-lived (affinity-stamped)
control-plane object accreting per-client state with no eviction seam.

`Registry._ledger` and `Registry._backlog` grow with client churn and
nothing ever removes entries: both must be flagged.  `_winners` has a
pop seam, `_recent` is bounded by construction, and `Scratch` is not
stamped (request-scoped): none of those may fire.
"""

from collections import OrderedDict, deque

from tpuminter.analysis import affinity


class Registry:
    def __init__(self):
        affinity.stamp(self)
        self._ledger = {}                 # BAD: keyed by ckey, never shrunk
        self._backlog = deque()           # BAD: unbounded queue
        self._winners = OrderedDict()     # ok: popped in retire()
        self._recent = deque(maxlen=64)   # ok: bounded by construction
        self._seeded = dict(alpha=1)      # ok: not an empty construction

    def book(self, ckey, value):
        self._ledger[ckey] = value
        self._backlog.append((ckey, value))
        self._recent.append(ckey)

    def retire(self, key):
        self._winners.pop(key, None)


class Scratch:
    """Request-scoped: lives for one call, no stamp, no lifetime risk."""

    def __init__(self):
        self.items = {}
