"""True-positive fixture: an uncached device-lane sweep factory.

The ISSUE 17 hazard variant of the pre-PR-7 bug class, shaped like the
new splitmix device-lane engine: a dispatch helper that rebuilds the
``jax.jit`` sweep program (and the Pallas lane kernel inside it) on
every window. The u32-pair arithmetic is cheap to trace once, but a
fmin sweep over millions of windows re-traces the whole scan body per
dispatch, and the engine's entire point — amortize one compile across a
job's constant (variant, width, rows, k) — never happens. Also carries
the sibling hazard: the fold's accumulator passed as a list into an
``lru_cache``'d factory, silently defeating the cache at runtime.
Parsed by tests/test_analysis.py, never imported.
"""

from functools import lru_cache

import jax
from jax.experimental import pallas as pl


def lane_dispatch(seed_words, base_words, width):
    # rebuilt per window: the scan body (and its fold) re-traces on
    # every dispatch even though (variant, width, rows, k) never change
    # within a job
    sweep = jax.jit(lambda s, b: _fmin_scan(s, b, width))
    return sweep(seed_words, base_words)


def lane_kernel(idx_hi, idx_lo):
    # same bug one layer down: a fresh pallas_call per batch means
    # Mosaic recompiles the lane kernel every time the worker hops jobs
    call = pl.pallas_call(_splitmix_body, out_shape=idx_hi)
    return call(idx_hi, idx_lo)


@lru_cache(maxsize=64)
def build_lane_sweep(variant, width, rows, k):
    return jax.jit(lambda s, b: _fmin_scan(s, b, width))


def resolve_window(seed_words, base_words):
    # unhashable argument defeats the factory cache at runtime: every
    # window builds (and traces) a brand-new sweep program
    return build_lane_sweep("fmin", 4096, [8], 1)(seed_words, base_words)


def _fmin_scan(seed_words, base_words, width):
    return seed_words[0] + base_words[0] + width


def _splitmix_body(ih_ref, il_ref, o_ref):
    o_ref[...] = ih_ref[...] ^ il_ref[...]
