"""True-positive fixture: a codec table violating every PR 4 invariant.

Two kinds share a tag, one tag is 0x7B (the JSON sniff byte), two
fixed-length kinds share a total packed length, nothing is sealed with
a CRC, one layout buries the tag mid-record, and a u64 field is packed
unguarded. Parsed by tests/test_analysis.py, never imported.
"""

import struct

_TAG_PING = 0xC1
_PING = struct.Struct("<BQ")

_TAG_PONG = 0xC1            # duplicate tag
_PONG = struct.Struct("<BI")

_TAG_BRACE = 0x7B           # collides with the JSON sniff byte
_BRACE = struct.Struct("<BII")

_TAG_ECHO = 0xC3            # same calcsize as _PING: length collision
_ECHO = struct.Struct("<BII")

_TAG_TAIL = 0xC4            # tag byte not first
_TAIL = struct.Struct("<QB")


def encode_ping(nonce: int) -> bytes:
    # u64 field packed with no _U64 range guard
    return _PING.pack(_TAG_PING, nonce)
