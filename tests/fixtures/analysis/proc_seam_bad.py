"""True-positive fixture for the proc-seam checker: every shortcut the
multi-process seam (PR 19) forbids, in one file. Never imported —
parsed by tests/test_analysis.py only."""

import asyncio  # noqa: F401  (makes the fork rule arm)
import multiprocessing

# module-level mutable that LOOKS like shared state across the boundary
SHARED_REGISTRY = {"binds": {}}


def _nested_target_factory():
    # nested def: spawn pickles targets by qualified name — this one
    # cannot be found at unpickle time
    def shard_body(cfg):
        return cfg

    return multiprocessing.Process(target=shard_body, args=({},))


def spawn_bad_fleet():
    # lambda target: unpicklable under spawn
    p1 = multiprocessing.Process(target=lambda: None)
    # lambda smuggled inside args
    p2 = multiprocessing.Process(
        target=print, args=(lambda x: x,),
    )
    # module-level mutable passed by name: the child mutates a COPY
    p3 = multiprocessing.Process(
        target=print, args=(SHARED_REGISTRY,),
    )
    return p1, p2, p3


def fork_with_loops():
    # fork start method in an asyncio-using module: cloned loop/lock
    # state deadlocks the child
    ctx = multiprocessing.get_context("fork")
    return ctx.Process(target=print, args=())
