"""True-positive fixture: the pre-PR-7 uncached jit.

Reconstructs the bug class PR 7 fixed — a fresh ``jax.jit`` wrapper
(and a fresh ``pl.pallas_call``) constructed per dispatch, so every job
paid the full re-trace (~0.6 s measured). Also carries the sibling
hazard: a list literal passed to an ``lru_cache``'d factory. Parsed by
tests/test_analysis.py, never imported.
"""

from functools import lru_cache

import jax
import jax.experimental.pallas as pl


def sweep_job(header, grid):
    # rebuilt per call: empty trace cache every time (the PR 7 bug)
    sweep = jax.jit(lambda h: h * 2)
    kernel = pl.pallas_call(_body, grid=grid)
    return sweep(kernel(header))


@lru_cache(maxsize=8)
def build_sweep(lanes, widths):
    return jax.jit(lambda h: h * lanes)


def dispatch(header):
    # unhashable argument defeats the factory cache at runtime
    return build_sweep(8, [128, 256])(header)


def _body(ref, out):
    out[...] = ref[...]
