"""True-positive fixture: an uncached shared-schedule jit factory.

The ISSUE 16 hazard variant of the pre-PR-7 bug class: a sweep helper
that builds a fresh ``jax.jit`` wrapper around the shared-schedule
(prepare-once, finish-per-nonce) hash on every dispatch. The schedule
prefix IS hoisted — but the wrapper itself is rebuilt per call, so each
job re-traces the whole unrolled second compression (~3 s measured per
(width, cand_bits) on CPU), and the amortization the layer exists for
never happens. Also carries the sibling hazard: the prepared-schedule
tuple passed as a list into an ``lru_cache``'d factory, silently
defeating the cache at runtime. Parsed by tests/test_analysis.py, never
imported.
"""

from functools import lru_cache

import jax


def sched_sweep(prep, nonces, width):
    # rebuilt per dispatch: the unrolled 64-round graph re-traces on
    # every window even though the schedule prefix was shared
    finish = jax.jit(lambda p, n: _finish_prepared(p, n), static_argnums=())
    return finish(prep, nonces)


@lru_cache(maxsize=32)
def build_sched_sweep(width, cand_bits):
    return jax.jit(lambda p, n: _finish_prepared(p, n))


def dispatch_window(prep, nonces):
    # unhashable argument defeats the factory cache at runtime: every
    # window builds (and traces) a brand-new sweep program
    return build_sched_sweep(256, [8, 32])(prep, nonces)


def _finish_prepared(prep, nonces):
    return prep[0] + nonces
