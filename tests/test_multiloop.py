"""Multi-loop sharded coordinator (ISSUE 6): the partition function's
properties, per-loop WAL segment reassembly, kernel/userspace steering,
batched socket I/O semantics, and the 2-loop smoke/crash/failover gates.

The partition tests are pure and sub-second; the drills are the tier-1
gates the issue names — zero lost connections, zero cross-shard answer
duplication, and exactly-once ledgers through kill -9 and machine-loss
failover with ``--loops 2``.
"""

import asyncio
import os
import random
import sys

import pytest

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ),
)

import loadgen  # noqa: E402  (scripts/ is not a package)

from tpuminter.journal import (  # noqa: E402
    Journal,
    RecoveredState,
    encode_settle,
    intersect_ranges,
    merge_states,
    replay,
)
from tpuminter.lsp import LspServer  # noqa: E402
from tpuminter.lsp.params import FAST  # noqa: E402
from tpuminter.lsp.transport import UdpEndpoint  # noqa: E402
from tpuminter.multiloop import (  # noqa: E402
    MultiLoopCoordinator,
    attach_conn_steering,
    shard_for_job,
    shard_of,
)
from tpuminter.protocol import request_to_obj, Request, PowMode  # noqa: E402

from tests.test_e2e import run  # noqa: E402


# ---------------------------------------------------------------------------
# the partition function (pure properties)
# ---------------------------------------------------------------------------

def test_shard_of_is_stable_across_reconnects_epochs_and_order():
    """The assignment is a pure function of the address: evaluation
    order, repetition, interleaving with other peers, and any notion of
    'epoch' cannot move a peer to a different shard."""
    rng = random.Random(0xC0FFEE)
    addrs = [
        ("127.0.0.1", rng.randrange(1024, 65536)) for _ in range(256)
    ] + [("10.%d.%d.%d" % (rng.randrange(256), rng.randrange(256),
                           rng.randrange(256)), rng.randrange(1024, 65536))
         for _ in range(256)]
    for loops in (2, 3, 4, 8):
        first = {a: shard_of(a, loops) for a in addrs}
        assert all(0 <= s < loops for s in first.values())
        # re-evaluate in shuffled order, many times over ("epochs")
        for _ in range(3):
            shuffled = list(addrs)
            rng.shuffle(shuffled)
            for a in shuffled:
                assert shard_of(a, loops) == first[a]


def test_shard_of_balances_random_peer_sets():
    """Binomial balance bound: over 512 uniform-random peers and 2
    shards, each side holds at least 35% (P[violation] ~ 1e-11 for a
    uniform hash — a failure here means the hash is broken, not
    unlucky). Looser per-shard floor for 4 shards."""
    rng = random.Random(1234)
    addrs = [
        ("127.0.0.1", rng.randrange(1024, 65536)) for _ in range(512)
    ]
    counts2 = [0, 0]
    for a in addrs:
        counts2[shard_of(a, 2)] += 1
    assert min(counts2) >= int(0.35 * len(addrs)), counts2
    counts4 = [0] * 4
    for a in addrs:
        counts4[shard_of(a, 4)] += 1
    assert min(counts4) >= int(0.12 * len(addrs)), counts4


def test_shard_for_job_matches_the_id_stripe():
    """Shard k allocates job ids ≡ k+1 (mod N) (Coordinator
    job_id_start/stride); shard_for_job must invert that exactly, so
    recovered jobs land back on the lane that minted them."""
    for loops in (2, 3, 5):
        for k in range(loops):
            ids = [k + 1 + i * loops for i in range(16)]
            assert all(shard_for_job(j, loops) == k for j in ids)
    # single loop degenerates to shard 0
    assert shard_for_job(12345, 1) == 0


def test_conn_id_stride_partitions_the_id_space():
    """A shard's LspServer allocates conn ids in its own residue class
    — the invariant the kernel's conn-id steering program relies on."""

    async def scenario():
        server = await LspServer.create(
            0, FAST, conn_id_start=3, conn_id_stride=4
        )
        try:
            ids = [
                server._new_conn(("127.0.0.1", 40000 + i)).conn_id
                for i in range(5)
            ]
            assert ids == [3, 7, 11, 15, 19]
        finally:
            await server.close(drain_timeout=0.2)

    run(scenario())


def test_attach_conn_steering_on_this_kernel():
    """The cBPF steering program must attach on Linux (this container's
    kernel accepted it during development — a regression here silently
    demotes every multi-loop run to the forwarding shim)."""
    import socket as s

    sock = s.socket(s.AF_INET, s.SOCK_DGRAM)
    try:
        sock.setsockopt(s.SOL_SOCKET, s.SO_REUSEPORT, 1)
        sock.bind(("127.0.0.1", 0))
        attached = attach_conn_steering(sock, 2)
    finally:
        sock.close()
    if sys.platform.startswith("linux"):
        assert attached
    else:
        assert not attached


# ---------------------------------------------------------------------------
# per-loop WAL segments reassemble into the single-journal state
# ---------------------------------------------------------------------------

def _req(jid: int) -> dict:
    return request_to_obj(Request(
        job_id=jid, mode=PowMode.MIN, lower=0, upper=4095,
        data=b"seg-%d" % jid, client_key=f"ck-{jid}",
    ))


def _records_for(jid: int) -> list:
    """One job's full record stream (job → settles → finish/abandon)."""
    recs = [{"k": "job", "id": jid, "req": _req(jid)}]
    recs.append({"k": "settle", "id": jid, "lo": 0, "hi": 1023,
                 "n": 7, "s": 1024, "h": "%x" % (1000 + jid)})
    recs.append({"k": "settle", "id": jid, "lo": 2048, "hi": 3071,
                 "n": 9, "s": 1024, "h": "%x" % (900 + jid)})
    if jid % 3 == 0:
        recs.append({
            "k": "finish", "id": jid, "ckey": f"ck-{jid}", "cjid": jid,
            "mode": "min", "n": 9, "h": "%x" % (900 + jid),
            "found": True, "s": 2048,
        })
    return recs


def _assert_states_equal(a: RecoveredState, b: RecoveredState) -> None:
    assert a.next_job_id == b.next_job_id
    assert set(a.jobs) == set(b.jobs)
    for jid in a.jobs:
        ja, jb = a.jobs[jid], b.jobs[jid]
        assert ja.remaining == jb.remaining, jid
        assert ja.best == jb.best
        assert ja.hashes_done == jb.hashes_done
        assert request_to_obj(ja.request) == request_to_obj(jb.request)
    assert dict(a.winners) == dict(b.winners)


def test_segment_merge_reassembles_the_single_journal_state():
    """The ISSUE 6 regression: records split across per-loop WAL
    segments by job affinity — including a segment that compacted
    itself into a snapshot mid-stream — must merge back into EXACTLY
    the state a single interleaved journal replays to."""
    loops = 2
    all_jobs = list(range(1, 9))
    # the single-journal ground truth: records interleaved across jobs
    single: list = []
    per_shard: dict = {0: [], 1: []}
    for jid in all_jobs:
        recs = _records_for(jid)
        single.extend(recs)
        per_shard[shard_for_job(jid, loops)].extend(recs)
    truth = replay(single)

    # plain split
    merged = merge_states([replay(per_shard[0]), replay(per_shard[1])])
    _assert_states_equal(truth, merged)

    # shard 0 compacts itself mid-stream: snapshot of its own replayed
    # prefix + the tail — a snapshot record must reset only ITS stream
    half = len(per_shard[0]) // 2
    st0 = replay(per_shard[0][:half])
    seg0_compacted = [st0.snapshot_obj()] + per_shard[0][half:]
    merged2 = merge_states([
        replay(seg0_compacted), replay(per_shard[1])
    ])
    _assert_states_equal(truth, merged2)


def test_intersect_ranges():
    assert intersect_ranges([(0, 10)], [(5, 20)]) == [(5, 10)]
    assert intersect_ranges([(0, 3), (8, 12)], [(2, 9)]) == [
        (2, 3), (8, 9)
    ]
    assert intersect_ranges([(0, 3)], [(4, 9)]) == []
    assert intersect_ranges([], [(0, 5)]) == []


def test_journal_open_absorbs_segments(tmp_path):
    """A single-loop restart over a segmented journal layout merges the
    segments, snapshots them into the base WAL, and deletes them —
    crossing loop counts/modes never loses coverage."""
    base = str(tmp_path / "w.wal")
    for k in (0, 1):
        j = Journal.fresh(f"{base}.s{k}", epoch=3)
        for jid in (k + 1, k + 3):
            for rec in _records_for(jid):
                if rec["k"] == "settle":
                    j.append_encoded(encode_settle(
                        rec["id"], rec["lo"], rec["hi"], rec["n"],
                        rec["s"], int(rec["h"], 16),
                    ))
                else:
                    j.append(rec["k"], rec)
        j._flush_buffered_sync()
        j._fh.close()
    journal, state = Journal.open(base)
    try:
        assert state.boot_epoch == 4
        # job 3 finished (jid % 3 == 0): in winners, not in jobs
        assert set(state.jobs) == {1, 2, 4}
        assert ("ck-3", 3) in state.winners
        assert not os.path.exists(f"{base}.s0")
        assert not os.path.exists(f"{base}.s1")
    finally:
        journal._fh.close()
    # a SECOND open replays the absorbed snapshot identically
    journal2, state2 = Journal.open(base)
    journal2._fh.close()
    _assert_states_equal(state, state2)


# ---------------------------------------------------------------------------
# cross-job group commit of finish fsyncs
# ---------------------------------------------------------------------------

def test_group_commit_shares_one_fsync_across_a_winner_burst(tmp_path):
    """Six winner-gating records arriving within the group-commit
    window must share far fewer fsyncs than one each — and every
    durability callback still fires."""

    async def scenario():
        journal, _ = Journal.open(str(tmp_path / "g.wal"))
        journal.group_commit = True  # measured-off default; see journal.py
        fired = []
        base_syncs = journal.stats["syncs"]  # the boot record's fsync
        for i in range(6):
            journal.append(
                "finish",
                {"id": i, "ckey": f"c{i}", "cjid": i, "mode": "min",
                 "n": 1, "h": "ff", "found": True, "s": 1},
                on_durable=lambda i=i: fired.append(i),
            )
            await asyncio.sleep(0.0005)
        await journal.flush()
        assert sorted(fired) == list(range(6))
        extra_syncs = journal.stats["syncs"] - base_syncs
        assert 1 <= extra_syncs <= 3, extra_syncs
        await journal.aclose()

    run(scenario())


# ---------------------------------------------------------------------------
# batched socket I/O: fault injection + grouped sends are mode-identical
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("io_batch", [True, False])
def test_endpoint_modes_deliver_and_inject_faults(io_batch):
    """Both transport backends deliver datagrams, honor the seeded
    drop-rate seam, and expose the same counters — the layers above
    must not be able to tell them apart."""

    async def scenario():
        got = []
        server = await UdpEndpoint.create(
            lambda d, a: got.append(bytes(d)), local_addr=("127.0.0.1", 0),
            io_batch=io_batch, seed=7,
        )
        sender = await UdpEndpoint.create(
            lambda d, a: None, io_batch=io_batch, seed=7
        )
        try:
            addr = server.local_addr
            for i in range(40):
                sender.send(b"m%d" % i, addr)
            sender.send_batch([b"b1", b"b2", b"b3"], addr)
            sender.send_grouped([(addr, [b"g1", b"g2"])])
            await asyncio.sleep(0.2)
            assert sorted(got) == sorted(
                [b"m%d" % i for i in range(40)]
                + [b"b1", b"b2", b"b3", b"g1", b"g2"]
            )
            assert sender.sent == 45
            assert server.received == 45
            # the read-drop seam still bites in this mode
            server.set_read_drop_rate(1.0)
            sender.send(b"dropped", addr)
            await asyncio.sleep(0.1)
            assert server.dropped_in >= 1
            assert b"dropped" not in got
        finally:
            server.close()
            sender.close()
            await server.wait_closed()
            await sender.wait_closed()

    run(scenario())


# ---------------------------------------------------------------------------
# construction constraints (loud fallbacks)
# ---------------------------------------------------------------------------

def test_multiloop_rejects_bad_configs(tmp_path):
    async def scenario():
        with pytest.raises(ValueError):
            await MultiLoopCoordinator.create(loops=0)
        with pytest.raises(ValueError):
            await MultiLoopCoordinator.create(
                loops=2, recover_from=str(tmp_path / "x.wal"),
                journal_mode="segments",
                replicate_to=[("127.0.0.1", 1)],
            )
        with pytest.raises(ValueError):
            await MultiLoopCoordinator.create(
                loops=2, replicate_to=[("127.0.0.1", 1)]
            )

    run(scenario())


# ---------------------------------------------------------------------------
# the 2-loop gates (ISSUE 6 acceptance)
# ---------------------------------------------------------------------------

def test_two_loop_smoke_no_losses_no_cross_shard_duplication():
    """The tier-1 2-loop smoke gate: a fleet-16 burst across 2 loops
    sustains with zero lost connections, zero duplicate answers, both
    shards carrying peers, and the partitioning verifiably live."""
    metrics = run(loadgen.run_load(16, 4, 1.2, loops=2), timeout=60.0)
    assert loadgen.smoke_check(metrics) == [], metrics
    assert metrics["loops"] == 2
    assert metrics["dup_answers"] == 0
    assert metrics["miners_lost"] == 0
    shards = metrics["loop_metrics"]
    assert len(shards) == 2
    # every loop carries peers and traffic (20 peers over a uniform
    # hash: an empty shard is ~2^-19 — a failure is a partitioning
    # bug). Per-shard RESULTS are deliberately not asserted: 4 clients
    # can legitimately all hash to one shard (~12% of runs), and a
    # job mines on its client's shard — that is affinity working, not
    # a bug. The balance evidence is connections, not results.
    assert all(s["conns"] > 0 for s in shards), shards
    assert all(s["datagrams_received"] > 0 for s in shards), shards
    assert sum(s["results_accepted"] for s in shards) > 0, shards


def test_two_loop_crash_drill_exactly_once():
    """kill -9 a 2-loop coordinator mid-burst (single-writer journal),
    restart it with 2 loops on the same port: every submitted request
    answered exactly once. Runs under the runtime loop-affinity race
    detector (ISSUE 9): every coordinator/journal/replication mutation
    across the burst, kill, and recovery is checked against its owning
    loop, and one cross-loop write fails the drill."""
    metrics = run(
        loadgen.run_crash(
            16, 2, pre=1.0, post=2.0, loops=2, loop_affinity=True
        ),
        timeout=120.0,
    )
    assert loadgen.crash_check(metrics) == [], metrics
    assert metrics["answers_duplicated"] == 0
    assert metrics["answers_lost"] == 0
    assert metrics["loops"] == 2
    assert metrics["affinity_violations"] == 0, metrics["affinity_sample"]


def test_two_loop_crash_drill_segments_mode():
    """Same drill on per-loop WAL segments: recovery reassembles the
    segments into one coherent state (the journal-seam alternative)."""
    metrics = run(
        loadgen.run_crash(
            16, 2, pre=1.0, post=2.0, loops=2, journal_mode="segments"
        ),
        timeout=120.0,
    )
    assert loadgen.crash_check(metrics) == [], metrics
    assert metrics["answers_duplicated"] == 0
    assert metrics["answers_lost"] == 0


def test_two_loop_failover_drill_exactly_once():
    """Machine-loss failover of a SHARDED primary: the 2-loop
    coordinator ships one coherent WAL stream; the standby promotes
    fenced and the fleet lands — exactly-once across the loss."""
    metrics = run(
        loadgen.run_failover(16, 2, pre=1.2, post=2.0, loops=2),
        timeout=120.0,
    )
    assert loadgen.failover_check(metrics) == [], metrics
    assert metrics["answers_duplicated"] == 0
    assert metrics["answers_lost"] == 0
    assert metrics["loops"] == 2
    assert metrics["replicated_records_pre_kill"] > 0
