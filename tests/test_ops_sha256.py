"""Device-op equivalence tests (SURVEY.md §4 rebuild plan (c)): the jnp
SHA-256 path must match hashlib / chain.py exactly, on the CPU backend,
including unaligned and block-straddling nonce placements."""

import hashlib
import struct

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuminter import chain
from tpuminter.ops import (
    compress,
    digest_to_int,
    double_sha256_header_batch,
    hash_words_be,
    header_template,
    lex_argmin,
    lex_le,
    sha256_batch,
    target_to_words,
    toy_template,
)


def words(b: bytes) -> np.ndarray:
    return np.frombuffer(b, dtype=">u4").astype(np.uint32)


def test_compress_matches_chain_reference():
    rng = np.random.default_rng(0)
    block = rng.integers(0, 256, 64, dtype=np.uint8).tobytes()
    want = chain.sha256_compress(chain.SHA256_H0, block)
    got = compress(
        jnp.asarray(np.array(chain.SHA256_H0, dtype=np.uint32)),
        jnp.asarray(words(block)),
    )
    assert tuple(int(w) for w in got) == want


def test_single_block_sha256_matches_hashlib():
    # 55-byte message fits one padded block; midstate is H0, whole message
    # is "tail". Exercise via toy_template with zero-length hole trickery:
    # data of 47 bytes → message = data + 8 nonce bytes = 55.
    data = b"x" * 47
    tmpl = toy_template(data)
    nonce = 0x0123456789ABCDEF
    got = sha256_batch(
        tmpl,
        jnp.asarray(np.array([nonce >> 32], dtype=np.uint32)),
        jnp.asarray(np.array([nonce & 0xFFFFFFFF], dtype=np.uint32)),
    )
    want = hashlib.sha256(data + struct.pack(">Q", nonce)).digest()
    assert bytes(np.asarray(got[0]).astype(">u4").tobytes()) == want


@pytest.mark.parametrize("data_len", [0, 1, 3, 20, 47, 48, 55, 56, 63, 64, 100, 119, 120, 200])
def test_toy_template_all_alignments(data_len):
    """Nonce placement sweeps every alignment class: unaligned starts,
    block-straddling, and multi-block prefixes."""
    rng = np.random.default_rng(data_len)
    data = rng.integers(0, 256, data_len, dtype=np.uint8).tobytes()
    tmpl = toy_template(data)
    nonces = [0, 1, 0xFFFFFFFF, 0x1_0000_0000, 0xDEADBEEF_CAFEBABE, 2**64 - 1]
    hi = jnp.asarray(np.array([n >> 32 for n in nonces], dtype=np.uint32))
    lo = jnp.asarray(np.array([n & 0xFFFFFFFF for n in nonces], dtype=np.uint32))
    got = np.asarray(sha256_batch(tmpl, hi, lo))
    for i, n in enumerate(nonces):
        want = hashlib.sha256(data + struct.pack(">Q", n)).digest()
        assert got[i].astype(">u4").tobytes() == want, f"nonce {n:#x}"
        # and the toy fold (top 64 bits) matches chain.toy_hash
        fold = (int(got[i][0]) << 32) | int(got[i][1])
        assert fold == chain.toy_hash(data, n)


def test_header_template_genesis_block():
    tmpl = header_template(chain.GENESIS_HEADER.pack())
    nonces = jnp.asarray(
        np.array([chain.GENESIS_HEADER.nonce, 0, 12345], dtype=np.uint32)
    )
    got = np.asarray(double_sha256_header_batch(tmpl, nonces))
    assert digest_to_int(got[0]) == chain.GENESIS_HEADER.block_hash_int()
    assert (
        got[0].astype(">u4").tobytes()[::-1].hex() == chain.GENESIS_HASH_HEX
    )
    for i, n in enumerate([chain.GENESIS_HEADER.nonce, 0, 12345]):
        want = chain.GENESIS_HEADER.with_nonce(n).block_hash()
        assert got[i].astype(">u4").tobytes() == want


def test_header_template_random_headers():
    rng = np.random.default_rng(7)
    for _ in range(5):
        raw = rng.integers(0, 256, 80, dtype=np.uint8).tobytes()
        tmpl = header_template(raw)
        nonces_np = rng.integers(0, 2**32, 8, dtype=np.uint32)
        got = np.asarray(double_sha256_header_batch(tmpl, jnp.asarray(nonces_np)))
        for i, n in enumerate(nonces_np):
            want = chain.dsha256(raw[:76] + struct.pack("<I", int(n)))
            assert got[i].astype(">u4").tobytes() == want


def test_target_compare_matches_int_compare():
    tmpl = header_template(chain.GENESIS_HEADER.pack())
    rng = np.random.default_rng(3)
    nonces_np = rng.integers(0, 2**32, 64, dtype=np.uint32)
    digests = double_sha256_header_batch(tmpl, jnp.asarray(nonces_np))
    hw = hash_words_be(digests)
    for target in [chain.bits_to_target(0x1D00FFFF), (1 << 252) - 1, 1 << 255]:
        ok = np.asarray(lex_le(hw, jnp.asarray(target_to_words(target))))
        for i, n in enumerate(nonces_np):
            h = chain.hash_to_int(
                chain.dsha256(
                    chain.GENESIS_HEADER.pack()[:76] + struct.pack("<I", int(n))
                )
            )
            assert bool(ok[i]) == (h <= target)


def test_lex_argmin_matches_python_min():
    rng = np.random.default_rng(11)
    # include duplicate rows to exercise tie-breaking to lowest index
    rows = rng.integers(0, 4, (32, 8), dtype=np.uint32)
    idx = int(lex_argmin(jnp.asarray(rows)))
    want = min(range(32), key=lambda i: (tuple(rows[i]), i))
    assert idx == want


def test_template_is_jit_cache_key():
    """Templates hash/eq by value, so jit(static_argnums) caching works."""
    t1 = toy_template(b"abc")
    t2 = toy_template(b"abc")
    assert t1 == t2 and hash(t1) == hash(t2)
    calls = []

    @jax.jit
    def step(lo):
        calls.append(1)
        return sha256_batch(t1, jnp.zeros_like(lo), lo)

    step(jnp.zeros(4, dtype=jnp.uint32))
    step(jnp.ones(4, dtype=jnp.uint32))
    assert len(calls) == 1  # traced once


def test_e60_e61_early_reject_matches_full_digest():
    """The candidate kernel's truncated second compression
    (sym.double_sha256_e60_e61) must agree with the full digest path on
    words 7 and 6 — the whole soundness argument of the ≥1 GH/s search
    (a candidate test that missed a winner would silently drop blocks).
    Includes the genesis winner, whose digest word 7 is 0."""
    from tpuminter.ops import symbolic as sym

    template = header_template(chain.GENESIS_HEADER.pack())
    rng = np.random.default_rng(7)
    nonces = np.concatenate(
        [[chain.GENESIS_HEADER.nonce], rng.integers(0, 2**32, 1024)]
    ).astype(np.uint32)
    nj = jnp.asarray(nonces)
    e60, e61 = sym.double_sha256_e60_e61(template, 0, nj)
    digests = np.asarray(double_sha256_header_batch(template, nj))
    cand = np.asarray(e60) == np.uint32(sym.CAND_E60)
    assert (cand == (digests[:, 7] == 0)).all()
    assert cand[0]  # genesis IS a candidate
    d6 = (np.uint32(sym.DIGEST6_BIAS) + np.asarray(e61)).astype(np.uint32)
    assert (d6 == digests[:, 6]).all()


def test_e60_e61_scalar_constant_folds_to_chain():
    """With constant nonces the truncated compress folds entirely at
    trace time; pin it against chain.dsha256's digest words."""
    from tpuminter.ops import symbolic as sym

    template = header_template(chain.GENESIS_HEADER.pack())
    for nonce in (0, 1, chain.GENESIS_HEADER.nonce, 0xFFFFFFFF):
        e60, e61 = sym.double_sha256_e60_e61(template, 0, nonce)
        assert isinstance(e60, int) and isinstance(e61, int)
        digest = chain.dsha256(
            chain.GENESIS_HEADER.with_nonce(nonce).pack()
        )
        w7, w6 = struct.unpack(">8I", digest)[7], struct.unpack(">8I", digest)[6]
        assert (sym.CAND_E60 == e60) == (w7 == 0)
        assert (sym.DIGEST6_BIAS + e61) & 0xFFFFFFFF == w6


def test_e60_e61_op_count_stays_at_the_partial_eval_floor():
    """PERF.md: the candidate test traces to 5,939 ops per nonce batch —
    the structural floor of the 61+61 variable SHA rounds after symbolic
    partial evaluation (midstate, constant early rounds, K+W folds). CI
    cannot measure GH/s, but it can catch a folding regression: if this
    count creeps up, the kernel slows proportionally on hardware. A 3%
    headroom absorbs jax-version tracing drift; raise the bound only
    with a measured bench justifying it."""
    import jax
    import jax.numpy as jnp

    from tpuminter import chain
    from tpuminter.ops import sha256 as ops
    from tpuminter.ops import symbolic as sym

    tmpl = ops.header_template(chain.GENESIS_HEADER.pack())

    def f(nonces):
        return sym.double_sha256_e60_e61(tmpl, jnp.uint32(0), nonces)

    jaxpr = jax.make_jaxpr(f)(jnp.arange(128, dtype=jnp.uint32))

    def count(jx):
        n = 0
        for eq in jx.eqns:
            n += 1
            for sub in eq.params.values():
                # higher-order primitives carry sub-jaxprs either bare
                # (scan/while 'jaxpr') or in sequences (cond 'branches')
                for item in sub if isinstance(sub, (tuple, list)) else (sub,):
                    if hasattr(item, "jaxpr"):
                        n += count(item.jaxpr)
        return n

    n = count(jaxpr.jaxpr)
    assert n <= int(5939 * 1.03), (
        f"symbolic partial evaluation regressed: {n} ops (floor 5939) — "
        "the Pallas kernel's throughput scales with this count"
    )
