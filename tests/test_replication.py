"""Replicated-coordinator tests (ISSUE 5's fault-injection layer).

Same strata as tests/test_recovery.py:

- **Pure shipping-stream properties** (deterministic seeded drives;
  hypothesis mirrors live in tests/test_properties.py, absent in this
  image): a truncated/corrupted shipped batch only ever loses a
  suffix — the standby applies an exact record prefix, never different
  records — and incremental shadow apply equals full replay.
- **Shipping runtime**: primary→standby WAL shipping builds a shadow
  equal to replaying the primary's file; a standby restart resumes
  from its durable cursor and replays no record twice; the serve-tick
  journal flusher writes what the task flusher wrote.
- **Failover e2e**: the fencing regression (a restarted old primary's
  datagram draws RESET and its connection is declared lost — alongside
  test_recovery.py's fresh-session pin), the replica-ack gate, the
  SlowMiner failover drill (kill the primary machine mid-job; the
  promoted standby answers both bound clients exactly once with
  brute-force-equal results), and the loadgen failover scenario's
  tier-1 gate.
"""

import asyncio
import os
import random
import sys
import time

import pytest

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ),
)

import loadgen  # noqa: E402  (scripts/ is not a package)

from tpuminter.client import submit  # noqa: E402
from tpuminter.coordinator import Coordinator  # noqa: E402
from tpuminter.journal import (  # noqa: E402
    Journal,
    RecoveredState,
    encode_record,
    encode_settle,
    frame_payload,
    replay,
    scan,
    scan_with_cursor,
)
from tpuminter.protocol import (  # noqa: E402
    PowMode,
    Request,
    request_to_obj,
)
from tpuminter.replication import (  # noqa: E402
    FENCE_JUMP,
    ReplicationPrimary,
    ReplicationStandby,
    gate_any,
    parse_addr_list,
)
from tpuminter.worker import run_miner_reconnect  # noqa: E402

from tests.test_e2e import FAST, brute_min, run  # noqa: E402
from tests.test_recovery import SlowMiner  # noqa: E402


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _req_obj(jid, upper=4095, ckey=""):
    return request_to_obj(Request(
        job_id=jid, mode=PowMode.MIN, lower=0, upper=upper,
        data=b"rep-%d" % jid, client_key=ckey,
    ))


def _record_stream(rng, n=30):
    """A plausible journal byte stream: jobs, packed settles, finishes
    (ground-truth records come back out via ``scan``)."""
    blobs = []
    for jid in range(1, n + 1):
        blobs.append(encode_record({"k": "job", "id": jid,
                                    "req": _req_obj(jid)}))
        lo = rng.randrange(0, 2048)
        blobs.append(frame_payload(
            encode_settle(jid, lo, lo + 511, lo, 512, rng.randrange(2**64))
        ))
        if rng.random() < 0.3:
            blobs.append(encode_record(
                {"k": "finish", "id": jid, "ckey": f"c{jid}", "cjid": jid,
                 "mode": "min", "n": lo, "h": "ab", "found": True, "s": 512}
            ))
    return b"".join(blobs)


async def _drain(coro_or_task):
    coro_or_task.cancel()
    await asyncio.gather(coro_or_task, return_exceptions=True)


# ---------------------------------------------------------------------------
# pure shipping-stream properties (deterministic; hypothesis mirrors in
# tests/test_properties.py)
# ---------------------------------------------------------------------------

def test_corrupted_shipped_batch_applies_only_an_exact_prefix():
    """A single-byte flip anywhere in a shipped batch may end the
    readable stream, but what DOES decode must be an exact record
    prefix of the original — corruption can only look like loss of a
    suffix, never like different records (the property the standby's
    ingestion leans on before touching its shadow state)."""
    rng = random.Random(0x5EED)
    for trial in range(40):
        stream = _record_stream(rng, n=rng.randrange(2, 12))
        clean_records, clean = scan(stream)
        assert clean == len(stream)
        wire = bytearray(stream)
        i = rng.randrange(len(wire))
        wire[i] ^= rng.randrange(1, 256)
        got, got_clean, _last = scan_with_cursor(bytes(wire))
        assert got_clean <= clean
        assert got == clean_records[: len(got)], (
            f"trial {trial}: flip at {i} produced records that are not "
            f"an exact prefix"
        )


def test_truncated_shipped_batch_applies_only_an_exact_prefix():
    rng = random.Random(0xCAFE)
    for _ in range(40):
        stream = _record_stream(rng, n=rng.randrange(2, 12))
        clean_records, _ = scan(stream)
        keep = rng.randrange(len(stream))
        got, got_clean, _last = scan_with_cursor(stream[:keep])
        assert got_clean <= keep
        assert got == clean_records[: len(got)]


def test_incremental_shadow_apply_equals_full_replay():
    """The standby applies records batch-by-batch as they arrive; the
    result must equal replaying the whole stream at once, however the
    batch boundaries fall (including mid-record splits, which the
    contiguity check re-ships)."""
    rng = random.Random(7)
    for _ in range(20):
        stream = _record_stream(rng, n=rng.randrange(3, 15))
        records, _ = scan(stream)
        shadow = RecoveredState()
        i = 0
        while i < len(records):
            step = rng.randrange(1, 5)
            for rec in records[i : i + step]:
                shadow.apply(rec)
            i += step
        full = replay(records)
        assert shadow.jobs.keys() == full.jobs.keys()
        for jid, job in full.jobs.items():
            assert shadow.jobs[jid].remaining == job.remaining
            assert shadow.jobs[jid].best == job.best
        assert shadow.winners == full.winners
        assert shadow.next_job_id == full.next_job_id


# ---------------------------------------------------------------------------
# shipping runtime
# ---------------------------------------------------------------------------

def test_shipping_builds_a_shadow_equal_to_replaying_the_primary(tmp_path):
    pwal = str(tmp_path / "p.wal")
    swal = str(tmp_path / "s.wal")

    async def scenario():
        journal, _ = Journal.open(pwal)
        standby = await ReplicationStandby.create(swal, params=FAST)
        runner = asyncio.ensure_future(standby.run())
        prim = ReplicationPrimary(journal, "127.0.0.1", standby.port,
                                  params=FAST)
        prim.start()
        for jid in range(1, 40):
            journal.append("job", {"id": jid, "req": _req_obj(jid)})
        await journal.flush()
        t0 = time.monotonic()
        while standby.size < journal.size:
            assert time.monotonic() - t0 < 15, "shipping stalled"
            await asyncio.sleep(0.02)
        with open(pwal, "rb") as fh:
            records, clean = scan(fh.read())
        full = replay(records)
        assert standby.shadow.jobs.keys() == full.jobs.keys()
        assert standby.size == clean == journal.size
        # the local copy is byte-identical to the primary's clean prefix
        with open(swal, "rb") as fh:
            assert scan(fh.read())[1] == clean
        assert prim.synced and prim.acked == journal.size
        await prim.stop()
        await _drain(runner)
        await standby.close()
        await journal.aclose()

    run(scenario(), timeout=30.0)


def test_cursor_resume_after_standby_restart_replays_no_record_twice(
    tmp_path,
):
    """Kill the standby, restart it over the same local WAL: its
    SyncFrom cursor resumes the stream exactly where it stopped — the
    primary ships only the missed tail (no resync-from-0, no record
    applied twice), pinned by the applied-record count."""
    pwal = str(tmp_path / "p.wal")
    swal = str(tmp_path / "s.wal")

    async def scenario():
        journal, _ = Journal.open(pwal)
        standby = await ReplicationStandby.create(swal, params=FAST)
        runner = asyncio.ensure_future(standby.run())
        prim = ReplicationPrimary(journal, "127.0.0.1", standby.port,
                                  params=FAST)
        prim.start()
        for jid in range(1, 21):
            journal.append("job", {"id": jid, "req": _req_obj(jid)})
        await journal.flush()
        t0 = time.monotonic()
        while standby.size < journal.size:
            assert time.monotonic() - t0 < 15
            await asyncio.sleep(0.02)
        # -- standby dies --------------------------------------------------
        await prim.stop()
        await _drain(runner)
        await standby.close()
        # -- restart over the same file ------------------------------------
        standby2 = await ReplicationStandby.create(swal, params=FAST)
        applied_from_file = standby2.stats["records_applied"]
        runner2 = asyncio.ensure_future(standby2.run())
        prim2 = ReplicationPrimary(journal, "127.0.0.1", standby2.port,
                                   params=FAST)
        prim2.start()
        for jid in range(21, 31):
            journal.append("job", {"id": jid, "req": _req_obj(jid)})
        await journal.flush()
        t0 = time.monotonic()
        while standby2.size < journal.size:
            assert time.monotonic() - t0 < 15
            await asyncio.sleep(0.02)
        assert prim2.stats["resyncs"] == 0, (
            "a valid cursor must resume, not restart the stream"
        )
        shipped_new = standby2.stats["records_applied"] - applied_from_file
        assert shipped_new == 10, (
            f"exactly the 10 missed records must ship, got {shipped_new}"
        )
        with open(pwal, "rb") as fh:
            full = replay(scan(fh.read())[0])
        assert standby2.shadow.jobs.keys() == full.jobs.keys()
        await prim2.stop()
        await _drain(runner2)
        await standby2.close()
        await journal.aclose()

    run(scenario(), timeout=30.0)


def test_journal_flush_tick_writes_and_fires_durable_callbacks(tmp_path):
    """The serve-tick flusher (PERF.md §Round 10): with tick_flush on,
    nothing hits the disk until flush_tick (or the fallback timer)
    runs; callback-free batches write inline, durable batches still
    fsync and fire on_durable; the reopened journal replays
    identically to the task-flusher path."""
    path = str(tmp_path / "tick.wal")

    async def scenario():
        journal, _ = Journal.open(path)
        journal.tick_flush = True
        journal.append("job", {"id": 1, "req": _req_obj(1)})
        assert journal._buffer  # buffered, not yet written
        journal.flush_tick()
        assert not journal._buffer
        fired = []
        journal.append(
            "finish",
            {"id": 1, "ckey": "c", "cjid": 1, "mode": "min", "n": 3,
             "h": "ab", "found": True, "s": 4096},
            on_durable=lambda: fired.append(True),
        )
        journal.flush_tick()  # durable tier: task path + fsync
        await journal.flush()
        assert fired == [True]
        # the fallback timer covers appends with no serve tick behind
        # them (offloaded-verification settles)
        journal.append("abandon", {"id": 1})
        t0 = time.monotonic()
        while journal._buffer:
            assert time.monotonic() - t0 < 2.0, "fallback timer never fired"
            await asyncio.sleep(0.005)
        await journal.aclose()
        _journal2, state = Journal.open(path)
        await _journal2.aclose()
        assert state.finished == {1}
        assert ("c", 1) in state.winners

    run(scenario(), timeout=15.0)


def test_replica_ack_gate_parks_until_acked(tmp_path):
    """The replica-acked durability tier: with a synced standby the
    callback parks until the ack high-water passes the target; with no
    synced standby it fires immediately (availability over replica
    durability)."""
    pwal = str(tmp_path / "p.wal")

    async def scenario():
        journal, _ = Journal.open(pwal)
        prim = ReplicationPrimary(journal, "127.0.0.1", 1, params=FAST)
        fired = []
        # no synced session: release immediately
        gate_any([prim], 100, lambda: fired.append("now"))
        assert fired == ["now"]
        # synced session, ack behind the target: park, then release on
        # ack (_shipped bounds plausible acks — a real stream never
        # acks bytes it was not sent)
        prim.synced = True
        prim._shipped = 1000
        prim.acked = 50
        gate_any([prim], 100, lambda: fired.append("later"))
        assert fired == ["now"]
        prim._on_ack(99)
        assert fired == ["now"]
        prim._on_ack(100)
        assert fired == ["now", "later"]
        # session loss releases parked callbacks rather than wedging
        gate_any([prim], 500, lambda: fired.append("released"))
        prim._fire_gates("test teardown")
        assert fired == ["now", "later", "released"]
        await journal.aclose()

    run(scenario(), timeout=10.0)


def test_replica_ack_gate_survives_compaction_space_change(tmp_path):
    """A compaction swaps the journal's offset space (generation bump,
    size reset to the boot+snapshot length). Three hazards around the
    replica-ack tier, each pinned: a gate placed after the swap must
    not be released by the OLD space's ack high water; a stale
    old-space SyncAck arriving after the stream's resync must not
    poison the new space; and a gate placed before the swap re-bases
    to the end of the compacted file (the snapshot covers its record)
    instead of wedging behind an old-space byte target."""
    pwal = str(tmp_path / "p.wal")

    async def scenario():
        journal, state = Journal.open(pwal, compact_bytes=512, fsync=False)
        journal.snapshot_provider = lambda: state.snapshot_obj()
        prim = ReplicationPrimary(journal, "127.0.0.1", 1, params=FAST)
        fired = []
        # a synced stream that has shipped + acked the whole file
        prim.synced = True
        prim._shipped = journal.size
        prim.acked = journal.size
        # park a gate just past the ack high water, then drive a REAL
        # compaction underneath it
        gate_any([prim], journal.size + 1, lambda: fired.append("pre"))
        assert fired == []
        state.apply({"k": "job", "id": 1, "req": _req_obj(1)})
        journal.append("job", {"id": 1, "req": _req_obj(1)})
        for i in range(40):
            rec = {"k": "settle", "id": 1, "lo": 100 * i,
                   "hi": 100 * i + 49, "h": "ff", "n": 100 * i, "s": 50}
            state.apply(rec)
            journal.append("settle", dict(rec))
            await asyncio.sleep(0)
        await journal.flush()
        assert journal.stats["compactions"] >= 1
        assert journal.generation >= 1
        # (1) the journal moved ahead of the stream: a gate for the NEW
        # space must not be released by the old space's big ack value
        gate_any([prim], journal.size, lambda: fired.append("post"))
        assert fired == []
        # the shipping session notices the generation change (the real
        # resync path) ...
        prim._switch_generation()
        assert prim.acked == 0
        # (2) ... so a stale old-space ack arriving late is ignored
        prim._on_ack(10 ** 6)
        assert prim.acked == 0 and fired == []
        # (3) new-space acks release BOTH gates once the standby holds
        # the compacted file: the pre-compaction gate re-based to its
        # end rather than wedging at old-space byte `size + 1`
        prim._shipped = journal.size
        prim._on_ack(journal.size)
        assert sorted(fired) == ["post", "pre"]
        await journal.aclose()

    run(scenario(), timeout=10.0)


# ---------------------------------------------------------------------------
# fencing: the machine-loss sibling of test_recovery.py's
# test_server_restart_mid_connection_is_a_fresh_session
# ---------------------------------------------------------------------------

def test_restarted_old_primary_draws_reset_and_cannot_corrupt(tmp_path):
    """The acceptance regression: after failover, the OLD primary
    restarts from its own journal (epoch +1) and tries to resume
    shipping to the promoted standby. The promoted coordinator — whose
    epoch jumped FENCE_JUMP ahead — rejects the hello; the zombie's
    next datagram draws a RESET epoch-ack, its connection is declared
    lost, its shipping lane marks itself fenced, and the promoted
    state is untouched."""
    pwal = str(tmp_path / "p.wal")
    swal = str(tmp_path / "s.wal")

    async def scenario():
        standby = await ReplicationStandby.create(swal, params=FAST)
        runner = asyncio.ensure_future(standby.run())
        coord = await Coordinator.create(
            params=FAST, recover_from=pwal,
            replicate_to=[("127.0.0.1", standby.port)],
        )
        old_epoch = coord.boot_epoch
        serve = asyncio.ensure_future(coord.serve())
        # one journaled job so the promoted shadow is non-trivial
        journal = coord._journal
        journal.append("job", {"id": 1, "req": _req_obj(1, ckey="ck")})
        await journal.flush()
        t0 = time.monotonic()
        while standby.stats["records_applied"] < 2:  # boot + job
            assert time.monotonic() - t0 < 15, "shipping never started"
            await asyncio.sleep(0.02)
        # -- the primary machine dies -----------------------------------
        await _drain(serve)
        coord.crash()
        await asyncio.wait_for(
            standby.primary_lost.wait(),
            20 * FAST.epoch_limit * FAST.epoch_seconds,
        )
        coord2 = await standby.promote()
        assert coord2.boot_epoch >= old_epoch + FENCE_JUMP
        serve2 = asyncio.ensure_future(coord2.serve())
        jobs_before = set(coord2._jobs)
        # -- the old primary restarts and tries to resume its old role --
        zombie = await Coordinator.create(
            params=FAST, recover_from=pwal,
            replicate_to=[("127.0.0.1", coord2.port)],
        )
        assert zombie.boot_epoch == old_epoch + 1  # its own lineage
        serve3 = asyncio.ensure_future(zombie.serve())
        lane = zombie._replicas[0]
        t0 = time.monotonic()
        while not lane.fenced:
            assert time.monotonic() - t0 < 20, "zombie never fenced"
            await asyncio.sleep(0.05)
        # the loss was the RESET path, not a silence timeout
        assert "reset ack" in (lane.last_loss_reason or "") or (
            "restarted" in (lane.last_loss_reason or "")
        )
        assert coord2.stats["replication_fenced"] >= 1
        assert set(coord2._jobs) == jobs_before  # nothing corrupted
        await _drain(serve3)
        await _drain(serve2)
        await _drain(runner)
        await zombie.close()
        await coord2.close()

    run(scenario(), timeout=60.0)


# ---------------------------------------------------------------------------
# failover e2e (the acceptance drill, SlowMiner edition)
# ---------------------------------------------------------------------------

def test_failover_exactly_once_with_bound_clients(tmp_path):
    """Kill the primary MACHINE (its journal is never re-read) with a
    SlowMiner fleet and two bound clients mid-job; the standby promotes
    and the address-listed fleet lands on it — both clients get exactly
    one answer each, equal to brute force: no acknowledged work lost,
    no duplicate mining, zero manual intervention."""
    pwal = str(tmp_path / "p.wal")
    swal = str(tmp_path / "s.wal")
    upper = 8191
    payloads = [b"failover-client-a", b"failover-client-b"]

    async def scenario():
        standby = await ReplicationStandby.create(swal, params=FAST)
        runner = asyncio.ensure_future(standby.run())
        coord = await Coordinator.create(
            params=FAST, chunk_size=512, recover_from=pwal,
            replicate_to=[("127.0.0.1", standby.port)], replica_ack=True,
        )
        ports = [("127.0.0.1", coord.port), ("127.0.0.1", standby.port)]
        serve = asyncio.ensure_future(coord.serve())
        miners = [
            asyncio.ensure_future(run_miner_reconnect(
                "", 0, SlowMiner(), params=FAST, addrs=ports,
                base_backoff=0.05, max_backoff=0.4,
                rng=random.Random(100 + i),
            ))
            for i in range(3)
        ]
        await asyncio.sleep(0.2)
        subs = [
            asyncio.ensure_future(submit(
                "", 0, Request(job_id=70 + i, mode=PowMode.MIN, lower=0,
                               upper=upper, data=payloads[i]),
                params=FAST, client_key=f"failover-client-{i}",
                reconnect=True, base_backoff=0.05,
                rng=random.Random(i), addrs=ports,
            ))
            for i in range(2)
        ]
        serve2 = None
        try:
            t0 = time.monotonic()
            while coord.stats["results_accepted"] < 4:
                assert time.monotonic() - t0 < 20, "no progress pre-crash"
                await asyncio.sleep(0.01)
            assert coord.stats["jobs_done"] == 0, (
                "crash must land mid-job; slow the miners down"
            )
            # settles must actually have shipped (machine loss forgives
            # only the in-flight tail)
            t0 = time.monotonic()
            while standby.stats["records_applied"] < 4:
                assert time.monotonic() - t0 < 10, "shipping lagged"
                await asyncio.sleep(0.01)
            # -- the primary machine dies, journal and all ---------------
            await _drain(serve)
            coord.crash()
            await asyncio.wait_for(
                standby.primary_lost.wait(),
                20 * FAST.epoch_limit * FAST.epoch_seconds,
            )
            coord2 = await standby.promote(chunk_size=512)
            assert len(coord2._jobs) == 2, (
                "both mid-flight jobs must be live in the shadow"
            )
            assert sum(
                j.hashes_done for j in coord2._jobs.values()
            ) > 0, "shipped settles must survive into the shadow"
            serve2 = asyncio.ensure_future(coord2.serve())
            # -- the fleet lands on the promoted standby unattended ------
            results = await asyncio.wait_for(asyncio.gather(*subs), 90.0)
            for i, res in enumerate(results):
                expect = brute_min(payloads[i], 0, upper)
                assert (res.hash_value, res.nonce) == expect
                assert res.found
            assert not coord2._jobs  # both retired
        finally:
            for t in miners + subs:
                t.cancel()
            await asyncio.gather(*miners, *subs, return_exceptions=True)
            await _drain(runner)
            if serve2 is not None:
                await _drain(serve2)
                await coord2.close()

    run(scenario(), timeout=120.0)


# ---------------------------------------------------------------------------
# the loadgen failover scenario is the tier-1 gate (CI satellite)
# ---------------------------------------------------------------------------

def test_loadgen_failover_scenario_smoke(capsys):
    """`loadgen --scenario failover --smoke`: in-process primary kill +
    standby promotion under load must produce an exactly-once ledger
    and a takeover under one loss horizon — the replication sibling of
    the crash smoke gate."""
    rc = loadgen.main([
        "--scenario", "failover", "--smoke", "--json",
        "--miners", "6", "--duration", "1.5",
    ])
    out = capsys.readouterr()
    assert rc == 0, f"failover smoke failed:\n{out.out}\n{out.err}"


def test_parse_addr_list():
    assert parse_addr_list("a:1,b:2") == [("a", 1), ("b", 2)]
    assert parse_addr_list(":9000") == [("127.0.0.1", 9000)]
    with pytest.raises(ValueError):
        parse_addr_list(",")


def test_quota_buckets_survive_failover_promotion(tmp_path):
    """ISSUE 19 satellite: the ``quota`` record rides the replication
    WAL stream like every other append, so a promoted standby restores
    tenant budgets instead of resetting them — losing the primary
    MACHINE (its journal is never re-read) must not hand every tenant
    a fresh burst."""
    from tpuminter.journal import scan_file
    from tpuminter.lsp import LspClient
    from tpuminter.protocol import encode_msg

    pwal = str(tmp_path / "p.wal")
    swal = str(tmp_path / "s.wal")

    async def scenario():
        standby = await ReplicationStandby.create(swal, params=FAST)
        runner = asyncio.ensure_future(standby.run())
        coord = await Coordinator.create(
            params=FAST, chunk_size=512, recover_from=pwal,
            replicate_to=[("127.0.0.1", standby.port)], replica_ack=True,
            quota_rate=0.001, quota_burst=6,
        )
        serve = asyncio.ensure_future(coord.serve())
        coord2 = None
        client = None
        try:
            # no miners: this drill is about the admission ledger, the
            # submitted jobs just queue in the shadow
            client = await LspClient.connect(
                "127.0.0.1", coord.port, FAST
            )
            for jid in range(1, 5):
                client.write(encode_msg(Request(
                    job_id=jid, mode=PowMode.MIN, lower=0, upper=4095,
                    data=b"failover-quota-%d" % jid,
                    client_key="tenant-f",
                )))
            t0 = time.monotonic()
            while len(coord._jobs) < 4:
                assert time.monotonic() - t0 < 10, "submissions lost"
                await asyncio.sleep(0.01)
            tok, _, strikes = coord._buckets["tenant-f"]
            assert tok == pytest.approx(2.0, abs=0.01)
            coord._journal_quota()
            # the record must have SHIPPED (landed in the standby's
            # local WAL) before the machine dies — machine loss only
            # forgives the in-flight tail
            t0 = time.monotonic()
            while not replay(scan_file(swal)).quota:
                assert time.monotonic() - t0 < 10, "quota never shipped"
                await asyncio.sleep(0.02)
            # -- the primary machine dies, journal and all ---------------
            await _drain(serve)
            coord.crash()
            await asyncio.wait_for(
                standby.primary_lost.wait(),
                20 * FAST.epoch_limit * FAST.epoch_seconds,
            )
            coord2 = await standby.promote(
                quota_rate=0.001, quota_burst=6
            )
            assert "tenant-f" in coord2._buckets, (
                "the tenant's bucket must survive into the promotion"
            )
            tok2, _, strikes2 = coord2._buckets["tenant-f"]
            assert tok2 == pytest.approx(tok, abs=0.01)
            assert strikes2 == strikes
        finally:
            if client is not None:
                await client.close(drain_timeout=0.1)
            await _drain(runner)
            await _drain(serve)
            if coord2 is not None:
                await coord2.close()

    run(scenario(), timeout=90.0)
