"""Chaos-plan unit + seam tests (ISSUE 12).

Covers the pieces underneath ``loadgen --scenario chaos`` one layer at
a time, so a matrix failure localizes:

- :class:`tpuminter.chaos.FaultPlan` semantics in isolation — partition
  windows and heal, per-direction and per-peer matching with
  most-specific-wins, verdict shapes, determinism from the seed;
- the transport seam: a plan installed on a live ``UdpEndpoint``
  actually blacks out / duplicates datagrams and books the counters;
- :class:`tpuminter.chaos.DiskFaultPlan` through ``Journal._write_sync``:
  a torn-tail write is truncated by the next ``Journal.open`` scan, a
  one-shot ENOSPC trips the loud availability-over-durability path
  (callbacks still fire), an fsync stall flips the sticky slow-fsync
  executor fallback without killing the journal;
- ``lsp.params.jittered_backoff`` properties, deterministically (the
  hypothesis variants live in test_properties.py and only run where
  hypothesis is installed): jitter bounds, the cap ceiling, and that
  all four production redial loops respect the ceiling under a long
  total partition (every dial refused).
"""

import asyncio
import os
import random
import sys

import pytest

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ),
)

import loadgen  # noqa: E402  (scripts/ is not a package)

from tpuminter.chaos import (  # noqa: E402
    DELIVER,
    DROP,
    DiskFaultPlan,
    FaultPlan,
)
from tpuminter.client import submit  # noqa: E402
from tpuminter.journal import Journal, scan  # noqa: E402
from tpuminter.lsp import LspClient, LspConnectError  # noqa: E402
from tpuminter.lsp.params import FAST, jittered_backoff  # noqa: E402
from tpuminter.lsp.transport import UdpEndpoint  # noqa: E402
from tpuminter.protocol import PowMode, Request  # noqa: E402
from tpuminter.worker import CpuMiner, run_miner_reconnect  # noqa: E402


def run(coro, timeout=30.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


A1 = ("127.0.0.1", 9401)
A2 = ("127.0.0.1", 9402)


# ---------------------------------------------------------------------------
# FaultPlan semantics (pure: no sockets, no clock — `now` injected)
# ---------------------------------------------------------------------------

def test_partition_window_and_heal():
    plan = FaultPlan(0).partition(peer=9401, start=1.0, duration=2.0)
    plan.arm(now=100.0)
    # before the window opens the link is clean
    assert not plan.partitioned("in", A1, now=100.5)
    assert plan.decide("in", A1, now=100.5) is None
    # inside the window: total blackout, both directions by default
    assert plan.decide("in", A1, now=101.5) == (DROP, "partition")
    assert plan.decide("out", A1, now=102.9) == (DROP, "partition")
    assert plan.stats["partitioned"] == 2
    # the window closes on its own
    assert plan.decide("in", A1, now=103.1) is None
    # heal() ends an open-ended partition early
    plan2 = FaultPlan(0).partition(peer=9401)  # duration=None: no self-heal
    plan2.arm(now=0.0)
    assert plan2.decide("in", A1, now=1e6) == (DROP, "partition")
    plan2.heal()
    assert plan2.decide("in", A1, now=1e6) is None


def test_partition_direction_and_peer_matching():
    plan = FaultPlan(0).partition(peer=9401, direction="in")
    plan.arm(now=0.0)
    # matched peer, matched direction only
    assert plan.decide("in", A1, now=1.0) == (DROP, "partition")
    assert plan.decide("out", A1, now=1.0) is None
    # other peers unaffected (bare-port spec matches any host)
    assert plan.decide("in", A2, now=1.0) is None
    assert plan.partitioned("in", ("10.0.0.9", 9401), now=1.0)


def test_rule_specificity_most_specific_wins():
    plan = (
        FaultPlan(1)
        .link(peer="*", drop=1.0)
        .link(peer=A1, drop=0.0)
    )
    plan.arm(now=0.0)
    # exact-address rule (drop=0) shadows the wildcard for A1 ...
    kind, delays = plan.decide("in", A1)
    assert kind == DELIVER and delays == [0]
    # ... while everyone else eats the wildcard's certain drop
    assert plan.decide("in", A2) == (DROP, "rate")
    assert plan.decide("out", ("10.0.0.9", 1234)) == (DROP, "rate")


def test_no_match_falls_through_to_endpoint_rates():
    plan = FaultPlan(2).link(peer=9999, drop=1.0)
    plan.arm(now=0.0)
    assert plan.decide("in", A1) is None  # port 9401 != 9999
    assert plan.stats["passed"] == 0


def test_verdict_shapes_dup_delay_reorder():
    plan = FaultPlan(3).link(
        peer="*", dup=1.0, reorder=1.0, reorder_delay=0.5,
        delay=0.01, delay_jitter=0.005,
    )
    plan.arm(now=0.0)
    kind, delays = plan.decide("in", A1)
    assert kind == DELIVER
    assert len(delays) == 2  # certain dup: two copies
    for held in delays:
        # delay + U[0, jitter) + certain reorder_delay
        assert 0.51 <= held < 0.515
    assert plan.stats["duplicated"] == 1
    assert plan.stats["delayed"] == 2


def test_plan_is_deterministic_from_seed():
    def drive(plan):
        plan.arm(now=0.0)
        return [
            plan.decide("in" if i % 2 else "out", A1 if i % 3 else A2)
            for i in range(200)
        ]

    mk = lambda s: FaultPlan(s).link(  # noqa: E731
        peer="*", drop=0.2, dup=0.2, reorder=0.2, delay_jitter=0.01
    )
    a, b = drive(mk(42)), drive(mk(42))
    assert a == b
    assert drive(mk(43)) != a  # and the seed actually matters


def test_invalid_specs_rejected_loudly():
    with pytest.raises(ValueError):
        FaultPlan(0).link(peer="*", direction="sideways")
    with pytest.raises(ValueError):
        FaultPlan(0).partition(peer="anyone")  # only "*" as a string
    with pytest.raises((TypeError, ValueError)):
        FaultPlan(0).link(peer=("h", 1, 2))  # not a 2-tuple


# ---------------------------------------------------------------------------
# the transport seam: a plan on a live endpoint
# ---------------------------------------------------------------------------

def test_endpoint_partition_blocks_then_heals():
    async def scenario():
        got = []
        server = await UdpEndpoint.create(
            lambda d, a: got.append(bytes(d)), local_addr=("127.0.0.1", 0)
        )
        sender = await UdpEndpoint.create(lambda d, a: None)
        try:
            addr = server.local_addr
            plan = FaultPlan(7).partition(peer="*", direction="in")
            server.set_fault_plan(plan)
            for i in range(10):
                sender.send(b"x%d" % i, addr)
            await asyncio.sleep(0.1)
            assert got == []
            assert server.partitioned_in == 10
            assert plan.stats["partitioned"] == 10
            plan.heal()
            sender.send(b"after", addr)
            await asyncio.sleep(0.1)
            assert got == [b"after"]
        finally:
            server.close()
            sender.close()
            await server.wait_closed()
            await sender.wait_closed()

    run(scenario())


def test_endpoint_outbound_plan_drops_and_duplicates():
    async def scenario():
        got = []
        server = await UdpEndpoint.create(
            lambda d, a: got.append(bytes(d)), local_addr=("127.0.0.1", 0)
        )
        sender = await UdpEndpoint.create(lambda d, a: None)
        try:
            addr = server.local_addr
            # certain duplication on the way OUT of the sender
            plan = FaultPlan(7).link(peer="*", direction="out", dup=1.0)
            sender.set_fault_plan(plan)
            sender.send(b"twice", addr)
            await asyncio.sleep(0.1)
            assert got == [b"twice", b"twice"]
            # outbound partition: nothing leaves, counter books it
            sender.set_fault_plan(
                FaultPlan(7).partition(peer="*", direction="out")
            )
            sender.send(b"never", addr)
            await asyncio.sleep(0.1)
            assert b"never" not in got
            assert sender.partitioned_out == 1
        finally:
            server.close()
            sender.close()
            await server.wait_closed()
            await sender.wait_closed()

    run(scenario())


# ---------------------------------------------------------------------------
# the disk seam: DiskFaultPlan through Journal._write_sync
# ---------------------------------------------------------------------------

def test_torn_tail_write_truncated_on_reopen(tmp_path):
    path = str(tmp_path / "wal")
    j, _ = Journal.open(path, fsync=False)
    j.append("note", {"v": 1})  # no loop: written through synchronously
    clean_size = j.size
    j.fault_plan = DiskFaultPlan(torn_tail_once=True)
    # the torn write persists half the record then dies like a power cut;
    # with no loop running the append path surfaces the OSError directly
    with pytest.raises(OSError):
        j.append("note", {"v": 2})
    assert j.fault_plan.stats["torn_writes"] == 1
    j.crash()
    with open(path, "rb") as fh:
        data = fh.read()
    assert len(data) > clean_size  # the torn half really hit the disk
    records, clean = scan(data)
    assert clean == clean_size  # scan stops exactly at the clean prefix
    # reopen: the scan truncates the torn tail in place and the journal
    # carries on from a clean file (plus its new boot record)
    j2, state = Journal.open(path, fsync=False)
    assert state.boot_epoch == 2
    with open(path, "rb") as fh:
        data2 = fh.read()
    records2, clean2 = scan(data2)
    assert clean2 == len(data2)  # nothing unreadable remains
    j2.crash()


def test_enospc_trips_loud_undurable_path_but_replies_flow(tmp_path):
    async def scenario():
        j, _ = Journal.open(str(tmp_path / "wal"))
        plan = DiskFaultPlan(enospc_once=True)
        j.fault_plan = plan
        fired = asyncio.Event()
        j.append("note", {"v": 1}, on_durable=fired.set)
        # availability over durability: the reply gate opens even though
        # the write died on the floor
        await asyncio.wait_for(fired.wait(), 5.0)
        assert j._failed
        assert plan.stats["enospc"] == 1
        # later appends short-circuit, but their callbacks still fire —
        # a dead WAL must never wedge a client reply
        fired2 = asyncio.Event()
        j.append("note", {"v": 2}, on_durable=fired2.set)
        assert fired2.is_set()
        j.crash()

    run(scenario())


def test_fsync_stall_flips_sticky_executor_fallback(tmp_path):
    async def scenario():
        j, _ = Journal.open(str(tmp_path / "wal"))
        plan = DiskFaultPlan(fsync_stall_s=0.01)  # > INLINE_FSYNC_BUDGET_S
        j.fault_plan = plan
        assert not j._fsync_slow
        fired = asyncio.Event()
        j.append("note", {"v": 1}, on_durable=fired.set)
        await asyncio.wait_for(fired.wait(), 5.0)
        # the stalled inline fsync trips the sticky flag ...
        assert j._fsync_slow
        assert not j._failed
        # ... and the next durable batch (executor tier now) still lands
        fired2 = asyncio.Event()
        j.append("note", {"v": 2}, on_durable=fired2.set)
        await asyncio.wait_for(fired2.wait(), 5.0)
        assert plan.stats["stalls"] == 2
        assert not j._failed
        j.crash()

    run(scenario())


# ---------------------------------------------------------------------------
# jittered_backoff properties, deterministically (hypothesis-free)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [0, 1, 7, 99])
@pytest.mark.parametrize("base,cap", [(0.05, 1.0), (0.2, 5.0), (0.1, 2.0)])
def test_backoff_jitter_bounds_and_cap(seed, base, cap):
    gen = jittered_backoff(base, cap, random.Random(seed))
    unjittered = base
    for _ in range(60):
        got = next(gen)
        # each yield is the current envelope value under [0.5, 1.5) jitter
        assert unjittered * 0.5 <= got < unjittered * 1.5
        # the hard ceiling no draw may ever exceed
        assert got < cap * 1.5
        unjittered = min(unjittered * 2, cap)
    # the envelope actually reached the cap (monotone doubling saturates)
    assert unjittered == cap


def test_backoff_deterministic_from_seed():
    gen1 = jittered_backoff(0.05, 1.0, random.Random(5))
    gen2 = jittered_backoff(0.05, 1.0, random.Random(5))
    gen3 = jittered_backoff(0.05, 1.0, random.Random(6))
    seq1 = [next(gen1) for _ in range(30)]
    assert seq1 == [next(gen2) for _ in range(30)]
    assert seq1 != [next(gen3) for _ in range(30)]


# ---------------------------------------------------------------------------
# all four production redial loops respect the ceiling under a long
# total partition (every dial refused, so each loop lives in its
# backoff forever — no recorded wait may exceed cap * 1.5)
# ---------------------------------------------------------------------------

def test_all_redial_loops_respect_backoff_ceiling(monkeypatch):
    real_sleep = asyncio.sleep

    recorded = []

    async def fake_sleep(delay, *args, **kwargs):
        recorded.append(delay)
        await real_sleep(0)

    async def refuse_dial(*args, **kwargs):
        raise LspConnectError("chaos: total partition")

    monkeypatch.setattr(asyncio, "sleep", fake_sleep)
    monkeypatch.setattr(LspClient, "connect", refuse_dial)

    async def drain(task, want=20):
        # the loops are unbounded: cancel once enough waits are recorded
        while len(recorded) < want and not task.done():
            await real_sleep(0)
        task.cancel()
        try:
            await task
        except (asyncio.CancelledError, LspConnectError):
            pass

    def check(cap, loop_name):
        assert recorded, f"{loop_name}: no backoff waits recorded"
        assert max(recorded) < cap * 1.5, (
            f"{loop_name}: a wait exceeded the jittered ceiling"
        )
        # the envelope saturated at the cap (a real long partition)
        assert max(recorded) >= cap * 0.5
        recorded.clear()

    async def scenario():
        # 1. worker fleet redial loop (bounded natively via max_dials)
        await run_miner_reconnect(
            "127.0.0.1", 1, CpuMiner(), params=FAST,
            base_backoff=0.05, max_backoff=1.0, max_dials=25,
            rng=random.Random(0),
        )
        check(1.0, "worker.run_miner_reconnect")

        # 2. durable client redial loop (client.submit reconnect=True)
        req = Request(job_id=0, mode=PowMode.MIN, lower=0, upper=10,
                      data=b"x")
        await drain(asyncio.ensure_future(submit(
            "127.0.0.1", 1, req, params=FAST, reconnect=True,
            base_backoff=0.05, max_backoff=1.0, rng=random.Random(0),
        )))
        check(1.0, "client.submit")

        # 3. loadgen resilient miner actor
        await drain(asyncio.ensure_future(
            loadgen._resilient_instant_miner([1], FAST, 0, binary=True)
        ))
        check(1.0, "loadgen._resilient_instant_miner")

        # 4. loadgen durable client actor
        ledger = {"answers": {}, "submitted": 0, "stop": False}
        await drain(asyncio.ensure_future(
            loadgen._durable_client_loop([1], FAST, 0, 50, ledger)
        ))
        check(1.0, "loadgen._durable_client_loop")

    asyncio.run(asyncio.wait_for(scenario(), 60.0))
