"""Roll-budget chunking tests (ISSUE 14): dispatch rolled work in
extranonce units with sub-chunk progress beacons.

- **Arithmetic mirrors** (deterministic versions of the hypothesis
  properties in tests/test_properties.py, since this image lacks
  hypothesis): ``chain.roll_span`` must expand to exactly ``count``
  whole segments — the coordinator's carve and the worker's expansion
  agree bit-for-bit — and beacon-style PARTIAL settles must replay
  through the journal exactly like interval subtraction.
- **End-to-end**: a roll-capable fleet against a budgeted coordinator
  mines a shrunken rolled job via RollAssign dispatch (counted, not
  assumed), emits accepted Beacons, and still lands the bit-exact
  min-fold and hash accounting. Both no-flag-day directions are
  pinned like the PR 4 codec negotiation tests: an old (``roll=False``)
  worker gets classic Assigns from a budgeted coordinator, and a roll
  worker gets classic Assigns from a budget-0 coordinator — exact
  results either way, zero RollAssigns/Beacons on the wire.
- **Crash drill**: kill -9 the journaled coordinator after >= 2
  accepted beacons mid-chunk; the journal replays the beacon settles
  as ordinary 0xB7 records, the recovered job re-mines ONLY the
  un-settled suffix, and the resumed fleet still lands the exact min.
"""

import asyncio
import random
import struct
import time

from tpuminter import chain
from tpuminter.client import submit
from tpuminter.coordinator import Coordinator
from tpuminter.journal import encode_record, merge_ranges, replay, scan
from tpuminter.protocol import PowMode, Request, request_to_obj
from tpuminter.worker import CpuMiner, run_miner, run_miner_reconnect

from tests.test_e2e import FAST, run
from tests.test_extranonce import fixture

NB = 10  # nonce_bits under test (shrunken so a CI sweep rolls)


def _brute(prefix, suffix, branch, hdr80, ens):
    """(hash, global index) minimum over ``ens`` extranonce segments."""
    cb = chain.CoinbaseTemplate(prefix, suffix, 4)
    best = None
    for en in range(ens):
        p76 = chain.rolled_header(hdr80, cb, branch, en).pack()[:76]
        for n in range(1 << NB):
            h = chain.hash_to_int(chain.dsha256(p76 + struct.pack("<I", n)))
            cand = (h, (en << NB) | n)
            if best is None or cand < best:
                best = cand
    return best


def _rolled_request(ens, *, target, job_id=1, client_key=""):
    prefix, suffix, branch, hdr80 = fixture()
    return Request(
        job_id=job_id, mode=PowMode.TARGET, lower=0,
        upper=(ens << NB) - 1, header=hdr80, target=target,
        coinbase_prefix=prefix, coinbase_suffix=suffix,
        extranonce_size=4, branch=tuple(branch), nonce_bits=NB,
        client_key=client_key,
    )


# ---------------------------------------------------------------------------
# arithmetic mirrors
# ---------------------------------------------------------------------------

def test_roll_span_matches_segment_expansion():
    """roll_span(e0, count) is exactly count WHOLE segments: aligned at
    both ends and tiled by rolled_segments with full nonce sweeps —
    the one expansion the carve and the worker must share."""
    rng = random.Random(0xB9)
    cases = [(1, 0, 1), (1, 5, 3), (10, 2, 4), (32, 0, 1),
             (32, 0xFFFFFFFF, 1)]
    cases += [
        (rng.choice([2, 7, 10, 20, 32]), rng.randrange(1 << 16),
         rng.randrange(1, 64))
        for _ in range(50)
    ]
    for nb, e0, count in cases:
        lower, upper = chain.roll_span(e0, count, nb)
        mask = (1 << nb) - 1
        assert lower == e0 << nb
        assert upper - lower + 1 == count << nb
        segs = list(chain.rolled_segments(lower, upper, nb))
        assert len(segs) == count
        assert [en for en, _, _, _ in segs] == list(range(e0, e0 + count))
        assert all(n_lo == 0 and n_hi == mask for _, _, n_lo, n_hi in segs)


def test_roll_span_rejects_empty_count():
    import pytest

    with pytest.raises(ValueError):
        chain.roll_span(3, 0, 10)


def test_beacon_partial_settles_replay_like_subtraction():
    """A journal mixing beacon-style PARTIAL settles (a prefix of an
    in-flight chunk) with whole-chunk settles replays to exactly the
    set-model's un-settled ranges — the zero-format-change property
    recovery leans on: a beacon settle IS an ordinary settle record
    over a sub-range."""
    rng = random.Random(14)
    for _ in range(30):
        segs = rng.randrange(1, 9)
        total = segs << NB
        req = _rolled_request(segs, target=1)
        covered = set()
        blob = encode_record(
            {"k": "job", "id": 1, "req": request_to_obj(req)}
        )
        # random chunk grid; each chunk gets 0..2 monotone beacon
        # prefixes and then maybe its final whole-range settle
        cuts = sorted(rng.sample(range(1, total), min(5, total - 1)))
        chunks = list(zip([0] + cuts, [c - 1 for c in cuts] + [total - 1]))
        for lo, hi in chunks:
            hw = lo - 1
            for _ in range(rng.randrange(3)):
                if hw >= hi - 1:
                    break
                hw = rng.randrange(hw + 1, hi)
                blob += encode_record({
                    "k": "settle", "id": 1, "lo": lo, "hi": hw,
                    "n": lo, "s": hw - lo + 1, "h": "ff",
                })
                covered.update(range(lo, hw + 1))
                lo = hw + 1  # the live chunk advances past the beacon
            if rng.random() < 0.6 and lo <= hi:
                blob += encode_record({
                    "k": "settle", "id": 1, "lo": lo, "hi": hi,
                    "n": lo, "s": hi - lo + 1, "h": "ff",
                })
                covered.update(range(lo, hi + 1))
        recs, _ = scan(blob)
        state = replay(recs)
        want = []
        g = 0
        while g < total:
            if g in covered:
                g += 1
                continue
            start = g
            while g < total and g not in covered:
                g += 1
            want.append((start, g - 1))
        assert merge_ranges(state.jobs[1].remaining) == want
        assert state.jobs[1].hashes_done == len(covered)


# ---------------------------------------------------------------------------
# end-to-end: the dialect engages, and both interop directions hold
# ---------------------------------------------------------------------------

async def _rolled_cluster_run(req, *, roll_budget, worker_roll,
                              beacon_interval=1e-6, batch=16,
                              chunk_size=100_000, n_miners=1):
    """One rolled job through a real coordinator + run_miner fleet with
    the given dialect knobs; returns (final Result, coordinator stats,
    dispatched-chunk count)."""
    coord = await Coordinator.create(
        params=FAST, chunk_size=chunk_size, roll_budget=roll_budget,
    )
    serve = asyncio.ensure_future(coord.serve())
    miners = [
        asyncio.ensure_future(run_miner(
            "127.0.0.1", coord.port, CpuMiner(batch=batch), params=FAST,
            roll=worker_roll, beacon_interval=beacon_interval,
        ))
        for _ in range(n_miners)
    ]
    try:
        await asyncio.sleep(0.1)
        res = await asyncio.wait_for(
            submit("127.0.0.1", coord.port, req, params=FAST), 60.0
        )
        return res, dict(coord.stats), coord._next_chunk_id - 1
    finally:
        for t in miners:
            t.cancel()
        await asyncio.gather(*miners, return_exceptions=True)
        serve.cancel()
        await asyncio.gather(serve, return_exceptions=True)
        await coord.close()


def test_rolled_e2e_budget_engages_beacons_and_exact_min():
    """The positive direction: budgeted coordinator + roll worker. The
    job is dispatched as RollAssigns (counted), sub-chunk progress
    flows back as accepted Beacons, and the exhaustion answer is still
    the bit-exact min with bit-exact hash accounting — beacon settles
    and final Results never double-count."""
    ens = 8
    prefix, suffix, branch, hdr80 = fixture()
    h_min, g_min = _brute(prefix, suffix, branch, hdr80, ens)
    req = _rolled_request(ens, target=1)  # unbeatable: exhaust + min

    async def scenario():
        return await _rolled_cluster_run(
            req, roll_budget=8, worker_roll=True,
        )

    res, stats, chunks = run(scenario())
    assert not res.found
    assert (res.hash_value, res.nonce) == (h_min, g_min)
    assert stats["chunks_roll_dispatched"] > 0
    assert stats["chunks_roll_dispatched"] == chunks  # no classic mix-in
    assert stats["beacons_accepted"] > 0
    assert stats["hashes"] == ens << NB  # exact: no double-count
    assert stats["results_rejected"] == 0


def test_rolled_e2e_budget_finds_winner():
    """Same stack, beatable target: the winner Result (not a beacon)
    finishes the job, exactly like classic dispatch."""
    ens = 4
    prefix, suffix, branch, hdr80 = fixture()
    h_min, g_min = _brute(prefix, suffix, branch, hdr80, ens)
    req = _rolled_request(ens, target=h_min)

    async def scenario():
        return await _rolled_cluster_run(
            req, roll_budget=4, worker_roll=True,
        )

    res, stats, _ = run(scenario())
    assert res.found
    assert (res.nonce, res.hash_value) == (g_min, h_min)
    assert stats["chunks_roll_dispatched"] > 0


def test_rolled_e2e_old_worker_gets_classic_assigns():
    """No-flag-day, worker side: a pre-dialect worker (roll=False —
    its Join never advertises) against a BUDGETED coordinator must see
    only classic Assigns and still land the exact answer."""
    ens = 4
    prefix, suffix, branch, hdr80 = fixture()
    h_min, g_min = _brute(prefix, suffix, branch, hdr80, ens)
    req = _rolled_request(ens, target=1)

    async def scenario():
        return await _rolled_cluster_run(
            req, roll_budget=8, worker_roll=False, chunk_size=1024,
        )

    res, stats, _ = run(scenario())
    assert not res.found
    assert (res.hash_value, res.nonce) == (h_min, g_min)
    assert stats["chunks_roll_dispatched"] == 0
    assert stats["beacons_accepted"] == 0
    assert stats["hashes"] == ens << NB


def test_rolled_e2e_budget_zero_is_the_old_coordinator():
    """No-flag-day, coordinator side: a roll-capable worker against a
    budget-0 coordinator (the shipping default) sees only classic
    Assigns, emits zero beacons, and lands the exact answer — every
    pre-dialect deployment keeps behaving bit-for-bit."""
    ens = 4
    prefix, suffix, branch, hdr80 = fixture()
    h_min, g_min = _brute(prefix, suffix, branch, hdr80, ens)
    req = _rolled_request(ens, target=1)

    async def scenario():
        return await _rolled_cluster_run(
            req, roll_budget=0, worker_roll=True, chunk_size=1024,
        )

    res, stats, _ = run(scenario())
    assert not res.found
    assert (res.hash_value, res.nonce) == (h_min, g_min)
    assert stats["chunks_roll_dispatched"] == 0
    assert stats["beacons_accepted"] == 0
    assert stats["hashes"] == ens << NB


# ---------------------------------------------------------------------------
# crash drill: beacons bound the re-mine
# ---------------------------------------------------------------------------

class _SlowRollMiner(CpuMiner):
    """CpuMiner that naps per batch so a CI-sized rolled chunk stays
    mid-flight long enough to beacon at least twice before the kill."""

    def __init__(self, batch=16, nap=0.002):
        super().__init__(batch=batch)
        self._nap = nap

    def mine(self, request):
        for item in super().mine(request):
            time.sleep(self._nap)
            yield item


def test_crash_mid_roll_chunk_replays_only_unsettled(tmp_path):
    """Kill -9 the journaled coordinator after >= 2 accepted beacons on
    an in-flight roll-budget chunk. The journal (unchanged 0xB7 settle
    records) must replay the beaconed prefix as SETTLED — the recovered
    job re-mines only the un-settled suffix — and the resumed fleet
    still lands the bit-exact min with exactly-once accounting."""
    wal = str(tmp_path / "roll.wal")
    ens = 8
    total = ens << NB
    prefix, suffix, branch, hdr80 = fixture()
    h_min, g_min = _brute(prefix, suffix, branch, hdr80, ens)
    req = _rolled_request(ens, target=1, client_key="roll-crash")

    async def scenario():
        coord = await Coordinator.create(
            params=FAST, chunk_size=100_000, roll_budget=8,
            recover_from=wal,
        )
        port = coord.port
        serve = asyncio.ensure_future(coord.serve())
        miner = asyncio.ensure_future(run_miner_reconnect(
            "127.0.0.1", port, _SlowRollMiner(), params=FAST,
            base_backoff=0.05, max_backoff=0.4, beacon_interval=1e-6,
        ))
        sub = asyncio.ensure_future(submit(
            "127.0.0.1", port, req, params=FAST,
            client_key="roll-crash", reconnect=True, base_backoff=0.05,
        ))
        coord2 = None
        try:
            t0 = time.monotonic()
            while coord.stats["beacons_accepted"] < 2:
                assert time.monotonic() - t0 < 30, "no beacons pre-crash"
                await asyncio.sleep(0.01)
            assert coord.stats["jobs_done"] == 0, (
                "crash must land mid-job; slow the miner down"
            )
            assert coord.stats["chunks_roll_dispatched"] > 0
            # the tick flush is a normal runtime event — run one so the
            # drill's replay assertions are deterministic (a settle
            # still buffered at the instant of death just re-mines)
            await coord._journal.flush()
            # -- kill -9 -------------------------------------------------
            serve.cancel()
            await asyncio.gather(serve, return_exceptions=True)
            endpoint = coord.server.endpoint
            coord.crash()
            await endpoint.wait_closed()
            # -- the journal alone bounds the re-mine --------------------
            with open(wal, "rb") as fh:
                recs, _ = scan(fh.read())
            state = replay(recs)
            job = state.jobs[req.job_id]
            settled = job.hashes_done
            assert 0 < settled < total
            remaining = merge_ranges(job.remaining)
            assert sum(hi - lo + 1 for lo, hi in remaining) == (
                total - settled
            )
            # beacons settle chunk PREFIXES from index 0, so recovery
            # re-mines a pure suffix of the space
            assert remaining[0][0] == settled
            # -- restart on the same port; the fleet resumes -------------
            for attempt in range(100):
                try:
                    coord2 = await Coordinator.create(
                        port, params=FAST, chunk_size=100_000,
                        roll_budget=8, recover_from=wal,
                    )
                    break
                except OSError:
                    await asyncio.sleep(0.02)
            assert coord2 is not None, "could not rebind the port"
            serve2 = asyncio.ensure_future(coord2.serve())
            try:
                res = await asyncio.wait_for(sub, 60.0)
                assert not res.found
                assert (res.hash_value, res.nonce) == (h_min, g_min)
                # the recovered coordinator mined ONLY the un-settled
                # suffix: its own hash ledger is the complement of the
                # replayed prefix
                assert coord2.stats["hashes"] == total - settled
                assert coord2.stats["results_rejected"] == 0
            finally:
                serve2.cancel()
                await asyncio.gather(serve2, return_exceptions=True)
        finally:
            miner.cancel()
            sub.cancel()
            await asyncio.gather(miner, sub, return_exceptions=True)
            if coord2 is not None:
                await coord2.close()
            await coord.close()

    run(scenario(), timeout=120.0)
