"""Seeded concurrency fuzz of the coordinator (SURVEY.md §5 race-
detection practice; VERDICT r3 coverage row 22): a randomized fleet —
honest fast/slow workers, foragers of fake winners, lazy under-
searchers, random deaths and elastic rejoins — against concurrent
clients, under transport faults, with hedging and audits enabled. The
invariant is absolute: every job the clients get an answer for carries
the exact brute-force minimum, no matter what the fleet did.

The fleet's behavior stream is seeded (random.Random), so a failure
reproduces by seed; asyncio interleaving still varies run to run, which
is the point — the scheduler's bookkeeping must hold under any
interleaving.
"""

import asyncio
import random

import pytest

from tpuminter import chain
from tpuminter.client import submit
from tpuminter.lsp import LspClient, LspConnectionLost
from tpuminter.protocol import (
    Assign,
    Cancel,
    Join,
    PowMode,
    Refuse,
    Request,
    Result,
    Setup,
    decode_msg,
    encode_msg,
)

from tests.test_e2e import FAST, Cluster, brute_min, run


async def _actor(port: int, rng: random.Random, behavior: str) -> None:
    """One fuzz worker. Behaviors:

    - "honest": mines exactly (host brute force), tiny random delays
    - "slow":   honest but sleepy (hedging fodder)
    - "liar":   claims found winners with impossible hashes (rejected,
                eventually evicted)
    - "lazy":   answers instantly with the verifiable hash of the
                range's first nonce (audits catch)
    - "flaky":  honest, but randomly Refuses dispatches (template
                resync path)
    """
    w = await LspClient.connect("127.0.0.1", port, FAST)
    w.write(encode_msg(Join(backend=behavior, lanes=1)))
    templates = {}
    try:
        while True:
            msg = decode_msg(await w.read())
            if isinstance(msg, Setup):
                templates[msg.request.job_id] = msg.request
            elif isinstance(msg, Cancel):
                templates.pop(msg.job_id, None)
            elif isinstance(msg, Assign):
                req = templates.get(msg.job_id)
                if req is None or (behavior == "flaky" and rng.random() < 0.3):
                    w.write(encode_msg(Refuse(msg.job_id, msg.chunk_id)))
                    continue
                if behavior == "liar" and rng.random() < 0.8:
                    w.write(encode_msg(Result(
                        msg.job_id, req.mode, nonce=msg.lower, hash_value=0,
                        found=True, searched=1, chunk_id=msg.chunk_id,
                    )))
                    continue
                if behavior == "lazy":
                    w.write(encode_msg(Result(
                        msg.job_id, req.mode, nonce=msg.lower,
                        hash_value=chain.toy_hash(req.data, msg.lower),
                        found=True, searched=msg.upper - msg.lower + 1,
                        chunk_id=msg.chunk_id,
                    )))
                    continue
                if behavior == "slow":
                    await asyncio.sleep(rng.uniform(0.2, 0.6))
                else:
                    await asyncio.sleep(rng.uniform(0.0, 0.02))
                h, n = brute_min(req.data, msg.lower, msg.upper)
                w.write(encode_msg(Result(
                    msg.job_id, req.mode, n, h, found=True,
                    searched=msg.upper - msg.lower + 1,
                    chunk_id=msg.chunk_id,
                )))
    except (LspConnectionLost, asyncio.CancelledError):
        pass
    finally:
        await w.close(drain_timeout=0.5)


@pytest.mark.parametrize("seed", [1, 7, 23, 57, 101, 211, 349, 499])
def test_scheduler_fuzz_exact_answers_despite_hostile_fleet(seed, monkeypatch):
    from tpuminter import coordinator as coord_mod

    # full-coverage audits: at the default sampled rate a lazy worker's
    # chunk legitimately escapes with p ≈ (1 - rate) + rate/sample — the
    # probabilistic defense working as documented. The fuzz invariant
    # ("every answer exact") needs the deterministic regime: every
    # accepted chunk re-mined in full.
    monkeypatch.setattr(coord_mod, "AUDIT_SAMPLE", 600)

    async def scenario():
        rng = random.Random(seed)
        cluster = await Cluster.create(
            n_miners=0, chunk_size=600,
            hedge_after=0.4, audit_rate=1.0, audit_seed=seed,
        )
        # transport faults on top of everything else — expressed as a
        # chaos FaultPlan (ISSUE 12) so fuzz and the loadgen chaos
        # matrix share one seeded fault vocabulary; a wildcard link
        # rule in both directions is exactly the old uniform rates
        from tpuminter.chaos import FaultPlan

        cluster.coord._server.endpoint.set_fault_plan(
            FaultPlan(seed).link(
                peer="*", direction="both", drop=0.05, dup=0.05,
                reorder=0.05, reorder_delay=0.01,
            )
        )
        actors = []

        def spawn(behavior):
            actors.append(asyncio.ensure_future(
                _actor(cluster.coord.port, random.Random(rng.random()),
                       behavior)
            ))

        try:
            # two honest anchors guarantee liveness; the rest is chaos
            for behavior in ("honest", "honest", "slow", "liar", "lazy",
                             "flaky"):
                spawn(behavior)
            await asyncio.sleep(0.2)

            jobs = []
            for jid in range(4):
                data = f"fuzz-{seed}-{jid}".encode()
                upper = rng.randrange(3_000, 9_000)
                req = Request(job_id=jid, mode=PowMode.MIN, lower=0,
                              upper=upper, data=data)
                jobs.append((data, upper, asyncio.ensure_future(
                    submit("127.0.0.1", cluster.coord.port, req, params=FAST)
                )))
                await asyncio.sleep(rng.uniform(0.0, 0.2))

            # mid-flight churn: kill a random actor, add replacements
            await asyncio.sleep(0.3)
            victim = actors[rng.randrange(len(actors))]
            victim.cancel()
            spawn("honest")
            spawn("flaky")

            for data, upper, task in jobs:
                # generous budget: full-coverage audits re-mine every
                # chunk and the 1-core CI host runs this mid-suite under
                # load (healthy scenarios finish in ~2 s)
                result = await asyncio.wait_for(task, 150.0)
                assert (result.hash_value, result.nonce) == brute_min(
                    data, 0, upper
                ), data
            # the scheduler saw real adversity (not a vacuous pass)
            stats = cluster.coord.stats
            assert stats["results_rejected"] >= 1  # the liar fired
            assert stats["jobs_done"] == 4
        finally:
            for a in actors:
                a.cancel()
            await asyncio.gather(*actors, return_exceptions=True)
            await cluster.close()

    # 180 s bounds a wedged scenario's cost without risking the tier-1
    # suite envelope. (This budget caught a real bug: scenarios wedged
    # here whenever teardown cancelled an actor mid-connect — the
    # wait_for/shield cancellation-swallow race in LspClient.connect,
    # fixed at the source. A future wedge means a NEW liveness bug, not
    # a budget problem.)
    run(scenario(), timeout=180.0)
