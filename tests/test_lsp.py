"""LSP protocol tests (SURVEY.md §4: connect, ordered delivery, window
enforcement, epoch retransmit under injected loss, connection-loss
detection, heartbeat liveness — multi-node faked on localhost, faults
injected at the transport seam)."""

import asyncio

import pytest

from tpuminter.lsp import (
    Frame,
    LspClient,
    LspConnectError,
    LspConnectionLost,
    LspServer,
    MsgType,
    Params,
    decode,
    encode,
)

FAST = Params(epoch_limit=5, epoch_millis=40, window_size=4, max_backoff_interval=2)


def run(coro, timeout=30.0):
    async def wrapped():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.run(wrapped())


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------

def test_codec_roundtrip():
    f = Frame(MsgType.DATA, 7, 42, b"payload bytes")
    assert decode(encode(f)) == f


def test_codec_rejects_garbage_and_corruption():
    assert decode(b"") is None
    assert decode(b"short") is None
    good = encode(Frame(MsgType.DATA, 1, 1, b"x" * 20))
    flipped = bytes([good[0]]) + good[1:-1] + bytes([good[-1] ^ 0xFF])
    assert decode(flipped) is None
    truncated = good[:-3]
    assert decode(truncated) is None


# ---------------------------------------------------------------------------
# happy path
# ---------------------------------------------------------------------------

def test_connect_and_echo_in_order():
    async def scenario():
        server = await LspServer.create(params=FAST)
        client = await LspClient.connect("127.0.0.1", server.port, FAST)
        assert client.conn_id >= 1
        for i in range(20):
            client.write(f"msg-{i}".encode())
        for i in range(20):
            conn_id, payload = await server.read()
            assert payload == f"msg-{i}".encode()
            server.write(conn_id, b"echo:" + payload)
        for i in range(20):
            assert await client.read() == f"echo:msg-{i}".encode()
        await client.close()
        await server.close()

    run(scenario())


def test_multiple_clients_demuxed():
    async def scenario():
        server = await LspServer.create(params=FAST)
        clients = [
            await LspClient.connect("127.0.0.1", server.port, FAST) for _ in range(4)
        ]
        ids = {c.conn_id for c in clients}
        assert len(ids) == 4
        for c in clients:
            c.write(f"hello from {c.conn_id}".encode())
        seen = {}
        for _ in range(4):
            conn_id, payload = await server.read()
            seen[conn_id] = payload
        assert seen == {c.conn_id: f"hello from {c.conn_id}".encode() for c in clients}
        for c in clients:
            await c.close()
        await server.close()

    run(scenario())


def test_large_payloads_fragment_and_reassemble():
    """App messages above MAX_PAYLOAD travel as multiple DATA frames and
    reassemble exactly (VERDICT r3 missing #1): a realistic rolled job
    encodes to several kB. Interleaved with small messages, in both
    directions, and under loss."""
    from tpuminter.lsp.message import MAX_PAYLOAD

    payloads = [
        b"small",
        bytes(range(256)) * 20,          # ~5 kB: 4 fragments
        b"x" * MAX_PAYLOAD,              # exactly one fragment boundary
        b"",                             # empty message still delivers
        b"y" * (3 * MAX_PAYLOAD + 17),   # larger, unaligned
    ]

    async def scenario():
        server = await LspServer.create(params=FAST, seed=3)
        client = await LspClient.connect("127.0.0.1", server.port, FAST, seed=4)
        client.endpoint.set_write_drop_rate(0.1)
        client.endpoint.set_read_drop_rate(0.1)
        for p in payloads:
            client.write(p)
        conn_id = None
        for want in payloads:
            conn_id, payload = await server.read()
            assert payload == want
        for p in payloads:
            server.write(conn_id, p)
        for want in payloads:
            assert await client.read() == want
        await client.close()
        await server.close()

    run(scenario(), timeout=60.0)


def test_drop_dup_reorder_storm_delivers_exactly_once_in_order():
    """In-order exactly-once delivery survives 10% drop + 10% dup + 10%
    reorder simultaneously in both directions (VERDICT r3 missing #3:
    real UDP duplicates and reorders, not just drops). A multi-fragment
    payload rides along so reassembly is stressed under the same storm."""

    async def scenario():
        server = await LspServer.create(params=FAST, seed=5)
        client = await LspClient.connect("127.0.0.1", server.port, FAST, seed=6)
        for ep in (server.endpoint, client.endpoint):
            ep.set_fault_rates(drop=0.1, dup=0.1, reorder=0.1)
            ep.reorder_delay = 0.02
        n = 60
        payloads = [i.to_bytes(4, "big") for i in range(n)] + [b"frag" * 1000]
        for p in payloads:
            client.write(p)
        conn_id = None
        for want in payloads:
            conn_id, payload = await server.read()
            assert payload == want
        for p in payloads:
            server.write(conn_id, p)
        for want in payloads:
            assert await client.read() == want
        eps = (server.endpoint, client.endpoint)
        assert sum(e.dropped_out + e.dropped_in for e in eps) > 0
        assert sum(e.duplicated_out + e.duplicated_in for e in eps) > 0
        assert sum(e.reordered_out + e.reordered_in for e in eps) > 0
        await client.close()
        await server.close()

    run(scenario(), timeout=60.0)


def test_reassembly_overflow_declares_connection_lost():
    """A peer streaming more-fragments forever must not grow our memory
    without bound (code-review r4): past MAX_MESSAGE the connection is
    declared lost and the partial buffer discarded."""
    from tpuminter.lsp.connection import ConnState, FRAGMENT_SIZE, MAX_MESSAGE
    from tpuminter.lsp.message import Frame, MsgType
    from tpuminter.lsp.params import Params as P

    async def scenario():
        delivered, lost = [], []
        conn = ConnState(1, P(), lambda f: None, delivered.append, lost.append)
        n = MAX_MESSAGE // FRAGMENT_SIZE + 2
        for seq in range(1, n + 1):
            conn.on_frame(
                Frame(MsgType.DATA, 1, seq, b"\x01" + b"z" * FRAGMENT_SIZE)
            )
            if conn.lost:
                break
        assert conn.lost and lost
        assert not delivered
        assert conn._rx_parts == [] and conn._rx_bytes == 0

    run(scenario())


# ---------------------------------------------------------------------------
# fault injection at the transport seam
# ---------------------------------------------------------------------------

def test_retransmission_survives_heavy_loss():
    async def scenario():
        server = await LspServer.create(params=FAST, seed=1)
        client = await LspClient.connect("127.0.0.1", server.port, FAST, seed=2)
        # 30% loss in both directions on the client side of the seam
        client.endpoint.set_write_drop_rate(0.3)
        client.endpoint.set_read_drop_rate(0.3)
        n = 40
        for i in range(n):
            client.write(i.to_bytes(4, "big"))
        got = []
        for _ in range(n):
            _, payload = await server.read()
            assert payload is not None
            got.append(int.from_bytes(payload, "big"))
        assert got == list(range(n))  # exactly once, in order
        # and the reverse direction
        for i in range(n):
            server.write(client.conn_id, i.to_bytes(4, "big"))
        got = [int.from_bytes(await client.read(), "big") for _ in range(n)]
        assert got == list(range(n))
        await client.close()
        await server.close()

    run(scenario(), timeout=60.0)


def test_window_limits_in_flight_frames():
    async def scenario():
        params = Params(epoch_limit=10, epoch_millis=40, window_size=3)
        server = await LspServer.create(params=params)
        client = await LspClient.connect("127.0.0.1", server.port, params)
        # black-hole everything the client sends post-connect: acks never come
        client.endpoint.set_write_drop_rate(1.0)
        for i in range(10):
            client.write(bytes([i]))
        await asyncio.sleep(4 * params.epoch_seconds)
        assert client._conn.in_flight == 3  # window_size caps unacked sends
        # heal the link: everything must flow, in order
        client.endpoint.set_write_drop_rate(0.0)
        got = []
        for _ in range(10):
            _, payload = await server.read()
            got.append(payload[0])
        assert got == list(range(10))
        await client.close()
        await server.close()

    run(scenario())


def test_corrupt_datagrams_are_ignored():
    async def scenario():
        server = await LspServer.create(params=FAST)
        client = await LspClient.connect("127.0.0.1", server.port, FAST)
        # spray garbage at the server's port from a raw socket
        loop = asyncio.get_running_loop()
        transport, _ = await loop.create_datagram_endpoint(
            asyncio.DatagramProtocol, local_addr=("127.0.0.1", 0)
        )
        for junk in (b"", b"\x00", b"garbage" * 50, encode(Frame(MsgType.DATA, 99, 5, b"x"))[:-2]):
            transport.sendto(junk, ("127.0.0.1", server.port))
        transport.close()
        client.write(b"still works")
        conn_id, payload = await server.read()
        assert payload == b"still works"
        await client.close()
        await server.close()

    run(scenario())


# ---------------------------------------------------------------------------
# liveness
# ---------------------------------------------------------------------------

def test_client_detects_dead_server():
    async def scenario():
        server = await LspServer.create(params=FAST)
        client = await LspClient.connect("127.0.0.1", server.port, FAST)
        client.write(b"ping")
        await server.read()
        # server dies silently (no close handshake exists — like a crash)
        await server.close()
        with pytest.raises(LspConnectionLost):
            while True:
                await asyncio.wait_for(client.read(), timeout=5.0)
        assert client.is_lost
        await client.close()

    run(scenario())


def test_server_detects_dead_client_and_reports_loss_event():
    async def scenario():
        server = await LspServer.create(params=FAST)
        client = await LspClient.connect("127.0.0.1", server.port, FAST)
        client.write(b"hello")
        conn_id, payload = await server.read()
        assert payload == b"hello"
        client.endpoint.close()  # client process "crashes"
        lost_id, lost_payload = await server.read()
        assert (lost_id, lost_payload) == (conn_id, None)
        assert conn_id not in server.conn_ids
        await server.close()

    run(scenario())


def test_heartbeats_keep_idle_connection_alive():
    async def scenario():
        server = await LspServer.create(params=FAST)
        client = await LspClient.connect("127.0.0.1", server.port, FAST)
        # idle for well past epoch_limit epochs — heartbeats must keep it up
        await asyncio.sleep(3 * FAST.epoch_limit * FAST.epoch_seconds)
        assert not client.is_lost
        client.write(b"alive")
        conn_id, payload = await server.read()
        assert payload == b"alive"
        await client.close()
        await server.close()

    run(scenario())


def test_connect_to_nothing_raises():
    async def scenario():
        params = Params(epoch_limit=3, epoch_millis=40)
        with pytest.raises(LspConnectError):
            await LspClient.connect("127.0.0.1", 1, params)  # port 1: nobody home

    run(scenario())


def test_write_after_loss_raises():
    async def scenario():
        server = await LspServer.create(params=FAST)
        client = await LspClient.connect("127.0.0.1", server.port, FAST)
        await server.close()
        with pytest.raises(LspConnectionLost):
            while True:
                await asyncio.wait_for(client.read(), timeout=5.0)
        with pytest.raises(LspConnectionLost):
            client.write(b"too late")
        await client.close()

    run(scenario())

def test_close_drains_pending_writes():
    async def scenario():
        server = await LspServer.create(params=FAST, seed=3)
        client = await LspClient.connect("127.0.0.1", server.port, FAST, seed=4)
        client.endpoint.set_write_drop_rate(0.4)  # force retransmission work
        n = 15
        for i in range(n):
            client.write(bytes([i]))
        await client.close()  # must not return until data is acked (or timeout)
        got = []
        for _ in range(n):
            _, payload = await server.read()
            assert payload is not None
            got.append(payload[0])
        assert got == list(range(n))
        await server.close()

    run(scenario(), timeout=60.0)


def test_server_close_conn_drains_in_flight_data():
    async def scenario():
        params = Params(epoch_limit=8, epoch_millis=40, window_size=1)
        server = await LspServer.create(params=params, seed=5)
        client = await LspClient.connect("127.0.0.1", server.port, params, seed=6)
        client.write(b"hi")
        conn_id, _ = await server.read()
        server.endpoint.set_write_drop_rate(0.4)  # force retransmission work
        for i in range(5):
            server.write(conn_id, bytes([i]))
        server.close_conn(conn_id)  # must keep retransmitting until drained
        got = [(await client.read())[0] for _ in range(5)]
        assert got == list(range(5))
        await client.close()
        await server.close()

    run(scenario(), timeout=60.0)


def test_client_read_after_graceful_close_raises():
    async def scenario():
        server = await LspServer.create(params=FAST)
        client = await LspClient.connect("127.0.0.1", server.port, FAST)
        await client.close()
        with pytest.raises(LspConnectionLost):
            await asyncio.wait_for(client.read(), timeout=5.0)
        await server.close()

    run(scenario())


def test_smoke_runners_roundtrip(capsys):
    """The srunner/crunner smoke pair (SURVEY.md §2 #11): echo server and
    client exercise the bare LSP stack end-to-end in-process."""
    import asyncio

    from tpuminter.lsp import crunner, srunner

    async def scenario():
        port_ready = asyncio.get_running_loop().create_future()
        server = asyncio.create_task(
            srunner.serve(0, on_ready=port_ready.set_result)
        )
        port = await asyncio.wait_for(port_ready, 5.0)
        try:
            await asyncio.wait_for(
                crunner.run("127.0.0.1", port, ["alpha", "beta"]), 10.0
            )
        finally:
            server.cancel()
            try:
                await server
            except (asyncio.CancelledError, Exception):
                pass

    asyncio.run(scenario())
    out = capsys.readouterr().out
    assert out.splitlines() == [
        "alpha", "beta", "done: 2 replies, in order, loss-free"
    ]
