"""ExtraNonce / Merkle-roll tests (BASELINE.json:9-10; SURVEY.md §7
stage 6): the device roll is pinned bit-for-bit to the host reference
(``chain.rolled_header`` → ``hashlib``), the rolled miners are pinned to
brute force, and a rolled job runs end-to-end through the cluster with
the winning extranonce ≥ 1 — i.e. a search that actually exhausted a
(shrunken, ``nonce_bits``-wide) nonce space and rolled past it.

The fixture is deterministic: seed 0's global argmin lands at
extranonce 2 (asserted, not assumed). ``nonce_bits=10`` shrinks the
per-extranonce space so the roll happens within a CI-sized sweep; the
full-width (2^32) roll runs on the real chip in tests/test_kernels_tpu.
"""

import asyncio
import struct

import numpy as np
import jax.numpy as jnp
import pytest

from tpuminter import chain
from tpuminter.ops import sha256 as ops
from tpuminter.ops import merkle
from tpuminter.protocol import PowMode, Request, decode_msg, encode_msg
from tpuminter.worker import CpuMiner

NB = 10  # nonce_bits under test
ENS = 4  # extranonce values covered


def fixture(seed: int = 0):
    rng = np.random.RandomState(seed)
    prefix = rng.bytes(41)  # odd sizes: unaligned extranonce hole
    suffix = rng.bytes(60)
    branch = [rng.bytes(32) for _ in range(2)]
    return prefix, suffix, branch, chain.GENESIS_HEADER.pack()


def brute(prefix, suffix, branch, hdr80):
    """(hash, global index) for every index in the fixture space."""
    cb = chain.CoinbaseTemplate(prefix, suffix, 4)
    out = []
    for en in range(ENS):
        p76 = chain.rolled_header(hdr80, cb, branch, en).pack()[:76]
        for n in range(1 << NB):
            h = chain.hash_to_int(chain.dsha256(p76 + struct.pack("<I", n)))
            out.append((h, (en << NB) | n))
    return out


@pytest.fixture(scope="module")
def ground_truth():
    prefix, suffix, branch, hdr80 = fixture()
    all_h = brute(prefix, suffix, branch, hdr80)
    h_min, g_min = min(all_h)
    assert g_min >> NB == 2, "fixture invariant: winner at extranonce 2"
    return prefix, suffix, branch, hdr80, all_h, h_min, g_min


# ---------------------------------------------------------------------------
# host primitives
# ---------------------------------------------------------------------------

def test_split_global():
    assert chain.split_global(0, 32) == (0, 0)
    assert chain.split_global((5 << 32) | 77, 32) == (5, 77)
    assert chain.split_global((3 << 10) | 1023, 10) == (3, 1023)


def test_rolled_header_matches_manual_merkle():
    prefix, suffix, branch, hdr80 = fixture()
    cb = chain.CoinbaseTemplate(prefix, suffix, 4)
    for en in (0, 1, 0xDEADBEEF):
        txid = chain.dsha256(prefix + en.to_bytes(4, "little") + suffix)
        root = chain.merkle_root_from_branch(txid, branch)
        hdr = chain.rolled_header(hdr80, cb, branch, en)
        assert hdr.merkle_root == root
        # everything but the root is untouched
        base = chain.BlockHeader.unpack(hdr80)
        assert (hdr.version, hdr.prev_hash, hdr.timestamp, hdr.bits) == (
            base.version, base.prev_hash, base.timestamp, base.bits
        )


# ---------------------------------------------------------------------------
# the device roll (jnp path; Pallas twin tested on the real chip)
# ---------------------------------------------------------------------------

def test_device_roll_matches_host_template():
    """roll(en) ≡ header_template(rolled_header(en)) for midstate AND
    tail words — the exact values the search kernels specialize on."""
    prefix, suffix, branch, hdr80 = fixture()
    cb = chain.CoinbaseTemplate(prefix, suffix, 4)
    roll = merkle.make_extranonce_roll(hdr80, prefix, suffix, 4, branch)
    for en in (0, 1, 2, 0xDEADBEEF):
        want_hdr = chain.rolled_header(hdr80, cb, branch, en)
        t = ops.header_template(want_hdr.pack())
        mid, tw = roll(jnp.uint32(0), jnp.uint32(en))
        assert tuple(int(x) for x in np.asarray(mid)) == t.midstate
        assert tuple(int(x) for x in np.asarray(tw)) == want_hdr.tail_words()


def test_device_roll_wide_extranonce():
    """8-byte extranonces travel as (hi, lo) u32 pairs."""
    prefix, suffix, branch, hdr80 = fixture()
    cb = chain.CoinbaseTemplate(prefix, suffix, 8)
    roll = merkle.make_extranonce_roll(hdr80, prefix, suffix, 8, branch)
    en = 0x0123456789ABCDEF
    want = ops.header_template(chain.rolled_header(hdr80, cb, branch, en).pack())
    mid, _ = roll(jnp.uint32(en >> 32), jnp.uint32(en & 0xFFFFFFFF))
    assert tuple(int(x) for x in np.asarray(mid)) == want.midstate


def test_device_roll_empty_branch():
    """A block whose only tx is the coinbase: root == txid."""
    prefix, suffix, _, hdr80 = fixture()
    cb = chain.CoinbaseTemplate(prefix, suffix, 4)
    roll = merkle.make_extranonce_roll(hdr80, prefix, suffix, 4, ())
    want = ops.header_template(chain.rolled_header(hdr80, cb, (), 9).pack())
    mid, tw = roll(jnp.uint32(0), jnp.uint32(9))
    assert tuple(int(x) for x in np.asarray(mid)) == want.midstate


def test_header_digest_dyn_matches_hashlib():
    """The dynamic-header hash fed by the roll ≡ hashlib double-SHA."""
    prefix, suffix, branch, hdr80 = fixture()
    cb = chain.CoinbaseTemplate(prefix, suffix, 4)
    roll = merkle.make_extranonce_roll(hdr80, prefix, suffix, 4, branch)
    for en in (0, 3):
        mid, tw = roll(jnp.uint32(0), jnp.uint32(en))
        nonces = jnp.asarray(np.array([0, 1, 77, 2**32 - 1], np.uint32))
        dw = np.asarray(ops.header_digest_dyn(mid, tw, nonces))
        p76 = chain.rolled_header(hdr80, cb, branch, en).pack()[:76]
        for i, n in enumerate([0, 1, 77, 2**32 - 1]):
            want = chain.dsha256(p76 + struct.pack("<I", n))
            got = b"".join(int(w).to_bytes(4, "big") for w in dw[i])
            assert got == want, (en, n)


# ---------------------------------------------------------------------------
# protocol plumbing
# ---------------------------------------------------------------------------

def test_rolled_request_roundtrip():
    prefix, suffix, branch, hdr80 = fixture()
    req = Request(
        job_id=5, mode=PowMode.TARGET, lower=0, upper=(ENS << NB) - 1,
        header=hdr80, target=123456789,
        coinbase_prefix=prefix, coinbase_suffix=suffix,
        extranonce_size=4, branch=tuple(branch), nonce_bits=NB,
    )
    assert req.rolled
    got = decode_msg(encode_msg(req))
    assert got == req


def test_rolled_request_validation():
    prefix, suffix, branch, hdr80 = fixture()
    from tpuminter.protocol import ProtocolError

    with pytest.raises(ProtocolError):  # rolling is TARGET-only
        Request(job_id=1, mode=PowMode.MIN, lower=0, upper=10,
                data=b"x", coinbase_prefix=prefix)
    with pytest.raises(ProtocolError):  # upper beyond the global space
        Request(job_id=1, mode=PowMode.TARGET, lower=0,
                upper=1 << (NB + 32), header=hdr80, target=1,
                coinbase_prefix=prefix, nonce_bits=NB)
    with pytest.raises(ProtocolError):  # bad branch entry
        Request(job_id=1, mode=PowMode.TARGET, lower=0, upper=10,
                header=hdr80, target=1, coinbase_prefix=prefix,
                branch=(b"short",))


# ---------------------------------------------------------------------------
# miners vs brute force
# ---------------------------------------------------------------------------

def _rolled_request(ground_truth, target, lower=0, upper=None, job_id=1):
    prefix, suffix, branch, hdr80, _, _, _ = ground_truth
    return Request(
        job_id=job_id, mode=PowMode.TARGET,
        lower=lower, upper=(ENS << NB) - 1 if upper is None else upper,
        header=hdr80, target=target,
        coinbase_prefix=prefix, coinbase_suffix=suffix,
        extranonce_size=4, branch=tuple(branch), nonce_bits=NB,
    )


def drain(gen):
    result = None
    for item in gen:
        if item is not None:
            result = item
    return result


def test_cpu_miner_rolls_to_winner(ground_truth):
    *_, all_h, h_min, g_min = ground_truth
    req = _rolled_request(ground_truth, target=h_min)
    result = drain(CpuMiner(batch=256).mine(req))
    assert result.found
    assert (result.nonce, result.hash_value) == (g_min, h_min)
    assert result.nonce >> NB >= 1  # the roll actually happened
    # first-winner semantics: nothing below g_min wins
    assert all(h > h_min for h, g in all_h if g < g_min)
    assert result.searched == g_min + 1


def test_cpu_miner_rolled_exhausted_reports_min(ground_truth):
    *_, h_min, g_min = ground_truth
    req = _rolled_request(ground_truth, target=1)  # unbeatable
    result = drain(CpuMiner(batch=256).mine(req))
    assert not result.found
    assert (result.hash_value, result.nonce) == (h_min, g_min)
    assert result.searched == ENS << NB


def test_jax_miner_rolled_matches_cpu(ground_truth):
    from tpuminter.jax_worker import JaxMiner

    *_, h_min, g_min = ground_truth
    req = _rolled_request(ground_truth, target=h_min)
    result = drain(JaxMiner(batch=512).mine(req))
    assert result.found
    assert (result.nonce, result.hash_value) == (g_min, h_min)

    req = _rolled_request(ground_truth, target=1)
    result = drain(JaxMiner(batch=512).mine(req))
    assert not result.found
    assert (result.hash_value, result.nonce) == (h_min, g_min)


def test_jax_miner_rolled_partial_chunk(ground_truth):
    """A chunk that starts mid-segment and ends mid-segment (what the
    coordinator's carving produces) still maps global indices right."""
    from tpuminter.jax_worker import JaxMiner

    prefix, suffix, branch, hdr80, all_h, _, _ = ground_truth
    lo, hi = (1 << NB) + 100, (3 << NB) + 50  # en 1..3, ragged edges
    want = min((h, g) for h, g in all_h if lo <= g <= hi)
    req = _rolled_request(ground_truth, target=1, lower=lo, upper=hi)
    result = drain(JaxMiner(batch=512).mine(req))
    assert not result.found
    assert (result.hash_value, result.nonce) == want


# ---------------------------------------------------------------------------
# end-to-end through the cluster (eval configs 3-4 shape)
# ---------------------------------------------------------------------------

def test_realistic_rolled_job_via_client_cli():
    """A mainnet-scale rolled job — 250-byte coinbase, 12-deep merkle
    branch — encodes to more than one LSP frame (VERDICT r3 missing #1)
    and must still travel the REAL client CLI path (a subprocess running
    ``python -m tpuminter.client``) to a winner a mixed fleet mines and
    the coordinator host-verifies. Exercises LSP fragmentation on the
    submit leg and the Setup/Assign template split on the dispatch leg."""
    import sys

    from tests.test_e2e import run
    from tpuminter.coordinator import Coordinator
    from tpuminter.jax_worker import JaxMiner
    from tpuminter.lsp.message import MAX_PAYLOAD
    from tpuminter.lsp.params import FAST as LSP_FAST
    from tpuminter.worker import run_miner

    rng = np.random.RandomState(7)
    prefix, suffix = rng.bytes(120), rng.bytes(126)
    branch = [rng.bytes(32) for _ in range(12)]
    hdr80 = chain.GENESIS_HEADER.pack()
    assert len(prefix) + 4 + len(suffix) == 250  # the realistic coinbase

    # pick a target a CI-sized sweep of extranonce 0 can beat: the min
    # over its first 40k nonces, rounded UP to a representable compact
    # (truncation rounds down, which would un-win the winner)
    cb = chain.CoinbaseTemplate(prefix, suffix, 4)
    p76 = chain.rolled_header(hdr80, cb, branch, 0).pack()[:76]
    h_min = min(
        chain.hash_to_int(chain.dsha256(p76 + struct.pack("<I", n)))
        for n in range(40_000)
    )
    bits = chain.target_to_bits(h_min)
    if chain.bits_to_target(bits) < h_min:
        bits += 1
    target = chain.bits_to_target(bits)
    assert target >= h_min

    # the submitted Request genuinely exceeds one LSP frame
    probe = Request(
        job_id=1, mode=PowMode.TARGET, lower=0, upper=(3 << 32) | 0xFFFFFFFF,
        header=hdr80, target=target, coinbase_prefix=prefix,
        coinbase_suffix=suffix, extranonce_size=4, branch=tuple(branch),
    )
    assert len(encode_msg(probe)) > MAX_PAYLOAD

    async def scenario():
        # production (lsp.params.FAST) timing on both sides: the CLI
        # subprocess heartbeats at 250 ms epochs, so the coordinator must
        # tolerate that cadence
        coord = await Coordinator.create(params=LSP_FAST, chunk_size=8192)
        serve = asyncio.ensure_future(coord.serve())
        miners = [
            asyncio.ensure_future(run_miner(
                "127.0.0.1", coord.port, CpuMiner(), params=LSP_FAST)),
            asyncio.ensure_future(run_miner(
                "127.0.0.1", coord.port, JaxMiner(batch=8192, lanes=2),
                params=LSP_FAST)),
        ]
        await asyncio.sleep(0.2)
        argv = [
            sys.executable, "-m", "tpuminter.client",
            f"127.0.0.1:{coord.port}",
            "--header", hdr80.hex(), "--bits", hex(bits),
            "--coinbase-prefix", prefix.hex(),
            "--coinbase-suffix", suffix.hex(),
            "--extranonce-size", "4", "--max-extranonce", "3",
        ]
        for sib in branch:
            argv += ["--branch", sib.hex()]
        try:
            proc = await asyncio.create_subprocess_exec(
                *argv,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.PIPE,
            )
            out, err = await asyncio.wait_for(proc.communicate(), 90.0)
            line = out.decode().strip()
            assert line.startswith("Result "), (line, err.decode())
            _, hash_hex, en_part, n_part = line.split()
            en = int(en_part.split("=")[1])
            n = int(n_part.split("=")[1])
            # independent re-verification of the printed winner
            p76w = chain.rolled_header(hdr80, cb, branch, en).pack()[:76]
            digest = chain.dsha256(p76w + struct.pack("<I", n))
            assert chain.hash_to_hex(digest) == hash_hex
            assert chain.hash_to_int(digest) <= target
            assert coord.stats["results_rejected"] == 0
        finally:
            for t in miners:
                t.cancel()
            serve.cancel()
            await asyncio.gather(*miners, serve, return_exceptions=True)
            await coord.close()

    run(scenario(), timeout=150.0)


def test_rolled_job_end_to_end(ground_truth):
    from tests.test_e2e import FAST, Cluster, run
    from tpuminter.client import submit

    *_, h_min, g_min = ground_truth

    async def scenario():
        cluster = await Cluster.create(
            n_miners=2, chunk_size=300,
            miner_factory=lambda: CpuMiner(batch=128),
        )
        try:
            req = _rolled_request(ground_truth, target=h_min, job_id=42)
            result = await submit(
                "127.0.0.1", cluster.coord.port, req, params=FAST
            )
            assert result.found
            assert (result.nonce, result.hash_value) == (g_min, h_min)
            assert result.nonce >> NB >= 1
            # the coordinator's host verification accepted a rolled win
            assert cluster.coord.stats["results_rejected"] == 0
        finally:
            await cluster.close()

    run(scenario())


# ---------------------------------------------------------------------------
# batched rolling (ISSUE 7): one dispatch sweeps many rolls
# ---------------------------------------------------------------------------

def test_batched_roll_property_pin():
    """Seeded property pin: batched roll rows == per-extranonce scalar
    ``roll()`` == midstates derived from ``chain.rolled_header`` +
    hashlib, across random (extranonce_size, branch depth, B) combos."""
    import random as _random

    hdr80 = chain.GENESIS_HEADER.pack()
    for seed in range(4):
        rnd = _random.Random(1000 + seed)
        en_size = rnd.choice([1, 2, 4, 8])
        depth = rnd.randrange(0, 5)
        b = rnd.choice([1, 2, 5, 9])
        rng = np.random.RandomState(2000 + seed)
        prefix = rng.bytes(rnd.randrange(1, 90))
        suffix = rng.bytes(rnd.randrange(0, 90))
        branch = tuple(rng.bytes(32) for _ in range(depth))
        cb = chain.CoinbaseTemplate(prefix, suffix, en_size)
        ens = [rnd.randrange(0, 1 << (8 * en_size)) for _ in range(b)]
        batch = merkle.make_extranonce_roll_batch(
            hdr80, prefix, suffix, en_size, branch
        )
        scalar = merkle.make_extranonce_roll(
            hdr80, prefix, suffix, en_size, branch
        )
        mids, tails = batch(
            jnp.asarray(np.array([e >> 32 for e in ens], np.uint32)),
            jnp.asarray(np.array([e & 0xFFFFFFFF for e in ens], np.uint32)),
        )
        mids, tails = np.asarray(mids), np.asarray(tails)
        for i, en in enumerate(ens):
            want_hdr = chain.rolled_header(hdr80, cb, branch, en)
            t = ops.header_template(want_hdr.pack())  # hashlib-derived
            s_mid, s_tw = scalar(
                jnp.uint32(en >> 32), jnp.uint32(en & 0xFFFFFFFF)
            )
            assert tuple(int(x) for x in mids[i]) == t.midstate, (seed, en)
            assert tuple(int(x) for x in tails[i]) == want_hdr.tail_words()
            assert (np.asarray(s_mid) == mids[i]).all()
            assert (np.asarray(s_tw) == tails[i]).all()


def test_plan_tiles_padding_and_ragged_tail():
    """A dispatch window is decomposed into ≤ rows global-order tiles,
    padded with valid=0 — including the B > remaining-segments ragged
    tail, where the window extends past the domain end."""
    from tpuminter import rolled

    nb, en_size = 8, 1  # domain = 2^16 global indices
    hard_end = (1 << (nb + 8 * en_size)) - 1
    width = rolled.tile_width(nb, 1 << 20)
    assert width == 1 << nb  # segment-capped
    # B=6 window starting 2.5 segments before the domain end: only the
    # remaining segments materialize, the rest is padding
    start = hard_end - (5 << (nb - 1)) + 1  # 2.5 segments left
    plan = rolled.plan_tiles(start, 6 * width, nb, width, 8, hard_end)
    covered = int(plan.valids.sum())
    assert covered == hard_end - start + 1
    real = plan.valids > 0
    assert real.sum() == 3  # 2 full + 1 half segment
    assert (plan.valids[~real] == 0).all()
    # global order, and every tile inside one segment
    gs = plan.goffs[real]
    assert (np.diff(gs.astype(np.int64)) > 0).all()
    for i in np.flatnonzero(real):
        g = start + int(plan.goffs[i])
        en, nonce = chain.split_global(g, nb)
        assert en == (int(plan.en_hi[i]) << 32 | int(plan.en_lo[i]))
        assert nonce == int(plan.bases[i])
        assert nonce + int(plan.valids[i]) <= 1 << nb
    # a window too wide for the row budget raises loudly (unclamped)
    with pytest.raises(ValueError):
        rolled.plan_tiles(0, 20 * width, nb, width, 8, hard_end)


def _drain(gen):
    result = None
    for item in gen:
        if item is not None:
            result = item
    return result


def test_jax_miner_rolled_batched_equals_per_segment_baseline(ground_truth):
    """`--roll-batch 1` reproduces today's behavior bit-for-bit: the
    batched tracking sweep and the per-segment loop return identical
    Results on found, exhausted, and ragged partial-chunk jobs."""
    from tpuminter.jax_worker import JaxMiner

    prefix, suffix, branch, hdr80, all_h, h_min, g_min = ground_truth
    lo, hi = (1 << NB) + 100, (3 << NB) + 50
    jobs = [
        _rolled_request(ground_truth, target=h_min),          # found
        _rolled_request(ground_truth, target=1),              # exhausted
        _rolled_request(ground_truth, target=1, lower=lo, upper=hi),
    ]
    for req in jobs:
        base = _drain(JaxMiner(batch=512, roll_batch=1).mine(req))
        for rb in (2, 8):
            got = _drain(JaxMiner(batch=512, roll_batch=rb).mine(req))
            assert (got.found, got.nonce, got.hash_value, got.searched) == (
                base.found, base.nonce, base.hash_value, base.searched
            ), (rb, req.lower, req.upper)


@pytest.fixture(scope="module")
def candidate_truth(ground_truth):
    """The fixture space's candidates at an 8-bit candidate bar (top
    hash byte zero) — what the fast path surfaces when tests shrink
    ``cand_bits`` to make a CI-sized space contain candidates."""
    *_, all_h, _, _ = ground_truth
    cands = [(h, g) for h, g in all_h if h >> 248 == 0]
    assert len(cands) >= 4  # the seed-0 space has a healthy candidate set
    return cands


def test_fast_tracking_equivalence_batched_and_unbatched(
    ground_truth, candidate_truth
):
    """Fast/tracking equivalence regression: on an overlapping
    toy-difficulty rolled job — target = the candidate minimum, so every
    winner clears the candidate bar and both paths are exact — the
    candidate pipeline (`mine_rolled_fast`, TpuMiner's engine) and the
    tracking sweep (`mine_rolled_tracking`) return identical (found,
    nonce, hash), batched and unbatched."""
    from tpuminter import rolled
    from tpuminter.jax_worker import JaxMiner

    h_c, g_c = min(candidate_truth)
    req = _rolled_request(ground_truth, target=h_c)
    results = {
        "fast_b4": _drain(rolled.mine_rolled_fast(
            req, slab=256, roll_batch=4, engine="jnp", cand_bits=8)),
        "fast_b1": _drain(rolled.mine_rolled_fast(
            req, slab=256, roll_batch=1, engine="jnp", cand_bits=8)),
        "tracking_b4": _drain(rolled.mine_rolled_tracking(
            req, width_cap=256, roll_batch=4)),
        "tracking_b1": _drain(JaxMiner(batch=256, roll_batch=1).mine(req)),
    }
    for name, r in results.items():
        assert (r.found, r.nonce, r.hash_value) == (True, g_c, h_c), (name, r)
        assert r.nonce >> NB >= 1, name  # the roll actually happened
    # ordered acceptance: everything below the winner was searched. The
    # sequential baseline stops at exactly the prefix; the batched
    # pipeline may additionally count in-flight windows above the win
    # that resolved before it (honest coverage, never less than prefix).
    assert results["fast_b1"].searched == g_c + 1
    assert g_c + 1 <= results["fast_b4"].searched <= req.upper + 1


def test_fast_exhausted_candidate_min_batched_matches_baseline(
    ground_truth, candidate_truth
):
    """Exhausted fast sweeps report the exact range minimum iff a
    candidate surfaced — and the batched path's global-index candidate
    bookkeeping agrees with the per-segment baseline."""
    from tpuminter import rolled

    req = _rolled_request(ground_truth, target=1)  # unbeatable
    want = min(candidate_truth)
    for rb in (1, 4):
        r = _drain(rolled.mine_rolled_fast(
            req, slab=256, roll_batch=rb, engine="jnp", cand_bits=8))
        assert not r.found
        assert (r.hash_value, r.nonce) == want, rb
        assert r.searched == ENS << NB, rb
