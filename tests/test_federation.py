"""Federation tier tests (ISSUE 18).

The tentpole drills: a two-tier tree (client → parent coordinator →
aggregator → local fleet) mines a rolled TARGET job to the exact
brute-forced minimum; the exactly-once ledger holds across an
aggregator crash mid-lease, a sibling steal of an un-beaconed suffix,
and a parent failover to a promoted standby. Around them, the
unit layers one seam at a time:

- codec: the epoch-bearing RollAssign/Beacon binary variants, the
  aggregator Join fallback, the JSON-only Steal;
- policy: ``federation.steal.pick_victim`` against hand-built books,
  the bounded StolenRegistry;
- durability: lease records through journal replay, and the restarted
  aggregator's one-sided drop of recovered leases;
- folds (satellite): two-level ``tree_merge`` equals the flat fold for
  every discipline, under duplicate delivery, replay, and
  partial-coverage reporting;
- transport (satellite): the slow-loris read/first-message deadlines
  at the ConnState layer — total-time bounds that byte-per-epoch
  drip-feeding cannot evade;
- scale (satellite): >= 20k durable ckeys through the quota and
  winner/dedup tables stay inside their caps (100k behind ``-m slow``);
- WAL bound (satellite): live compaction keeps a writer-mode journal
  file bounded under sustained load.
"""

import asyncio
import dataclasses
import os
import random
import time
from collections import OrderedDict

import pytest

from tpuminter.client import JobRefused, submit
from tpuminter.coordinator import QUOTA_BUCKETS_CAP, Coordinator
from tpuminter.federation import steal as fsteal
from tpuminter.federation.aggregator import Aggregator
from tpuminter.federation.lease import Lease, lease_end_record, lease_record
from tpuminter.journal import Journal, replay
from tpuminter.lsp import LspConnectError, LspConnectionLost
from tpuminter.lsp.connection import _MORE, ConnState
from tpuminter.lsp.message import Frame, MsgType
from tpuminter.lsp.params import Params
from tpuminter.protocol import (
    Beacon,
    Join,
    PowMode,
    RollAssign,
    Steal,
    decode_msg,
    encode_msg,
    payload_is_binary,
)
from tpuminter.worker import CpuMiner, run_miner
from tpuminter.workloads import folds as wfolds

from tests.test_e2e import FAST, run
from tests.test_extranonce import fixture
from tests.test_roll_budget import NB, _brute, _rolled_request


# ---------------------------------------------------------------------------
# codec: the epoch-bearing wire variants
# ---------------------------------------------------------------------------

def test_rollassign_and_beacon_epoch_variants_roundtrip_binary():
    for msg in (
        RollAssign(3, 17, 5, 4, lease_epoch=9),
        RollAssign(3, 17, 5, 4),  # epoch 0: the legacy tag
        Beacon(3, 17, 5000, 42, 0xDEAD, lease_epoch=2),
        Beacon(3, 17, 5000, 42, 0xDEAD),
    ):
        raw = encode_msg(msg, binary=True)
        assert payload_is_binary(raw)
        assert decode_msg(raw) == msg
        # JSON stays the universal fallback
        assert decode_msg(encode_msg(msg, binary=False)) == msg


def test_aggregator_join_falls_back_to_json_and_steal_roundtrips():
    join = Join(backend="agg", lanes=8, codec="bin", roll=True, agg="a1")
    raw = encode_msg(join, binary=True)
    # the binary Join layout predates the agg field: composing tiers
    # must not silently drop the hello, so it rides JSON
    assert not payload_is_binary(raw)
    assert decode_msg(raw) == join
    for steal in (Steal(), Steal(job_id=7)):
        assert decode_msg(encode_msg(steal, binary=True)) == steal


# ---------------------------------------------------------------------------
# policy: pick_victim against hand-built books
# ---------------------------------------------------------------------------

class _M:
    def __init__(self, conn_id, chunks):
        self.conn_id = conn_id
        self.chunks = OrderedDict(chunks)


class _J:
    def __init__(self, request, done=False):
        self.request = request
        self.done = done


def _books(steal_after=0.5, now=100.0):
    seg = 1 << NB
    req = _rolled_request(8, target=1)
    jobs = {1: _J(req)}
    # conn 10 holds a stalled whole-segment chunk (cid 100, age 10s)
    # and a FRESH one (cid 101); conn 20 (the thief) holds its own
    miners = {
        10: _M(10, {
            100: (1, 0, 4 * seg - 1, now - 10.0),
            101: (1, 4 * seg, 8 * seg - 1, now - 0.1),
        }),
        20: _M(20, {102: (1, 8 * seg, 12 * seg - 1, now - 10.0)}),
    }
    return miners, jobs, req, seg


def test_pick_victim_takes_the_oldest_stalled_whole_segment_chunk():
    miners, jobs, _req, seg = _books()
    got = fsteal.pick_victim(
        miners, jobs, {}, thief_conn=20, steal_after=0.5, now=100.0
    )
    assert got == (10, 100, 1, 0, 4 * seg - 1)


def test_pick_victim_denials():
    miners, jobs, req, seg = _books()
    deny = dict(thief_conn=20, steal_after=0.5, now=100.0)
    # never rob yourself: the only other holder is the thief
    assert fsteal.pick_victim(
        {20: miners[20]}, jobs, {}, **deny
    ) is None
    # audits are evidence, not capacity
    assert fsteal.pick_victim(
        miners, jobs, {100: object(), 101: object()}, **deny
    ) is None
    # a beaconing (fresh-progress) holder is not a straggler
    fresh = {10: _M(10, {100: (1, 0, 4 * seg - 1, 99.9)})}
    assert fsteal.pick_victim(fresh, jobs, {}, **deny) is None
    # done job / unknown job
    assert fsteal.pick_victim(
        miners, {1: _J(req, done=True)}, {}, **deny
    ) is None
    # sub-segment suffix finishes sooner than a re-lease round-trips
    subseg = {10: _M(10, {100: (1, 0, seg - 2, 90.0)})}
    assert fsteal.pick_victim(subseg, jobs, {}, **deny) is None
    # non-rolled and scrypt jobs never qualify
    flat = dataclasses.replace(req, coinbase_prefix=None, target=1)
    assert fsteal.pick_victim(miners, {1: _J(flat)}, {}, **deny) is None
    # job_id filter narrows the hunt
    assert fsteal.pick_victim(
        miners, jobs, {}, job_id=2, **deny
    ) is None


def test_stolen_registry_is_bounded_and_remembers_newest():
    reg = fsteal.StolenRegistry(cap=4)
    for cid in range(10):
        reg.add(cid, lease_epoch=cid + 1)
    assert len(reg) == 4
    assert 9 in reg and 6 in reg
    assert 5 not in reg and 0 not in reg
    with pytest.raises(ValueError):
        fsteal.StolenRegistry(cap=0)


# ---------------------------------------------------------------------------
# durability: lease records through replay; one-sided drop on restart
# ---------------------------------------------------------------------------

def test_lease_records_replay_open_leases_only():
    l1 = Lease(parent_job_id=5, parent_chunk_id=100, lower=0,
               upper=4095, lease_epoch=2, inner_job_id=9)
    l2 = Lease(parent_job_id=5, parent_chunk_id=101, lower=4096,
               upper=8191)
    assert Lease.from_record(lease_record(l1)) == l1
    records = [
        {"k": "boot", "epoch": 1},
        {"k": "lease", **lease_record(l1)},
        {"k": "lease", **lease_record(l2)},
        {"k": "lease_end", **lease_end_record(l2.parent_chunk_id)},
    ]
    state = replay(records)
    assert set(state.leases) == {100}
    assert Lease.from_record(state.leases[100]) == l1
    # double replay is a structural no-op, same as every other kind
    assert set(replay(records + records).leases) == {100}
    # a snapshot carries open leases across compaction
    state2 = replay(
        [{"k": "boot", "epoch": 1}, state.snapshot_obj()]
    )
    assert Lease.from_record(state2.leases[100]) == l1


def test_restarted_aggregator_drops_recovered_leases(tmp_path):
    wal = str(tmp_path / "agg.wal")

    async def scenario():
        journal, _ = Journal.open(wal)
        for pc in (100, 101):
            journal.append("lease", lease_record(Lease(
                parent_job_id=5, parent_chunk_id=pc,
                lower=0, upper=4095,
            )))
        await journal.flush()
        await journal.aclose()
        agg = await Aggregator.create(
            "a1", [("127.0.0.1", 1)], params=FAST, recover_from=wal,
        )
        # the open leases were dropped one-sidedly at boot: the parent
        # already requeued those ranges, possibly to a sibling
        assert agg.stats["leases_dropped"] == 2
        assert not agg.inner.recovered_leases
        await agg.close()
        state = replay_wal(wal)
        assert not state.leases

    def replay_wal(path):
        from tpuminter.journal import scan
        with open(path, "rb") as fh:
            records, _clean = scan(fh.read())
        return replay(records)

    run(scenario())


# ---------------------------------------------------------------------------
# the two-tier drills (the tier-1 federation gate)
# ---------------------------------------------------------------------------

async def _fleet(port, n=2, batch=64):
    return [
        asyncio.ensure_future(run_miner(
            "127.0.0.1", port, CpuMiner(batch=batch), params=FAST,
            roll=True, beacon_interval=1e-6,
        ))
        for _ in range(n)
    ]


async def _teardown(miners=(), serves=(), nodes=()):
    for t in list(miners) + list(serves):
        t.cancel()
    await asyncio.gather(*miners, *serves, return_exceptions=True)
    for node in nodes:
        try:
            await node.close()
        except Exception:
            pass


def test_two_tier_rolled_target_end_to_end():
    """Client → parent → aggregator → fleet: the exact brute-forced
    minimum comes back through both tiers, every index is counted at
    the parent exactly once, and the parent's control traffic is the
    MERGED beacon stream (at most one per lease per tick), not the
    fleet's."""
    ens = 8
    prefix, suffix, branch, hdr80 = fixture()
    h_min, g_min = _brute(prefix, suffix, branch, hdr80, ens)
    req = _rolled_request(ens, target=1)

    async def scenario():
        parent = await Coordinator.create(params=FAST, roll_budget=4)
        pserve = asyncio.ensure_future(parent.serve())
        agg = await Aggregator.create(
            "a1", [("127.0.0.1", parent.port)], params=FAST,
            beacon_interval=0.05, roll_budget=2,
        )
        aserve = asyncio.ensure_future(agg.serve())
        miners = await _fleet(agg.port)
        try:
            res = await asyncio.wait_for(
                submit("127.0.0.1", parent.port, req, params=FAST), 60.0
            )
            assert not res.found
            assert (res.hash_value, res.nonce) == (h_min, g_min)
            assert parent.stats["hashes"] == ens << NB
            assert parent.stats["leases_delegated"] > 0
            assert agg.stats["leases_taken"] > 0
            assert agg.stats["results_up"] > 0
            # fan-in flattening: the parent accepted (far) fewer
            # beacons than the inner tier absorbed from the fleet
            inner_beacons = agg.inner.stats["beacons_accepted"]
            if inner_beacons:
                assert (
                    parent.stats["beacons_accepted"] <= inner_beacons
                )
        finally:
            await _teardown(miners, [aserve, pserve], [agg, parent])

    run(scenario())


def test_aggregator_crash_mid_lease_is_exactly_once(tmp_path):
    """Kill the aggregator mid-lease (journal crashed, no goodbye),
    restart it over the same WAL with a fresh fleet: the parent
    requeues the dead tier's dispatches, the restarted node drops any
    replayed open lease, and the job still settles to the exact
    minimum with every index counted at the parent exactly once."""
    ens = 8
    prefix, suffix, branch, hdr80 = fixture()
    h_min, g_min = _brute(prefix, suffix, branch, hdr80, ens)
    req = _rolled_request(ens, target=1)
    wal = str(tmp_path / "agg.wal")

    async def scenario():
        parent = await Coordinator.create(params=FAST, roll_budget=2)
        pserve = asyncio.ensure_future(parent.serve())
        agg1 = await Aggregator.create(
            "a1", [("127.0.0.1", parent.port)], params=FAST,
            recover_from=wal, beacon_interval=0.05, roll_budget=1,
        )
        aserve1 = asyncio.ensure_future(agg1.serve())
        miners1 = await _fleet(agg1.port)
        submit_task = asyncio.ensure_future(submit(
            "127.0.0.1", parent.port, req, params=FAST
        ))
        agg2 = None
        aserve2 = None
        miners2 = []
        try:
            t0 = time.monotonic()
            while agg1.stats["leases_taken"] < 1:
                assert time.monotonic() - t0 < 30, "no lease ever taken"
                await asyncio.sleep(0.005)
            # -- kill -9 mid-lease -----------------------------------
            agg1.crash()
            for t in miners1:
                t.cancel()
            await asyncio.gather(*miners1, return_exceptions=True)
            aserve1.cancel()
            await asyncio.gather(aserve1, return_exceptions=True)
            # -- restart over the same journal -----------------------
            agg2 = await Aggregator.create(
                "a1", [("127.0.0.1", parent.port)], params=FAST,
                recover_from=wal, beacon_interval=0.05, roll_budget=1,
            )
            aserve2 = asyncio.ensure_future(agg2.serve())
            miners2 = await _fleet(agg2.port)
            res = await asyncio.wait_for(submit_task, 60.0)
            submit_task = None
            assert not res.found
            assert (res.hash_value, res.nonce) == (h_min, g_min)
            # the parent's ledger: every index settled exactly once —
            # beaconed prefixes kept, the requeued remainder re-mined
            # by the restarted tier, nothing double-counted
            assert parent.stats["hashes"] == ens << NB
        finally:
            if submit_task is not None:
                submit_task.cancel()
                await asyncio.gather(submit_task, return_exceptions=True)
            serves = [s for s in (aserve2, pserve) if s is not None]
            nodes = [n for n in (agg2, parent) if n is not None]
            await _teardown(miners2, serves, nodes)

    run(scenario())


def test_sibling_steals_the_unbeaconed_suffix():
    """Two sibling aggregators under one parent: one's fleet never
    progresses, the other drains early and Steals. The parent
    re-leases the stalled assignment's un-beaconed suffix under a
    bumped lease epoch; the thief mines it and the job settles to the
    exact minimum with no index double-counted."""
    ens = 8
    prefix, suffix, branch, hdr80 = fixture()
    h_min, g_min = _brute(prefix, suffix, branch, hdr80, ens)
    req = _rolled_request(ens, target=1)

    async def scenario():
        parent = await Coordinator.create(
            params=FAST, roll_budget=4, pipeline_depth=1,
            steal_after=0.1,
        )
        pserve = asyncio.ensure_future(parent.serve())
        # the straggler: a tier with NO fleet — its lease never moves
        slow = await Aggregator.create(
            "slow", [("127.0.0.1", parent.port)], params=FAST,
            beacon_interval=0.05, roll_budget=1,
        )
        sserve = asyncio.ensure_future(slow.serve())
        fast = await Aggregator.create(
            "fast", [("127.0.0.1", parent.port)], params=FAST,
            beacon_interval=0.05, steal_interval=0.15, roll_budget=1,
        )
        fserve = asyncio.ensure_future(fast.serve())
        miners = await _fleet(fast.port)
        try:
            t0 = time.monotonic()
            while len(parent._miners) < 2:
                assert time.monotonic() - t0 < 30
                await asyncio.sleep(0.005)
            res = await asyncio.wait_for(
                submit("127.0.0.1", parent.port, req, params=FAST), 60.0
            )
            assert not res.found
            assert (res.hash_value, res.nonce) == (h_min, g_min)
            assert parent.stats["chunks_stolen"] >= 1
            assert fast.stats["steals_sent"] >= 1
            # exactly-once across the steal: the stolen suffix settled
            # through the thief only
            assert parent.stats["hashes"] == ens << NB
        finally:
            await _teardown(
                miners, [fserve, sserve, pserve], [fast, slow, parent]
            )

    run(scenario())


def test_parent_failover_to_promoted_standby(tmp_path):
    """Kill the parent machine mid-lease: the WAL-shipped standby
    promotes with a fenced epoch, the aggregator's upward rotation
    lands on it, the durable client re-submits and rebinds, and the
    answer is still the exact two-tier minimum."""
    ens = 8
    prefix, suffix, branch, hdr80 = fixture()
    h_min, g_min = _brute(prefix, suffix, branch, hdr80, ens)
    req = _rolled_request(ens, target=1, client_key="t:fed")
    pwal = str(tmp_path / "parent.wal")
    swal = str(tmp_path / "standby.wal")

    async def resilient_submit(ports):
        while True:
            for port in ports:
                try:
                    return await submit(
                        "127.0.0.1", port, req, params=FAST,
                    )
                except (LspConnectError, LspConnectionLost, JobRefused):
                    await asyncio.sleep(0.05)

    async def scenario():
        from tpuminter.replication import ReplicationStandby

        standby = await ReplicationStandby.create(swal, params=FAST)
        standby_task = asyncio.ensure_future(standby.run())
        parent = await Coordinator.create(
            params=FAST, roll_budget=2, recover_from=pwal,
            replicate_to=[("127.0.0.1", standby.port)],
        )
        pserve = asyncio.ensure_future(parent.serve())
        agg = await Aggregator.create(
            "a1",
            [("127.0.0.1", parent.port), ("127.0.0.1", standby.port)],
            params=FAST, beacon_interval=0.05, roll_budget=1,
        )
        aserve = asyncio.ensure_future(agg.serve())
        miners = await _fleet(agg.port)
        client = asyncio.ensure_future(
            resilient_submit([parent.port, standby.port])
        )
        promoted = None
        promoted_serve = None
        try:
            t0 = time.monotonic()
            while parent.stats["leases_delegated"] < 1:
                assert time.monotonic() - t0 < 30, "no lease delegated"
                await asyncio.sleep(0.005)
            # -- the parent machine dies -----------------------------
            parent.crash()
            await asyncio.wait_for(standby.primary_lost.wait(), 15.0)
            promoted = await standby.promote(roll_budget=2)
            promoted_serve = asyncio.ensure_future(promoted.serve())
            res = await asyncio.wait_for(client, 60.0)
            client = None
            assert not res.found
            assert (res.hash_value, res.nonce) == (h_min, g_min)
            # the promoted parent served the surviving tier: the
            # aggregator rotated to it and leased from it
            assert promoted.stats["leases_delegated"] >= 1
        finally:
            if client is not None:
                client.cancel()
                await asyncio.gather(client, return_exceptions=True)
            pserve.cancel()
            standby_task.cancel()
            serves = [s for s in (promoted_serve,) if s is not None]
            await asyncio.gather(
                pserve, standby_task, return_exceptions=True
            )
            nodes = [agg] + ([promoted] if promoted is not None else [])
            await _teardown(miners, [aserve] + serves, nodes)

    run(scenario())


# ---------------------------------------------------------------------------
# folds satellite: two-level tree_merge == flat fold
# ---------------------------------------------------------------------------

def _fold_cases():
    return [
        wfolds.FMin(),
        wfolds.TopK(4),
        wfolds.FirstMatch(threshold=1 << 18),
        wfolds.FSum(),
    ]


def _chunk_partials(fold, rng, n_chunks=12, width=16):
    """Per-chunk accumulators over a deterministic value landscape,
    keyed by chunk index (the dedup key a coverage gate uses)."""
    partials = {}
    for c in range(n_chunks):
        values = [rng.randrange(1 << 22) for _ in range(width)]
        partials[c] = fold.of_batch(c * width, values)
    return partials


def _flat(fold, parts):
    acc = fold.initial()
    for p in parts:
        acc = fold.combine(acc, p)
    return acc


def test_two_level_merge_equals_flat_fold_for_every_discipline():
    rng = random.Random(18)
    for fold in _fold_cases():
        partials = _chunk_partials(fold, rng)
        chunks = list(partials)
        for _trial in range(20):
            rng.shuffle(chunks)
            # random partition into aggregator-sized groups
            groups, i = [], 0
            while i < len(chunks):
                step = rng.randrange(1, 5)
                groups.append(
                    [partials[c] for c in chunks[i:i + step]]
                )
                i += step
            assert wfolds.tree_merge(fold, groups) == _flat(
                fold, [partials[c] for c in sorted(partials)]
            ), fold.name


def test_duplicate_delivery_and_replay_are_harmless_when_gated():
    """Idempotent folds absorb duplicates structurally; the sum fold
    (and fmatch's probe count) rely on the coverage gate instead —
    modeled here as per-chunk dedup at EACH tier, which is exactly
    what the journal plane's interval subtraction provides. Composed
    tiers therefore stay exactly-once without any cross-tier
    bookkeeping."""
    rng = random.Random(19)
    for fold in _fold_cases():
        partials = _chunk_partials(fold, rng)
        want = _flat(fold, [partials[c] for c in sorted(partials)])
        chunks = list(partials) + list(partials)[:5]  # duplicates
        rng.shuffle(chunks)
        if fold.idempotent and fold.name != "fmatch":
            # duplicates may flow straight into the fold
            groups = [
                [partials[c] for c in chunks[:7]],
                [partials[c] for c in chunks[7:]],
            ]
            # replay: the whole second group delivered twice
            groups.append(groups[1])
            assert wfolds.tree_merge(fold, groups) == want, fold.name
        # with the per-tier gate (dedup by chunk id at each level),
        # EVERY fold — including non-idempotent sum — composes
        seen_l1, seen_l2 = set(), set()
        groups = [[], []]
        for j, c in enumerate(chunks):
            tier = j % 2
            seen = seen_l1 if tier == 0 else seen_l2
            if c in seen:
                continue  # the gate: a range absorbs once per tier
            seen.add(c)
            groups[tier].append(partials[c])
        if seen_l1 & seen_l2:
            # cross-group duplicates must be gated at the TOP tier
            # too; model the parent's gate by removing them
            dup = seen_l1 & seen_l2
            groups[1] = [
                partials[c] for c in sorted(seen_l2 - dup)
            ]
        assert wfolds.tree_merge(fold, groups) == want, fold.name


def test_partial_coverage_beacons_compose():
    """A tier reporting only a prefix of its chunks (the merged-beacon
    shape) still composes: the two-level merge over any reported
    subset equals the flat fold over that subset, for every fold."""
    rng = random.Random(20)
    for fold in _fold_cases():
        partials = _chunk_partials(fold, rng)
        for _trial in range(10):
            reported = sorted(
                c for c in partials if rng.random() < 0.6
            )
            cut = rng.randrange(len(reported) + 1)
            groups = [
                [partials[c] for c in reported[:cut]],
                [partials[c] for c in reported[cut:]],
            ]
            assert wfolds.tree_merge(fold, groups) == _flat(
                fold, [partials[c] for c in reported]
            ), fold.name


# ---------------------------------------------------------------------------
# transport satellite: slow-loris deadlines at the ConnState layer
# ---------------------------------------------------------------------------

def _conn(**params):
    delivered, lost = [], []
    conn = ConnState(
        1, Params(**params), lambda f: None, delivered.append,
        lost.append,
    )
    return conn, delivered, lost


def test_drip_feeder_hits_the_total_time_read_deadline():
    """One more-fragments frame per epoch: byte progress EVERY epoch,
    so the silent-epoch liveness never fires — only the total-time
    read deadline bounds it."""
    conn, delivered, lost = _conn(read_deadline_epochs=6)
    seq = 1
    for _epoch in range(10):
        conn.on_frame(Frame(MsgType.DATA, 1, seq, bytes(_MORE) + b"z"))
        seq += 1
        conn.on_epoch()
        if conn.lost:
            break
    assert conn.lost and lost
    assert "mid-reassembly" in lost[0]
    assert not delivered


def test_completed_messages_reset_the_reassembly_clock():
    conn, delivered, _lost = _conn(read_deadline_epochs=4)
    seq = 1
    for _round in range(5):
        # two fragments, two epochs apart: finishes inside the bound
        conn.on_frame(Frame(MsgType.DATA, 1, seq, bytes(_MORE) + b"a"))
        seq += 1
        conn.on_epoch()
        conn.on_frame(Frame(MsgType.DATA, 1, seq, b"\x00" + b"b"))
        seq += 1
        conn.on_epoch()
    assert not conn.lost
    assert len(delivered) == 5


def test_mute_peer_hits_the_first_message_deadline():
    conn, _delivered, lost = _conn(read_deadline_epochs=3)
    conn.first_msg_deadline_epochs = 3
    for _epoch in range(5):
        # heartbeats flow: liveness is satisfied, only the first-app-
        # message deadline can fire
        conn._received_this_epoch = True
        conn.on_epoch()
        if conn.lost:
            break
    assert conn.lost and lost
    assert "no application message" in lost[0]


def test_deadlines_default_off_and_honest_peers_unaffected():
    conn, delivered, _lost = _conn()
    assert conn.params.read_deadline_epochs == 0
    conn.on_frame(Frame(MsgType.DATA, 1, 1, b"\x00hello"))
    for _epoch in range(4):
        conn._received_this_epoch = True
        conn.on_epoch()
    assert not conn.lost
    assert len(delivered) == 1
    with pytest.raises(ValueError):
        Params(read_deadline_epochs=-1)


# ---------------------------------------------------------------------------
# scale satellite: durable ckeys through the bounded tables
# ---------------------------------------------------------------------------

def _scale_probe(n_keys):
    # winner/dedup table: n_keys distinct durable identities replayed
    # through the journal fold stay inside winners_cap, newest kept
    records = [{"k": "boot", "epoch": 1}]
    for i in range(n_keys):
        records.append({
            "k": "finish", "id": i + 1, "ckey": f"scale:{i}", "cjid": 1,
            "mode": PowMode.MIN.value, "n": i, "h": "ff", "found": False,
            "s": 1, "ts": 0.0,
        })
    cap = 2048
    state = replay(records, winners_cap=cap)
    assert len(state.winners) == cap
    assert (f"scale:{n_keys - 1}", 1) in state.winners
    assert (f"scale:{n_keys - cap - 1}", 1) not in state.winners
    assert not state.jobs  # every finish retired its job

    async def quota():
        coord = await Coordinator.create(
            params=FAST, quota_rate=5.0, quota_burst=2.0,
        )
        req = _rolled_request(1, target=1)
        admitted = 0
        for i in range(n_keys):
            msg = dataclasses.replace(req, client_key=f"scale:{i}")
            if coord._admit(i, msg) == 0:
                admitted += 1
        # every identity got its burst admission; the bucket table
        # LRU-shed down to its cap instead of holding n_keys entries
        assert admitted == n_keys
        assert len(coord._buckets) <= QUOTA_BUCKETS_CAP
        await coord.close()

    run(quota())


def test_scale_probe_20k_durable_ckeys():
    _scale_probe(20_000)


@pytest.mark.slow
def test_scale_probe_100k_durable_ckeys():
    _scale_probe(100_000)


# ---------------------------------------------------------------------------
# WAL-bound satellite: live compaction keeps the file bounded
# ---------------------------------------------------------------------------

def test_writer_wal_stays_bounded_under_sustained_load(tmp_path):
    """Soak shape: many short-lived jobs through a writer-mode journal
    with a small compaction threshold — the live state stays tiny, so
    automatic compaction must keep the FILE bounded (threshold plus
    one snapshot plus the batch in flight), not merely growing slower."""
    path = str(tmp_path / "soak.wal")

    async def scenario():
        from tests.test_replication import _req_obj

        journal, state = Journal.open(path, compact_bytes=32 * 1024)
        # owner's contract: compaction needs a snapshot of live state —
        # fold the same records into a shadow and hand it over, exactly
        # as the coordinator's snapshot_provider does
        journal.snapshot_provider = state.snapshot_obj

        def log(kind, obj):
            journal.append(kind, obj)
            state.apply({**obj, "k": kind})

        peak = 0
        for jid in range(1, 2001):
            log("job", {"id": jid, "req": _req_obj(jid)})
            log("finish", {
                "id": jid, "ckey": "", "cjid": 0,
                "mode": PowMode.MIN.value, "n": 0, "h": "ff",
                "found": False, "s": 1, "ts": 0.0,
            })
            if jid % 100 == 0:
                await journal.flush()
                peak = max(peak, os.path.getsize(path))
        await journal.flush()
        peak = max(peak, os.path.getsize(path))
        await journal.aclose()
        assert journal.stats["compactions"] >= 1
        # bound: threshold + one snapshot of (tiny) live state + slack
        # for the record batch in flight when the threshold tripped
        assert peak < 3 * 32 * 1024, peak
        # and the surviving file still replays to the right state
        _journal2, state = Journal.open(path)
        assert not state.jobs

    run(scenario())
