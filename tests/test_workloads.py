"""The pluggable-workload plane (ISSUE 15), pinned at every seam:
registry collision rules, the params and chunk-partial codecs (tagged +
CRC-trailed, same discipline as the wire codec), per-fold reduction
semantics, the coverage gate that makes NON-idempotent folds
exactly-once under replay, segmented-WAL state merges, the off-loop
verifier's trust model, the worker compute seam, and — as deterministic
mirrors of tests/test_properties.py's hypothesis cases (this image
lacks hypothesis) — seeded random schedules for replay idempotence,
chunk-order independence, and beacon-style partial-settle splits.

The tier-1 gate for the full fleet drill (`loadgen --scenario workload
--smoke`: real CpuMiners through a worker kill + a kill -9 coordinator
crash with an exact-answer-per-fold ledger) rides at the bottom,
mirroring test_recovery.py's crash-scenario gate.
"""

import json as _json
import os
import random
import sys

import pytest

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ),
)

import loadgen  # noqa: E402  (scripts/ is not a package)

from tpuminter import workloads  # noqa: E402
from tpuminter.protocol import (  # noqa: E402
    PowMode,
    Request,
    WorkResult,
)
from tpuminter.workloads import (  # noqa: E402
    FMin,
    FSum,
    FirstMatch,
    TopK,
    Workload,
    absorb,
    absorb_payload,
    fold_of,
    merge_states,
    new_state,
)
from tpuminter.workloads import folds  # noqa: E402
from tpuminter.workloads import hashcore as hc  # noqa: E402

ALL_FOLDS = (FMin(), TopK(3), FirstMatch(1 << 60), FSum())


def _req(variant="fmin", seed=7, threshold=0, k=3, lo=0, hi=99,
         job_id=1, chunk_id=1):
    return Request(
        job_id=job_id, mode=PowMode.MIN, lower=lo, upper=hi,
        data=hc.pack_params(variant, seed=seed, threshold=threshold, k=k),
        chunk_id=chunk_id, workload="hashcore",
    )


def _vals(seed, lo, hi):
    return [hc.objective(seed, i) for i in range(lo, hi + 1)]


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_hashcore_is_registered_and_advertised(self):
        assert "hashcore" in workloads.names()
        assert workloads.get("hashcore").wid == hc.HASHCORE_WID
        assert workloads.by_wid(hc.HASHCORE_WID).name == "hashcore"
        assert workloads.maybe("no-such-workload") is None

    def test_register_rejects_name_and_wid_collisions(self):
        class Clone(Workload):
            name = "hashcore"
            wid = 250

        with pytest.raises(ValueError, match="name"):
            workloads.register(Clone())

        class WidClash(Workload):
            name = "widclash-test"
            wid = hc.HASHCORE_WID

        with pytest.raises(ValueError, match="wid"):
            workloads.register(WidClash())
        assert "widclash-test" not in workloads.names()

    def test_register_rejects_bad_identity(self):
        class NoName(Workload):
            name = ""
            wid = 7

        with pytest.raises(ValueError, match="name"):
            workloads.register(NoName())

        class BadWid(Workload):
            name = "badwid-test"
            wid = 256

        with pytest.raises(ValueError, match="u8"):
            workloads.register(BadWid())

    def test_reregistering_the_same_object_is_idempotent(self):
        live = workloads.get("hashcore")
        assert workloads.register(live) is live


# ---------------------------------------------------------------------------
# params codec: tag | fields | crc, every corruption is a loud refusal
# ---------------------------------------------------------------------------

class TestParamsCodec:
    def test_roundtrip_every_variant(self):
        for variant in hc.VARIANTS:
            p = hc.parse_params(
                hc.pack_params(variant, seed=99, threshold=5, k=4)
            )
            assert (p.variant, p.seed, p.threshold, p.k) == (
                variant, 99, 5, 4
            )

    def test_pack_rejects_out_of_range(self):
        with pytest.raises(ValueError):
            hc.pack_params("fmin", seed=1 << 64)
        with pytest.raises(ValueError):
            hc.pack_params("fmin", seed=1, threshold=-1)
        with pytest.raises(ValueError):
            hc.pack_params("topk", seed=1, k=folds.TOPK_SLOTS + 1)
        with pytest.raises(ValueError):
            hc.pack_params("nope", seed=1)

    def test_every_single_byte_corruption_is_rejected(self):
        good = hc.pack_params("fmatch", seed=3, threshold=17, k=2)
        hc.parse_params(good)
        for pos in range(len(good)):
            for flip in (0x01, 0x80, 0xFF):
                bad = bytearray(good)
                bad[pos] ^= flip
                if bytes(bad) == good:
                    continue
                with pytest.raises(ValueError):
                    hc.parse_params(bytes(bad))

    def test_truncation_and_padding_are_rejected(self):
        good = hc.pack_params("fsum", seed=3)
        for n in range(len(good)):
            with pytest.raises(ValueError, match="bytes"):
                hc.parse_params(good[:n])
        with pytest.raises(ValueError, match="bytes"):
            hc.parse_params(good + b"\x00")

    def test_fold_of_resolves_and_refuses(self):
        assert isinstance(fold_of(_req("topk", k=5)), TopK)
        assert fold_of(_req("topk", k=5)).k == 5
        assert isinstance(fold_of(_req("fmatch", threshold=9)), FirstMatch)
        # malformed params and unknown workloads resolve to None (the
        # coordinator's Refuse path), never raise on the serve loop
        req = _req()
        object.__setattr__(req, "data", b"garbage")
        assert fold_of(req) is None
        object.__setattr__(req, "workload", "no-such")
        assert fold_of(req) is None


# ---------------------------------------------------------------------------
# chunk-partial codecs: one frame per discipline, CRC load-bearing
# ---------------------------------------------------------------------------

class TestFoldCodecs:
    ACCS = {
        "fmin": [None, [5, 12]],
        "topk": [[], [[3, 7]], [[1, 4], [1, 9], [2, 0]]],
        "fmatch": [None, [None, None, 64], [12, 3, 13]],
        "fsum": [[0, 0], [123456789, 42]],
    }

    def test_roundtrip_per_fold(self):
        for fold in ALL_FOLDS:
            for acc in self.ACCS[fold.name]:
                got = fold.decode(fold.encode(acc))
                want = acc
                if fold.name == "fmatch" and acc == [None, None, 0]:
                    want = None
                assert got == want, (fold.name, acc)

    def test_single_byte_corruption_per_fold(self):
        for fold in ALL_FOLDS:
            wire = fold.encode(self.ACCS[fold.name][-1])
            for pos in range(len(wire)):
                bad = bytearray(wire)
                bad[pos] ^= 0xFF
                with pytest.raises(ValueError):
                    fold.decode(bytes(bad))

    def test_cross_fold_payloads_never_misparse(self):
        # distinct tags: one discipline's frame is a loud error to
        # every other (lengths differ too, the checker's second key)
        for a in ALL_FOLDS:
            wire = a.encode(self.ACCS[a.name][-1])
            for b in ALL_FOLDS:
                if b.name == a.name:
                    continue
                with pytest.raises(ValueError):
                    b.decode(wire)

    def test_encode_rejects_out_of_range(self):
        with pytest.raises(ValueError):
            FMin().encode([1 << 64, 0])
        with pytest.raises(ValueError):
            TopK(2).encode([[0, 1 << 64]])
        with pytest.raises(ValueError):
            FirstMatch(0).encode([1, 1 << 64, 1])
        with pytest.raises(ValueError):
            FSum().encode([1 << 128, 1])

    def test_topk_rejects_overfull_claims(self):
        over = [[v, v] for v in range(folds.TOPK_SLOTS + 1)]
        with pytest.raises(ValueError):
            TopK(folds.TOPK_SLOTS).encode(over)
        wire = bytearray(TopK(2).encode([[1, 2], [3, 4]]))
        wire[1] = folds.TOPK_SLOTS + 1  # forged count
        import zlib as _zlib
        body = bytes(wire[:-4])
        wire[-4:] = folds._CRC.pack(_zlib.crc32(body))
        with pytest.raises(ValueError, match="count"):
            TopK(2).decode(bytes(wire))


# ---------------------------------------------------------------------------
# fold semantics
# ---------------------------------------------------------------------------

class TestFoldSemantics:
    def test_fmin_ties_break_to_the_lowest_index(self):
        f = FMin()
        assert f.combine([5, 9], [5, 3]) == [5, 3]
        assert f.combine(None, [5, 3]) == [5, 3]
        assert f.combine([4, 9], [5, 3]) == [4, 9]
        assert f.of_batch(10, [7, 3, 3, 8]) == [3, 11]

    def test_topk_is_globally_ordered_with_low_index_ties(self):
        f = TopK(3)
        a = f.of_batch(0, [5, 2, 5])     # [[2,1],[5,0],[5,2]]
        b = f.of_batch(10, [2, 5, 1])    # [[1,12],[2,10],[5,11]]
        assert f.combine(a, b) == [[1, 12], [2, 1], [2, 10]]
        # commutative: same answer either way
        assert f.combine(b, a) == f.combine(a, b)
        with pytest.raises(ValueError):
            TopK(0)

    def test_topk_dedups_a_replayed_index(self):
        f = TopK(2)
        assert f.combine([[3, 7]], [[3, 7]]) == [[3, 7]]

    def test_fmatch_probes_account_exactly(self):
        f = FirstMatch(10)
        assert f.of_batch(100, [50, 9, 70]) == [101, 9, 2]
        assert f.of_batch(100, [50, 60, 70]) == [None, None, 3]
        assert f.of_batch(100, []) is None
        # dry prefix + hit: probes accumulate to index - lo + 1
        dry = f.of_batch(0, [99] * 40)
        hit = f.of_batch(40, [99, 4])
        assert f.combine(dry, hit) == [41, 4, 42]
        # two hits keep the earliest index but ALL the probes
        assert f.combine([5, 1, 6], [50, 2, 51]) == [5, 1, 57]
        assert f.is_final([5, 1, 6]) and not f.is_final([None, None, 6])

    def test_fsum_is_a_plain_monoid(self):
        f = FSum()
        assert f.combine([3, 2], [5, 1]) == [8, 3]
        assert f.combine(None, [5, 1]) == [5, 1]
        assert f.of_batch(0, [1, 2, 3]) == [6, 3]
        assert not f.idempotent

    def test_every_fold_matches_a_direct_scan(self):
        seed, lo, hi = 11, 0, 499
        values = _vals(seed, lo, hi)
        pairs = sorted([v, lo + i] for i, v in enumerate(values))
        for fold, want in (
            (FMin(), list(pairs[0])),
            (TopK(3), [list(p) for p in pairs[:3]]),
            (FSum(), [sum(values), len(values)]),
        ):
            acc = fold.initial()
            for at in range(lo, hi + 1, 64):
                end = min(hi, at + 63)
                acc = fold.combine(
                    acc, fold.of_batch(at, values[at - lo:end - lo + 1])
                )
            assert acc == want, fold.name


# ---------------------------------------------------------------------------
# the coverage gate: exactly-once for non-idempotent folds
# ---------------------------------------------------------------------------

class TestCoverageGate:
    def test_absorb_refuses_any_overlap(self):
        f = FSum()
        st = new_state(f)
        assert absorb(f, st, 0, 9, [10, 10])
        assert not absorb(f, st, 0, 9, [10, 10])     # exact replay
        assert not absorb(f, st, 5, 14, [10, 10])    # partial overlap
        assert not absorb(f, st, 9, 9, [1, 1])       # edge touch
        assert not absorb(f, st, 5, 4, [0, 0])       # inverted range
        assert absorb(f, st, 10, 19, [7, 10])
        assert st["acc"] == [17, 20]
        assert st["covered"] == [[0, 19]]            # coalesced

    def test_double_replay_is_a_structural_noop(self):
        # the journal's replay path: same settle stream twice, any fold
        for fold in ALL_FOLDS:
            settles = [
                (0, 9, fold.of_batch(0, _vals(3, 0, 9))),
                (10, 19, fold.of_batch(10, _vals(3, 10, 19))),
            ]
            once = new_state(fold)
            for lo, hi, acc in settles:
                absorb(fold, once, lo, hi, acc)
            twice = new_state(fold)
            for lo, hi, acc in settles + settles:
                absorb(fold, twice, lo, hi, acc)
            assert once == twice, fold.name

    def test_absorb_payload_skips_garbage_and_duplicates(self):
        req = _req("fsum", seed=3, lo=0, hi=19)
        fold = fold_of(req)
        wp = fold.encode([100, 10])
        st, ok = absorb_payload(req, None, 0, 9, wp)
        assert ok and st["acc"] == [100, 10]
        st2, ok = absorb_payload(req, st, 0, 9, wp)
        assert not ok and st2 is st and st["acc"] == [100, 10]
        st3, ok = absorb_payload(req, st, 10, 19, wp[:-1])
        assert not ok and st3["acc"] == [100, 10]


# ---------------------------------------------------------------------------
# merge_states: independent WAL segments
# ---------------------------------------------------------------------------

class TestMergeStates:
    def _state(self, fold, spans, seed=3):
        st = new_state(fold)
        for lo, hi in spans:
            absorb(fold, st, lo, hi, fold.of_batch(lo, _vals(seed, lo, hi)))
        return st

    def test_disjoint_segments_combine_for_every_fold(self):
        for fold in ALL_FOLDS:
            a = self._state(fold, [(0, 9)])
            b = self._state(fold, [(10, 19)])
            whole = self._state(fold, [(0, 9), (10, 19)])
            assert merge_states(fold, a, b) == whole, fold.name

    def test_overlapping_sum_keeps_the_larger_coverage(self):
        f = FSum()
        a = self._state(f, [(0, 19)])
        b = self._state(f, [(10, 29), (40, 44)])
        merged = merge_states(f, a, b)
        assert merged == b                       # 25 indices beats 20
        assert merge_states(f, b, a) == b        # symmetric

    def test_overlapping_idempotent_folds_still_combine(self):
        f = FMin()
        a = self._state(f, [(0, 19)])
        b = self._state(f, [(10, 29)])
        merged = merge_states(f, a, b)
        assert merged["covered"] == [[0, 29]]
        assert merged["acc"] == self._state(f, [(0, 29)])["acc"]

    def test_empty_and_none_edges(self):
        f = FSum()
        a = self._state(f, [(0, 9)])
        assert merge_states(f, None, a) == a
        assert merge_states(f, a, None) == a
        assert merge_states(f, new_state(f), a) == a
        assert merge_states(f, None, None) is None


# ---------------------------------------------------------------------------
# verify_claim: the off-loop trust model, per variant
# ---------------------------------------------------------------------------

class TestVerifyClaim:
    def _result(self, req, acc):
        fold = fold_of(req)
        return WorkResult(
            job_id=req.job_id, chunk_id=req.chunk_id,
            wid=hc.HASHCORE_WID, searched=req.upper - req.lower + 1,
            payload=fold.encode(acc),
        )

    def test_honest_claims_verify(self):
        seed, lo, hi = 21, 64, 191
        values = _vals(seed, lo, hi)
        pairs = sorted([v, lo + i] for i, v in enumerate(values))
        lo_v, lo_i = pairs[0]
        cases = [
            (_req("fmin", seed, lo=lo, hi=hi), [lo_v, lo_i]),
            (_req("topk", seed, k=3, lo=lo, hi=hi),
             [list(p) for p in pairs[:3]]),
            (_req("fmatch", seed, threshold=lo_v, lo=lo, hi=hi),
             [lo_i, lo_v, lo_i - lo + 1]),
            (_req("fmatch", seed, threshold=0, lo=lo, hi=hi),
             [None, None, hi - lo + 1]),
            (_req("fsum", seed, lo=lo, hi=hi),
             [sum(values), len(values)]),
        ]
        for req, acc in cases:
            assert workloads.verify_claim(req, self._result(req, acc)), acc

    def test_byzantine_claims_are_rejected(self):
        seed, lo, hi = 21, 64, 191
        values = _vals(seed, lo, hi)
        pairs = sorted([v, lo + i] for i, v in enumerate(values))
        lo_v, lo_i = pairs[0]
        cases = [
            # wrong value for the witness index
            (_req("fmin", seed, lo=lo, hi=hi), [lo_v ^ 1, lo_i]),
            # witness outside the chunk range
            (_req("fmin", seed, lo=lo, hi=hi),
             [hc.objective(seed, hi + 1), hi + 1]),
            # right pairs, wrong cardinality
            (_req("topk", seed, k=3, lo=lo, hi=hi),
             [list(p) for p in pairs[:2]]),
            # unordered claim
            (_req("topk", seed, k=2, lo=lo, hi=hi),
             [list(pairs[1]), list(pairs[0])]),
            # probes don't account for the dry prefix
            (_req("fmatch", seed, threshold=lo_v, lo=lo, hi=hi),
             [lo_i, lo_v, 1 if lo_i != lo else 2]),
            # "nothing here" hiding a real match: rescan catches it
            (_req("fmatch", seed, threshold=lo_v, lo=lo, hi=hi),
             [None, None, hi - lo + 1]),
            # a later match claimed as first
            (_req("fmatch", seed, threshold=pairs[1][0], lo=lo, hi=hi),
             [pairs[1][1], pairs[1][0], pairs[1][1] - lo + 1]
             if pairs[1][1] > lo_i else None),
            # off-by-one total
            (_req("fsum", seed, lo=lo, hi=hi),
             [sum(values) + 1, len(values)]),
            # short count
            (_req("fsum", seed, lo=lo, hi=hi),
             [sum(values), len(values) - 1]),
        ]
        for req, acc in cases:
            if acc is None:
                continue
            assert not workloads.verify_claim(
                req, self._result(req, acc)
            ), acc

    def test_wid_and_payload_gates(self):
        req = _req("fmin", seed=21, lo=0, hi=9)
        good = self._result(req, [min(_vals(21, 0, 9)), 0])
        wrong_wid = WorkResult(
            job_id=good.job_id, chunk_id=good.chunk_id, wid=200,
            searched=good.searched, payload=good.payload,
        )
        assert not workloads.verify_claim(req, wrong_wid)
        torn = WorkResult(
            job_id=good.job_id, chunk_id=good.chunk_id,
            wid=good.wid, searched=good.searched,
            payload=good.payload[:-1],
        )
        assert not workloads.verify_claim(req, torn)


# ---------------------------------------------------------------------------
# the worker compute seam
# ---------------------------------------------------------------------------

class TestComputeSeam:
    def _drive(self, req, engine="cpu"):
        yields = 0
        for msg in workloads.compute(req, engine=engine):
            if msg is None:
                yields += 1
                continue
            return yields, msg
        raise AssertionError("generator ended without a WorkResult")

    def test_compute_yields_cooperatively_and_folds_exactly(self):
        seed, hi = 5, 3 * 2048 + 100   # several _BATCH steps
        req = _req("fmin", seed=seed, lo=0, hi=hi)
        yields, msg = self._drive(req)
        assert yields >= 3             # one heartbeat per batch
        assert msg.searched == hi + 1
        assert msg.wid == hc.HASHCORE_WID
        values = _vals(seed, 0, hi)
        v = min(values)
        assert fold_of(req).decode(msg.payload) == [v, values.index(v)]
        assert workloads.verify_claim(req, msg)

    def test_engines_agree_bit_exactly(self):
        req = _req("fsum", seed=9, lo=100, hi=4200)
        _, cpu = self._drive(req, engine="cpu")
        _, vec = self._drive(req, engine="jax")
        assert cpu.payload == vec.payload

    def test_first_match_stops_early(self):
        seed, hi = 5, 200_000
        # a threshold high enough that some early index clears it
        req = _req("fmatch", seed=seed, threshold=(1 << 64) // 16, hi=hi)
        _, msg = self._drive(req)
        acc = fold_of(req).decode(msg.payload)
        assert acc[0] is not None
        assert msg.searched < hi + 1   # the cancel mirror: no full scan
        assert workloads.verify_claim(
            Request(
                job_id=req.job_id, mode=PowMode.MIN, lower=0,
                upper=msg.searched - 1, data=req.data,
                chunk_id=req.chunk_id, workload="hashcore",
            ),
            msg,
        )


# ---------------------------------------------------------------------------
# deterministic mirrors of the hypothesis fold properties
# (tests/test_properties.py runs them under hypothesis where available)
# ---------------------------------------------------------------------------

def _random_partition(rng, lo, hi):
    cuts = sorted(rng.sample(range(lo + 1, hi + 1),
                             rng.randint(0, min(8, hi - lo))))
    spans, at = [], lo
    for c in cuts + [hi + 1]:
        spans.append((at, c - 1))
        at = c
    return spans


def test_mirror_chunk_order_never_changes_the_answer():
    """Any partition of the range, absorbed in any order, with any
    duplicates injected, lands on the same fold state — the property
    that makes replay + out-of-order settles + WAL merges safe."""
    rng = random.Random(0xF01D)
    for trial in range(25):
        seed = rng.randrange(1 << 32)
        lo, hi = 0, rng.randint(10, 300)
        spans = _random_partition(rng, lo, hi)
        for fold in ALL_FOLDS:
            settles = [
                (a, b, fold.of_batch(a, _vals(seed, a, b)))
                for a, b in spans
            ]
            baseline = new_state(fold)
            for a, b, acc in settles:
                assert absorb(fold, baseline, a, b, acc)
            shuffled = settles[:]
            rng.shuffle(shuffled)
            # inject duplicate deliveries at random points
            for dup in rng.sample(settles, min(2, len(settles))):
                shuffled.insert(rng.randint(0, len(shuffled)), dup)
            state = new_state(fold)
            for a, b, acc in shuffled:
                absorb(fold, state, a, b, acc)
            assert state == baseline, (fold.name, trial)


def test_mirror_beacon_prefix_splits_settle_exactly():
    """ISSUE 14's beacon shape on the workload plane: a chunk settled
    as a prefix beacon + its remainder folds to the same state as the
    whole chunk at once, and replaying the beacon afterwards is a
    no-op — sub-chunk progress is safe for every discipline, including
    the non-idempotent sum."""
    rng = random.Random(0xBEAC)
    for trial in range(25):
        seed = rng.randrange(1 << 32)
        lo, hi = 0, rng.randint(20, 200)
        cut = rng.randint(lo, hi - 1)
        for fold in ALL_FOLDS:
            whole = new_state(fold)
            assert absorb(
                fold, whole, lo, hi, fold.of_batch(lo, _vals(seed, lo, hi))
            )
            beacon = fold.of_batch(lo, _vals(seed, lo, cut))
            rest = fold.of_batch(cut + 1, _vals(seed, cut + 1, hi))
            split = new_state(fold)
            assert absorb(fold, split, lo, cut, beacon)
            assert absorb(fold, split, cut + 1, hi, rest)
            assert not absorb(fold, split, lo, cut, beacon)  # replay
            assert split["covered"] == whole["covered"]
            if fold.name == "fmatch":
                # probes under early-cancel are schedule-relative; the
                # decided (index, value) is what must agree
                assert split["acc"][:2] == whole["acc"][:2]
            else:
                assert split["acc"] == whole["acc"], fold.name


def test_mirror_codec_roundtrip_under_random_accs():
    rng = random.Random(0xC0DEC)
    for _ in range(200):
        v = rng.randrange(1 << 64)
        i = rng.randrange(1 << 64)
        n = rng.randint(0, folds.TOPK_SLOTS)
        accs = [
            (FMin(), [v, i]),
            (TopK(folds.TOPK_SLOTS),
             sorted([rng.randrange(1 << 64), k] for k in range(n))),
            (FirstMatch(0), [i, v, rng.randrange(1, 1 << 64)]),
            (FSum(), [rng.randrange(1 << 128), rng.randrange(1 << 64)]),
        ]
        for fold, acc in accs:
            assert fold.decode(fold.encode(acc)) == acc


# ---------------------------------------------------------------------------
# the fleet drill gate (tier-1): loadgen --scenario workload --smoke
# ---------------------------------------------------------------------------

def test_loadgen_workload_scenario_smoke(capsys):
    """All four disciplines through a REAL fleet — CpuMiners over LSP,
    a worker kill, then a kill -9 coordinator crash and a journal
    restart — with an exact-answer-per-fold exactly-once ledger: every
    decoded answer checked against ground truth, zero wrong, zero
    duplicated, zero lost, zero fail-fast refusals."""
    rc = loadgen.main([
        "--scenario", "workload", "--duration", "1.5",
        "--smoke", "--json",
    ])
    out = capsys.readouterr().out
    assert rc == 0, f"workload gate failed: {out}"
    metrics = _json.loads(out.splitlines()[0])
    assert metrics["answered"] > 0
    assert metrics["answers_wrong"] == 0
    assert metrics["answers_duplicated"] == 0
    assert metrics["answers_lost"] == 0
    assert metrics["refused_fatal"] == 0
    assert metrics["restart_to_first_assign_ms"] < 10_000
    assert metrics["journal"]["records"] > 0
    for fold in ("fmin", "topk", "fmatch_hit", "fmatch_dry", "fsum"):
        assert metrics["answered_by_fold"].get(fold, 0) > 0, fold


def test_loadgen_workload_scenario_dev_lanes(capsys):
    """The SAME drill — worker kill, kill -9 coordinator crash, journal
    restart — with the fleet forced onto the u32-pair device-lane
    engine (ISSUE 17). The ledger's exact-value checks are computed
    from the scalar objective, so zero ``answers_wrong`` here IS the
    device/host equality claim under crash recovery; the gate
    additionally requires the device engine demonstrably dispatched
    (``dev_dispatches`` > 0 — a silent host fallback would make the
    equality vacuous)."""
    rc = loadgen.main([
        "--scenario", "workload", "--duration", "1.5",
        "--smoke", "--json", "--dev-lanes",
    ])
    out = capsys.readouterr().out
    assert rc == 0, f"dev-lanes workload gate failed: {out}"
    metrics = _json.loads(out.splitlines()[0])
    assert metrics["dev_lanes"] is True
    assert metrics["dev_dispatches"] > 0
    assert metrics["answered"] > 0
    assert metrics["answers_wrong"] == 0
    assert metrics["answers_duplicated"] == 0
    assert metrics["answers_lost"] == 0
    assert metrics["refused_fatal"] == 0
