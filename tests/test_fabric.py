"""The compute fabric (ISSUE 20), pinned seam by seam:

- the dict workload's opaque-domain codec (tag 0xC5, u16 length-prefixed
  entries, CRC trailer) — roundtrip, every corruption a loud refusal,
  global-index windowing and the per-window chunk cap;
- per-variant verification trust model over a shipped candidate list
  (witnesses for fmin/topk, full recompute for fmatch/fsum);
- the Emit wire dialect (tag 0xBE, CRC-sealed) and the ``"strm"``
  no-flag-day Request key;
- fold-state merge semantics under partial emission — deterministic
  mirrors of the hypothesis-style properties (this image lacks
  hypothesis): snapshots are monotone in coverage, duplicate/replayed
  Emits never regress a gated client, WAL-segment merges compose;
- the weighted-fair park queue driven at the unit level (stride
  scheduling order, LRU shed + Refuse at overflow, nothing journaled
  or minted while parked, dead/superseded entries dropped, late class
  joins at the current virtual time);
- real-fleet e2e: dict jobs through CpuMiners with exactly-once dedup,
  streaming partials under a chaos FaultPlan, and windowed dispatch of
  an over-budget catalog recombining exactly;
- the tier-1 gates for ``loadgen --scenario stream|starve|soak``
  (full-length soak rides behind ``-m slow``).
"""

import asyncio
import json as _json
import os
import random
import sys

import pytest

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ),
)

import loadgen  # noqa: E402  (scripts/ is not a package)

from tpuminter import workloads  # noqa: E402
from tpuminter.chaos import FaultPlan  # noqa: E402
from tpuminter.client import submit  # noqa: E402
from tpuminter.coordinator import Coordinator  # noqa: E402
from tpuminter.lsp.params import FAST  # noqa: E402
from tpuminter.protocol import (  # noqa: E402
    Emit,
    PowMode,
    ProtocolError,
    Refuse,
    Request,
    WorkResult,
    decode_msg,
    encode_msg,
    request_from_obj,
    request_to_obj,
)
from tpuminter.workloads import (  # noqa: E402
    FMin,
    FSum,
    FirstMatch,
    TopK,
    absorb,
    covered_span,
    fold_of,
    merge_states,
    new_state,
)
from tpuminter.workloads import dictsearch as ds  # noqa: E402
from tpuminter.worker import CpuMiner, run_miner  # noqa: E402


def run(coro, timeout=60.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def _scores(seed, cands):
    return [ds.score(seed, c) for c in cands]


def _dreq(variant, seed, cands, *, job_id=1, threshold=0, k=1, ckey="",
          stream=False, lo=0, hi=None, chunk_id=0):
    return Request(
        job_id=job_id, mode=PowMode.MIN, lower=lo,
        upper=(len(cands) - 1 if hi is None else hi),
        data=ds.pack_params(
            variant, seed, cands, threshold=threshold, k=k
        ),
        client_key=ckey, workload="dict", stream=stream,
        chunk_id=chunk_id,
    )


# ---------------------------------------------------------------------------
# dict params codec: tag | fields | entry table | crc
# ---------------------------------------------------------------------------

class TestDictCodec:
    def test_roundtrip_and_global_index_windowing(self):
        cands = [b"alpha", b"", b"x" * 40, b"omega"]
        data = ds.pack_params(
            "topk", 0xFEED, cands, threshold=9, k=2, base=100
        )
        p = ds.parse_params(data)
        assert (p.variant, p.seed, p.threshold, p.k, p.base) == (
            "topk", 0xFEED, 9, 2, 100
        )
        assert p.entries == tuple(cands)
        # entry() resolves GLOBAL indices through the window base
        assert p.entry(100) == b"alpha"
        assert p.entry(103) == b"omega"
        for outside in (99, 104):
            with pytest.raises(ValueError, match="outside"):
                p.entry(outside)

    def test_parse_cache_returns_the_same_object(self):
        data = ds.pack_params("fmin", 7, [b"one", b"two"])
        assert ds.parse_params(data) is ds.parse_params(bytes(data))

    def test_pack_rejects_malformed_inputs(self):
        with pytest.raises(ValueError, match="variant"):
            ds.pack_params("fmax", 1, [b"a"])
        with pytest.raises(ValueError, match="u64"):
            ds.pack_params("fmin", 1 << 64, [b"a"])
        with pytest.raises(ValueError, match="k must"):
            ds.pack_params("topk", 1, [b"a"], k=0)
        with pytest.raises(ValueError, match="count"):
            ds.pack_params("fmin", 1, [])
        with pytest.raises(ValueError, match="exceeds"):
            ds.pack_params("fmin", 1, [b"x" * (ds.MAX_ENTRY + 1)])

    def test_every_corruption_is_a_loud_refusal(self):
        data = ds.pack_params("fmin", 3, [b"aa", b"bb", b"cc"])
        # single-bit flip anywhere in the body: CRC catches it
        for off in (0, 1, 10, len(data) - 6):
            bent = bytearray(data)
            bent[off] ^= 0x40
            with pytest.raises(ValueError, match="CRC|tag|variant"):
                ds.parse_params(bytes(bent))
        # truncation at every prefix length is refused, never a crash
        for cut in range(len(data)):
            with pytest.raises(ValueError):
                ds.parse_params(data[:cut])
        # a lying entry count (resealed so the CRC passes) is caught by
        # the entry-table walk, not trusted
        head = ds._BIN_DICTPARAMS_HEAD
        body = bytearray(data[:-4])
        tag, variant, seed, threshold, k, base, count = head.unpack_from(
            body
        )
        head.pack_into(
            body, 0, tag, variant, seed, threshold, k, base, count + 1
        )
        with pytest.raises(ValueError, match="truncated"):
            ds.parse_params(ds._seal(bytes(body)))
        # trailing junk between the entries and the CRC is refused
        with pytest.raises(ValueError, match="trailing"):
            ds.parse_params(ds._seal(data[:-4] + b"\x00"))

    def test_fold_for_enforces_the_shipped_range(self):
        cands = [b"c%d" % i for i in range(8)]
        req = _dreq("fmin", 5, cands)
        assert isinstance(fold_of(req), FMin)
        bad = _dreq("fmin", 5, cands, lo=0, hi=8)
        with pytest.raises(ValueError, match="outside"):
            ds.DictSearch().fold_for(bad)
        # a window frame's base bounds the range from below too
        win = Request(
            job_id=1, mode=PowMode.MIN, lower=99, upper=101,
            data=ds.pack_params("fmin", 5, cands, base=100),
            workload="dict",
        )
        with pytest.raises(ValueError, match="outside"):
            ds.DictSearch().fold_for(win)
        # per-variant fold resolution
        assert isinstance(fold_of(_dreq("topk", 5, cands, k=3)), TopK)
        assert isinstance(
            fold_of(_dreq("fmatch", 5, cands, threshold=9)), FirstMatch
        )
        assert isinstance(fold_of(_dreq("fsum", 5, cands)), FSum)

    def test_window_and_chunk_cap_semantics(self):
        small = _dreq("fmin", 1, [b"tiny"] * 4)
        assert workloads.window_for(small, 0, 3) is None
        assert workloads.chunk_cap(small) == 0
        cands = [b"window-%06d" % i for i in range(2600)]
        req = _dreq("fmin", 2, cands)
        assert len(req.data) > ds.WINDOW_BYTES
        cap = workloads.chunk_cap(req)
        assert cap >= 16
        hi = min(len(cands) - 1, 1000 + cap - 1)
        win = workloads.window_for(req, 1000, hi)
        assert win is not None and len(win) <= ds.WINDOW_BYTES + 64
        p = ds.parse_params(win)
        assert p.base == 1000
        assert p.entries == tuple(cands[1000 : hi + 1])
        assert p.entry(1000) == cands[1000]  # global index still works
        with pytest.raises(ValueError, match="window"):
            ds.DictSearch().window(req, 2599, 2600)


# ---------------------------------------------------------------------------
# compute + per-variant verification over a shipped list
# ---------------------------------------------------------------------------

class TestDictSemantics:
    SEED = 0xD1C7
    CANDS = [b"pw-%04d" % i for i in range(300)]

    def _compute(self, req):
        for msg in workloads.compute(req):
            if msg is not None:
                return msg
        raise AssertionError("compute ended without a WorkResult")

    def test_compute_matches_brute_force_per_variant(self):
        vals = _scores(self.SEED, self.CANDS)
        pairs = sorted((v, i) for i, v in enumerate(vals))
        cases = [
            ("fmin", dict(), [pairs[0][0], pairs[0][1]]),
            ("topk", dict(k=3), [list(p) for p in pairs[:3]]),
            ("fsum", dict(), [sum(vals), len(vals)]),
        ]
        for variant, kw, want in cases:
            req = _dreq(variant, self.SEED, self.CANDS, **kw)
            msg = self._compute(req)
            assert msg.wid == ds.DICT_WID
            assert fold_of(req).decode(msg.payload) == want, variant
            assert workloads.verify_claim(req, msg), variant

    def test_first_match_early_stop_and_dry_scan(self):
        vals = _scores(self.SEED, self.CANDS)
        pairs = sorted((v, i) for i, v in enumerate(vals))
        hit = _dreq(
            "fmatch", self.SEED, self.CANDS, threshold=pairs[3][0]
        )
        msg = self._compute(hit)
        index, value, probes = fold_of(hit).decode(msg.payload)
        first = next(i for i, v in enumerate(vals) if v <= pairs[3][0])
        assert (index, value) == (first, vals[first])
        assert msg.searched < len(self.CANDS)  # early-stop, not a scan
        dry = _dreq("fmatch", self.SEED, self.CANDS, threshold=0)
        dmsg = self._compute(dry)
        assert fold_of(dry).decode(dmsg.payload)[0] is None
        assert dmsg.searched == len(self.CANDS)
        assert workloads.verify_claim(dry, dmsg)

    def test_byzantine_claims_are_rejected(self):
        vals = _scores(self.SEED, self.CANDS)
        pairs = sorted((v, i) for i, v in enumerate(vals))
        lo_v, lo_i = pairs[0]

        def claim(req, acc):
            fold = fold_of(req)
            return WorkResult(
                job_id=req.job_id, chunk_id=req.chunk_id,
                wid=ds.DICT_WID,
                searched=req.upper - req.lower + 1,
                payload=fold.encode(acc),
            )

        cases = [
            # wrong witness value for the claimed index
            (_dreq("fmin", self.SEED, self.CANDS), [lo_v ^ 1, lo_i]),
            # witness outside the chunk range
            (_dreq("fmin", self.SEED, self.CANDS, hi=99),
             [vals[150], 150]),
            # a dry first-match claim hiding a real hit: rescan finds it
            (_dreq("fmatch", self.SEED, self.CANDS, threshold=lo_v),
             [None, None, len(self.CANDS)]),
            # sum off by one
            (_dreq("fsum", self.SEED, self.CANDS),
             [sum(vals) + 1, len(vals)]),
            # short count
            (_dreq("fsum", self.SEED, self.CANDS),
             [sum(vals), len(vals) - 1]),
        ]
        for req, acc in cases:
            assert not workloads.verify_claim(req, claim(req, acc)), acc
        # wrong wid never verifies
        good = _dreq("fmin", self.SEED, self.CANDS)
        msg = claim(good, [lo_v, lo_i])
        assert workloads.verify_claim(good, msg)
        bad_wid = WorkResult(
            job_id=msg.job_id, chunk_id=msg.chunk_id, wid=99,
            searched=msg.searched, payload=msg.payload,
        )
        assert not workloads.verify_claim(good, bad_wid)


# ---------------------------------------------------------------------------
# the Emit wire dialect and the "strm" Request key
# ---------------------------------------------------------------------------

class TestEmitWire:
    def test_binary_roundtrip_is_tagged_and_crc_sealed(self):
        e = Emit(job_id=7, seq=3, covered=120, total=999,
                 payload=b"\x01\x02\x03")
        raw = encode_msg(e, binary=True)
        assert raw[0] == 0xBE
        back = decode_msg(raw)
        assert isinstance(back, Emit)
        assert (back.job_id, back.seq, back.covered, back.total) == (
            7, 3, 120, 999
        )
        assert bytes(back.payload) == b"\x01\x02\x03"
        # JSON dialect carries the same fields
        jback = decode_msg(encode_msg(e))
        assert (jback.covered, jback.total) == (120, 999)
        assert bytes(jback.payload) == b"\x01\x02\x03"

    def test_corruption_and_truncation_are_loud(self):
        raw = encode_msg(
            Emit(job_id=1, seq=1, covered=5, total=9, payload=b"zz"),
            binary=True,
        )
        bent = bytearray(raw)
        bent[6] ^= 0x10
        with pytest.raises(ProtocolError):
            decode_msg(bytes(bent))
        with pytest.raises(ProtocolError):
            decode_msg(raw[:10])

    def test_out_of_range_fields_fall_back_to_json(self):
        e = Emit(job_id=1, seq=1, covered=1 << 64, total=1, payload=b"")
        raw = encode_msg(e, binary=True)
        assert raw[0] != 0xBE  # JSON fallback, not a corrupt frame

    def test_strm_key_is_omitted_when_false(self):
        req = _dreq("fmin", 1, [b"a", b"b"], ckey="k")
        obj = request_to_obj(req)
        assert "strm" not in obj  # an old coordinator sees no new key
        assert request_from_obj(obj).stream is False
        sobj = request_to_obj(
            _dreq("fmin", 1, [b"a", b"b"], ckey="k", stream=True)
        )
        assert sobj["strm"] == 1  # the compact wire form
        assert request_from_obj(sobj).stream is True


# ---------------------------------------------------------------------------
# fold-state merge under partial emission (deterministic mirrors of the
# hypothesis-style properties; seeded RNG, no hypothesis in this image)
# ---------------------------------------------------------------------------

def _random_partition(rng, lo, hi):
    cuts = sorted(rng.sample(range(lo + 1, hi + 1),
                             rng.randint(0, min(8, hi - lo))))
    spans, at = [], lo
    for c in cuts + [hi + 1]:
        spans.append((at, c - 1))
        at = c
    return spans


class TestEmitMerge:
    ENTRIES = [b"emit-%04d" % i for i in range(160)]

    def _folds(self, rng, vals):
        return (
            FMin(), TopK(3), FirstMatch(rng.choice(sorted(vals)[:8])),
            FSum(),
        )

    def test_partial_snapshots_are_monotone_and_converge(self):
        """Absorbing settles in any order yields emission snapshots
        whose coverage strictly increases, whose payloads roundtrip the
        fold codec, and whose last state equals the whole-range fold —
        what makes a stream of Emits a converging answer."""
        rng = random.Random(0xE517)
        for trial in range(20):
            seed = rng.randrange(1 << 32)
            n = rng.randint(20, len(self.ENTRIES))
            vals = _scores(seed, self.ENTRIES[:n])
            for fold in self._folds(rng, vals):
                spans = _random_partition(rng, 0, n - 1)
                rng.shuffle(spans)
                state = new_state(fold)
                snapshots = []
                for a, b in spans:
                    assert absorb(
                        fold, state, a, b, fold.of_batch(a, vals[a:b + 1])
                    )
                    snapshots.append(
                        (covered_span(state),
                         fold.encode(state["acc"]))
                    )
                covs = [c for c, _p in snapshots]
                assert covs == sorted(covs) and len(set(covs)) == len(covs)
                assert covs[-1] == n
                for _c, payload in snapshots:
                    enc = fold.encode(fold.decode(payload))
                    assert enc == payload
                whole = new_state(fold)
                absorb(fold, whole, 0, n - 1, fold.of_batch(0, vals))
                if fold.name == "fmatch":
                    # probes are schedule-relative; the decided
                    # (index, value) is the claim that must agree
                    assert state["acc"][:2] == whole["acc"][:2]
                else:
                    assert state["acc"] == whole["acc"], fold.name

    def test_duplicate_and_replayed_emits_never_regress(self):
        """The client contract (client.submit docstring): gate on
        ``covered`` only. A redelivered Emit, or a replayed incarnation
        re-emitting its whole prefix with seq reset to 0, renders no
        regression — and at the fold layer the duplicate span is a
        coverage-gated no-op."""
        rng = random.Random(0xD0B1)
        for trial in range(10):
            seed = rng.randrange(1 << 32)
            n = rng.randint(24, len(self.ENTRIES))
            vals = _scores(seed, self.ENTRIES[:n])
            fold = FSum()  # non-idempotent: regressions would corrupt
            spans = _random_partition(rng, 0, n - 1)
            rng.shuffle(spans)
            state = new_state(fold)
            emits = []
            for seq, (a, b) in enumerate(spans):
                acc = fold.of_batch(a, vals[a:b + 1])
                assert absorb(fold, state, a, b, acc)
                # the duplicate delivery is a no-op: same acc, state kept
                before = (list(state["covered"]), list(state["acc"]))
                assert not absorb(fold, state, a, b, acc)
                assert (list(state["covered"]), list(state["acc"])) == (
                    before
                )
                emits.append(Emit(
                    job_id=1, seq=seq, covered=covered_span(state),
                    total=n, payload=fold.encode(state["acc"]),
                ))
            # wire schedule: duplicates injected, then a failover replay
            # of a prefix with seq restarting from zero
            schedule = list(emits)
            for dup in rng.sample(emits, min(3, len(emits))):
                schedule.insert(rng.randint(0, len(schedule)), dup)
            cut = rng.randint(1, len(emits))
            for i, e in enumerate(emits[:cut]):
                schedule.append(Emit(
                    job_id=1, seq=i, covered=e.covered, total=e.total,
                    payload=e.payload,
                ))
            rendered = []
            seen = -1
            for e in schedule:
                if e.covered <= seen:
                    continue
                seen = e.covered
                rendered.append((e.covered, bytes(e.payload)))
            covs = [c for c, _p in rendered]
            assert covs == sorted(covs) and len(set(covs)) == len(covs)
            assert covs[-1] == n
            assert rendered[-1][1] == fold.encode(
                [sum(vals), len(vals)]
            )

    def test_wal_segment_merges_compose_with_partial_states(self):
        """journal.merge_states' per-job rule on dict folds: disjoint
        segment states union; overlapping NON-idempotent states keep
        the richer side instead of double-counting."""
        seed, n = 0x5EC5, 60
        vals = _scores(seed, self.ENTRIES[:n])
        for fold in (FMin(), FSum()):
            a = new_state(fold)
            absorb(fold, a, 0, 29, fold.of_batch(0, vals[:30]))
            b = new_state(fold)
            absorb(fold, b, 30, n - 1, fold.of_batch(30, vals[30:]))
            merged = merge_states(fold, a, b)
            assert covered_span(merged) == n
            whole = new_state(fold)
            absorb(fold, whole, 0, n - 1, fold.of_batch(0, vals))
            assert merged["acc"] == whole["acc"], fold.name
        # overlapping fsum segments: conservative richer-side pick
        fold = FSum()
        rich = new_state(fold)
        absorb(fold, rich, 0, 39, fold.of_batch(0, vals[:40]))
        poor = new_state(fold)
        absorb(fold, poor, 20, 29, fold.of_batch(20, vals[20:30]))
        merged = merge_states(fold, poor, rich)
        assert merged == rich  # never summed twice over [20, 29]


# ---------------------------------------------------------------------------
# the weighted-fair park queue, driven at the unit level (no loop: the
# ticker no-ops by design and the drives call _drain_parked directly)
# ---------------------------------------------------------------------------

class _StubServer:
    def __init__(self, conn_ids=()):
        self.conn_ids = set(conn_ids)
        self.writes = []

    def write(self, conn_id, data):
        self.writes.append((conn_id, bytes(data)))


def _mine_req(job_id, ckey=""):
    return Request(job_id=job_id, mode=PowMode.MIN, lower=0, upper=31,
                   data=b"park-%d" % job_id, client_key=ckey)


_DICT_DATA = ds.pack_params("fmin", 0xFA1A, [b"pa", b"pb", b"pc"])


def _dict_req(job_id, ckey=""):
    return Request(job_id=job_id, mode=PowMode.MIN, lower=0, upper=2,
                   data=_DICT_DATA, client_key=ckey, workload="dict")


def _park_coord(**kw):
    kw.setdefault("max_jobs", 1)
    kw.setdefault("park_capacity", 32)
    kw.setdefault("retry_after_ms", 50)
    server = _StubServer({1, 2})
    coord = Coordinator(server, **kw)
    # one live job fills the table so every new submission parks
    coord._mint_job(1, _mine_req(900))
    return coord, server


class TestParkStride:
    def test_stride_drain_tracks_the_weight_split(self):
        coord, _server = _park_coord(
            workload_weights={"mine": 3.0, "dict": 1.0}
        )
        for i in range(12):
            coord._on_request(1, _mine_req(i + 1))
            coord._on_request(2, _dict_req(i + 101))
        assert coord.stats["jobs_parked"] == 24
        assert len(coord._jobs) == 1  # nothing minted while parked
        # free one slot at a time — the degenerate schedule a
        # quantum-per-round DRR loses: stride must still split 3:1
        order = []
        for _ in range(8):
            coord._max_jobs = len(coord._jobs) + 1
            before = dict(coord.parked_drained_by_class)
            coord._drain_parked()
            after = coord.parked_drained_by_class
            (cls,) = [
                c for c in after
                if after[c] != before.get(c, 0)
            ]
            order.append(cls)
        assert order == [
            "dict", "mine", "mine", "mine",
            "dict", "mine", "mine", "mine",
        ]
        assert coord.parked_drained_by_class == {"mine": 6, "dict": 2}
        # admitted parked entries took the normal mint path
        minted = [
            j.request.workload or "mine"
            for j in coord._jobs.values()
        ][1:]
        assert minted.count("mine") == 6 and minted.count("dict") == 2

    def test_overflow_lru_sheds_oldest_with_explicit_refuse(self):
        coord, server = _park_coord(park_capacity=2)
        for jid in (11, 12, 13):
            coord._on_request(2, _dict_req(jid, ckey="flood"))
        assert coord.stats["jobs_parked"] == 3
        assert coord.stats["parked_shed"] == 1
        assert len(coord._parked["dict"]) == 2
        # the shed entry was the OLDEST and got a Refuse with the
        # retry hint — explicit backpressure, never a silent drop
        refusals = [decode_msg(d) for _c, d in server.writes]
        refusals = [m for m in refusals if isinstance(m, Refuse)]
        assert [m.job_id for m in refusals] == [11]
        assert refusals[0].retry_after_ms == 50
        # parked entries are invisible to exactly-once state: nothing
        # journaled, nothing bound, no job minted
        assert len(coord._jobs) == 1
        assert coord._bound == {}

    def test_dead_and_superseded_entries_drop_without_minting(self):
        coord, _server = _park_coord()
        coord._on_request(99, _mine_req(5))        # conn 99 is dead
        coord._on_request(2, _mine_req(6, ckey="k"))
        coord._bound[("k", 6)] = 777  # superseded while parked
        coord._max_jobs = 10
        coord._drain_parked()
        assert coord.stats["parked_drained"] == 0
        assert len(coord._jobs) == 1
        assert coord._parked == {}  # both entries dropped, queue gone

    def test_late_class_joins_at_the_current_virtual_time(self):
        coord, _server = _park_coord(
            workload_weights={"mine": 1.0, "dict": 1.0}
        )
        for i in range(4):
            coord._on_request(1, _mine_req(i + 1))
        for _ in range(2):
            coord._max_jobs = len(coord._jobs) + 1
            coord._drain_parked()
        assert coord._park_deficit["mine"] == pytest.approx(2.0)
        # a class parking NOW starts at the live virtual time — not at
        # zero, which would let it lap the backlogged class
        coord._on_request(2, _dict_req(50))
        assert coord._park_deficit["dict"] == pytest.approx(2.0)

    def test_full_table_with_park_armed_never_line_jump_sheds(self):
        coord, _server = _park_coord()
        shed_before = coord.stats["jobs_shed"]
        coord._on_request(1, _mine_req(41))
        # the pending seed job was NOT LRU-evicted to admit the
        # newcomer: with the park queue armed, arrivals wait their turn
        assert coord.stats["jobs_shed"] == shed_before
        assert coord.stats["jobs_parked"] == 1
        assert 900 in {
            j.client_job_id for j in coord._jobs.values()
        }


# ---------------------------------------------------------------------------
# real-fleet e2e: dict jobs over CpuMiners
# ---------------------------------------------------------------------------

class _Fleet:
    def __init__(self, coord):
        self.coord = coord
        self.serve = asyncio.ensure_future(coord.serve())
        self.miners = []

    @classmethod
    async def create(cls, n_miners=2, **kw):
        kw.setdefault("params", FAST)
        coord = await Coordinator.create(**kw)
        self = cls(coord)
        for _ in range(n_miners):
            self.miners.append(asyncio.ensure_future(run_miner(
                "127.0.0.1", coord.port, CpuMiner(), params=FAST,
            )))
        await asyncio.sleep(0.05)  # let the Joins land
        return self

    async def close(self):
        for t in self.miners:
            t.cancel()
        self.serve.cancel()
        await asyncio.gather(
            *self.miners, self.serve, return_exceptions=True
        )
        await self.coord.close()


def test_dict_job_end_to_end_exactly_once():
    async def scenario():
        fleet = await _Fleet.create(n_miners=2, chunk_size=64)
        try:
            cands = [b"pw-%04d" % i for i in range(300)]
            req = _dreq("fmin", 0xD1C7, cands, ckey="fabric-e2e")
            res = await submit(
                "127.0.0.1", fleet.coord.port, req, params=FAST
            )
            vals = _scores(0xD1C7, cands)
            want = min((v, i) for i, v in enumerate(vals))
            assert fold_of(req).decode(bytes(res.payload)) == list(want)
            # a duplicate submission under the same (ckey, cjid) is
            # answered from the winners table — nothing re-minted
            next_id = fleet.coord._next_job_id
            res2 = await submit(
                "127.0.0.1", fleet.coord.port, req, params=FAST
            )
            assert bytes(res2.payload) == bytes(res.payload)
            assert fleet.coord._next_job_id == next_id
        finally:
            await fleet.close()

    run(scenario())


def test_dict_streaming_partials_exact_under_chaos():
    """A streaming fsum (NON-idempotent: any double-settle corrupts the
    answer) through a dup/reorder/delay FaultPlan on the coordinator's
    socket: the final sum is exact, >= 3 partials arrive, each partial's
    decoded count equals its claimed coverage, and gated coverage never
    regresses."""
    async def scenario():
        fleet = await _Fleet.create(
            n_miners=2, chunk_size=16, emit_interval=0.0
        )
        try:
            plan = FaultPlan(11).link(
                peer="*", dup=0.25, reorder=0.2, reorder_delay=0.01,
                delay=0.002, delay_jitter=0.003,
            )
            for ep in loadgen._endpoints(fleet.coord):
                ep.set_fault_plan(plan)
            cands = [b"chaos-%04d" % i for i in range(600)]
            req = _dreq(
                "fsum", 0xFA57, cands, ckey="fabric-chaos", stream=True
            )
            partials = []
            res = await submit(
                "127.0.0.1", fleet.coord.port, req, params=FAST,
                on_emit=lambda e: partials.append(
                    (e.covered, e.total, bytes(e.payload))
                ),
            )
            vals = _scores(0xFA57, cands)
            fold = fold_of(req)
            assert fold.decode(bytes(res.payload)) == [sum(vals), 600]
            assert len(partials) >= 3
            assert fleet.coord.stats["emits_sent"] >= 3
            gated = []
            for cov, total, payload in partials:
                assert total == 600
                _s, count = fold.decode(payload)
                assert count == cov  # the payload matches its coverage
                if not gated or cov > gated[-1]:
                    gated.append(cov)
            assert len(gated) >= 3
            assert gated == sorted(gated)
        finally:
            await fleet.close()

    run(scenario())


def test_dict_windowed_dispatch_recombines_exactly():
    """An over-budget catalog (> WINDOW_BYTES) dispatches as per-chunk
    windowed Setups; the re-based windows must recombine to the exact
    global top-k, with >= 2 partials proving the job really split."""
    async def scenario():
        fleet = await _Fleet.create(
            n_miners=2, chunk_size=4096, emit_interval=0.0
        )
        try:
            cands = [b"window-%06d" % i for i in range(2600)]
            req = _dreq(
                "topk", 0x3157, cands, k=3, ckey="fabric-window",
                stream=True,
            )
            assert len(req.data) > ds.WINDOW_BYTES
            partials = []
            res = await submit(
                "127.0.0.1", fleet.coord.port, req, params=FAST,
                on_emit=lambda e: partials.append(e.covered),
            )
            pairs = sorted(
                (v, i) for i, v in enumerate(_scores(0x3157, cands))
            )
            got = fold_of(req).decode(bytes(res.payload))
            assert [tuple(p) for p in got] == pairs[:3]
            # >= 1 strict-partial emit proves the job really split into
            # windowed chunks (the LAST settle yields the final Result,
            # not an Emit)
            assert partials and max(partials) < 2600
        finally:
            await fleet.close()

    run(scenario())


# ---------------------------------------------------------------------------
# the fleet drill gates (tier-1): loadgen --scenario stream|starve|soak
# ---------------------------------------------------------------------------

def test_loadgen_stream_scenario_smoke(capsys):
    """The streaming gate: >= 3 monotone partials before the exact
    final answer, a kill -9 mid-stream, partials that keep flowing from
    the REPLAYED incarnation, and a final payload bit-identical to the
    non-streaming submission's."""
    rc = loadgen.main(["--scenario", "stream", "--smoke", "--json"])
    out = capsys.readouterr().out
    assert rc == 0, f"stream gate failed: {out}"
    metrics = _json.loads(out.splitlines()[0])
    assert metrics["partials"] >= 3
    assert metrics["monotone"] is True
    assert metrics["crashed_mid_stream"] is True
    assert metrics["partials_post_crash"] >= 1
    assert metrics["emits_post_crash"] >= 1
    assert metrics["final_exact"] is True
    assert metrics["bit_identical_final"] is True
    assert (
        0
        < metrics["time_to_first_partial_ms"]
        < metrics["time_to_final_ms"]
    )


def test_loadgen_starve_scenario_smoke(capsys):
    """The starvation gate: a greedy dict flood against background
    mining tenants on one coordinator — the flood demonstrably parks
    and overflows the bounded queue, the mining p99 stays within the
    2x bar, and weight-normalized drain counts track the configured
    DRR share."""
    rc = loadgen.main([
        "--scenario", "starve", "--duration", "1.5",
        "--smoke", "--json",
    ])
    out = capsys.readouterr().out
    assert rc == 0, f"starve gate failed: {out}"
    metrics = _json.loads(out.splitlines()[0])
    flood = metrics["flood"]
    assert flood["jobs_parked"] > 0
    assert flood["parked_shed"] > 0
    assert flood["park_queue_high_water"] <= 2 * metrics["park_capacity"]
    assert metrics["baseline"]["mining_jobs"] > 0
    assert flood["mining_jobs"] > 0
    assert 1 / 3 <= metrics["drr_fairness_ratio"] <= 3.0


def test_loadgen_soak_scenario_smoke(capsys):
    """The soak gate: steady mixed load (mining + dict + churn + a park
    pulse) with live compaction — ZERO second-half growth in every
    ``*_high_water`` gauge, a WAL bounded by compaction, and the
    exactly-once ledgers clean."""
    rc = loadgen.main([
        "--scenario", "soak", "--duration", "3", "--smoke", "--json",
    ])
    out = capsys.readouterr().out
    assert rc == 0, f"soak gate failed: {out}"
    metrics = _json.loads(out.splitlines()[0])
    assert metrics["hw_growth"] == {}
    assert metrics["journal"]["compactions"] >= 1
    assert metrics["wal_end_bytes"] <= 4 * metrics["compact_bytes"]
    assert metrics["mining_answered"] > 0
    assert metrics["dict_answered"] > 0
    assert metrics["churn_done"] > 0
    assert metrics["jobs_parked"] > 0
    assert metrics["answers_duplicated"] == 0
    assert metrics["answers_wrong"] == 0
    assert metrics["poisoned_answers"] == 0


@pytest.mark.slow
def test_loadgen_soak_scenario_full(capsys):
    """The full-length soak (same gates, 8s+ of steady state) — the
    long-haul leak hunt tier-1 runs in miniature above."""
    rc = loadgen.main(["--scenario", "soak", "--duration", "8", "--json"])
    out = capsys.readouterr().out
    assert rc == 0, f"full soak gate failed: {out}"
    metrics = _json.loads(out.splitlines()[0])
    assert metrics["hw_growth"] == {}
    assert metrics["journal"]["compactions"] >= 1
